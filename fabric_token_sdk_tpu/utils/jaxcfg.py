"""Single source of truth for the JAX persistent-compile-cache policy.

The limbed EC kernels trace to large graphs; first compiles take minutes on
both backends. Every entry point (tests, bench, graft entry) funnels through
configure_jax_cache so the policy cannot drift between them.
"""

from __future__ import annotations

import os


def raise_stack_limit() -> None:
    """Lift RLIMIT_STACK before XLA compiles anything.

    LLVM's recursive passes compiling the large unrolled EC kernels can
    blow the default 8 MiB thread stack on XLA:CPU (observed as a SIGSEGV
    inside compile_or_get_cached on single-core hosts). Must run before
    jax creates its compilation threads — their stack size is fixed at
    thread creation from the soft limit."""
    try:
        import resource

        soft, hard = resource.getrlimit(resource.RLIMIT_STACK)
        want = 512 * 1024 * 1024
        if soft != resource.RLIM_INFINITY and soft < want:
            new_soft = want if hard == resource.RLIM_INFINITY \
                else min(want, hard)
            resource.setrlimit(resource.RLIMIT_STACK, (new_soft, hard))
    except (ImportError, ValueError, OSError):
        pass  # best effort: platform without rlimits or no privilege


def _host_tag() -> str:
    """Fingerprint of the host CPU feature set.

    XLA:CPU AOT cache entries bake in the compile machine's features;
    loading them on a host with a different set fails or SIGILLs
    (observed: /tmp/jax_cache carried over from an avx512+amx machine
    crashed the suite mid-compile). Keying the cache dir by the feature
    set makes stale entries unreachable instead of fatal."""
    import hashlib

    try:
        with open("/proc/cpuinfo") as fh:
            for line in fh:
                if line.startswith("flags"):
                    return hashlib.sha256(line.encode()).hexdigest()[:12]
    except OSError:
        pass
    import platform

    return hashlib.sha256(platform.processor().encode()).hexdigest()[:12]


def configure_jax_cache() -> None:
    import jax

    raise_stack_limit()
    base = os.environ.get("JAX_CACHE_DIR", "/tmp/jax_cache")
    # Segment by backend platform AND host CPU: the axon (remote-TPU)
    # client writes XLA:CPU AOT artifacts compiled on the REMOTE host into
    # the cache; loading those under the local cpu backend SIGILLs/aborts
    # (root cause of the mid-suite faulthandler crashes).
    platform = (jax.config.jax_platforms or "default").replace(",", "_")
    jax.config.update("jax_compilation_cache_dir",
                      f"{base}-{platform}-{_host_tag()}")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
