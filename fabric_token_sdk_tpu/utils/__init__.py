"""Shared codecs and helpers (json codec, proto wire format, slices)."""
