"""Minimal proto3 wire-format codec.

The reference serializes public parameters and actions with protobuf
(reference token/core/zkatdlog/nogh/protos/*.proto, token/driver/protos/*.proto).
This hand-rolled codec produces byte-identical output for the message shapes
used there (varint + length-delimited fields, tag order, proto3 default
omission) without requiring generated code.
"""

from __future__ import annotations

VARINT = 0
I64 = 1
LEN = 2
I32 = 5


def encode_varint(value: int) -> bytes:
    if value < 0:
        value += 1 << 64  # proto int64 two's complement
    out = bytearray()
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def decode_varint(data: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(data):
            raise ValueError("proto: truncated varint")
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift >= 70:
            raise ValueError("proto: varint too long")


def tag(field_number: int, wire_type: int) -> bytes:
    return encode_varint((field_number << 3) | wire_type)


def uint64_field(field_number: int, value: int) -> bytes:
    """proto3 scalar: omitted when zero."""
    if value == 0:
        return b""
    return tag(field_number, VARINT) + encode_varint(value)


def bytes_field(field_number: int, value: bytes | None) -> bytes:
    """proto3 bytes/string/submessage: omitted when empty/None.

    Note: a present-but-empty submessage must be emitted explicitly with
    message_field(..., force=True) semantics by callers that need it.
    """
    if not value:
        return b""
    return tag(field_number, LEN) + encode_varint(len(value)) + value


def message_field(field_number: int, body: bytes | None, present: bool = None) -> bytes:
    """Submessage: emitted when present (even if empty body)."""
    if present is None:
        present = body is not None
    if not present:
        return b""
    body = body or b""
    return tag(field_number, LEN) + encode_varint(len(body)) + body


def string_field(field_number: int, value: str) -> bytes:
    return bytes_field(field_number, value.encode("utf-8"))


def iter_fields(data: bytes):
    """Yield (field_number, wire_type, value) over a serialized message."""
    pos = 0
    while pos < len(data):
        key, pos = decode_varint(data, pos)
        field_number = key >> 3
        wire_type = key & 7
        if wire_type == VARINT:
            value, pos = decode_varint(data, pos)
        elif wire_type == LEN:
            length, pos = decode_varint(data, pos)
            if pos + length > len(data):
                raise ValueError("proto: truncated length-delimited field")
            value = data[pos:pos + length]
            pos += length
        elif wire_type == I64:
            if pos + 8 > len(data):
                raise ValueError("proto: truncated fixed64 field")
            value = data[pos:pos + 8]
            pos += 8
        elif wire_type == I32:
            if pos + 4 > len(data):
                raise ValueError("proto: truncated fixed32 field")
            value = data[pos:pos + 4]
            pos += 4
        else:
            raise ValueError(f"proto: unsupported wire type {wire_type}")
        yield field_number, wire_type, value


def parse_fields(data: bytes) -> dict[int, list]:
    """Collect fields into {field_number: [values...]} preserving order."""
    out: dict[int, list] = {}
    for num, _, value in iter_fields(data):
        out.setdefault(num, []).append(value)
    return out
