"""Witness-row packing for the device prover (models layer).

Mirrors the verifier's pipeline shape (range_verifier._pack_rows): every
witness a range-proof chunk needs — value, blinding factor, and the six
blinding-draw groups of ``crypto.rp.RangeProverDraws`` — is packed into
ONE contiguous (B, W) uint32 row matrix so a chunk costs exactly one
host->device upload. The unpack direction turns the device program's
(point bytes, scalar limbs) outputs back into ``rp.RangeProof`` host
objects whose ``serialize()`` is byte-identical to the host prover's.

Row layout, W = (6 + 2n) * 16 u32 words of 16-bit LE limbs:

    [value | bf | rho | eta | tau1 | tau2 | random_left*n | random_right*n]

Values are stored mod R (the device commits ``cg0^value`` from the full
residue while the bit decomposition uses only the low n bits — exactly
the host prover's truncating behavior, which is what makes seeded
out-of-range FORGED witnesses produce byte-identical invalid proofs on
both paths).
"""

from __future__ import annotations

import numpy as np

from ..crypto import bn254
from ..crypto import rp
from ..ops import limbs

R = bn254.R
_NL = limbs.NLIMBS


def witness_width(bit_length: int) -> int:
    """Packed u32 row width for one witness at ``bit_length`` bits."""
    return (6 + 2 * bit_length) * _NL


def pack_range_witnesses(values, blinding_factors, draws,
                         bit_length: int) -> np.ndarray:
    """(values, bfs, RangeProverDraws list) -> (B, W) uint32 packed rows."""
    B = len(values)
    out = np.zeros((B, witness_width(bit_length)), dtype=np.uint32)
    for r in range(B):
        d = draws[r]
        if (len(d.random_left) != bit_length
                or len(d.random_right) != bit_length):
            raise ValueError(
                f"draws row {r}: expected {bit_length} random_left/right "
                f"draws, got {len(d.random_left)}/{len(d.random_right)}")
        row = ([values[r] % R, blinding_factors[r] % R, d.rho % R,
                d.eta % R, d.tau1 % R, d.tau2 % R]
               + [v % R for v in d.random_left]
               + [v % R for v in d.random_right])
        out[r] = limbs.ints_to_limbs(row).reshape(-1)
    return out


def pad_witness_rows(packed: np.ndarray, target_rows: int) -> np.ndarray:
    """Pad the row axis with all-zero witnesses (value 0, bf 0, zero
    draws — valid degenerate proofs) so every chunk reuses one compiled
    (B, W) program shape; callers drop the padded tail after unpack."""
    B = packed.shape[0]
    if B == target_rows:
        return packed
    pad = np.zeros((target_rows - B, packed.shape[1]), dtype=np.uint32)
    return np.concatenate([packed, pad], axis=0)


def _point(b64: np.ndarray) -> bn254.G1:
    """64 canonical device bytes -> host affine point (no curve check:
    device outputs feed the verifiers, which reject off-curve bytes)."""
    raw = b64.tobytes()
    if raw == b"\x00" * 64:
        return bn254.G1_IDENTITY
    return bn254.G1(int.from_bytes(raw[:32], "big"),
                    int.from_bytes(raw[32:], "big"))


def unpack_range_outputs(pts_bytes: np.ndarray, scalars: np.ndarray,
                         rounds: int):
    """Device prover outputs -> (proofs, commitments) host objects.

    pts_bytes: (B, 5 + 2*rounds, 64) u8 canonical G1 bytes in the order
        [C, D, com, T1, T2, L_0..L_{r-1}, R_0..R_{r-1}];
    scalars: (B, 5, 16) u32 canonical plain limbs in the order
        [tau, delta, inner_product, ipa.left, ipa.right].
    """
    pts_bytes = np.asarray(pts_bytes, dtype=np.uint8)
    scalars = np.asarray(scalars, dtype=np.uint32)
    proofs: list[rp.RangeProof] = []
    commitments: list[bn254.G1] = []
    for r in range(pts_bytes.shape[0]):
        row = pts_bytes[r]
        sc = [limbs.limbs_to_int(scalars[r, k]) for k in range(5)]
        data = rp.RangeProofData(
            T1=_point(row[3]), T2=_point(row[4]), tau=sc[0],
            C=_point(row[0]), D=_point(row[1]), delta=sc[1],
            inner_product=sc[2])
        ipa = rp.IPA(
            left=sc[3], right=sc[4],
            L=[_point(row[5 + i]) for i in range(rounds)],
            R=[_point(row[5 + rounds + i]) for i in range(rounds)])
        proofs.append(rp.RangeProof(data=data, ipa=ipa))
        commitments.append(_point(row[2]))
    return proofs, commitments
