"""Batched TPU verifiers — the "model" layer of the framework.

Each verifier lowers a zkatdlog proof-system check to batched multi-scalar
multiplications executed on device (SURVEY.md §7 item 3), replacing the
reference's sequential per-proof Go loops (rangecorrectness.go:137-162).
"""

from . import range_verifier  # noqa: F401
