"""Batched TPU verifiers — the "model" layer of the framework.

Each verifier lowers a zkatdlog proof-system check to batched multi-scalar
multiplications executed on device (SURVEY.md §7 item 3), replacing the
reference's sequential per-proof Go loops (rangecorrectness.go:137-162).

Device entry-point contract: ``BatchRangeVerifier.verify(proofs,
commitments)`` (here) and ``ZKVerifier.verify_block(transfers, issues)``
(core/zkatdlog/verifier.py) are the two blocking device dispatch points
the serve/ frontend funnels batches through, and therefore the exact
surface resilience/ shims: ``FaultInjector.wrap`` intercepts them for
chaos testing, and the retry/breaker/watchdog/fallback machinery
assumes each call either returns a complete verdict vector or raises —
no partial results. Keep new verifiers on that contract.
"""

from . import range_verifier  # noqa: F401
