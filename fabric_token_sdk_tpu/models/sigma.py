"""Batched device verification of the zkatdlog Σ-protocols.

Replaces the reference's per-action host loops for the two Schnorr-style
proofs with one device pass per batch (SURVEY.md §2.2 marks both
batchable):

  - type-and-sum (reference crypto/transfer/typeandsum.go:230-277): per
    input the verifier recomputes in_com_i = g^{v_i} h^{b_i} A_i^{-c},
    plus sum_com = h^{eq} S^{-c} and type_com = q^{t} h^{tbf} T^{-c},
    then re-derives the Fiat-Shamir challenge from their bytes.
  - same-type (reference crypto/issue/sametype.go:167-183): one
    com = q^{t} h^{bf} C_T^{-c} per issue action.

Every recomputed point is the SAME shape: a fixed-base part over the
three Pedersen generators (q=ped[0], g=ped[1], h=ped[2]) plus ONE
variable-point windowed multiplication — so a whole batch flattens into
one (rows, 3)-scalar fixed-base MSM + one (rows, 1)-term windowed MSM +
a single batched affine conversion (one Fermat inversion for all rows).
Challenge re-derivation (SHA) stays on host; adjusted points A_i, S are
host point ADDS only (no scalar muls — those all ride the device).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..crypto import bn254
from ..crypto import serialization as ser
from ..crypto.bn254 import fr_neg, g1_add, g1_neg, hash_to_zr
from ..ops import ec, limbs
from .batching import bucket_rows as _bucket_rows
from .range_verifier import affine_batch_to_bytes


@jax.jit
def _sigma_tables_kernel(gens):
    return ec.fixed_base_planes(gens)


@jax.jit
def _sigma_rows_kernel(tables, fixed_sc, var_pts, var_sc):
    """Per row: fixed-base MSM over the 3 Pedersen generators plus one
    windowed variable-point mul; returns canonical affine (R, 2, 16).

    tables: (3, 32, 256, 96); fixed_sc: (R, 3, 16); var_pts: (R, 3, 16);
    var_sc: (R, 16)."""
    fixed = ec.fixed_base_msm(tables, fixed_sc)              # (R, 3, 16)
    var = ec.msm_windowed(var_pts[:, None], var_sc[:, None])  # (R, 3, 16)
    total = ec.add(fixed, var)
    # one batched inversion across every row (leading singleton batch)
    return ec.to_affine_batch(total[None])[0]                # (R, 2, 16)


@dataclass(frozen=True)
class _Row:
    """One recomputed commitment: fixed scalars + var point + var scalar."""

    fixed: tuple          # (s_q, s_g, s_h) ints
    var_point: object     # host G1
    var_scalar: int


class BatchSigmaVerifier:
    """Device-batched type-and-sum / same-type verification for one pp."""

    def __init__(self, pp):
        self.pp = pp
        gens = limbs.points_to_projective_limbs(
            list(pp.pedersen_generators[:3]))
        self.tables = _sigma_tables_kernel(jnp.asarray(gens))

    def prewarm(self, batch_sizes=(1,)) -> None:
        """Compile _sigma_rows_kernel for the row buckets covering
        `batch_sizes` (pp-install availability, tcc.go:90 semantics)."""
        g = bn254.G1_GENERATOR
        for b in batch_sizes:
            self._run_rows([_Row(fixed=(1, 1, 1), var_point=g,
                                 var_scalar=1)] * b)

    # ------------------------------------------------------------ device
    def _run_rows(self, rows: list[_Row]) -> np.ndarray:
        """(R, 64)-byte affine encodings for every row, device-computed."""
        r_bucket = _bucket_rows(max(1, len(rows)))
        fixed = np.zeros((r_bucket, 3, limbs.NLIMBS), dtype=np.uint32)
        var_sc = np.zeros((r_bucket, limbs.NLIMBS), dtype=np.uint32)
        id_pt = limbs.point_to_projective_limbs(bn254.G1_IDENTITY)
        var_pts = np.broadcast_to(
            id_pt, (r_bucket,) + id_pt.shape).copy()
        for i, row in enumerate(rows):
            fixed[i] = limbs.scalars_to_limbs(list(row.fixed))
            var_pts[i] = limbs.point_to_projective_limbs(row.var_point)
            var_sc[i] = limbs.scalars_to_limbs([row.var_scalar])[0]
        aff = _sigma_rows_kernel(self.tables, jnp.asarray(fixed),
                                 jnp.asarray(var_pts), jnp.asarray(var_sc))
        return affine_batch_to_bytes(np.asarray(aff)[:len(rows)])

    # ------------------------------------------------------- same-type
    def verify_same_type(self, proofs: list) -> np.ndarray:
        """Batch of issue SameTypeProof -> bool accept vector."""
        B = len(proofs)
        ok = np.zeros(B, dtype=bool)
        rows, live = [], []
        for i, p in enumerate(proofs):
            if (p is None or p.type_ is None or p.blinding_factor is None
                    or p.challenge is None or p.commitment_to_type is None):
                continue
            live.append(i)
            rows.append(_Row(fixed=(p.type_, 0, p.blinding_factor),
                             var_point=p.commitment_to_type,
                             var_scalar=fr_neg(p.challenge)))
        if not live:
            return ok
        enc = self._run_rows(rows)
        for row_i, i in enumerate(live):
            p = proofs[i]
            com_hex = bytes(enc[row_i]).hex().encode("ascii")
            transcript = ser.SEPARATOR.join(
                [ser.g1_to_bytes(p.commitment_to_type).hex().encode("ascii"),
                 com_hex])
            ok[i] = hash_to_zr(transcript) == p.challenge
        return ok

    # --------------------------------------------------- type-and-sum
    def verify_type_and_sum(self, items: list) -> np.ndarray:
        """items: (TypeAndSumProof, inputs, outputs) triples -> accepts."""
        B = len(items)
        ok = np.zeros(B, dtype=bool)
        rows: list[_Row] = []
        meta = []  # (item idx, n_in, adj_inputs, adj_outputs, sum_)
        for i, (p, inputs, outputs) in enumerate(items):
            if (p is None or p.type_blinding_factor is None
                    or p.type_ is None or p.commitment_to_type is None
                    or p.equality_of_sum is None or p.challenge is None):
                continue
            if (len(p.input_values) < len(inputs)
                    or len(p.input_blinding_factors) < len(inputs)
                    or any(v is None for v in p.input_values[:len(inputs)])):
                continue
            neg_c = fr_neg(p.challenge)
            adj_in, adj_out = [], []
            sum_ = bn254.G1_IDENTITY
            for pt in inputs:
                a = g1_add(pt, g1_neg(p.commitment_to_type))
                adj_in.append(a)
                sum_ = g1_add(sum_, a)
            for pt in outputs:
                a = g1_add(pt, g1_neg(p.commitment_to_type))
                adj_out.append(a)
                sum_ = g1_add(sum_, g1_neg(a))
            for j in range(len(inputs)):
                rows.append(_Row(
                    fixed=(0, p.input_values[j],
                           p.input_blinding_factors[j]),
                    var_point=adj_in[j], var_scalar=neg_c))
            rows.append(_Row(fixed=(0, 0, p.equality_of_sum),
                             var_point=sum_, var_scalar=neg_c))
            rows.append(_Row(fixed=(p.type_, 0, p.type_blinding_factor),
                             var_point=p.commitment_to_type,
                             var_scalar=neg_c))
            meta.append((i, len(inputs), adj_in, adj_out, sum_))
        if not meta:
            return ok
        enc = self._run_rows(rows)
        cursor = 0
        for i, n_in, adj_in, adj_out, sum_ in meta:
            p = items[i][0]
            in_hex = [bytes(enc[cursor + j]).hex().encode("ascii")
                      for j in range(n_in)]
            sum_hex = bytes(enc[cursor + n_in]).hex().encode("ascii")
            type_hex = bytes(enc[cursor + n_in + 1]).hex().encode("ascii")
            cursor += n_in + 2
            # transcript order per typeandsum.go:214,267
            transcript = ser.SEPARATOR.join(
                in_hex + [type_hex, sum_hex]
                + [ser.g1_to_bytes(q).hex().encode("ascii")
                   for q in (adj_in + adj_out
                             + [p.commitment_to_type, sum_])])
            ok[i] = hash_to_zr(transcript) == p.challenge
        return ok
