"""Batched device verification of the zkatdlog Σ-protocols.

Replaces the reference's per-action host loops for the two Schnorr-style
proofs with one device pass per batch (SURVEY.md §2.2 marks both
batchable):

  - type-and-sum (reference crypto/transfer/typeandsum.go:230-277): per
    input the verifier recomputes in_com_i = g^{v_i} h^{b_i} A_i^{-c},
    plus sum_com = h^{eq} S^{-c} and type_com = q^{t} h^{tbf} T^{-c},
    then re-derives the Fiat-Shamir challenge from their bytes.
  - same-type (reference crypto/issue/sametype.go:167-183): one
    com = q^{t} h^{bf} C_T^{-c} per issue action.

Every recomputed point is the SAME shape: a fixed-base part over the
three Pedersen generators (q=ped[0], g=ped[1], h=ped[2]) plus ONE
variable-point windowed multiplication — so a whole batch flattens into
one (rows, 3)-scalar fixed-base MSM + one (rows, 1)-term windowed MSM +
a single batched affine conversion (one Fermat inversion for all rows).
Challenge re-derivation (SHA) stays on host. The adjusted points
A_i = in_i - com_type and the per-action signed sum S also ride the
device (one batched complete-add + a log2(K) tree fold inside the same
jit program — round-5: the host bigint adds were ~1 s per 4k-action
block and sat on the critical path); their affine bytes come back in the
same single-inversion conversion as the row commitments.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..crypto import bn254
from ..crypto import serialization as ser
from ..crypto.bn254 import fr_neg, hash_to_zr
from ..obs import GLOBAL as _METRICS
from ..ops import ec, limbs
from .batching import bucket_rows as _bucket_rows, next_pow2 as _next_pow2
from .range_verifier import affine_batch_to_bytes, hex_ascii

#: Σ-protocol family metadata (HELP independent of call-site order).
_SIGMA_FAMILIES = {
    "sigma_dispatches_total": "Σ-protocol device dispatches, by kind",
    "sigma_rows_total": "Live Σ rows verified, by kind",
    "sigma_pad_rows_total": "Σ padding rows added for bucketing, by kind",
}
for _fam, _help in _SIGMA_FAMILIES.items():
    _METRICS.describe(_fam, _help)


@jax.jit
def _sigma_tables_kernel(gens):
    return ec.fixed_base_planes(gens)


@jax.jit
def _sigma_rows_kernel(tables, fixed_sc, var_pts, var_sc):
    """Per row: fixed-base MSM over the 3 Pedersen generators plus one
    windowed variable-point mul; returns canonical affine (R, 2, 16).

    tables: (3, 32, 256, 96); fixed_sc: (R, 3, 16); var_pts: (R, 3, 16);
    var_sc: (R, 16)."""
    fixed = ec.fixed_base_msm(tables, fixed_sc)              # (R, 3, 16)
    var = ec.msm_windowed(var_pts[:, None], var_sc[:, None])  # (R, 3, 16)
    total = ec.add(fixed, var)
    # one batched inversion across every row (leading singleton batch)
    return ec.to_affine_batch(total[None])[0]                # (R, 2, 16)


@jax.jit
def _tas_block_kernel(tables, ptp, cttp, valid, out_slot, var_sel,
                      fixed_sc, var_sc):
    """The whole type-and-sum batch in ONE device program.

    ptp:      (A, K, 3, 16) input+output points per action (inputs first,
              identity/zero padded); cttp: (A, 3, 16) commitment_to_type.
    valid:    (A, K) bool — real slots; out_slot: (A, K) bool — outputs
              (negated in the sum fold).
    var_sel:  (R,) int32 index into the pool [adj rows | sums | ctt].
    fixed_sc: (R, 3, 16); var_sc: (R, 16).

    Computes adj = pt - ctt (typeandsum.go:230-248's adjusted
    commitments), the per-action signed sum S = sum(adj_in) - sum(adj_out)
    via a log2(K) tree fold, then the Σ-row commitments, and converts
    rows + adj + sums to affine in one batched inversion. Returns
    (R + A*K + A, 64) u8 canonical mathlib G1 bytes (packed on device).
    """
    A, K = ptp.shape[0], ptp.shape[1]
    neg_ctt = jnp.broadcast_to(ec.neg(cttp)[:, None], ptp.shape)
    adj = ec.add(ptp, neg_ctt)                               # (A, K, 3, 16)
    adj = jnp.where(valid[..., None, None], adj, ec.identity((A, K)))
    signed = jnp.where(out_slot[..., None, None], ec.neg(adj), adj)
    k = K
    while k > 1:
        half = k // 2
        signed = ec.add(signed[:, :half], signed[:, half:k])
        k = half
    sums = signed[:, 0]                                      # (A, 3, 16)
    adj_flat = adj.reshape(A * K, 3, limbs.NLIMBS)
    pool = jnp.concatenate([adj_flat, sums, cttp], axis=0)
    var_pts = jnp.take(pool, var_sel, axis=0)                # (R, 3, 16)
    fixed = ec.fixed_base_msm(tables, fixed_sc)              # (R, 3, 16)
    var = ec.msm_windowed(var_pts[:, None], var_sc[:, None])
    total = ec.add(fixed, var)
    allp = jnp.concatenate([total, adj_flat, sums], axis=0)
    from .range_verifier import _limbs_to_bytes_dev

    # bytes leave the device pre-packed: 64 B/point instead of 128 B of
    # limbs, and no host-side conversion over the padded rows
    return _limbs_to_bytes_dev(ec.to_affine_batch(allp[None])[0])


def _start_host_copy(arr) -> None:
    """Fire the device->host transfer without blocking (best-effort)."""
    try:
        arr.copy_to_host_async()
    except (AttributeError, NotImplementedError, TypeError):
        pass


@dataclass(frozen=True)
class _Row:
    """One recomputed commitment: fixed scalars + var point + var scalar."""

    fixed: tuple          # (s_q, s_g, s_h) ints
    var_point: object     # host G1
    var_scalar: int


class BatchSigmaVerifier:
    """Device-batched type-and-sum / same-type verification for one pp."""

    def __init__(self, pp):
        self.pp = pp
        gens = limbs.points_to_projective_limbs(
            list(pp.pedersen_generators[:3]))
        self.tables = _sigma_tables_kernel(jnp.asarray(gens))

    def prewarm(self, batch_sizes=(1,)) -> None:
        """Compile the Σ kernels for the row buckets covering
        `batch_sizes` (pp-install availability, tcc.go:90 semantics):
        the same-type row kernel and the type-and-sum block kernel at a
        2-in/2-out action shape (the production transfer layout)."""
        from types import SimpleNamespace

        g = bn254.G1_GENERATOR
        for b in batch_sizes:
            self._run_rows([_Row(fixed=(1, 1, 1), var_point=g,
                                 var_scalar=1)] * b)

            def mk(n_in, n_out):
                p = SimpleNamespace(
                    type_=1, type_blinding_factor=1, commitment_to_type=g,
                    equality_of_sum=1, challenge=1,
                    input_values=[1] * n_in,
                    input_blinding_factors=[1] * n_in)
                return (p, [g] * n_in, [g] * n_out)

            # _tas_block_kernel shapes are keyed on (A_b, K_b, R_b) with
            # R data-dependent (sum n_in + 2A). Cover every combination a
            # K<=4 block of b actions can produce: uniform 2-in/2-out
            # (K4, R=4b), uniform ownership 1-in/1-out (K2, 3b), mixed
            # mostly-1/1 (K4, ~3b), and 3-in/1-out heavy (K4, 5b).
            # Actions with >4 in+out slots still compile on first sight.
            self.verify_type_and_sum([mk(2, 2)] * b)
            self.verify_type_and_sum([mk(1, 1)] * b)
            self.verify_type_and_sum([mk(2, 2)] + [mk(1, 1)] * (b - 1))
            self.verify_type_and_sum([mk(3, 1)] * b)

    # ------------------------------------------------------------ device
    def _run_rows_async(self, rows: list[_Row]):
        """Dispatch the row kernel; returns collect() -> (R, 64) bytes.

        The device->host copy is started immediately, so callers can
        overlap further dispatches/marshal with the transfer."""
        r_bucket = _bucket_rows(max(1, len(rows)))
        fixed = np.zeros((r_bucket, 3, limbs.NLIMBS), dtype=np.uint32)
        var_sc = np.zeros((r_bucket, limbs.NLIMBS), dtype=np.uint32)
        id_pt = limbs.point_to_projective_limbs(bn254.G1_IDENTITY)
        var_pts = np.broadcast_to(
            id_pt, (r_bucket,) + id_pt.shape).copy()
        for i, row in enumerate(rows):
            fixed[i] = limbs.scalars_to_limbs(list(row.fixed))
            var_pts[i] = limbs.point_to_projective_limbs(row.var_point)
            var_sc[i] = limbs.scalars_to_limbs([row.var_scalar])[0]
        aff = _sigma_rows_kernel(self.tables, jnp.asarray(fixed),
                                 jnp.asarray(var_pts), jnp.asarray(var_sc))
        _start_host_copy(aff)
        return lambda: affine_batch_to_bytes(np.asarray(aff)[:len(rows)])

    def _run_rows(self, rows: list[_Row]) -> np.ndarray:
        """(R, 64)-byte affine encodings for every row, device-computed."""
        return self._run_rows_async(rows)()

    # ------------------------------------------------------- same-type
    def verify_same_type_async(self, proofs: list):
        """Dispatch the same-type batch; returns collect() -> accepts."""
        B = len(proofs)
        ok = np.zeros(B, dtype=bool)
        rows, live = [], []
        for i, p in enumerate(proofs):
            if (p is None or p.type_ is None or p.blinding_factor is None
                    or p.challenge is None or p.commitment_to_type is None):
                continue
            live.append(i)
            rows.append(_Row(fixed=(p.type_, 0, p.blinding_factor),
                             var_point=p.commitment_to_type,
                             var_scalar=fr_neg(p.challenge)))
        if not live:
            return lambda: ok
        _METRICS.counter("sigma_dispatches_total", kind="same_type").add()
        _METRICS.counter("sigma_rows_total",
                         kind="same_type").add(len(live))
        handle = self._run_rows_async(rows)

        def collect() -> np.ndarray:
            enc = handle()
            for row_i, i in enumerate(live):
                p = proofs[i]
                com_hex = bytes(enc[row_i]).hex().encode("ascii")
                transcript = ser.SEPARATOR.join(
                    [ser.g1_to_bytes(
                        p.commitment_to_type).hex().encode("ascii"),
                     com_hex])
                ok[i] = hash_to_zr(transcript) == p.challenge
            return ok

        return collect

    def verify_same_type(self, proofs: list) -> np.ndarray:
        """Batch of issue SameTypeProof -> bool accept vector."""
        return self.verify_same_type_async(proofs)()

    # --------------------------------------------------- type-and-sum
    def verify_type_and_sum_async(self, items: list):
        """Dispatch the type-and-sum batch; returns collect() -> accepts.

        The adjusted commitments, their signed sum, and every Σ-row
        commitment are computed in one device program
        (_tas_block_kernel); the host only packs limbs, hexes the
        returned byte rows, and re-derives the Fiat-Shamir challenges.
        Dispatch and challenge re-derivation are split so callers can
        overlap other device/host work with the kernel + transfer."""
        B = len(items)
        ok = np.zeros(B, dtype=bool)
        live = []
        for i, (p, inputs, outputs) in enumerate(items):
            if (p is None or p.type_blinding_factor is None
                    or p.type_ is None or p.commitment_to_type is None
                    or p.equality_of_sum is None or p.challenge is None):
                continue
            if (len(p.input_values) < len(inputs)
                    or len(p.input_blinding_factors) < len(inputs)
                    or any(v is None for v in p.input_values[:len(inputs)])):
                continue
            live.append((i, p, inputs, outputs))
        if not live:
            return lambda: ok
        NL = limbs.NLIMBS
        A = len(live)
        A_b = _bucket_rows(A)
        # K from a fixed bucket set so shapes stay compile-cacheable
        # (prewarm covers 2 and 4; larger actions are rare)
        K_b = max(2, _next_pow2(max(
            len(ins) + len(outs) for _, _, ins, outs in live)))
        R = sum(len(ins) for _, _, ins, _ in live) + 2 * A
        R_b = _bucket_rows(R)
        ptp = np.zeros((A_b, K_b, 3, NL), dtype=np.uint32)
        valid = np.zeros((A_b, K_b), dtype=bool)
        out_slot = np.zeros((A_b, K_b), dtype=bool)
        fixed_i = np.zeros((R_b, 3), dtype=object)
        var_sel = np.zeros((R_b,), dtype=np.int32)
        var_act = np.zeros((R_b,), dtype=np.int32)  # row -> action index
        # one batched native conversion for EVERY point (ctt first, then
        # the per-action slot points) and one for every scalar
        all_pts = []
        meta = []  # (item idx, action idx, n_in, n_out, first row)
        r = 0
        for a, (i, p, inputs, outputs) in enumerate(live):
            n_in, n_out = len(inputs), len(outputs)
            all_pts.append(p.commitment_to_type)
            for j, pt in enumerate(inputs + outputs):
                all_pts.append(pt)
                valid[a, j] = True
                out_slot[a, j] = j >= n_in
            meta.append((i, a, n_in, n_out, r))
            for j in range(n_in):
                fixed_i[r] = (0, p.input_values[j],
                              p.input_blinding_factors[j])
                var_sel[r] = a * K_b + j
                var_act[r] = a
                r += 1
            fixed_i[r] = (0, 0, p.equality_of_sum)
            var_sel[r] = A_b * K_b + a          # sums section
            var_act[r] = a
            r += 1
            fixed_i[r] = (p.type_, 0, p.type_blinding_factor)
            var_sel[r] = A_b * K_b + A_b + a    # ctt section
            var_act[r] = a
            r += 1
        pts_l = limbs.points_to_projective_limbs(all_pts)  # (M, 3, 16)
        cttp = np.zeros((A_b, 3, NL), dtype=np.uint32)
        cursor = 0
        for a, (i, p, inputs, outputs) in enumerate(live):
            k = len(inputs) + len(outputs)
            cttp[a] = pts_l[cursor]
            for j in range(k):
                ptp[a, j] = pts_l[cursor + 1 + j]
            cursor += 1 + k
        fixed = limbs.scalars_to_limbs(
            [int(v) for row in fixed_i[:r] for v in row]).reshape(r, 3, NL)
        fixed = np.concatenate(
            [fixed, np.zeros((R_b - r, 3, NL), dtype=np.uint32)])
        negc_l = limbs.scalars_to_limbs(
            [fr_neg(p.challenge) for _, p, _, _ in live])   # (A, 16)
        var_sc = np.zeros((R_b, NL), dtype=np.uint32)
        var_sc[:r] = negc_l[var_act[:r]]
        _METRICS.counter("sigma_dispatches_total",
                         kind="type_and_sum").add()
        _METRICS.counter("sigma_rows_total", kind="type_and_sum").add(r)
        _METRICS.counter("sigma_pad_rows_total",
                         kind="type_and_sum").add(R_b - r)
        enc = _tas_block_kernel(
            self.tables, jnp.asarray(ptp), jnp.asarray(cttp),
            jnp.asarray(valid), jnp.asarray(out_slot),
            jnp.asarray(var_sel), jnp.asarray(fixed), jnp.asarray(var_sc))
        _start_host_copy(enc)

        def collect() -> np.ndarray:
            hx = hex_ascii(np.asarray(enc))
            adj0, sum0 = R_b, R_b + A_b * K_b
            for i, a, n_in, n_out, r0 in meta:
                p = items[i][0]
                in_hex = [hx[r0 + j].tobytes() for j in range(n_in)]
                sum_hex = hx[r0 + n_in].tobytes()
                type_hex = hx[r0 + n_in + 1].tobytes()
                adj_hex = [hx[adj0 + a * K_b + j].tobytes()
                           for j in range(n_in + n_out)]
                # transcript order per typeandsum.go:214,267
                transcript = ser.SEPARATOR.join(
                    in_hex + [type_hex, sum_hex] + adj_hex
                    + [ser.g1_to_bytes(
                        p.commitment_to_type).hex().encode("ascii"),
                       hx[sum0 + a].tobytes()])
                ok[i] = hash_to_zr(transcript) == p.challenge
            return ok

        return collect

    def verify_type_and_sum(self, items: list) -> np.ndarray:
        """items: (TypeAndSumProof, inputs, outputs) triples -> accepts."""
        return self.verify_type_and_sum_async(items)()
