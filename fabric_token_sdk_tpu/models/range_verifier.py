"""Batched TPU verification of Bulletproof-style range proofs.

Replaces the reference's sequential verifier loop (reference
token/core/zkatdlog/nogh/v1/crypto/rp/rangecorrectness.go:137-162 and
rp/bulletproof.go:252-333, rp/ipa.go:190-262) with device passes over a
whole batch of proofs:

  Pass 1 (device): for every proof, compute the IPA input commitment K and
    the primed right generators H'_i = y^-i * H_i, returned as canonical
    affine limbs. These are the only group elements the Fiat-Shamir
    transcript needs that are not literal proof bytes. Both ride the
    precomputed 8-bit fixed-base tables of the pp generators — no doublings.

  Host: recompute every challenge (x, y, z from proof bytes; the first IPA
    challenge from pass-1 bytes; round challenges from L_r/R_r bytes) and
    expand the whole verification — including the log-round generator
    folding — into per-proof scalar vectors over fixed term lists.

  Pass 2 (device), fast path: ONE random-linear-combination MSM. Every
    proof's two checks
      eq1 (5 terms):   cg0^(ip-polEval) cg1^tau T1^-x T2^-x^2 Com^-z^2 == O
      eq2 (2n+2r+5):   folded IPA + commitment equation == O
    is weighted by fresh per-proof random scalars (w1_b for eq1, w2_b for
    eq2) and summed; fixed-generator coefficients collapse on host, so the
    device sees one fixed-base MSM plus one windowed MSM over the per-proof
    points (D, C, L_r, R_r, T1, T2, Com). Identity => every proof accepted
    (soundness: a false accept requires predicting the weights; failure
    probability <= 2/r per invalid proof, standard batch verification).

    Single-chip, pass 2's var-MSM partial is NOT a separate dispatch:
    none of its scalars depend on the pass-1 digests (the var terms need
    x, z, the round challenges — recoverable on device from the round
    digests — and the RLC weights, drawn at dispatch time), so the whole
    chunk runs as ONE fused device program with ONE packed upload
    (_pass12_fused_fn). Only the fixed-generator accumulation and the
    finalize fold stay split (they sum ACROSS chunks).

  Pass 2, exact path: when the combined check rejects — or when the caller
    asks — per-proof windowed MSM identity checks give the bit-exact
    accept/reject vector of the host oracle, proof by proof.

Error *messages* for rejected proofs are produced by re-running the host
verifier, preserving the reference's observable error ordering.
"""

from __future__ import annotations

import functools
import hashlib
import os
import secrets
import time
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..crypto import bn254, rp
from ..crypto import serialization as ser
from ..crypto.bn254 import fr_add, fr_batch_inv, fr_inv, fr_mul, fr_sub
from ..native import load_frmont
from ..obs import GLOBAL as _METRICS
from ..obs import RECORDS as _RECORDS
from ..obs import TRACER as _TRACER
from ..obs import BatchRecord, PhaseTimer
from ..ops import ec, limbs
from .batching import bucket_rows as _bucket_rows
from .batching import next_pow2 as _next_pow2
from .batching import pad_rows as _pad_rows

R = bn254.R

# Native host-phase accelerator (C Montgomery Fr); None -> pure Python.
_FRNATIVE = load_frmont()

#: rows per pipeline chunk (single-chip): all chunks' pass-1 kernels are
#: dispatched before any sync, so host stage-2 of chunk k overlaps the
#: device's pass-1 of chunks k+1... (the round-4 profile's host wall).
_CHUNK_ROWS = max(1, int(os.environ.get("FTS_VERIFY_CHUNK", "256")))

#: test/profiling seam: when set to a callable, the single-chip verify
#: path reports every host->device upload and device program launch as
#: _DISPATCH_HOOK(kind), kind in {"chunk_upload", "chunk_dispatch",
#: "finalize"}. perf_profile.py --mode pipeline and the range_verifier
#: single-dispatch test install a counter here (monkeypatched, None in
#: production — zero overhead).
_DISPATCH_HOOK = None


def _count(kind: str) -> None:
    if _DISPATCH_HOOK is not None:
        _DISPATCH_HOOK(kind)


def _fused_pipeline_enabled() -> bool:
    """Single-program chunk pipeline (pass-2 var partial merged into the
    pass-1 chunk program): default on for single-chip AND under a mesh
    (the sharded flavor runs the same fused program per device shard
    with an all-gather partial fold, _pass12_sharded_fn);
    FTS_NO_FUSED_PIPELINE=1 restores the split per-pass dispatches."""
    return not os.environ.get("FTS_NO_FUSED_PIPELINE")


#: Mesh-path family metadata (HELP independent of call-site order).
_MESH_FAMILIES = {
    "mesh_devices": "Devices in the verifier's (dp, tp) mesh",
    "mesh_chunk_dispatches_total":
        "Fused chunk programs dispatched under shard_map, whole mesh",
    "mesh_pad_rows_total":
        "Identity-padded rows added for per-shard chunk divisibility",
    "mesh_allgather_bytes_total":
        "Bytes moved by the per-chunk Jacobian-partial all-gather",
}
for _fam, _help in _MESH_FAMILIES.items():
    _METRICS.describe(_fam, _help)


# --------------------------------------------------------------------------
# host codecs
# --------------------------------------------------------------------------

def affine_limbs_to_bytes(arr: np.ndarray) -> bytes:
    """Canonical affine limbs (2, 16) -> 64-byte mathlib G1 encoding."""
    # limbs are little-endian 16-bit; bytes are big-endian 32 per coord.
    out = bytearray(64)
    for c in range(2):
        coord = np.asarray(arr[c], dtype=np.uint32)
        for i in range(16):
            v = int(coord[15 - i])
            out[c * 32 + 2 * i] = v >> 8
            out[c * 32 + 2 * i + 1] = v & 0xFF
    return bytes(out)


def affine_batch_to_bytes(arr: np.ndarray) -> np.ndarray:
    """Vectorized limb->bytes: (..., 2, 16) uint32 -> (...,) 64-byte rows.

    Returns a uint8 array of shape (..., 64) laid out exactly like
    mathlib G1.Bytes() (x||y, 32-byte big-endian each).
    """
    a = np.asarray(arr, dtype=np.uint32)
    # big-endian limb order, then split each 16-bit limb into two bytes
    a = a[..., ::-1]  # (..., 2, 16) most-significant limb first
    hi = (a >> 8).astype(np.uint8)
    lo = (a & 0xFF).astype(np.uint8)
    inter = np.stack([hi, lo], axis=-1)  # (..., 2, 16, 2)
    return inter.reshape(*a.shape[:-2], 64)


_HEX_LUT = np.frombuffer(b"0123456789abcdef", dtype=np.uint8)


def hex_ascii(a: np.ndarray) -> np.ndarray:
    """Vectorized bytes->lowercase-hex-ascii: (..., K) u8 -> (..., 2K) u8.

    One batch of table lookups replaces per-proof bytes.hex() loops (the
    Fiat-Shamir transcripts hash hex text, reference
    crypto/common/array.go:25-36)."""
    a = np.asarray(a, dtype=np.uint8)
    out = np.empty(a.shape[:-1] + (2 * a.shape[-1],), dtype=np.uint8)
    out[..., 0::2] = _HEX_LUT[a >> 4]
    out[..., 1::2] = _HEX_LUT[a & 0xF]
    return out


# --------------------------------------------------------------------------
# device kernels
# --------------------------------------------------------------------------

# Kernels are jitted separately: fusing them into one graph makes XLA:CPU
# compile superlinearly; split, each compiles in seconds and the persistent
# cache reuses them across runs.
_tables_kernel = jax.jit(ec.fixed_base_planes)
# Split pipeline for when BOTH plane flavors are needed (Pallas path) or a
# flavor is served from the on-disk table cache: one raw table pass feeds
# byte-plane packing and the affine (madd) tables.
_raw_tables_kernel = jax.jit(ec.fixed_base_tables)
_planes_kernel = jax.jit(ec._to_byte_planes)
_affine_planes_kernel = jax.jit(ec.affine_planes_from_tables)


# --------------------------------------------------------------------------
# fixed-base table cache (opt-in via FTS_TABLE_CACHE_DIR)
# --------------------------------------------------------------------------
# Byte planes hold exact uint8 values (0..255) whatever plane_dtype() is,
# so an .npz of uint8 arrays round-trips bit-identically AND is backend
# portable (a CPU-written cache warms a TPU run and vice versa). Keyed by
# the same generator digest as _PARAMS_CACHE: two pp sets differing in any
# generator can never share a cache file.

def _table_cache_path(bit_length: int, digest: str, flavor: str):
    base = os.environ.get("FTS_TABLE_CACHE_DIR")
    if not base or not digest:
        return None
    import pathlib

    return (pathlib.Path(base)
            / f"fbtables_n{bit_length}_{digest}_{flavor}.npz")


def _table_cache_load(bit_length: int, digest: str, flavor: str):
    f = _table_cache_path(bit_length, digest, flavor)
    if f is None or not f.exists():
        return None
    try:
        with np.load(f) as z:
            arr = z["planes"]
    except Exception:
        return None  # truncated/corrupt cache file: rebuild, don't crash
    return jnp.asarray(arr).astype(ec.plane_dtype())


def _table_cache_save(bit_length: int, digest: str, flavor: str,
                      planes: jnp.ndarray) -> None:
    f = _table_cache_path(bit_length, digest, flavor)
    if f is None or f.exists():
        return
    try:
        f.parent.mkdir(parents=True, exist_ok=True)
        arr = np.asarray(
            jax.device_get(planes.astype(jnp.float32))).astype(np.uint8)
        tmp = f.with_name(f.name + f".tmp{os.getpid()}")
        np.savez(tmp, planes=arr)
        # np.savez appends .npz to names without it
        os.replace(str(tmp) + ".npz", f)
    except Exception:
        pass  # cache is best-effort; the build already succeeded


def _limbs_to_bytes_dev(aff: jnp.ndarray) -> jnp.ndarray:
    """Device twin of affine_batch_to_bytes: (..., 2, 16) u32 -> (..., 64)
    u8 mathlib G1 bytes. Halves the device->host transfer (the tunnel is
    a measured cost at B>=1024) and removes the host-side conversion."""
    a = aff[..., ::-1]
    hi = (a >> 8).astype(jnp.uint8)
    lo = (a & 0xFF).astype(jnp.uint8)
    inter = jnp.stack([hi, lo], axis=-1)  # (..., 2, 16, 2)
    return inter.reshape(*a.shape[:-2], 64)


@jax.jit
def _affine_bytes_rows_kernel(pts):
    """(B, T, 3, 16) projective -> (B, T, 64) u8 canonical bytes."""
    return _limbs_to_bytes_dev(ec.to_affine_batch(pts))


@jax.jit
def _affine_bytes_kernel(pts):
    """(B, 3, 16) projective -> (B, 64) u8 canonical bytes."""
    return _limbs_to_bytes_dev(ec.to_affine(pts))


def _pallas_enabled() -> bool:
    """Fused Pallas kernels: TPU backend only (the kernels are written
    against Mosaic lowering constraints; on any other non-CPU backend the
    Triton lowering would likely fail — ADVICE r4), opt-out via
    FTS_NO_PALLAS=1. The CPU backend and the CPU-mesh dryrun keep the XLA
    one-hot path."""
    if os.environ.get("FTS_NO_PALLAS"):
        return False
    return jax.default_backend() == "tpu"


@jax.jit
def _k_pass_kernel(tables, k_idx, k_fixed_sc, dc_pts, dc_sc):
    """K = fixed-base part + x*D + C, per proof: (B, 3, 16).

    The K-equation generators are gathered from the full table set inside
    the jit (k_idx) so no second device-resident copy of the tables exists.
    """
    fixed = ec.fixed_base_msm(jnp.take(tables, k_idx, axis=0), k_fixed_sc)
    var = ec.msm_var_mixed(dc_pts, dc_sc)
    return ec.add(fixed, var)


@jax.jit
def _rgp_gather_kernel(tables, rgp_idx, scalars):
    """Right-generator fold: gather H_i tables in-jit, then per-term mul."""
    return ec.fixed_base_gather(jnp.take(tables, rgp_idx, axis=0), scalars)


@jax.jit
def _exact_pass_kernel(eq1_pts, eq1_sc, eq2_pts, eq2_sc):
    """Two per-proof MSM identity checks; returns (B,) bool accept vector.

    Round 7: the interior is the lazy-carry mixed-affine MSM
    (ec.msm_var_mixed) — all inputs here are host-marshalled affine
    points / identities (Z in {1, 0}), its precondition."""
    ok1 = ec.is_identity(ec.msm_var_mixed(eq1_pts, eq1_sc))
    ok2 = ec.is_identity(ec.msm_var_mixed(eq2_pts, eq2_sc))
    return jnp.logical_and(ok1, ok2)


@jax.jit
def _exact_var_tail_kernel(f1_pt, f2_pt, eq1_pts, eq1_sc, eq2_pts, eq2_sc):
    """Fused-exact tail: per-proof fixed-base results + small var MSMs.

    The deterministic exact pass is the adversarial DoS floor (one forged
    proof forces it for its chunk); 87% of its terms are fixed generators,
    so those ride the accumulated Pallas fixed-base kernel and only the
    ~15 per-proof points stay variable-base — since round 7 on the
    lazy-carry mixed-affine walk (ec.msm_var_mixed; inputs are
    host-marshalled affine points, Z in {1, 0})."""
    ok1 = ec.is_identity(ec.add(f1_pt, ec.msm_var_mixed(eq1_pts, eq1_sc)))
    ok2 = ec.is_identity(ec.add(f2_pt, ec.msm_var_mixed(eq2_pts, eq2_sc)))
    return jnp.logical_and(ok1, ok2)


# standalone var-MSM dispatch: the legacy split pipeline's pass-2 partial
# (FTS_NO_FUSED_PIPELINE) and the mesh bisect path. Round 7 swaps the
# eager one-hot walk for the lazy-carry mixed-affine interior; every
# caller feeds _reconstruct_points / host-marshalled points (Z in {1, 0}).
_var_partial_kernel = jax.jit(ec.msm_var_mixed)


@jax.jit
def _finalize_kernel(tables, fixed_sc, partials):
    """Fixed-generator MSM + fold of per-chunk var partials -> () bool."""
    fixed_pt = ec.fixed_base_msm(tables, fixed_sc)
    var_pt = ec._tree_sum_shrink(partials)
    return ec.is_identity(ec.add(fixed_pt, var_pt))


@jax.jit
def _finalize_total_kernel(tables, fixed_sc, total):
    """Finalize against the chain-folded var total -> () bool.

    The cross-chunk fold no longer happens here: every fused chunk
    program adds its own var partial onto the previous chunk's running
    total (the ``prev`` input of _pass12_fused_fn), so the last chunk's
    ``total`` output already carries the whole batch's var point and the
    finalize shrinks to fixed-MSM + one add + identity — O(1) in the
    chunk count, no jnp.stack over per-chunk partials. The stacked
    _finalize_kernel stays for the bisect and split paths, which need
    per-chunk partials individually."""
    fixed_pt = ec.fixed_base_msm(tables, fixed_sc)
    return ec.is_identity(ec.add(fixed_pt, total))


@jax.jit
def _exact_mixed_tail_kernel(planes_f2, planes_f1, f2_sc, f1_sc,
                             eq1_pts, eq1_sc, eq2_pts, eq2_sc):
    """Exact tail with lazified FIXED-base gathers: the CPU/XLA twin of
    the Pallas fused-exact tail (_exact_var_tail_kernel's caller branch).

    The per-proof fixed-generator sums ride ec.fixed_base_msm_mixed —
    the digit-0-masked madd/lazy-carry gather chain over affine 64-byte
    planes (one normalize per window chain) — instead of being stuffed
    into the projective var MSM as 2n+4 extra variable-base terms. The
    small per-proof tails stay on the lazy-carry mixed-affine var MSM.
    planes_f2 covers [G.., H.., P, Q], planes_f1 [cg0, cg1]; layout
    matches the Pallas branch, so verdicts are bit-identical (the accept
    bit is an identity check, invariant to the fold regrouping)."""
    f2_pt = ec.fixed_base_msm_mixed(planes_f2, f2_sc)
    f1_pt = ec.fixed_base_msm_mixed(planes_f1, f1_sc)
    ok1 = ec.is_identity(ec.add(f1_pt, ec.msm_var_mixed(eq1_pts, eq1_sc)))
    ok2 = ec.is_identity(ec.add(f2_pt, ec.msm_var_mixed(eq2_pts, eq2_sc)))
    return jnp.logical_and(ok1, ok2)


# --------------------------------------------------------------------------
# verifier parameters (device-resident, cached per pp)
# --------------------------------------------------------------------------

@dataclass(frozen=True)
class RangeVerifierParams:
    """Device-resident public parameters for one (pp, bit_length) config.

    Fixed-base table layout (one 8-bit windowed byte-plane table per
    generator, ec.fixed_base_planes): index order is
        [G_0..G_{n-1}, H_0..H_{n-1}, P, Q, cg0, cg1, S_G]
    where S_G = sum_i G_i (K's G-coefficients are all -z, so the whole G
    block collapses to one term in the K equation).
    """

    bit_length: int
    rounds: int
    left_gen: list          # host points G_i
    right_gen: list         # host points H_i
    P: object
    Q: object
    commitment_gen: list    # [cg0, cg1] (pedersen_generators[1:3])
    tables: jnp.ndarray     # (2n+5, 32, 256, 96) bf16 planes, all gens
    k_idx: jnp.ndarray      # (n+2,) indexes of H_i ++ [P, S_G] into tables
    rgp_idx: jnp.ndarray    # (n,) indexes of H_i into tables
    # precomputed transcript prefix: bytes of right_gen' are per-proof, but
    # left_gen ++ [Q] bytes are pp constants.
    left_gen_bytes: tuple
    q_bytes: bytes
    # transposed AFFINE (64, 256)-contraction tables for the fused Pallas
    # kernels (TPU only; None on CPU): Montgomery affine (x, y) byte
    # planes feeding the mixed-add (madd) fold — 2/3 the select-matmul
    # rows and HBM of the projective 96-plane layout. tables_t_all covers
    # every generator in the `tables` index order; rgp/k are views/gathers
    # of it (pre-built so per-call jnp.take copies disappear too).
    tables_t_all: jnp.ndarray | None = None   # (2n+5, 32, 64, 256)
    tables_t_rgp: jnp.ndarray | None = None   # (n, 32, 64, 256)
    tables_t_k: jnp.ndarray | None = None     # (n+2, 32, 64, 256)
    #: generator digest keying the on-disk table cache (empty when the
    #: params were built without one); the lazy exact-pass affine planes
    #: (_exact_mixed_planes) reuse it so a warm "affine" cache file makes
    #: the mixed exact tail free to enable.
    cache_digest: str = ""

    @classmethod
    def from_pp(cls, pp, cache_digest: str = "") -> "RangeVerifierParams":
        rpp = pp.range_proof_params
        n = rpp.bit_length
        s_g = bn254.G1_IDENTITY
        for g in rpp.left_generators:
            s_g = bn254.g1_add(s_g, g)
        gen_points = (list(rpp.left_generators) + list(rpp.right_generators)
                      + [rpp.P, rpp.Q] + list(pp.pedersen_generators[1:3])
                      + [s_g])
        pallas_on = _pallas_enabled()
        tables = _table_cache_load(n, cache_digest, "proj")
        aff_planes = (_table_cache_load(n, cache_digest, "affine")
                      if pallas_on else None)
        if tables is None or (pallas_on and aff_planes is None):
            gen_dev = jnp.asarray(
                limbs.points_to_projective_limbs(gen_points))
            if pallas_on:
                # one raw table pass feeds both plane flavors
                raw = _raw_tables_kernel(gen_dev)
                if tables is None:
                    tables = _planes_kernel(raw)
                    _table_cache_save(n, cache_digest, "proj", tables)
                if aff_planes is None:
                    aff_planes = _affine_planes_kernel(raw)
                    _table_cache_save(n, cache_digest, "affine", aff_planes)
                del raw
            else:
                # CPU/XLA path: raw tables never materialize (fused in-jit)
                tables = _tables_kernel(gen_dev)
                _table_cache_save(n, cache_digest, "proj", tables)
        k_idx = list(range(n, 2 * n)) + [2 * n, 2 * n + 4]  # H_i ++ [P, S_G]
        tables_t_all = tables_t_rgp = tables_t_k = None
        if pallas_on:
            from ..ops import pallas_fb

            tables_t_all = jax.jit(pallas_fb.transpose_planes)(aff_planes)
            tables_t_rgp = tables_t_all[n:2 * n]
            # H_i ++ P (contiguous n..2n) ++ S_G
            tables_t_k = jnp.concatenate(
                [tables_t_all[n:2 * n + 1],
                 tables_t_all[2 * n + 4:2 * n + 5]], axis=0)
        return cls(
            bit_length=n,
            rounds=rpp.number_of_rounds,
            left_gen=list(rpp.left_generators),
            right_gen=list(rpp.right_generators),
            P=rpp.P,
            Q=rpp.Q,
            commitment_gen=list(pp.pedersen_generators[1:3]),
            tables=tables,
            k_idx=jnp.asarray(k_idx),
            rgp_idx=jnp.arange(n, 2 * n),
            left_gen_bytes=tuple(
                ser.g1_to_bytes(p).hex().encode("ascii")
                for p in rpp.left_generators),
            q_bytes=ser.g1_to_bytes(rpp.Q).hex().encode("ascii"),
            tables_t_all=tables_t_all,
            tables_t_rgp=tables_t_rgp,
            tables_t_k=tables_t_k,
            cache_digest=cache_digest,
        )


# Cache params per pp identity: table construction costs one device pass and
# ~hundreds of MB; validator instances sharing a pp share the tables.
_PARAMS_CACHE: dict = {}


def _params_for(pp) -> RangeVerifierParams:
    """Key on a digest of EVERY generator baked into the tables — two pp
    sets differing in any generator must never share cached tables. The
    same digest keys the on-disk table cache (FTS_TABLE_CACHE_DIR)."""
    import hashlib

    rpp = pp.range_proof_params
    h = hashlib.sha256()
    for p in ([rpp.P, rpp.Q] + list(rpp.left_generators)
              + list(rpp.right_generators)
              + list(pp.pedersen_generators[1:3])):
        h.update(ser.g1_to_bytes(p))
    key = (rpp.bit_length, h.digest())
    if key not in _PARAMS_CACHE:
        _PARAMS_CACHE[key] = RangeVerifierParams.from_pp(
            pp, cache_digest=h.hexdigest()[:16])
    return _PARAMS_CACHE[key]


#: (bit_length, digest) -> (planes_f2, planes_f1) device pair, or None
#: when the mixed exact tail is unavailable for that params set.
_EXACT_MIXED_CACHE: dict = {}


def _exact_mixed_planes(params):
    """Affine (madd) planes for the exact-pass FIXED-base tails, lazily.

    The CPU/XLA param build materializes only the projective 96-byte
    planes; the mixed exact tail needs the affine 64-plane flavor, whose
    from-scratch build costs one batched Fermat inversion over
    T*32*256 table entries (tens of seconds at n=16, minutes at n=64) —
    far too much to impose on every process that might run one exact
    pass. So: serve it from the on-disk table cache when a warm "affine"
    file exists (written by any prior Pallas param build or forced build
    sharing the generator digest), build it only under FTS_EXACT_MIXED=1
    (recovering the raw tables from the resident byte planes — exact,
    values are 0..255), and disable entirely under FTS_EXACT_MIXED=0.
    Returns (planes_f2 [G..,H..,P,Q], planes_f1 [cg0,cg1]) or None
    (callers fall back to the all-variable-base exact kernel)."""
    mode = os.environ.get("FTS_EXACT_MIXED", "")
    if mode == "0":
        return None
    n = params.bit_length
    key = (n, params.cache_digest)
    if key in _EXACT_MIXED_CACHE:
        return _EXACT_MIXED_CACHE[key]
    planes = _table_cache_load(n, params.cache_digest, "affine")
    if planes is None:
        if mode != "1":
            _EXACT_MIXED_CACHE[key] = None
            return None
        raw = jax.jit(ec._from_byte_planes)(
            params.tables.astype(jnp.float32))
        planes = _affine_planes_kernel(raw)
        _table_cache_save(n, params.cache_digest, "affine", planes)
    out = (planes[:2 * n + 2], planes[2 * n + 2:2 * n + 4])
    _EXACT_MIXED_CACHE[key] = out
    return out


def _pad_terms(pts: np.ndarray, sc: np.ndarray, t_target: int):
    """Pad the term axis to a shared bucket with identity points / zero
    scalars (exact no-ops in the MSM) so distinct equations reuse one
    compiled kernel shape."""
    B, T = pts.shape[0], pts.shape[1]
    if T == t_target:
        return pts, sc
    id_pt = limbs.point_to_projective_limbs(bn254.G1_IDENTITY)
    pad_pts = np.broadcast_to(id_pt, (B, t_target - T) + id_pt.shape)
    pad_sc = np.zeros((B, t_target - T, limbs.NLIMBS), dtype=np.uint32)
    return (np.concatenate([pts, pad_pts], axis=1),
            np.concatenate([sc, pad_sc], axis=1))


def _structure_ok(proof: rp.RangeProof, rounds: int) -> bool:
    """Host-side nil/shape checks (bulletproof.go:254-264, ipa.go:193-201)."""
    d = proof.data
    if d is None or proof.ipa is None:
        return False
    for el in (d.T1, d.T2, d.C, d.D):
        if el is None:
            return False
    if d.inner_product is None or d.tau is None or d.delta is None:
        return False
    ipa = proof.ipa
    if ipa.left is None or ipa.right is None:
        return False
    if len(ipa.L) != len(ipa.R) or len(ipa.L) != rounds:
        return False
    if any(p is None for p in ipa.L) or any(p is None for p in ipa.R):
        return False
    return True


def _fold_coefficients(challenge_pairs: list[tuple[int, int]], n: int,
                       invert_first_half: bool) -> list[int]:
    """Expand IPA generator folding into per-index coefficients.

    Left generators fold as lg'[i] = x^-1 lg[i] + x lg[i+half]
    (reference ipa.go:343-356), so coefficient of G_j is the product over
    rounds of x_r when j falls in the high half at round r, else x_r^-1.
    Right generators fold with x and x^-1 swapped.

    Round 1 splits on the full-width halves, so its challenge binds to the
    index's MOST-significant bit; building the coefficient table by repeated
    doubling appends one bit per step with the last-processed challenge on
    the MSB — hence the challenges are consumed in reverse round order.

    challenge_pairs: (x_r, x_r^-1) per round — inverses are batch-computed
    by the caller (one Fermat inversion per proof, not one per round).
    """
    coeffs = [1]
    for x, x_inv in reversed(challenge_pairs):
        lo, hi = (x_inv, x) if invert_first_half else (x, x_inv)
        coeffs = [fr_mul(c, lo) for c in coeffs] + \
                 [fr_mul(c, hi) for c in coeffs]
    assert len(coeffs) == n
    return coeffs


@dataclass
class _ProofTranscript:
    x: int
    y: int
    z: int
    y_pows: list[int]
    yinv_pows: list[int]
    pol_eval: int
    k_fixed_scalars: list[int]
    k_var_scalars: list[int]
    # native path: the same scalars as packed 32-byte-LE blobs (set when
    # the _frmont extension produced them; consumers then skip the
    # int->limb conversions entirely)
    yinv_packed: bytes | None = None
    pol_eval_packed: bytes | None = None
    k_fixed_packed: bytes | None = None


def _phase_a_challenges_batch(proofs, commitments, ch):
    """x, y, z challenges for every proof in `ch`, one vectorized assembly
    (reference bulletproof.go:266-282: x = H(hex(T1)||hex(T2)),
    y = H(hex(C)||hex(D)||hex(Com)), z = H(bytes32(y)))."""
    L = len(ch)
    sep = np.frombuffer(ser.SEPARATOR, dtype=np.uint8)
    ptx = np.empty((L, 2, 64), dtype=np.uint8)
    pty = np.empty((L, 3, 64), dtype=np.uint8)
    for row, i in enumerate(ch):
        d = proofs[i].data
        ptx[row, 0] = np.frombuffer(ser.g1_to_bytes(d.T1), dtype=np.uint8)
        ptx[row, 1] = np.frombuffer(ser.g1_to_bytes(d.T2), dtype=np.uint8)
        pty[row, 0] = np.frombuffer(ser.g1_to_bytes(d.C), dtype=np.uint8)
        pty[row, 1] = np.frombuffer(ser.g1_to_bytes(d.D), dtype=np.uint8)
        pty[row, 2] = np.frombuffer(ser.g1_to_bytes(commitments[i]),
                                    dtype=np.uint8)
    hx = hex_ascii(ptx)
    hy = hex_ascii(pty)
    msgx = np.empty((L, 258), dtype=np.uint8)
    msgx[:, :128] = hx[:, 0]
    msgx[:, 128:130] = sep
    msgx[:, 130:] = hx[:, 1]
    msgy = np.empty((L, 388), dtype=np.uint8)
    msgy[:, :128] = hy[:, 0]
    msgy[:, 128:130] = sep
    msgy[:, 130:258] = hy[:, 1]
    msgy[:, 258:260] = sep
    msgy[:, 260:] = hy[:, 2]
    out = []
    for r in range(L):
        x = int.from_bytes(hashlib.sha256(msgx[r].data).digest(),
                           "big") % R
        y = int.from_bytes(hashlib.sha256(msgy[r].data).digest(),
                           "big") % R
        z = int.from_bytes(
            hashlib.sha256(y.to_bytes(32, "big")).digest(), "big") % R
        out.append((x, y, z))
    return out


def _host_phase_a(proof: rp.RangeProof, commitment, params,
                  xyz=None) -> _ProofTranscript:
    """Challenges + K-equation scalars from literal proof bytes."""
    n = params.bit_length
    d = proof.data
    if xyz is not None:
        x, y, z = xyz
    else:
        x = rp.challenge_x(d.T1, d.T2)
        y, z = rp.challenges_y_z(d.C, d.D, commitment)

    if _FRNATIVE is not None:
        # fused native assembly (frmont.c phase_a, parity-pinned)
        raw = _FRNATIVE.phase_a(
            n, y.to_bytes(32, "little") + z.to_bytes(32, "little")
            + (d.delta % R).to_bytes(32, "little"))
        s = 32
        return _ProofTranscript(
            x=x, y=y, z=z,
            y_pows=[], yinv_pows=[], pol_eval=0, k_fixed_scalars=[],
            k_var_scalars=[x, 1],
            yinv_packed=raw[n * s:2 * n * s],
            pol_eval_packed=raw[2 * n * s:(2 * n + 1) * s],
            k_fixed_packed=raw[(2 * n + 1) * s:])

    z_sq = fr_mul(z, z)
    y_inv = fr_inv(y)

    y_pows, yinv_pows = [1], [1]
    for i in range(1, n):
        y_pows.append(fr_mul(y, y_pows[-1]))
        yinv_pows.append(fr_mul(y_inv, yinv_pows[-1]))

    ipy = 0
    ip2 = 0
    p2 = 1
    for i in range(n):
        ipy = fr_add(ipy, y_pows[i])
        if i > 0:
            p2 = fr_mul(2, p2)
        ip2 = fr_add(ip2, p2)
    z_cube = fr_mul(z_sq, z)
    pol_eval = fr_sub(fr_mul(fr_sub(z, z_sq), ipy), fr_mul(z_cube, ip2))

    # K = x*D + C - z*sum G_i + sum (z + z^2 2^i y^-i) H_i - delta*P
    # fixed term order (k_tables): H_i ++ [P, S_G]; variable: [D, C].
    k_fixed = []
    for i in range(n):
        k_fixed.append(
            fr_add(z, fr_mul(z_sq, fr_mul(pow(2, i, R), yinv_pows[i]))))
    k_fixed.append(fr_sub(0, d.delta))   # P
    k_fixed.append(fr_sub(0, z))         # S_G = sum G_i
    k_var = [x, 1]
    return _ProofTranscript(x=x, y=y, z=z, y_pows=y_pows,
                            yinv_pows=yinv_pows, pol_eval=pol_eval,
                            k_fixed_scalars=k_fixed, k_var_scalars=k_var)


# --------------------------------------------------------------------------
# batched Fiat-Shamir transcript assembly (host, numpy-vectorized)
# --------------------------------------------------------------------------

_XIPA_LAYOUTS: dict = {}


def _xipa_layout(params):
    """Precomputed byte template + fill indices for the first-IPA-challenge
    message (reference ipa.go:159-173).

    The message is marshal_std_bytes_slices([array_bytes, SEPARATOR,
    zr_to_bytes(ip)]) where array_bytes joins fixed-length hex items:
    n per-proof H' points, the constant left generators, Q, and the
    per-proof K. Every length is static for a given bit_length, so one
    uint8 template + three fancy-index fills assemble the whole batch.
    """
    # key covers EVERY byte baked into the template: two pp sets differing
    # in any generator must never share a cached layout
    key = (params.bit_length, params.q_bytes, params.left_gen_bytes)
    if key in _XIPA_LAYOUTS:
        return _XIPA_LAYOUTS[key]
    n = params.bit_length
    hexlen = 128
    sep = ser.SEPARATOR
    buf = bytearray()
    rgp_off = []
    for _ in range(n):
        rgp_off.append(len(buf))
        buf += b"\x00" * hexlen + sep
    for lg in params.left_gen_bytes:
        buf += lg + sep
    buf += params.q_bytes + sep
    k_off = len(buf)
    buf += b"\x00" * hexlen
    array_bytes = bytes(buf)
    oct1 = b"\x04" + ser._der_len(len(array_bytes)) + array_bytes
    oct2 = b"\x04" + ser._der_len(len(sep)) + sep
    oct3 = b"\x04" + ser._der_len(32) + b"\x00" * 32
    body = oct1 + oct2 + oct3
    msg = b"\x30" + ser._der_len(len(body)) + body
    base = len(msg) - len(body) + (len(oct1) - len(array_bytes))
    tmpl = np.frombuffer(msg, dtype=np.uint8).copy()
    rgp_idx = np.concatenate(
        [np.arange(base + o, base + o + hexlen) for o in rgp_off])
    k_idx = np.arange(base + k_off, base + k_off + hexlen)
    ip_idx = np.arange(len(tmpl) - 32, len(tmpl))
    _XIPA_LAYOUTS[key] = (tmpl, rgp_idx, k_idx, ip_idx)
    return _XIPA_LAYOUTS[key]


def _hex_ascii_dev(a: jnp.ndarray) -> jnp.ndarray:
    """Device twin of hex_ascii: (..., K) u8 -> (..., 2K) u8 ascii."""
    lut = jnp.asarray(_HEX_LUT)
    hi = jnp.take(lut, (a >> 4).astype(jnp.int32))
    lo = jnp.take(lut, (a & 0xF).astype(jnp.int32))
    return jnp.stack([hi, lo], axis=-1).reshape(*a.shape[:-1],
                                               2 * a.shape[-1])


_XIPA_DEV_FNS: dict = {}


_POW2_MONT: dict = {}


def _pow2_mont_limbs(n: int) -> np.ndarray:
    """(n, 16) uint32: 2^i in Fr Montgomery form (device constants for the
    on-device K-coefficient derivation)."""
    if n not in _POW2_MONT:
        _POW2_MONT[n] = np.stack([
            limbs.int_to_limbs((pow(2, i, R) * limbs.MONT_R) % R)
            for i in range(n)])
    return _POW2_MONT[n]


@functools.partial(jax.jit, static_argnames=("n",))
def _derive_pass1_scalars(sc4, n: int):
    """Expand per-proof (y^-1, z, delta, x) into the pass-1 scalar arrays
    ON DEVICE: 4 uploaded scalars replace n + (n+2) + 2 of them (the
    measured round-5 wall is host->device transfer on the tunneled chip).

    sc4: (B, 4, 16) PLAIN limbs. Returns (yinv_pows (B, n, 16),
    k_fixed (B, n+2, 16), k_var (B, 2, 16)) plain limbs, exactly the
    vectors _host_phase_a produces (k_fixed[i] = z + z^2 2^i y^-i;
    P -> -delta; S_G -> -z; k_var = [x, 1]).
    """
    from ..ops import field

    FR = field.FR
    B = sc4.shape[0]
    yinv_m = field.to_mont(sc4[:, 0], FR)
    z_m = field.to_mont(sc4[:, 1], FR)
    delta_m = field.to_mont(sc4[:, 2], FR)

    # y^-i powers by log-depth doubling: step k maps 2^k computed powers
    # to 2^(k+1) with ONE (B, 2^k, 16) mont_mul — ~6 wide steps instead
    # of an n-step sequential scan (the scan was dispatch-depth-bound at
    # chunk shapes: 12 ms of the 87 ms fused pass-1).
    pows_m = jnp.broadcast_to(FR.r1_arr, (B, 1, limbs.NLIMBS))
    shifter = yinv_m                               # y^-(2^k)
    while pows_m.shape[1] < n:
        nxt = field.mont_mul(pows_m, shifter[:, None], FR)
        pows_m = jnp.concatenate([pows_m, nxt], axis=1)
        if pows_m.shape[1] < n:
            shifter = field.mont_mul(shifter, shifter, FR)
    pows_m = pows_m[:, :n]                         # (B, n, 16) y^-i mont
    z_sq = field.mont_mul(z_m, z_m, FR)
    two_i = jnp.asarray(_pow2_mont_limbs(n))       # (n, 16) mont
    term = field.mont_mul(
        field.mont_mul(z_sq[:, None], two_i[None], FR), pows_m, FR)
    kf = field.add(jnp.broadcast_to(z_m[:, None], term.shape), term, FR)
    k_fixed_m = jnp.concatenate(
        [kf, field.neg(delta_m, FR)[:, None], field.neg(z_m, FR)[:, None]],
        axis=1)
    one_plain = jnp.zeros((B, 1, limbs.NLIMBS),
                          dtype=jnp.uint32).at[..., 0].set(1)
    k_var = jnp.concatenate([sc4[:, 3][:, None], one_plain], axis=1)
    return (field.from_mont(pows_m, FR), field.from_mont(k_fixed_m, FR),
            k_var)


@functools.partial(jax.jit, static_argnames=("rounds",))
def _round_digests(xy_m, inf, rounds: int):
    """IPA round-challenge digests ON DEVICE: (B, nv, 2, 16) Montgomery
    affine points + identity mask -> (B, rounds, 8) digest words of
    H(hex(L_r) || '||' || hex(R_r)) (reference ipa.go:224-252 via
    ipa_round_challenge). The L/R points ride the stage-1 upload, so the
    host stops serializing/hashing 2*rounds points per proof."""
    from ..ops import field
    from ..ops import sha256 as dsha

    B = xy_m.shape[0]
    Lp = xy_m[:, 2:2 + rounds]
    Rp = xy_m[:, 2 + rounds:2 + 2 * rounds]
    li = inf[:, 2:2 + rounds]
    ri = inf[:, 2 + rounds:2 + 2 * rounds]

    def pbytes(p, m):
        plain = field.from_mont(p, field.FP)
        b = _limbs_to_bytes_dev(plain)
        return jnp.where((m != 0)[..., None], jnp.zeros_like(b), b)

    lb = _hex_ascii_dev(pbytes(Lp, li))
    rb = _hex_ascii_dev(pbytes(Rp, ri))
    sep = jnp.broadcast_to(
        jnp.asarray(np.frombuffer(ser.SEPARATOR, dtype=np.uint8)),
        (B, rounds, 2))
    tail = jnp.broadcast_to(jnp.asarray(dsha.pad_tail(258)),
                            (B, rounds, 62))
    msg = jnp.concatenate([lb, sep, rb, tail], axis=-1)
    return dsha.digest_padded(
        msg.reshape(B * rounds, 320)).reshape(B, rounds, 8)


@functools.partial(jax.jit, static_argnames=("rounds",))
def _derive_var_scalars(sc4, w12, rdig, rounds: int):
    """Weighted pass-2 var-MSM scalars ON DEVICE — the derivation that
    lets the var partial ride the pass-1 chunk program: every var term's
    scalar is a product of phase-a challenges (x, z from sc4), IPA round
    challenges (recovered from the device round digests, no host round
    trip) and the per-proof RLC weights (w12, drawn host-side at
    dispatch time). Nothing here touches the pass-1 x_ipa digests — only
    the FIXED-generator scalars do, which is why the merge is sound.

    sc4:  (B, 4, 16) plain limbs (y^-1, z, delta, x) — the stage-1 row.
    w12:  (B, 2, 16) plain limbs (w1, w2); all-zero on padded rows.
    rdig: (B, rounds, 8) u32 big-endian digest words of the round hashes
          (_round_digests output).

    Returns (B, nv, 16) plain limbs in the _weight_equations var order
    [D, C, L_r.., R_r.., T1, T2, Com]:
        [-x*w2, -w2, -xr^2*w2 .., -xr^-2*w2 .., -x*w1, -x^2*w1, -z^2*w1]
    bit-identical to the host fr_mul(w, fr_sub(0, s)) path: the round
    challenge is digest mod R, the Fermat inverse equals fr_batch_inv's,
    and padded rows carry w = 0 so every scalar there is 0 (their points
    are identity — exact MSM no-ops).
    """
    from ..ops import field

    FR = field.FR
    B = sc4.shape[0]
    z_m = field.to_mont(sc4[:, 1], FR)
    x_m = field.to_mont(sc4[:, 3], FR)
    w1_m = field.to_mont(w12[:, 0], FR)
    w2_m = field.to_mont(w12[:, 1], FR)

    # digest words (BE, word 0 most significant) -> 16-bit LE limbs:
    # limb 2k = lo(word 7-k), limb 2k+1 = hi(word 7-k). The raw 256-bit
    # value is < 2^256 ~ 5.3R; one conditional subtract brings it under
    # 2^256 - R < 5R, inside mont_mul's single-lazy-operand value bound
    # (rule R3, ops/field.py), so to_mont lands exactly on
    # to_mont(digest mod R) — no full reduction needed.
    lim = jnp.stack([rdig & 0xFFFF, rdig >> 16], axis=-1)
    lim = lim[..., ::-1, :].reshape(B, rounds, limbs.NLIMBS)
    dig = field._cond_sub_mod(
        jnp.concatenate(
            [lim, jnp.zeros((B, rounds, 1), dtype=jnp.uint32)], axis=-1),
        FR)
    xr_m = field.to_mont(dig, FR)
    xrinv_m = field.inv(xr_m, FR)     # one vectorized Fermat chain

    w2b = jnp.broadcast_to(w2_m[:, None], xr_m.shape)
    head = jnp.stack([field.mont_mul(x_m, w2_m, FR), w2_m], axis=1)
    mid = jnp.concatenate(
        [field.mont_mul(field.mont_mul(xr_m, xr_m, FR), w2b, FR),
         field.mont_mul(field.mont_mul(xrinv_m, xrinv_m, FR), w2b, FR)],
        axis=1)
    tail = jnp.stack(
        [field.mont_mul(x_m, w1_m, FR),
         field.mont_mul(field.mont_mul(x_m, x_m, FR), w1_m, FR),
         field.mont_mul(field.mont_mul(z_m, z_m, FR), w1_m, FR)], axis=1)
    prod_m = jnp.concatenate([head, mid, tail], axis=1)   # (B, nv, 16)
    # every var term is the NEGATIVE of the product above; neg commutes
    # with from_mont, so one uniform neg covers the whole layout
    return field.from_mont(field.neg(prod_m, FR), FR)


_PASS12_FUSED_FNS: dict = {}
_PASS12_SHARDED_FNS: dict = {}


def _pass12_layout(params):
    """Packed-row offsets shared by the fused and sharded chunk programs:
    (nv, o_inf, o_ip, o_w) for the u32 layout
    [sc4 64 | xy-as-u16-pairs nv*2*8 | inf nv | ip 8 | w12 32]."""
    nv = 2 + 2 * params.rounds + 3
    o_inf = 64 + nv * 16
    o_ip = o_inf + nv
    o_w = o_ip + 8
    return nv, o_inf, o_ip, o_w


def _pass12_body(params):
    """Un-jitted chunk body shared by _pass12_fused_fn (single chip) and
    _pass12_sharded_fn (per device shard under shard_map): unpack the
    single uploaded u32 row -> derive pass-1 scalar vectors ->
    fixed-base folds -> affine bytes -> transcript SHA -> round digests
    -> weighted var scalars -> var-MSM partial. Returns
    body(packed, rgp_fn, kfixed_fn, mul2_fn, var_fn) ->
    ((B, 8) x_ipa digests, (B, rounds, 8) round digests,
    (B, nv, 3, 16) projective points, (3, 16) var partial)."""
    n = params.bit_length
    rr = params.rounds
    nv, o_inf, o_ip, o_w = _pass12_layout(params)
    xipa = _xipa_device_fn(params)
    o_xy = 64

    def body(packed, rgp_fn, kfixed_fn, mul2_fn, var_fn):
        B = packed.shape[0]
        sc4 = packed[:, :o_xy].reshape(B, 4, limbs.NLIMBS)
        xyw = packed[:, o_xy:o_inf].reshape(B, nv, 2, 8)
        xy = jnp.stack([xyw & 0xFFFF, xyw >> 16], axis=-1).reshape(
            B, nv, 2, limbs.NLIMBS)
        inf = packed[:, o_inf:o_ip].astype(jnp.uint8)
        ipw = packed[:, o_ip:o_w]
        ip_u8 = jnp.stack(
            [ipw & 0xFF, (ipw >> 8) & 0xFF, (ipw >> 16) & 0xFF,
             ipw >> 24], axis=-1).reshape(B, 32).astype(jnp.uint8)
        w12 = packed[:, o_w:].reshape(B, 2, limbs.NLIMBS)

        yinv, k_fixed, dc_sc = _derive_pass1_scalars(sc4, n)
        pts = _reconstruct_points(xy, inf)
        k_pt = ec.add(kfixed_fn(k_fixed), mul2_fn(pts[:, :2], dc_sc))
        digests = xipa(
            _limbs_to_bytes_dev(ec.to_affine_batch(rgp_fn(yinv))),
            _limbs_to_bytes_dev(ec.to_affine(k_pt)), ip_u8)
        rdig = _round_digests(xy, inf, rr)
        var_sc = _derive_var_scalars(sc4, w12, rdig, rr)
        partial = var_fn(pts.reshape(B * nv, 3, limbs.NLIMBS),
                         var_sc.reshape(B * nv, limbs.NLIMBS))
        return digests, rdig, pts, partial

    return body


def _pass12_xla_kernels(tables, rgp_idx, k_idx):
    """(rgp_fn, kfixed_fn, mul2_fn, var_fn) — XLA twin kernel bodies."""
    return (lambda yinv: ec.fixed_base_gather(
                jnp.take(tables, rgp_idx, axis=0), yinv),
            lambda kf: ec.fixed_base_msm(
                jnp.take(tables, k_idx, axis=0), kf),
            ec.msm_var_mixed,
            ec.msm_var_mixed)


def _pass12_pallas_kernels(t_rgp, t_k):
    """(rgp_fn, kfixed_fn, mul2_fn, var_fn) — Pallas VMEM kernel bodies."""
    from ..ops import pallas_fb

    return (lambda yinv: pallas_fb.fixed_base_gather_fused(t_rgp, yinv),
            lambda kf: pallas_fb.fixed_base_msm_fused(t_k, kf),
            pallas_fb.mul2_rows_fused,
            pallas_fb.msm_var_fused)


def _pass12_fused_fn(params):
    """ONE jitted device program for a whole chunk's pass-1 AND its
    pass-2 var-MSM partial (the single-program chunk pipeline): see
    _pass12_body for the program structure. One dispatch + one packed
    upload per chunk where the round-6 pipeline issued ~3 calls + 1
    upload — per-call tunnel latency (measured ~2.5 ms/dispatch,
    ~6.5 ms/device_put) was the next wall.

    Both backends share the program STRUCTURE; only the kernel bodies
    switch: TPU runs the Pallas VMEM kernels, CPU/XLA the gather +
    msm_var_mixed twins — so the merged pipeline (including the device
    round-digest and var-scalar derivations) is exercised by the CPU CI,
    not only on chip.

    ``prev`` chains the cross-chunk fold through the pipeline (ROOFLINE
    "Remaining items" #2): chunk k's program adds its own partial onto
    chunk k-1's running ``total``, so the last chunk's total already
    holds the whole batch's var point and the finalize shrinks to
    _finalize_total_kernel — the per-verify stack+tree-fold dispatch is
    gone. Chaining costs one point add per chunk INSIDE the program and
    does not serialize the host: dispatches stay async, XLA sequences
    the data dependency device-side.

    Returns (run, nv, o_inf, o_ip, o_w); run(tables, rgp_idx, k_idx,
    packed, prev) (XLA) or run(t_rgp, t_k, packed, prev) (Pallas) ->
    (digests, rdig, pts, partial, total)."""
    pallas_on = params.tables_t_rgp is not None
    key = (params.bit_length, params.q_bytes, params.left_gen_bytes,
           pallas_on)
    if key in _PASS12_FUSED_FNS:
        return _PASS12_FUSED_FNS[key]

    body = _pass12_body(params)
    nv, o_inf, o_ip, o_w = _pass12_layout(params)

    if pallas_on:

        @jax.jit
        def run(t_rgp, t_k, packed, prev):
            digests, rdig, pts, partial = body(
                packed, *_pass12_pallas_kernels(t_rgp, t_k))
            return digests, rdig, pts, partial, ec.add(partial, prev)
    else:

        @jax.jit
        def run(tables, rgp_idx, k_idx, packed, prev):
            digests, rdig, pts, partial = body(
                packed, *_pass12_xla_kernels(tables, rgp_idx, k_idx))
            return digests, rdig, pts, partial, ec.add(partial, prev)

    _PASS12_FUSED_FNS[key] = (run, nv, o_inf, o_ip, o_w)
    return _PASS12_FUSED_FNS[key]


def _pass12_sharded_fn(params, mesh):
    """The fused chunk program under shard_map: every device runs
    _pass12_body on its row shard, then the per-shard var partials are
    all-gathered (96 uint32 per device riding ICI) and tree-folded
    locally, exactly the collective pattern of _make_sharded_combined —
    point addition is not a psum-able ring op, so gather+fold is the
    TPU-native collective for it.

    The chunk's rows shard over the WHOLE (dp, tp) device grid: the
    var-MSM term axis is the flattened (rows * nv) axis, so sharding
    rows over dp x tp IS the batch-on-dp / terms-on-tp decomposition
    with strictly less communication than replicating pass-1 across tp
    would cost (pass-1 runs once per row, nowhere twice). Padded rows
    carry identity points + zero weights — exact MSM no-ops — so ragged
    batches just round up to a shard-divisible bucket.

    This replaces the legacy mesh arrangement (one giant single-chunk
    program over the split per-stage closures) that never finished
    compiling on the dryrun hosts: per-shard chunks keep every compiled
    program at the same small shapes the single-chip pipeline uses.

    Returns (run, nv, o_inf, o_ip, o_w); run has the _pass12_fused_fn
    signature and the same (digests, rdig, pts, partial, total) outputs,
    with partial/total replicated across the mesh (chunk chaining and
    the finalize read them anywhere)."""
    pallas_on = params.tables_t_rgp is not None
    key = (params.bit_length, params.q_bytes, params.left_gen_bytes,
           pallas_on, mesh)
    if key in _PASS12_SHARDED_FNS:
        return _PASS12_SHARDED_FNS[key]

    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import _shard_map

    axes = tuple(mesh.axis_names)
    body = _pass12_body(params)
    nv, o_inf, o_ip, o_w = _pass12_layout(params)

    def _fold(partial, prev):
        gathered = jax.lax.all_gather(partial, axes)    # (ndev, 3, 16)
        folded = ec._tree_sum_shrink(gathered)
        return folded, ec.add(folded, prev)

    out_specs = (P(axes, None), P(axes, None, None),
                 P(axes, None, None, None), P(), P())
    if pallas_on:

        def shard_body(t_rgp, t_k, packed, prev):
            digests, rdig, pts, partial = body(
                packed, *_pass12_pallas_kernels(t_rgp, t_k))
            folded, total = _fold(partial, prev)
            return digests, rdig, pts, folded, total

        sharded = _shard_map(
            shard_body, mesh=mesh,
            in_specs=(P(), P(), P(axes, None), P()),
            out_specs=out_specs)
    else:

        def shard_body(tables, rgp_idx, k_idx, packed, prev):
            digests, rdig, pts, partial = body(
                packed, *_pass12_xla_kernels(tables, rgp_idx, k_idx))
            folded, total = _fold(partial, prev)
            return digests, rdig, pts, folded, total

        sharded = _shard_map(
            shard_body, mesh=mesh,
            in_specs=(P(), P(), P(), P(axes, None), P()),
            out_specs=out_specs)

    run = jax.jit(sharded)
    _PASS12_SHARDED_FNS[key] = (run, nv, o_inf, o_ip, o_w)
    return _PASS12_SHARDED_FNS[key]


@jax.jit
def _reconstruct_points(xy, inf_mask):
    """(B, T, 2, 16) affine Montgomery limbs + (B, T) u8 identity mask ->
    (B, T, 3, 16) projective (identity = (0 : 1 : 0))."""
    B, T = xy.shape[0], xy.shape[1]
    r1 = jnp.asarray(np.array(limbs.int_to_limbs(limbs.P_R1_INT),
                              dtype=np.uint32))
    zed = jnp.where((inf_mask == 0)[..., None],
                    jnp.broadcast_to(r1, (B, T, limbs.NLIMBS)),
                    jnp.zeros((B, T, limbs.NLIMBS), dtype=jnp.uint32))
    return jnp.concatenate([xy, zed[:, :, None]], axis=2)


def _xipa_device_fn(params):
    """Jitted on-device x_ipa transcript assembly + SHA-256.

    (rgp_bytes (B, n, 64) u8, k_bytes (B, 64) u8, ip (B, 32) u8)
    -> (B, 8) u32 digest words. The transcript is built by concatenating
    constant template segments (from _xipa_layout) with device-hexed
    pass-1 bytes, then hashed by the batched SHA-256 kernel — so only 32
    digest bytes per proof ever cross the host link (the measured
    transfer wall on the tunneled chip).
    """
    from ..ops import sha256 as dsha

    key = (params.bit_length, params.q_bytes, params.left_gen_bytes)
    if key in _XIPA_DEV_FNS:
        return _XIPA_DEV_FNS[key]
    n = params.bit_length
    tmpl, rgp_idx, k_idx, ip_idx = _xipa_layout(params)
    L = len(tmpl)
    start = int(rgp_idx[0])
    rgp_end = start + n * 130          # n x (128 hex + 2 sep)
    k_start, k_end = int(k_idx[0]), int(k_idx[0]) + 128
    ip_start = int(ip_idx[0])
    assert ip_start + 32 == L
    prefix = tmpl[:start]
    mid = tmpl[rgp_end:k_start]
    tail1 = tmpl[k_end:ip_start]
    shapad = dsha.pad_tail(L)
    sep2 = np.frombuffer(ser.SEPARATOR, dtype=np.uint8)

    @jax.jit
    def run(rgp_bytes, k_bytes, ip_bytes):
        B = rgp_bytes.shape[0]
        hx = _hex_ascii_dev(rgp_bytes)                   # (B, n, 128)
        sep_b = jnp.broadcast_to(jnp.asarray(sep2), (B, n, 2))
        rgp_seg = jnp.concatenate([hx, sep_b], axis=2).reshape(B, n * 130)
        const = lambda seg: jnp.broadcast_to(jnp.asarray(seg),
                                             (B, len(seg)))
        msg = jnp.concatenate(
            [const(prefix), rgp_seg, const(mid), _hex_ascii_dev(k_bytes),
             const(tail1), ip_bytes, const(shapad)], axis=1)
        return dsha.digest_padded(msg)

    _XIPA_DEV_FNS[key] = run
    return run


def _xipa_batch(params, proofs, live, rgp_u8: np.ndarray,
                k_u8: np.ndarray) -> list[int]:
    """First IPA challenge for every live proof, one vectorized assembly.

    rgp_u8: (L, n, 64) u8 pass-1 H' bytes; k_u8: (L, 64) u8 K bytes.
    """
    tmpl, rgp_idx, k_idx, ip_idx = _xipa_layout(params)
    L = len(live)
    msg = np.tile(tmpl, (L, 1))
    msg[:, rgp_idx] = hex_ascii(rgp_u8).reshape(L, -1)
    msg[:, k_idx] = hex_ascii(k_u8)
    ip_np = np.frombuffer(
        b"".join(ser.zr_to_bytes(proofs[i].data.inner_product)
                 for i in live), dtype=np.uint8).reshape(L, 32)
    msg[:, ip_idx] = ip_np
    return [int.from_bytes(hashlib.sha256(msg[r].data).digest(), "big") % R
            for r in range(L)]


def _round_challenges_batch(proofs, live, rounds: int) -> np.ndarray:
    """IPA round challenges for every live proof (reference ipa.go:224-252):
    hash(hex(L_r) || hex(R_r)) per round, assembled as one uint8 batch.

    Returns an (L, rounds) object array of ints.
    """
    L = len(live)
    pts = np.empty((L, rounds, 2, 64), dtype=np.uint8)
    for row, i in enumerate(live):
        ipa = proofs[i].ipa
        for r_i in range(rounds):
            pts[row, r_i, 0] = np.frombuffer(
                ser.g1_to_bytes(ipa.L[r_i]), dtype=np.uint8)
            pts[row, r_i, 1] = np.frombuffer(
                ser.g1_to_bytes(ipa.R[r_i]), dtype=np.uint8)
    hexed = hex_ascii(pts)                       # (L, rounds, 2, 128)
    msg = np.empty((L, rounds, 258), dtype=np.uint8)
    msg[..., :128] = hexed[..., 0, :]
    msg[..., 128:130] = np.frombuffer(ser.SEPARATOR, dtype=np.uint8)
    msg[..., 130:] = hexed[..., 1, :]
    out = np.empty((L, rounds), dtype=object)
    for row in range(L):
        for r_i in range(rounds):
            out[row, r_i] = int.from_bytes(
                hashlib.sha256(msg[row, r_i].data).digest(), "big") % R
    return out


@dataclass
class _ProofEquations:
    """Per-proof eq1/eq2 scalars, split fixed-generator vs proof points.

    fixed order (matches RangeVerifierParams.tables):
        G_0..G_{n-1}, H_0..H_{n-1}, P, Q, cg0, cg1, S_G(unused->0)
    var order: D, C, L_0..L_{r-1}, R_0..R_{r-1}, T1, T2, Com

    Native path: the same vectors as packed 32-byte-LE blobs instead of
    int lists (exactly one of the representations is populated).
    """

    fixed: list[int]
    var: list[int]
    fixed_packed: bytes | None = None
    var_packed: bytes | None = None


def _host_phase_b(proof: rp.RangeProof, ts: _ProofTranscript,
                  x_ipa: int, round_ch: list[int], params,
                  ch_packed: bytes | None = None,
                  inv_packed: bytes | None = None) -> _ProofEquations:
    """Round folding -> combined scalar vectors.

    Challenges arrive precomputed: x_ipa from _xipa_batch (it needs the
    pass-1 bytes), round_ch from _round_challenges_batch (proof bytes
    only, so the caller overlaps them with the device pass). ch_packed /
    inv_packed carry the native-path packed forms when _FRNATIVE is live
    (inversions batched across the WHOLE chunk by the caller — one
    Fermat inversion per chunk, not per proof).
    """
    n = params.bit_length
    d = proof.data
    ipa = proof.ipa
    x, z = ts.x, ts.z
    z_sq = fr_mul(z, z)
    x_sq = fr_mul(x, x)

    if _FRNATIVE is not None:
        # fused native assembly (frmont.c phase_b, parity-pinned)
        if ch_packed is None:
            ch_packed = limbs.pack_scalars(round_ch)
            inv_packed = _FRNATIVE.batch_inv(ch_packed)
        scalars = limbs.pack_scalars(
            [ipa.left, ipa.right, ts.z, x, x_ipa, d.inner_product, d.tau,
             d.delta]) + ts.pol_eval_packed
        out = _FRNATIVE.phase_b(n, len(round_ch), scalars, ts.yinv_packed,
                                ch_packed, inv_packed)
        split = (2 * n + 5) * 32
        return _ProofEquations(fixed=[], var=[],
                               fixed_packed=out[:split],
                               var_packed=out[split:])

    # one batched inversion for every round challenge
    round_inv = fr_batch_inv(round_ch)
    pairs = list(zip(round_ch, round_inv))
    a_coeffs = _fold_coefficients(pairs, n, invert_first_half=True)
    b_coeffs = _fold_coefficients(pairs, n, invert_first_half=False)

    a, b = ipa.left, ipa.right
    fixed = []
    for j in range(n):                                   # G_j  (eq2)
        fixed.append(fr_add(fr_mul(a, a_coeffs[j]), z))
    for j in range(n):                                   # H_j  (eq2)
        coeff = fr_mul(fr_mul(b, b_coeffs[j]), ts.yinv_pows[j])
        coeff = fr_sub(coeff, z)
        coeff = fr_sub(coeff, fr_mul(z_sq,
                                     fr_mul(pow(2, j, R), ts.yinv_pows[j])))
        fixed.append(coeff)
    fixed.append(d.delta)                                # P    (eq2)
    fixed.append(fr_mul(x_ipa, fr_sub(fr_mul(a, b), d.inner_product)))  # Q
    fixed.append(fr_sub(d.inner_product, ts.pol_eval))   # cg0  (eq1)
    fixed.append(d.tau)                                  # cg1  (eq1)
    fixed.append(0)                                      # S_G  (unused here)

    var = [fr_sub(0, x), R - 1]                          # D, C (eq2)
    for xr in round_ch:                                  # L_r
        var.append(fr_sub(0, fr_mul(xr, xr)))
    for xr_inv in round_inv:                             # R_r
        var.append(fr_sub(0, fr_mul(xr_inv, xr_inv)))
    var.append(fr_sub(0, x))                             # T1   (eq1)
    var.append(fr_sub(0, x_sq))                          # T2   (eq1)
    var.append(fr_sub(0, z_sq))                          # Com  (eq1)
    return _ProofEquations(fixed=fixed, var=var)


@dataclass
class _ChunkStage:
    """Stage-1 state of one chunk in the single-program pipeline.

    ``partial``/``weights``/``total`` are populated only on the merged
    path (_pass12_fused_fn / _pass12_sharded_fn): the pass-2 var-MSM
    partial is already computed by the stage-1 dispatch, and the RLC
    weights it used (drawn host-side at dispatch time) are kept so stage
    2 can accumulate the matching fixed-generator scalars. ``total`` is
    the running cross-chunk fold (this chunk's partial added onto the
    previous chunk's total, computed INSIDE the chunk program) — the
    last chunk's total feeds _combined_finalize_total directly. On the
    legacy split path all three are None and stage 2 dispatches
    _combined_chunk as before."""

    transcripts: dict
    digests_dev: object          # (B, 8) x_ipa digest words, device
    rdig_dev: object | None      # (B, rounds, 8) round digests, device
    pts_dev: object              # (B, nv, 3, 16) projective proof points
    partial: object | None       # (3, 16) weighted var-MSM chunk partial
    weights: dict | None         # {proof_idx: (w1, w2)} ints
    total: object | None         # (3, 16) running cross-chunk var fold


def _make_sharded_combined(mesh, fused: bool = False):
    """Sharded RLC pass: var-MSM terms sharded over EVERY mesh device;
    each device runs the windowed MSM on its term shard, partial points
    are all-gathered (96 uint32/device riding ICI) and folded locally —
    point addition is not a psum-able ring op, so gather+fold is the
    TPU-native collective for it (SURVEY.md §2.5).

    With fused=True (TPU mesh) each device's term shard runs the Pallas
    VMEM-resident var-MSM kernel instead of the XLA one-hot walk — the
    sharded path no longer shards the slow kernels (VERDICT r4 ask #2).
    """
    from jax.sharding import PartitionSpec as P

    axes = tuple(mesh.axis_names)

    def body(fixed_pt, pts, sc):
        if fused:
            from ..ops import pallas_fb

            partial = pallas_fb.msm_var_fused(pts, sc)  # local term shard
        else:
            partial = ec.msm_var_mixed(pts, sc)
        gathered = jax.lax.all_gather(partial, axes)  # (ndev, 3, 16)
        total = ec._tree_sum_shrink(gathered)
        return ec.is_identity(ec.add(fixed_pt, total))

    from ..parallel.mesh import _shard_map

    # version-skew shim (check_vma on new jax, check_rep on old): the
    # identity-point constants are unvarying either way
    sharded = _shard_map(
        body, mesh=mesh,
        in_specs=(P(), P(axes, None, None), P(axes, None)),
        out_specs=P(),
    )

    @jax.jit
    def run(tables, fixed_sc, var_pts, var_sc):
        fixed_pt = ec.fixed_base_msm(tables, fixed_sc)
        return sharded(fixed_pt, var_pts, var_sc)

    return run


def _make_sharded_pass1(mesh, params):
    """Row-sharded fused pass-1: every device runs the Pallas select+fold
    kernels on its row shard, converts to canonical bytes, and hashes its
    x_ipa transcripts locally (device SHA-256); pure data-parallel, no
    communication (VERDICT r4 ask #2 — the multi-chip path rides the SAME
    fused kernels as single-chip). Output: (B, 8) digest words."""
    from jax.sharding import PartitionSpec as P

    from ..ops import pallas_fb

    axes = tuple(mesh.axis_names)
    xipa = _xipa_device_fn(params)

    def body(t_rgp, t_k, yinv, k_fixed, dc_pts, dc_sc, ip_bytes):
        rgp = pallas_fb.fixed_base_gather_fused(t_rgp, yinv)
        k = ec.add(pallas_fb.fixed_base_msm_fused(t_k, k_fixed),
                   pallas_fb.mul2_rows_fused(dc_pts, dc_sc))
        return xipa(_limbs_to_bytes_dev(ec.to_affine_batch(rgp)),
                    _limbs_to_bytes_dev(ec.to_affine(k)), ip_bytes)

    from ..parallel.mesh import _shard_map

    sharded = _shard_map(
        body, mesh=mesh,
        in_specs=(P(), P(),
                  P(axes, None, None), P(axes, None, None),
                  P(axes, None, None, None), P(axes, None, None),
                  P(axes, None)),
        out_specs=P(axes, None),
    )
    return jax.jit(sharded)


class BatchRangeVerifier:
    """Vectorized range-proof verification for one public-parameter set.

    With `mesh` (a (dp, tp) jax.sharding.Mesh) the production pipeline
    runs SPMD: the SAME fused pass12 chunk program as single-chip runs
    per device shard under shard_map (rows sharded over the whole
    device grid, identity-padded to shard divisibility), with one tiny
    all-gather point-fold of the 96-uint32 var partials per chunk —
    BASELINE config 5's shape. FTS_NO_FUSED_PIPELINE=1 restores the
    legacy split per-stage dispatches (one giant single-chunk program
    under the mesh).
    """

    def __init__(self, pp, mesh=None):
        self.params = _params_for(pp)
        self.mesh = mesh
        self._n_shard = int(mesh.devices.size) if mesh is not None else 1
        if mesh is not None:
            _METRICS.gauge("mesh_devices").set(float(mesh.devices.size))
        # fused Pallas kernels under the mesh (TPU); the CPU-mesh dryrun
        # keeps the XLA path via _pallas_enabled() -> tables_t_rgp is None
        self._fused_sharded = (mesh is not None
                               and self.params.tables_t_rgp is not None)
        self._pass1_sharded = (_make_sharded_pass1(mesh, self.params)
                               if self._fused_sharded else None)
        self._combined_sharded = (
            _make_sharded_combined(mesh, fused=self._fused_sharded)
            if mesh is not None else None)
        #: which verification strategy decided the last verify():
        #: "combined" (the RLC identity — computed inside the stage-1
        #: merged chunk program on the default single-chip path, or by
        #: the split dispatch under a mesh / FTS_NO_FUSED_PIPELINE),
        #: "exact" (per-proof checks ran, whether requested or forced by
        #: a rejecting RLC), or "structure-only" (nothing reached the
        #: device). Exposed for tests/metrics.
        self.last_path: str | None = None

    def _put_rows(self, arr: np.ndarray) -> jnp.ndarray:
        """Upload with the batch axis sharded over the whole mesh (or
        plain device_put single-chip)."""
        _count("chunk_upload")
        if self.mesh is None:
            return jnp.asarray(arr)
        from jax.sharding import NamedSharding, PartitionSpec as P

        spec = P(tuple(self.mesh.axis_names),
                 *([None] * (arr.ndim - 1)))
        return jax.device_put(arr, NamedSharding(self.mesh, spec))

    # ------------------------------------------------------------------
    def prewarm(self, batch_sizes=(1,)) -> float:
        """Compile every device kernel for the row buckets covering
        `batch_sizes`, at pp-install time rather than first-verify time.

        Drives one full verify (combined pass rejects the synthetic batch,
        so the exact pass compiles too) per bucket with a structurally
        valid all-generators proof. Returns elapsed seconds. The warm-up
        story for validators: call once after table build; first REAL
        verify then runs at steady-state latency (VERDICT r2 weak #7).
        """
        return sum(self.prewarm_shapes(batch_sizes).values())

    def prewarm_shapes(self, batch_sizes=(1,)) -> dict:
        """Per-shape variant of ``prewarm``: returns ``{batch_size:
        elapsed_seconds}`` so callers (the serve/ prewarm manager) can
        account each compiled executable separately."""
        import time as _time

        params = self.params
        g = bn254.G1_GENERATOR
        fake = rp.RangeProof(
            data=rp.RangeProofData(T1=g, T2=g, C=g, D=g, inner_product=1,
                                   tau=1, delta=1),
            ipa=rp.IPA(left=1, right=1,
                       L=[g] * params.rounds, R=[g] * params.rounds))
        out = {}
        for b in batch_sizes:
            t0 = _time.perf_counter()
            self.verify([fake] * b, [g] * b)
            out[b] = _time.perf_counter() - t0
        return out

    def kernel_cost(self, batch_size: int) -> dict | None:
        """XLA cost analysis (FLOPs, bytes accessed) of the standalone
        per-chunk variable-base MSM (``ec.msm_var_mixed``) at the padded
        chunk bucket covering ``batch_size``.

        Since round 7 this kernel is no longer a separate hot-path
        dispatch — the default single-chip pipeline computes the var
        partial inside the merged chunk program (see
        ``kernel_cost_fused``'s ``pass12_fused`` kind for that cost) —
        but the same MSM body still runs standalone on the mesh, bisect
        and FTS_NO_FUSED_PIPELINE paths, so its roofline stays tracked.

        Lowering only, never compiles: ``jit(...).lower`` traces the
        kernel against ShapeDtypeStructs and ``Lowered.cost_analysis``
        reads the estimate off the unoptimized module. Feeds the
        ``profile_bucket_*`` roofline gauges (obs/profiling.py); any
        failure (backend without cost analysis, jax API drift) returns
        None rather than disturbing the serving path.
        """
        try:
            rows = _bucket_rows(min(int(batch_size), _CHUNK_ROWS))
            nv = 2 + 2 * self.params.rounds + 3
            pts = jax.ShapeDtypeStruct((rows * nv, 3, limbs.NLIMBS),
                                       jnp.uint32)
            sc = jax.ShapeDtypeStruct((rows * nv, limbs.NLIMBS),
                                      jnp.uint32)
            cost = _var_partial_kernel.lower(pts, sc).cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else None
            if not isinstance(cost, dict):
                return None
            return {"kernel": "msm_var_mixed", "chunk_rows": rows,
                    "points": rows * nv,
                    "flops": cost.get("flops"),
                    "bytes_accessed": cost.get(
                        "bytes_accessed", cost.get("bytes accessed"))}
        except Exception:
            return None

    def kernel_cost_fused(self, batch_size: int) -> dict | None:
        """Cost analysis of the fused device programs at the padded chunk
        bucket covering ``batch_size``.

        Kinds: ``pass12_fused`` is the merged single-program chunk
        pipeline (pass-1 + weighted pass-2 var partial, one dispatch) —
        available on EVERY backend, since the CPU/XLA flavor runs the
        same program structure with XLA kernel bodies; ``fb_msm_t`` and
        ``msm_var_fused`` are the individual Pallas kernels and lower on
        the TPU path only.

        Same lower-only discipline as ``kernel_cost``; each estimate is
        published on the stable ``profile_bucket_*`` families under its
        own ``kind`` label (obs/profiling.py) — no new metric family.
        Returns ``{kind: cost_dict}`` for whichever programs lowered, or
        None."""
        params = self.params
        try:
            from ..obs.profiling import PROFILER

            rows = _bucket_rows(min(int(batch_size), _CHUNK_ROWS))
            nv = 2 + 2 * params.rounds + 3
            out = {}

            run, _nv, _oi, _op, o_w = _pass12_fused_fn(params)
            packed = jax.ShapeDtypeStruct((rows, o_w + 32), jnp.uint32)
            prev = jax.ShapeDtypeStruct((3, limbs.NLIMBS), jnp.uint32)
            if params.tables_t_rgp is not None:
                args = (jax.ShapeDtypeStruct(params.tables_t_rgp.shape,
                                             params.tables_t_rgp.dtype),
                        jax.ShapeDtypeStruct(params.tables_t_k.shape,
                                             params.tables_t_k.dtype),
                        packed, prev)
            else:
                args = (jax.ShapeDtypeStruct(params.tables.shape,
                                             params.tables.dtype),
                        jax.ShapeDtypeStruct(params.rgp_idx.shape,
                                             params.rgp_idx.dtype),
                        jax.ShapeDtypeStruct(params.k_idx.shape,
                                             params.k_idx.dtype),
                        packed, prev)
            c = PROFILER.capture_kernel_cost("pass12_fused", rows, run,
                                             *args)
            if c is not None:
                out["pass12_fused"] = c

            if params.tables_t_k is not None:
                from ..ops import pallas_fb

                tk = jax.ShapeDtypeStruct(params.tables_t_k.shape,
                                          params.tables_t_k.dtype)
                sc_k = jax.ShapeDtypeStruct(
                    (rows, params.tables_t_k.shape[0], limbs.NLIMBS),
                    jnp.uint32)
                vp = jax.ShapeDtypeStruct((rows * nv, 3, limbs.NLIMBS),
                                          jnp.uint32)
                vs = jax.ShapeDtypeStruct((rows * nv, limbs.NLIMBS),
                                          jnp.uint32)
                c = PROFILER.capture_kernel_cost(
                    "fb_msm_t", rows, pallas_fb.fixed_base_msm_fused,
                    tk, sc_k)
                if c is not None:
                    out["fb_msm_t"] = c
                c = PROFILER.capture_kernel_cost(
                    "msm_var_fused", rows, pallas_fb.msm_var_fused,
                    vp, vs)
                if c is not None:
                    out["msm_var_fused"] = c
            return out or None
        except Exception:
            return None

    def verify(self, proofs: list[rp.RangeProof], commitments: list,
               exact: bool = False) -> np.ndarray:
        """Returns a bool accept vector, one entry per (proof, commitment).

        Fast path: one random-linear-combination identity check for the
        whole batch; falls back to per-proof exact checks when it rejects
        (or when exact=True).

        Single-chip, the batch runs as a PIPELINE of row chunks: every
        chunk goes up as ONE packed upload + ONE fused device program
        that covers pass-1 AND the chunk's weighted var-MSM partial
        (dispatched async up front), so the host's challenge hashing +
        fixed-scalar accumulation for chunk k overlaps the device's work
        on chunks k+1... The cross-chunk var fold chains THROUGH the
        chunk programs (each adds its partial onto the previous total),
        so the finalize is one O(1) tail dispatch. Under a mesh the same
        chunk program runs per device shard (rows sharded over the whole
        grid, chunk size scaled by the device count) with an all-gather
        partial fold; FTS_NO_FUSED_PIPELINE restores the legacy split
        per-stage dispatches.

        Observability: each call produces one span tree (root
        "range_verify" with host_prep / device_execute / result_fetch
        children) and one obs.BatchRecord. Phase accounting respects the
        pipeline: async dispatch + host challenge work is host_prep;
        device_execute is measured at the blocking syncs where device
        completion is actually awaited (the combined finalize / exact
        collection — NOT an injected block_until_ready, which would
        destroy the host/device overlap the chunk pipeline exists for).
        """
        B = len(proofs)
        if B == 0:
            return np.zeros(0, dtype=bool)
        pt = PhaseTimer()
        t0 = time.perf_counter()
        with _TRACER.span("range_verify", batch=B,
                          bit_length=self.params.bit_length,
                          exact=exact) as sp:
            out = self._verify_instrumented(proofs, commitments, exact,
                                            pt, sp)
        a = sp.attributes
        buckets = a.get("chunk_buckets", ())
        _RECORDS.record(BatchRecord(
            kind="range_verify", batch=B, live=a.get("live", 0),
            bucket=max(buckets) if buckets else 0,
            padded_rows=sum(buckets),
            host_prep_s=pt.totals.get("host_prep", 0.0),
            device_execute_s=pt.totals.get("device_execute", 0.0),
            result_fetch_s=pt.totals.get("result_fetch", 0.0),
            total_s=time.perf_counter() - t0,
            path=self.last_path or "?", chunks=len(buckets),
            cold_compile=_RECORDS.is_cold(
                "range_verify",
                (self.params.bit_length, exact, self._n_shard, buckets)),
            attrs={"bit_length": self.params.bit_length}))
        return out

    def _verify_instrumented(self, proofs, commitments, exact,
                             pt: PhaseTimer, sp) -> np.ndarray:
        params = self.params
        B = len(proofs)
        with pt.phase("host_prep"):
            ok_structure = np.array(
                [proofs[i] is not None
                 and _structure_ok(proofs[i], params.rounds)
                 for i in range(B)])
            live = [i for i in range(B) if ok_structure[i]]
        sp.set_attribute("live", len(live))
        if not live:
            self.last_path = "structure-only"
            sp.set_attribute("chunk_buckets", ())
            return ok_structure

        if self.mesh is not None:
            # fused: per-shard chunks stay at the single-chip shapes (the
            # legacy one-giant-chunk program never finished compiling on
            # the dryrun hosts); legacy split keeps the single chunk.
            chunk = (_CHUNK_ROWS * self._n_shard
                     if _fused_pipeline_enabled() else len(live))
        else:
            chunk = _CHUNK_ROWS
        chunks = [live[o:o + chunk] for o in range(0, len(live), chunk)]
        sp.set_attribute(
            "chunk_buckets", tuple(_bucket_rows(len(ch)) for ch in chunks))

        with pt.phase("host_prep"):
            # ---- stage 1: all chunks' pass-1 dispatched before any sync;
            # prev chains the cross-chunk var fold through the programs
            # (async — XLA sequences the device-side data dependency)
            stage1 = []
            prev = None
            for ch in chunks:
                st = self._dispatch_pass1(proofs, commitments, ch, prev)
                prev = st.total
                stage1.append(st)

            # ---- stage 2: per chunk, sync bytes -> challenges ->
            # equations; combined partial dispatched immediately (device
            # keeps working). Each chunk keeps its OWN fixed accumulator
            # so a rejecting batch can be bisected per chunk (adversarial
            # floor: one bad proof costs an exact pass over its chunk,
            # not the whole batch).
            n_fixed = 2 * params.bit_length + 5
            zero_acc = (bytes(32 * n_fixed) if _FRNATIVE is not None
                        else None)
            equations: dict[int, _ProofEquations] = {}
            chunk_rlc: list = []    # (rows, fixed_acc_chunk, partial)
            for ch, st in zip(chunks, stage1):
                eqs_ch = self._host_stage2(proofs, ch, st)
                equations.update(eqs_ch)
                if not exact and (self.mesh is None
                                  or st.partial is not None):
                    acc = zero_acc if zero_acc is not None else [0] * n_fixed
                    if st.partial is not None:
                        # merged pipeline: the chunk's var partial was
                        # computed by the stage-1 dispatch; only the
                        # fixed-generator accumulation (host scalar
                        # arithmetic, same weights) happens here.
                        acc, _, _ = self._weight_equations(
                            proofs, commitments, ch, eqs_ch, acc,
                            weights=st.weights, want_var=False)
                        part = st.partial
                    else:
                        acc, part = self._combined_chunk(
                            proofs, commitments, ch, eqs_ch, acc,
                            st.pts_dev)
                    chunk_rlc.append((ch, acc, part))

        # ---- pass 2
        bad_rows = live
        if not exact:
            with pt.phase("device_execute", stage="combined"):
                if not chunk_rlc:
                    # legacy split mesh path (FTS_NO_FUSED_PIPELINE)
                    ok = self._verify_combined(proofs, commitments, live,
                                               equations)
                else:
                    total = self._sum_fixed_accs(
                        [a for _, a, _ in chunk_rlc])
                    last_total = stage1[-1].total
                    if last_total is not None:
                        # cross-chunk fold already chained through the
                        # chunk programs: O(1) finalize tail
                        ok = self._combined_finalize_total(total,
                                                           last_total)
                    else:
                        ok = self._combined_finalize(
                            total, [p for _, _, p in chunk_rlc])
            if ok:
                self.last_path = "combined"
                with pt.phase("result_fetch"):
                    return ok_structure
            if len(chunk_rlc) > 1:
                # bisect: re-check each chunk's RLC; exact only where it
                # fails (a passing chunk RLC carries the same soundness
                # as the whole-batch one: fresh per-proof weights)
                with pt.phase("device_execute", stage="bisect"):
                    bad_rows = []
                    for ch, acc, part in chunk_rlc:
                        if not self._combined_finalize(acc, [part]):
                            bad_rows.extend(ch)
                if not bad_rows:    # unreachable, kept for safety
                    bad_rows = live
        with pt.phase("device_execute", stage="exact"):
            accepts_bad = self._verify_exact(proofs, commitments, bad_rows,
                                             equations)
        self.last_path = "exact"
        with pt.phase("result_fetch"):
            out = ok_structure.copy()
            bad_set = {i: row for row, i in enumerate(bad_rows)}
            for i in live:
                if i in bad_set:
                    out[i] = bool(accepts_bad[bad_set[i]])
        return out

    def _sum_fixed_accs(self, accs):
        """Fold per-chunk fixed-scalar accumulators into one vector."""
        if _FRNATIVE is not None:
            ones = (1).to_bytes(32, "little") * (len(accs[0]) // 32)
            total = accs[0]
            for a in accs[1:]:
                total = _FRNATIVE.addmul_many(total, a, ones)
            return total
        total = list(accs[0])
        for a in accs[1:]:
            for j, v in enumerate(a):
                total[j] = fr_add(total[j], v)
        return total

    # ------------------------------------------------------------------
    def _dispatch_pass1(self, proofs, commitments, ch, prev=None):
        """Host phase-a + marshal for one chunk, then async dispatch of
        the chunk's device work; returns a _ChunkStage with the digest
        device->host copies already in flight.

        With the pipeline enabled (default) this is ONE packed upload +
        ONE fused device program covering pass-1 AND the chunk's
        weighted pass-2 var-MSM partial — the RLC weights are drawn
        here, ride the packed row, and are kept on the stage for the
        host-side fixed-scalar accumulation in stage 2. Under a mesh
        the same program runs per device shard (_pass12_sharded_fn,
        rows sharded over the whole grid). ``prev`` is the previous
        chunk's running var total (identity for chunk 0); the program
        adds its own partial onto it so the finalize is O(1) in chunk
        count. The FTS_NO_FUSED_PIPELINE escape keeps the split
        uploads/dispatches (partial=None -> stage 2 runs
        _combined_chunk)."""
        params = self.params
        n = params.bit_length
        xyz = _phase_a_challenges_batch(proofs, commitments, ch)
        transcripts = {i: _host_phase_a(proofs[i], commitments[i], params,
                                        xyz=xyz[row])
                       for row, i in enumerate(ch)}
        b_bucket = _bucket_rows(len(ch))
        if self._n_shard > 1:
            # batch rows must divide evenly over the mesh
            b_bucket = max(b_bucket, self._n_shard)
            b_bucket += (-b_bucket) % self._n_shard
        zero_sc = np.zeros(limbs.NLIMBS, dtype=np.uint32)

        # 4 scalars per proof (y^-1, z, delta, x): the device derives the
        # n + (n+2) + 2 pass-1 vectors itself (_derive_pass1_scalars) —
        # host->device bytes drop ~85% (the tunnel's upload side is a
        # measured wall)
        def sc4_bytes(i):
            ts = transcripts[i]
            yinv1 = (ts.yinv_packed[32:64]
                     if ts.yinv_packed is not None
                     else (ts.yinv_pows[1] % R).to_bytes(32, "little"))
            return (yinv1 + (ts.z % R).to_bytes(32, "little")
                    + (proofs[i].data.delta % R).to_bytes(32, "little")
                    + (ts.x % R).to_bytes(32, "little"))

        sc4_np = limbs.packed_to_limbs(
            b"".join(sc4_bytes(i) for i in ch)
        ).reshape(len(ch), 4, limbs.NLIMBS)

        # every proof's points, marshalled ONCE as affine + identity mask
        # (stage 1), reused by the var-MSM partial in stage 2:
        # [D, C, L_r.., R_r.., T1, T2, Com] — the _weight_equations order.
        nv = 2 + 2 * params.rounds + 3
        allpts = []
        for i in ch:
            d = proofs[i].data
            allpts += ([d.D, d.C] + proofs[i].ipa.L + proofs[i].ipa.R
                       + [d.T1, d.T2, commitments[i]])
        proj = limbs.points_to_projective_limbs(allpts).reshape(
            len(ch), nv, 3, limbs.NLIMBS)
        inf_np = (proj[:, :, 2] == 0).all(-1).astype(np.uint32)
        # x_ipa transcript tail: the per-proof inner-product bytes (the
        # only literal proof bytes in that hash); padded rows hash garbage
        # that is never read back.
        ip_np = np.frombuffer(
            b"".join(ser.zr_to_bytes(proofs[i].data.inner_product)
                     for i in ch), dtype=np.uint8).reshape(len(ch), 32)

        partial = weights = total = None
        if _fused_pipeline_enabled():
            # single-program chunk pipeline: ONE packed upload + ONE
            # fused device program per chunk covering pass-1 AND the
            # weighted pass-2 var partial (per-call tunnel latency is a
            # measured cost). The RLC weights are drawn NOW — none of
            # the var scalars need the pass-1 digests, which is what
            # makes the merge sound (see _derive_var_scalars).
            weights = {i: (1 + secrets.randbelow(R - 1),
                           1 + secrets.randbelow(R - 1)) for i in ch}
            if self.mesh is not None:
                run, nv_, o_inf, o_ip, o_w = _pass12_sharded_fn(
                    params, self.mesh)
            else:
                run, nv_, o_inf, o_ip, o_w = _pass12_fused_fn(params)
            packed = np.zeros((len(ch), o_w + 32), dtype=np.uint32)
            packed[:, :64] = sc4_np.reshape(len(ch), 64)
            xyu16 = proj[:, :, :2].astype("<u2")          # (L, nv, 2, 16)
            packed[:, 64:o_inf] = np.ascontiguousarray(
                xyu16.reshape(len(ch), -1)).view("<u4")
            packed[:, o_inf:o_ip] = inf_np
            packed[:, o_ip:o_w] = np.ascontiguousarray(ip_np).view("<u4")
            packed[:, o_w:] = limbs.packed_to_limbs(
                b"".join(w1.to_bytes(32, "little")
                         + w2.to_bytes(32, "little")
                         for w1, w2 in (weights[i] for i in ch))
            ).reshape(len(ch), 32)
            pad_row = np.zeros(o_w + 32, dtype=np.uint32)
            pad_row[o_inf:o_ip] = 1        # identity points, zero weights
            padded = _pad_rows(packed, b_bucket, pad_row)
            if prev is None:
                prev = jnp.asarray(limbs.point_to_projective_limbs(
                    bn254.G1_IDENTITY))
            if self.mesh is not None:
                _METRICS.counter("mesh_chunk_dispatches_total").add()
                _METRICS.counter("mesh_pad_rows_total").add(
                    b_bucket - len(ch))
                # one (3, 16)-u32 Jacobian partial per device rides the
                # per-chunk all-gather
                _METRICS.counter("mesh_allgather_bytes_total").add(
                    3 * limbs.NLIMBS * 4 * self._n_shard)
                packed_dev = self._put_rows(padded)  # counts the upload
            else:
                _count("chunk_upload")
                packed_dev = jnp.asarray(padded)
            _count("chunk_dispatch")
            if params.tables_t_rgp is not None:     # Pallas kernel bodies
                digests_dev, rdig_dev, pts_proj, partial, total = run(
                    params.tables_t_rgp, params.tables_t_k, packed_dev,
                    prev)
            else:                                   # XLA twin bodies
                digests_dev, rdig_dev, pts_proj, partial, total = run(
                    params.tables, params.rgp_idx, params.k_idx,
                    packed_dev, prev)
        else:
            rdig_dev = None
            sc4 = self._put_rows(_pad_rows(sc4_np, b_bucket, zero_sc))
            xy = self._put_rows(_pad_rows(
                proj[:, :, :2], b_bucket,
                np.zeros((nv, 2, limbs.NLIMBS), dtype=np.uint32)))
            inf = self._put_rows(_pad_rows(
                inf_np.astype(np.uint8), b_bucket,
                np.ones(nv, dtype=np.uint8)))
            ip_dev = self._put_rows(_pad_rows(
                ip_np, b_bucket, np.zeros(32, dtype=np.uint8)))
            yinv, k_fixed, dc_sc = _derive_pass1_scalars(sc4, n)
            pts_proj = _reconstruct_points(xy, inf)      # (B, nv, 3, 16)
            dc_pts = pts_proj[:, :2]
            for _ in range(2):          # derive + reconstruct dispatches
                _count("chunk_dispatch")

            if self._pass1_sharded is not None:
                # fused Pallas kernels per device under the mesh
                _count("chunk_dispatch")
                digests_dev = self._pass1_sharded(
                    params.tables_t_rgp, params.tables_t_k, yinv, k_fixed,
                    dc_pts, dc_sc, ip_dev)
            else:
                rgp_pts = _rgp_gather_kernel(params.tables, params.rgp_idx,
                                             yinv)
                k_pt = _k_pass_kernel(params.tables, params.k_idx, k_fixed,
                                      dc_pts, dc_sc)
                digests_dev = _xipa_device_fn(params)(
                    _affine_bytes_rows_kernel(rgp_pts),
                    _affine_bytes_kernel(k_pt), ip_dev)
                for _ in range(5):      # gather, K, 2x bytes, xipa
                    _count("chunk_dispatch")
        for arr in (digests_dev, rdig_dev):
            try:
                arr.copy_to_host_async()
            except (AttributeError, NotImplementedError, TypeError):
                pass
        return _ChunkStage(transcripts, digests_dev, rdig_dev, pts_proj,
                           partial, weights, total)

    def _host_stage2(self, proofs, ch, st) -> dict:
        """Challenges (vectorized) + per-proof scalar expansion for one
        chunk. Blocks on that chunk's pass-1 bytes only."""
        from ..ops import sha256 as dsha

        params = self.params
        rr = params.rounds
        transcripts = st.transcripts
        digests_dev, rdig_dev = st.digests_dev, st.rdig_dev
        if rdig_dev is None:
            # XLA/mesh path: round challenges hashed on host (proof bytes
            # only — run BEFORE blocking on the device transfer)
            rch = _round_challenges_batch(proofs, ch, rr)
        else:
            rwords = np.asarray(rdig_dev)[:len(ch)]
            flat = dsha.digest_words_to_ints(rwords.reshape(-1, 8))
            rch = np.empty((len(ch), rr), dtype=object)
            for row in range(len(ch)):
                for r_i in range(rr):
                    rch[row, r_i] = flat[row * rr + r_i] % R
        words = np.asarray(digests_dev)[:len(ch)]
        x_ipa = [v % R for v in dsha.digest_words_to_ints(words)]
        ch_packed_all = inv_packed_all = None
        if _FRNATIVE is not None:
            ch_packed_all = limbs.pack_scalars(
                [rch[row, r] for row in range(len(ch)) for r in range(rr)])
            inv_packed_all = _FRNATIVE.batch_inv(ch_packed_all)
        eqs: dict[int, _ProofEquations] = {}
        for row, i in enumerate(ch):
            sl = slice(row * rr * 32, (row + 1) * rr * 32)
            eqs[i] = _host_phase_b(
                proofs[i], transcripts[i], x_ipa[row], list(rch[row]),
                params,
                ch_packed_all[sl] if ch_packed_all is not None else None,
                inv_packed_all[sl] if inv_packed_all is not None else None)
        return eqs

    def _weight_equations(self, proofs, commitments, ch, equations,
                          fixed_acc, weights=None, want_var=True):
        """RLC-weight one row set: per-proof (w1, w2), fixed-generator
        scalars accumulated into fixed_acc on host, weighted var scalars
        collected. Returns (fixed_acc, var_pts, var_scalar_limbs_fn).

        Shared by the single-chip chunk pipeline and the sharded full
        pass — the weight layout lives HERE only. ``weights`` (a
        {proof_idx: (w1, w2)} dict) replays the weights a merged stage-1
        dispatch already committed to on device; in that case the var
        scalars were derived there too, so callers pass want_var=False
        and get (fixed_acc, None, None) — host work drops to the fixed
        accumulation only. Without ``weights``, fresh per-proof randoms
        are drawn here (legacy split path, mesh path).
        """
        params = self.params
        n = params.bit_length
        n_eq2 = 2 + 2 * params.rounds

        var_pts: list = []
        if want_var:
            for i in ch:
                d = proofs[i].data
                var_pts.extend([d.D, d.C] + proofs[i].ipa.L
                               + proofs[i].ipa.R
                               + [d.T1, d.T2, commitments[i]])

        def draw(i):
            if weights is not None:
                return weights[i]
            return (1 + secrets.randbelow(R - 1),
                    1 + secrets.randbelow(R - 1))

        if _FRNATIVE is not None:
            var_sc_packed: list[bytes] = []
            zero32 = bytes(32)
            for i in ch:
                w1i, w2i = draw(i)
                w1 = w1i.to_bytes(32, "little")
                w2 = w2i.to_bytes(32, "little")
                eq = equations[i]
                # fixed layout: G(n), H(n), P, Q @ w2 | cg0, cg1 @ w1 | S_G
                wts = w2 * (2 * n + 2) + w1 * 2 + zero32
                fixed_acc = _FRNATIVE.addmul_many(
                    fixed_acc, eq.fixed_packed, wts)
                if want_var:
                    var_sc_packed.append(_FRNATIVE.mul_many(
                        eq.var_packed, w2 * n_eq2 + w1 * 3))
            sc_blob = b"".join(var_sc_packed)

            def var_scalar_limbs(n_pad: int) -> np.ndarray:
                return limbs.packed_to_limbs(sc_blob + bytes(32) * n_pad)
        else:
            var_sc: list[int] = []
            for i in ch:
                w1, w2 = draw(i)
                eq = equations[i]
                for j in range(2 * n + 2):
                    fixed_acc[j] = fr_add(fixed_acc[j],
                                          fr_mul(w2, eq.fixed[j]))
                for j in (2 * n + 2, 2 * n + 3):
                    fixed_acc[j] = fr_add(fixed_acc[j],
                                          fr_mul(w1, eq.fixed[j]))
                if want_var:
                    wts = [w2] * n_eq2 + [w1] * 3
                    var_sc.extend(fr_mul(w, s)
                                  for w, s in zip(wts, equations[i].var))

            def var_scalar_limbs(n_pad: int) -> np.ndarray:
                return limbs.scalars_to_limbs(var_sc + [0] * n_pad)

        if not want_var:
            return fixed_acc, None, None
        return fixed_acc, var_pts, var_scalar_limbs

    def _combined_chunk(self, proofs, commitments, ch, equations,
                        fixed_acc, pts_dev):
        """LEGACY split pass-2 (mesh / FTS_NO_FUSED_PIPELINE): weight one
        chunk's equations into the running RLC and dispatch the chunk's
        var-MSM partial on device. The var POINTS are the stage-1 device
        upload (pts_dev (b_bucket, 17, 3, 16), identity on padded rows) —
        only the weighted scalars go up here. Returns (fixed_acc,
        partial_device_point). The default single-chip path computes the
        partial inside the stage-1 merged program instead
        (_pass12_fused_fn) and never reaches this."""
        params = self.params
        fixed_acc, var_pts, var_scalar_limbs = self._weight_equations(
            proofs, commitments, ch, equations, fixed_acc)

        b_bucket, nv = pts_dev.shape[0], pts_dev.shape[1]
        n_pad = b_bucket * nv - len(var_pts)
        _count("chunk_upload")
        sc = jnp.asarray(var_scalar_limbs(n_pad))
        _count("chunk_dispatch")
        flat_pts = pts_dev.reshape(b_bucket * nv, 3, limbs.NLIMBS)
        if params.tables_t_rgp is not None:
            from ..ops import pallas_fb

            part = pallas_fb.msm_var_fused(flat_pts, sc)
        else:
            part = _var_partial_kernel(flat_pts, sc)
        return fixed_acc, part

    def _combined_finalize(self, fixed_acc, partials) -> bool:
        """Fixed-base MSM of the accumulated scalars + fold of the chunk
        partials; True iff the total is the identity."""
        fixed_np = (limbs.packed_to_limbs(fixed_acc)
                    if _FRNATIVE is not None
                    else limbs.scalars_to_limbs(fixed_acc))
        _count("finalize")
        parts = jnp.stack(partials)
        return bool(_finalize_kernel(self.params.tables,
                                     jnp.asarray(fixed_np), parts))

    def _combined_finalize_total(self, fixed_acc, total) -> bool:
        """Finalize against the chain-folded var total (the LAST chunk's
        ``total`` output): the cross-chunk fold already happened inside
        the chunk programs (ROOFLINE "Remaining items" #2), so this tail
        is one fixed-base MSM + one add + one identity test — O(1) in
        chunk count where _combined_finalize stacks and tree-folds the
        per-chunk partials. The split finalize stays in use under bisect
        (per-chunk re-checks need the un-chained partials)."""
        fixed_np = (limbs.packed_to_limbs(fixed_acc)
                    if _FRNATIVE is not None
                    else limbs.scalars_to_limbs(fixed_acc))
        _count("finalize")
        return bool(_finalize_total_kernel(self.params.tables,
                                           jnp.asarray(fixed_np), total))

    # ------------------------------------------------------------------
    def _verify_combined(self, proofs, commitments, live,
                         equations) -> bool:
        """Sharded RLC pass (mesh path): one MSM over every live proof's
        eq1+eq2 with the term axis sharded over the mesh; True iff
        identity. Weight layout lives in _weight_equations (shared with
        the single-chip chunk pipeline).

        Per-proof weights w1 (eq1 terms) and w2 (eq2 terms) are fresh
        uniform randoms, so cross-proof or cross-equation cancellation of
        invalid proofs has probability <= 2/r.
        """
        params = self.params
        n_fixed = 2 * params.bit_length + 5
        fixed_acc = (bytes(32 * n_fixed) if _FRNATIVE is not None
                     else [0] * n_fixed)
        fixed_acc, var_pts, var_scalar_limbs = self._weight_equations(
            proofs, commitments, live, equations, fixed_acc)
        fixed_np = (limbs.packed_to_limbs(fixed_acc)
                    if _FRNATIVE is not None
                    else limbs.scalars_to_limbs(fixed_acc))

        # pad the variable MSM to the next {2^k, 1.5*2^k} bucket: still a
        # handful of compiled shapes, but at most 33% padding waste (a
        # plain pow2 ladder wastes up to 2x device work on the hot path)
        v = len(var_pts)
        p = _next_pow2(max(128, v))
        v_target = (3 * p // 4) if v <= 3 * p // 4 else p
        v_target += (-v_target) % self._n_shard
        pts_np = limbs.points_to_projective_limbs(
            var_pts + [bn254.G1_IDENTITY] * (v_target - v))
        sc_np = var_scalar_limbs(v_target - v)
        ok = self._combined_sharded(
            params.tables, jnp.asarray(fixed_np),
            self._put_rows(pts_np), self._put_rows(sc_np))
        return bool(ok)

    # ------------------------------------------------------------------
    def _verify_exact(self, proofs, commitments, live, equations) -> np.ndarray:
        """Per-proof eq1/eq2 identity checks (bit-exact vs the oracle)."""
        params = self.params
        n = params.bit_length
        rr = params.rounds
        b_bucket = _bucket_rows(len(live))
        id_pt = limbs.point_to_projective_limbs(bn254.G1_IDENTITY)
        zero_sc = np.zeros(limbs.NLIMBS, dtype=np.uint32)
        native = _FRNATIVE is not None
        fused = params.tables_t_all is not None
        # XLA/CPU twin of the Pallas split: lazified madd planes for the
        # FIXED-base tails, when the affine table flavor is available
        mixed_planes = None if fused else _exact_mixed_planes(params)
        split_fixed = fused or mixed_planes is not None

        eq1_pt_rows, eq1_sc_rows = [], []
        eq2_pt_rows, eq2_sc_rows = [], []
        f1_sc_rows, f2_sc_rows = [], []
        for i in live:
            eq = equations[i]
            d = proofs[i].data
            if split_fixed:
                # fixed generators ride the Pallas per-lane fixed-base MSM
                # (tables index order: G.., H.., P, Q | cg0, cg1);
                # only the per-proof points stay variable-base
                eq1_pt_rows.append([d.T1, d.T2, commitments[i]])
                eq2_pt_rows.append([d.D, d.C] + proofs[i].ipa.L
                                   + proofs[i].ipa.R)
            else:
                # eq1: [cg0, cg1, T1, T2, Com]
                eq1_pt_rows.append([params.commitment_gen[0],
                                    params.commitment_gen[1],
                                    d.T1, d.T2, commitments[i]])
                # eq2: G_i ++ H_i ++ [P, Q, D, C] ++ L_r ++ R_r
                eq2_pt_rows.append(
                    params.left_gen + params.right_gen
                    + [params.P, params.Q, d.D, d.C]
                    + proofs[i].ipa.L + proofs[i].ipa.R)
            if native:
                f, v = eq.fixed_packed, eq.var_packed
                if split_fixed:
                    f2_sc_rows.append(f[:(2 * n + 2) * 32])
                    f1_sc_rows.append(f[(2 * n + 2) * 32:(2 * n + 4) * 32])
                    eq1_sc_rows.append(v[-3 * 32:])
                    eq2_sc_rows.append(v[:(2 + 2 * rr) * 32])
                else:
                    eq1_sc_rows.append(f[(2 * n + 2) * 32:(2 * n + 4) * 32]
                                       + v[-3 * 32:])
                    eq2_sc_rows.append(f[:(2 * n + 2) * 32] + v[:2 * 32]
                                       + v[2 * 32:(2 + 2 * rr) * 32])
            else:
                if split_fixed:
                    f2_sc_rows.append(eq.fixed[:2 * n + 2])
                    f1_sc_rows.append(eq.fixed[2 * n + 2:2 * n + 4])
                    eq1_sc_rows.append([eq.var[-3], eq.var[-2],
                                        eq.var[-1]])
                    eq2_sc_rows.append(eq.var[:2 + 2 * rr])
                else:
                    eq1_sc_rows.append([eq.fixed[2 * n + 2],
                                        eq.fixed[2 * n + 3],
                                        eq.var[-3], eq.var[-2],
                                        eq.var[-1]])
                    eq2_sc_rows.append(
                        eq.fixed[: 2 * n + 2] + eq.var[:2]
                        + eq.var[2 : 2 + 2 * rr])

        eq1_pts_np = np.stack(
            [limbs.points_to_projective_limbs(rw) for rw in eq1_pt_rows])
        eq2_pts_np = np.stack(
            [limbs.points_to_projective_limbs(rw) for rw in eq2_pt_rows])
        n_eq1 = eq1_pts_np.shape[1]
        n_eq2 = eq2_pts_np.shape[1]
        if native:
            eq1_sc_np = limbs.packed_to_limbs(b"".join(eq1_sc_rows)).reshape(
                len(live), n_eq1, limbs.NLIMBS)
            eq2_sc_np = limbs.packed_to_limbs(b"".join(eq2_sc_rows)).reshape(
                len(live), n_eq2, limbs.NLIMBS)
        else:
            eq1_sc_np = np.stack(
                [limbs.scalars_to_limbs(rw) for rw in eq1_sc_rows])
            eq2_sc_np = np.stack(
                [limbs.scalars_to_limbs(rw) for rw in eq2_sc_rows])
        eq1_pts_np, eq1_sc_np = _pad_terms(eq1_pts_np, eq1_sc_np, 8)
        eq2_pts_np, eq2_sc_np = _pad_terms(
            eq2_pts_np, eq2_sc_np, _next_pow2(n_eq2))

        if split_fixed:
            if native:
                f2_np = limbs.packed_to_limbs(b"".join(f2_sc_rows)).reshape(
                    len(live), 2 * n + 2, limbs.NLIMBS)
                f1_np = limbs.packed_to_limbs(b"".join(f1_sc_rows)).reshape(
                    len(live), 2, limbs.NLIMBS)
            else:
                f2_np = np.stack(
                    [limbs.scalars_to_limbs(rw) for rw in f2_sc_rows])
                f1_np = np.stack(
                    [limbs.scalars_to_limbs(rw) for rw in f1_sc_rows])
            f2_sc_dev = jnp.asarray(_pad_rows(f2_np, b_bucket, zero_sc))
            f1_sc_dev = jnp.asarray(_pad_rows(f1_np, b_bucket, zero_sc))
        if fused:
            from ..ops import pallas_fb

            f2_pt = pallas_fb.fixed_base_msm_fused(
                params.tables_t_all[:2 * n + 2], f2_sc_dev)
            f1_pt = pallas_fb.fixed_base_msm_fused(
                params.tables_t_all[2 * n + 2:2 * n + 4], f1_sc_dev)
            accept = np.asarray(_exact_var_tail_kernel(
                f1_pt, f2_pt,
                jnp.asarray(_pad_rows(eq1_pts_np, b_bucket, id_pt)),
                jnp.asarray(_pad_rows(eq1_sc_np, b_bucket, zero_sc)),
                jnp.asarray(_pad_rows(eq2_pts_np, b_bucket, id_pt)),
                jnp.asarray(_pad_rows(eq2_sc_np, b_bucket, zero_sc))))
        elif mixed_planes is not None:
            planes_f2, planes_f1 = mixed_planes
            accept = np.asarray(_exact_mixed_tail_kernel(
                planes_f2, planes_f1, f2_sc_dev, f1_sc_dev,
                jnp.asarray(_pad_rows(eq1_pts_np, b_bucket, id_pt)),
                jnp.asarray(_pad_rows(eq1_sc_np, b_bucket, zero_sc)),
                jnp.asarray(_pad_rows(eq2_pts_np, b_bucket, id_pt)),
                jnp.asarray(_pad_rows(eq2_sc_np, b_bucket, zero_sc))))
        else:
            accept = np.asarray(_exact_pass_kernel(
                jnp.asarray(_pad_rows(eq1_pts_np, b_bucket, id_pt)),
                jnp.asarray(_pad_rows(eq1_sc_np, b_bucket, zero_sc)),
                jnp.asarray(_pad_rows(eq2_pts_np, b_bucket, id_pt)),
                jnp.asarray(_pad_rows(eq2_sc_np, b_bucket, zero_sc))))
        return accept[:len(live)]

    def verify_range_correctness(self, rc: rp.RangeCorrectness,
                                 commitments: list) -> np.ndarray:
        return self.verify(list(rc.proofs), commitments)
