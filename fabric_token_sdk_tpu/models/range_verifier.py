"""Batched TPU verification of Bulletproof-style range proofs.

Replaces the reference's sequential verifier loop (reference
token/core/zkatdlog/nogh/v1/crypto/rp/rangecorrectness.go:137-162 and
rp/bulletproof.go:252-333, rp/ipa.go:190-262) with two device passes over a
whole batch of proofs:

  Pass 1 (device): for every proof, compute the IPA input commitment K and
    the primed right generators H'_i = y^-i * H_i, returned as canonical
    affine limbs. These are the only group elements the Fiat-Shamir
    transcript needs that are not literal proof bytes.

  Host: recompute every challenge (x, y, z from proof bytes; the first IPA
    challenge from pass-1 bytes; round challenges from L_r/R_r bytes) and
    expand the whole verification — including the log-round generator
    folding — into per-proof scalar vectors over fixed term lists.

  Pass 2 (device): two MSM-is-identity checks per proof:
      eq1 (5 terms):   cg0^(ip-polEval) cg1^tau T1^-x T2^-x^2 Com^-z^2 == O
      eq2 (2n+2r+5):   folded IPA + commitment equation == O
    (derivation in _eq2_scalars below).

Accept iff both hold. The decision is exactly the oracle's accept/reject
(tests assert agreement, including tampered proofs); error *messages* for
rejected proofs are produced by re-running the host verifier, preserving the
reference's observable error ordering.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..crypto import bn254, rp
from ..crypto import serialization as ser
from ..crypto.bn254 import fr_add, fr_inv, fr_mul, fr_sub, hash_to_zr
from ..ops import ec, limbs

R = bn254.R


# --------------------------------------------------------------------------
# host codecs
# --------------------------------------------------------------------------

def affine_limbs_to_bytes(arr: np.ndarray) -> bytes:
    """Canonical affine limbs (2, 16) -> 64-byte mathlib G1 encoding."""
    # limbs are little-endian 16-bit; bytes are big-endian 32 per coord.
    out = bytearray(64)
    for c in range(2):
        coord = np.asarray(arr[c], dtype=np.uint32)
        for i in range(16):
            v = int(coord[15 - i])
            out[c * 32 + 2 * i] = v >> 8
            out[c * 32 + 2 * i + 1] = v & 0xFF
    return bytes(out)


def affine_batch_to_bytes(arr: np.ndarray) -> np.ndarray:
    """Vectorized limb->bytes: (..., 2, 16) uint32 -> (...,) 64-byte rows.

    Returns a uint8 array of shape (..., 64) laid out exactly like
    mathlib G1.Bytes() (x||y, 32-byte big-endian each).
    """
    a = np.asarray(arr, dtype=np.uint32)
    # big-endian limb order, then split each 16-bit limb into two bytes
    a = a[..., ::-1]  # (..., 2, 16) most-significant limb first
    hi = (a >> 8).astype(np.uint8)
    lo = (a & 0xFF).astype(np.uint8)
    inter = np.stack([hi, lo], axis=-1)  # (..., 2, 16, 2)
    return inter.reshape(*a.shape[:-2], 64)


# --------------------------------------------------------------------------
# device kernels
# --------------------------------------------------------------------------

# Kernels are jitted separately: fusing them into one graph makes XLA:CPU
# compile superlinearly (three 256-step loops in one module); split, each
# compiles in seconds and the persistent cache reuses them across runs.
_rgp_kernel = jax.jit(
    jax.vmap(jax.vmap(ec.scalar_mul, in_axes=(0, 0)), in_axes=(None, 0)))
_msm_kernel = jax.jit(ec.msm)
_affine_kernel = jax.jit(ec.to_affine)
_msm_id_kernel = jax.jit(ec.msm_is_identity)


def _pass1_kernel(h_pts, yinv_pows, k_pts, k_scalars):
    """Compute right_gen' points and K commitments for the whole batch.

    h_pts:     (n, 3, 16) shared right generators (Jacobian Montgomery)
    yinv_pows: (B, n, 16) scalars y^-i per proof
    k_pts:     (B, T_k, 3, 16) K-equation term points
    k_scalars: (B, T_k, 16)
    Returns (rgp_affine (B, n, 2, 16), k_affine (B, 2, 16)) canonical limbs.
    """
    rgp = _rgp_kernel(h_pts, yinv_pows)
    k = _msm_kernel(k_pts, k_scalars)
    return _affine_kernel(rgp), _affine_kernel(k)


def _pass2_kernel(eq1_pts, eq1_sc, eq2_pts, eq2_sc):
    """Two batched MSM identity checks; returns (B,) bool accept vector."""
    ok1 = _msm_id_kernel(eq1_pts, eq1_sc)
    ok2 = _msm_id_kernel(eq2_pts, eq2_sc)
    return jnp.logical_and(ok1, ok2)


# --------------------------------------------------------------------------
# verifier
# --------------------------------------------------------------------------

@dataclass(frozen=True)
class RangeVerifierParams:
    """Device-resident public parameters for one (pp, bit_length) config."""

    bit_length: int
    rounds: int
    left_gen: list          # host points G_i
    right_gen: list         # host points H_i
    P: object
    Q: object
    commitment_gen: list    # [cg0, cg1] (pedersen_generators[1:3])
    left_gen_dev: jnp.ndarray      # (n, 3, 16)
    right_gen_dev: jnp.ndarray     # (n, 3, 16)
    # precomputed transcript prefix: bytes of right_gen' are per-proof, but
    # left_gen ++ [Q] bytes are pp constants.
    left_gen_bytes: tuple
    q_bytes: bytes

    @classmethod
    def from_pp(cls, pp) -> "RangeVerifierParams":
        rpp = pp.range_proof_params
        return cls(
            bit_length=rpp.bit_length,
            rounds=rpp.number_of_rounds,
            left_gen=list(rpp.left_generators),
            right_gen=list(rpp.right_generators),
            P=rpp.P,
            Q=rpp.Q,
            commitment_gen=list(pp.pedersen_generators[1:3]),
            left_gen_dev=jnp.asarray(
                limbs.points_to_projective_limbs(rpp.left_generators)),
            right_gen_dev=jnp.asarray(
                limbs.points_to_projective_limbs(rpp.right_generators)),
            left_gen_bytes=tuple(
                ser.g1_to_bytes(p).hex().encode("ascii")
                for p in rpp.left_generators),
            q_bytes=ser.g1_to_bytes(rpp.Q).hex().encode("ascii"),
        )


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def _pad_terms(pts: np.ndarray, sc: np.ndarray, t_target: int):
    """Pad the term axis to a shared bucket with identity points / zero
    scalars (exact no-ops in the MSM) so distinct equations reuse one
    compiled kernel shape."""
    B, T = pts.shape[0], pts.shape[1]
    if T == t_target:
        return pts, sc
    id_pt = limbs.point_to_projective_limbs(bn254.G1_IDENTITY)
    pad_pts = np.broadcast_to(id_pt, (B, t_target - T) + id_pt.shape)
    pad_sc = np.zeros((B, t_target - T, limbs.NLIMBS), dtype=np.uint32)
    return (np.concatenate([pts, pad_pts], axis=1),
            np.concatenate([sc, pad_sc], axis=1))


# Batch-dimension buckets: every request size pads up to one of these so the
# device kernels compile for a handful of shapes total (compiles of the
# 256-step loop kernels are expensive; see module docstring).
_B_BUCKETS = (16, 128, 1024, 4096)


def _bucket_rows(b: int) -> int:
    for cap in _B_BUCKETS:
        if b <= cap:
            return cap
    return ((b + _B_BUCKETS[-1] - 1) // _B_BUCKETS[-1]) * _B_BUCKETS[-1]


def _pad_rows(arr: np.ndarray, b_target: int, pad_row: np.ndarray) -> np.ndarray:
    """Pad the batch axis to the bucket size by repeating `pad_row`."""
    B = arr.shape[0]
    if B == b_target:
        return arr
    pad = np.broadcast_to(pad_row, (b_target - B,) + arr.shape[1:])
    return np.concatenate([arr, pad], axis=0)


def _structure_ok(proof: rp.RangeProof, rounds: int) -> bool:
    """Host-side nil/shape checks (bulletproof.go:254-264, ipa.go:193-201)."""
    d = proof.data
    if d is None or proof.ipa is None:
        return False
    for el in (d.T1, d.T2, d.C, d.D):
        if el is None:
            return False
    if d.inner_product is None or d.tau is None or d.delta is None:
        return False
    ipa = proof.ipa
    if ipa.left is None or ipa.right is None:
        return False
    if len(ipa.L) != len(ipa.R) or len(ipa.L) != rounds:
        return False
    if any(p is None for p in ipa.L) or any(p is None for p in ipa.R):
        return False
    return True


def _fold_coefficients(round_challenges: list[int], n: int,
                       invert_first_half: bool) -> list[int]:
    """Expand IPA generator folding into per-index coefficients.

    Left generators fold as lg'[i] = x^-1 lg[i] + x lg[i+half]
    (reference ipa.go:343-356), so coefficient of G_j is the product over
    rounds of x_r when j falls in the high half at round r, else x_r^-1.
    Right generators fold with x and x^-1 swapped.
    """
    coeffs = [1]
    for x in round_challenges:
        x_inv = fr_inv(x)
        lo, hi = (x_inv, x) if invert_first_half else (x, x_inv)
        coeffs = [fr_mul(c, lo) for c in coeffs] + \
                 [fr_mul(c, hi) for c in coeffs]
    assert len(coeffs) == n
    return coeffs


@dataclass
class _ProofTranscript:
    x: int
    y: int
    z: int
    y_pows: list[int]
    yinv_pows: list[int]
    pol_eval: int
    k_scalars: list[int]


def _host_phase_a(proof: rp.RangeProof, commitment, params) -> _ProofTranscript:
    """Challenges + K-equation scalars from literal proof bytes."""
    n = params.bit_length
    d = proof.data
    x = rp.challenge_x(d.T1, d.T2)
    y, z = rp.challenges_y_z(d.C, d.D, commitment)
    z_sq = fr_mul(z, z)
    y_inv = fr_inv(y)

    y_pows, yinv_pows = [1], [1]
    for i in range(1, n):
        y_pows.append(fr_mul(y, y_pows[-1]))
        yinv_pows.append(fr_mul(y_inv, yinv_pows[-1]))

    ipy = 0
    ip2 = 0
    p2 = 1
    for i in range(n):
        ipy = fr_add(ipy, y_pows[i])
        if i > 0:
            p2 = fr_mul(2, p2)
        ip2 = fr_add(ip2, p2)
    z_cube = fr_mul(z_sq, z)
    pol_eval = fr_sub(fr_mul(fr_sub(z, z_sq), ipy), fr_mul(z_cube, ip2))

    # K = x*D + C - z*sum G_i + sum (z + z^2 2^i y^-i) H_i - delta*P
    # term order: [D, C, P] ++ G_i ++ H_i
    k_scalars = [x, 1, fr_sub(0, d.delta)]
    k_scalars += [fr_sub(0, z)] * n
    for i in range(n):
        k_scalars.append(
            fr_add(z, fr_mul(z_sq, fr_mul(pow(2, i, R), yinv_pows[i]))))
    return _ProofTranscript(x=x, y=y, z=z, y_pows=y_pows,
                            yinv_pows=yinv_pows, pol_eval=pol_eval,
                            k_scalars=k_scalars)


def _host_phase_b(proof: rp.RangeProof, ts: _ProofTranscript,
                  rgp_bytes_hex: list[bytes], k_bytes_hex: bytes,
                  params) -> tuple[list[int], list[int]]:
    """First IPA challenge + round folding -> eq1/eq2 scalar vectors."""
    n = params.bit_length
    d = proof.data
    ipa = proof.ipa
    x, z = ts.x, ts.z
    z_sq = fr_mul(z, z)
    x_sq = fr_mul(x, x)

    # eq1 term order: [cg0, cg1, T1, T2, commitment]
    eq1 = [fr_sub(d.inner_product, ts.pol_eval), d.tau,
           fr_sub(0, x), fr_sub(0, x_sq), fr_sub(0, z_sq)]

    # first IPA challenge: hash(right_gen' ++ left_gen ++ [Q, K], ip)
    # (reference ipa.go:159-173 — right generators first).
    array_bytes = ser.SEPARATOR.join(
        list(rgp_bytes_hex) + list(params.left_gen_bytes)
        + [params.q_bytes, k_bytes_hex])
    raw = ser.marshal_std_bytes_slices(
        [array_bytes, ser.SEPARATOR, ser.zr_to_bytes(d.inner_product)])
    x_ipa = hash_to_zr(raw)

    round_ch = [rp.ipa_round_challenge(L, Rp) for L, Rp in zip(ipa.L, ipa.R)]
    a_coeffs = _fold_coefficients(round_ch, n, invert_first_half=True)
    b_coeffs = _fold_coefficients(round_ch, n, invert_first_half=False)

    a, b = ipa.left, ipa.right
    # eq2 term order: G_i ++ H_i ++ [Q, D, C, P] ++ L_r ++ R_r
    eq2 = []
    for j in range(n):
        eq2.append(fr_add(fr_mul(a, a_coeffs[j]), z))
    for j in range(n):
        coeff = fr_mul(fr_mul(b, b_coeffs[j]), ts.yinv_pows[j])
        coeff = fr_sub(coeff, z)
        coeff = fr_sub(coeff, fr_mul(z_sq,
                                     fr_mul(pow(2, j, R), ts.yinv_pows[j])))
        eq2.append(coeff)
    eq2.append(fr_mul(x_ipa, fr_sub(fr_mul(a, b), d.inner_product)))
    eq2.append(fr_sub(0, x))
    eq2.append(R - 1)
    eq2.append(d.delta)
    for xr in round_ch:
        eq2.append(fr_sub(0, fr_mul(xr, xr)))
    for xr in round_ch:
        xr_inv = fr_inv(xr)
        eq2.append(fr_sub(0, fr_mul(xr_inv, xr_inv)))
    return eq1, eq2


class BatchRangeVerifier:
    """Vectorized range-proof verification for one public-parameter set."""

    def __init__(self, pp):
        self.params = RangeVerifierParams.from_pp(pp)

    def verify(self, proofs: list[rp.RangeProof], commitments: list) -> np.ndarray:
        """Returns a bool accept vector, one entry per (proof, commitment)."""
        params = self.params
        n = params.bit_length
        B = len(proofs)
        if B == 0:
            return np.zeros(0, dtype=bool)
        ok_structure = np.array(
            [proofs[i] is not None and _structure_ok(proofs[i], params.rounds)
             for i in range(B)])
        live = [i for i in range(B) if ok_structure[i]]
        if not live:
            return ok_structure

        transcripts = {i: _host_phase_a(proofs[i], commitments[i], params)
                       for i in live}

        # ---- pass 1: K + right_gen' on device
        k_point_list = {}
        for i in live:
            d = proofs[i].data
            pts = [d.D, d.C, params.P] + params.left_gen + params.right_gen
            k_point_list[i] = pts
        # K and eq2 share one padded term bucket -> one compiled MSM shape;
        # the batch axis pads to a size bucket for the same reason.
        t_bucket = _next_pow2(2 * n + 2 * params.rounds + 5)
        b_bucket = _bucket_rows(len(live))
        id_pt = limbs.point_to_projective_limbs(bn254.G1_IDENTITY)
        zero_sc = np.zeros(limbs.NLIMBS, dtype=np.uint32)
        k_pts_np = np.stack(
            [limbs.points_to_projective_limbs(k_point_list[i]) for i in live])
        k_sc_np = np.stack(
            [limbs.scalars_to_limbs(transcripts[i].k_scalars) for i in live])
        k_pts_np, k_sc_np = _pad_terms(k_pts_np, k_sc_np, t_bucket)
        k_pts = jnp.asarray(_pad_rows(k_pts_np, b_bucket, id_pt))
        k_sc = jnp.asarray(_pad_rows(k_sc_np, b_bucket, zero_sc))
        yinv_np = np.stack(
            [limbs.scalars_to_limbs(transcripts[i].yinv_pows) for i in live])
        yinv = jnp.asarray(_pad_rows(yinv_np, b_bucket, zero_sc))
        rgp_aff, k_aff = _pass1_kernel(params.right_gen_dev, yinv, k_pts, k_sc)
        rgp_bytes = affine_batch_to_bytes(np.asarray(rgp_aff)[:len(live)])
        k_bytes = affine_batch_to_bytes(np.asarray(k_aff)[:len(live)])

        # ---- host: challenges + scalar expansion
        eq1_sc_rows, eq2_sc_rows = [], []
        eq1_pt_rows, eq2_pt_rows = [], []
        for row, i in enumerate(live):
            d = proofs[i].data
            rgp_hex = [bytes(rgp_bytes[row, j]).hex().encode("ascii")
                       for j in range(n)]
            k_hex = bytes(k_bytes[row]).hex().encode("ascii")
            eq1, eq2 = _host_phase_b(proofs[i], transcripts[i], rgp_hex,
                                     k_hex, params)
            eq1_sc_rows.append(eq1)
            eq2_sc_rows.append(eq2)
            eq1_pt_rows.append([params.commitment_gen[0],
                                params.commitment_gen[1],
                                d.T1, d.T2, commitments[i]])
            eq2_pt_rows.append(
                params.left_gen + params.right_gen
                + [params.Q, d.D, d.C, params.P]
                + proofs[i].ipa.L + proofs[i].ipa.R)

        eq1_pts_np = np.stack(
            [limbs.points_to_projective_limbs(r) for r in eq1_pt_rows])
        eq1_sc_np = np.stack(
            [limbs.scalars_to_limbs(r) for r in eq1_sc_rows])
        eq2_pts_np = np.stack(
            [limbs.points_to_projective_limbs(r) for r in eq2_pt_rows])
        eq2_sc_np = np.stack(
            [limbs.scalars_to_limbs(r) for r in eq2_sc_rows])
        eq2_pts_np, eq2_sc_np = _pad_terms(eq2_pts_np, eq2_sc_np, t_bucket)

        accept_live = np.asarray(_pass2_kernel(
            jnp.asarray(_pad_rows(eq1_pts_np, b_bucket, id_pt)),
            jnp.asarray(_pad_rows(eq1_sc_np, b_bucket, zero_sc)),
            jnp.asarray(_pad_rows(eq2_pts_np, b_bucket, id_pt)),
            jnp.asarray(_pad_rows(eq2_sc_np, b_bucket, zero_sc))))[:len(live)]
        out = np.zeros(B, dtype=bool)
        for row, i in enumerate(live):
            out[i] = bool(accept_live[row])
        return out

    def verify_range_correctness(self, rc: rp.RangeCorrectness,
                                 commitments: list) -> np.ndarray:
        return self.verify(list(rc.proofs), commitments)
