"""Shared batch/shape bucketing policy for the device verifier kernels.

Every device entry point pads its batch and term axes up to a small fixed
set of bucket sizes so the whole framework compiles a handful of XLA
executables total (first compiles are minutes; the persistent cache then
serves every run). Both the range verifier and the audit reopen use this
module so the policy cannot drift between kernels.
"""

from __future__ import annotations

import numpy as np

#: Batch-dimension buckets: every request size pads up to one of these.
#: 2048 exists so the chip's post-fusion sweet spot doesn't pad to 4096
#: (a 2048-proof block would otherwise pay double device work); 256/512
#: exist because the pipelined verifier's row CHUNKS (default 256) must
#: land exactly on a bucket — padding a chunk to 1024 would quadruple
#: pass-1 device work.
B_BUCKETS = (16, 128, 256, 512, 1024, 2048, 4096)


def bucket_rows(b: int) -> int:
    for cap in B_BUCKETS:
        if b <= cap:
            return cap
    return ((b + B_BUCKETS[-1] - 1) // B_BUCKETS[-1]) * B_BUCKETS[-1]


def next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def pad_rows(arr: np.ndarray, b_target: int, pad_row: np.ndarray) -> np.ndarray:
    """Pad the batch axis to the bucket size by repeating `pad_row`."""
    B = arr.shape[0]
    if B == b_target:
        return arr
    pad = np.broadcast_to(pad_row, (b_target - B,) + arr.shape[1:])
    return np.concatenate([arr, pad], axis=0)
