"""Batched TPU commitment re-opening for the ZK auditor.

The reference auditor inspects each output sequentially: recompute
``commit(H(type), value, bf)`` over the three Pedersen generators and compare
with the token data (reference token/core/zkatdlog/nogh/v1/crypto/audit/
auditor.go:225-246). That is a width-3 fixed-base MSM plus one point
comparison per output — embarrassingly parallel across a request (or a whole
block of requests, BASELINE config 3).

Device formulation, one row per output:
    g0^H(type) * g1^value * g2^bf - Data == identity
i.e. a 3-term fixed-base MSM over the pp Pedersen generators (8-bit windowed
tables, no doublings) plus the negated variable point. One kernel launch per
batch; rows padded to the shared batch buckets so a handful of compiled
shapes cover every request size.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..crypto import bn254
from ..crypto.bn254 import hash_to_zr
from ..ops import ec, limbs
from .batching import bucket_rows as _bucket_rows

R = bn254.R


@jax.jit
def _reopen_kernel(tables, fixed_sc, data_pts):
    """(B,) bool: fixed-base commit MSM minus the claimed data is identity."""
    com = ec.fixed_base_msm(tables, fixed_sc)
    return ec.is_identity(ec.add(com, ec.neg(data_pts)))


class BatchAuditReopen:
    """Vectorized commitment re-open for one public-parameter set."""

    def __init__(self, pp):
        gens = list(pp.pedersen_generators)
        if len(gens) != 3:
            raise ValueError("length of Pedersen basis != 3")
        gen_dev = jnp.asarray(limbs.points_to_projective_limbs(gens))
        self.tables = jax.jit(ec.fixed_base_planes)(gen_dev)

    def verify(self, openings: list[tuple]) -> np.ndarray:
        """openings: list of (data G1, token_type str, value, bf).

        Returns a bool accept vector; rows with a malformed opening (None
        value/bf or value out of Fr) are False without touching the device.
        """
        B = len(openings)
        if B == 0:
            return np.zeros(0, dtype=bool)
        ok = np.zeros(B, dtype=bool)
        live, rows_sc, rows_pt = [], [], []
        for i, (data, token_type, value, bf) in enumerate(openings):
            if data is None or value is None or bf is None:
                continue
            if not (0 <= value < R and 0 <= bf < R):
                continue
            live.append(i)
            rows_sc.append([hash_to_zr(token_type.encode()), value, bf])
            rows_pt.append(data)
        if not live:
            return ok

        b_bucket = _bucket_rows(len(live))
        sc = np.stack([limbs.scalars_to_limbs(r) for r in rows_sc])
        pts = limbs.points_to_projective_limbs(rows_pt)
        if len(live) < b_bucket:
            pad = b_bucket - len(live)
            sc = np.concatenate(
                [sc, np.zeros((pad,) + sc.shape[1:], dtype=sc.dtype)])
            id_pt = limbs.point_to_projective_limbs(bn254.G1_IDENTITY)
            pts = np.concatenate(
                [pts, np.broadcast_to(id_pt, (pad,) + id_pt.shape)])
        accept = np.asarray(
            _reopen_kernel(self.tables, jnp.asarray(sc), jnp.asarray(pts)))
        for row, i in enumerate(live):
            ok[i] = bool(accept[row])
        return ok
