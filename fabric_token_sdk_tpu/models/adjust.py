"""Batched device point adjustment: out[i] = points[i] - minus[i].

The zkatdlog verifiers adjust every range commitment by the action's
commitment_to_type before verification (reference
crypto/transfer/transfer.go:176-180, crypto/issue/verifier.go:50-53:
com = out - com_type). The host affine add costs ~0.5 ms each (one
Fermat inversion per add), so a 4k-action block spends seconds on
adjustments alone; this routes them through one device complete-add +
a single batched-inversion affine conversion and rebuilds host points
from the returned 64-byte encodings.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..crypto import bn254
from ..crypto import serialization as ser
from ..crypto.bn254 import g1_add, g1_neg
from ..obs import GLOBAL as _METRICS
from ..ops import ec, limbs
from .batching import bucket_rows
from .range_verifier import affine_batch_to_bytes

_METRICS.describe(
    "adjust_points_total",
    "Commitment adjustments performed, by host/device path")

#: Below this count the two host adds beat the device round-trip.
_HOST_THRESHOLD = 16


@jax.jit
def _adjust_kernel(a, b):
    out = ec.add(a, ec.neg(b))
    return ec.to_affine_batch(out[None])[0]


def adjust_points_async(points: list, minus: list):
    """Dispatch the device adjustment and return a collect() closure.

    The kernel call and the device->host copy go out immediately
    (copy_to_host_async); the returned closure blocks only when the host
    points are actually needed — callers overlap other dispatches (the
    Σ batch, the range pass-1 marshal) with the transfer.
    """
    n = len(points)
    assert len(minus) == n
    if n == 0 or n < _HOST_THRESHOLD:
        if n:
            _METRICS.counter("adjust_points_total", path="host").add(n)
        out = [g1_add(p, g1_neg(m)) for p, m in zip(points, minus)]
        return lambda: out
    _METRICS.counter("adjust_points_total", path="device").add(n)
    nb = bucket_rows(n)
    arr_a = np.zeros((nb, 3, limbs.NLIMBS), dtype=np.uint32)
    arr_b = np.zeros((nb, 3, limbs.NLIMBS), dtype=np.uint32)
    arr_a[:n] = limbs.points_to_projective_limbs(list(points))
    arr_b[:n] = limbs.points_to_projective_limbs(list(minus))
    aff = _adjust_kernel(jnp.asarray(arr_a), jnp.asarray(arr_b))
    try:
        aff.copy_to_host_async()
    except (AttributeError, NotImplementedError, TypeError):
        pass

    def collect() -> list:
        enc = affine_batch_to_bytes(np.asarray(aff)[:n])
        zero = b"\x00" * ser.G1_BYTES_LEN
        out = []
        for i in range(n):
            raw = enc[i].tobytes()
            if raw == zero:
                out.append(bn254.G1_IDENTITY)
            else:
                # device output is on-curve by construction; skip the check
                out.append(bn254.G1(int.from_bytes(raw[:32], "big"),
                                    int.from_bytes(raw[32:], "big")))
        return out

    return collect


def adjust_points(points: list, minus: list) -> list:
    """Element-wise points[i] - minus[i] -> host G1 list.

    One device pass for large batches; the host oracle path for small
    ones (per-request latency: two bigint adds beat a tunnel dispatch).
    """
    return adjust_points_async(points, minus)()


def prewarm(batch_sizes=(1024,)) -> None:
    """Compile _adjust_kernel for the buckets covering `batch_sizes`;
    sizes below the host threshold still warm the smallest device bucket
    (the first real >=16-commitment request must not pay the compile)."""
    g = bn254.G1_GENERATOR
    for b in batch_sizes:
        n = max(b, _HOST_THRESHOLD)
        adjust_points([g] * n, [g] * n)
