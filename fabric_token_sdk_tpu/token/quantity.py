"""Precision-bounded token quantity arithmetic.

Behavioral mirror of reference token/token/quantity.go: quantities are
non-negative integers bounded to a bit precision (16/32/64 in shipped
drivers); string parsing follows Go big.Int#scan (base prefixes 0x/0o/0b,
underscores rejected unless base 0 allows them), hex output is "0x"-prefixed,
and add/sub fail on precision overflow or negative results.
"""

from __future__ import annotations

from dataclasses import dataclass


class QuantityError(ValueError):
    pass


def _digit_val(ch: str) -> int | None:
    if "0" <= ch <= "9":
        return ord(ch) - ord("0")
    if "a" <= ch <= "z":
        return ord(ch) - ord("a") + 10
    if "A" <= ch <= "Z":
        return ord(ch) - ord("A") + 10
    return None


def _parse_scan(s: str) -> int | None:
    """Go big.Int.SetString(s, 0) semantics, implemented exactly (not via
    Python int(s, 0), which diverges): no whitespace is accepted; a leading
    "0" with more digits is the legacy OCTAL prefix ("010" == 8); "0x"/"0o"/
    "0b" select hex/octal/binary; '_' separators are permitted only between
    a base prefix and a digit or between successive digits; the whole string
    must be consumed."""
    if not s:
        return None
    neg = s[0] == "-"
    body = s[1:] if s[0] in "+-" else s
    if not body:
        return None
    base, digits, prefixed = 10, body, False
    if body[0] == "0" and len(body) > 1:
        c = body[1]
        if c in "xX":
            base, digits, prefixed = 16, body[2:], True
        elif c in "oO":
            base, digits, prefixed = 8, body[2:], True
        elif c in "bB":
            base, digits, prefixed = 2, body[2:], True
        else:
            base, digits, prefixed = 8, body[1:], True  # legacy octal
    val = 0
    prev = "prefix" if prefixed else "start"
    for ch in digits:
        if ch == "_":
            if prev not in ("digit", "prefix"):
                return None
            prev = "_"
            continue
        d = _digit_val(ch)
        if d is None or d >= base:
            return None
        val = val * base + d
        prev = "digit"
    if prev != "digit":  # empty digits ("0x") or trailing underscore
        return None
    return -val if neg else val


@dataclass(frozen=True)
class Quantity:
    """Immutable non-negative integer bounded to `precision` bits."""

    value: int
    precision: int

    def add(self, other: "Quantity") -> "Quantity":
        res = self.value + other.value
        if res.bit_length() > self.precision:
            raise QuantityError(
                f"{res} has precision {res.bit_length()} > {self.precision}")
        return Quantity(res, self.precision)

    def sub(self, other: "Quantity") -> "Quantity":
        res = self.value - other.value
        if res < 0:
            raise QuantityError(f"{self.value} < {other.value}")
        return Quantity(res, self.precision)

    def cmp(self, other: "Quantity") -> int:
        return (self.value > other.value) - (self.value < other.value)

    def hex(self) -> str:
        return hex(self.value)

    def decimal(self) -> str:
        return str(self.value)

    def __str__(self) -> str:
        return self.decimal()


def to_quantity(s: str, precision: int) -> Quantity:
    """Parse per big.Int#scan; reject negatives and precision overflow
    (quantity.go:46-69)."""
    if precision == 0:
        raise QuantityError("precision must be larger than 0")
    v = _parse_scan(s)
    if v is None:
        raise QuantityError(f"invalid input [{s},{precision}]")
    if v < 0:
        raise QuantityError("quantity must be larger than 0")
    if v.bit_length() > precision:
        raise QuantityError(
            f"{s} has precision {v.bit_length()} > {precision}")
    return Quantity(v, precision)


def uint64_to_quantity(v: int, precision: int) -> Quantity:
    """quantity.go:71-93."""
    if precision == 0:
        raise QuantityError("precision must be larger than 0")
    if v < 0:
        raise QuantityError("quantity must be larger than 0")
    if v.bit_length() > precision:
        raise QuantityError(f"{v} has precision {v.bit_length()} > {precision}")
    return Quantity(v, precision)


def new_zero(precision: int) -> Quantity:
    return Quantity(0, precision)


def new_one(precision: int) -> Quantity:
    return Quantity(1, precision)


def sum_quantities(hex_values: list[str], precision: int) -> Quantity:
    total = new_zero(precision)
    for h in hex_values:
        total = total.add(to_quantity(h, precision))
    return total
