"""Token API facade: ManagementService bound to one TMSID.

Behavioral mirror of reference token/tms.go:32-185: the single entry point
an application holds for one token management service instance — exposing
the public-parameters manager, the validator, the driver services, and the
request factory. ``GetManagementService`` (tms.go:185) maps to
``TMSProvider.get_management_service`` in core/registry.py.
"""

from __future__ import annotations

from .request_builder import Request


class PublicParametersManager:
    """token/ppm.go facade over the driver's pp (serialize / validate /
    precision / auditors / issuers surface)."""

    def __init__(self, pp):
        self._pp = pp

    def public_parameters(self):
        return self._pp

    def serialize(self) -> bytes:
        return self._pp.serialize()

    def validate(self) -> None:
        self._pp.validate()

    def precision(self) -> int:
        rpp = getattr(self._pp, "range_proof_params", None)
        if rpp is not None:
            return rpp.bit_length
        return self._pp.quantity_precision

    def auditors(self) -> list[bytes]:
        auditor = getattr(self._pp, "auditor", None)
        return [bytes(auditor)] if auditor else []

    def issuers(self) -> list[bytes]:
        return [bytes(i) for i in getattr(self._pp, "issuer_ids", [])]


class TokenManagementService:
    """token.ManagementService (tms.go:32): facade over one driver bundle."""

    def __init__(self, tmsid, bundle):
        self.tmsid = tmsid
        self._bundle = bundle
        self._ppm = PublicParametersManager(bundle.public_params)

    # ------------------------------------------------------------ accessors
    def public_parameters_manager(self) -> PublicParametersManager:
        return self._ppm

    def validator(self):
        """tms.go Validator() — the request verifier (TPU-batched for
        zkatdlog when the bundle was built with device=True)."""
        return self._bundle.validator

    def deserializer(self):
        return self._bundle.deserializer

    def driver_services(self):
        return self._bundle.services

    @property
    def label(self) -> str:
        return self._bundle.label

    # ------------------------------------------------------------- requests
    def new_request(self, anchor: str) -> Request:
        """token.NewRequest (tms.go/request.go:165): an empty request bound
        to this TMS and anchor."""
        return Request(anchor, self._bundle.services)
