"""Token API facade: ManagementService bound to one TMSID.

Behavioral mirror of reference token/tms.go:32-185: the single entry point
an application holds for one token management service instance — exposing
the public-parameters manager, the validator, the driver services, and the
request factory. ``GetManagementService`` (tms.go:185) maps to
``TMSProvider.get_management_service`` in core/registry.py.
"""

from __future__ import annotations

from .request_builder import Request


class Vault:
    """sdk/vault/vault.go:20-90: the {tokendb, ttxdb, auditdb} triple plus
    the certification storage, exposed through a QueryEngine."""

    def __init__(self, tokendb, ttxdb=None, auditdb=None,
                 certification_db=None):
        self.tokendb = tokendb
        self.ttxdb = ttxdb
        self.auditdb = auditdb
        self.certification_db = certification_db

    # ---- QueryEngine (driver/vault.go surface)
    def unspent_tokens_iterator(self, wallet_id=None, token_type=None):
        return iter(self.tokendb.unspent_tokens(wallet_id, token_type))

    def unspent_tokens(self, wallet_id=None, token_type=None):
        return self.tokendb.unspent_tokens(wallet_id, token_type)

    def balance(self, wallet_id, token_type) -> int:
        return self.tokendb.balance(wallet_id, token_type)

    def is_mine(self, token_id, wallet_id) -> bool:
        return self.tokendb.is_mine(token_id, wallet_id)

    def get_status(self, tx_id) -> str:
        if self.ttxdb is None:
            raise LookupError("vault has no transaction store")
        return self.ttxdb.get_status(tx_id)

    # ---- CertificationStorage (sdk/vault CertificationStorage)
    def certification_exists(self, token_id) -> bool:
        return (self.certification_db is not None
                and self.certification_db.exists(token_id))

    def store_certifications(self, certifications) -> None:
        if self.certification_db is None:
            raise LookupError("vault has no certification store")
        self.certification_db.store(certifications)


class PublicParametersManager:
    """token/ppm.go facade over the driver's pp (serialize / validate /
    precision / auditors / issuers surface)."""

    def __init__(self, pp):
        self._pp = pp

    def public_parameters(self):
        return self._pp

    def serialize(self) -> bytes:
        return self._pp.serialize()

    def validate(self) -> None:
        self._pp.validate()

    def precision(self) -> int:
        rpp = getattr(self._pp, "range_proof_params", None)
        if rpp is not None:
            return rpp.bit_length
        return self._pp.quantity_precision

    def auditors(self) -> list[bytes]:
        auditor = getattr(self._pp, "auditor", None)
        return [bytes(auditor)] if auditor else []

    def issuers(self) -> list[bytes]:
        return [bytes(i) for i in getattr(self._pp, "issuer_ids", [])]


class TokenManagementService:
    """token.ManagementService (tms.go:32): facade over one driver bundle.

    The node-scoped components (vault, wallet manager, selector, signing
    identity) attach via ``bind`` — the reference wires the same pieces
    into the TMS through dig providers at node bootstrap (sdk/dig)."""

    def __init__(self, tmsid, bundle):
        self.tmsid = tmsid
        self._bundle = bundle
        self._ppm = PublicParametersManager(bundle.public_params)
        self._vault = None
        self._wallet_manager = None
        self._selector_manager = None
        self._sig_service = None

    # -------------------------------------------------------------- binding
    def bind(self, vault=None, wallet_manager=None, selector_manager=None,
             sig_service=None) -> "TokenManagementService":
        self._vault = vault or self._vault
        self._wallet_manager = wallet_manager or self._wallet_manager
        self._selector_manager = selector_manager or self._selector_manager
        self._sig_service = sig_service or self._sig_service
        return self

    def _bound(self, obj, what: str):
        if obj is None:
            raise LookupError(
                f"TMS [{self.tmsid}] has no {what} bound (node-scoped "
                "component; attach with .bind())")
        return obj

    # ------------------------------------------------------------ accessors
    def public_parameters_manager(self) -> PublicParametersManager:
        return self._ppm

    def validator(self):
        """tms.go Validator() — the request verifier (TPU-batched for
        zkatdlog when the bundle was built with device=True)."""
        return self._bundle.validator

    def deserializer(self):
        return self._bundle.deserializer

    def driver_services(self):
        return self._bundle.services

    def vault(self) -> Vault:
        """tms.go Vault(): the node's token/tx/audit stores."""
        return self._bound(self._vault, "vault")

    def wallet_manager(self):
        """tms.go WalletManager(): the role-based wallet registry."""
        return self._bound(self._wallet_manager, "wallet manager")

    def selector_manager(self):
        """tms.go SelectorManager(): the token selector."""
        return self._bound(self._selector_manager, "selector manager")

    def sig_service(self):
        """tms.go SigService(): the node's signing identity."""
        return self._bound(self._sig_service, "sig service")

    @property
    def label(self) -> str:
        return self._bundle.label

    # ------------------------------------------------------------- requests
    def new_request(self, anchor: str) -> Request:
        """token.NewRequest (tms.go/request.go:165): an empty request bound
        to this TMS and anchor."""
        return Request(anchor, self._bundle.services)

    def new_full_request_from_bytes(self, raw: bytes) -> "FullRequest":
        """tms.go NewFullRequestFromBytes: unmarshal a wire TokenRequest
        AND its driver actions through this TMS's validator — the bound
        shape finality listeners re-derive tokens from."""
        from ..driver.request import TokenRequest

        wire = TokenRequest.from_bytes(raw)
        actions = self._bundle.validator.unmarshal_actions(raw)
        return FullRequest(wire=wire, actions=actions)


class FullRequest:
    """A received (fully assembled) request: the wire TokenRequest plus its
    deserialized driver actions (token/request.go NewFullRequestFromBytes
    result surface used by ingestion)."""

    def __init__(self, wire, actions):
        self.wire = wire
        self.actions = actions

    def token_request(self):
        return self.wire

    def to_bytes(self) -> bytes:
        return self.wire.to_bytes()

    def message_to_sign(self, anchor: bytes) -> bytes:
        return self.wire.message_to_sign(anchor)

    def outputs(self):
        """All output slots across actions, ingestion order (issues then
        transfers — the global output numbering)."""
        out = []
        for action in self.actions:
            out.extend(action.get_outputs())
        return out
