"""token.Request builder: the application-facing action assembler.

Behavioral mirror of reference token/request.go: a Request accumulates
Issue/Transfer/Redeem actions (request.go:225,287,341) together with their
metadata, produces the serialized driver TokenRequest and the
message-to-sign (request.go:968 MarshalToSign), and runs the auditor-side
AuditCheck (request.go:1145) through the driver's audit service.

The heavy lifting per action is delegated to the driver services bound at
construction (fabtoken plaintext or zkatdlog ZK) — the same layering as the
reference, where Request methods call into the driver's
IssueService/TransferService.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..driver.request import TokenRequest


class RequestBuilderError(Exception):
    pass


@dataclass
class _PlannedOutput:
    """Distribution bookkeeping: which action's output goes to whom.

    Global output indexes are resolved at read time (``distribution()``)
    because ingestion numbers outputs issues-first then transfers
    (core/common/validator.py unmarshal order), regardless of the order the
    builder methods were called in.
    """

    kind: str                 # "issue" | "transfer"
    action_pos: int           # position within that kind's list
    local_index: int          # output index within the action
    receiver: object
    opening: bytes | None


class Request:
    """One token request under assembly, bound to an anchor + driver."""

    def __init__(self, anchor: str, driver_services):
        self.anchor = anchor
        self.driver = driver_services
        self._issues: list = []           # (action, metadata | None)
        self._transfers: list = []
        self._planned: list[_PlannedOutput] = []
        self._input_owner_ids: list[bytes] = []

    # ------------------------------------------------------------- builders
    def issue(self, issuer_identity: bytes, outputs,
              receivers: list | None = None) -> object:
        """request.go:225 Issue: append one issue action.

        outputs: list[OutputSpec]; receivers: parallel opaque receiver tags
        (e.g. node names) recorded in the distribution plan.
        """
        action, md = self.driver.assemble_issue(issuer_identity, outputs)
        self._plan_outputs("issue", len(self._issues), md, outputs, receivers)
        self._issues.append((action, md))
        return action

    def transfer(self, input_rows, outputs, wallet=None,
                 sender_audit_info=None, receivers: list | None = None
                 ) -> object:
        """request.go:287 Transfer / :341 Redeem (a redeem is a transfer
        whose output has an empty owner)."""
        action, md = self.driver.assemble_transfer(
            input_rows, outputs, wallet=wallet,
            sender_audit_info=sender_audit_info)
        self._plan_outputs("transfer", len(self._transfers), md, outputs,
                           receivers)
        self._transfers.append((action, md))
        self._input_owner_ids.extend(bytes(r.owner) for r in input_rows)
        return action

    def upgrade(self, input_rows, receiver: bytes, wallet=None,
                sender_audit_info=None, receiver_tag=None,
                receiver_audit_info: bytes = b"") -> object:
        """request.go:389 Upgrade: convert old-format ledger tokens into
        tokens under the CURRENT public parameters, crediting the full
        value to `receiver`.

        The reference routes upgrades through the issue service with a
        TokensUpgradeRequest; this framework's equivalent mechanism is the
        transfer path — old-format inputs automatically acquire upgrade
        witnesses binding the fresh commitments to the ledger bytes
        (core/zkatdlog/driver.py assemble_transfer, validated by the
        validator's upgrade-witness step). The verb surface is the same:
        one call, old tokens in, new-format tokens out.
        """
        rows = list(input_rows)
        if not rows:
            raise RequestBuilderError("tokens is empty")
        from ..core.fabtoken.driver import OutputSpec
        from ..token.quantity import sum_quantities

        precision = getattr(self.driver, "precision", None)
        if precision is None:
            # zkatdlog: value range is the range-proof bit length
            precision = self.driver.pp.range_proof_params.bit_length
        total = sum_quantities([r.quantity for r in rows], precision)
        spec = OutputSpec(owner=bytes(receiver), token_type=rows[0].type,
                          value=total.value, audit_info=receiver_audit_info)
        return self.transfer(rows, [spec], wallet=wallet,
                             sender_audit_info=sender_audit_info,
                             receivers=[receiver_tag] if receiver_tag
                             else None)

    def _plan_outputs(self, kind, action_pos, md, outputs, receivers) -> None:
        for i, spec in enumerate(outputs):
            opening = None
            if md is not None:
                opening = md.outputs[i].output_metadata
            receiver = receivers[i] if receivers else None
            self._planned.append(_PlannedOutput(
                kind=kind, action_pos=action_pos, local_index=i,
                receiver=receiver, opening=opening))

    def _global_index(self, p: _PlannedOutput) -> int:
        """Issues-first numbering, matching ingestion order."""
        base = 0
        if p.kind == "issue":
            for a, _ in self._issues[:p.action_pos]:
                base += len(a.get_outputs())
        else:
            for a, _ in self._issues:
                base += len(a.get_outputs())
            for a, _ in self._transfers[:p.action_pos]:
                base += len(a.get_outputs())
        return base + p.local_index

    # -------------------------------------------------------------- outputs
    def token_request(self) -> TokenRequest:
        """The wire-level driver request (request.go RequestToBytes)."""
        return TokenRequest(
            issues=[a.serialize() for a, _ in self._issues],
            transfers=[a.serialize() for a, _ in self._transfers])

    def request_metadata(self):
        """driver.TokenRequestMetadata for commitment drivers, else None."""
        issue_md = [md for _, md in self._issues]
        transfer_md = [md for _, md in self._transfers]
        if all(m is None for m in issue_md + transfer_md):
            return None
        from ..core.zkatdlog.metadata import RequestMetadata

        return RequestMetadata(
            issues=[m for m in issue_md if m is not None],
            transfers=[m for m in transfer_md if m is not None])

    def distribution(self) -> list[tuple[object, int, bytes]]:
        """(receiver, global index, opening) triples for the ttx
        distribution step (endorse.go:444)."""
        return [(p.receiver, self._global_index(p), p.opening)
                for p in self._planned
                if p.receiver is not None and p.opening is not None]

    def input_owner_ids(self) -> list[bytes]:
        return list(self._input_owner_ids)

    def bind_to(self, binder, identity: bytes, wallet_service) -> None:
        """request.go:1069 BindTo: when the party submitting this request
        changes (e.g. a recipient finalizes a transaction assembled by the
        sender), every transfer sender, extra signer, and receiver identity
        that is NOT owned by a local wallet must be bound to the submitting
        party's identity so endorsement-signature resolution routes to it.

        binder: any object with bind(long_term: bytes, ephemeral: bytes)
        (the endpoint-binding service); wallet_service: the local
        WalletService used to recognize own identities (required — without
        it every local identity would be mis-bound to the submitter).
        """
        if wallet_service is None:
            raise RequestBuilderError(
                "bind_to needs the local wallet service")
        ws = wallet_service

        def is_mine(ident: bytes) -> bool:
            return ws.wallet(ident) is not None

        seen: set[bytes] = set()

        def bind(ident) -> None:
            if ident is None:
                return
            b = bytes(ident)
            if not b or b in seen or is_mine(b):
                return
            seen.add(b)
            binder.bind(bytes(identity), b)

        for sender in self._input_owner_ids:       # transfer senders
            bind(sender)
        for a, md in self._transfers:
            # extra signers live on the transfer METADATA (metadata.py
            # TransferActionMetadata.extra_signers), not the action
            for eid in getattr(md, "extra_signers", None) or []:
                bind(eid)
            for out in a.get_outputs():            # receivers
                bind(getattr(out, "owner", None))

    def marshal_to_sign(self) -> bytes:
        """request.go:968 MarshalToSign: the bytes every endorser, the
        issuer, and the auditor sign."""
        return self.token_request().message_to_sign(self.anchor.encode())

    # ------------------------------------------------------------- auditing
    def audit_check(self, input_tokens=None) -> None:
        """request.go:1145 AuditCheck -> driver AuditorService."""
        self.driver.audit_check(self.token_request(),
                                self.request_metadata(), input_tokens,
                                self.anchor)
