"""Input/Output streams: the app- and audit-facing token filter API.

Behavioral mirror of reference token/stream.go:1-354 — applications and
the auditor walk a request's inputs/outputs through typed filter chains
(ByRecipient / ByType / ByEnrollmentID, Sum, Count, EnrollmentIDs, ...)
instead of poking at raw actions. Streams are immutable: every filter
returns a new stream over the surviving rows.
"""

from __future__ import annotations

from dataclasses import dataclass

from .model import ID
from .quantity import to_quantity


@dataclass
class Output:
    """One output of a token action (stream.go:23-49)."""

    owner: bytes = b""
    type: str = ""
    quantity: str = "0x0"         # hex string, like token.Token.Quantity
    action_index: int = 0
    index: int = 0                # absolute position in the request
    owner_audit_info: bytes = b""
    enrollment_id: str = ""
    revocation_handler: str = ""
    ledger_output: bytes = b""
    ledger_output_format: str = ""
    ledger_output_metadata: bytes = b""
    issuer: bytes = b""

    def id(self, tx_id: str) -> ID:
        return ID(tx_id=tx_id, index=self.index)


@dataclass
class Input:
    """One input of a token action (stream.go:175-184)."""

    action_index: int = 0
    id: ID | None = None
    owner: bytes = b""
    owner_audit_info: bytes = b""
    enrollment_id: str = ""
    revocation_handler: str = ""
    type: str = ""
    quantity: str = "0x0"


def _dedup(values):
    seen, out = set(), []
    for v in values:
        if v and v not in seen:
            seen.add(v)
            out.append(v)
    return out


class OutputStream:
    """Filterable view over a request's outputs (stream.go:56-172)."""

    def __init__(self, outputs: list[Output], precision: int = 64):
        self._outputs = list(outputs)
        self.precision = precision

    def filter(self, pred) -> "OutputStream":
        return OutputStream([o for o in self._outputs if pred(o)],
                            self.precision)

    def by_recipient(self, identity: bytes) -> "OutputStream":
        identity = bytes(identity)
        return self.filter(lambda o: bytes(o.owner) == identity)

    def by_type(self, token_type: str) -> "OutputStream":
        return self.filter(lambda o: o.type == token_type)

    def by_enrollment_id(self, eid: str) -> "OutputStream":
        return self.filter(lambda o: o.enrollment_id == eid)

    def outputs(self) -> list[Output]:
        return list(self._outputs)

    def count(self) -> int:
        return len(self._outputs)

    def at(self, i: int) -> Output:
        return self._outputs[i]

    def sum(self) -> int:
        total = 0
        for o in self._outputs:
            total += to_quantity(o.quantity, self.precision).value
        return total

    def enrollment_ids(self) -> list[str]:
        return _dedup(o.enrollment_id for o in self._outputs)

    def token_types(self) -> list[str]:
        return _dedup(o.type for o in self._outputs)

    def revocation_handles(self) -> list[str]:
        return _dedup(o.revocation_handler for o in self._outputs)

    def __iter__(self):
        return iter(self._outputs)


class InputStream:
    """Filterable view over a request's inputs (stream.go:186-345).

    `query_service` needs one method: is_mine(token_id) -> bool."""

    def __init__(self, query_service, inputs: list[Input],
                 precision: int = 64):
        self._qs = query_service
        self._inputs = list(inputs)
        self.precision = precision

    def filter(self, pred) -> "InputStream":
        return InputStream(self._qs, [i for i in self._inputs if pred(i)],
                           self.precision)

    def by_enrollment_id(self, eid: str) -> "InputStream":
        return self.filter(lambda i: i.enrollment_id == eid)

    def by_type(self, token_type: str) -> "InputStream":
        return self.filter(lambda i: i.type == token_type)

    def count(self) -> int:
        return len(self._inputs)

    def at(self, i: int) -> Input:
        return self._inputs[i]

    def inputs(self) -> list[Input]:
        return list(self._inputs)

    def ids(self) -> list[ID]:
        return [i.id for i in self._inputs]

    def owners(self) -> "OwnerStream":
        return OwnerStream([bytes(i.owner) for i in self._inputs])

    def is_any_mine(self) -> bool:
        return any(self._qs.is_mine(i.id) for i in self._inputs)

    def enrollment_ids(self) -> list[str]:
        return _dedup(i.enrollment_id for i in self._inputs)

    def revocation_handles(self) -> list[str]:
        return _dedup(i.revocation_handler for i in self._inputs)

    def token_types(self) -> list[str]:
        return _dedup(i.type for i in self._inputs)

    def sum(self) -> int:
        total = 0
        for i in self._inputs:
            total += to_quantity(i.quantity, self.precision).value
        return total

    def __iter__(self):
        return iter(self._inputs)


class OwnerStream:
    """Deduplicated owner set (stream.go:347-354)."""

    def __init__(self, owners: list[bytes]):
        self._owners = _dedup(bytes(o) for o in owners)

    def count(self) -> int:
        return len(self._owners)

    def owners(self) -> list[bytes]:
        return list(self._owners)
