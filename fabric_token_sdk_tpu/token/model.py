"""Core token model.

Behavioral mirror of reference token/token/token.go:13-140: a token ID is
(tx_id, index); a Token carries (owner, type, quantity-hex); Format (ledger
encoding) and Type (currency) are distinct concepts.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from . import quantity as q


@dataclass(frozen=True)
class ID:
    """Token identity: creating transaction + output index (token.go:13-27)."""

    tx_id: str
    index: int = 0

    def __str__(self) -> str:
        return f"[{self.tx_id}:{self.index}]"


# Type is the currency (e.g. "USD"); Format is the on-ledger encoding
# (e.g. "fabtoken128", "comm") — a many-to-many relation with drivers
# (token.go:29-36).
Type = str
Format = str


@dataclass
class Token:
    """Result of issue/transfer: owner, type, base-16 "0x" quantity
    (token.go:38-47)."""

    owner: bytes
    type: Type
    quantity: str

    def quantity_int(self, precision: int) -> int:
        return q.to_quantity(self.quantity, precision).value


@dataclass
class IssuedToken:
    """Issued token view for the issuer wallet (token.go:49-62)."""

    id: ID | None
    owner: bytes
    type: Type
    quantity: str
    issuer: bytes = b""


@dataclass
class UnspentToken:
    """Unspent token view (token.go:113-124)."""

    id: ID | None
    owner: bytes
    type: Type
    quantity: str


@dataclass
class UnspentTokenInWallet:
    """Unspent token owned solely by one wallet (token.go:95-105)."""

    id: ID | None
    wallet_id: str
    type: Type
    quantity: str


@dataclass
class LedgerToken:
    """Raw on-ledger token: format + opaque payloads (token.go:107-112)."""

    id: ID
    format: Format
    token: bytes
    token_metadata: bytes


@dataclass
class TokensCollection:
    """Common container with Sum/ByType helpers (token.go:64-93,126-140)."""

    tokens: list = field(default_factory=list)

    def count(self) -> int:
        return len(self.tokens)

    def sum(self, precision: int) -> "q.Quantity":
        total = q.new_zero(precision)
        for t in self.tokens:
            total = total.add(q.to_quantity(t.quantity, precision))
        return total

    def by_type(self, token_type: Type) -> "TokensCollection":
        return TokensCollection(
            [t for t in self.tokens if t.type == token_type])
