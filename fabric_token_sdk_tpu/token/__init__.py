"""Token API layer — the application-facing surface.

Mirrors the capability surface of the reference Token API (reference
token/*.go and token/token/*.go; SURVEY.md §2.1): token model, quantity
arithmetic, token requests, and the management service façade.
"""

from .model import ID, Token, UnspentToken, IssuedToken, LedgerToken  # noqa: F401
from .quantity import Quantity, to_quantity, uint64_to_quantity  # noqa: F401
