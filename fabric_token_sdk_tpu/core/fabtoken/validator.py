"""fabtoken validation chain.

Behavioral mirror of reference token/core/fabtoken/v1/validator: transfer
chain = ActionValidate -> SignatureValidate -> BalanceValidate ->
HTLCValidate; issue chain = IssueValidate. Error strings follow the
reference so observable accept/reject behavior matches
(validator_transfer.go:23-170, validator_issue.go:17-63).
"""

from __future__ import annotations

import time as time_mod

from ...driver import TokenRequest
from ...token import quantity as q
from ..common.validator import Context, ValidationError, Validator
from .actions import IssueAction, TransferAction


class ActionDeserializer:
    """validator.go:20-42."""

    def deserialize_actions(self, tr: TokenRequest):
        issues = [IssueAction.deserialize(raw) for raw in tr.issues]
        transfers = [TransferAction.deserialize(raw) for raw in tr.transfers]
        return issues, transfers


def transfer_action_validate(ctx: Context) -> None:
    ctx.transfer_action.validate()


def transfer_signature_validate(ctx: Context) -> None:
    """validator_transfer.go:28-47: every input owner must have signed."""
    ctx.input_tokens = list(ctx.transfer_action.input_tokens)
    for tok in ctx.input_tokens:
        owner = tok.get_owner()
        try:
            verifier = ctx.deserializer.get_owner_verifier(owner)
        except Exception as e:
            raise ValidationError(
                f"failed deserializing owner [{e}]") from e
        try:
            sigma = ctx.signature_provider.has_been_signed_by(owner, verifier)
        except Exception as e:
            raise ValidationError(
                f"failed signature verification [{e}]") from e
        ctx.signatures.append(sigma)


def transfer_balance_validate(ctx: Context) -> None:
    """validator_transfer.go:50-93: same type everywhere, sum-in == sum-out."""
    action = ctx.transfer_action
    if action.num_outputs() == 0:
        raise ValidationError("there is no output")
    if len(ctx.input_tokens) == 0:
        raise ValidationError("there is no input")
    if ctx.input_tokens[0] is None:
        raise ValidationError("first input is nil")
    precision = ctx.pp.precision()
    typ = ctx.input_tokens[0].type
    input_sum = q.new_zero(precision)
    output_sum = q.new_zero(precision)
    for i, tok in enumerate(ctx.input_tokens):
        if tok is None:
            raise ValidationError(f"input {i} is nil")
        try:
            input_sum = input_sum.add(q.to_quantity(tok.quantity, precision))
        except q.QuantityError as e:
            raise ValidationError(
                f"failed parsing quantity [{tok.quantity}]: {e}") from e
        if tok.type != typ:
            raise ValidationError(
                f"input type {tok.type} does not match type {typ}")
    for out in action.get_outputs():
        try:
            output_sum = output_sum.add(q.to_quantity(out.quantity, precision))
        except q.QuantityError as e:
            raise ValidationError(
                f"failed parsing quantity [{out.quantity}]: {e}") from e
        if out.type != typ:
            raise ValidationError(
                f"output type {out.type} does not match type {typ}")
    if input_sum.cmp(output_sum) != 0:
        raise ValidationError(
            f"input sum {input_sum} does not match output sum {output_sum}")


def transfer_htlc_validate(ctx: Context) -> None:
    """validator_transfer.go:96-170; deferred to the htlc service module."""
    from ...services.interop import htlc

    htlc.transfer_htlc_validate_fabtoken(ctx, now=time_mod.time())


def issue_validate(ctx: Context) -> None:
    """validator_issue.go:17-63."""
    action = ctx.issue_action
    try:
        action.validate()
    except Exception as e:
        raise ValidationError(
            f"failed validating issue action: {e}") from e
    if action.num_outputs() == 0:
        raise ValidationError("there is no output")
    precision = ctx.pp.precision()
    for out in action.get_outputs():
        try:
            quantity = q.to_quantity(out.quantity, precision)
        except q.QuantityError as e:
            raise ValidationError(
                f"failed parsing quantity [{out.quantity}]: {e}") from e
        if quantity.value == 0:
            raise ValidationError("quantity is zero")
    issuers = ctx.pp.issuers()
    if issuers:
        if not any(bytes(action.issuer) == bytes(i) for i in issuers):
            raise ValidationError(
                f"issuer [{action.issuer!r}] is not in issuers")
    try:
        verifier = ctx.deserializer.get_issuer_verifier(action.issuer)
    except Exception as e:
        raise ValidationError(
            f"failed getting verifier for issuer identity: {e}") from e
    try:
        ctx.signature_provider.has_been_signed_by(action.issuer, verifier)
    except Exception as e:
        raise ValidationError(f"failed verifying signature: {e}") from e


def new_validator(pp, deserializer, extra_transfer_validators=()) -> Validator:
    """validator.go:48-70."""
    transfer_chain = [
        transfer_action_validate,
        transfer_signature_validate,
        transfer_balance_validate,
        transfer_htlc_validate,
        *extra_transfer_validators,
    ]
    return Validator(
        pp=pp,
        deserializer=deserializer,
        action_deserializer=ActionDeserializer(),
        transfer_validators=transfer_chain,
        issue_validators=[issue_validate],
    )
