"""fabtoken driver services: action assembly + output extraction.

The driver-facing service object a TokenNode binds (reference
token/core/fabtoken/v1/{issue.go,transfer.go,tokens.go} — IssueService,
TransferService, TokensService): plaintext actions, no request metadata, and
trivially "deobfuscated" outputs (everything is already in the clear).
"""

from __future__ import annotations

from dataclasses import dataclass

from ...driver.identity import Identity
from ...services.tokens import ExtractedOutput
from ...token.model import ID
from . import actions


@dataclass
class OutputSpec:
    """One requested output: owner identity bytes + type + integer value.

    owner == b"" denotes a redeem output (request.go:341 Redeem).
    """

    owner: bytes
    token_type: str
    value: int
    audit_info: bytes = b""


class FabTokenDriverService:
    """Driver services for the plaintext UTXO driver."""

    label = "fabtoken"
    actions = actions

    def __init__(self, precision: int = 64):
        self.precision = precision

    # ------------------------------------------------------------- assembly
    def assemble_issue(self, issuer_identity: bytes,
                       outputs: list[OutputSpec]):
        """v1/issue.go Issue: plaintext outputs, no metadata."""
        action = actions.IssueAction(
            issuer=Identity(issuer_identity),
            outputs=[actions.Output(owner=o.owner, type=o.token_type,
                                    quantity=hex(o.value)) for o in outputs],
        )
        return action, None

    def assemble_transfer(self, input_rows, outputs: list[OutputSpec],
                          wallet=None, sender_audit_info=None):
        """v1/transfer.go Transfer: claimed input tokens + plaintext outputs.

        input_rows: UnspentToken rows from the selector (owner/type/quantity
        in the clear).
        """
        action = actions.TransferAction(
            inputs=[r.id for r in input_rows],
            input_tokens=[actions.Output(owner=bytes(r.owner), type=r.type,
                                         quantity=r.quantity)
                          for r in input_rows],
            outputs=[actions.Output(owner=o.owner, type=o.token_type,
                                    quantity=hex(o.value)) for o in outputs],
        )
        return action, None

    # ------------------------------------------------------------ ingestion
    def extract_outputs(self, action, openings=None) -> list[ExtractedOutput]:
        """TokensService.Deobfuscate for plaintext tokens (everything is in
        the clear; openings are unused)."""
        outs = []
        for i, out in enumerate(action.get_outputs()):
            outs.append(ExtractedOutput(
                index=i,
                owner_raw=bytes(out.owner),
                token_type=out.type,
                quantity_hex=out.quantity,
                ledger_format=self.label,
                ledger_token=out.serialize(),
            ))
        return outs

    def parse_ledger_output(self, raw: bytes,
                            opening: bytes | None = None
                            ) -> ExtractedOutput | None:
        """Ledger-scan ingestion (processor.go:40): plaintext outputs parse
        directly; the opening is unused."""
        out = actions.Output.deserialize(raw)
        return ExtractedOutput(
            index=0, owner_raw=bytes(out.owner), token_type=out.type,
            quantity_hex=out.quantity, ledger_format=self.label,
            ledger_token=raw)

    # ------------------------------------------------------------- auditing
    def audit_check(self, request, metadata, input_tokens, tx_id: str) -> None:
        """Plaintext actions carry no commitments: nothing to re-open.
        (The app-level auditor still records/locks/endorses.)"""
        return None
