"""fabtoken public parameters.

Behavioral mirror of reference token/core/fabtoken/v1/core/setup.go:24-120:
{Label "fabtoken", Ver, QuantityPrecision <= 64, Auditor, IssuerIDs,
MaxToken = 2^precision - 1}, serialized as JSON inside the driver-level
{identifier, raw} wrapper (same envelope as zkatdlog pp).
"""

from __future__ import annotations

import base64
import json
from dataclasses import dataclass, field

from ...driver.identity import Identity

FABTOKEN_LABEL = "fabtoken"
VERSION = "1.0.0"
DEFAULT_PRECISION = 64


class SetupError(Exception):
    pass


@dataclass
class PublicParams:
    label: str = FABTOKEN_LABEL
    ver: str = VERSION
    quantity_precision: int = DEFAULT_PRECISION
    auditor: bytes = b""
    issuer_ids: list[Identity] = field(default_factory=list)
    max_token: int = (1 << DEFAULT_PRECISION) - 1

    # ---- driver.PublicParameters surface
    def identifier(self) -> str:
        return self.label

    def precision(self) -> int:
        return self.quantity_precision

    def auditors(self) -> list[Identity]:
        return [Identity(self.auditor)] if self.auditor else []

    def issuers(self) -> list[Identity]:
        return list(self.issuer_ids)

    def max_token_value(self) -> int:
        return self.max_token

    def graph_hiding(self) -> bool:
        return False

    # ---- serialization (setup.go:66-95)
    def serialize(self) -> bytes:
        inner = json.dumps({
            "Label": self.label,
            "Ver": self.ver,
            "QuantityPrecision": self.quantity_precision,
            "Auditor": base64.b64encode(self.auditor).decode("ascii"),
            "IssuerIDs": [base64.b64encode(bytes(i)).decode("ascii")
                          for i in self.issuer_ids],
            "MaxToken": self.max_token,
        }).encode()
        return json.dumps({
            "identifier": self.label,
            "raw": base64.b64encode(inner).decode("ascii"),
        }).encode()

    @classmethod
    def deserialize(cls, raw: bytes) -> "PublicParams":
        outer = json.loads(raw)
        if outer.get("identifier") != FABTOKEN_LABEL:
            raise SetupError(
                f"invalid identifier [{outer.get('identifier')}]")
        inner = json.loads(base64.b64decode(outer["raw"]))
        pp = cls(
            label=inner["Label"],
            ver=inner["Ver"],
            quantity_precision=inner["QuantityPrecision"],
            auditor=base64.b64decode(inner.get("Auditor", "")),
            issuer_ids=[Identity(base64.b64decode(x))
                        for x in inner.get("IssuerIDs", [])],
            max_token=inner["MaxToken"],
        )
        pp.validate()
        return pp

    def validate(self) -> None:
        """setup.go:97-109."""
        if self.quantity_precision > 64:
            raise SetupError(
                f"invalid precision [{self.quantity_precision}], must be "
                "smaller or equal than 64")
        if self.quantity_precision == 0:
            raise SetupError("invalid precision, should be greater than 0")
        if self.max_token != (1 << self.quantity_precision) - 1:
            raise SetupError("invalid max token")


def setup(precision: int = DEFAULT_PRECISION) -> PublicParams:
    """setup.go:41-64."""
    if precision > 64:
        raise SetupError(
            f"invalid precision [{precision}], must be smaller or equal than 64")
    if precision == 0:
        raise SetupError("invalid precision, should be greater than 0")
    return PublicParams(
        quantity_precision=precision,
        max_token=(1 << precision) - 1,
    )
