"""fabtoken actions: plaintext JSON tokens and issue/transfer actions.

Behavioral mirror of reference token/core/fabtoken/v1/core/actions.go:40-300:
an Output is a cleartext Token (owner/type/hex-quantity) wrapped with the
fabtoken format tag; IssueAction carries issuer + outputs; TransferAction
carries input IDs + the claimed input tokens + outputs. All JSON.
"""

from __future__ import annotations

import base64
import json
from dataclasses import dataclass, field

from ...driver.identity import Identity
from ...token.model import ID

# services/tokens/core/fabtoken/token.go:18-20: format tag of fabtoken tokens.
FABTOKEN_FORMAT = 1


class ActionError(ValueError):
    pass


def _b64(b: bytes) -> str:
    return base64.b64encode(bytes(b)).decode("ascii")


def _unb64(s: str | None) -> bytes:
    return base64.b64decode(s) if s else b""


def wrap_token_with_type(raw: bytes) -> bytes:
    """tokens.WrapWithType: typed-token envelope {Type, Token}."""
    return json.dumps({"Type": FABTOKEN_FORMAT, "Token": _b64(raw)}).encode()


def unmarshal_typed_token(raw: bytes) -> bytes:
    t = json.loads(raw)
    if t.get("Type") != FABTOKEN_FORMAT:
        raise ActionError(f"invalid token type [{t.get('Type')}]")
    return _unb64(t.get("Token"))


@dataclass
class Output:
    """Cleartext token output (actions.go:40-68)."""

    owner: bytes
    type: str
    quantity: str  # "0x..." base-16

    def is_redeem(self) -> bool:
        return len(self.owner) == 0

    def get_owner(self) -> bytes:
        return self.owner

    def serialize(self) -> bytes:
        raw = json.dumps({
            "owner": _b64(self.owner), "type": self.type,
            "quantity": self.quantity,
        }).encode()
        return wrap_token_with_type(raw)

    @classmethod
    def deserialize(cls, raw: bytes) -> "Output":
        body = json.loads(unmarshal_typed_token(raw))
        return cls(owner=_unb64(body.get("owner")), type=body["type"],
                   quantity=body["quantity"])

    def to_dict(self) -> dict:
        return {"owner": _b64(self.owner), "type": self.type,
                "quantity": self.quantity}

    @classmethod
    def from_dict(cls, d: dict) -> "Output":
        return cls(owner=_unb64(d.get("owner")), type=d["type"],
                   quantity=d["quantity"])


@dataclass
class IssueAction:
    """actions.go:72-175."""

    issuer: Identity
    outputs: list[Output] = field(default_factory=list)
    metadata: dict[str, bytes] = field(default_factory=dict)

    def validate(self) -> None:
        if len(self.issuer) == 0:
            raise ActionError("issuer is not set")
        if not self.outputs:
            raise ActionError("no outputs in issue action")
        if any(o is None for o in self.outputs):
            raise ActionError("nil output in issue action")

    def num_outputs(self) -> int:
        return len(self.outputs)

    def get_outputs(self) -> list[Output]:
        return list(self.outputs)

    def get_serialized_outputs(self) -> list[bytes]:
        return [o.serialize() for o in self.outputs]

    def get_inputs(self) -> list[ID]:
        return []

    def get_metadata(self) -> dict[str, bytes]:
        return self.metadata

    def is_anonymous(self) -> bool:
        return False

    def serialize(self) -> bytes:
        return json.dumps({
            "issuer": _b64(self.issuer),
            "outputs": [o.to_dict() for o in self.outputs],
            "metadata": {k: _b64(v) for k, v in self.metadata.items()},
        }).encode()

    @classmethod
    def deserialize(cls, raw: bytes) -> "IssueAction":
        d = json.loads(raw)
        return cls(
            issuer=Identity(_unb64(d.get("issuer"))),
            outputs=[Output.from_dict(o) for o in d.get("outputs", [])],
            metadata={k: _unb64(v) for k, v in (d.get("metadata") or {}).items()},
        )


@dataclass
class TransferAction:
    """actions.go:177-300."""

    inputs: list[ID] = field(default_factory=list)
    input_tokens: list[Output] = field(default_factory=list)
    outputs: list[Output] = field(default_factory=list)
    metadata: dict[str, bytes] = field(default_factory=dict)

    def validate(self) -> None:
        if not self.inputs:
            raise ActionError("invalid number of token inputs in transfer action")
        if len(self.inputs) != len(self.input_tokens):
            raise ActionError("invalid transfer action: inputs and input "
                              "tokens do not match")
        if not self.outputs:
            raise ActionError("invalid number of token outputs in transfer action")

    def num_inputs(self) -> int:
        return len(self.inputs)

    def num_outputs(self) -> int:
        return len(self.outputs)

    def get_inputs(self) -> list[ID]:
        return list(self.inputs)

    def get_outputs(self) -> list[Output]:
        return list(self.outputs)

    def get_serialized_outputs(self) -> list[bytes]:
        return [o.serialize() for o in self.outputs]

    def get_serialized_inputs(self) -> list[bytes]:
        return [t.serialize() for t in self.input_tokens]

    def is_redeem_at(self, index: int) -> bool:
        return self.outputs[index].is_redeem()

    def get_metadata(self) -> dict[str, bytes]:
        return self.metadata

    def is_graph_hiding(self) -> bool:
        return False

    def serialize(self) -> bytes:
        return json.dumps({
            "inputs": [{"tx_id": i.tx_id, "index": i.index} for i in self.inputs],
            "input_tokens": [t.to_dict() for t in self.input_tokens],
            "outputs": [o.to_dict() for o in self.outputs],
            "metadata": {k: _b64(v) for k, v in self.metadata.items()},
        }).encode()

    @classmethod
    def deserialize(cls, raw: bytes) -> "TransferAction":
        d = json.loads(raw)
        return cls(
            inputs=[ID(i["tx_id"], i.get("index", 0))
                    for i in d.get("inputs", [])],
            input_tokens=[Output.from_dict(t)
                          for t in d.get("input_tokens", [])],
            outputs=[Output.from_dict(o) for o in d.get("outputs", [])],
            metadata={k: _unb64(v) for k, v in (d.get("metadata") or {}).items()},
        )
