"""fabtoken actions: plaintext JSON tokens and issue/transfer actions.

Behavioral mirror of reference token/core/fabtoken/v1/core/actions.go:40-300:
an Output is a cleartext Token (owner/type/hex-quantity) wrapped with the
fabtoken format tag; IssueAction carries issuer + outputs; TransferAction
carries input IDs + the claimed input tokens + outputs. All JSON.
"""

from __future__ import annotations

import base64
import json
from dataclasses import dataclass, field

from ...crypto import serialization as ser
from ...driver.identity import Identity
from ...token.model import ID

# services/tokens/core/fabtoken/token.go:18-20: format tag of fabtoken tokens.
FABTOKEN_FORMAT = 1


class ActionError(ValueError):
    pass


def _b64(b: bytes) -> str:
    return base64.b64encode(bytes(b)).decode("ascii")


def _unb64(s: str | None) -> bytes:
    return base64.b64decode(s) if s else b""


def _go_json(obj) -> bytes:
    """Go json.Marshal byte conventions: no spaces, keys in Go struct
    declaration order, and HTML escaping of <, >, & to \\u003c/\\u003e/
    \\u0026 (Go escapes them by default; token types are free user strings
    so this is reachable)."""
    raw = json.dumps(obj, separators=(",", ":"))
    raw = raw.replace("&", "\\u0026").replace("<", "\\u003c") \
             .replace(">", "\\u003e")
    return raw.encode()


def wrap_token_with_type(raw: bytes) -> bytes:
    """services/tokens/typed.go:37 WrapWithType: Go asn1.Marshal of
    TypedToken{INTEGER Type, OCTET STRING Token}."""
    return ser.der_sequence(ser.der_integer(FABTOKEN_FORMAT),
                            ser.der_octet_string(raw))


def unmarshal_typed_token(raw: bytes) -> bytes:
    """typed.go:28 + tokens/core/fabtoken/token.go type check."""
    try:
        seq = ser.DerReader(raw).read_sequence()
        typ = seq.read_integer()
        body = seq.read_octet_string()
    except Exception as e:
        raise ActionError(f"failed to unmarshal to TypedToken: {e}") from e
    if typ != FABTOKEN_FORMAT:
        raise ActionError(f"invalid token type [{typ}]")
    return body


@dataclass
class Output:
    """Cleartext token output (actions.go:40-68)."""

    owner: bytes
    type: str
    quantity: str  # "0x..." base-16

    def is_redeem(self) -> bool:
        return len(self.owner) == 0

    def get_owner(self) -> bytes:
        return self.owner

    def serialize(self) -> bytes:
        """Standalone (ledger) form: ASN.1 TypedToken{1, json} exactly as
        Go json.Marshal of token.Token (tags owner/type/quantity,omitempty)
        wrapped by tokens/typed.go WrapWithType."""
        return wrap_token_with_type(_go_json(self.to_dict()))

    @classmethod
    def deserialize(cls, raw: bytes) -> "Output":
        body = json.loads(unmarshal_typed_token(raw))
        return cls.from_dict(body)

    def to_dict(self) -> dict:
        """Go json.Marshal field set: omitempty on every field."""
        d = {}
        if self.owner:
            d["owner"] = _b64(self.owner)
        if self.type:
            d["type"] = self.type
        if self.quantity:
            d["quantity"] = self.quantity
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Output":
        return cls(owner=_unb64(d.get("owner")), type=d.get("type", ""),
                   quantity=d.get("quantity", ""))


@dataclass
class IssueAction:
    """actions.go:72-175."""

    issuer: Identity
    outputs: list[Output] = field(default_factory=list)
    metadata: dict[str, bytes] = field(default_factory=dict)

    def validate(self) -> None:
        if len(self.issuer) == 0:
            raise ActionError("issuer is not set")
        if not self.outputs:
            raise ActionError("no outputs in issue action")
        if any(o is None for o in self.outputs):
            raise ActionError("nil output in issue action")

    def num_outputs(self) -> int:
        return len(self.outputs)

    def get_outputs(self) -> list[Output]:
        return list(self.outputs)

    def get_serialized_outputs(self) -> list[bytes]:
        return [o.serialize() for o in self.outputs]

    def get_inputs(self) -> list[ID]:
        return []

    def get_metadata(self) -> dict[str, bytes]:
        return self.metadata

    def is_anonymous(self) -> bool:
        return False

    def serialize(self) -> bytes:
        """Go json.Marshal of the IssueAction struct (actions.go:97-99):
        field-name keys, nil map -> null."""
        return _go_json({
            "Issuer": _b64(self.issuer) if len(self.issuer) else None,
            "Outputs": [o.to_dict() for o in self.outputs] or None,
            "Metadata": {k: _b64(v) for k, v in self.metadata.items()}
            or None,
        })

    @classmethod
    def deserialize(cls, raw: bytes) -> "IssueAction":
        d = json.loads(raw)
        return cls(
            issuer=Identity(_unb64(d.get("Issuer"))),
            outputs=[Output.from_dict(o) for o in d.get("Outputs") or []],
            metadata={k: _unb64(v)
                      for k, v in (d.get("Metadata") or {}).items()},
        )


@dataclass
class TransferAction:
    """actions.go:177-300."""

    inputs: list[ID] = field(default_factory=list)
    input_tokens: list[Output] = field(default_factory=list)
    outputs: list[Output] = field(default_factory=list)
    metadata: dict[str, bytes] = field(default_factory=dict)

    def validate(self) -> None:
        if not self.inputs:
            raise ActionError("invalid number of token inputs in transfer action")
        if len(self.inputs) != len(self.input_tokens):
            raise ActionError("invalid transfer action: inputs and input "
                              "tokens do not match")
        if not self.outputs:
            raise ActionError("invalid number of token outputs in transfer action")

    def num_inputs(self) -> int:
        return len(self.inputs)

    def num_outputs(self) -> int:
        return len(self.outputs)

    def get_inputs(self) -> list[ID]:
        return list(self.inputs)

    def get_outputs(self) -> list[Output]:
        return list(self.outputs)

    def get_serialized_outputs(self) -> list[bytes]:
        return [o.serialize() for o in self.outputs]

    def get_serialized_inputs(self) -> list[bytes]:
        return [t.serialize() for t in self.input_tokens]

    def is_redeem_at(self, index: int) -> bool:
        return self.outputs[index].is_redeem()

    def get_metadata(self) -> dict[str, bytes]:
        return self.metadata

    def is_graph_hiding(self) -> bool:
        return False

    def serialize(self) -> bytes:
        """Go json.Marshal of the TransferAction struct (actions.go:193):
        token.ID json tags are tx_id/index with omitempty."""
        def _id(i: ID) -> dict:
            d = {}
            if i.tx_id:
                d["tx_id"] = i.tx_id
            if i.index:
                d["index"] = i.index
            return d

        return _go_json({
            "Inputs": [_id(i) for i in self.inputs] or None,
            "InputTokens": [t.to_dict() for t in self.input_tokens] or None,
            "Outputs": [o.to_dict() for o in self.outputs] or None,
            "Metadata": {k: _b64(v) for k, v in self.metadata.items()}
            or None,
        })

    @classmethod
    def deserialize(cls, raw: bytes) -> "TransferAction":
        d = json.loads(raw)
        return cls(
            inputs=[ID(i.get("tx_id", ""), i.get("index", 0))
                    for i in d.get("Inputs") or []],
            input_tokens=[Output.from_dict(t)
                          for t in d.get("InputTokens") or []],
            outputs=[Output.from_dict(o) for o in d.get("Outputs") or []],
            metadata={k: _unb64(v)
                      for k, v in (d.get("Metadata") or {}).items()},
        )
