"""fabtoken driver: plaintext UTXO tokens (reference token/core/fabtoken/v1).

Quantities travel in the clear; validation checks owner signatures and
plaintext balance. The simplest driver — and the reference model for the
action/validator plumbing the zkatdlog driver extends with ZK proofs.
"""

from .setup import PublicParams, setup  # noqa: F401
from .actions import Output, IssueAction, TransferAction  # noqa: F401
from .validator import new_validator  # noqa: F401
