"""Driver registry + shared driver plumbing + concrete drivers.

Mirrors reference token/core (SURVEY.md §2.1): a named-factory registry with
lazy TMS instantiation, the generic validation pipeline, and the fabtoken
(plaintext UTXO) and zkatdlog (ZK privacy) drivers.
"""
