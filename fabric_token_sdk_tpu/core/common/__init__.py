"""Driver-agnostic plumbing shared by fabtoken and zkatdlog."""
