"""Generic driver-agnostic validation pipeline.

Behavioral mirror of reference token/core/common/validator.go:78-253 and
backend.go: unmarshal request -> auditor signature -> per-action validator
chains -> metadata-coverage invariant. Drivers plug in action deserializers
and chains of validator steps; the zkatdlog chain routes its ZK step to the
TPU batch verifier (SURVEY.md §3.2 "where the TPU backend plugs in").
"""

from __future__ import annotations

import base64
import json
from dataclasses import dataclass, field
from typing import Callable

from ...driver import TokenRequest
from ...driver.api import GetStateFnc, ValidationAttributes
from ...driver.identity import Identity

TOKEN_REQUEST_TO_SIGN = "trs"
TOKEN_REQUEST_SIGNATURES = "sigs"


class ValidationError(Exception):
    pass


class Backend:
    """Ledger view + signature provider over one request (backend.go:31).

    Tracks a cursor over the provided signatures: each HasBeenSignedBy call
    consumes the next signature and verifies it against the message.
    """

    def __init__(self, get_state: GetStateFnc, message: bytes,
                 signatures: list[bytes]):
        self._get_state = get_state
        self.message = message
        self.signatures = signatures
        self.cursor = 0

    # driver.Ledger
    def get_state(self, token_id) -> bytes | None:
        return self._get_state(token_id)

    # driver.SignatureProvider
    def has_been_signed_by(self, identity: Identity, verifier) -> bytes:
        if self.cursor >= len(self.signatures):
            raise ValidationError("invalid number of signatures")
        sigma = self.signatures[self.cursor]
        verifier.verify(self.message, sigma)
        self.cursor += 1
        return sigma

    def sigs(self) -> list[bytes]:
        return self.signatures


@dataclass
class Context:
    """Per-action validation context (validator.go:25-41)."""

    pp: object
    deserializer: object
    signature_provider: Backend
    ledger: object
    attributes: ValidationAttributes
    issue_action: object = None
    transfer_action: object = None
    input_tokens: list = field(default_factory=list)
    signatures: list = field(default_factory=list)
    metadata_counter: dict = field(default_factory=dict)
    # extension point for drivers that batch across actions (TPU verifier)
    bundle: object = None

    def count_metadata_key(self, key: str) -> None:
        self.metadata_counter[key] = self.metadata_counter.get(key, 0) + 1


ValidateStep = Callable[[Context], None]


class Validator:
    """Pluggable validation pipeline (validator.go:52-110)."""

    def __init__(self, pp, deserializer, action_deserializer,
                 transfer_validators: list[ValidateStep],
                 issue_validators: list[ValidateStep],
                 bundle_factory: Callable[[], object] | None = None,
                 bundle_flush: Callable[[object], None] | None = None):
        self.pp = pp
        self.deserializer = deserializer
        self.action_deserializer = action_deserializer
        self.transfer_validators = transfer_validators
        self.issue_validators = issue_validators
        # Batching hooks: drivers may collect device-verifiable work across
        # all actions of a request and flush it in one TPU batch.
        self.bundle_factory = bundle_factory
        self.bundle_flush = bundle_flush

    def unmarshal_actions(self, raw: bytes) -> list:
        tr = TokenRequest.from_bytes(raw)
        issues, transfers = self.action_deserializer.deserialize_actions(tr)
        return list(issues) + list(transfers)

    def verify_token_request_from_raw(self, get_state: GetStateFnc,
                                      anchor: str, raw: bytes
                                      ) -> tuple[list, ValidationAttributes]:
        """validator.go:78-110."""
        if not raw:
            raise ValidationError("empty token request")
        try:
            tr = TokenRequest.from_bytes(raw)
        except Exception as e:
            raise ValidationError(
                f"failed to unmarshal token request: {e}") from e
        signed = tr.message_to_sign(anchor.encode())
        if len(self.pp.auditors()) != 0:
            signatures = list(tr.auditor_signatures) + list(tr.signatures)
        else:
            signatures = list(tr.signatures)
        # Signatures attribute mirrors Go json.Marshal of [][]byte, which
        # emits base64 strings (validator.go ValidationAttributes).
        attributes: ValidationAttributes = {
            TOKEN_REQUEST_TO_SIGN: signed,
            TOKEN_REQUEST_SIGNATURES: json.dumps(
                [base64.b64encode(s).decode() for s in signatures]).encode(),
        }
        backend = Backend(get_state, signed, signatures)
        return self.verify_token_request(backend, backend, anchor, tr,
                                         attributes)

    def verify_token_request(self, ledger, signature_provider, anchor: str,
                             tr: TokenRequest,
                             attributes: ValidationAttributes
                             ) -> tuple[list, ValidationAttributes]:
        self._verify_auditor_signature(signature_provider, anchor)
        try:
            issues, transfers = self.action_deserializer.deserialize_actions(tr)
        except Exception as e:
            raise ValidationError(
                f"failed to unmarshal actions [{anchor}]: {e}") from e
        bundle = self.bundle_factory() if self.bundle_factory else None
        self._verify_actions("issue", issues, self.issue_validators, ledger,
                             signature_provider, attributes, anchor, bundle)
        self._verify_actions("transfer", transfers, self.transfer_validators,
                             ledger, signature_provider, attributes, anchor,
                             bundle)
        if bundle is not None and self.bundle_flush is not None:
            self.bundle_flush(bundle)
        return list(issues) + list(transfers), attributes

    def _verify_auditor_signature(self, signature_provider, anchor: str) -> None:
        """validator.go:160-173: first auditor's signature must be present."""
        auditors = self.pp.auditors()
        if len(auditors) == 0:
            return
        auditor = auditors[0]
        try:
            verifier = self.deserializer.get_auditor_verifier(auditor)
        except Exception as e:
            raise ValidationError(
                "failed to deserialize auditor's public key") from e
        try:
            signature_provider.has_been_signed_by(auditor, verifier)
        except Exception as e:
            raise ValidationError(
                f"failed to verifier auditor's signature [{anchor}]: {e}"
            ) from e

    def _verify_actions(self, kind: str, actions: list,
                        validators: list[ValidateStep], ledger,
                        signature_provider, attributes, anchor: str,
                        bundle) -> None:
        for i, action in enumerate(actions):
            ctx = Context(
                pp=self.pp,
                deserializer=self.deserializer,
                signature_provider=signature_provider,
                ledger=ledger,
                attributes=attributes,
                bundle=bundle,
            )
            if kind == "issue":
                ctx.issue_action = action
            else:
                ctx.transfer_action = action
            try:
                for step in validators:
                    step(ctx)
            except Exception as e:
                raise ValidationError(
                    f"failed to verify {kind} action at [{i}] [{anchor}]: {e}"
                ) from e
            self._check_metadata_coverage(action, ctx, kind, i)

    @staticmethod
    def _check_metadata_coverage(action, ctx: Context, kind: str, i: int) -> None:
        """Every metadata key must be validated exactly once
        (validator.go:203-216,244-253)."""
        counter = 0
        for k, c in ctx.metadata_counter.items():
            if c > 1:
                raise ValidationError(
                    f"metadata key [{k}] appeared more than one time")
            counter += c
        metadata = action.get_metadata() or {}
        if len(metadata) != counter:
            raise ValidationError(
                f"more metadata than those validated [{len(metadata)}]!="
                f"[{counter}] in {kind} action [{i}]")
