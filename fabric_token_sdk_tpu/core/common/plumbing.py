"""Driver-composable common plumbing: token loaders + ownership mux.

Standalone equivalents of the reference's generic driver helpers that
round 3 kept inline in services/node.py (VERDICT r3 missing #4):

  - VaultTokenLoader: load spendable (token, metadata) rows from the
    vault for transfer assembly, with the reference's missing-token
    error semantics (reference token/core/common/loaders.go:47-231).
  - AuthorizationMultiplexer + WalletOwnership/EscrowOwnership: resolve
    which local wallets own an on-ledger owner identity; drivers compose
    the chain instead of sharing logic through the node object
    (reference token/core/common/authrorization.go:18-141).

Both fabtoken and zkatdlog nodes now share ownership resolution through
this layer (services/node.py builds the mux), and the mux satisfies the
driver SPI Authorization contract (driver/api.py).
"""

from __future__ import annotations

from ...token.model import ID


class TokenLoadError(Exception):
    pass


class VaultTokenLoader:
    """loaders.go:209-231 VaultTokenLoader over the local tokendb.

    Callable with one ID (the Request builder's `wallet` hook shape) or
    with a list via load_tokens; a spent/unknown id raises — the
    reference fails transfer assembly the same way ("token not found").
    """

    def __init__(self, tokendb):
        self._tokendb = tokendb

    def __call__(self, token_id: ID):
        row = self._tokendb.get_ledger_token(token_id)
        if row is None:
            raise TokenLoadError(
                f"token {token_id.tx_id}:{token_id.index} does not exist "
                "in the vault (spent or never committed)")
        return row

    def load_tokens(self, token_ids: list[ID]) -> list:
        """loaders.go:146-180 LoadTokens: all-or-error."""
        return [self(tid) for tid in token_ids]


class WalletOwnership:
    """authrorization.go:31-66 WalletBasedAuthorization: the TMS owner
    wallet claims identities it holds keys for, under the node wallet id."""

    def __init__(self, wallet_id: str, wallet, auditor: bool = False):
        self.wallet_id = wallet_id
        self._wallet = wallet
        self._auditor = auditor

    def is_mine(self, owner_raw: bytes) -> list[str]:
        return [self.wallet_id] if self._wallet.owns(owner_raw) else []

    def am_i_an_auditor(self) -> bool:
        return self._auditor


class EscrowOwnership:
    """ttx/multisig escrow authorization (identity/multisig/
    deserializer.go:25-122): co-owned tokens land in a separate
    '<wallet>.ms' wallet so the ordinary selector never spends them.

    `unwrap` is injected (identity.multisig.unwrap shape: raw ->
    (is_multisig, component_ids)) so this core layer never imports the
    services tier — the composition direction stays services -> core."""

    def __init__(self, wallet_id: str, wallet, unwrap):
        self.wallet_id = f"{wallet_id}.ms"
        self._wallet = wallet
        self._unwrap = unwrap

    def is_mine(self, owner_raw: bytes) -> list[str]:
        is_ms, ids = self._unwrap(owner_raw)
        if is_ms and any(self._wallet.owns(i) for i in ids):
            return [self.wallet_id]
        return []

    def am_i_an_auditor(self) -> bool:
        return False


class AuthorizationMultiplexer:
    """authrorization.go:69-141: ask each authorization in order; the
    first one that recognizes the owner wins.

    `unmarshal_typed` (identity.typed.unmarshal_typed_identity shape) is
    injected for owner_type so the core layer stays below services."""

    def __init__(self, *auths, unmarshal_typed=None):
        self._auths = list(auths)
        self._unmarshal_typed = unmarshal_typed

    def is_mine(self, owner_raw: bytes) -> tuple[list[str], bool]:
        for auth in self._auths:
            ids = auth.is_mine(owner_raw)
            if ids:
                return ids, True
        return [], False

    def am_i_an_auditor(self) -> bool:
        return any(a.am_i_an_auditor() for a in self._auths)

    def owner_type(self, owner_raw: bytes) -> tuple[str, bytes]:
        """authrorization.go:133-141 OwnerType: the typed-identity tag
        ('htlc', 'ms', ...; 'plain' for raw keys)."""
        if self._unmarshal_typed is None:
            return "plain", owner_raw
        try:
            ti = self._unmarshal_typed(owner_raw)
            return ti.type, ti.identity
        except Exception:
            return "plain", owner_raw
