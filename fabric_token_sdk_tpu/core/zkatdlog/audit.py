"""zkatdlog auditor: commitment re-open + identity inspection + endorse.

Behavioral mirror of reference token/core/zkatdlog/nogh/v1/crypto/audit/
auditor.go:
  - ``Check`` (auditor.go:135-177) walks issues then transfers, re-opening
    every output commitment from the request metadata and matching every
    owner identity against its audit info.
  - ``InspectOutput`` (auditor.go:225-246) recomputes
    commit(H(type), value, bf) over the Pedersen generators and compares
    with the token data — batched here as ONE device MSM pass over every
    output in the request (models/audit.py), the second TPU consumer named
    by SURVEY.md §3.4. First-failure error messages keep the reference's
    sequential ordering.
  - ``InspectIdentity`` (auditor.go:265-282) matches owner audit info via a
    pluggable InfoMatcher (x509 equality today; Idemix NymEID matching plugs
    in the same hook).
  - ``Endorse`` (auditor.go:117-132) signs the request's message-to-sign.
"""

from __future__ import annotations

from dataclasses import dataclass

from ...crypto import token_commit
from ...crypto import serialization as ser
from ...driver.request import TokenRequest
from .actions import IssueAction, Token, TransferAction
from .metadata import RequestMetadata, TokenMetadata


class AuditError(Exception):
    pass


class EqualityInfoMatcher:
    """Plain-identity matcher: audit info must equal the identity bytes.

    The x509 analogue of the reference's enrollment-ID matcher; Idemix
    replaces this with NymEID matching (identity/idemix/km.go:46-365).
    """

    def match_identity(self, identity: bytes, audit_info: bytes) -> None:
        if identity != audit_info:
            raise AuditError("identity does not match audit info")


@dataclass
class _InspectableToken:
    data: object            # G1 commitment
    token_type: str
    value: int
    blinding_factor: int
    owner: bytes
    audit_info: bytes


@dataclass
class _InspectableIdentity:
    identity: bytes
    identity_from_meta: bytes
    audit_info: bytes


class Auditor:
    """Per-pp zkatdlog auditor with an optional device batch backend."""

    def __init__(self, pp, signer=None, info_matcher=None,
                 device: bool = True):
        self.pp = pp
        self.signer = signer
        self.info_matcher = info_matcher or EqualityInfoMatcher()
        self._reopen = None
        if device:
            from ...models.audit import BatchAuditReopen

            self._reopen = BatchAuditReopen(pp)

    # ------------------------------------------------------------- endorse
    def endorse(self, request: TokenRequest, tx_id: str) -> bytes:
        """Sign a valid token request (auditor.go:117-132)."""
        if request is None:
            raise AuditError(
                f"audit of tx [{tx_id}] failed: token request is nil")
        if self.signer is None:
            raise AuditError(f"audit of tx [{tx_id}] failed: signer is nil")
        return self.signer.sign(request.message_to_sign(tx_id.encode()))

    # --------------------------------------------------------------- check
    def check(self, request: TokenRequest, metadata: RequestMetadata,
              input_tokens: list[list[Token]], tx_id: str) -> None:
        """auditor.go:135-177: issues first, then transfers; raises
        AuditError with the reference's first-failure ordering."""
        issue_outputs, issue_identities = self._audit_info_for_issues(
            request, metadata, tx_id)
        transfer_inputs, transfer_outputs = self._audit_info_for_transfers(
            request, metadata, input_tokens, tx_id)

        # one batched device pass over every output commitment in the request
        all_outputs = [t for group in issue_outputs + transfer_outputs
                       for t in group]
        accepts = self._reopen_batch(all_outputs)

        cursor = 0
        for k, group in enumerate(issue_outputs):
            for i, tok in enumerate(group):
                if not accepts[cursor]:
                    raise AuditError(
                        f"audit of {k} th issue in tx [{tx_id}] failed: "
                        f"output at index [{i}] does not match the provided "
                        f"opening")
                self._inspect_token_identity(tok, i, f"issue {k}")
                cursor += 1
        for k, ident in enumerate(issue_identities):
            self._inspect_identity(ident, k, f"identity for issue [{tx_id}]")
        for k, group in enumerate(transfer_outputs):
            for i, tok in enumerate(group):
                if not accepts[cursor]:
                    raise AuditError(
                        f"audit of {k} th transfer in tx [{tx_id}] failed: "
                        f"output at index [{i}] does not match the provided "
                        f"opening")
                self._inspect_token_identity(tok, i, f"transfer {k}")
                cursor += 1
        for k, group in enumerate(transfer_inputs):
            for i, ident in enumerate(group):
                self._inspect_identity(
                    ident, i, f"input of transfer {k} in tx [{tx_id}]")

    # ------------------------------------------------------------- helpers
    def _reopen_batch(self, tokens: list[_InspectableToken]) -> list[bool]:
        openings = [(t.data, t.token_type, t.value, t.blinding_factor)
                    for t in tokens]
        if self._reopen is not None:
            return list(self._reopen.verify(openings))
        out = []
        for data, token_type, value, bf in openings:
            try:
                token_commit.audit_inspect_output(
                    data, token_type, value, bf, self.pp.pedersen_generators)
                out.append(True)
            except token_commit.TokenError:
                out.append(False)
        return out

    def _inspect_token_identity(self, tok: _InspectableToken, index: int,
                                what: str) -> None:
        if len(tok.owner) == 0:
            return  # redeemed output: no identity to inspect
        if len(tok.audit_info) == 0:
            raise AuditError(
                f"failed to inspect identity at index [{index}] of {what}: "
                f"audit info is nil")
        try:
            self.info_matcher.match_identity(tok.owner, tok.audit_info)
        except Exception as e:
            raise AuditError(
                f"owner at index [{index}] of {what} does not match the "
                f"provided opening: {e}") from e

    def _inspect_identity(self, ident: _InspectableIdentity, index: int,
                          what: str) -> None:
        """auditor.go:265-282."""
        if len(ident.identity) == 0:
            raise AuditError(
                f"identity at index [{index}] is nil, cannot inspect it")
        if len(ident.audit_info) == 0:
            raise AuditError(
                f"failed to inspect identity at index [{index}]: audit info "
                f"is nil")
        if ident.identity_from_meta and \
                ident.identity_from_meta != ident.identity:
            raise AuditError(
                f"failed to inspect identity at index [{index}]: identity "
                f"does not match the identity from metadata")
        try:
            self.info_matcher.match_identity(ident.identity,
                                             ident.audit_info)
        except Exception as e:
            raise AuditError(
                f"failed checking {what}: owner at index [{index}] does not "
                f"match the provided opening: {e}") from e

    def _audit_info_for_issues(self, request, metadata, tx_id):
        """auditor.go:286-341 GetAuditInfoForIssues."""
        if len(request.issues) != len(metadata.issues):
            raise AuditError(
                "number of issues does not match number of provided metadata")
        outputs, identities = [], []
        for k, md in enumerate(metadata.issues):
            try:
                action = IssueAction.deserialize(request.issues[k])
            except Exception as e:
                raise AuditError(
                    f"failed to deserialize issue action at index [{k}]"
                ) from e
            if len(action.outputs) != len(md.outputs):
                raise AuditError(
                    "number of output does not match number of provided "
                    "metadata")
            group = []
            for i, omd in enumerate(md.outputs):
                tok = action.outputs[i]
                if tok is None or tok.data is None:
                    raise AuditError(f"output token at index [{i}] is nil")
                if tok.is_redeem():
                    raise AuditError("issue cannot redeem tokens")
                if not omd.receivers:
                    raise AuditError("issue must have at least one receiver")
                opening = self._opening(omd.output_metadata, i)
                group.append(_InspectableToken(
                    data=tok.data, token_type=opening.token_type,
                    value=opening.value,
                    blinding_factor=opening.blinding_factor,
                    owner=tok.owner,
                    audit_info=omd.receivers[0].audit_info))
            outputs.append(group)
            identities.append(_InspectableIdentity(
                identity=bytes(action.issuer),
                identity_from_meta=md.issuer.identity,
                audit_info=md.issuer.audit_info))
        return outputs, identities

    def _audit_info_for_transfers(self, request, metadata, input_tokens,
                                  tx_id):
        """auditor.go:344-430 GetAuditInfoForTransfers."""
        if len(request.transfers) != len(metadata.transfers):
            raise AuditError(
                "number of transfers does not match the number of provided "
                "metadata")
        if len(input_tokens) != len(metadata.transfers):
            raise AuditError(
                "number of inputs does not match the number of provided "
                "metadata")
        inputs, outputs = [], []
        for k, md in enumerate(metadata.transfers):
            try:
                action = TransferAction.deserialize(request.transfers[k])
            except Exception as e:
                raise AuditError(
                    f"failed to deserialize transfer action at index [{k}]"
                ) from e
            if len(md.inputs) != len(input_tokens[k]):
                raise AuditError(
                    f"number of inputs does not match the number of senders "
                    f"[{len(md.inputs)}]!=[{len(input_tokens[k])}]")
            in_group = []
            for i, imd in enumerate(md.inputs):
                tok = input_tokens[k][i]
                if tok is None:
                    raise AuditError(f"invalid input at index [{i}]")
                if tok.is_redeem():
                    continue  # no identity to inspect
                if not imd.senders:
                    raise AuditError(
                        f"transfer input at index [{i}] has no sender")
                in_group.append(_InspectableIdentity(
                    identity=tok.owner, identity_from_meta=b"",
                    audit_info=imd.senders[0].audit_info))
            if len(md.outputs) != len(action.outputs):
                raise AuditError(
                    "number of output does not match number of provided "
                    "metadata")
            out_group = []
            for i, omd in enumerate(md.outputs):
                tok = action.outputs[i]
                if tok is None or tok.data is None:
                    raise AuditError(f"invalid output at index [{i}]")
                opening = self._opening(omd.output_metadata, i)
                audit_info = b""
                if not tok.is_redeem():
                    if not omd.receivers:
                        raise AuditError(
                            f"transfer output at index [{i}] has no receiver")
                    audit_info = omd.receivers[0].audit_info
                out_group.append(_InspectableToken(
                    data=tok.data, token_type=opening.token_type,
                    value=opening.value,
                    blinding_factor=opening.blinding_factor,
                    owner=tok.owner, audit_info=audit_info))
            inputs.append(in_group)
            outputs.append(out_group)
        return inputs, outputs

    @staticmethod
    def _opening(raw: bytes, index: int) -> TokenMetadata:
        try:
            return TokenMetadata.deserialize(raw)
        except Exception as e:
            raise AuditError(
                f"failed to deserialize metadata at index [{index}]") from e
