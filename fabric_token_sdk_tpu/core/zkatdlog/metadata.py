"""zkatdlog request metadata: commitment openings + auditable identities.

Byte-exact wire mirror of the reference protos:
  - token opening ``TokenMetadata`` (noghactions.proto + crypto/token/
    token.go:132-180): {type, Zr value, Zr blinding_factor, Identity
    issuer}, wrapped standalone as ASN.1 TypedMetadata{Type=2, proto}
    (tokens/typed.go:46-72).
  - request metadata (token/driver/protos/request.proto +
    driver/request.go:105-330): AuditableIdentity / OutputMetadata /
    TransferInputMetadata / IssueMetadata / TransferMetadata /
    ActionMetadata(oneof) / TokenRequestMetadata.

The request metadata never reaches the ledger; it flows sender -> auditor
(audit check re-opens every commitment) and sender -> receiver (wallet
ingestion of fresh openings). Conformance is pinned against
protoc-compiled reference protos in tests/test_wire_conformance.py.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ...crypto import serialization as ser
from ...token.model import ID
from ...utils import protowire as pw
from .actions import (_token_id_from_msg, _token_id_msg,
                      unmarshal_typed_token, wrap_token_with_type)

#: driver/request.go TokenRequestMetadata version.
METADATA_VERSION = 1


class MetadataError(ValueError):
    pass


def _zr_msg(v: int) -> bytes:
    """noghmath.proto Zr{1: raw} (32-byte big-endian scalar)."""
    return pw.bytes_field(1, ser.zr_to_bytes(v))


def _zr_from_msg(raw: bytes) -> int:
    fields = pw.parse_fields(raw)
    if 1 not in fields:
        raise MetadataError("invalid Zr proto: missing raw")
    return ser.zr_from_bytes(bytes(fields[1][0]))


def _identity_msg(raw: bytes) -> bytes:
    """Identity{1: raw}."""
    return pw.bytes_field(1, raw)


def _identity_from_msg(raw: bytes) -> bytes:
    return bytes(pw.parse_fields(raw).get(1, [b""])[0])


@dataclass
class TokenMetadata:
    """Opening of one commitment token (crypto/token/token.go:132-180)."""

    token_type: str
    value: int
    blinding_factor: int
    issuer: bytes = b""

    def to_proto(self) -> bytes:
        """noghactions.proto TokenMetadata{1: type, 2: Zr, 3: Zr, 4: Id}."""
        out = (pw.string_field(1, self.token_type)
               + pw.message_field(2, _zr_msg(self.value), present=True)
               + pw.message_field(3, _zr_msg(self.blinding_factor),
                                  present=True))
        # token.go:170-177 always emits the Identity wrapper
        out += pw.message_field(4, _identity_msg(self.issuer), present=True)
        return out

    @classmethod
    def from_proto(cls, raw: bytes) -> "TokenMetadata":
        fields = pw.parse_fields(raw)
        if 2 not in fields or 3 not in fields:
            raise MetadataError("invalid token metadata: missing opening")
        issuer = b""
        if 4 in fields:
            issuer = _identity_from_msg(bytes(fields[4][0]))
        return cls(
            token_type=bytes(fields.get(1, [b""])[0]).decode(),
            value=_zr_from_msg(bytes(fields[2][0])),
            blinding_factor=_zr_from_msg(bytes(fields[3][0])),
            issuer=issuer,
        )

    def serialize(self) -> bytes:
        """Standalone form (token.go:161-180): ASN.1 TypedMetadata{2, ...}
        — the same envelope as tokens (tokens/typed.go)."""
        return wrap_token_with_type(self.to_proto())

    @classmethod
    def deserialize(cls, raw: bytes) -> "TokenMetadata":
        """token.go:136-158."""
        try:
            body = unmarshal_typed_token(raw)
        except Exception as e:
            raise MetadataError(
                f"failed deserializing metadata: {e}") from e
        return cls.from_proto(body)


@dataclass
class AuditableIdentity:
    """request.proto AuditableIdentity{1: Identity, 2: audit_info}."""

    identity: bytes = b""
    audit_info: bytes = b""

    def serialize(self) -> bytes:
        return (pw.message_field(1, _identity_msg(self.identity),
                                 present=True)
                + pw.bytes_field(2, self.audit_info))

    @classmethod
    def deserialize(cls, raw: bytes) -> "AuditableIdentity":
        fields = pw.parse_fields(raw)
        identity = b""
        if 1 in fields:
            identity = _identity_from_msg(bytes(fields[1][0]))
        return cls(identity=identity,
                   audit_info=bytes(fields.get(2, [b""])[0]))


@dataclass
class IssueOutputMetadata:
    """request.proto OutputMetadata{1: metadata, 2: audit_info, 3: recv}."""

    output_metadata: bytes = b""            # serialized TokenMetadata
    output_audit_info: bytes = b""
    receivers: list[AuditableIdentity] = field(default_factory=list)

    def serialize(self) -> bytes:
        out = (pw.bytes_field(1, self.output_metadata)
               + pw.bytes_field(2, self.output_audit_info))
        for r in self.receivers:
            out += pw.message_field(3, r.serialize())
        return out

    @classmethod
    def deserialize(cls, raw: bytes) -> "IssueOutputMetadata":
        fields = pw.parse_fields(raw)
        return cls(
            output_metadata=bytes(fields.get(1, [b""])[0]),
            output_audit_info=bytes(fields.get(2, [b""])[0]),
            receivers=[AuditableIdentity.deserialize(bytes(b))
                       for b in fields.get(3, [])],
        )


#: Transfer outputs share the same OutputMetadata message.
TransferOutputMetadata = IssueOutputMetadata


@dataclass
class IssueActionMetadata:
    """request.proto IssueMetadata{1: issuer, 2: inputs, 3: outputs,
    4: extra_signers}."""

    issuer: AuditableIdentity = field(default_factory=AuditableIdentity)
    outputs: list[IssueOutputMetadata] = field(default_factory=list)
    extra_signers: list[bytes] = field(default_factory=list)

    def serialize(self) -> bytes:
        out = pw.message_field(1, self.issuer.serialize(), present=True)
        for o in self.outputs:
            out += pw.message_field(3, o.serialize())
        for s in self.extra_signers:
            out += pw.message_field(4, _identity_msg(s), present=True)
        return out

    @classmethod
    def deserialize(cls, raw: bytes) -> "IssueActionMetadata":
        fields = pw.parse_fields(raw)
        issuer = AuditableIdentity()
        if 1 in fields:
            issuer = AuditableIdentity.deserialize(bytes(fields[1][0]))
        return cls(
            issuer=issuer,
            outputs=[IssueOutputMetadata.deserialize(bytes(b))
                     for b in fields.get(3, [])],
            extra_signers=[_identity_from_msg(bytes(b))
                           for b in fields.get(4, [])],
        )


@dataclass
class TransferInputMetadata:
    """request.proto TransferInputMetadata{1: TokenID, 2: senders}."""

    token_id: ID | None = None
    senders: list[AuditableIdentity] = field(default_factory=list)

    def serialize(self) -> bytes:
        out = b""
        if self.token_id is not None:
            out += pw.message_field(1, _token_id_msg(self.token_id))
        for s in self.senders:
            out += pw.message_field(2, s.serialize())
        return out

    @classmethod
    def deserialize(cls, raw: bytes) -> "TransferInputMetadata":
        fields = pw.parse_fields(raw)
        token_id = None
        if 1 in fields:
            token_id = _token_id_from_msg(bytes(fields[1][0]))
        return cls(
            token_id=token_id,
            senders=[AuditableIdentity.deserialize(bytes(b))
                     for b in fields.get(2, [])],
        )


@dataclass
class TransferActionMetadata:
    """request.proto TransferMetadata{1: inputs, 2: outputs,
    8: extra_signers}."""

    inputs: list[TransferInputMetadata] = field(default_factory=list)
    outputs: list[TransferOutputMetadata] = field(default_factory=list)
    extra_signers: list[bytes] = field(default_factory=list)

    def serialize(self) -> bytes:
        out = b""
        for i in self.inputs:
            out += pw.message_field(1, i.serialize())
        for o in self.outputs:
            out += pw.message_field(2, o.serialize())
        for s in self.extra_signers:
            out += pw.message_field(8, _identity_msg(s), present=True)
        return out

    @classmethod
    def deserialize(cls, raw: bytes) -> "TransferActionMetadata":
        fields = pw.parse_fields(raw)
        return cls(
            inputs=[TransferInputMetadata.deserialize(bytes(b))
                    for b in fields.get(1, [])],
            outputs=[TransferOutputMetadata.deserialize(bytes(b))
                     for b in fields.get(2, [])],
            extra_signers=[_identity_from_msg(bytes(b))
                           for b in fields.get(8, [])],
        )


@dataclass
class RequestMetadata:
    """request.proto TokenRequestMetadata{1: version, 2: repeated
    ActionMetadata (oneof issue=1 / transfer=2), 3: application map}.

    Action order on the wire matches the TokenRequest action order:
    issues first, then transfers (driver/request.go marshalling).
    """

    issues: list[IssueActionMetadata] = field(default_factory=list)
    transfers: list[TransferActionMetadata] = field(default_factory=list)
    application: dict[str, bytes] = field(default_factory=dict)

    def serialize(self) -> bytes:
        out = pw.uint64_field(1, METADATA_VERSION)
        for i in self.issues:
            body = pw.message_field(1, i.serialize(), present=True)
            out += pw.message_field(2, body)
        for t in self.transfers:
            body = pw.message_field(2, t.serialize(), present=True)
            out += pw.message_field(2, body)
        for k in sorted(self.application):
            entry = pw.string_field(1, k) + \
                pw.bytes_field(2, self.application[k])
            out += pw.message_field(3, entry)
        return out

    @classmethod
    def deserialize(cls, raw: bytes) -> "RequestMetadata":
        fields = pw.parse_fields(raw)
        issues, transfers = [], []
        for b in fields.get(2, []):
            sub = pw.parse_fields(bytes(b))
            if 1 in sub:
                issues.append(
                    IssueActionMetadata.deserialize(bytes(sub[1][0])))
            elif 2 in sub:
                transfers.append(
                    TransferActionMetadata.deserialize(bytes(sub[2][0])))
            else:
                raise MetadataError("empty action metadata")
        application = {}
        for b in fields.get(3, []):
            sub = pw.parse_fields(bytes(b))
            application[bytes(sub.get(1, [b""])[0]).decode()] = \
                bytes(sub.get(2, [b""])[0])
        return cls(issues=issues, transfers=transfers,
                   application=application)
