"""zkatdlog request metadata: commitment openings + auditable identities.

Behavioral mirror of the reference metadata model:
  - token opening (reference token/core/zkatdlog/nogh/v1/crypto/token/
    token.go:132-180 ``Metadata``): Type, Value, BlindingFactor, Issuer.
  - per-action metadata (reference token/driver/request.go:105-330
    ``IssueMetadata`` / ``TransferMetadata``): auditable identities
    (identity + audit info) for issuer/senders/receivers plus the serialized
    opening per output.

The request metadata never reaches the ledger; it flows sender -> auditor
(audit check re-opens every commitment) and sender -> receiver (wallet
ingestion of fresh openings). Wire format is this framework's protowire
messages; proof-relevant bytes (Zr scalars) keep exact reference encoding
via crypto/serialization.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ...crypto import serialization as ser
from ...token.model import ID
from ...utils import protowire as pw


class MetadataError(ValueError):
    pass


@dataclass
class TokenMetadata:
    """Opening of one commitment token (crypto/token/token.go:132-180)."""

    token_type: str
    value: int
    blinding_factor: int
    issuer: bytes = b""

    def serialize(self) -> bytes:
        return (pw.string_field(1, self.token_type)
                + pw.bytes_field(2, ser.zr_to_bytes(self.value))
                + pw.bytes_field(3, ser.zr_to_bytes(self.blinding_factor))
                + pw.bytes_field(4, self.issuer))

    @classmethod
    def deserialize(cls, raw: bytes) -> "TokenMetadata":
        fields = pw.parse_fields(raw)
        v_raw = bytes(fields.get(2, [b""])[0])
        bf_raw = bytes(fields.get(3, [b""])[0])
        if not v_raw or not bf_raw:
            raise MetadataError("invalid token metadata: missing opening")
        return cls(
            token_type=bytes(fields.get(1, [b""])[0]).decode(),
            value=ser.zr_from_bytes(v_raw),
            blinding_factor=ser.zr_from_bytes(bf_raw),
            issuer=bytes(fields.get(4, [b""])[0]),
        )


@dataclass
class AuditableIdentity:
    """Identity + audit info pair (driver/request.go:105-121)."""

    identity: bytes = b""
    audit_info: bytes = b""

    def serialize(self) -> bytes:
        return (pw.bytes_field(1, self.identity)
                + pw.bytes_field(2, self.audit_info))

    @classmethod
    def deserialize(cls, raw: bytes) -> "AuditableIdentity":
        fields = pw.parse_fields(raw)
        return cls(identity=bytes(fields.get(1, [b""])[0]),
                   audit_info=bytes(fields.get(2, [b""])[0]))


@dataclass
class IssueOutputMetadata:
    """driver/request.go:144-181."""

    output_metadata: bytes = b""            # serialized TokenMetadata
    receivers: list[AuditableIdentity] = field(default_factory=list)

    def serialize(self) -> bytes:
        out = pw.bytes_field(1, self.output_metadata)
        for r in self.receivers:
            out += pw.message_field(2, r.serialize())
        return out

    @classmethod
    def deserialize(cls, raw: bytes) -> "IssueOutputMetadata":
        fields = pw.parse_fields(raw)
        return cls(
            output_metadata=bytes(fields.get(1, [b""])[0]),
            receivers=[AuditableIdentity.deserialize(bytes(b))
                       for b in fields.get(2, [])],
        )


@dataclass
class IssueActionMetadata:
    """driver/request.go:184-246."""

    issuer: AuditableIdentity = field(default_factory=AuditableIdentity)
    outputs: list[IssueOutputMetadata] = field(default_factory=list)

    def serialize(self) -> bytes:
        out = pw.message_field(1, self.issuer.serialize())
        for o in self.outputs:
            out += pw.message_field(2, o.serialize())
        return out

    @classmethod
    def deserialize(cls, raw: bytes) -> "IssueActionMetadata":
        fields = pw.parse_fields(raw)
        issuer = AuditableIdentity()
        if 1 in fields:
            issuer = AuditableIdentity.deserialize(bytes(fields[1][0]))
        return cls(
            issuer=issuer,
            outputs=[IssueOutputMetadata.deserialize(bytes(b))
                     for b in fields.get(2, [])],
        )


@dataclass
class TransferInputMetadata:
    """driver/request.go:249-279."""

    token_id: ID | None = None
    senders: list[AuditableIdentity] = field(default_factory=list)

    def serialize(self) -> bytes:
        out = b""
        if self.token_id is not None:
            id_msg = (pw.string_field(1, self.token_id.tx_id)
                      + pw.uint64_field(2, self.token_id.index))
            out += pw.message_field(1, id_msg)
        for s in self.senders:
            out += pw.message_field(2, s.serialize())
        return out

    @classmethod
    def deserialize(cls, raw: bytes) -> "TransferInputMetadata":
        fields = pw.parse_fields(raw)
        token_id = None
        if 1 in fields:
            id_fields = pw.parse_fields(bytes(fields[1][0]))
            token_id = ID(bytes(id_fields.get(1, [b""])[0]).decode(),
                          id_fields.get(2, [0])[0])
        return cls(
            token_id=token_id,
            senders=[AuditableIdentity.deserialize(bytes(b))
                     for b in fields.get(2, [])],
        )


@dataclass
class TransferOutputMetadata:
    """driver/request.go:281-330."""

    output_metadata: bytes = b""            # serialized TokenMetadata
    output_audit_info: bytes = b""
    receivers: list[AuditableIdentity] = field(default_factory=list)

    def serialize(self) -> bytes:
        out = (pw.bytes_field(1, self.output_metadata)
               + pw.bytes_field(2, self.output_audit_info))
        for r in self.receivers:
            out += pw.message_field(3, r.serialize())
        return out

    @classmethod
    def deserialize(cls, raw: bytes) -> "TransferOutputMetadata":
        fields = pw.parse_fields(raw)
        return cls(
            output_metadata=bytes(fields.get(1, [b""])[0]),
            output_audit_info=bytes(fields.get(2, [b""])[0]),
            receivers=[AuditableIdentity.deserialize(bytes(b))
                       for b in fields.get(3, [])],
        )


@dataclass
class TransferActionMetadata:
    """driver/request.go TransferMetadata: per-input + per-output info."""

    inputs: list[TransferInputMetadata] = field(default_factory=list)
    outputs: list[TransferOutputMetadata] = field(default_factory=list)

    def serialize(self) -> bytes:
        out = b""
        for i in self.inputs:
            out += pw.message_field(1, i.serialize())
        for o in self.outputs:
            out += pw.message_field(2, o.serialize())
        return out

    @classmethod
    def deserialize(cls, raw: bytes) -> "TransferActionMetadata":
        fields = pw.parse_fields(raw)
        return cls(
            inputs=[TransferInputMetadata.deserialize(bytes(b))
                    for b in fields.get(1, [])],
            outputs=[TransferOutputMetadata.deserialize(bytes(b))
                     for b in fields.get(2, [])],
        )


@dataclass
class RequestMetadata:
    """Token-request metadata: one entry per action, in request order
    (driver.TokenRequestMetadata)."""

    issues: list[IssueActionMetadata] = field(default_factory=list)
    transfers: list[TransferActionMetadata] = field(default_factory=list)

    def serialize(self) -> bytes:
        out = b""
        for i in self.issues:
            out += pw.message_field(1, i.serialize())
        for t in self.transfers:
            out += pw.message_field(2, t.serialize())
        return out

    @classmethod
    def deserialize(cls, raw: bytes) -> "RequestMetadata":
        fields = pw.parse_fields(raw)
        return cls(
            issues=[IssueActionMetadata.deserialize(bytes(b))
                    for b in fields.get(1, [])],
            transfers=[TransferActionMetadata.deserialize(bytes(b))
                       for b in fields.get(2, [])],
        )
