"""zkatdlog driver services: ZK action assembly, deobfuscation, audit hook.

The driver-facing service object a TokenNode binds for the privacy driver
(reference token/core/zkatdlog/nogh/v1/{issue.go,transfer.go,tokens.go,
auditor.go}):

  - ``assemble_issue``  — GenerateZKIssue (crypto/issue/issuer.go:39-91):
    fresh commitments + witnesses, same-type + range proofs, request
    metadata carrying the openings for receivers and the auditor.
  - ``assemble_transfer`` — Sender.GenerateZKTransfer (crypto/transfer/
    sender.go:54-108): loads input openings from the wallet (tokendb
    ledger metadata), commits the outputs, proves type-and-sum + range.
  - ``extract_outputs`` — TokensService.Deobfuscate (v1/tokens.go:111):
    opens each output commitment with the opening received during
    distribution; outputs without an opening are opaque to this node and
    skipped (that is the privacy model working as intended).
  - ``audit_check`` — driver AuditorService.AuditorCheck (v1/auditor.go:58)
    delegating to the batched-reopen Auditor (audit.py).
"""

from __future__ import annotations

import logging

from ...crypto import token_commit
from ...services.tokens import ExtractedOutput
from ...token.model import ID
from ..fabtoken.driver import OutputSpec
from . import actions as zk_actions
from .actions import (ActionInput, IssueAction, Token, TransferAction,
                      UpgradeWitness)
from .audit import Auditor
from .metadata import (AuditableIdentity, IssueActionMetadata,
                       IssueOutputMetadata, RequestMetadata, TokenMetadata,
                       TransferActionMetadata, TransferInputMetadata,
                       TransferOutputMetadata)


logger = logging.getLogger("fabric_token_sdk_tpu.zkatdlog.driver")


class DriverError(Exception):
    pass


class ZkDlogDriverService:
    """Driver services for the ZK privacy driver, bound to one pp set."""

    label = "zkatdlog"
    actions = zk_actions

    def __init__(self, pp, device: bool = True, info_matcher=None):
        from ...crypto import issue_proof, transfer_proof

        self.pp = pp
        self._issue_prove = issue_proof.issue_prove
        self._transfer_prove = transfer_proof.transfer_prove
        self._device = device
        self._info_matcher = info_matcher
        # lazy: only auditor nodes ever call audit_check, and the device
        # reopen tables cost a table build per pp — non-auditing nodes must
        # not pay it
        self._auditor_instance: Auditor | None = None

    @property
    def _auditor(self) -> Auditor:
        if self._auditor_instance is None:
            self._auditor_instance = Auditor(
                self.pp, info_matcher=self._info_matcher,
                device=self._device)
        return self._auditor_instance

    # ------------------------------------------------------------- assembly
    def assemble_issue(self, issuer_identity: bytes,
                       outputs: list[OutputSpec]):
        """crypto/issue/issuer.go:39-91 GenerateZKIssue."""
        if not outputs:
            raise DriverError("no outputs to issue")
        token_type = outputs[0].token_type
        if any(o.token_type != token_type for o in outputs):
            raise DriverError("issue outputs must share one token type")
        coms, wits = token_commit.get_tokens_with_witness(
            [o.value for o in outputs], token_type,
            self.pp.pedersen_generators)
        proof = self._issue_prove([w.as_tuple() for w in wits], coms, self.pp)
        action = IssueAction(
            issuer=issuer_identity,
            outputs=[Token(owner=o.owner, data=c)
                     for o, c in zip(outputs, coms)],
            proof=proof,
        )
        md = IssueActionMetadata(
            issuer=AuditableIdentity(identity=bytes(issuer_identity),
                                     audit_info=bytes(issuer_identity)),
            outputs=[IssueOutputMetadata(
                output_metadata=TokenMetadata(
                    token_type=w.token_type, value=w.value,
                    blinding_factor=w.blinding_factor,
                    issuer=bytes(issuer_identity)).serialize(),
                receivers=[AuditableIdentity(
                    identity=o.owner,
                    audit_info=o.audit_info or o.owner)])
                for o, w in zip(outputs, wits)],
        )
        return action, md

    def assemble_transfer(self, input_rows, outputs: list[OutputSpec],
                          wallet=None, sender_audit_info=None):
        """crypto/transfer/sender.go:54-108 GenerateZKTransfer.

        input_rows: UnspentToken rows from the selector; ``wallet`` maps a
        token ID to its (serialized Token, serialized TokenMetadata) pair —
        the openings this node learned at ingestion time.
        ``sender_audit_info(owner_raw) -> bytes`` supplies the per-input
        audit info (Idemix pseudonym openings; defaults to the identity
        bytes, the x509 equality convention).
        """
        from ...crypto.bn254 import fr_rand

        if wallet is None:
            raise DriverError("zkatdlog transfers need a wallet of openings")
        in_tokens, in_wits, witnesses = [], [], []
        for row in input_rows:
            stored = wallet(row.id)
            if stored is None:
                raise DriverError(f"no opening for token {row.id}")
            tok_raw, md_raw = stored
            try:
                tok = Token.deserialize(tok_raw)
                is_comm = True
            except Exception:
                # dispatch on the typed-token tag: not a comm token means a
                # fabtoken-format ledger token (pre-pp-update)
                is_comm = False
            if is_comm:
                # commitment token: the opening MUST parse — a corrupt
                # opening is a wallet error, never an upgrade
                try:
                    opening = TokenMetadata.deserialize(md_raw)
                except Exception as e:
                    raise DriverError(
                        f"bad opening stored for token {row.id}: {e}"
                    ) from e
                witnesses.append(None)
            else:
                # UPGRADE: commit to the plaintext with a fresh blinding
                # factor and attach the witness binding the commitment to
                # the ledger token (v1/tokens.go:208-284).
                value = int(row.quantity, 16)
                bf = fr_rand()
                com = token_commit.commit_token(
                    row.type, value, bf, self.pp.pedersen_generators)
                tok = Token(owner=bytes(row.owner), data=com)
                opening = TokenMetadata(token_type=row.type, value=value,
                                        blinding_factor=bf)
                witnesses.append(UpgradeWitness(
                    owner=bytes(row.owner), token_type=row.type,
                    quantity=row.quantity, blinding_factor=bf))
            in_tokens.append(tok)
            in_wits.append((opening.token_type, opening.value,
                            opening.blinding_factor))
        token_type = in_wits[0][0]
        out_coms, out_wits = token_commit.get_tokens_with_witness(
            [o.value for o in outputs], token_type,
            self.pp.pedersen_generators)
        proof = self._transfer_prove(
            in_wits, [w.as_tuple() for w in out_wits],
            [t.data for t in in_tokens], out_coms, self.pp)
        action = TransferAction(
            inputs=[ActionInput(id=row.id, token=tok, upgrade_witness=w)
                    for row, tok, w in zip(input_rows, in_tokens,
                                           witnesses)],
            outputs=[Token(owner=o.owner, data=c)
                     for o, c in zip(outputs, out_coms)],
            proof=proof,
        )
        if sender_audit_info is None:
            sender_audit_info = bytes
        md = TransferActionMetadata(
            inputs=[TransferInputMetadata(
                token_id=row.id,
                senders=[AuditableIdentity(
                    identity=bytes(tok.owner),
                    audit_info=sender_audit_info(tok.owner))])
                for row, tok in zip(input_rows, in_tokens)],
            outputs=[TransferOutputMetadata(
                output_metadata=TokenMetadata(
                    token_type=w.token_type, value=w.value,
                    blinding_factor=w.blinding_factor).serialize(),
                receivers=[AuditableIdentity(
                    identity=o.owner,
                    audit_info=o.audit_info or o.owner)])
                for o, w in zip(outputs, out_wits)],
        )
        return action, md

    # ------------------------------------------------------------ ingestion
    def extract_outputs(self, action, openings=None) -> list[ExtractedOutput]:
        """v1/tokens.go:111 Deobfuscate: open each output this node holds an
        opening for; opaque outputs surface with owner b"" (skipped).

        A malformed or mismatched opening — peers supply these bytes —
        degrades that one output to opaque (logged) instead of failing the
        whole ingestion: the ledger commit already happened and the other
        outputs are still recoverable.
        """
        openings = openings or {}
        outs = []
        for i, tok in enumerate(action.get_outputs()):
            md_raw = openings.get(i)
            opaque = ExtractedOutput(index=i, owner_raw=b"", token_type="",
                                     quantity_hex="0x0")
            if md_raw is None or tok.is_redeem():
                outs.append(opaque)
                continue
            try:
                opening = TokenMetadata.deserialize(md_raw)
                clear = token_commit.to_clear(
                    tok.data, tok.owner, opening.token_type, opening.value,
                    opening.blinding_factor, self.pp.pedersen_generators)
            except Exception:
                logger.exception(
                    "discarding output [%d]: opening does not parse or does "
                    "not match the commitment", i)
                outs.append(opaque)
                continue
            outs.append(ExtractedOutput(
                index=i,
                owner_raw=bytes(tok.owner),
                token_type=clear["type"],
                quantity_hex=clear["quantity"],
                ledger_format=self.label,
                ledger_token=tok.serialize(),
                ledger_metadata=md_raw,
            ))
        return outs

    def parse_ledger_output(self, raw: bytes,
                            opening: bytes | None = None
                            ) -> ExtractedOutput | None:
        """Ledger-scan ingestion: a commitment token is opaque without its
        opening — nodes only recover outputs they hold openings for.

        Fabtoken-format ledger tokens (written before a pp update) are in
        the clear and ingest directly (reference Deobfuscate tries comm
        then fabtoken, v1/tokens.go:111-127); they become spendable via the
        upgrade-witness path.
        """
        from ..fabtoken.actions import Output as FabOutput

        try:
            out = FabOutput.deserialize(raw)
            return ExtractedOutput(
                index=0, owner_raw=bytes(out.owner), token_type=out.type,
                quantity_hex=out.quantity, ledger_format="fabtoken",
                ledger_token=raw)
        except Exception:
            pass
        if opening is None:
            return None
        tok = Token.deserialize(raw)
        if tok.is_redeem():
            return None
        try:
            md = TokenMetadata.deserialize(opening)
            clear = token_commit.to_clear(
                tok.data, tok.owner, md.token_type, md.value,
                md.blinding_factor, self.pp.pedersen_generators)
        except Exception:
            logger.exception("discarding ledger output: bad opening")
            return None
        return ExtractedOutput(
            index=0, owner_raw=bytes(tok.owner), token_type=clear["type"],
            quantity_hex=clear["quantity"], ledger_format=self.label,
            ledger_token=raw, ledger_metadata=opening)

    # ------------------------------------------------------------- auditing
    def audit_check(self, request, metadata: RequestMetadata | None,
                    input_tokens: list[list[Token]] | None,
                    tx_id: str) -> None:
        """v1/auditor.go:58 AuditorCheck -> audit.Auditor.Check."""
        if metadata is None:
            raise DriverError(
                f"audit of tx [{tx_id}] failed: missing request metadata")
        if input_tokens is None:
            input_tokens = [
                TransferAction.deserialize(raw).input_tokens()
                for raw in request.transfers
            ]
        self._auditor.check(request, metadata, input_tokens, tx_id)
