"""zkatdlog driver: ZK privacy tokens (reference token/core/zkatdlog/nogh/v1).

Tokens are Pedersen commitments; transfers carry type-and-sum Σ-proofs plus
Bulletproof-style range proofs; issues carry same-type + range proofs. The
range-proof workload — the entire ZK verification cost (SURVEY.md §3.2) —
routes to the TPU batch verifier behind the driver.Validator boundary.
"""

from .actions import Token, IssueAction, TransferAction  # noqa: F401
from .audit import Auditor, AuditError  # noqa: F401
from .driver import ZkDlogDriverService  # noqa: F401
from .metadata import (AuditableIdentity, IssueActionMetadata,  # noqa: F401
                       IssueOutputMetadata, RequestMetadata, TokenMetadata,
                       TransferActionMetadata, TransferInputMetadata,
                       TransferOutputMetadata)
from .validator import new_validator  # noqa: F401
from .verifier import ZKVerifier  # noqa: F401
