"""zkatdlog proof verification with TPU-batched range proofs.

The plugin point promised by BASELINE.json: the sub-tree under
TransferZKProofValidate / IssueValidate (reference crypto/transfer/
transfer.go:153-197, crypto/issue/verifier.go:32-57) re-routed so that the
Σ-protocol checks (cheap, per-action) run on host while every range proof in
the request is verified in one batched device pass. On batch rejection the
host oracle re-verifies the failing action to produce the reference's exact
error message (SURVEY.md north star: bit-identical accept/reject).
"""

from __future__ import annotations

import logging
import time

import numpy as np

from ...crypto import issue_proof, rp, transfer_proof
from ...crypto.bn254 import G1
from ...crypto.rp import ProofError
from ...models.adjust import adjust_points, adjust_points_async
from ...obs import GLOBAL as _METRICS
from ...obs import TRACER as _TRACER

logger = logging.getLogger("fabric_token_sdk_tpu.zkverifier")

#: zk_* family metadata (HELP independent of call-site order).
_ZK_FAMILIES = {
    "zk_blocks_verified_total": "Block-level verify_block calls",
    "zk_block_actions_total":
        "Actions through verify_block, by accept/reject",
    "zk_range_batch_verify_seconds":
        "Batched device range-proof verification wall",
    "zk_range_proofs_verified_total":
        "Range proofs verified on the device batch path, by verdict",
    "zk_sigma_verify_seconds": "Σ-protocol verification wall per action",
}
for _fam, _help in _ZK_FAMILIES.items():
    _METRICS.describe(_fam, _help)


def __getattr__(name: str):
    # Back-compat for the old module-global disagreement count: the value
    # now lives in the metrics registry (one source of truth, resettable
    # via metrics.GLOBAL.reset() between tests).
    if name == "DEVICE_DISAGREEMENTS":
        # read-only peek: must not (re)register the family
        return int(_METRICS.snapshot().get(
            ("zk_device_oracle_disagreements_total", ()), 0))
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")


def host_range_verify(pp, proof, commitment) -> None:
    """One range proof through the pure-host oracle (rp.range_verify with
    this pp's generators); raises ProofError on reject.

    THE bit-identity reference for a single range row: the device batch
    path defers to it on rejects (below), and the resilience layer's
    HostFallbackVerifier routes whole batches through it when the device
    path is exhausted or the breaker is open."""
    rpp = pp.range_proof_params
    rp.range_verify(proof, commitment, pp.pedersen_generators[1:3],
                    rpp.left_generators, rpp.right_generators,
                    rpp.P, rpp.Q, rpp.number_of_rounds, rpp.bit_length)


class ZKVerifier:
    """Per-pp verifier with an optional device batch backend."""

    def __init__(self, pp, device: bool = True):
        self.pp = pp
        self._range = None
        self._sigma = None
        if device:
            from ...models.range_verifier import BatchRangeVerifier
            from ...models.sigma import BatchSigmaVerifier

            self._range = BatchRangeVerifier(pp)
            self._sigma = BatchSigmaVerifier(pp)

    def prewarm(self, batch_sizes=(1,)) -> float:
        """Compile the device kernels at pp-install time (tcc.go:90
        availability semantics: a validator must answer its first invoke
        at steady-state latency, not after minutes of first-compile).
        Covers BOTH device backends — the batched range verifier and the
        Σ-row kernel. Returns elapsed seconds; no-op without a device
        backend."""
        if self._range is None:
            return 0.0
        return sum(self.prewarm_shapes(batch_sizes).values())

    def prewarm_shapes(self, batch_sizes=(1,),
                       include_block: bool = True) -> dict:
        """Per-shape variant of ``prewarm``: returns ``{batch_size:
        elapsed_seconds}``. With ``include_block`` the Σ-row and adjust
        kernels compile alongside each range bucket; without it only the
        range backend warms (range-only serving frontends)."""
        if self._range is None:
            return {b: 0.0 for b in batch_sizes}
        import time as _time

        from ...models import adjust as _adjust

        out = {}
        for b in batch_sizes:
            t0 = _time.perf_counter()
            self._range.prewarm(batch_sizes=(b,))
            if include_block:
                if self._sigma is not None:
                    self._sigma.prewarm(batch_sizes=(b,))
                _adjust.prewarm(batch_sizes=(b,))
            out[b] = _time.perf_counter() - t0
        return out

    def kernel_cost(self, batch_size: int) -> dict | None:
        """XLA cost analysis of the dominant range kernel at a bucket
        (see ``BatchRangeVerifier.kernel_cost``); None without a device
        backend. Consumed duck-typed by the device profiler at serve
        prewarm (the FaultyZK chaos shim passes it through)."""
        if self._range is None:
            return None
        return self._range.kernel_cost(batch_size)

    def kernel_cost_fused(self, batch_size: int) -> dict | None:
        """Fused device-program cost analysis at a bucket: the merged
        single-program chunk pipeline (``pass12_fused``, every backend)
        plus the individual Pallas kernels (``fb_msm_t`` +
        ``msm_var_fused``, TPU only). Duck-typed by the device profiler
        like ``kernel_cost``."""
        if self._range is None:
            return None
        return self._range.kernel_cost_fused(batch_size)

    # ------------------------------------------------------------ transfer
    def verify_transfer(self, proof_raw: bytes, inputs: list[G1],
                        outputs: list[G1]) -> None:
        """transfer.go:153-197 semantics; range part batched on device."""
        with _TRACER.span("zk.verify_transfer", inputs=len(inputs),
                          outputs=len(outputs)):
            self._verify_transfer_inner(proof_raw, inputs, outputs)

    def _verify_transfer_inner(self, proof_raw: bytes, inputs: list[G1],
                               outputs: list[G1]) -> None:
        if self._range is None:
            transfer_proof.transfer_verify(proof_raw, inputs, outputs, self.pp)
            return
        try:
            proof = transfer_proof.TransferProof.deserialize(proof_raw)
        except (ValueError, ProofError) as e:
            raise ProofError(f"invalid transfer proof: {e}") from e
        if proof.type_and_sum is None:
            raise ProofError("invalid transfer proof")
        try:
            self._verify_type_and_sum(proof.type_and_sum, inputs, outputs)
        except ProofError as e:
            raise ProofError(f"invalid transfer proof: {e}") from e
        if len(inputs) != 1 or len(outputs) != 1:
            if proof.range_correctness is None:
                raise ProofError("invalid transfer proof")
            ctt = proof.type_and_sum.commitment_to_type
            coms = adjust_points(outputs, [ctt] * len(outputs))
            self._verify_range_batch(proof.range_correctness, coms)

    # --------------------------------------------------------------- issue
    def verify_issue(self, proof_raw: bytes, commitments: list[G1]) -> None:
        """issue/verifier.go:32-57 semantics; range part batched on device."""
        with _TRACER.span("zk.verify_issue", commitments=len(commitments)):
            self._verify_issue_inner(proof_raw, commitments)

    def _verify_issue_inner(self, proof_raw: bytes,
                            commitments: list[G1]) -> None:
        if self._range is None:
            issue_proof.issue_verify(proof_raw, commitments, self.pp)
            return
        try:
            proof = issue_proof.IssueProof.deserialize(proof_raw)
        except (ValueError, ProofError) as e:
            raise ProofError(f"invalid issue proof: {e}") from e
        try:
            self._verify_same_type(proof.same_type)
        except ProofError as e:
            raise ProofError(f"invalid issue proof: {e}") from e
        ctt = proof.same_type.commitment_to_type
        coms = adjust_points(commitments, [ctt] * len(commitments))
        try:
            self._verify_range_batch(proof.range_correctness, coms)
        except ProofError as e:
            raise ProofError(f"invalid issue proof: {e}") from e

    # ---------------------------------------------------------------- block
    def verify_block(self, transfers: list, issues: list) -> "tuple":
        """Whole-block verification (BASELINE config 3: the auditor's batch
        re-verify of a mixed Issue+Transfer block).

        transfers: (proof_raw, inputs, outputs) per transfer action;
        issues: (proof_raw, commitments) per issue action. Returns
        (transfer_accepts, issue_accepts) bool vectors. ALL Σ-protocol
        checks ride one device pass (models/sigma.py) and ALL range proofs
        across every action ride one batched range pass — per-action host
        verification only happens on rejects (exact error reproduction is
        the per-action APIs' job; this is the throughput path).
        """
        with _TRACER.span("zk.verify_block", transfers=len(transfers),
                          issues=len(issues)) as blk_span:
            t_ok, i_ok = self._verify_block_inner(transfers, issues,
                                                  blk_span)
        _METRICS.counter("zk_blocks_verified_total").add()
        _METRICS.counter("zk_block_actions_total", status="accepted").add(
            int(t_ok.sum()) + int(i_ok.sum()))
        _METRICS.counter("zk_block_actions_total", status="rejected").add(
            int((~t_ok).sum()) + int((~i_ok).sum()))
        return t_ok, i_ok

    def _verify_block_inner(self, transfers: list, issues: list,
                            blk_span) -> "tuple":
        t_ok = np.zeros(len(transfers), dtype=bool)
        i_ok = np.zeros(len(issues), dtype=bool)
        if self._range is None or self._sigma is None:
            for k, (raw, ins, outs) in enumerate(transfers):
                try:
                    self.verify_transfer(raw, ins, outs)
                    t_ok[k] = True
                except ProofError:
                    pass
            for k, (raw, coms) in enumerate(issues):
                try:
                    self.verify_issue(raw, coms)
                    i_ok[k] = True
                except ProofError:
                    pass
            return t_ok, i_ok

        # 1. deserialize; structural failures stay rejected
        t_proofs: dict[int, object] = {}
        i_proofs: dict[int, object] = {}
        with _TRACER.span("zk.deserialize"):
            for k, (raw, ins, outs) in enumerate(transfers):
                try:
                    p = transfer_proof.TransferProof.deserialize(raw)
                    if p.type_and_sum is not None:
                        t_proofs[k] = p
                except (ValueError, ProofError):
                    pass
            for k, (raw, coms) in enumerate(issues):
                try:
                    i_proofs[k] = issue_proof.IssueProof.deserialize(raw)
                except (ValueError, ProofError):
                    pass

        # 2. assemble the cross-action range batch for every structurally
        # valid action (Σ verdicts are still pending — a Σ-failing action's
        # range rows are verified too and simply ANDed away, which keeps
        # all three device phases overlappable; honest blocks pay nothing
        # extra). Structural range failures reject here.
        sigma_ok_t = {k: True for k in t_proofs}
        sigma_ok_i = {k: True for k in i_proofs}
        range_proofs, raw_pts, raw_ctts, owners = [], [], [], []
        for k in sorted(t_proofs):
            p, (_, ins, outs) = t_proofs[k], transfers[k]
            if len(ins) == 1 and len(outs) == 1:
                continue  # ownership transfer: no range part
            if p.range_correctness is None \
                    or len(p.range_correctness.proofs) != len(outs):
                sigma_ok_t[k] = False
                continue
            ctt = p.type_and_sum.commitment_to_type
            for o, rp_proof in zip(outs, p.range_correctness.proofs):
                range_proofs.append(rp_proof)
                raw_pts.append(o)
                raw_ctts.append(ctt)
                owners.append(("t", k))
        for k in sorted(i_proofs):
            p, (_, coms) = i_proofs[k], issues[k]
            if p.range_correctness is None \
                    or len(p.range_correctness.proofs) != len(coms):
                sigma_ok_i[k] = False
                continue
            ctt = p.same_type.commitment_to_type
            for c, rp_proof in zip(coms, p.range_correctness.proofs):
                range_proofs.append(rp_proof)
                raw_pts.append(c)
                raw_ctts.append(ctt)
                owners.append(("i", k))

        # 3. dispatch all three device phases back-to-back, collect in
        # dependency order: the commitment adjustment first (it gates the
        # range pass-1 marshal), the Σ verdicts last (nothing reads them
        # until the final combine). Only the Σ kernel execution and its
        # async D2H copy overlap the range pass: the Σ host challenge
        # re-derivation lives in the collect() closures, which run after
        # self._range.verify has blocked to completion.
        blk_span.set_attribute("range_rows", len(range_proofs))
        with _TRACER.span("zk.dispatch"):
            adjust_collect = adjust_points_async(raw_pts, raw_ctts)
            ts_items = [(t_proofs[k].type_and_sum, transfers[k][1],
                         transfers[k][2]) for k in sorted(t_proofs)]
            st_items = [i_proofs[k].same_type for k in sorted(i_proofs)]
            ts_collect = self._sigma.verify_type_and_sum_async(ts_items)
            st_collect = self._sigma.verify_same_type_async(st_items)

        accepts = None
        if range_proofs:
            with _TRACER.span("zk.adjust_collect"):
                range_coms = adjust_collect()
            accepts = self._range.verify(range_proofs, range_coms)

        with _TRACER.span("zk.sigma_collect"):
            ts_acc = ts_collect()
            st_acc = st_collect()
        for j, k in enumerate(sorted(t_proofs)):
            sigma_ok_t[k] = sigma_ok_t[k] and bool(ts_acc[j])
        for j, k in enumerate(sorted(i_proofs)):
            sigma_ok_i[k] = sigma_ok_i[k] and bool(st_acc[j])
        if accepts is not None:
            for acc, (kind, k) in zip(accepts, owners):
                if not acc:
                    if kind == "t":
                        sigma_ok_t[k] = False
                    else:
                        sigma_ok_i[k] = False

        for k, v in sigma_ok_t.items():
            t_ok[k] = v
        for k, v in sigma_ok_i.items():
            i_ok[k] = v
        return t_ok, i_ok

    # ------------------------------------------------------------- helpers
    def _verify_sigma(self, kind: str, device_call, host_call) -> None:
        """One Σ check with the scalar muls on device (VERDICT r3 #4).

        The device batch (models/sigma.py) decides accept/reject; the host
        oracle (typeandsum.go:230-277 / sametype.go:167-183 semantics)
        runs only on rejects to produce the reference's exact error — same
        division of labor as ranges. A device-reject the host fully
        accepts is a kernel bug: counted, logged, and the host verdict
        wins (exactness)."""
        if self._sigma is None:
            host_call()
            return
        t0 = time.perf_counter()
        with _TRACER.span("zk.sigma_verify", kind=kind):
            acc = device_call()
        _METRICS.histogram("zk_sigma_verify_seconds",
                           kind=kind).observe(time.perf_counter() - t0)
        if bool(acc[0]):
            return
        host_call()
        self._record_disagreement(kind)

    def _verify_type_and_sum(self, proof, inputs, outputs) -> None:
        self._verify_sigma(
            "type_and_sum",
            lambda: self._sigma.verify_type_and_sum(
                [(proof, inputs, outputs)]),
            lambda: transfer_proof.type_and_sum_verify(
                proof, self.pp.pedersen_generators, inputs, outputs))

    def _verify_same_type(self, proof) -> None:
        self._verify_sigma(
            "same_type",
            lambda: self._sigma.verify_same_type([proof]),
            lambda: issue_proof.same_type_verify(
                proof, self.pp.pedersen_generators))

    def _record_disagreement(self, what: str) -> None:
        _METRICS.counter(
            "zk_device_oracle_disagreements_total",
            help="Device-reject/host-accept disagreements (kernel bug "
                 "indicator; stays 0 on honest input)").add()
        logger.error(
            "device/oracle disagreement: device rejected a %s check the "
            "host oracle accepts (kernel bug?)", what)

    def _verify_range_batch(self, rc: rp.RangeCorrectness,
                            commitments: list[G1]) -> None:
        """Device-batched RangeCorrectness with host fallback for the exact
        reference error (rangecorrectness.go:137-162 ordering)."""
        if len(rc.proofs) != len(commitments):
            raise ProofError("invalid range proof")
        t0 = time.perf_counter()
        accepts = self._range.verify_range_correctness(rc, commitments)
        _METRICS.histogram(
            "zk_range_batch_verify_seconds",
            path=self._range.last_path or "?").observe(
            time.perf_counter() - t0)
        _METRICS.counter("zk_range_proofs_verified_total").add(
            len(rc.proofs))
        if accepts.all():
            return
        # Reproduce the sequential loop's first-failure error exactly. The
        # device's exact pass is bit-identical per row, so only the rows it
        # REJECTED need the host oracle (the reference loop would have
        # stopped at the first of them; device-accepted rows before it are
        # already proven accepts). Bounds the adversarial re-verify cost to
        # O(#invalid), not O(tail) — VERDICT r3 #5.
        for i in np.flatnonzero(~accepts):
            try:
                host_range_verify(self.pp, rc.proofs[int(i)],
                                  commitments[int(i)])
            except ProofError as e:
                raise ProofError(f"invalid range proof at index {i}: {e}") from e
        # Device said reject but host accepts every rejected row: a
        # device/oracle disagreement is a kernel bug, never a bad proof.
        # Count and log it loudly so it can't silently mask a broken device
        # path, then trust the host oracle for the accept/reject decision.
        self._record_disagreement(
            f"range (index {int(accepts.argmin())} of {len(rc.proofs)})")
        return
