"""zkatdlog proof verification with TPU-batched range proofs.

The plugin point promised by BASELINE.json: the sub-tree under
TransferZKProofValidate / IssueValidate (reference crypto/transfer/
transfer.go:153-197, crypto/issue/verifier.go:32-57) re-routed so that the
Σ-protocol checks (cheap, per-action) run on host while every range proof in
the request is verified in one batched device pass. On batch rejection the
host oracle re-verifies the failing action to produce the reference's exact
error message (SURVEY.md north star: bit-identical accept/reject).
"""

from __future__ import annotations

import logging
import time

from ...crypto import issue_proof, rp, transfer_proof
from ...crypto.bn254 import G1, g1_add, g1_neg
from ...crypto.rp import ProofError

logger = logging.getLogger("fabric_token_sdk_tpu.zkverifier")

#: Count of device-reject / host-accept disagreements (should stay 0; tests
#: assert it never moves on honest input). Exposed for metrics scraping.
DEVICE_DISAGREEMENTS = 0


class ZKVerifier:
    """Per-pp verifier with an optional device batch backend."""

    def __init__(self, pp, device: bool = True):
        self.pp = pp
        self._range = None
        if device:
            from ...models.range_verifier import BatchRangeVerifier

            self._range = BatchRangeVerifier(pp)

    # ------------------------------------------------------------ transfer
    def verify_transfer(self, proof_raw: bytes, inputs: list[G1],
                        outputs: list[G1]) -> None:
        """transfer.go:153-197 semantics; range part batched on device."""
        if self._range is None:
            transfer_proof.transfer_verify(proof_raw, inputs, outputs, self.pp)
            return
        try:
            proof = transfer_proof.TransferProof.deserialize(proof_raw)
        except (ValueError, ProofError) as e:
            raise ProofError(f"invalid transfer proof: {e}") from e
        if proof.type_and_sum is None:
            raise ProofError("invalid transfer proof")
        try:
            transfer_proof.type_and_sum_verify(
                proof.type_and_sum, self.pp.pedersen_generators, inputs,
                outputs)
        except ProofError as e:
            raise ProofError(f"invalid transfer proof: {e}") from e
        if len(inputs) != 1 or len(outputs) != 1:
            if proof.range_correctness is None:
                raise ProofError("invalid transfer proof")
            coms = [g1_add(o, g1_neg(proof.type_and_sum.commitment_to_type))
                    for o in outputs]
            self._verify_range_batch(proof.range_correctness, coms)

    # --------------------------------------------------------------- issue
    def verify_issue(self, proof_raw: bytes, commitments: list[G1]) -> None:
        """issue/verifier.go:32-57 semantics; range part batched on device."""
        if self._range is None:
            issue_proof.issue_verify(proof_raw, commitments, self.pp)
            return
        try:
            proof = issue_proof.IssueProof.deserialize(proof_raw)
        except (ValueError, ProofError) as e:
            raise ProofError(f"invalid issue proof: {e}") from e
        try:
            issue_proof.same_type_verify(proof.same_type,
                                         self.pp.pedersen_generators)
        except ProofError as e:
            raise ProofError(f"invalid issue proof: {e}") from e
        coms = [g1_add(t, g1_neg(proof.same_type.commitment_to_type))
                for t in commitments]
        try:
            self._verify_range_batch(proof.range_correctness, coms)
        except ProofError as e:
            raise ProofError(f"invalid issue proof: {e}") from e

    # ------------------------------------------------------------- helpers
    def _verify_range_batch(self, rc: rp.RangeCorrectness,
                            commitments: list[G1]) -> None:
        """Device-batched RangeCorrectness with host fallback for the exact
        reference error (rangecorrectness.go:137-162 ordering)."""
        from ...services import metrics

        if len(rc.proofs) != len(commitments):
            raise ProofError("invalid range proof")
        t0 = time.perf_counter()
        accepts = self._range.verify_range_correctness(rc, commitments)
        metrics.GLOBAL.histogram(
            "zk_range_batch_verify_seconds",
            path=self._range.last_path or "?").observe(
            time.perf_counter() - t0)
        metrics.GLOBAL.counter("zk_range_proofs_verified_total").add(
            len(rc.proofs))
        if accepts.all():
            return
        # Reproduce the sequential loop's first-failure error exactly.
        first_bad = int(accepts.argmin())
        rpp = self.pp.range_proof_params
        for i in range(first_bad, len(rc.proofs)):
            try:
                rp.range_verify(rc.proofs[i], commitments[i],
                                self.pp.pedersen_generators[1:3],
                                rpp.left_generators, rpp.right_generators,
                                rpp.P, rpp.Q, rpp.number_of_rounds,
                                rpp.bit_length)
            except ProofError as e:
                raise ProofError(f"invalid range proof at index {i}: {e}") from e
        # Device said reject but host accepts everything: a device/oracle
        # disagreement is a kernel bug, never a bad proof. Count and log it
        # loudly so it can't silently mask a broken device path, then trust
        # the host oracle for the accept/reject decision (exactness).
        from ...services import metrics

        global DEVICE_DISAGREEMENTS
        DEVICE_DISAGREEMENTS += 1
        metrics.GLOBAL.counter("zk_device_oracle_disagreements_total").add()
        logger.error(
            "device/oracle disagreement: device rejected index %d of a "
            "%d-proof batch the host oracle fully accepts (kernel bug?)",
            first_bad, len(rc.proofs))
        return
