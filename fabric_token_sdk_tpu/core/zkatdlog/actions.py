"""zkatdlog actions: commitment tokens, issue/transfer actions.

Byte-exact wire mirror of the reference protos
(token/core/zkatdlog/nogh/protos/noghactions.proto, generated
protos-go/actions) and the standalone token envelope
(token/services/tokens/typed.go + tokens/core/comm/token.go:41): a token
embedded in an action is the bare proto message
``Token{owner, G1{raw}}``; a token travelling alone (ledger state,
Deobfuscate input) is ASN.1 ``TypedToken{Type=2, OCTET STRING proto}``.
Conformance is pinned against protoc-compiled reference protos in
tests/test_wire_conformance.py.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ...crypto import serialization as ser
from ...crypto.bn254 import G1
from ...driver.identity import Identity
from ...token.model import ID
from ...utils import protowire as pw

#: tokens/core/comm/token.go:18 — the comm (commitment) token format tag.
COMM_TOKEN_TYPE = 2


class ActionError(ValueError):
    pass


def wrap_token_with_type(raw: bytes, typ: int = COMM_TOKEN_TYPE) -> bytes:
    """tokens/typed.go:37 WrapWithType: ASN.1 {INTEGER type, OCTET STRING}."""
    return ser.der_sequence(ser.der_integer(typ), ser.der_octet_string(raw))


def unmarshal_typed_token(raw: bytes, typ: int = COMM_TOKEN_TYPE) -> bytes:
    """tokens/typed.go:28 + comm/token.go:45: unwrap and check the type."""
    try:
        seq = ser.DerReader(raw).read_sequence()
        got_typ = seq.read_integer()
        body = seq.read_octet_string()
    except Exception as e:
        raise ActionError(f"failed to unmarshal to TypedToken: {e}") from e
    if got_typ != typ:
        raise ActionError(f"invalid token type [{got_typ}]")
    return body


def _g1_msg(p: G1) -> bytes:
    """noghmath.proto G1{1: raw}."""
    return pw.bytes_field(1, ser.g1_to_bytes(p))


def _g1_from_msg(raw: bytes) -> G1:
    fields = pw.parse_fields(raw)
    if 1 not in fields:
        raise ActionError("invalid G1 proto: missing raw")
    return ser.g1_from_bytes(bytes(fields[1][0]))


@dataclass
class Token:
    """Committed token (crypto/token/token.go:22): owner + G1 commitment."""

    owner: bytes
    data: G1

    def to_proto(self) -> bytes:
        """noghactions.proto Token{1: owner, 2: G1} — embedded form."""
        return (pw.bytes_field(1, self.owner)
                + pw.message_field(2, _g1_msg(self.data)))

    @classmethod
    def from_proto(cls, raw: bytes) -> "Token":
        fields = pw.parse_fields(raw)
        if 2 not in fields:
            raise ActionError("invalid token: missing data")
        return cls(owner=bytes(fields.get(1, [b""])[0]),
                   data=_g1_from_msg(bytes(fields[2][0])))

    def serialize(self) -> bytes:
        """Standalone form (crypto/token/token.go:35-47): typed-wrapped."""
        return wrap_token_with_type(self.to_proto())

    @classmethod
    def deserialize(cls, raw: bytes) -> "Token":
        """crypto/token/token.go:51-66."""
        return cls.from_proto(unmarshal_typed_token(raw))

    def get_owner(self) -> bytes:
        return self.owner

    def is_redeem(self) -> bool:
        return len(self.owner) == 0


def _token_id_msg(token_id: ID) -> bytes:
    """noghactions.proto TokenID{1: id, 2: index}."""
    return (pw.string_field(1, token_id.tx_id)
            + pw.uint64_field(2, token_id.index))


def _token_id_from_msg(raw: bytes) -> ID:
    fields = pw.parse_fields(raw)
    return ID(bytes(fields.get(1, [b""])[0]).decode(),
              fields.get(2, [0])[0])


@dataclass
class UpgradeWitness:
    """noghactions.proto TransferActionInputUpgradeWitness{1: fabtoken
    Token, 2: Zr blinding_factor}: binds a plaintext (fabtoken-format)
    ledger token to the commitment claimed for it, enabling old tokens to
    be spent under the zkatdlog pp after a public-params update
    (v1/tokens.go:208-284, validator_transfer.go:64-93)."""

    owner: bytes
    token_type: str
    quantity: str                    # "0x..." base-16, fabtoken convention
    blinding_factor: int

    def serialize(self) -> bytes:
        fab = (pw.bytes_field(1, self.owner)
               + pw.string_field(2, self.token_type)
               + pw.string_field(3, self.quantity))
        return (pw.message_field(1, fab, present=True)
                + pw.message_field(
                    2, pw.bytes_field(1, ser.zr_to_bytes(
                        self.blinding_factor)), present=True))

    @classmethod
    def deserialize(cls, raw: bytes) -> "UpgradeWitness":
        fields = pw.parse_fields(raw)
        if 1 not in fields or 2 not in fields:
            raise ActionError("invalid upgrade witness")
        fab = pw.parse_fields(bytes(fields[1][0]))
        bf_fields = pw.parse_fields(bytes(fields[2][0]))
        if 1 not in bf_fields:
            raise ActionError("invalid upgrade witness: missing bf")
        return cls(
            owner=bytes(fab.get(1, [b""])[0]),
            token_type=bytes(fab.get(2, [b""])[0]).decode(),
            quantity=bytes(fab.get(3, [b""])[0]).decode(),
            blinding_factor=ser.zr_from_bytes(bytes(bf_fields[1][0])),
        )

    def fabtoken_bytes(self) -> bytes:
        """The plaintext token exactly as it sits on the ledger (typed
        fabtoken envelope) — the content the spent-input key binds to."""
        from ..fabtoken.actions import Output

        return Output(owner=self.owner, type=self.token_type,
                      quantity=self.quantity).serialize()


@dataclass
class ActionInput:
    """noghactions.proto TransferActionInput{1: TokenID, 2: Token,
    3: upgrade witness}."""

    id: ID
    token: Token
    upgrade_witness: UpgradeWitness | None = None

    def serialize(self) -> bytes:
        out = (pw.message_field(1, _token_id_msg(self.id))
               + pw.message_field(2, self.token.to_proto()))
        if self.upgrade_witness is not None:
            out += pw.message_field(3, self.upgrade_witness.serialize())
        return out

    @classmethod
    def deserialize(cls, raw: bytes) -> "ActionInput":
        fields = pw.parse_fields(raw)
        if 1 not in fields or 2 not in fields:
            raise ActionError("invalid transfer action input")
        witness = None
        if 3 in fields and bytes(fields[3][0]):
            witness = UpgradeWitness.deserialize(bytes(fields[3][0]))
        return cls(id=_token_id_from_msg(bytes(fields[1][0])),
                   token=Token.from_proto(bytes(fields[2][0])),
                   upgrade_witness=witness)


def _proof_msg(proof: bytes) -> bytes:
    """noghactions.proto Proof{1: proof}."""
    return pw.bytes_field(1, proof)


def _proof_from_msg(raw: bytes) -> bytes:
    fields = pw.parse_fields(raw)
    return bytes(fields.get(1, [b""])[0])


def _metadata_fields(field_number: int, metadata: dict[str, bytes]) -> bytes:
    """proto map<string, bytes>: repeated {1: key, 2: value}, sorted keys
    (Go's map order is random; sorted is a deterministic subset)."""
    out = b""
    for k in sorted(metadata):
        entry = pw.string_field(1, k) + pw.bytes_field(2, metadata[k])
        out += pw.message_field(field_number, entry)
    return out


def _metadata_from_fields(fields, field_number: int) -> dict[str, bytes]:
    md = {}
    for raw in fields.get(field_number, []):
        sub = pw.parse_fields(raw)
        key = bytes(sub.get(1, [b""])[0]).decode()
        md[key] = bytes(sub.get(2, [b""])[0])
    return md


@dataclass
class TransferAction:
    """noghactions.proto TransferAction (transfer/action.go:115-378)."""

    inputs: list[ActionInput] = field(default_factory=list)
    outputs: list[Token] = field(default_factory=list)
    proof: bytes = b""
    metadata: dict[str, bytes] = field(default_factory=dict)

    def validate(self) -> None:
        """action.go:244-283."""
        if not self.inputs:
            raise ActionError("invalid number of token inputs in transfer action")
        for i, inp in enumerate(self.inputs):
            if inp is None or inp.token is None:
                raise ActionError(f"invalid input at index [{i}] in transfer action")
            if not inp.id.tx_id:
                raise ActionError(f"invalid input's ID at index [{i}] in transfer action")
        if not self.outputs:
            raise ActionError("invalid number of token outputs in transfer action")
        for i, out in enumerate(self.outputs):
            if out is None or out.data is None:
                raise ActionError(f"invalid output at index [{i}] in transfer action")
        if not self.proof:
            raise ActionError("invalid proof in transfer action")

    # driver surface
    def num_inputs(self) -> int:
        return len(self.inputs)

    def num_outputs(self) -> int:
        return len(self.outputs)

    def get_inputs(self) -> list[ID]:
        return [inp.id for inp in self.inputs]

    def input_tokens(self) -> list[Token]:
        return [inp.token for inp in self.inputs]

    def get_serialized_inputs(self) -> list[bytes]:
        """Standalone forms as they sit ON THE LEDGER: commitment tokens
        normally, the witness's plaintext fabtoken for upgrade inputs (the
        spent-input key must bind to the ledger content)."""
        out = []
        for inp in self.inputs:
            if inp.upgrade_witness is not None:
                out.append(inp.upgrade_witness.fabtoken_bytes())
            else:
                out.append(inp.token.serialize())
        return out

    def get_outputs(self) -> list[Token]:
        return list(self.outputs)

    def get_output_commitments(self) -> list[G1]:
        return [o.data for o in self.outputs]

    def get_serialized_outputs(self) -> list[bytes]:
        """action.go:221-229 — standalone (typed-wrapped) forms."""
        return [o.serialize() for o in self.outputs]

    def is_redeem_at(self, index: int) -> bool:
        return self.outputs[index].is_redeem()

    def is_graph_hiding(self) -> bool:
        return False

    def get_proof(self) -> bytes:
        return self.proof

    def get_metadata(self) -> dict[str, bytes]:
        return self.metadata

    def serialize(self) -> bytes:
        out = b""
        for inp in self.inputs:
            out += pw.message_field(1, inp.serialize())
        for o in self.outputs:
            # TransferActionOutput{1: Token}
            out += pw.message_field(
                2, pw.message_field(1, o.to_proto(), present=True))
        out += pw.message_field(3, _proof_msg(self.proof),
                                present=bool(self.proof))
        out += _metadata_fields(4, self.metadata)
        return out

    @classmethod
    def deserialize(cls, raw: bytes) -> "TransferAction":
        fields = pw.parse_fields(raw)
        outputs = []
        for b in fields.get(2, []):
            sub = pw.parse_fields(bytes(b))
            if 1 not in sub:
                raise ActionError("invalid output in transfer action")
            outputs.append(Token.from_proto(bytes(sub[1][0])))
        return cls(
            inputs=[ActionInput.deserialize(bytes(b))
                    for b in fields.get(1, [])],
            outputs=outputs,
            proof=_proof_from_msg(bytes(fields.get(3, [b""])[0])),
            metadata=_metadata_from_fields(fields, 4),
        )


@dataclass
class IssueAction:
    """noghactions.proto IssueAction{1: Identity, 2: inputs, 3: outputs,
    4: Proof, 5: metadata} (issue/action.go)."""

    issuer: Identity = Identity(b"")
    outputs: list[Token] = field(default_factory=list)
    proof: bytes = b""
    metadata: dict[str, bytes] = field(default_factory=dict)

    def validate(self) -> None:
        if len(self.issuer) == 0:
            raise ActionError("issuer is not set")
        if not self.outputs:
            raise ActionError("no outputs in issue action")
        for i, out in enumerate(self.outputs):
            if out is None or out.data is None:
                raise ActionError(f"invalid output at index [{i}] in issue action")
        if not self.proof:
            raise ActionError("invalid proof in issue action")

    def num_outputs(self) -> int:
        return len(self.outputs)

    def get_inputs(self) -> list[ID]:
        return []

    def get_serialized_inputs(self) -> list[bytes]:
        return []

    def get_outputs(self) -> list[Token]:
        return list(self.outputs)

    def get_commitments(self) -> list[G1]:
        return [o.data for o in self.outputs]

    def get_serialized_outputs(self) -> list[bytes]:
        return [o.serialize() for o in self.outputs]

    def get_proof(self) -> bytes:
        return self.proof

    def get_metadata(self) -> dict[str, bytes]:
        return self.metadata

    def is_anonymous(self) -> bool:
        return False

    def serialize(self) -> bytes:
        # Identity{1: raw}
        out = pw.message_field(1, pw.bytes_field(1, bytes(self.issuer)),
                               present=len(self.issuer) > 0)
        for o in self.outputs:
            # IssueActionOutput{1: Token}
            out += pw.message_field(
                3, pw.message_field(1, o.to_proto(), present=True))
        out += pw.message_field(4, _proof_msg(self.proof),
                                present=bool(self.proof))
        out += _metadata_fields(5, self.metadata)
        return out

    @classmethod
    def deserialize(cls, raw: bytes) -> "IssueAction":
        fields = pw.parse_fields(raw)
        issuer = b""
        if 1 in fields:
            issuer = bytes(pw.parse_fields(
                bytes(fields[1][0])).get(1, [b""])[0])
        if fields.get(2):
            raise ActionError(
                "issue-with-inputs (redeem-by-issuer) is not supported")
        outputs = []
        for b in fields.get(3, []):
            sub = pw.parse_fields(bytes(b))
            if 1 not in sub:
                raise ActionError("invalid output in issue action")
            outputs.append(Token.from_proto(bytes(sub[1][0])))
        return cls(
            issuer=Identity(issuer),
            outputs=outputs,
            proof=_proof_from_msg(bytes(fields.get(4, [b""])[0])),
            metadata=_metadata_from_fields(fields, 5),
        )
