"""zkatdlog actions: commitment tokens, issue/transfer actions.

Behavioral mirror of reference token/core/zkatdlog/nogh/v1/crypto/transfer/
action.go:24-378 and .../issue/action.go: a token is (owner bytes,
Data = Pedersen commitment in G1); actions carry commitment outputs, input
IDs + input tokens, the serialized ZK proof, and a metadata map. Wire format
here is this framework's protowire messages (token: {1: owner, 2: g1},
actions: repeated submessages) — the Fiat-Shamir-relevant proof bytes keep
exact reference encoding via crypto/serialization.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ...crypto import serialization as ser
from ...crypto.bn254 import G1
from ...driver.identity import Identity
from ...token.model import ID
from ...utils import protowire as pw


class ActionError(ValueError):
    pass


@dataclass
class Token:
    """Committed token (crypto/token/token.go:22): owner + G1 commitment."""

    owner: bytes
    data: G1

    def serialize(self) -> bytes:
        return (pw.bytes_field(1, self.owner)
                + pw.bytes_field(2, ser.g1_to_bytes(self.data)))

    @classmethod
    def deserialize(cls, raw: bytes) -> "Token":
        fields = pw.parse_fields(raw)
        data_raw = bytes(fields.get(2, [b""])[0])
        if not data_raw:
            raise ActionError("invalid token: missing data")
        return cls(owner=bytes(fields.get(1, [b""])[0]),
                   data=ser.g1_from_bytes(data_raw))

    def get_owner(self) -> bytes:
        return self.owner

    def is_redeem(self) -> bool:
        return len(self.owner) == 0


@dataclass
class ActionInput:
    """transfer/action.go:24-113: input ID + claimed token."""

    id: ID
    token: Token

    def serialize(self) -> bytes:
        id_msg = (pw.string_field(1, self.id.tx_id)
                  + pw.uint64_field(2, self.id.index))
        return (pw.message_field(1, id_msg)
                + pw.message_field(2, self.token.serialize()))

    @classmethod
    def deserialize(cls, raw: bytes) -> "ActionInput":
        fields = pw.parse_fields(raw)
        if 1 not in fields or 2 not in fields:
            raise ActionError("invalid transfer action input")
        id_fields = pw.parse_fields(fields[1][0])
        tx_id = bytes(id_fields.get(1, [b""])[0]).decode()
        index = id_fields.get(2, [0])[0]
        return cls(id=ID(tx_id, index),
                   token=Token.deserialize(bytes(fields[2][0])))


def _metadata_fields(metadata: dict[str, bytes]) -> bytes:
    out = b""
    for k in sorted(metadata):
        entry = pw.string_field(1, k) + pw.bytes_field(2, metadata[k])
        out += pw.message_field(4, entry)
    return out


def _metadata_from_fields(fields) -> dict[str, bytes]:
    md = {}
    for raw in fields.get(4, []):
        sub = pw.parse_fields(raw)
        key = bytes(sub.get(1, [b""])[0]).decode()
        md[key] = bytes(sub.get(2, [b""])[0])
    return md


@dataclass
class TransferAction:
    """transfer/action.go:115-378."""

    inputs: list[ActionInput] = field(default_factory=list)
    outputs: list[Token] = field(default_factory=list)
    proof: bytes = b""
    metadata: dict[str, bytes] = field(default_factory=dict)

    def validate(self) -> None:
        """action.go:244-283."""
        if not self.inputs:
            raise ActionError("invalid number of token inputs in transfer action")
        for i, inp in enumerate(self.inputs):
            if inp is None or inp.token is None:
                raise ActionError(f"invalid input at index [{i}] in transfer action")
            if not inp.id.tx_id:
                raise ActionError(f"invalid input's ID at index [{i}] in transfer action")
        if not self.outputs:
            raise ActionError("invalid number of token outputs in transfer action")
        for i, out in enumerate(self.outputs):
            if out is None or out.data is None:
                raise ActionError(f"invalid output at index [{i}] in transfer action")
        if not self.proof:
            raise ActionError("invalid proof in transfer action")

    # driver surface
    def num_inputs(self) -> int:
        return len(self.inputs)

    def num_outputs(self) -> int:
        return len(self.outputs)

    def get_inputs(self) -> list[ID]:
        return [inp.id for inp in self.inputs]

    def input_tokens(self) -> list[Token]:
        return [inp.token for inp in self.inputs]

    def get_serialized_inputs(self) -> list[bytes]:
        return [inp.token.serialize() for inp in self.inputs]

    def get_outputs(self) -> list[Token]:
        return list(self.outputs)

    def get_output_commitments(self) -> list[G1]:
        return [o.data for o in self.outputs]

    def get_serialized_outputs(self) -> list[bytes]:
        return [o.serialize() for o in self.outputs]

    def is_redeem_at(self, index: int) -> bool:
        return self.outputs[index].is_redeem()

    def is_graph_hiding(self) -> bool:
        return False

    def get_proof(self) -> bytes:
        return self.proof

    def get_metadata(self) -> dict[str, bytes]:
        return self.metadata

    def serialize(self) -> bytes:
        out = b""
        for inp in self.inputs:
            out += pw.message_field(1, inp.serialize())
        for o in self.outputs:
            out += pw.message_field(2, o.serialize())
        out += pw.bytes_field(3, self.proof)
        out += _metadata_fields(self.metadata)
        return out

    @classmethod
    def deserialize(cls, raw: bytes) -> "TransferAction":
        fields = pw.parse_fields(raw)
        return cls(
            inputs=[ActionInput.deserialize(bytes(b))
                    for b in fields.get(1, [])],
            outputs=[Token.deserialize(bytes(b)) for b in fields.get(2, [])],
            proof=bytes(fields.get(3, [b""])[0]),
            metadata=_metadata_from_fields(fields),
        )


@dataclass
class IssueAction:
    """issue/action.go: issuer + commitment outputs + proof."""

    issuer: Identity = Identity(b"")
    outputs: list[Token] = field(default_factory=list)
    proof: bytes = b""
    metadata: dict[str, bytes] = field(default_factory=dict)

    def validate(self) -> None:
        if len(self.issuer) == 0:
            raise ActionError("issuer is not set")
        if not self.outputs:
            raise ActionError("no outputs in issue action")
        for i, out in enumerate(self.outputs):
            if out is None or out.data is None:
                raise ActionError(f"invalid output at index [{i}] in issue action")
        if not self.proof:
            raise ActionError("invalid proof in issue action")

    def num_outputs(self) -> int:
        return len(self.outputs)

    def get_inputs(self) -> list[ID]:
        return []

    def get_serialized_inputs(self) -> list[bytes]:
        return []

    def get_outputs(self) -> list[Token]:
        return list(self.outputs)

    def get_commitments(self) -> list[G1]:
        return [o.data for o in self.outputs]

    def get_serialized_outputs(self) -> list[bytes]:
        return [o.serialize() for o in self.outputs]

    def get_proof(self) -> bytes:
        return self.proof

    def get_metadata(self) -> dict[str, bytes]:
        return self.metadata

    def is_anonymous(self) -> bool:
        return False

    def serialize(self) -> bytes:
        out = pw.bytes_field(1, bytes(self.issuer))
        for o in self.outputs:
            out += pw.message_field(2, o.serialize())
        out += pw.bytes_field(3, self.proof)
        out += _metadata_fields(self.metadata)
        return out

    @classmethod
    def deserialize(cls, raw: bytes) -> "IssueAction":
        fields = pw.parse_fields(raw)
        return cls(
            issuer=Identity(bytes(fields.get(1, [b""])[0])),
            outputs=[Token.deserialize(bytes(b)) for b in fields.get(2, [])],
            proof=bytes(fields.get(3, [b""])[0]),
            metadata=_metadata_from_fields(fields),
        )
