"""zkatdlog validation chain.

Behavioral mirror of reference token/core/zkatdlog/nogh/v1/validator:
transfer chain = ActionValidate -> SignatureValidate ->
UpgradeWitnessValidate -> ZKProofValidate -> HTLCValidate; issue chain =
IssueValidate (validator.go:53-80). The ZK step routes through ZKVerifier,
which batches all range proofs on the TPU (the north-star plugin boundary,
validator_transfer.go:96-110).
"""

from __future__ import annotations

import time as time_mod

from ...driver import TokenRequest
from ..common.validator import Context, ValidationError, Validator
from .actions import IssueAction, TransferAction
from .verifier import ZKVerifier


class ActionDeserializer:
    """v1/validator/validator.go:29-49."""

    def deserialize_actions(self, tr: TokenRequest):
        issues = [IssueAction.deserialize(raw) for raw in tr.issues]
        transfers = [TransferAction.deserialize(raw) for raw in tr.transfers]
        return issues, transfers


def transfer_action_validate(ctx: Context) -> None:
    """validator_transfer.go:25."""
    ctx.transfer_action.validate()


def transfer_signature_validate(ctx: Context) -> None:
    """validator_transfer.go:29-61: every input owner must have signed."""
    ctx.input_tokens = ctx.transfer_action.input_tokens()
    for tok in ctx.input_tokens:
        owner = tok.get_owner()
        try:
            verifier = ctx.deserializer.get_owner_verifier(owner)
        except Exception as e:
            raise ValidationError(f"failed deserializing owner [{e}]") from e
        try:
            sigma = ctx.signature_provider.has_been_signed_by(owner, verifier)
        except Exception as e:
            raise ValidationError(
                f"failed signature verification [{e}]") from e
        ctx.signatures.append(sigma)


def transfer_upgrade_witness_validate(ctx: Context) -> None:
    """validator_transfer.go:64-93: token-upgrade witnesses.

    An upgrade input claims a commitment for a plaintext (fabtoken-format)
    ledger token; the witness must open the commitment to exactly the
    plaintext (type, quantity) and carry the same owner. The spent-input
    key separately binds the witness's plaintext to the actual ledger
    content (actions.get_serialized_inputs), so a witness for a token that
    is not on the ledger cannot commit.
    """
    from ...crypto import token_commit
    from ...token import quantity as q

    for inp in ctx.transfer_action.inputs:
        witness = inp.upgrade_witness
        if witness is None:
            continue
        if not witness.token_type or not witness.quantity:
            raise ValidationError("fabtoken token not found in witness")
        try:
            value = q.to_quantity(witness.quantity,
                                  ctx.pp.quantity_precision).value
        except Exception as e:
            raise ValidationError(
                f"failed to unmarshal quantity: {e}") from e
        com = token_commit.commit_token(
            witness.token_type, value, witness.blinding_factor,
            ctx.pp.pedersen_generators)
        if com != inp.token.data:
            raise ValidationError("recomputed commitment does not match")
        if bytes(inp.token.owner) != bytes(witness.owner):
            raise ValidationError("owners do not correspond")


def transfer_zk_proof_validate(ctx: Context) -> None:
    """validator_transfer.go:96-110 — the entire ZK cost, TPU-batched."""
    inputs = [tok.data for tok in ctx.input_tokens]
    outputs = ctx.transfer_action.get_output_commitments()
    verifier: ZKVerifier = ctx.pp.zk_verifier
    verifier.verify_transfer(ctx.transfer_action.get_proof(), inputs, outputs)


def transfer_htlc_validate(ctx: Context) -> None:
    """validator_transfer.go:112-175 (commitment-token variant: exactly
    1-in/1-out, no plaintext type/quantity checks)."""
    from ...services.interop import htlc

    htlc.transfer_htlc_validate_zkatdlog(ctx, now=time_mod.time())


def issue_validate(ctx: Context) -> None:
    """validator_issue.go:17-57."""
    action = ctx.issue_action
    try:
        action.validate()
    except Exception as e:
        raise ValidationError(f"failed validating issue action: {e}") from e
    commitments = action.get_commitments()
    verifier: ZKVerifier = ctx.pp.zk_verifier
    verifier.verify_issue(action.get_proof(), commitments)
    issuers = ctx.pp.issuers()
    if issuers:
        if not any(bytes(action.issuer) == bytes(i) for i in issuers):
            raise ValidationError(
                f"issuer [{action.issuer!r}] is not in issuers")
    try:
        sig_verifier = ctx.deserializer.get_issuer_verifier(action.issuer)
    except Exception as e:
        raise ValidationError(
            f"failed getting verifier for issuer: {e}") from e
    try:
        ctx.signature_provider.has_been_signed_by(action.issuer, sig_verifier)
    except Exception as e:
        raise ValidationError(f"failed verifying signature: {e}") from e


class _PPFacade:
    """Binds the crypto PublicParams to a shared ZKVerifier instance."""

    def __init__(self, pp, device: bool):
        self._pp = pp
        self.zk_verifier = ZKVerifier(pp, device=device)

    def __getattr__(self, name):
        return getattr(self._pp, name)


def new_validator(pp, deserializer, device: bool = True,
                  extra_transfer_validators=()) -> Validator:
    """validator.go:53-80; `device=True` routes range proofs to the TPU."""
    facade = _PPFacade(pp, device)
    transfer_chain = [
        transfer_action_validate,
        transfer_signature_validate,
        transfer_upgrade_witness_validate,
        transfer_zk_proof_validate,
        transfer_htlc_validate,
        *extra_transfer_validators,
    ]
    return Validator(
        pp=facade,
        deserializer=deserializer,
        action_deserializer=ActionDeserializer(),
        transfer_validators=transfer_chain,
        issue_validators=[issue_validate],
    )
