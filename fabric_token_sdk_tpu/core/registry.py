"""Driver registry + TMS provider: config-driven token service assembly.

Behavioral mirror of reference token/core/service.go:29 (factoryDirectory:
named driver factories) and token/core/tms.go:63,207-274 (TMSProvider: lazy
TMS instantiation keyed by TMSID; public-params resolution order
opts -> storage -> fetcher).

A driver factory takes the serialized public parameters and returns the
assembled driver bundle (driver services + validator + deserializer). The
provider peeks at the pp envelope's ``identifier`` field — both pp formats
serialize as JSON{identifier, raw} — to pick the factory, exactly how the
reference dispatches on PublicParameters.Identifier.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Callable


class RegistryError(Exception):
    pass


@dataclass(frozen=True)
class TMSID:
    """token/tms.go:20-30: (network, channel, namespace) triple."""

    network: str
    channel: str = ""
    namespace: str = ""

    def __str__(self) -> str:
        return f"{self.network},{self.channel},{self.namespace}"


@dataclass
class DriverBundle:
    """What a driver factory assembles (v1/driver/driver.go:69-169):
    services + validator + deserializer bound to one pp set."""

    label: str
    public_params: object
    services: object                 # driver service (assemble/extract/audit)
    validator: object
    deserializer: object


class DriverRegistry:
    """Named-factory directory (core/service.go:29-106)."""

    def __init__(self):
        self._factories: dict[str, Callable[[bytes], DriverBundle]] = {}

    def register(self, label: str,
                 factory: Callable[[bytes], DriverBundle]) -> None:
        if label in self._factories:
            raise RegistryError(f"driver [{label}] already registered")
        self._factories[label] = factory

    def labels(self) -> list[str]:
        return sorted(self._factories)

    def new_bundle(self, pp_raw: bytes) -> DriverBundle:
        """Dispatch on the pp envelope identifier (core/tms.go driver
        selection via PublicParametersFromBytes)."""
        try:
            identifier = json.loads(pp_raw).get("identifier")
        except Exception as e:
            raise RegistryError(
                f"failed to unmarshal public parameters: {e}") from e
        factory = self._factories.get(identifier)
        if factory is None:
            raise RegistryError(
                f"no driver found for [{identifier}], available: "
                f"{self.labels()}")
        return factory(pp_raw)


def default_registry(device: bool = False) -> DriverRegistry:
    """Registry with the two shipped drivers (sdk/dig wiring equivalent)."""
    reg = DriverRegistry()

    def _fabtoken(pp_raw: bytes) -> DriverBundle:
        from ..services.identity.deserializer import Deserializer
        from .fabtoken import new_validator
        from .fabtoken.driver import FabTokenDriverService
        from .fabtoken.setup import PublicParams

        pp = PublicParams.deserialize(pp_raw)
        deser = Deserializer()
        return DriverBundle(
            label="fabtoken", public_params=pp,
            services=FabTokenDriverService(pp.quantity_precision),
            validator=new_validator(pp, deser), deserializer=deser)

    def _zkatdlog(pp_raw: bytes) -> DriverBundle:
        from ..crypto.setup import PublicParams
        from ..services.identity.deserializer import Deserializer
        from ..services.identity.idemix import idemix_owner_resolver
        from . import zkatdlog
        from .zkatdlog.driver import ZkDlogDriverService

        pp = PublicParams.deserialize(pp_raw)
        deser = Deserializer(extra_owner_resolvers=[idemix_owner_resolver])
        return DriverBundle(
            label="zkatdlog", public_params=pp,
            services=ZkDlogDriverService(pp, device=device),
            validator=zkatdlog.new_validator(pp, deser, device=device),
            deserializer=deser)

    reg.register("fabtoken", _fabtoken)
    reg.register("zkatdlog", _zkatdlog)
    return reg


class TMSProvider:
    """Lazy TMS directory (core/tms.go:63-120).

    Public parameters resolve in the reference's order (tms.go:207-274):
    explicit opts -> the provider's storage -> the registered fetcher
    (e.g. read from the ledger's setup key).
    """

    def __init__(self, registry: DriverRegistry,
                 fetcher: Callable[[TMSID], bytes | None] | None = None):
        self.registry = registry
        self.fetcher = fetcher
        self._storage: dict[TMSID, bytes] = {}
        self._services: dict[TMSID, object] = {}

    def store_public_params(self, tmsid: TMSID, pp_raw: bytes) -> None:
        self._storage[tmsid] = pp_raw

    def _load_public_params(self, tmsid: TMSID,
                            pp_raw: bytes | None) -> bytes:
        if pp_raw is not None:                  # 1. explicit opts
            return pp_raw
        if tmsid in self._storage:              # 2. storage
            return self._storage[tmsid]
        if self.fetcher is not None:            # 3. fetcher
            fetched = self.fetcher(tmsid)
            if fetched is not None:
                self._storage[tmsid] = fetched
                return fetched
        raise RegistryError(
            f"cannot resolve public parameters for TMS [{tmsid}]")

    def get_management_service(self, tmsid: TMSID, pp_raw: bytes = None):
        """GetTokenManagerService (tms.go:63): one TMS per TMSID, lazily."""
        if tmsid not in self._services:
            from ..token.tms import TokenManagementService

            raw = self._load_public_params(tmsid, pp_raw)
            bundle = self.registry.new_bundle(raw)
            self._services[tmsid] = TokenManagementService(tmsid, bundle)
        return self._services[tmsid]

    def update(self, tmsid: TMSID, pp_raw: bytes) -> None:
        """Live public-params update (tms.go:117 Update): replace the
        stored pp and drop the cached TMS so the next access rebuilds."""
        self._storage[tmsid] = pp_raw
        self._services.pop(tmsid, None)
