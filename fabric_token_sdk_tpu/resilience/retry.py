"""Shared retry policy: error classification + seeded decorrelated jitter.

One policy object answers the three questions every retry loop in the
codebase used to answer ad-hoc (selector sleep-backoff, certifier bounded
retry, custodian broadcast attempts, and now the serve/ device dispatch):

  - *is this error worth retrying?* — ``is_transient`` classifies by
    exception type: anything deriving from :class:`TransientError` (the
    base the fault injector and watchdog raise), plus the stdlib
    transient families (``ConnectionError``, ``TimeoutError``) and
    runtime errors whose type name marks a device/runtime hiccup
    (``XlaRuntimeError`` — jaxlib raises these for RESOURCE_EXHAUSTED /
    transient dispatch failures). Everything else is permanent: retrying
    a proof that deterministically fails verification only burns time.
  - *how long to wait?* — decorrelated jitter
    (``sleep = min(cap, uniform(base, prev * 3))``), drawn from a seeded
    ``random.Random`` so a bench or test replays the identical backoff
    schedule run-over-run. Jitter decorrelates retry storms across
    callers; the seed keeps each caller deterministic.
  - *how do retries show up?* — every pause increments
    ``resil_retries_total{op=...}`` and runs inside a ``resil.retry``
    span, so uniform backoff behaviour is also uniformly observable.
"""

from __future__ import annotations

import random
import time

from ..obs import GLOBAL as _METRICS
from ..obs import TRACER as _TRACER


class TransientError(RuntimeError):
    """Base class for errors that are worth retrying by construction
    (injected transient faults, watchdog-abandoned dispatches)."""


class RetryExhausted(RuntimeError):
    """Every attempt failed with a transient error.

    Carries ``last_error`` and ``attempts`` so callers can reformat the
    failure in their own domain vocabulary (the custodian's
    ``broadcast ... failed after N attempts`` message, the certifier's
    ``certification request failed`` one).
    """

    def __init__(self, msg: str, last_error: Exception | None,
                 attempts: int):
        super().__init__(msg)
        self.last_error = last_error
        self.attempts = attempts


#: Exception types retried by default. Type NAMES are matched too (see
#: ``is_transient``) so jaxlib's XlaRuntimeError is covered without
#: importing jaxlib here.
TRANSIENT_TYPES: tuple = (TransientError, ConnectionError, TimeoutError)

#: Runtime-error type names treated as transient device hiccups.
_TRANSIENT_TYPE_NAMES = frozenset({"XlaRuntimeError"})


class RetryPolicy:
    """Bounded retry with deterministic decorrelated-jitter backoff.

    Exception-driven loops use :meth:`call`; manual loops (the selector's
    "not enough unlocked tokens yet" retry, the serve dispatcher's async
    loop) consume :meth:`delays` and report each wait via :meth:`pause`
    (or an ``asyncio.sleep`` of their own, counting the retry
    themselves). Two policies built with the same parameters and seed
    produce the same delay sequence — the determinism contract the chaos
    bench and the state-machine tests rely on.
    """

    def __init__(self, max_attempts: int = 3, base_s: float = 0.01,
                 cap_s: float = 1.0, seed: int = 0,
                 transient_types: tuple = TRANSIENT_TYPES,
                 op: str = "retry"):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = max_attempts
        self.base_s = base_s
        self.cap_s = cap_s
        self.seed = seed
        self.transient_types = transient_types
        self.op = op
        self._rng = random.Random(seed)

    # -------------------------------------------------------- classification
    def is_transient(self, exc: BaseException) -> bool:
        """Transient (retry) vs permanent (surface immediately)."""
        if isinstance(exc, self.transient_types):
            return True
        return type(exc).__name__ in _TRANSIENT_TYPE_NAMES

    # ------------------------------------------------------------- schedule
    def delays(self):
        """Infinite generator of backoff sleeps (seconds), decorrelated
        jitter: ``min(cap, uniform(base, prev * 3))``. Consumes this
        policy's seeded RNG, so the sequence is deterministic per
        instance."""
        prev = self.base_s
        while True:
            prev = min(self.cap_s, self._rng.uniform(self.base_s,
                                                     max(self.base_s,
                                                         prev * 3)))
            yield prev

    def pause(self, delay_s: float, op: str | None = None,
              sleep=time.sleep, parent=None) -> None:
        """One observable retry wait: counter + span + sleep.

        ``parent`` explicitly parents the ``resil.retry`` span (the serve
        dispatcher attaches retries under its batch span, which lives
        outside the contextvar chain)."""
        op = op or self.op
        _METRICS.counter(
            "resil_retries_total",
            help="Retry waits taken, by logical operation",
            op=op).add()
        with _TRACER.span("resil.retry", parent=parent, op=op,
                          sleep_s=round(delay_s, 6)):
            if delay_s > 0:
                sleep(delay_s)

    # ----------------------------------------------------------------- call
    def call(self, fn, *, op: str | None = None, classify=None,
             sleep=time.sleep):
        """Run ``fn()`` with bounded retry on transient errors.

        Permanent errors (per ``classify``, default :meth:`is_transient`)
        propagate unchanged on the attempt that raised them; transient
        exhaustion raises :class:`RetryExhausted` wrapping the last
        error.
        """
        op = op or self.op
        classify = classify or self.is_transient
        delays = self.delays()
        last: Exception | None = None
        for attempt in range(self.max_attempts):
            try:
                return fn()
            except Exception as exc:  # noqa: BLE001 — classified below
                if not classify(exc):
                    raise
                last = exc
                if attempt + 1 < self.max_attempts:
                    self.pause(next(delays), op=op, sleep=sleep)
        raise RetryExhausted(
            f"{op} failed after {self.max_attempts} attempts: {last}",
            last_error=last, attempts=self.max_attempts) from last
