"""Circuit breaker over the device dispatch path (closed/open/half-open).

Retry absorbs *isolated* transient failures; the breaker handles the
*correlated* ones — a device that has started failing most calls. Retrying
into a sick backend multiplies load exactly when the backend can least
absorb it and adds a full retry-budget of latency to every batch, so once
the failure rate over a sliding outcome window crosses the threshold the
breaker OPENS and the dispatcher routes straight to the host fallback
(bit-identical, slower, never wrong). After ``reset_timeout_s`` the
breaker goes HALF-OPEN and admits a bounded number of probe calls: enough
consecutive probe successes close it again, a single probe failure snaps
it back open.

The state machine is pure logic (injectable clock) so the transition
tests run without sleeping; state changes are observable via the
``resil_breaker_state`` gauge (0=closed, 1=half-open, 2=open) and the
``resil_breaker_transitions_total{to=...}`` counter.
"""

from __future__ import annotations

import time
from collections import deque

from ..obs import GLOBAL as _METRICS
from ..obs.journal import EVENT_BREAKER_TRANSITION, JOURNAL

STATE_CLOSED = "closed"
STATE_OPEN = "open"
STATE_HALF_OPEN = "half_open"

#: Gauge encoding of the state (dashboard-friendly ordering: higher is
#: further from healthy).
_STATE_VALUE = {STATE_CLOSED: 0, STATE_HALF_OPEN: 1, STATE_OPEN: 2}


class CircuitBreaker:
    """Failure-rate breaker with half-open probe accounting.

    - CLOSED: every call allowed; outcomes land in a sliding window of
      the last ``window`` results. With at least ``min_volume`` outcomes
      recorded and a failure rate >= ``failure_threshold`` -> OPEN.
    - OPEN: every call refused until ``reset_timeout_s`` has elapsed
      since opening, then -> HALF-OPEN.
    - HALF-OPEN: up to ``half_open_probes`` calls admitted concurrently;
      ``half_open_probes`` successes -> CLOSED (window cleared), any
      failure -> OPEN (timer restarts).

    ``force_open()`` latches the breaker open until ``force_close()`` —
    the operational kill switch (and the chaos bench's
    all-traffic-to-host mode).
    """

    def __init__(self, window: int = 64, failure_threshold: float = 0.5,
                 min_volume: int = 8, reset_timeout_s: float = 5.0,
                 half_open_probes: int = 2, clock=time.monotonic,
                 name: str = "device"):
        self.window = window
        self.failure_threshold = failure_threshold
        self.min_volume = min_volume
        self.reset_timeout_s = reset_timeout_s
        self.half_open_probes = max(1, half_open_probes)
        self.clock = clock
        self.name = name
        self.state = STATE_CLOSED
        self._events: deque = deque(maxlen=window)  # True == failure
        self._opened_at: float | None = None
        self._probes_inflight = 0
        self._probe_successes = 0
        self._forced_open = False
        self._publish()

    # ------------------------------------------------------------- plumbing
    def _publish(self) -> None:
        _METRICS.gauge(
            "resil_breaker_state",
            help="Circuit-breaker state (0=closed, 1=half-open, 2=open)",
            breaker=self.name).set(_STATE_VALUE[self.state])

    def _transition(self, state: str) -> None:
        if state == self.state:
            return
        prev, self.state = self.state, state
        _METRICS.counter(
            "resil_breaker_transitions_total",
            help="Circuit-breaker state transitions, by target state",
            breaker=self.name, to=state).add()
        self._publish()
        JOURNAL.record(EVENT_BREAKER_TRANSITION, breaker=self.name,
                       src=prev, dst=state, forced=self._forced_open,
                       failure_rate=round(self.failure_rate, 4))

    @property
    def failure_rate(self) -> float:
        if not self._events:
            return 0.0
        return sum(self._events) / len(self._events)

    # ------------------------------------------------------------ decisions
    def allow(self) -> bool:
        """May the caller attempt a device call right now?

        In HALF-OPEN this *claims* a probe slot: pair every ``allow() ==
        True`` with exactly one ``record_success``/``record_failure``.
        """
        if self._forced_open:
            return False
        if self.state == STATE_OPEN:
            if (self._opened_at is not None
                    and self.clock() - self._opened_at
                    >= self.reset_timeout_s):
                self._probes_inflight = 0
                self._probe_successes = 0
                self._transition(STATE_HALF_OPEN)
            else:
                return False
        if self.state == STATE_HALF_OPEN:
            if self._probes_inflight >= self.half_open_probes:
                return False
            self._probes_inflight += 1
            return True
        return True

    def record_success(self) -> None:
        if self.state == STATE_HALF_OPEN:
            self._probes_inflight = max(0, self._probes_inflight - 1)
            self._probe_successes += 1
            if self._probe_successes >= self.half_open_probes:
                self._events.clear()
                self._transition(STATE_CLOSED)
            return
        self._events.append(False)

    def record_failure(self) -> None:
        if self.state == STATE_HALF_OPEN:
            self._probes_inflight = max(0, self._probes_inflight - 1)
            self._open()
            return
        self._events.append(True)
        if (self.state == STATE_CLOSED
                and len(self._events) >= self.min_volume
                and self.failure_rate >= self.failure_threshold):
            self._open()

    def _open(self) -> None:
        self._opened_at = self.clock()
        self._transition(STATE_OPEN)

    # ------------------------------------------------------------ overrides
    def force_open(self) -> None:
        """Latch open (kill switch): every call refused until
        ``force_close``. Used by ops and by the chaos bench's
        all-host-fallback phase."""
        self._forced_open = True
        self._opened_at = self.clock()
        self._transition(STATE_OPEN)
        JOURNAL.incident(
            "breaker_force_open",
            reason=f"breaker {self.name!r} latched open "
                   f"(failure_rate={self.failure_rate:.3f})")

    def force_close(self) -> None:
        self._forced_open = False
        self._events.clear()
        self._probes_inflight = 0
        self._probe_successes = 0
        self._transition(STATE_CLOSED)
