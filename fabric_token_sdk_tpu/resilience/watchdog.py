"""Watchdog over the blocking device dispatch: abandon hung calls.

The serve/ dispatcher funnels every device call through one executor
thread. A device dispatch that *hangs* (runtime deadlock, collective
stuck waiting for a peer, driver wedge) would therefore freeze the whole
dispatcher: the event loop sits in ``await run_in_executor(...)`` forever
and every queued request misses its deadline with no terminal status.

``DispatchWatchdog`` owns that executor and bounds the wait: past
``timeout_s`` the future is abandoned, the executor is REPLACED with a
fresh single thread (the hung thread cannot be killed — Python offers no
thread cancellation — so it is orphaned and its eventual result, if any,
is discarded), ``resil_watchdog_trips_total`` counts the trip, and
:class:`WatchdogTimeout` (a :class:`TransientError`) surfaces to the
retry/fallback machinery. The dispatcher stays live; the batch gets
retried on the fresh thread or falls back to the host path.
"""

from __future__ import annotations

import asyncio
from concurrent.futures import ThreadPoolExecutor

from ..obs import GLOBAL as _METRICS
from ..obs.journal import EVENT_WATCHDOG_ABANDON, JOURNAL
from .retry import TransientError


class WatchdogTimeout(TransientError):
    """A device dispatch exceeded the watchdog budget and was abandoned."""


class DispatchWatchdog:
    """Single-thread dispatch executor with a hang budget.

    ``timeout_s=None`` disables the watchdog (plain awaited executor
    call — the pre-resilience behaviour). The executor is always
    accessed through :attr:`executor` because a trip swaps it out.
    """

    def __init__(self, timeout_s: float | None = None,
                 thread_name_prefix: str = "serve-dispatch"):
        self.timeout_s = timeout_s
        self.trips = 0
        self._prefix = thread_name_prefix
        self._executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix=thread_name_prefix)

    @property
    def executor(self) -> ThreadPoolExecutor:
        return self._executor

    async def run(self, fn, *args):
        """Run ``fn(*args)`` on the dispatch thread, bounded by
        ``timeout_s``. Raises :class:`WatchdogTimeout` on a trip."""
        loop = asyncio.get_running_loop()
        fut = loop.run_in_executor(self._executor, fn, *args)
        if self.timeout_s is None:
            return await fut
        try:
            return await asyncio.wait_for(fut, self.timeout_s)
        except asyncio.TimeoutError:
            self._abandon()
            raise WatchdogTimeout(
                f"device dispatch exceeded {self.timeout_s}s and was "
                "abandoned (fresh dispatch thread started)") from None

    def _abandon(self) -> None:
        self.trips += 1
        _METRICS.counter(
            "resil_watchdog_trips_total",
            help="Hung device dispatches abandoned by the watchdog").add()
        JOURNAL.record(EVENT_WATCHDOG_ABANDON, timeout_s=self.timeout_s,
                       trips=self.trips)
        # Snapshot BEFORE the executor swap: the wedged thread's stack
        # (and its open serve.dispatch span) are the incident's payload.
        JOURNAL.incident(
            "watchdog_abandon",
            reason=f"device dispatch exceeded {self.timeout_s}s "
                   f"(trip #{self.trips})")
        # The hung thread is unkillable; orphan it and start fresh so the
        # next dispatch does not queue behind the wedge.
        self._executor.shutdown(wait=False)
        self._executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix=self._prefix)

    def shutdown(self, wait: bool = True) -> None:
        self._executor.shutdown(wait=wait)
