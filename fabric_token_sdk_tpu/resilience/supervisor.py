"""Process supervisor: restart-with-backoff over child processes.

Generalizes the multichip dryrun's monitor loop (parallel/dryrun.py) —
"watch one worker's heartbeat file, kill it when it wedges" — into a
reusable supervision tree node: N children (NWO node processes, the
sidecar verification worker), each with an exit watch and an optional
heartbeat-stall watch, restarted through an escalation ladder:

  1. **restart** — respawn with seeded decorrelated-jitter backoff
     (:meth:`RetryPolicy.delays`, so a crash-looping child backs off
     deterministically per seed instead of hot-spinning);
  2. **cold restart** — after ``cold_after`` failures without a stable
     interval, the restart context carries ``cold=True`` so the spawn
     callable can clear warm state (persistent compile / table caches)
     in case the warm state itself is what keeps killing the child;
  3. **give up** — after ``give_up_after`` failures, stop restarting,
     write an incident snapshot (obs/journal.py) and notify
     ``on_give_up``: a supervisor that flaps forever is an outage
     generator, not a remedy.

``stable_reset_s`` of uninterrupted uptime clears the ladder, so one
bad hour a week does not creep a child toward give-up.

Failure detection is edge-driven per :meth:`poll` pass (a daemon thread
calls it; tests drive it with a fake clock and fake handles):

  - *exit*: the handle reports not-alive — the exit code lands in the
    journal and on ``crash_failures_total{cause="exit"}``;
  - *stall*: the child's heartbeat file (obs/heartbeat.py, written by
    the child, read here via :class:`FileHeartbeatReader`) is older
    than its phase deadline — the wedged process is poked with SIGUSR1
    (a cooperative child dumps stacks), then terminate, then kill,
    exactly the dryrun ladder.

RTO accounting: detection instant -> the restarted child's first fresh
heartbeat (stamped by the NEW pid), or the respawn instant for children
without heartbeat files — exported as ``crash_rto_seconds{child}``.

Stable families: ``crash_failures_total{child,cause}``,
``crash_restarts_total{child,rung}``,
``crash_escalations_total{child,rung}``, ``crash_rto_seconds{child}``,
``crash_child_up{child}``, ``crash_injected_signals_total{signal}``
(the bench kill schedule reports through the same family block).
"""

from __future__ import annotations

import os
import random
import signal
import threading
import time
from dataclasses import dataclass, field

from ..obs import GLOBAL as _METRICS
from ..obs.heartbeat import FileHeartbeatReader, StallDetector, read_last
from ..obs.journal import (EVENT_CHILD_FAILURE, EVENT_CHILD_RESTART,
                           JOURNAL)
from .retry import RetryPolicy

_CRASH_FAMILIES = {
    "crash_failures_total":
        "Supervised child failures detected, by child and cause "
        "(exit / stall / spawn_error).",
    "crash_restarts_total":
        "Supervised child restarts performed, by child and ladder rung "
        "(restart / cold_restart).",
    "crash_escalations_total":
        "Escalation-ladder advances, by child and rung reached "
        "(cold_restart / give_up).",
    "crash_rto_seconds":
        "Recovery time objective per restart: failure detection until "
        "the restarted child's first fresh heartbeat (or respawn "
        "completion without one), by child.",
    "crash_child_up":
        "1 while the supervised child process is believed alive, else "
        "0, by child.",
    "crash_injected_signals_total":
        "Kill-schedule signals injected by the crash bench, by signal.",
}

#: Escalation-ladder rungs.
RUNG_RESTART = "restart"
RUNG_COLD_RESTART = "cold_restart"
RUNG_GIVE_UP = "give_up"

#: Env knobs a cold restart should clear before spawning, so the child
#: rebuilds its warm state from scratch (the caches themselves may be
#: what keeps killing it).
COLD_CACHE_ENV = ("FTS_TABLE_CACHE_DIR", "BENCH_COMPILE_CACHE_DIR",
                  "JAX_CACHE_DIR")

_STATE_RUNNING = "running"
_STATE_BACKOFF = "backoff"
_STATE_FAILED = "failed"
_STATE_STOPPED = "stopped"


@dataclass(frozen=True)
class SupervisorPolicy:
    """Backoff + escalation policy shared by every child.

    ``cold_after``: failures (without a stable interval) after which
    restarts become cold; ``give_up_after``: failures after which the
    supervisor stops restarting. ``seed`` keys the per-child backoff
    RNG — two supervisors with the same policy replay the same
    schedules (the chaos/crash-bench determinism contract).
    """

    backoff_base_s: float = 0.05
    backoff_cap_s: float = 2.0
    seed: int = 0
    cold_after: int = 3
    give_up_after: int = 6
    stable_reset_s: float = 30.0


@dataclass(frozen=True)
class RestartContext:
    """What a ``ChildSpec.start`` callable learns about the restart."""

    child: str
    failures: int = 0
    rung: str = RUNG_RESTART
    cold: bool = False


@dataclass
class ChildSpec:
    """One supervised child.

    ``start(ctx: RestartContext) -> handle`` spawns (or respawns) the
    child and returns a process handle — ``multiprocessing.Process``
    or ``subprocess.Popen``, duck-typed (alive / exitcode / terminate /
    kill / pid). ``heartbeat_file`` additionally arms a stall watch
    with per-phase ``deadlines`` (obs.heartbeat semantics).
    """

    name: str
    start: object
    heartbeat_file: str | None = None
    deadlines: dict = field(default_factory=dict)
    default_deadline_s: float = 120.0
    grace_s: float = 60.0
    on_give_up: object = None


# ----------------------------------------------------------- handle ops
def _alive(handle) -> bool:
    if handle is None:
        return False
    if hasattr(handle, "is_alive"):
        return bool(handle.is_alive())
    # io-deadline: Popen.poll() is non-blocking (returns immediately)
    return handle.poll() is None  # subprocess.Popen


def _exitcode(handle):
    if handle is None:
        return None
    if hasattr(handle, "exitcode"):
        return handle.exitcode
    return handle.returncode


def _join(handle, timeout_s: float) -> None:
    try:
        if hasattr(handle, "join"):
            handle.join(timeout=timeout_s)
        else:
            handle.wait(timeout=timeout_s)
    except Exception:  # noqa: BLE001 — a join that raises is a dead child
        pass


class _Child:
    """Mutable supervision state for one ChildSpec."""

    def __init__(self, spec: ChildSpec, delays):
        self.spec = spec
        self.handle = None
        self.state = _STATE_STOPPED
        self.failures = 0
        self.restarts = 0
        self.rung = RUNG_RESTART
        self.delays = delays          # seeded backoff generator
        self.restart_at: float | None = None
        self.started_t: float | None = None
        self.detect_t: float | None = None   # failure detection instant
        self.last_exitcode = None
        self.last_cause = ""
        self.detector: StallDetector | None = None


class Supervisor:
    """Restart-with-escalation over a set of child processes.

    Lifecycle::

        sup = Supervisor([ChildSpec("worker", start=spawn_fn, ...)])
        sup.start()              # spawns unspawned children + monitor
        ...
        sup.stop()               # stops monitoring (children keep running
                                 # unless terminate_children=True)

    Already-running children register with :meth:`add_child`
    (``handle=...``) — the Platform wires its node processes in this
    way. :meth:`poll` is one synchronous detection/restart pass, the
    fake-clock test surface.
    """

    def __init__(self, specs=(), policy: SupervisorPolicy | None = None,
                 provider=None, journal=None, clock=time.time,
                 poll_s: float = 0.2):
        self.policy = policy or SupervisorPolicy()
        self.provider = provider or _METRICS
        self.journal = journal if journal is not None else JOURNAL
        self.clock = clock
        self.poll_s = poll_s
        for fam, help_text in _CRASH_FAMILIES.items():
            self.provider.describe(fam, help_text)
        self._children: dict[str, _Child] = {}
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._started = False
        for spec in specs:
            self.add_child(spec)

    # ------------------------------------------------------------ wiring
    def add_child(self, spec: ChildSpec, handle=None) -> None:
        """Register a child; ``handle`` adopts an already-running
        process (it is watched and restarted like any other)."""
        with self._lock:
            index = len(self._children)
            # decorrelated-jitter schedule, deterministic per (policy
            # seed, registration order)
            policy = RetryPolicy(
                max_attempts=2, base_s=self.policy.backoff_base_s,
                cap_s=self.policy.backoff_cap_s,
                seed=self.policy.seed * 1000003 + index,
                op=f"supervise_{spec.name}")
            child = _Child(spec, policy.delays())
            self._children[spec.name] = child
            if handle is not None:
                self._adopt(child, handle, self.clock())
            elif self._started:
                self._spawn(child, self.clock())

    def _new_detector(self, spec: ChildSpec) -> StallDetector | None:
        if not spec.heartbeat_file:
            return None
        return StallDetector(
            FileHeartbeatReader(spec.heartbeat_file),
            deadlines=dict(spec.deadlines),
            default_deadline_s=spec.default_deadline_s,
            grace_s=spec.grace_s, provider=self.provider,
            clock=self.clock)

    def _adopt(self, child: _Child, handle, now: float) -> None:
        child.handle = handle
        child.state = _STATE_RUNNING
        child.started_t = now
        child.detector = self._new_detector(child.spec)
        self.provider.gauge("crash_child_up",
                            child=child.spec.name).set(1)

    # --------------------------------------------------------- lifecycle
    def start(self) -> "Supervisor":
        """Spawn every unspawned child, then monitor on a daemon
        thread."""
        now = self.clock()
        with self._lock:
            self._started = True
            for child in self._children.values():
                if child.state == _STATE_STOPPED and child.handle is None:
                    self._spawn(child, now)
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="fts-supervisor", daemon=True)
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                # io-deadline: one non-blocking supervision scan
                self.poll()
            except Exception:  # noqa: BLE001 — monitor must survive
                pass

    def stop(self, terminate_children: bool = False,
             timeout_s: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)
            self._thread = None
        if terminate_children:
            with self._lock:
                children = list(self._children.values())
            for child in children:
                if _alive(child.handle):
                    self._kill_handle(child.handle, grace_s=timeout_s)
                child.state = _STATE_STOPPED
                self.provider.gauge("crash_child_up",
                                    child=child.spec.name).set(0)

    # ----------------------------------------------------------- polling
    def poll(self, now: float | None = None) -> None:
        """One detection/restart pass over every child."""
        now = self.clock() if now is None else now
        with self._lock:
            children = list(self._children.values())
        for child in children:
            if child.state == _STATE_RUNNING:
                self._poll_running(child, now)
            elif child.state == _STATE_BACKOFF \
                    and child.restart_at is not None \
                    and now >= child.restart_at:
                self._spawn(child, now)

    def _poll_running(self, child: _Child, now: float) -> None:
        if not _alive(child.handle):
            self._on_failure(child, now, cause="exit",
                             exitcode=_exitcode(child.handle))
            return
        if child.detector is not None:
            fired = child.detector.check()
            if fired is not None:
                phase, age = fired
                # the wedged process still holds the port/queues: take
                # it down (SIGUSR1 poke -> terminate -> kill, the
                # dryrun ladder) before scheduling the restart
                self._kill_handle(child.handle, grace_s=2.0, poke=True)
                self._on_failure(child, now, cause="stall",
                                 detail=f"phase {phase!r} "
                                        f"heartbeat {age:.1f}s old")
                return
        if child.detect_t is not None and self._came_back(child):
            rto = max(0.0, now - child.detect_t)
            self.provider.histogram(
                "crash_rto_seconds",
                child=child.spec.name).observe(round(rto, 6))
            child.detect_t = None
        if child.failures and child.started_t is not None \
                and child.detect_t is None \
                and now - child.started_t >= self.policy.stable_reset_s:
            child.failures = 0        # stable uptime clears the ladder
            child.rung = RUNG_RESTART

    def _came_back(self, child: _Child) -> bool:
        """Recovery point for RTO: a fresh heartbeat from the NEW pid,
        or mere liveness for children without heartbeat files."""
        if not child.spec.heartbeat_file:
            return True
        stamp = read_last(child.spec.heartbeat_file)
        if stamp is None:
            return False
        pid = getattr(child.handle, "pid", None)
        return pid is not None and stamp.get("pid") == pid

    # ---------------------------------------------------------- failures
    def _on_failure(self, child: _Child, now: float, cause: str,
                    exitcode=None, detail: str = "") -> None:
        name = child.spec.name
        child.failures += 1
        child.last_exitcode = exitcode
        child.last_cause = cause
        if child.detect_t is None:
            child.detect_t = now      # RTO clock starts at detection
        self.provider.counter("crash_failures_total", child=name,
                              cause=cause).add()
        self.provider.gauge("crash_child_up", child=name).set(0)
        self.journal.record(EVENT_CHILD_FAILURE, child=name, cause=cause,
                            exitcode=exitcode, failures=child.failures,
                            detail=detail)
        prev_rung = child.rung
        if child.failures > self.policy.give_up_after:
            child.rung = RUNG_GIVE_UP
        elif child.failures > self.policy.cold_after:
            child.rung = RUNG_COLD_RESTART
        if child.rung != prev_rung:
            self.provider.counter("crash_escalations_total", child=name,
                                  rung=child.rung).add()
        if child.rung == RUNG_GIVE_UP:
            child.state = _STATE_FAILED
            child.restart_at = None
            self.journal.incident(
                "supervisor_give_up",
                reason=f"child {name!r} failed {child.failures}x "
                       f"(last cause: {cause})",
                extra={"child": name, "exitcode": exitcode,
                       "failures": child.failures})
            if child.spec.on_give_up is not None:
                try:
                    child.spec.on_give_up(name, child.failures)
                except Exception:  # noqa: BLE001 — callback isolation
                    pass
            return
        child.state = _STATE_BACKOFF
        child.restart_at = now + next(child.delays)

    def _spawn(self, child: _Child, now: float) -> None:
        name = child.spec.name
        cold = child.rung == RUNG_COLD_RESTART
        ctx = RestartContext(child=name, failures=child.failures,
                             rung=child.rung, cold=cold)
        saved = {}
        if cold:
            for key in COLD_CACHE_ENV:
                if key in os.environ:
                    saved[key] = os.environ.pop(key)
        try:
            handle = child.spec.start(ctx)
        except Exception as exc:  # noqa: BLE001 — a spawn that raises
            # is just the next failure on the ladder
            self._on_failure(child, now, cause="spawn_error",
                             detail=f"{type(exc).__name__}: {exc}")
            return
        finally:
            os.environ.update(saved)
        self._adopt(child, handle, now)
        if child.failures:
            child.restarts += 1
            self.provider.counter("crash_restarts_total", child=name,
                                  rung=ctx.rung).add()
        self.journal.record(EVENT_CHILD_RESTART, child=name,
                            rung=ctx.rung, cold=cold,
                            failures=child.failures,
                            pid=getattr(handle, "pid", None))

    @staticmethod
    def _kill_handle(handle, grace_s: float = 2.0,
                     poke: bool = False) -> None:
        pid = getattr(handle, "pid", None)
        if poke and pid is not None and hasattr(signal, "SIGUSR1"):
            try:  # cooperative children dump stacks on SIGUSR1
                os.kill(pid, signal.SIGUSR1)
            except (OSError, ProcessLookupError):
                pass
        try:
            handle.terminate()
        except Exception:  # noqa: BLE001
            pass
        _join(handle, grace_s)
        if _alive(handle) and hasattr(handle, "kill"):
            try:
                handle.kill()
            except Exception:  # noqa: BLE001
                pass
            _join(handle, grace_s)

    # ------------------------------------------------------------ status
    def status(self) -> dict:
        """JSON-serializable snapshot for /statusz and incidents."""
        with self._lock:
            return {name: {
                "state": child.state,
                "alive": _alive(child.handle),
                "pid": getattr(child.handle, "pid", None),
                "failures": child.failures,
                "restarts": child.restarts,
                "rung": child.rung,
                "last_cause": child.last_cause,
                "last_exitcode": child.last_exitcode,
            } for name, child in self._children.items()}


class KillSchedule:
    """Seeded schedule of SIGKILL/SIGSTOP injections against one pid —
    the fault source for ``BENCH_MODE=crash``.

    Offsets are drawn from ``random.Random(seed)`` over the middle of
    the load window (``[start_frac, end_frac] * duration_s``) so the
    schedule is replayable run-over-run. SIGSTOP is the stealth
    failure: the process stays "alive" but its heartbeat freezes, so
    recovery must come from the supervisor's stall watch (which
    SIGKILLs the stopped process — SIGTERM would stay queued and
    undelivered).
    """

    def __init__(self, seed: int, duration_s: float, kills: int = 2,
                 stops: int = 1, start_frac: float = 0.15,
                 end_frac: float = 0.85):
        rng = random.Random(seed)
        lo, hi = start_frac * duration_s, end_frac * duration_s
        self.events = sorted(
            [(rng.uniform(lo, hi), "SIGKILL") for _ in range(kills)]
            + [(rng.uniform(lo, hi), "SIGSTOP") for _ in range(stops)])
        self.delivered: list[tuple[float, str, int | None]] = []
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    def start(self, get_pid, provider=None,
              clock=time.monotonic) -> "KillSchedule":
        """Fire the schedule on a daemon thread; ``get_pid() -> int |
        None`` is read at each firing so restarts are targeted too."""
        provider = provider or _METRICS
        t0 = clock()

        def _run():
            for offset, signame in self.events:
                delay = offset - (clock() - t0)
                if delay > 0 and self._stop.wait(delay):
                    return
                pid = get_pid()
                if pid is None:
                    self.delivered.append((offset, signame, None))
                    continue
                try:
                    os.kill(pid, getattr(signal, signame))
                except (OSError, ProcessLookupError):
                    pid = None
                self.delivered.append((offset, signame, pid))
                provider.counter("crash_injected_signals_total",
                                 signal=signame).add()
                JOURNAL.record(EVENT_CHILD_FAILURE, child="kill_schedule",
                               cause="injected", detail=signame, pid=pid)

        self._thread = threading.Thread(
            target=_run, name="fts-kill-schedule", daemon=True)
        self._thread.start()
        return self

    def join(self, timeout_s: float | None = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)

    def cancel(self) -> None:
        self._stop.set()
        self.join(timeout_s=1.0)
