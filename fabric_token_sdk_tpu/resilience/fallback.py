"""Host-path graceful degradation: bit-identical verdicts, no device.

When retries are exhausted or the circuit breaker is open, the serve/
dispatcher routes the SAME batch through the pure-host proof verifiers
(``crypto/rp.py`` range checks, ``crypto/transfer_proof.py`` /
``crypto/issue_proof.py`` action checks — the exact oracle the device
path already defers to on rejects). The host path is orders of magnitude
slower per proof, but it is the reference semantics itself: callers get
the same accept/reject vector a healthy device would have produced,
annotated ``served_by="host"`` instead of ``served_by="device"``.
Degradation trades throughput, never correctness.
"""

from __future__ import annotations

import numpy as np

from ..obs import GLOBAL as _METRICS

#: Request kinds understood by the fallback — string-identical to the
#: serve/ request kinds (serve imports resilience, so the constants are
#: mirrored here rather than imported).
KIND_RANGE = "range"
KIND_TRANSFER = "transfer"
KIND_ISSUE = "issue"


class HostFallbackVerifier:
    """Pure-host verification of serve/ batches.

    ``verify_batch`` accepts the same request list the device dispatch
    takes (objects with ``.kind`` and ``.payload``) and returns a bool
    verdict vector aligned with it — the same contract as the device
    path, so the dispatcher demultiplexes either result identically.
    """

    def __init__(self, pp):
        from ..core.zkatdlog.verifier import ZKVerifier

        self.pp = pp
        # device=False: verify_transfer/verify_issue collapse to the pure
        # host proof verifiers (transfer_verify / issue_verify)
        self._host_zk = ZKVerifier(pp, device=False)

    # ----------------------------------------------------------- primitives
    def verify_range_rows(self, proofs, commitments) -> np.ndarray:
        """Per-row host range verification (rp.range_verify semantics)."""
        from ..core.zkatdlog.verifier import host_range_verify
        from ..crypto.rp import ProofError

        out = np.zeros(len(proofs), dtype=bool)
        for i, (proof, com) in enumerate(zip(proofs, commitments)):
            try:
                host_range_verify(self.pp, proof, com)
                out[i] = True
            except ProofError:
                pass
        return out

    def verify_action(self, kind: str, payload: tuple) -> bool:
        """One transfer/issue action through the host verifier."""
        from ..crypto.rp import ProofError

        try:
            if kind == KIND_TRANSFER:
                raw, inputs, outputs = payload
                self._host_zk.verify_transfer(raw, inputs, outputs)
            elif kind == KIND_ISSUE:
                raw, commitments = payload
                self._host_zk.verify_issue(raw, commitments)
            else:
                raise ValueError(f"unknown action kind: {kind}")
            return True
        except ProofError:
            return False

    # ---------------------------------------------------------------- batch
    def verify_batch(self, batch) -> np.ndarray:
        """Verdict vector for a serve/ batch, bit-identical to the device
        path's accept/reject decisions."""
        rows = len(batch)
        _METRICS.counter(
            "resil_fallback_rows_total",
            help="Requests served by the host fallback path").add(rows)
        if batch and batch[0].kind == KIND_RANGE:
            return self.verify_range_rows(
                [r.payload[0] for r in batch],
                [r.payload[1] for r in batch])
        return np.asarray(
            [self.verify_action(r.kind, r.payload) for r in batch],
            dtype=bool)
