"""resilience/ — fault tolerance for the verification pipeline.

The serving promise (ROADMAP: "heavy traffic from millions of users")
includes the days the hardware misbehaves. This package makes failure a
first-class, *testable* behaviour instead of an unhandled exception:

  - :class:`FaultInjector` / :class:`FaultyZK` (faults.py): seeded,
    replayable fault schedules shimmed over the device entry points —
    transient/permanent errors, stalls, verdict corruption;
  - :class:`RetryPolicy` (retry.py): shared error classification +
    exponential backoff with seeded decorrelated jitter, used by the
    serve dispatcher AND the services-tier retry loops (selector,
    certifier, custodian broadcast);
  - :class:`CircuitBreaker` (breaker.py): closed/open/half-open over a
    failure-rate window, with half-open probe accounting;
  - :class:`HostFallbackVerifier` (fallback.py): routes a batch through
    the pure-host proof verifiers for bit-identical verdicts when the
    device path is exhausted or the breaker is open;
  - :class:`DispatchWatchdog` (watchdog.py): bounds the blocking device
    dispatch so a hung call is abandoned (fresh executor thread) and
    retried/fallen back instead of freezing the dispatcher;
  - :class:`Supervisor` (supervisor.py): restart-with-backoff over
    child *processes* (exit + heartbeat-stall detection, escalation
    ladder restart -> cold restart -> give-up + incident snapshot),
    reporting under the stable ``crash_*`` family — the layer that
    survives what the in-process layers cannot (SIGKILL).

Everything reports under the stable ``resil_*`` metric family
(``resil_retries_total``, ``resil_breaker_state``,
``resil_breaker_transitions_total``, ``resil_fallback_batches_total``,
``resil_fallback_rows_total``, ``resil_watchdog_trips_total``,
``resil_injected_faults_total``) plus ``resil.retry`` /
``resil.fallback`` spans. See README "Resilience".
"""

from __future__ import annotations

from dataclasses import dataclass

from .breaker import (STATE_CLOSED, STATE_HALF_OPEN, STATE_OPEN,
                      CircuitBreaker)
from .fallback import HostFallbackVerifier
from .faults import (ACTIONS, FaultInjector, FaultyZK,
                     InjectedPermanentError, InjectedTransientError)
from .retry import (TRANSIENT_TYPES, RetryExhausted, RetryPolicy,
                    TransientError)
from .supervisor import (RUNG_COLD_RESTART, RUNG_GIVE_UP, RUNG_RESTART,
                         ChildSpec, KillSchedule, RestartContext,
                         Supervisor, SupervisorPolicy)
from .watchdog import DispatchWatchdog, WatchdogTimeout


@dataclass(frozen=True)
class ResilienceConfig:
    """Declarative policy for the serve/ dispatcher's failure handling.

    retry_attempts / retry_base_s / retry_cap_s / seed: the shared
        :class:`RetryPolicy` over transient device errors (seeded
        decorrelated jitter — deterministic backoff schedules).
    breaker_*: the :class:`CircuitBreaker` window (failure rate over the
        last ``breaker_window`` outcomes, openable once
        ``breaker_min_volume`` outcomes exist), open-state dwell time,
        and half-open probe count.
    watchdog_timeout_s: hang budget for one blocking device dispatch;
        ``None`` disables the watchdog.
    fallback: route exhausted/broken-open batches through the pure-host
        verifiers (bit-identical verdicts, ``served_by="host"``) instead
        of failing them. Requires the backend to expose ``pp`` (or an
        explicit fallback verifier passed to the service).
    """

    retry_attempts: int = 3
    retry_base_s: float = 0.005
    retry_cap_s: float = 0.25
    seed: int = 0
    breaker_window: int = 64
    breaker_failure_threshold: float = 0.5
    breaker_min_volume: int = 8
    breaker_reset_s: float = 5.0
    breaker_half_open_probes: int = 2
    watchdog_timeout_s: float | None = 60.0
    fallback: bool = True

    def build_retry_policy(self, op: str = "serve_dispatch") -> RetryPolicy:
        return RetryPolicy(max_attempts=self.retry_attempts,
                           base_s=self.retry_base_s, cap_s=self.retry_cap_s,
                           seed=self.seed, op=op)

    def build_breaker(self, name: str = "device") -> CircuitBreaker:
        return CircuitBreaker(
            window=self.breaker_window,
            failure_threshold=self.breaker_failure_threshold,
            min_volume=self.breaker_min_volume,
            reset_timeout_s=self.breaker_reset_s,
            half_open_probes=self.breaker_half_open_probes,
            name=name)


__all__ = [
    "ACTIONS",
    "ChildSpec",
    "CircuitBreaker",
    "DispatchWatchdog",
    "KillSchedule",
    "RestartContext",
    "RUNG_COLD_RESTART",
    "RUNG_GIVE_UP",
    "RUNG_RESTART",
    "Supervisor",
    "SupervisorPolicy",
    "FaultInjector",
    "FaultyZK",
    "HostFallbackVerifier",
    "InjectedPermanentError",
    "InjectedTransientError",
    "ResilienceConfig",
    "RetryExhausted",
    "RetryPolicy",
    "STATE_CLOSED",
    "STATE_HALF_OPEN",
    "STATE_OPEN",
    "TRANSIENT_TYPES",
    "TransientError",
    "WatchdogTimeout",
]
