"""Deterministic fault injection over the device verification entry points.

A serving system cannot claim failure behaviour it has never executed.
``FaultInjector`` produces a *seeded, replayable* fault schedule and
``FaultyZK`` applies it as a shim over the two device entry points the
serve/ frontend dispatches to (``BatchRangeVerifier.verify`` via
``zk._range`` and ``ZKVerifier.verify_block``), without touching the real
verifier code:

  - ``transient``  — raise :class:`InjectedTransientError` before the
    call (a retryable hiccup: the next attempt may succeed);
  - ``permanent``  — raise :class:`InjectedPermanentError` (a
    non-retryable failure: classification must route it to fallback /
    error immediately, not burn the retry budget);
  - ``stall``      — sleep ``stall_s`` before the call (latency fault;
    with a watchdog configured, long stalls become abandoned dispatches);
  - ``corrupt``    — let the call run, then flip one seeded entry of the
    verdict vector (a lying device: the hazard the chaos bench's parity
    check exists to expose — nothing downstream can detect it, which is
    exactly the point).

Determinism contract: the schedule is a pure function of ``(seed, call
index)`` — exactly one RNG draw per call decides the action (corruption
indices come from an independent RNG so they never perturb the action
stream). Same seed, same call sequence -> same faults, so a chaos run is
replayable and a parity check against a fault-free run is meaningful.

Every injected fault counts in ``resil_injected_faults_total{kind}``.
"""

from __future__ import annotations

import random
import time

import numpy as np

from ..obs import GLOBAL as _METRICS
from .retry import TransientError


class InjectedTransientError(TransientError):
    """A scripted transient device failure (retry should absorb it)."""


class InjectedPermanentError(RuntimeError):
    """A scripted permanent device failure (never retried)."""


#: Action precedence when rates are given: the single uniform draw is
#: compared against the cumulative rate ladder in this order.
ACTIONS = ("transient", "permanent", "stall", "corrupt")


class FaultInjector:
    """Seeded fault schedule over an abstract sequence of device calls.

    Either give per-action rates (each call draws once and picks the
    action whose cumulative-probability band the draw lands in) or an
    explicit ``schedule`` mapping call index -> action name, which
    overrides the rates entirely (scripted scenarios: "fail calls 3..5,
    stall call 9").
    """

    def __init__(self, seed: int = 0, transient_rate: float = 0.0,
                 permanent_rate: float = 0.0, stall_rate: float = 0.0,
                 stall_s: float = 0.02, corrupt_rate: float = 0.0,
                 schedule: dict | None = None, sleep=time.sleep):
        rates = (transient_rate, permanent_rate, stall_rate, corrupt_rate)
        if any(r < 0 for r in rates) or sum(rates) > 1.0 + 1e-9:
            raise ValueError("fault rates must be >= 0 and sum to <= 1")
        self.seed = seed
        self.rates = dict(zip(ACTIONS, rates))
        self.stall_s = stall_s
        self.schedule = schedule
        self.calls = 0
        self.injected: dict[str, int] = {a: 0 for a in ACTIONS}
        self._sleep = sleep
        self._rng = random.Random(seed)
        # independent stream for corruption row picks: keeps the action
        # schedule a pure function of (seed, call index)
        self._corrupt_rng = random.Random((seed << 1) ^ 0x5EEDFA17)

    # ------------------------------------------------------------- schedule
    def next_action(self) -> str | None:
        """The scripted action for the next call (consumes one call
        index; exactly one RNG draw in rate mode)."""
        idx = self.calls
        self.calls += 1
        if self.schedule is not None:
            return self.schedule.get(idx)
        u = self._rng.random()
        edge = 0.0
        for action in ACTIONS:
            edge += self.rates[action]
            if u < edge:
                return action
        return None

    def fire(self, entry: str) -> str | None:
        """Apply the next scheduled action at device entry point
        ``entry``. Raises for error faults, sleeps for stalls, and
        returns ``"corrupt"`` when the caller must corrupt the verdict
        vector after the real call."""
        action = self.next_action()
        if action is None:
            return None
        self.injected[action] += 1
        _METRICS.counter(
            "resil_injected_faults_total",
            help="Faults injected into device entry points, by kind",
            kind=action, entry=entry).add()
        call_idx = self.calls - 1
        if action == "transient":
            raise InjectedTransientError(
                f"injected transient fault at {entry} (call {call_idx})")
        if action == "permanent":
            raise InjectedPermanentError(
                f"injected permanent fault at {entry} (call {call_idx})")
        if action == "stall":
            self._sleep(self.stall_s)
            return None
        return action  # "corrupt"

    def corrupt_verdicts(self, verdicts) -> np.ndarray:
        """Flip one seeded entry of a verdict vector (device lying)."""
        out = np.array(verdicts, dtype=bool).reshape(-1).copy()
        if out.size:
            out[self._corrupt_rng.randrange(out.size)] ^= True
        return out

    def wrap(self, zk) -> "FaultyZK":
        return FaultyZK(zk, self)


class _FaultyRange:
    """Shim over ``BatchRangeVerifier``: faults fire at ``verify``."""

    def __init__(self, inner, injector: FaultInjector):
        self._inner = inner
        self._injector = injector

    def verify(self, proofs, commitments, **kwargs):
        action = self._injector.fire("range.verify")
        out = self._inner.verify(proofs, commitments, **kwargs)
        if action == "corrupt":
            return self._injector.corrupt_verdicts(out)
        return out

    def __getattr__(self, name):
        return getattr(self._inner, name)


class FaultyZK:
    """Shim over ``ZKVerifier``: same surface, scripted faults at the
    device entry points. Prewarm and host-oracle paths pass through
    unfaulted (faults model the *device dispatch*, not startup compiles
    or host crypto)."""

    def __init__(self, zk, injector: FaultInjector):
        self._inner = zk
        self.injector = injector
        inner_range = getattr(zk, "_range", None)
        self._range = (None if inner_range is None
                       else _FaultyRange(inner_range, injector))

    def verify_block(self, transfers, issues):
        action = self.injector.fire("verify_block")
        t_ok, i_ok = self._inner.verify_block(transfers, issues)
        if action == "corrupt":
            # one flipped row across the block, action stream untouched
            if len(t_ok):
                t_ok = self.injector.corrupt_verdicts(t_ok)
            elif len(i_ok):
                i_ok = self.injector.corrupt_verdicts(i_ok)
        return t_ok, i_ok

    def __getattr__(self, name):
        return getattr(self._inner, name)
