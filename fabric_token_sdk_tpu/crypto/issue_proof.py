"""Host-side issue proof: same-type Σ-protocol + range correctness.

Behavioral mirror of:
  - reference token/core/zkatdlog/nogh/v1/crypto/issue/sametype.go
  - reference token/core/zkatdlog/nogh/v1/crypto/issue/{prover,verifier}.go
"""

from __future__ import annotations

from dataclasses import dataclass

from . import rp as rp_mod
from . import serialization as ser
from .bn254 import (
    G1,
    fr_add,
    fr_mul,
    fr_rand,
    fr_sub,
    g1_add,
    g1_mul,
    g1_neg,
    hash_to_zr,
)
from .rp import ProofError, RangeCorrectness


@dataclass
class SameTypeProof:
    """reference sametype.go:19-29."""

    type_: int = None
    blinding_factor: int = None
    challenge: int = None
    commitment_to_type: G1 = None

    def serialize(self) -> bytes:
        # reference sametype.go:32-39
        return ser.marshal_math(
            (ser.ZR_KIND, self.type_),
            (ser.ZR_KIND, self.blinding_factor),
            (ser.ZR_KIND, self.challenge),
            (ser.G1_KIND, self.commitment_to_type),
        )

    @classmethod
    def deserialize(cls, raw: bytes) -> "SameTypeProof":
        um = ser.MathUnmarshaller(raw)
        return cls(um.next_zr(), um.next_zr(), um.next_zr(), um.next_g1())


def same_type_prove(token_type: str, type_bf: int, commitment_to_type: G1,
                    ped_params: list[G1]) -> SameTypeProof:
    """reference sametype.go:103-148."""
    type_zr = hash_to_zr(token_type.encode())
    r_type = fr_rand()
    r_bf = fr_rand()
    commitment = g1_add(g1_mul(ped_params[0], r_type), g1_mul(ped_params[2], r_bf))
    chal = hash_to_zr(ser.g1_array_bytes([commitment_to_type, commitment]))
    return SameTypeProof(
        type_=fr_add(fr_mul(chal, type_zr), r_type),
        blinding_factor=fr_add(fr_mul(chal, type_bf), r_bf),
        challenge=chal,
        commitment_to_type=commitment_to_type,
    )


def same_type_verify(proof: SameTypeProof, ped_params: list[G1]) -> None:
    """reference sametype.go:167-183. Raises ProofError on rejection."""
    if (proof.type_ is None or proof.blinding_factor is None
            or proof.challenge is None or proof.commitment_to_type is None):
        raise ProofError("invalid same type proof")
    com = g1_add(g1_mul(ped_params[0], proof.type_),
                 g1_mul(ped_params[2], proof.blinding_factor))
    com = g1_add(com, g1_neg(g1_mul(proof.commitment_to_type, proof.challenge)))
    chal = hash_to_zr(ser.g1_array_bytes([proof.commitment_to_type, com]))
    if chal != proof.challenge:
        raise ProofError("invalid same type proof")


@dataclass
class IssueProof:
    same_type: SameTypeProof = None
    range_correctness: RangeCorrectness = None

    def serialize(self) -> bytes:
        # reference issue/prover.go:27-29
        return ser.marshal_serializers([
            self.same_type.serialize(),
            self.range_correctness.serialize() if self.range_correctness else None,
        ])

    @classmethod
    def deserialize(cls, raw: bytes) -> "IssueProof":
        parts = ser.unmarshal_serializers(raw, 2)
        st = SameTypeProof.deserialize(parts[0])
        rc = RangeCorrectness.deserialize(parts[1]) if parts[1] else RangeCorrectness()
        return cls(st, rc)


def issue_prove(witness: list[tuple[str, int, int]], tokens: list[G1], pp) -> bytes:
    """reference issue/prover.go:46-112. Witnesses are (type, value, bf)."""
    token_type = witness[0][0]
    type_zr = hash_to_zr(token_type.encode())
    type_bf = fr_rand()
    commitment_to_type = g1_add(g1_mul(pp.pedersen_generators[0], type_zr),
                                g1_mul(pp.pedersen_generators[2], type_bf))
    st = same_type_prove(token_type, type_bf, commitment_to_type,
                         pp.pedersen_generators)

    values = [w[1] for w in witness]
    bfs = [fr_sub(w[2], type_bf) for w in witness]
    coms = [g1_add(t, g1_neg(commitment_to_type)) for t in tokens]
    rpp = pp.range_proof_params
    rc = rp_mod.range_correctness_prove(
        coms, values, bfs, pp.pedersen_generators[1:],
        rpp.left_generators, rpp.right_generators, rpp.P, rpp.Q,
        rpp.bit_length, rpp.number_of_rounds)
    return IssueProof(same_type=st, range_correctness=rc).serialize()


def issue_verify(proof_raw: bytes, tokens: list[G1], pp) -> None:
    """reference issue/verifier.go:32-57. Raises ProofError on rejection."""
    try:
        proof = IssueProof.deserialize(proof_raw)
    except (ValueError, ProofError) as e:
        raise ProofError(f"invalid issue proof: {e}") from e
    try:
        same_type_verify(proof.same_type, pp.pedersen_generators)
    except ProofError as e:
        raise ProofError(f"invalid issue proof: {e}") from e
    coms = [g1_add(t, g1_neg(proof.same_type.commitment_to_type)) for t in tokens]
    rpp = pp.range_proof_params
    try:
        rp_mod.range_correctness_verify(
            proof.range_correctness, coms, pp.pedersen_generators[1:],
            rpp.left_generators, rpp.right_generators, rpp.P, rpp.Q,
            rpp.bit_length, rpp.number_of_rounds)
    except ProofError as e:
        raise ProofError(f"invalid issue proof: {e}") from e
