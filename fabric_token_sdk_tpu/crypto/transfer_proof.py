"""Host-side transfer proof: type-and-sum Σ-protocol + range correctness.

Behavioral mirror of:
  - reference token/core/zkatdlog/nogh/v1/crypto/transfer/typeandsum.go
  - reference token/core/zkatdlog/nogh/v1/crypto/transfer/transfer.go

A transfer proof shows (1) all inputs and outputs commit to one shared type,
(2) sum of input values equals sum of output values, and (3) every output
value lies in [0, 2^BitLength) — except for 1-in/1-out ownership transfers,
where the range part is skipped (transfer.go:53-57,101-112).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from . import bn254
from . import rp as rp_mod
from . import serialization as ser
from .bn254 import (
    G1,
    fr_add,
    fr_mul,
    fr_rand,
    fr_sub,
    g1_add,
    g1_mul,
    g1_neg,
    hash_to_zr,
)
from .rp import ProofError, RangeCorrectness, RangeProverDraws


@dataclass
class TypeAndSumDraws:
    """Every blinding draw `type_and_sum_prove` consumes (the same
    externally-generated-randomness seam as rp.RangeProverDraws: the TPU
    prover draws these host-side and synthesizes the Σ-protocol
    commitments/responses on device; same draws => identical proofs)."""

    r_type: int
    r_type_bf: int
    r_in_values: list[int]
    r_in_bfs: list[int]
    r_sum_bf: int

    @classmethod
    def random(cls, n_inputs: int) -> "TypeAndSumDraws":
        return cls(r_type=fr_rand(), r_type_bf=fr_rand(),
                   r_in_values=[fr_rand() for _ in range(n_inputs)],
                   r_in_bfs=[fr_rand() for _ in range(n_inputs)],
                   r_sum_bf=fr_rand())


@dataclass
class TransferDraws:
    """Draw record for a whole `transfer_prove`: the type blinding
    factor, the type-and-sum Σ draws, and one RangeProverDraws per
    output range proof (empty for the 1-in/1-out shape, which skips the
    range part)."""

    type_bf: int
    ts: TypeAndSumDraws
    ranges: list[RangeProverDraws]

    @classmethod
    def random(cls, n_inputs: int, n_outputs: int,
               bit_length: int) -> "TransferDraws":
        skip_range = n_inputs == 1 and n_outputs == 1
        return cls(
            type_bf=fr_rand(),
            ts=TypeAndSumDraws.random(n_inputs),
            ranges=[] if skip_range else [
                RangeProverDraws.random(bit_length)
                for _ in range(n_outputs)])


@dataclass
class TypeAndSumProof:
    """reference typeandsum.go:19-34."""

    commitment_to_type: G1 = None
    input_blinding_factors: list[int] = field(default_factory=list)
    input_values: list[int] = field(default_factory=list)
    type_: int = None
    type_blinding_factor: int = None
    equality_of_sum: int = None
    challenge: int = None

    def serialize(self) -> bytes:
        # reference typeandsum.go:37-55
        return ser.marshal_math(
            (ser.G1_KIND, self.commitment_to_type),
            (ser.ZR_ARRAY_KIND, self.input_blinding_factors),
            (ser.ZR_ARRAY_KIND, self.input_values),
            (ser.ZR_KIND, self.type_),
            (ser.ZR_KIND, self.type_blinding_factor),
            (ser.ZR_KIND, self.equality_of_sum),
            (ser.ZR_KIND, self.challenge),
        )

    @classmethod
    def deserialize(cls, raw: bytes) -> "TypeAndSumProof":
        um = ser.MathUnmarshaller(raw)
        return cls(um.next_g1(), um.next_zr_array(), um.next_zr_array(),
                   um.next_zr(), um.next_zr(), um.next_zr(), um.next_zr())


def _transcript_bytes(in_coms: list[G1], type_com: G1, sum_com: G1,
                      inputs: list[G1], outputs: list[G1],
                      commitment_to_type: G1, sum_: G1) -> bytes:
    """Challenge input ordering per typeandsum.go:214,267."""
    return ser.g1_array_bytes(
        list(in_coms) + [type_com, sum_com] + list(inputs) + list(outputs)
        + [commitment_to_type, sum_])


def type_and_sum_prove(ped_params: list[G1], inputs: list[G1], outputs: list[G1],
                       commitment_to_type: G1, in_values: list[int],
                       in_bfs: list[int], out_bfs: list[int], type_zr: int,
                       type_bf: int,
                       draws: TypeAndSumDraws | None = None) -> TypeAndSumProof:
    """reference typeandsum.go:189-227,280-356.

    `draws` pins the Σ-protocol randomness (TypeAndSumDraws); None keeps
    fresh draws. The challenge is HashToZr over the hex-"||" G1 array of
    [com_inputs.., com_type, com_sum, adj_in.., adj_out..,
    commitment_to_type, sum_] (_transcript_bytes, typeandsum.go:214,267)
    with adj_i = point - commitment_to_type and
    sum_ = sum(adj_in) - sum(adj_out).
    """
    # randomness + commitments (computeCommitments, typeandsum.go:319-356)
    if draws is None:
        draws = TypeAndSumDraws.random(len(inputs))
    r_type = draws.r_type
    r_type_bf = draws.r_type_bf
    com_type = g1_add(g1_mul(ped_params[0], r_type), g1_mul(ped_params[2], r_type_bf))
    r_in_values = list(draws.r_in_values)
    r_in_bfs = list(draws.r_in_bfs)
    com_inputs = [
        g1_add(g1_mul(ped_params[1], r_in_values[i]), g1_mul(ped_params[2], r_in_bfs[i]))
        for i in range(len(inputs))
    ]
    r_sum_bf = draws.r_sum_bf
    com_sum = g1_mul(ped_params[2], r_sum_bf)

    # adjusted statement (Prove, typeandsum.go:195-211)
    adj_inputs = []
    adj_outputs = []
    sum_ = bn254.G1_IDENTITY
    for pt in inputs:
        a = g1_add(pt, g1_neg(commitment_to_type))
        adj_inputs.append(a)
        sum_ = g1_add(sum_, a)
    for pt in outputs:
        a = g1_add(pt, g1_neg(commitment_to_type))
        adj_outputs.append(a)
        sum_ = g1_add(sum_, g1_neg(a))

    chal = hash_to_zr(_transcript_bytes(
        com_inputs, com_type, com_sum, adj_inputs, adj_outputs,
        commitment_to_type, sum_))

    # responses (computeProof, typeandsum.go:280-316)
    proof = TypeAndSumProof(commitment_to_type=commitment_to_type, challenge=chal)
    proof.type_ = fr_add(fr_mul(chal, type_zr), r_type)
    proof.type_blinding_factor = fr_add(fr_mul(chal, type_bf), r_type_bf)
    sum_bf = 0
    for i in range(len(inputs)):
        proof.input_values.append(fr_add(fr_mul(chal, in_values[i]), r_in_values[i]))
        t = fr_sub(in_bfs[i], type_bf)
        proof.input_blinding_factors.append(fr_add(fr_mul(chal, t), r_in_bfs[i]))
        sum_bf = fr_add(sum_bf, t)
    for i in range(len(outputs)):
        t = fr_sub(out_bfs[i], type_bf)
        sum_bf = fr_sub(sum_bf, t)
    proof.equality_of_sum = fr_add(fr_mul(chal, sum_bf), r_sum_bf)
    return proof


def type_and_sum_verify(proof: TypeAndSumProof, ped_params: list[G1],
                        inputs: list[G1], outputs: list[G1]) -> None:
    """reference typeandsum.go:230-277. Raises ProofError on rejection."""
    if (proof.type_blinding_factor is None or proof.type_ is None
            or proof.commitment_to_type is None or proof.equality_of_sum is None):
        raise ProofError("invalid sum and type proof")
    if len(proof.input_values) < len(inputs) or len(proof.input_blinding_factors) < len(inputs):
        raise ProofError("invalid sum and type proof")

    adj_inputs = []
    adj_outputs = []
    sum_ = bn254.G1_IDENTITY
    in_coms = []
    for i, pt in enumerate(inputs):
        if proof.input_values[i] is None:
            raise ProofError("invalid sum and type proof")
        a = g1_add(pt, g1_neg(proof.commitment_to_type))
        adj_inputs.append(a)
        sum_ = g1_add(sum_, a)
        c = g1_add(g1_mul(ped_params[1], proof.input_values[i]),
                   g1_mul(ped_params[2], proof.input_blinding_factors[i]))
        c = g1_add(c, g1_neg(g1_mul(a, proof.challenge)))
        in_coms.append(c)
    for pt in outputs:
        a = g1_add(pt, g1_neg(proof.commitment_to_type))
        adj_outputs.append(a)
        sum_ = g1_add(sum_, g1_neg(a))

    sum_com = g1_add(g1_mul(ped_params[2], proof.equality_of_sum),
                     g1_neg(g1_mul(sum_, proof.challenge)))
    type_com = g1_add(g1_mul(ped_params[0], proof.type_),
                      g1_mul(ped_params[2], proof.type_blinding_factor))
    type_com = g1_add(type_com, g1_neg(g1_mul(proof.commitment_to_type, proof.challenge)))

    chal = hash_to_zr(_transcript_bytes(
        in_coms, type_com, sum_com, adj_inputs, adj_outputs,
        proof.commitment_to_type, sum_))
    if chal != proof.challenge:
        raise ProofError("invalid sum and type proof")


# --------------------------------------------------------------------------
# Transfer proof composition (transfer.go)
# --------------------------------------------------------------------------

@dataclass
class TransferProof:
    type_and_sum: TypeAndSumProof = None
    range_correctness: RangeCorrectness = None

    def serialize(self) -> bytes:
        # reference transfer.go:31-33
        rc = self.range_correctness.serialize() if self.range_correctness else None
        return ser.marshal_serializers([self.type_and_sum.serialize(), rc])

    @classmethod
    def deserialize(cls, raw: bytes) -> "TransferProof":
        parts = ser.unmarshal_serializers(raw, 2)
        ts = TypeAndSumProof.deserialize(parts[0])
        rc = RangeCorrectness.deserialize(parts[1]) if parts[1] else RangeCorrectness()
        return cls(ts, rc)


def transfer_prove(input_witness: list[tuple[str, int, int]],
                   output_witness: list[tuple[str, int, int]],
                   inputs: list[G1], outputs: list[G1], pp,
                   draws: TransferDraws | None = None) -> bytes:
    """reference transfer.go:69-150. Witnesses are (type, value, blinding_factor).

    pp is a crypto.setup.PublicParams. `draws` pins all randomness
    (TransferDraws); None keeps fresh draws.
    """
    token_type = input_witness[0][0]
    type_zr = hash_to_zr(token_type.encode())
    if draws is None:
        draws = TransferDraws.random(len(input_witness),
                                     len(output_witness),
                                     pp.range_proof_params.bit_length)
    type_bf = draws.type_bf
    commitment_to_type = g1_add(g1_mul(pp.pedersen_generators[0], type_zr),
                                g1_mul(pp.pedersen_generators[2], type_bf))

    in_values = [w[1] for w in input_witness]
    in_bfs = [w[2] for w in input_witness]
    out_bfs = [w[2] for w in output_witness]

    ts = type_and_sum_prove(pp.pedersen_generators, inputs, outputs,
                            commitment_to_type, in_values, in_bfs, out_bfs,
                            type_zr, type_bf, draws=draws.ts)

    rc = None
    if len(input_witness) != 1 or len(output_witness) != 1:
        coms = [g1_add(outputs[i], g1_neg(commitment_to_type))
                for i in range(len(outputs))]
        values = [w[1] for w in output_witness]
        bfs = [fr_sub(w[2], type_bf) for w in output_witness]
        rpp = pp.range_proof_params
        rc = rp_mod.range_correctness_prove(
            coms, values, bfs, pp.pedersen_generators[1:],
            rpp.left_generators, rpp.right_generators, rpp.P, rpp.Q,
            rpp.bit_length, rpp.number_of_rounds,
            draws=draws.ranges or None)

    return TransferProof(type_and_sum=ts, range_correctness=rc).serialize()


def transfer_verify(proof_raw: bytes, inputs: list[G1], outputs: list[G1],
                    pp) -> None:
    """reference transfer.go:153-197. Raises ProofError on rejection."""
    try:
        proof = TransferProof.deserialize(proof_raw)
    except (ValueError, ProofError) as e:
        raise ProofError(f"invalid transfer proof: {e}") from e
    if proof.type_and_sum is None:
        raise ProofError("invalid transfer proof")

    try:
        type_and_sum_verify(proof.type_and_sum, pp.pedersen_generators,
                            inputs, outputs)
    except ProofError as e:
        raise ProofError(f"invalid transfer proof: {e}") from e

    if len(inputs) != 1 or len(outputs) != 1:
        if proof.range_correctness is None:
            raise ProofError("invalid transfer proof")
        coms = [g1_add(o, g1_neg(proof.type_and_sum.commitment_to_type))
                for o in outputs]
        rpp = pp.range_proof_params
        rp_mod.range_correctness_verify(
            proof.range_correctness, coms, pp.pedersen_generators[1:],
            rpp.left_generators, rpp.right_generators, rpp.P, rpp.Q,
            rpp.bit_length, rpp.number_of_rounds)
