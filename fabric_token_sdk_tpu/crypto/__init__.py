"""Host-side cryptographic control plane.

Pure-Python BN254 arithmetic (the correctness oracle and control-plane math),
gnark/mathlib-compatible serialization, Fiat-Shamir transcripts, and the
public-parameter model of the zkatdlog driver.

The heavy algebra (batched MSM, batched proof checks) lives in
fabric_token_sdk_tpu.ops / fabric_token_sdk_tpu.models as JAX programs; this
package is the byte-exact boundary layer around them.
"""
