"""Pure-Python BN254 (alt_bn128) arithmetic.

This is the host-side oracle and control-plane math layer. The reference
delegates all G1/Zr operations to github.com/IBM/mathlib which dispatches
BN254 to consensys/gnark-crypto (see reference
token/core/zkatdlog/nogh/v1/crypto/setup.go:14 and SURVEY.md §2.2). This
module provides the same operation surface (G1 add/sub/mul/equals, Zr modular
arithmetic, HashToZr, HashToG1) in pure Python integers.

The TPU kernels in fabric_token_sdk_tpu.ops are validated against this module;
the batched verifiers in fabric_token_sdk_tpu.models use it for host-side
transcript scalars.

Curve: y^2 = x^3 + 3 over Fp, order r, cofactor 1.
"""

from __future__ import annotations

import hashlib
import secrets
from dataclasses import dataclass

# BN254 base field modulus.
P = 21888242871839275222246405745257275088696311157297823662689037894645226208583
# BN254 group order (scalar field modulus).
R = 21888242871839275222246405745257275088548364400416034343698204186575808495617
# Curve equation y^2 = x^3 + B.
B = 3

# Number of bytes in a field element encoding (gnark fp.Bytes / fr.Bytes).
FP_BYTES = 32
FR_BYTES = 32

# mathlib curve identifier for BN254 (github.com/IBM/mathlib curve registry:
# FP256BN_AMCL=0, BN254=1, ...). Used in the ASN.1 Element framing of proofs
# (reference token/core/common/encoding/asn1/asn1.go:95-112).
CURVE_ID = 1


# --------------------------------------------------------------------------
# Scalar field Fr
# --------------------------------------------------------------------------

def fr_add(a: int, b: int) -> int:
    return (a + b) % R


def fr_sub(a: int, b: int) -> int:
    return (a - b) % R


def fr_mul(a: int, b: int) -> int:
    return (a * b) % R


def fr_neg(a: int) -> int:
    return (-a) % R


def fr_inv(a: int) -> int:
    if a % R == 0:
        raise ZeroDivisionError("inverse of zero in Fr")
    return pow(a, R - 2, R)


def fr_batch_inv(values: list[int]) -> list[int]:
    """Montgomery batch inversion: ONE field inversion + 3(n-1) muls.

    A single Fermat inversion costs ~256 modmuls; verifier hot paths invert
    a dozen scalars per proof, so batching is a ~10x host-side win."""
    n = len(values)
    if n == 0:
        return []
    prefix = [0] * n
    acc = 1
    for i, v in enumerate(values):
        if v % R == 0:
            raise ZeroDivisionError("inverse of zero in Fr")
        acc = acc * v % R
        prefix[i] = acc
    inv_acc = pow(acc, R - 2, R)
    out = [0] * n
    for i in range(n - 1, 0, -1):
        out[i] = prefix[i - 1] * inv_acc % R
        inv_acc = inv_acc * values[i] % R
    out[0] = inv_acc
    return out


def fr_rand() -> int:
    """Uniform random scalar in [0, R)."""
    return secrets.randbelow(R)


def hash_to_zr(data: bytes) -> int:
    """SHA-256 digest interpreted as a big-endian integer, reduced mod r.

    Mirrors mathlib Curve.HashToZr for the gnark-backed BN254 driver
    (digest -> fr.Element.SetBytes, which reduces mod r). Used for every
    Fiat-Shamir challenge in the reference proofs (e.g. reference
    rp/bulletproof.go:272-282, rp/ipa.go:173, transfer/typeandsum.go:219).
    """
    return int.from_bytes(hashlib.sha256(data).digest(), "big") % R


# --------------------------------------------------------------------------
# Base field Fp helpers
# --------------------------------------------------------------------------

def fp_sqrt(a: int) -> int | None:
    """Square root in Fp (p ≡ 3 mod 4), or None if a is not a QR."""
    a %= P
    if a == 0:
        return 0
    s = pow(a, (P + 1) // 4, P)
    if s * s % P != a:
        return None
    return s


def fp_sgn0(a: int) -> int:
    """RFC 9380 sgn0 for prime fields: parity of the canonical representative."""
    return a & 1


# --------------------------------------------------------------------------
# G1 points
# --------------------------------------------------------------------------

@dataclass(frozen=True)
class G1:
    """Affine BN254 G1 point; (0, 0) with inf=True is the identity.

    Frozen/hashable so points can key dicts (e.g. generator tables).
    """

    x: int
    y: int
    inf: bool = False

    def is_identity(self) -> bool:
        return self.inf

    def on_curve(self) -> bool:
        if self.inf:
            return True
        return (self.y * self.y - (self.x * self.x * self.x + B)) % P == 0

    def __add__(self, other: "G1") -> "G1":
        return g1_add(self, other)

    def __sub__(self, other: "G1") -> "G1":
        return g1_add(self, g1_neg(other))

    def __mul__(self, k: int) -> "G1":
        return g1_mul(self, k)

    __rmul__ = __mul__


G1_IDENTITY = G1(0, 0, True)
G1_GENERATOR = G1(1, 2)


def g1_neg(p: G1) -> G1:
    if p.inf:
        return p
    return G1(p.x, (-p.y) % P)


def g1_add(p: G1, q: G1) -> G1:
    if p.inf:
        return q
    if q.inf:
        return p
    if p.x == q.x:
        if (p.y + q.y) % P == 0:
            return G1_IDENTITY
        # doubling
        lam = (3 * p.x * p.x) * pow(2 * p.y, P - 2, P) % P
    else:
        lam = (q.y - p.y) * pow(q.x - p.x, P - 2, P) % P
    x3 = (lam * lam - p.x - q.x) % P
    y3 = (lam * (p.x - x3) - p.y) % P
    return G1(x3, y3)


def g1_double(p: G1) -> G1:
    return g1_add(p, p)


def g1_mul(p: G1, k: int) -> G1:
    """Scalar multiplication (double-and-add over a Jacobian accumulator)."""
    k %= R
    if k == 0 or p.inf:
        return G1_IDENTITY
    # Jacobian coordinates for speed (Python-int host path).
    X, Y, Z = p.x, p.y, 1
    RX, RY, RZ = 0, 1, 0  # identity
    for bit in bin(k)[2:]:
        RX, RY, RZ = _jac_double(RX, RY, RZ)
        if bit == "1":
            RX, RY, RZ = _jac_add_mixed(RX, RY, RZ, X, Y)
    return _jac_to_affine(RX, RY, RZ)


def _jac_double(X, Y, Z):
    if Z == 0:
        return X, Y, Z
    A = X * X % P
    Bv = Y * Y % P
    C = Bv * Bv % P
    D = 2 * ((X + Bv) * (X + Bv) - A - C) % P
    E = 3 * A % P
    F = E * E % P
    X3 = (F - 2 * D) % P
    Y3 = (E * (D - X3) - 8 * C) % P
    Z3 = 2 * Y * Z % P
    return X3, Y3, Z3


def _jac_add_mixed(X1, Y1, Z1, x2, y2):
    if Z1 == 0:
        return x2, y2, 1
    Z1Z1 = Z1 * Z1 % P
    U2 = x2 * Z1Z1 % P
    S2 = y2 * Z1 * Z1Z1 % P
    H = (U2 - X1) % P
    rr = (S2 - Y1) % P
    if H == 0:
        if rr == 0:
            return _jac_double(X1, Y1, Z1)
        return 0, 1, 0
    HH = H * H % P
    HHH = H * HH % P
    V = X1 * HH % P
    X3 = (rr * rr - HHH - 2 * V) % P
    Y3 = (rr * (V - X3) - Y1 * HHH) % P
    Z3 = Z1 * H % P
    return X3, Y3, Z3


def _jac_to_affine(X, Y, Z) -> G1:
    if Z == 0:
        return G1_IDENTITY
    zinv = pow(Z, P - 2, P)
    zinv2 = zinv * zinv % P
    return G1(X * zinv2 % P, Y * zinv2 * zinv % P)


def msm(points: list[G1], scalars: list[int]) -> G1:
    """Multi-scalar multiplication (host oracle; naive)."""
    acc = G1_IDENTITY
    for p, s in zip(points, scalars):
        acc = g1_add(acc, g1_mul(p, s))
    return acc


# --------------------------------------------------------------------------
# Hash-to-curve (Shallue–van de Woestijne, RFC 9380) for G1.
#
# The reference derives range-proof generators via curve.HashToG1 (reference
# crypto/setup.go:388-406). mathlib routes this to gnark-crypto's
# bn254.HashToG1 (SVDW map, expand_message_xmd/SHA-256, empty DST). Generator
# derivation only affects public-parameter *generation* — pp consumers read
# the points from the serialized pp — so cross-stack bit-parity of this map
# is not required for bit-identical accept/reject (pp.Validate only checks
# points are on-curve, reference crypto/setup.go:444-489).
# --------------------------------------------------------------------------

# SVDW constants for y^2 = x^3 + 3 with Z = 1 (g(Z) = 4):
_SVDW_Z = 1
_SVDW_C1 = 4  # g(Z)
_SVDW_C2 = (P - 1) * pow(2, P - 2, P) % P  # -Z / 2
# c3 = sqrt(-g(Z) * (3 Z^2 + 4 A)) = sqrt(-12), sign chosen so sgn0(c3) == 0
_c3 = fp_sqrt((-12) % P)
if _c3 is None:  # pragma: no cover - fixed constant
    raise RuntimeError("BN254 SVDW constant c3 does not exist")
_SVDW_C3 = _c3 if fp_sgn0(_c3) == 0 else P - _c3
# c4 = -4 g(Z) / (3 Z^2 + 4 A) = -16/3
_SVDW_C4 = (-16) % P * pow(3, P - 2, P) % P


def _g_of_x(x: int) -> int:
    return (x * x * x + B) % P


def map_to_curve_svdw(u: int) -> G1:
    """RFC 9380 SVDW map for BN254 G1 (straight-line, non-constant-time)."""
    tv1 = u * u % P * _SVDW_C1 % P
    tv2 = (1 + tv1) % P
    tv1 = (1 - tv1) % P
    tv3 = tv1 * tv2 % P
    tv3 = pow(tv3, P - 2, P) if tv3 else 0
    tv4 = u * tv1 % P * tv3 % P * _SVDW_C3 % P
    x1 = (_SVDW_C2 - tv4) % P
    y = fp_sqrt(_g_of_x(x1))
    if y is not None:
        x = x1
    else:
        x2 = (_SVDW_C2 + tv4) % P
        y = fp_sqrt(_g_of_x(x2))
        if y is not None:
            x = x2
        else:
            tv5 = tv2 * tv2 % P * tv3 % P
            x = (_SVDW_Z + _SVDW_C4 * tv5 * tv5) % P
            y = fp_sqrt(_g_of_x(x))
    assert y is not None
    if fp_sgn0(u) != fp_sgn0(y):
        y = P - y
    return G1(x, y)


def expand_message_xmd(msg: bytes, dst: bytes, out_len: int) -> bytes:
    """RFC 9380 expand_message_xmd with SHA-256."""
    h = hashlib.sha256
    b_in_bytes = 32
    r_in_bytes = 64
    ell = (out_len + b_in_bytes - 1) // b_in_bytes
    if ell > 255:
        raise ValueError("expand_message_xmd: output too long")
    dst_prime = dst + len(dst).to_bytes(1, "big")
    z_pad = b"\x00" * r_in_bytes
    l_i_b_str = out_len.to_bytes(2, "big")
    b0 = h(z_pad + msg + l_i_b_str + b"\x00" + dst_prime).digest()
    bvals = [h(b0 + b"\x01" + dst_prime).digest()]
    for i in range(2, ell + 1):
        tmp = bytes(x ^ y for x, y in zip(b0, bvals[-1]))
        bvals.append(h(tmp + i.to_bytes(1, "big") + dst_prime).digest())
    return b"".join(bvals)[:out_len]


def hash_to_field(msg: bytes, dst: bytes, count: int) -> list[int]:
    """RFC 9380 hash_to_field for Fp, L=48 (matches gnark bn254)."""
    L = 48
    uniform = expand_message_xmd(msg, dst, count * L)
    return [int.from_bytes(uniform[i * L:(i + 1) * L], "big") % P for i in range(count)]


def hash_to_g1(data: bytes, dst: bytes = b"") -> G1:
    """hash_to_curve for BN254 G1 (SVDW, random-oracle variant, cofactor 1)."""
    u0, u1 = hash_to_field(data, dst, 2)
    return g1_add(map_to_curve_svdw(u0), map_to_curve_svdw(u1))
