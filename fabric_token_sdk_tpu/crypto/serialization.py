"""Byte-exact serialization layer: gnark/mathlib element encodings + Go ASN.1.

Bit-identical Fiat-Shamir across the reference Go stack and this framework
depends on exact reproduction of three encoding layers (SURVEY.md §7 item 2):

1. Element bytes: mathlib G1.Bytes() = gnark G1Affine.RawBytes() = 64 bytes,
   x||y big-endian 32-byte each, uncompressed (flag bits 0b00 in the top two
   bits, which are naturally zero for BN254 since p < 2^254); the point at
   infinity encodes as 64 zero bytes. Zr.Bytes() = 32-byte big-endian of the
   reduced scalar.

2. G1 array bytes: hex-encode each element's bytes, join with the literal
   separator "||" (reference token/core/zkatdlog/nogh/v1/crypto/common/
   array.go:17-36).

3. ASN.1 framing: Go encoding/asn1 DER of
     Values  ::= SEQUENCE { values SEQUENCE OF OCTET STRING }
     Element ::= SEQUENCE { curveID INTEGER, raw OCTET STRING }
   (reference token/core/common/encoding/asn1/asn1.go:27-34,95-112), plus
   MarshalStd([][]byte) = SEQUENCE OF OCTET STRING (asn1.go:36-38).
"""

from __future__ import annotations

from . import bn254
from .bn254 import G1, R

SEPARATOR = b"||"  # reference crypto/common/array.go:19

G1_BYTES_LEN = 64


# --------------------------------------------------------------------------
# Element encodings
# --------------------------------------------------------------------------

def g1_to_bytes(p: G1) -> bytes:
    """mathlib G1.Bytes(): 64-byte uncompressed big-endian x||y."""
    if p.inf:
        return b"\x00" * G1_BYTES_LEN
    return p.x.to_bytes(32, "big") + p.y.to_bytes(32, "big")


def g1_from_bytes(raw: bytes) -> G1:
    """mathlib NewG1FromBytes: parse + on-curve check (cofactor 1 => in-group)."""
    if len(raw) != G1_BYTES_LEN:
        raise ValueError(f"invalid G1 encoding length {len(raw)}")
    if raw == b"\x00" * G1_BYTES_LEN:
        return bn254.G1_IDENTITY
    x = int.from_bytes(raw[:32], "big")
    y = int.from_bytes(raw[32:], "big")
    if x >= bn254.P or y >= bn254.P:
        raise ValueError("G1 coordinate out of range")
    p = G1(x, y)
    if not p.on_curve():
        raise ValueError("point not on BN254 G1")
    return p


def zr_to_bytes(s: int) -> bytes:
    """mathlib Zr.Bytes(): 32-byte big-endian of the value reduced mod r."""
    return (s % R).to_bytes(32, "big")


def zr_from_bytes(raw: bytes) -> int:
    """mathlib NewZrFromBytes (fr.Element.SetBytes semantics: reduce mod r)."""
    return int.from_bytes(raw, "big") % R


def g1_array_bytes(points: list[G1]) -> bytes:
    """G1Array.Bytes(): hex encodings joined by '||' (array.go:25-36)."""
    return SEPARATOR.join(g1_to_bytes(p).hex().encode("ascii") for p in points)


# --------------------------------------------------------------------------
# DER primitives (definite-length, matching Go encoding/asn1 output)
# --------------------------------------------------------------------------

def _der_len(n: int) -> bytes:
    if n < 0x80:
        return bytes([n])
    out = n.to_bytes((n.bit_length() + 7) // 8, "big")
    return bytes([0x80 | len(out)]) + out


def der_octet_string(b: bytes) -> bytes:
    return b"\x04" + _der_len(len(b)) + b


def der_integer(v: int) -> bytes:
    if v == 0:
        body = b"\x00"
    else:
        length = (v.bit_length() // 8) + 1  # minimal two's complement (v >= 0)
        body = v.to_bytes(length, "big", signed=True)
        # strip redundant leading 0x00 when the high bit is clear
        while len(body) > 1 and body[0] == 0 and body[1] < 0x80:
            body = body[1:]
    return b"\x02" + _der_len(len(body)) + body


def der_sequence(*parts: bytes) -> bytes:
    body = b"".join(parts)
    return b"\x30" + _der_len(len(body)) + body


class DerReader:
    def __init__(self, raw: bytes):
        self.raw = raw
        self.pos = 0

    def eof(self) -> bool:
        return self.pos >= len(self.raw)

    def _read_header(self, expected_tag: int) -> int:
        if self.pos >= len(self.raw):
            raise ValueError("DER: truncated")
        tag = self.raw[self.pos]
        if tag != expected_tag:
            raise ValueError(f"DER: expected tag {expected_tag:#x}, got {tag:#x}")
        self.pos += 1
        if self.pos >= len(self.raw):
            raise ValueError("DER: truncated length")
        first = self.raw[self.pos]
        self.pos += 1
        if first < 0x80:
            return first
        nbytes = first & 0x7F
        if nbytes == 0 or self.pos + nbytes > len(self.raw):
            raise ValueError("DER: truncated length")
        body = self.raw[self.pos:self.pos + nbytes]
        # DER requires minimal length encoding (Go encoding/asn1 rejects
        # non-minimal forms with a syntax error).
        if body[0] == 0 or (nbytes == 1 and body[0] < 0x80):
            raise ValueError("DER: non-minimal length")
        length = int.from_bytes(body, "big")
        self.pos += nbytes
        return length

    def read_octet_string(self) -> bytes:
        n = self._read_header(0x04)
        out = self.raw[self.pos:self.pos + n]
        if len(out) != n:
            raise ValueError("DER: truncated octet string")
        self.pos += n
        return out

    def read_integer(self) -> int:
        n = self._read_header(0x02)
        body = self.raw[self.pos:self.pos + n]
        if len(body) != n:
            raise ValueError("DER: truncated integer")
        # Go encoding/asn1 rejects empty and non-minimal INTEGER encodings.
        if n == 0:
            raise ValueError("DER: empty integer")
        if n > 1 and ((body[0] == 0 and body[1] < 0x80)
                      or (body[0] == 0xFF and body[1] >= 0x80)):
            raise ValueError("DER: integer not minimally encoded")
        self.pos += n
        return int.from_bytes(body, "big", signed=True)

    def read_sequence(self) -> "DerReader":
        n = self._read_header(0x30)
        body = self.raw[self.pos:self.pos + n]
        if len(body) != n:
            raise ValueError("DER: truncated sequence")
        self.pos += n
        return DerReader(body)


# --------------------------------------------------------------------------
# Go encoding/asn1 structures used by the reference
# --------------------------------------------------------------------------

def marshal_values(values: list[bytes]) -> bytes:
    """asn1.Marshal(Values{Values: ...}): SEQUENCE { SEQUENCE OF OCTET STRING }."""
    return der_sequence(der_sequence(*[der_octet_string(v) for v in values]))


def unmarshal_values(raw: bytes) -> list[bytes]:
    outer = DerReader(raw).read_sequence()
    inner = outer.read_sequence()
    out = []
    while not inner.eof():
        out.append(inner.read_octet_string())
    return out


def marshal_std_bytes_slices(values: list[bytes]) -> bytes:
    """asn1.MarshalStd([][]byte): SEQUENCE OF OCTET STRING (single level)."""
    return der_sequence(*[der_octet_string(v) for v in values])


def marshal_element(curve_id: int, raw: bytes) -> bytes:
    """asn1.Marshal(Element{CurveID, Raw}): SEQUENCE { INTEGER, OCTET STRING }."""
    return der_sequence(der_integer(curve_id), der_octet_string(raw))


def unmarshal_element(raw: bytes) -> tuple[int, bytes]:
    seq = DerReader(raw).read_sequence()
    return seq.read_integer(), seq.read_octet_string()


# "MarshalMath"-style framing: a Values wrapper of per-element Element frames
# (asn1.go:95-112). Elements are (kind, value) where kind selects encoding.

G1_KIND = "g1"
ZR_KIND = "zr"
G1_ARRAY_KIND = "g1array"
ZR_ARRAY_KIND = "zrarray"


def element_bytes(kind: str, value) -> bytes:
    if kind == G1_KIND:
        return g1_to_bytes(value)
    if kind == ZR_KIND:
        return zr_to_bytes(value)
    if kind == G1_ARRAY_KIND:
        return marshal_values([g1_to_bytes(p) for p in value])
    if kind == ZR_ARRAY_KIND:
        return marshal_values([zr_to_bytes(s) for s in value])
    raise ValueError(f"unknown element kind {kind}")


def marshal_math(*elements: tuple[str, object]) -> bytes:
    """MarshalMath(values...): each element framed, then wrapped in Values."""
    if not elements:
        raise ValueError("cannot marshal empty values")
    frames = [
        marshal_element(bn254.CURVE_ID, element_bytes(kind, value))
        for kind, value in elements
    ]
    return marshal_values(frames)


class MathUnmarshaller:
    """Mirror of asn1.NewUnmarshaller: sequential typed element extraction."""

    def __init__(self, raw: bytes):
        self.frames = unmarshal_values(raw)
        self.index = 0

    def _next(self) -> tuple[int, bytes] | None:
        if self.index >= len(self.frames):
            return None
        curve_id, body = unmarshal_element(self.frames[self.index])
        # The reference dispatches on CurveID (math.Curves[e.CurveID],
        # asn1.go:95-112); this stack supports BN254 only and must reject
        # rather than silently parse with the wrong curve.
        if curve_id != bn254.CURVE_ID:
            raise ValueError(f"unsupported curve ID {curve_id}")
        self.index += 1
        return curve_id, body

    def next_g1(self) -> G1:
        nxt = self._next()
        if nxt is None:
            raise ValueError("no more elements")
        return g1_from_bytes(nxt[1])

    def next_zr(self) -> int:
        nxt = self._next()
        if nxt is None:
            raise ValueError("no more elements")
        return zr_from_bytes(nxt[1])

    def next_g1_array(self) -> list[G1]:
        nxt = self._next()
        if nxt is None:
            raise ValueError("no more elements")
        return [g1_from_bytes(b) for b in unmarshal_values(nxt[1])]

    def next_zr_array(self) -> list[int]:
        nxt = self._next()
        if nxt is None:
            raise ValueError("no more elements")
        return [zr_from_bytes(b) for b in unmarshal_values(nxt[1])]


def marshal_serializers(parts: list[bytes | None]) -> bytes:
    """asn1.Marshal[Serializer](...): Values of pre-serialized members
    (nil members encode as empty octet strings, asn1.go:40-55)."""
    return marshal_values([p if p is not None else b"" for p in parts])


def unmarshal_serializers(raw: bytes, count: int) -> list[bytes]:
    vals = unmarshal_values(raw)
    if len(vals) != count:
        raise ValueError(f"number of values does not match: {len(vals)} != {count}")
    return vals
