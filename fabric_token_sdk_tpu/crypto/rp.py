"""Host-side range proof + inner-product argument (Bulletproof-style).

Behavioral mirror of the reference zkatdlog range-proof scheme:
  - prover/verifier:    reference token/core/zkatdlog/nogh/v1/crypto/rp/
                        bulletproof.go:209-509
  - inner-product arg.: reference .../rp/ipa.go:158-373
  - batch container:    reference .../rp/rangecorrectness.go:15-162

This module is the oracle + load generator. The batched TPU verification path
lives in fabric_token_sdk_tpu.models.range_proof and is tested for exact
accept/reject agreement with this module. Error strings intentionally match
the reference so observable behavior is identical.

The proof shows a committed value v < 2^BitLength. Commitments here are
"value commitments" com = G^v H^bf with (G, H) = CommitmentGenerators
(the callers pass PedersenGenerators[1:], see reference transfer/transfer.go:110
and issue/prover.go:76-88).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from . import bn254
from . import serialization as ser
from .bn254 import (
    G1,
    R,
    fr_add,
    fr_inv,
    fr_mul,
    fr_rand,
    fr_sub,
    g1_add,
    g1_mul,
    hash_to_zr,
)


class ProofError(Exception):
    """Raised when a proof fails verification; message mirrors the Go error."""


@dataclass
class RangeProverDraws:
    """Every blinding draw `range_prove` consumes, as an explicit record.

    The prover seam for externally-generated randomness (the TPU prover
    draws these host-side, packs them into the witness upload, and the
    device synthesizes the proof deterministically from them): handing
    the SAME draws to `range_prove` and to the device prover must yield
    byte-identical proofs, which is what tests/test_prover_parity.py
    pins. Fields are named for the reference's locals (bulletproof.go:
    336-466) rather than positionally, so the host prover's internal
    draw ORDER can change without breaking recorded draws.
    """

    rho: int
    eta: int
    random_left: list[int]
    random_right: list[int]
    tau1: int
    tau2: int

    @classmethod
    def random(cls, bit_length: int) -> "RangeProverDraws":
        return cls(rho=fr_rand(), eta=fr_rand(),
                   random_left=[fr_rand() for _ in range(bit_length)],
                   random_right=[fr_rand() for _ in range(bit_length)],
                   tau1=fr_rand(), tau2=fr_rand())


# --------------------------------------------------------------------------
# shared vector helpers (reference rp/ipa.go:358-373)
# --------------------------------------------------------------------------

def inner_product(left: list[int], right: list[int]) -> int:
    ip = 0
    for l, r in zip(left, right):
        ip = fr_add(ip, fr_mul(l, r))
    return ip


def commit_vector(left: list[int], right: list[int],
                  left_gen: list[G1], right_gen: list[G1]) -> G1:
    com = bn254.G1_IDENTITY
    for i in range(len(left)):
        com = g1_add(com, g1_mul(left_gen[i], left[i]))
        com = g1_add(com, g1_mul(right_gen[i], right[i]))
    return com


def reduce_generators(left_gen: list[G1], right_gen: list[G1],
                      x: int, x_inv: int) -> tuple[list[G1], list[G1]]:
    """One IPA folding round of the generator vectors (rp/ipa.go:343-356)."""
    n = len(left_gen) // 2
    lg, rg = [], []
    for i in range(n):
        lg.append(g1_add(g1_mul(left_gen[i], x_inv), g1_mul(left_gen[i + n], x)))
        rg.append(g1_add(g1_mul(right_gen[i], x), g1_mul(right_gen[i + n], x_inv)))
    return lg, rg


def reduce_vectors(left: list[int], right: list[int],
                   x: int, x_inv: int) -> tuple[list[int], list[int]]:
    n = len(left) // 2
    lp = [fr_add(fr_mul(left[i], x), fr_mul(left[i + n], x_inv)) for i in range(n)]
    rp_ = [fr_add(fr_mul(right[i], x_inv), fr_mul(right[i + n], x)) for i in range(n)]
    return lp, rp_


# --------------------------------------------------------------------------
# IPA (rp/ipa.go)
# --------------------------------------------------------------------------

@dataclass
class IPA:
    left: int = 0
    right: int = 0
    L: list[G1] = field(default_factory=list)
    R: list[G1] = field(default_factory=list)

    def serialize(self) -> bytes:
        # reference rp/ipa.go:33-43
        return ser.marshal_math(
            (ser.ZR_KIND, self.left),
            (ser.ZR_KIND, self.right),
            (ser.G1_ARRAY_KIND, self.L),
            (ser.G1_ARRAY_KIND, self.R),
        )

    @classmethod
    def deserialize(cls, raw: bytes) -> "IPA":
        um = ser.MathUnmarshaller(raw)
        return cls(um.next_zr(), um.next_zr(), um.next_g1_array(), um.next_g1_array())


def ipa_first_challenge(left_gen: list[G1], right_gen: list[G1],
                        Q: G1, commitment: G1, ip: int) -> int:
    """First IPA challenge; NOTE right generators hash first (ipa.go:159-173)."""
    array_bytes = ser.g1_array_bytes(list(right_gen) + list(left_gen) + [Q, commitment])
    raw = ser.marshal_std_bytes_slices(
        [array_bytes, ser.SEPARATOR, ser.zr_to_bytes(ip)])
    return hash_to_zr(raw)


def ipa_round_challenge(L: G1, Rp: G1) -> int:
    return hash_to_zr(ser.g1_array_bytes([L, Rp]))


def ipa_prove(ip: int, left: list[int], right: list[int], Q: G1,
              left_gen: list[G1], right_gen: list[G1], commitment: G1,
              rounds: int) -> IPA:
    """reference rp/ipa.go:158-186,267-322.

    Transcript layout (must match ipa_verify and the device verifier /
    prover bit-for-bit):

    - first challenge x = HashToZr(MarshalStd[[]byte]([array_bytes,
      "||", Zr.Bytes(ip)])) where array_bytes hex-encodes, joined by
      "||", the points [right_gen' .. , left_gen .. , Q, commitment] —
      note the RIGHT generators hash FIRST (ipa.go:159-173) and ip is
      the 32-byte big-endian CANONICAL reduced scalar.
    - per round r: L_r, R_r are committed, then
      x_r = HashToZr(hex(L_r) || "||" || hex(R_r)) (ipa_round_challenge)
      folds generators as lg' = x_r^-1*lg[:h] + x_r*lg[h:],
      rg' = x_r*rg[:h] + x_r^-1*rg[h:] and vectors with the transposed
      coefficients (reduce_vectors), h = len/2.
    - every hex() above is the lowercase ascii hex of the 64-byte
      uncompressed big-endian x||y encoding (identity = 64 zero bytes).
    """
    x = ipa_first_challenge(left_gen, right_gen, Q, commitment, ip)
    X = g1_mul(Q, x)
    L_arr: list[G1] = []
    R_arr: list[G1] = []
    for _ in range(rounds):
        n = len(left_gen) // 2
        left_ip = inner_product(left[:n], right[n:])
        right_ip = inner_product(left[n:], right[:n])
        L = g1_add(commit_vector(left[:n], right[n:], left_gen[n:], right_gen[:n]),
                   g1_mul(X, left_ip))
        Rp = g1_add(commit_vector(left[n:], right[:n], left_gen[:n], right_gen[n:]),
                    g1_mul(X, right_ip))
        L_arr.append(L)
        R_arr.append(Rp)
        xr = ipa_round_challenge(L, Rp)
        xr_inv = fr_inv(xr)
        left_gen, right_gen = reduce_generators(left_gen, right_gen, xr, xr_inv)
        left, right = reduce_vectors(left, right, xr, xr_inv)
    return IPA(left=left[0], right=right[0], L=L_arr, R=R_arr)


def ipa_verify(proof: IPA, ip: int, Q: G1, left_gen: list[G1],
               right_gen: list[G1], commitment: G1, rounds: int) -> None:
    """reference rp/ipa.go:190-262. Raises ProofError on rejection."""
    if proof.left is None or proof.right is None:
        raise ProofError("invalid IPA proof: nil elements")
    if len(proof.L) != len(proof.R) or len(proof.L) != rounds:
        raise ProofError("invalid IPA proof")
    x = ipa_first_challenge(left_gen, right_gen, Q, commitment, ip)
    C = g1_add(g1_mul(Q, fr_mul(x, ip)), commitment)
    X = g1_mul(Q, x)
    for i in range(rounds):
        if proof.L[i] is None or proof.R[i] is None:
            raise ProofError("invalid IPA proof: nil elements")
        xr = ipa_round_challenge(proof.L[i], proof.R[i])
        xr_inv = fr_inv(xr)
        x_sq = fr_mul(xr, xr)
        x_sq_inv = fr_inv(x_sq)
        C = g1_add(g1_add(g1_mul(proof.L[i], x_sq), C), g1_mul(proof.R[i], x_sq_inv))
        left_gen, right_gen = reduce_generators(left_gen, right_gen, xr, xr_inv)
    C_prime = g1_add(g1_mul(left_gen[0], proof.left), g1_mul(right_gen[0], proof.right))
    C_prime = g1_add(C_prime, g1_mul(X, fr_mul(proof.left, proof.right)))
    if C_prime != C:
        raise ProofError("invalid IPA")


# --------------------------------------------------------------------------
# Range proof (rp/bulletproof.go)
# --------------------------------------------------------------------------

@dataclass
class RangeProofData:
    T1: G1 = None
    T2: G1 = None
    tau: int = 0
    C: G1 = None
    D: G1 = None
    delta: int = 0
    inner_product: int = 0

    def serialize(self) -> bytes:
        # reference rp/bulletproof.go:37-47
        return ser.marshal_math(
            (ser.G1_KIND, self.T1),
            (ser.G1_KIND, self.T2),
            (ser.ZR_KIND, self.tau),
            (ser.G1_KIND, self.C),
            (ser.G1_KIND, self.D),
            (ser.ZR_KIND, self.delta),
            (ser.ZR_KIND, self.inner_product),
        )

    @classmethod
    def deserialize(cls, raw: bytes) -> "RangeProofData":
        um = ser.MathUnmarshaller(raw)
        return cls(um.next_g1(), um.next_g1(), um.next_zr(),
                   um.next_g1(), um.next_g1(), um.next_zr(), um.next_zr())


@dataclass
class RangeProof:
    data: RangeProofData = None
    ipa: IPA = None

    def serialize(self) -> bytes:
        # reference rp/bulletproof.go:93-95
        return ser.marshal_serializers([self.data.serialize(), self.ipa.serialize()])

    @classmethod
    def deserialize(cls, raw: bytes) -> "RangeProof":
        parts = ser.unmarshal_serializers(raw, 2)
        return cls(RangeProofData.deserialize(parts[0]), IPA.deserialize(parts[1]))


def challenge_x(T1: G1, T2: G1) -> int:
    """x = HashToZr(G1Array([T1, T2]).Bytes()) (bulletproof.go:266-272)."""
    return hash_to_zr(ser.g1_array_bytes([T1, T2]))


def challenges_y_z(C: G1, D: G1, commitment: G1) -> tuple[int, int]:
    """y, z from (C, D, Com) (bulletproof.go:276-282)."""
    y = hash_to_zr(ser.g1_array_bytes([C, D, commitment]))
    z = hash_to_zr(ser.zr_to_bytes(y))
    return y, z


def range_prove(commitment: G1, value: int, commitment_gen: list[G1],
                blinding_factor: int, left_gen: list[G1], right_gen: list[G1],
                P: G1, Q: G1, rounds: int, bit_length: int,
                draws: RangeProverDraws | None = None) -> RangeProof:
    """reference rp/bulletproof.go:209-249,336-466.

    `draws` injects every blinding draw (RangeProverDraws); None keeps
    the fresh-`fr_rand` behavior. With pinned draws the prover is a pure
    function of (commitment, value, blinding_factor) — the parity oracle
    the device prover (fabric_token_sdk_tpu.prover.range) is pinned to.

    Transcript layout (mirrors range_verify and the device paths):

    - (y, z) = challenges_y_z(C, D, com): y = HashToZr(hex(C) || "||" ||
      hex(D) || "||" || hex(com)), z = HashToZr(Zr.Bytes(y)) — so y's
      CANONICAL 32-byte big-endian reduction re-enters the transcript.
    - x = challenge_x(T1, T2) = HashToZr(hex(T1) || "||" || hex(T2)).
    - the IPA then runs over com_ipa = <left, G> + <right, H'> with
      H'_i = y^-i * H_i and ip = <left, right> (see ipa_prove's
      docstring for the x_ipa / round-challenge layout).
    - hex() is lowercase ascii of the 64-byte uncompressed big-endian
      x||y point encoding (identity = 64 zero bytes).
    """
    # -------- preprocess (bulletproof.go:336-466)
    if draws is None:
        draws = RangeProverDraws.random(bit_length)
    rho = draws.rho
    eta = draws.eta
    left = []
    right = []
    random_left = list(draws.random_left)
    random_right = list(draws.random_right)
    for i in range(bit_length):
        b = 1 if (value >> i) & 1 else 0
        left.append(b)
        right.append(fr_sub(b, 1))

    C = g1_add(commit_vector(left, right, left_gen, right_gen), g1_mul(P, rho))
    D = g1_add(commit_vector(random_left, random_right, left_gen, right_gen),
               g1_mul(P, eta))
    y, z = challenges_y_z(C, D, commitment)
    z_sq = fr_mul(z, z)

    left_prime = []
    right_prime = []
    rand_right_prime = []
    z_prime = []
    y2i = 1
    for i in range(bit_length):
        left_prime.append(fr_sub(left[i], z))
        if i > 0:
            y2i = fr_mul(y, y2i)
        right_prime.append(fr_mul(fr_add(right[i], z), y2i))
        rand_right_prime.append(fr_mul(random_right[i], y2i))
        z_prime.append(fr_mul(z_sq, pow(2, i, R)))

    t1 = inner_product(left_prime, rand_right_prime)
    t1 = fr_add(t1, inner_product(right_prime, random_left))
    t1 = fr_add(t1, inner_product(z_prime, random_left))
    tau1 = draws.tau1
    T1 = g1_add(g1_mul(commitment_gen[0], t1), g1_mul(commitment_gen[1], tau1))

    t2 = inner_product(random_left, rand_right_prime)
    tau2 = draws.tau2
    T2 = g1_add(g1_mul(commitment_gen[0], t2), g1_mul(commitment_gen[1], tau2))

    x = challenge_x(T1, T2)

    for i in range(bit_length):
        left[i] = fr_add(left_prime[i], fr_mul(x, random_left[i]))
        right[i] = fr_add(fr_add(right_prime[i], fr_mul(x, rand_right_prime[i])),
                          z_prime[i])
    tau = fr_mul(x, tau1)
    tau = fr_add(tau, fr_mul(tau2, fr_mul(x, x)))
    tau = fr_add(tau, fr_mul(z_sq, blinding_factor))
    delta = fr_add(rho, fr_mul(eta, x))

    proof = RangeProof(
        data=RangeProofData(T1=T1, T2=T2, tau=tau, C=C, D=D, delta=delta),
        ipa=None,
    )

    # -------- Prove (bulletproof.go:209-249)
    y_inv = fr_inv(y)
    right_gen_prime = [g1_mul(right_gen[i], pow(y_inv, i, R))
                       for i in range(len(right_gen))]
    com = commit_vector(left, right, left_gen, right_gen_prime)
    proof.data.inner_product = inner_product(left, right)
    proof.ipa = ipa_prove(proof.data.inner_product, left, right, Q,
                          left_gen, right_gen_prime, com, rounds)
    return proof


def range_verify(proof: RangeProof, commitment: G1, commitment_gen: list[G1],
                 left_gen: list[G1], right_gen: list[G1],
                 P: G1, Q: G1, rounds: int, bit_length: int) -> None:
    """reference rp/bulletproof.go:252-333,469-509. Raises ProofError."""
    d = proof.data
    if d is None or d.inner_product is None or d.C is None or d.D is None:
        raise ProofError("invalid range proof: nil elements")
    if d.T1 is None or d.T2 is None:
        raise ProofError("invalid range proof: nil elements")
    if d.tau is None or d.delta is None:
        raise ProofError("invalid range proof: nil elements")
    if proof.ipa is None:
        raise ProofError("invalid range proof: nil elements")

    x = challenge_x(d.T1, d.T2)
    x_sq = fr_mul(x, x)
    y, z = challenges_y_z(d.C, d.D, commitment)
    z_sq = fr_mul(z, z)
    z_cube = fr_mul(z_sq, z)

    y_pow = []
    ipy = 0
    ip2 = 0
    power2 = 1
    for i in range(bit_length):
        if i == 0:
            y_pow.append(1)
        else:
            y_pow.append(fr_mul(y, y_pow[i - 1]))
            power2 = fr_mul(2, power2)
        ipy = fr_add(ipy, y_pow[i])
        ip2 = fr_add(ip2, power2)

    pol_eval = fr_mul(fr_sub(z, z_sq), ipy)
    pol_eval = fr_sub(pol_eval, fr_mul(z_cube, ip2))

    com = g1_mul(commitment_gen[0], d.inner_product)
    com = g1_add(com, g1_mul(commitment_gen[1], d.tau))
    com = g1_add(com, bn254.g1_neg(g1_mul(d.T1, x)))
    com = g1_add(com, bn254.g1_neg(g1_mul(d.T2, x_sq)))

    com_prime = g1_add(g1_mul(commitment, z_sq), g1_mul(commitment_gen[0], pol_eval))
    if com != com_prime:
        raise ProofError("invalid range proof")

    # verifyIPA (bulletproof.go:469-509)
    com = g1_add(g1_mul(d.D, x), d.C)
    right_gen_prime = []
    for i in range(len(left_gen)):
        com = g1_add(com, bn254.g1_neg(g1_mul(left_gen[i], z)))
        y_inv_2i = fr_inv(y_pow[i])
        zi = fr_add(fr_mul(z, y_pow[i]), fr_mul(z_sq, pow(2, i, R)))
        rg = g1_mul(right_gen[i], y_inv_2i)
        right_gen_prime.append(rg)
        com = g1_add(com, g1_mul(rg, zi))
    com = g1_add(com, bn254.g1_neg(g1_mul(P, d.delta)))

    ipa_verify(proof.ipa, d.inner_product, Q, left_gen, right_gen_prime, com, rounds)


# --------------------------------------------------------------------------
# RangeCorrectness batch container (rp/rangecorrectness.go)
# --------------------------------------------------------------------------

@dataclass
class RangeCorrectness:
    proofs: list[RangeProof] = field(default_factory=list)

    def serialize(self) -> bytes:
        # reference rangecorrectness.go:19-25: Marshal(NewArray(proofs))
        inner = ser.marshal_serializers([p.serialize() for p in self.proofs])
        return ser.marshal_serializers([inner])

    @classmethod
    def deserialize(cls, raw: bytes) -> "RangeCorrectness":
        outer = ser.unmarshal_serializers(raw, 1)
        parts = ser.unmarshal_values(outer[0])
        return cls([RangeProof.deserialize(p) for p in parts])


def range_correctness_prove(commitments: list[G1], values: list[int],
                            blinding_factors: list[int],
                            pedersen_params: list[G1],
                            left_gen: list[G1], right_gen: list[G1],
                            P: G1, Q: G1, bit_length: int,
                            rounds: int,
                            draws: list[RangeProverDraws] | None = None,
                            ) -> RangeCorrectness:
    proofs = [
        range_prove(commitments[i], values[i], pedersen_params,
                    blinding_factors[i], left_gen, right_gen, P, Q,
                    rounds, bit_length,
                    draws=draws[i] if draws is not None else None)
        for i in range(len(commitments))
    ]
    return RangeCorrectness(proofs)


def range_correctness_verify(rc: RangeCorrectness, commitments: list[G1],
                             pedersen_params: list[G1],
                             left_gen: list[G1], right_gen: list[G1],
                             P: G1, Q: G1, bit_length: int,
                             rounds: int) -> None:
    """Sequential per-proof loop (rangecorrectness.go:137-162) — the primary
    batching opportunity that models.range_proof exploits on TPU."""
    if len(rc.proofs) != len(commitments):
        raise ProofError("invalid range proof")
    for i, proof in enumerate(rc.proofs):
        if proof is None:
            raise ProofError(f"invalid range proof: nil proof at index {i}")
        try:
            range_verify(proof, commitments[i], pedersen_params,
                         left_gen, right_gen, P, Q, rounds, bit_length)
        except ProofError as e:
            raise ProofError(f"invalid range proof at index {i}: {e}") from e
