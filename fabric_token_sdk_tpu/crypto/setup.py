"""zkatdlog public parameters: generation, validation, serialization.

Behavioral mirror of reference token/core/zkatdlog/nogh/v1/crypto/setup.go.

Wire format (setup.go:271-317): the inner message is the proto3
nogh.PublicParameters (protos/noghpp.proto), wrapped in the driver-level
protos.PublicParameters{identifier, raw} which is JSON-encoded
(token/core/common/encoding/pp/pp.go:16-22; raw is base64 in JSON, matching
Go's encoding/json treatment of []byte).

This framework extends the reference pp with optional TPU batching hints
(batch size, device-mesh shape) carried OUTSIDE the reference message so the
byte format stays compatible; see TpuBatchParams.
"""

from __future__ import annotations

import base64
import hashlib
import json
from dataclasses import dataclass, field

from ..utils import protowire as pw
from . import bn254
from . import serialization as ser
from .bn254 import G1, fr_rand, g1_mul, hash_to_g1

DLOG_PUBLIC_PARAMETERS = "zkatdlog"
VERSION = "1.0.0"
SUPPORTED_PRECISIONS = (16, 32, 64)


class SetupError(Exception):
    pass


def _log2(x: int) -> int:
    return x.bit_length() - 1


# --------------------------------------------------------------------------
# proto codecs for noghmath.proto / noghpp.proto messages
# --------------------------------------------------------------------------

def _g1_msg(p: G1 | None) -> bytes:
    if p is None:
        return b""
    return pw.bytes_field(1, ser.g1_to_bytes(p))


def _g1_from_msg(raw: bytes) -> G1:
    fields = pw.parse_fields(raw)
    if 1 not in fields:
        raise SetupError("invalid G1 proto: missing raw")
    return ser.g1_from_bytes(bytes(fields[1][0]))


def _curve_id_msg(curve_id: int) -> bytes:
    return pw.uint64_field(1, curve_id)


def _identity_msg(raw: bytes) -> bytes:
    return pw.bytes_field(1, raw)


def _identity_from_msg(raw: bytes) -> bytes:
    fields = pw.parse_fields(raw)
    return bytes(fields[1][0]) if 1 in fields else b""


@dataclass
class RangeProofParams:
    """reference setup.go:39-46."""

    left_generators: list[G1] = field(default_factory=list)
    right_generators: list[G1] = field(default_factory=list)
    P: G1 = None
    Q: G1 = None
    bit_length: int = 0
    number_of_rounds: int = 0

    def validate(self) -> None:
        """reference setup.go:48-78."""
        if self.bit_length == 0:
            raise SetupError("invalid range proof parameters: bit length is zero")
        if self.number_of_rounds == 0:
            raise SetupError("invalid range proof parameters: number of rounds is zero")
        if self.number_of_rounds > 64:
            raise SetupError(
                "invalid range proof parameters: number of rounds must be smaller or equal to 64")
        if self.bit_length != (1 << self.number_of_rounds):
            raise SetupError(
                f"invalid range proof parameters: bit length should be {1 << self.number_of_rounds}")
        if len(self.left_generators) != len(self.right_generators):
            raise SetupError(
                "invalid range proof parameters: the size of the left generators does not "
                f"match the size of the right generators [{len(self.left_generators)} vs, "
                f"{len(self.right_generators)}]")
        for name, pt in (("Q", self.Q), ("P", self.P)):
            if pt is None or pt.is_identity() or not pt.on_curve():
                raise SetupError(
                    f"invalid range proof parameters: generator {name} is invalid")
        for gens in (self.left_generators, self.right_generators):
            if len(gens) != self.bit_length:
                raise SetupError("invalid range proof parameters: wrong generator count")
            for pt in gens:
                if pt is None or pt.is_identity() or not pt.on_curve():
                    raise SetupError("invalid range proof parameters: invalid generator")

    def to_proto(self) -> bytes:
        out = b""
        for g in self.left_generators:
            out += pw.message_field(1, _g1_msg(g))
        for g in self.right_generators:
            out += pw.message_field(2, _g1_msg(g))
        out += pw.message_field(3, _g1_msg(self.P), present=self.P is not None)
        out += pw.message_field(4, _g1_msg(self.Q), present=self.Q is not None)
        out += pw.uint64_field(5, self.bit_length)
        out += pw.uint64_field(6, self.number_of_rounds)
        return out

    @classmethod
    def from_proto(cls, raw: bytes) -> "RangeProofParams":
        fields = pw.parse_fields(raw)
        rpp = cls()
        rpp.left_generators = [_g1_from_msg(b) for b in fields.get(1, [])]
        rpp.right_generators = [_g1_from_msg(b) for b in fields.get(2, [])]
        if 3 in fields:
            rpp.P = _g1_from_msg(fields[3][0])
        if 4 in fields:
            rpp.Q = _g1_from_msg(fields[4][0])
        rpp.bit_length = fields.get(5, [0])[0]
        rpp.number_of_rounds = fields.get(6, [0])[0]
        return rpp


@dataclass
class IdemixIssuerPublicKey:
    public_key: bytes = b""
    curve: int = 0

    def to_proto(self) -> bytes:
        return (pw.bytes_field(1, self.public_key)
                + pw.message_field(2, _curve_id_msg(self.curve), present=True))

    @classmethod
    def from_proto(cls, raw: bytes) -> "IdemixIssuerPublicKey":
        fields = pw.parse_fields(raw)
        pk = bytes(fields[1][0]) if 1 in fields else b""
        curve = 0
        if 2 in fields:
            sub = pw.parse_fields(fields[2][0])
            curve = sub.get(1, [0])[0]
        return cls(pk, curve)


@dataclass
class TpuBatchParams:
    """TPU-side batching hints emitted by our tokengen (--tpu-batch flags).

    This is the tokengen extension called for by BASELINE.json ("tokengen
    gains a flag to emit TPU-batched public parameters"). Carried beside the
    reference-compatible blob, never inside it.
    """

    batch_size: int = 1024
    mesh_devices: int = 1

    def to_dict(self) -> dict:
        return {"batch_size": self.batch_size, "mesh_devices": self.mesh_devices}

    @classmethod
    def from_dict(cls, d: dict) -> "TpuBatchParams":
        return cls(d.get("batch_size", 1024), d.get("mesh_devices", 1))


@dataclass
class PublicParams:
    """reference setup.go:158-181."""

    label: str = DLOG_PUBLIC_PARAMETERS
    version: str = VERSION
    curve: int = bn254.CURVE_ID
    pedersen_generators: list[G1] = field(default_factory=list)
    range_proof_params: RangeProofParams = None
    idemix_issuer_public_keys: list[IdemixIssuerPublicKey] = field(default_factory=list)
    auditor: bytes = b""
    issuer_ids: list[bytes] = field(default_factory=list)
    max_token: int = 0
    quantity_precision: int = 0
    # TPU batching hints; None means "not set" and keeps serialize() output
    # byte-identical to a reference-produced container round trip.
    tpu_batch: TpuBatchParams | None = None

    # -- reference-facade properties ------------------------------------
    def identifier(self) -> str:
        return self.label

    def token_data_hiding(self) -> bool:
        return True

    def graph_hiding(self) -> bool:
        return False

    def max_token_value(self) -> int:
        return self.max_token

    def precision(self) -> int:
        return self.quantity_precision

    def auditors(self) -> list[bytes]:
        return [self.auditor] if self.auditor else []

    def issuers(self) -> list[bytes]:
        return list(self.issuer_ids)

    def compute_max_token_value(self) -> int:
        return (1 << self.range_proof_params.bit_length) - 1

    def add_auditor(self, identity: bytes) -> None:
        self.auditor = identity

    def add_issuer(self, identity: bytes) -> None:
        self.issuer_ids.append(identity)

    # -- generation -----------------------------------------------------

    def generate_pedersen_parameters(self) -> None:
        """Three random generators (setup.go:374-386)."""
        self.pedersen_generators = [
            g1_mul(bn254.G1_GENERATOR, fr_rand()) for _ in range(3)
        ]

    def generate_range_proof_parameters(self, bit_length: int) -> None:
        """Deterministic hash-to-curve generators (setup.go:388-406)."""
        self.range_proof_params = RangeProofParams(
            P=hash_to_g1(b"0"),
            Q=hash_to_g1(b"1"),
            bit_length=bit_length,
            number_of_rounds=_log2(bit_length),
            left_generators=[
                hash_to_g1(f"RangeProof.{2 * (i + 1)}".encode())
                for i in range(bit_length)
            ],
            right_generators=[
                hash_to_g1(f"RangeProof.{2 * (i + 1) + 1}".encode())
                for i in range(bit_length)
            ],
        )

    # -- serialization --------------------------------------------------

    def to_proto(self) -> bytes:
        out = pw.string_field(1, self.label)
        out += pw.string_field(2, self.version)
        out += pw.message_field(3, _curve_id_msg(self.curve), present=True)
        for g in self.pedersen_generators:
            out += pw.message_field(4, _g1_msg(g))
        out += pw.message_field(5, self.range_proof_params.to_proto(),
                                present=self.range_proof_params is not None)
        for k in self.idemix_issuer_public_keys:
            out += pw.message_field(6, k.to_proto())
        out += pw.message_field(7, _identity_msg(self.auditor), present=True)
        for issuer in self.issuer_ids:
            out += pw.message_field(8, _identity_msg(issuer))
        out += pw.uint64_field(9, self.max_token)
        out += pw.uint64_field(10, self.quantity_precision)
        return out

    def serialize(self) -> bytes:
        """Full container: JSON{identifier, raw=base64(proto)} (+ tpu hints)."""
        raw = self.to_proto()
        container = {
            "identifier": self.label,
            "raw": base64.b64encode(raw).decode("ascii"),
        }
        if self.tpu_batch is not None:
            # extension key ignored by reference-style parsers
            container["tpu_batch"] = self.tpu_batch.to_dict()
        return json.dumps(container, separators=(",", ":"), sort_keys=False).encode()

    @classmethod
    def deserialize(cls, raw: bytes, label: str = DLOG_PUBLIC_PARAMETERS) -> "PublicParams":
        try:
            container = json.loads(raw.decode())
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            raise SetupError(f"failed to deserialize public parameters: {e}") from e
        if container.get("identifier") != label:
            raise SetupError(
                f"invalid identifier, expecting [{label}], got [{container.get('identifier')}]")
        body = base64.b64decode(container.get("raw", ""))
        fields = pw.parse_fields(body)
        pp = cls()
        pp.label = fields.get(1, [b""])[0].decode() if 1 in fields else ""
        pp.version = fields.get(2, [b""])[0].decode() if 2 in fields else ""
        if 3 not in fields:
            raise SetupError("invalid curve id, expecting curve id, got nil")
        pp.curve = pw.parse_fields(fields[3][0]).get(1, [0])[0]
        pp.pedersen_generators = [_g1_from_msg(b) for b in fields.get(4, [])]
        if 5 in fields:
            pp.range_proof_params = RangeProofParams.from_proto(fields[5][0])
        else:
            pp.range_proof_params = None
        pp.idemix_issuer_public_keys = [
            IdemixIssuerPublicKey.from_proto(b) for b in fields.get(6, [])
        ]
        if 7 in fields:
            pp.auditor = _identity_from_msg(fields[7][0])
        pp.issuer_ids = [_identity_from_msg(b) for b in fields.get(8, [])]
        pp.max_token = fields.get(9, [0])[0]
        pp.quantity_precision = fields.get(10, [0])[0]
        if "tpu_batch" in container:
            pp.tpu_batch = TpuBatchParams.from_dict(container["tpu_batch"])
        return pp

    def compute_hash(self) -> bytes:
        return hashlib.sha256(self.serialize()).digest()

    # -- validation (setup.go:444-489) ----------------------------------

    def validate(self) -> None:
        if len(self.idemix_issuer_public_keys) != 1:
            raise SetupError(
                f"expected one idemix issuer public key, found [{len(self.idemix_issuer_public_keys)}]")
        for issuer in self.idemix_issuer_public_keys:
            if not issuer.public_key:
                raise SetupError("expected idemix issuer public key to be non-empty")
        if len(self.pedersen_generators) != 3:
            raise SetupError("invalid pedersen generators")
        for pt in self.pedersen_generators:
            if pt is None or pt.is_identity() or not pt.on_curve():
                raise SetupError("invalid pedersen generators")
        if self.range_proof_params is None:
            raise SetupError("invalid public parameters: nil range proof parameters")
        if self.range_proof_params.bit_length not in SUPPORTED_PRECISIONS:
            raise SetupError(
                f"invalid bit length [{self.range_proof_params.bit_length}], "
                f"should be one of {list(SUPPORTED_PRECISIONS)}")
        self.range_proof_params.validate()
        if self.quantity_precision != self.range_proof_params.bit_length:
            raise SetupError(
                "invalid public parameters: quantity precision should be "
                f"[{self.range_proof_params.bit_length}] instead it is [{self.quantity_precision}]")
        if self.compute_max_token_value() != self.max_token:
            raise SetupError(
                f"invalid maxt token, [{self.compute_max_token_value()}]!=[{self.max_token}]")


def setup(bit_length: int, idemix_issuer_pk: bytes = b"\x00",
          idemix_curve_id: int = bn254.CURVE_ID,
          label: str = DLOG_PUBLIC_PARAMETERS,
          tpu_batch: TpuBatchParams | None = None) -> PublicParams:
    """reference setup.go:192-225."""
    if bit_length > 64:
        raise SetupError(f"invalid bit length [{bit_length}], should be smaller than 64")
    if bit_length == 0:
        raise SetupError("invalid bit length, should be greater than 0")
    pp = PublicParams(
        label=label,
        curve=bn254.CURVE_ID,
        version=VERSION,
        idemix_issuer_public_keys=[
            IdemixIssuerPublicKey(public_key=idemix_issuer_pk, curve=idemix_curve_id)
        ],
        quantity_precision=bit_length,
    )
    pp.generate_pedersen_parameters()
    pp.generate_range_proof_parameters(bit_length)
    pp.max_token = pp.compute_max_token_value()
    if tpu_batch is not None:
        pp.tpu_batch = tpu_batch
    return pp
