"""Token commitments and openings.

Behavioral mirror of reference token/core/zkatdlog/nogh/v1/crypto/token/token.go:
a zkatdlog token is (Owner bytes, Data = g0^H(type) * g1^value * g2^bf in G1);
metadata carries the opening (Type, Value, BlindingFactor, Issuer).
"""

from __future__ import annotations

from dataclasses import dataclass

from . import bn254
from .bn254 import G1, fr_rand, g1_add, g1_mul, hash_to_zr


class TokenError(Exception):
    pass


def commit(vector: list[int], generators: list[G1]) -> G1:
    """Pedersen commitment (token.go:208-217)."""
    com = bn254.G1_IDENTITY
    for i, v in enumerate(vector):
        if v is None:
            raise TokenError("cannot commit a nil element")
        com = g1_add(com, g1_mul(generators[i], v))
    return com


def commit_token(token_type: str, value: int, blinding_factor: int,
                 pedersen_generators: list[G1]) -> G1:
    """Data = g0^H(type) g1^value g2^bf (token.go:95-107)."""
    return commit([hash_to_zr(token_type.encode()), value, blinding_factor],
                  pedersen_generators)


@dataclass
class TokenDataWitness:
    """Opening of Data (token.go:182-196)."""

    token_type: str
    value: int
    blinding_factor: int

    def clone(self) -> "TokenDataWitness":
        return TokenDataWitness(self.token_type, self.value, self.blinding_factor)

    def as_tuple(self) -> tuple[str, int, int]:
        return (self.token_type, self.value, self.blinding_factor)


def get_tokens_with_witness(values: list[int], token_type: str,
                            pedersen_generators: list[G1]
                            ) -> tuple[list[G1], list[TokenDataWitness]]:
    """Fresh commitments + witnesses for output values (token.go:109-130)."""
    witnesses = [TokenDataWitness(token_type, v, fr_rand()) for v in values]
    tokens = [
        commit_token(w.token_type, w.value, w.blinding_factor, pedersen_generators)
        for w in witnesses
    ]
    return tokens, witnesses


def to_clear(data: G1, owner: bytes, token_type: str, value: int,
             blinding_factor: int, pedersen_generators: list[G1]) -> dict:
    """Open a committed token and fail if the opening mismatches
    (token.go:69-83). Returns the clear token {type, quantity, owner}."""
    com = commit_token(token_type, value, blinding_factor, pedersen_generators)
    if com != data:
        raise TokenError(
            "cannot retrieve token in the clear: output does not match provided opening")
    return {"type": token_type, "quantity": hex(value), "owner": owner}


def audit_inspect_output(data: G1, token_type: str, value: int,
                         blinding_factor: int,
                         pedersen_generators: list[G1]) -> None:
    """Auditor commitment-reopen check (reference crypto/audit/auditor.go:225-246):
    recompute commit(H(type), v, bf) and compare with the token data. This is
    the per-output check that models.audit batches on TPU."""
    if value is None or blinding_factor is None:
        raise TokenError("invalid opening")
    com = commit_token(token_type, value, blinding_factor, pedersen_generators)
    if com != data:
        raise TokenError("output does not match the provided opening")
