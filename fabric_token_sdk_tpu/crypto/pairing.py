"""BN254 optimal-ate pairing on the host: Fp2/Fp6/Fp12 tower + G2.

Restores the reference's pairing-based capability surface — the Idemix
credential chain proves possession of an issuer signature whose verification
equation is a pairing product (reference token/services/identity/idemix/
km.go:46-365 via IBM/idemix and mathlib's bn254 pairing). Pairings run
host-side only, per enrollment / per identity check — never inside the TPU
batch verification path (SURVEY.md §7 keeps pairings off the hot path).

Tower (standard alt_bn128 construction):
    Fp2  = Fp[u]  / (u^2 + 1)
    Fp6  = Fp2[v] / (v^3 - xi),  xi = 9 + u
    Fp12 = Fp6[w] / (w^2 - v)          => w^6 = xi
G2 lives on the D-type sextic twist E'(Fp2): y^2 = x^3 + 3/xi, untwisted
into E(Fp12) by (x, y) -> (x*w^2, y*w^3).

Representation: Fp2 elements are (a0, a1) int tuples; Fp6 three Fp2s; Fp12
two Fp6s. Pure-Python big-int arithmetic — simple, auditable, and fast
enough (~100 ms/pairing) for the enrollment-time paths that need it.
"""

from __future__ import annotations

from .bn254 import P
from .bn254 import R as _R_ORDER

# BN parameter t: p = 36t^4 + 36t^3 + 24t^2 + 6t + 1.
BN_T = 4965661367192848881
ATE_LOOP = 6 * BN_T + 2  # 29793968203157093288 (> 0: no final conjugation)

# ---------------------------------------------------------------------------
# Fp2 = Fp[u]/(u^2+1)
# ---------------------------------------------------------------------------

FP2_ZERO = (0, 0)
FP2_ONE = (1, 0)
XI = (9, 1)  # the Fp6/Fp12 non-residue 9 + u


def fp2_add(a, b):
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def fp2_sub(a, b):
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def fp2_neg(a):
    return ((-a[0]) % P, (-a[1]) % P)


def fp2_mul(a, b):
    # (a0 + a1 u)(b0 + b1 u) with u^2 = -1 (3-mul Karatsuba)
    t0 = a[0] * b[0]
    t1 = a[1] * b[1]
    t2 = (a[0] + a[1]) * (b[0] + b[1])
    return ((t0 - t1) % P, (t2 - t0 - t1) % P)


def fp2_sqr(a):
    # (a0 + a1 u)^2 = (a0+a1)(a0-a1) + 2 a0 a1 u
    t = a[0] * a[1]
    return ((a[0] + a[1]) * (a[0] - a[1]) % P, (t + t) % P)


def fp2_scalar(a, k: int):
    return (a[0] * k % P, a[1] * k % P)


def fp2_conj(a):
    return (a[0], (-a[1]) % P)


def fp2_inv(a):
    # 1/(a0 + a1 u) = (a0 - a1 u)/(a0^2 + a1^2)
    den = (a[0] * a[0] + a[1] * a[1]) % P
    if den == 0:
        raise ZeroDivisionError("fp2 inverse of zero")
    inv = pow(den, P - 2, P)
    return (a[0] * inv % P, (-a[1]) * inv % P)


def fp2_pow(a, e: int):
    out = FP2_ONE
    while e:
        if e & 1:
            out = fp2_mul(out, a)
        a = fp2_sqr(a)
        e >>= 1
    return out


# ---------------------------------------------------------------------------
# Fp6 = Fp2[v]/(v^3 - xi)
# ---------------------------------------------------------------------------

FP6_ZERO = (FP2_ZERO, FP2_ZERO, FP2_ZERO)
FP6_ONE = (FP2_ONE, FP2_ZERO, FP2_ZERO)


def _mul_xi(a):
    """a * (9 + u) for a in Fp2."""
    return ((9 * a[0] - a[1]) % P, (9 * a[1] + a[0]) % P)


def fp6_add(a, b):
    return tuple(fp2_add(x, y) for x, y in zip(a, b))


def fp6_sub(a, b):
    return tuple(fp2_sub(x, y) for x, y in zip(a, b))


def fp6_neg(a):
    return tuple(fp2_neg(x) for x in a)


def fp6_mul(a, b):
    a0, a1, a2 = a
    b0, b1, b2 = b
    t0 = fp2_mul(a0, b0)
    t1 = fp2_mul(a1, b1)
    t2 = fp2_mul(a2, b2)
    # Toom/Karatsuba-style interpolation (v^3 = xi)
    c0 = fp2_add(t0, _mul_xi(fp2_sub(
        fp2_mul(fp2_add(a1, a2), fp2_add(b1, b2)), fp2_add(t1, t2))))
    c1 = fp2_add(fp2_sub(fp2_mul(fp2_add(a0, a1), fp2_add(b0, b1)),
                         fp2_add(t0, t1)), _mul_xi(t2))
    c2 = fp2_add(fp2_sub(fp2_mul(fp2_add(a0, a2), fp2_add(b0, b2)),
                         fp2_add(t0, t2)), t1)
    return (c0, c1, c2)


def fp6_sqr(a):
    return fp6_mul(a, a)


def fp6_mul_by_v(a):
    """a * v: (a0, a1, a2) -> (xi*a2, a0, a1)."""
    return (_mul_xi(a[2]), a[0], a[1])


def fp6_inv(a):
    a0, a1, a2 = a
    c0 = fp2_sub(fp2_sqr(a0), _mul_xi(fp2_mul(a1, a2)))
    c1 = fp2_sub(_mul_xi(fp2_sqr(a2)), fp2_mul(a0, a1))
    c2 = fp2_sub(fp2_sqr(a1), fp2_mul(a0, a2))
    t = fp2_add(_mul_xi(fp2_add(fp2_mul(a2, c1), fp2_mul(a1, c2))),
                fp2_mul(a0, c0))
    tinv = fp2_inv(t)
    return (fp2_mul(c0, tinv), fp2_mul(c1, tinv), fp2_mul(c2, tinv))


# ---------------------------------------------------------------------------
# Fp12 = Fp6[w]/(w^2 - v)
# ---------------------------------------------------------------------------

FP12_ONE = (FP6_ONE, FP6_ZERO)


def fp12_add(a, b):
    return (fp6_add(a[0], b[0]), fp6_add(a[1], b[1]))


def fp12_sub(a, b):
    return (fp6_sub(a[0], b[0]), fp6_sub(a[1], b[1]))


def fp12_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    t0 = fp6_mul(a0, b0)
    t1 = fp6_mul(a1, b1)
    c0 = fp6_add(t0, fp6_mul_by_v(t1))
    c1 = fp6_sub(fp6_sub(fp6_mul(fp6_add(a0, a1), fp6_add(b0, b1)), t0), t1)
    return (c0, c1)


def fp12_sqr(a):
    return fp12_mul(a, a)


def fp12_conj(a):
    """Conjugation over Fp6 (w -> -w) = x^(p^6)."""
    return (a[0], fp6_neg(a[1]))


def fp12_inv(a):
    a0, a1 = a
    t = fp6_inv(fp6_sub(fp6_sqr(a0), fp6_mul_by_v(fp6_sqr(a1))))
    return (fp6_mul(a0, t), fp6_neg(fp6_mul(a1, t)))


def fp12_pow(a, e: int):
    out = FP12_ONE
    while e:
        if e & 1:
            out = fp12_mul(out, a)
        a = fp12_sqr(a)
        e >>= 1
    return out


def fp12_scalar_fp(a, k: int):
    """Multiply an Fp12 element by a scalar in Fp."""
    return (tuple(fp2_scalar(c, k) for c in a[0]),
            tuple(fp2_scalar(c, k) for c in a[1]))


# Frobenius: coefficient of the v^j w^e basis slot (w-exponent m = 2j+e)
# picks up xi^(m(p-1)/6) after conjugating the Fp2 coefficient
# (w^p = w * xi^((p-1)/6) since w^6 = xi and 6 | p-1).
_FROB_GAMMA = [fp2_pow(XI, m * (P - 1) // 6) for m in range(6)]


def fp12_frobenius(a):
    out0, out1 = [], []
    for j in range(3):
        out0.append(fp2_mul(fp2_conj(a[0][j]), _FROB_GAMMA[2 * j]))
        out1.append(fp2_mul(fp2_conj(a[1][j]), _FROB_GAMMA[2 * j + 1]))
    return (tuple(out0), tuple(out1))


# ---------------------------------------------------------------------------
# G2: affine points over Fp2 on the twist y^2 = x^3 + 3/xi
# ---------------------------------------------------------------------------

B2 = fp2_mul((3, 0), fp2_inv(XI))  # twist coefficient b' = 3/(9+u)

G2_GENERATOR = (
    (10857046999023057135944570762232829481370756359578518086990519993285655852781,
     11559732032986387107991004021392285783925812861821192530917403151452391805634),
    (8495653923123431417604973247489272438418190587263600148770280649306958101930,
     4082367875863433681332203403145435568316851327593401208105741076214120093531),
)

# G2 identity is None (affine representation, matching bn254.G1's style).


def g2_is_on_curve(q) -> bool:
    if q is None:
        return True
    x, y = q
    return fp2_sqr(y) == fp2_add(fp2_mul(fp2_sqr(x), x), B2)


def g2_neg(q):
    if q is None:
        return None
    return (q[0], fp2_neg(q[1]))


def g2_add(a, b):
    if a is None:
        return b
    if b is None:
        return a
    x1, y1 = a
    x2, y2 = b
    if x1 == x2:
        if fp2_add(y1, y2) == FP2_ZERO:
            return None
        lam = fp2_mul(fp2_scalar(fp2_sqr(x1), 3),
                      fp2_inv(fp2_scalar(y1, 2)))
    else:
        lam = fp2_mul(fp2_sub(y2, y1), fp2_inv(fp2_sub(x2, x1)))
    x3 = fp2_sub(fp2_sub(fp2_sqr(lam), x1), x2)
    y3 = fp2_sub(fp2_mul(lam, fp2_sub(x1, x3)), y1)
    return (x3, y3)


def g2_double(q):
    return g2_add(q, q)


def g2_mul(q, k: int):
    if k < 0:
        return g2_neg(g2_mul(q, -k))
    out = None
    add = q
    while k:
        if k & 1:
            out = g2_add(out, add)
        add = g2_add(add, add)
        k >>= 1
    return out


def g2_in_subgroup(q) -> bool:
    """Order-r check (the twist has cofactor > 1, unlike G1)."""
    return g2_is_on_curve(q) and g2_mul(q, _R_ORDER) is None


# ---------------------------------------------------------------------------
# Optimal ate pairing
# ---------------------------------------------------------------------------

def _untwist(q):
    """E'(Fp2) -> E(Fp12): (x, y) -> (x w^2, y w^3).

    w^2 = v, so x w^2 = (0, x, 0) in the Fp6 'even' part; w^3 = v w, so
    y w^3 = ((0, y, 0)) in the 'odd' part."""
    x, y = q
    return (((FP2_ZERO, x, FP2_ZERO), FP6_ZERO),
            (FP6_ZERO, (FP2_ZERO, y, FP2_ZERO)))


def _embed_fp(c: int):
    """Fp scalar -> Fp12."""
    return (((c % P, 0), FP2_ZERO, FP2_ZERO), FP6_ZERO)


def _pt12_eq(a, b):
    return a == b


def _line(t, q, p_embed):
    """Evaluate the line through t, q (E(Fp12) affine) at the embedded G1
    point, returning (value, t+q). Vertical lines evaluate into the Fp6
    subfield, which the final exponentiation kills — standard even-degree
    denominator elimination — so they are skipped (value 1)."""
    xp, yp = p_embed
    x1, y1 = t
    x2, y2 = q
    if x1 == x2 and y1 == y2:
        num = fp12_scalar_fp(fp12_sqr(x1), 3)
        lam = fp12_mul(num, fp12_inv(fp12_scalar_fp(y1, 2)))
    elif x1 == x2:
        return FP12_ONE, None  # vertical: subfield value, point at infinity
    else:
        lam = fp12_mul(fp12_sub(y2, y1), fp12_inv(fp12_sub(x2, x1)))
    # l(P) = lam*(xp - x1) - (yp - y1)
    val = fp12_sub(fp12_mul(lam, fp12_sub(xp, x1)), fp12_sub(yp, y1))
    x3 = fp12_sub(fp12_sub(fp12_sqr(lam), x1), x2)
    y3 = fp12_sub(fp12_mul(lam, fp12_sub(x1, x3)), y1)
    return val, (x3, y3)


def _pt12_frobenius(q):
    return (fp12_frobenius(q[0]), fp12_frobenius(q[1]))


def _pt12_neg(q):
    zero = (FP6_ZERO, FP6_ZERO)
    return (q[0], fp12_sub(zero, q[1]))


def _g1_is_identity(p) -> bool:
    """bn254.G1 spells the identity as G1(0, 0, inf=True) — never None —
    but accept both spellings defensively."""
    return p is None or getattr(p, "inf", False)


def miller_loop(p, q) -> tuple:
    """Miller loop f_{6t+2,Q}(P) * line corrections (optimal ate, BN254).

    p: bn254.G1 (affine host point); q: G2 affine pair over Fp2.
    Returns an Fp12 element — run final_exponentiation (or accumulate a
    product of loops first) to land in GT. Identity inputs contribute the
    neutral element (e(O, Q) = e(P, O) = 1).
    """
    if _g1_is_identity(p) or q is None:
        return FP12_ONE
    p_embed = (_embed_fp(p.x), _embed_fp(p.y))
    q12 = _untwist(q)
    f = FP12_ONE
    t = q12
    for i in range(ATE_LOOP.bit_length() - 2, -1, -1):
        val, t = _line(t, t, p_embed)
        f = fp12_mul(fp12_sqr(f), val)
        if (ATE_LOOP >> i) & 1:
            val, t = _line(t, q12, p_embed)
            f = fp12_mul(f, val)
    # the two optimal-ate correction lines with pi(Q) and -pi^2(Q)
    q1 = _pt12_frobenius(q12)
    q2 = _pt12_neg(_pt12_frobenius(q1))
    val, t = _line(t, q1, p_embed)
    f = fp12_mul(f, val)
    val, _ = _line(t, q2, p_embed)
    f = fp12_mul(f, val)
    return f


# hard-part exponent (p^4 - p^2 + 1) / r of the final exponentiation
_HARD_EXP = (P ** 4 - P ** 2 + 1) // _R_ORDER


def final_exponentiation(f) -> tuple:
    """f^((p^12-1)/r): easy part via conjugation/Frobenius, hard part by
    direct square-and-multiply (simple > clever here; ~1000 Fp12 ops)."""
    # easy: f^(p^6-1) = conj(f) * f^-1, then ^(p^2+1)
    e = fp12_mul(fp12_conj(f), fp12_inv(f))
    e = fp12_mul(fp12_frobenius(fp12_frobenius(e)), e)
    return fp12_pow(e, _HARD_EXP)


def pairing(p, q) -> tuple:
    """e(P, Q) for P in G1 (bn254.G1), Q in G2. Returns an Fp12 element."""
    return final_exponentiation(miller_loop(p, q))


def pairing_product_is_one(pairs) -> bool:
    """prod e(P_i, Q_i) == 1, with a single shared final exponentiation."""
    acc = FP12_ONE
    for p, q in pairs:
        acc = fp12_mul(acc, miller_loop(p, q))
    return final_exponentiation(acc) == FP12_ONE


def gt_eq(p1, q1, p2, q2) -> bool:
    """e(P1, Q1) == e(P2, Q2) without computing either final exp twice:
    product with one side negated must be 1."""
    from .bn254 import g1_neg

    neg_p2 = None if _g1_is_identity(p2) else g1_neg(p2)
    return pairing_product_is_one([(p1, q1), (neg_p2, q2)])
