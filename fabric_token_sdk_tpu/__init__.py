"""fabric_token_sdk_tpu — a TPU-native token framework.

A brand-new framework with the capabilities of the Hyperledger Fabric Token SDK
(reference: /root/reference, Go). The defining difference: zero-knowledge proof
verification (Bulletproof-style range proofs, inner-product arguments, Sigma-protocol
balance proofs over BN254) is a first-class batched TPU workload built on
JAX/XLA limb-decomposed field arithmetic, exposed behind the driver Validator
plugin boundary.

Layout:
  ops/       TPU compute kernels: limb bignum, Fp/Fr Montgomery arithmetic,
             BN254 G1 group ops (complete formulas), batched MSM.
  models/    batched proof-system verifiers/provers (range proof, IPA,
             type-and-sum, same-type, audit reopen) as JAX programs.
  parallel/  device mesh + sharded batch verification (pjit/shard_map).
  crypto/    host-side control plane: pure-Python BN254 oracle, gnark-compatible
             serialization, Fiat-Shamir transcripts, public parameters.
  token/     token API (ManagementService, Request, token model, quantities).
  driver/    driver SPI (interfaces + wire formats).
  core/      driver registry + generic validator skeleton + drivers
             (fabtoken, zkatdlog).
  services/  ttx lifecycle, auditor, tokens, selector, identity, network,
             interop/htlc, db facades.
  sdk/       dependency wiring.
  tokengen/  CLI for public-parameter generation.
  utils/     codecs and helpers.
"""

__version__ = "0.1.0"
