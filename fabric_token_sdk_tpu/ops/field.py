"""Vectorized Montgomery field arithmetic over 16-bit limbs in uint32 lanes.

Design notes (why this maps well to TPU / XLA, SURVEY.md §7 item 1):

- All loops below run over the *static* limb index (16 or 32 iterations) and
  are unrolled at trace time; the batch dimensions are the vector axes, so
  every emitted op is a full-width VPU op over the batch.
- 16x16-bit products fit exactly in uint32 ((2^16-1)^2 < 2^32), and lazy
  column accumulation adds at most ~2^6 such 16-bit half-terms, keeping
  every lane < 2^23 — no 64-bit integers anywhere, which TPUs lack natively.
- Montgomery (radix 2^256) keeps reduction multiplication-only; the single
  carry chain per mul is a 16-step scalar-dependency but each step is a
  batch-wide vector op.

The functions are modulus-generic: `FieldSpec` bundles the limb constants for
Fp (point coordinates) and Fr (scalars). Equivalent of the reference's
IBM/mathlib -> gnark-crypto assembly field layer (reference
token/core/zkatdlog/nogh/v1/crypto/setup.go:14).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np

from . import limbs as L

MASK = jnp.uint32(L.LIMB_MASK)
BITS = L.LIMB_BITS
N = L.NLIMBS


@dataclass(frozen=True)
class FieldSpec:
    """Static limb constants for one prime field (hashable -> jit-static)."""

    name: str
    mod: tuple[int, ...]       # modulus limbs
    r1: tuple[int, ...]        # montgomery 1
    r2: tuple[int, ...]        # montgomery R^2 (for to_mont)
    n0inv: int                 # -mod^-1 mod 2^16

    @property
    def mod_arr(self) -> jnp.ndarray:
        return jnp.asarray(np.array(self.mod, dtype=np.uint32))

    @property
    def r1_arr(self) -> jnp.ndarray:
        return jnp.asarray(np.array(self.r1, dtype=np.uint32))

    @property
    def r2_arr(self) -> jnp.ndarray:
        return jnp.asarray(np.array(self.r2, dtype=np.uint32))


FP = FieldSpec(
    name="fp",
    mod=tuple(int(v) for v in L.P_LIMBS),
    r1=tuple(int(v) for v in L.P_R1_LIMBS),
    r2=tuple(int(v) for v in L.P_R2_LIMBS),
    n0inv=int(L.P_N0INV),
)

FR = FieldSpec(
    name="fr",
    mod=tuple(int(v) for v in L.R_LIMBS),
    r1=tuple(int(v) for v in L.R_R1_LIMBS),
    r2=tuple(int(v) for v in L.R_R2_LIMBS),
    n0inv=int(L.R_N0INV),
)


def _carry_propagate(t: jnp.ndarray, out_limbs: int) -> jnp.ndarray:
    """Propagate lazy column sums (< 2^32) into canonical 16-bit limbs.

    t: (..., K) uint32. Returns (..., out_limbs); caller guarantees the value
    fits (any final carry would be dropped).
    """
    cols = []
    carry = jnp.zeros(t.shape[:-1], dtype=jnp.uint32)
    k = t.shape[-1]
    for i in range(out_limbs):
        cur = (t[..., i] if i < k else jnp.zeros_like(carry)) + carry
        cols.append(cur & MASK)
        carry = cur >> BITS
    return jnp.stack(cols, axis=-1)


def _sub_limbs(a: jnp.ndarray, b: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """a - b over canonical limbs; returns (diff, borrow_out in {0,1})."""
    cols = []
    borrow = jnp.zeros(a.shape[:-1], dtype=jnp.uint32)
    for i in range(a.shape[-1]):
        cur = a[..., i] + jnp.uint32(1 << BITS) - b[..., i] - borrow
        cols.append(cur & MASK)
        borrow = jnp.uint32(1) - (cur >> BITS)
    return jnp.stack(cols, axis=-1), borrow


def add(a: jnp.ndarray, b: jnp.ndarray, spec: FieldSpec) -> jnp.ndarray:
    """Modular addition of canonical-limb values < mod."""
    s = _carry_propagate(a + b, N + 1)
    # value < 2 * mod < 2^257: compare/subtract over 17 limbs.
    mod17 = jnp.concatenate(
        [spec.mod_arr, jnp.zeros(1, dtype=jnp.uint32)]).astype(jnp.uint32)
    mod17 = jnp.broadcast_to(mod17, s.shape)
    diff, borrow = _sub_limbs(s, mod17)
    keep = (borrow != 0)[..., None]
    return jnp.where(keep, s, diff)[..., :N]


def sub(a: jnp.ndarray, b: jnp.ndarray, spec: FieldSpec) -> jnp.ndarray:
    """Modular subtraction of canonical-limb values < mod."""
    diff, borrow = _sub_limbs(a, b)
    mod = jnp.broadcast_to(spec.mod_arr, a.shape)
    fixed = _carry_propagate(diff + mod, N)
    need_fix = (borrow != 0)[..., None]
    return jnp.where(need_fix, fixed, diff)


def neg(a: jnp.ndarray, spec: FieldSpec) -> jnp.ndarray:
    """Modular negation: mod - a, with -0 = 0."""
    mod = jnp.broadcast_to(spec.mod_arr, a.shape)
    diff, _ = _sub_limbs(mod, a)
    zero = is_zero(a)[..., None]
    return jnp.where(zero, jnp.zeros_like(a), diff)


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    """True where all limbs are zero; shape = batch shape."""
    return jnp.all(a == 0, axis=-1)


def mont_mul(a: jnp.ndarray, b: jnp.ndarray, spec: FieldSpec) -> jnp.ndarray:
    """Montgomery product a*b*R^-1 mod m over (..., 16) uint32 limbs.

    Product scanning with lo/hi split lazy columns, then an interleaved
    word-by-word Montgomery reduction. Output canonical (< mod).
    """
    shape = jnp.broadcast_shapes(a.shape, b.shape)
    a = jnp.broadcast_to(a, shape)
    b = jnp.broadcast_to(b, shape)
    batch = shape[:-1]
    t = jnp.zeros(batch + (2 * N + 1,), dtype=jnp.uint32)

    # Schoolbook partial products, lazily accumulated per column.
    for i in range(N):
        p = a[..., i : i + 1] * b  # (..., N) full 32-bit products
        t = t.at[..., i : i + N].add(p & MASK)
        t = t.at[..., i + 1 : i + N + 1].add(p >> BITS)

    # Interleaved Montgomery reduction: one m_i per low limb.
    mod = spec.mod_arr
    n0inv = jnp.uint32(spec.n0inv)
    carry = jnp.zeros(batch, dtype=jnp.uint32)
    for i in range(N):
        cur = t[..., i] + carry
        m = ((cur & MASK) * n0inv) & MASK
        pm = m[..., None] * mod  # (..., N)
        t = t.at[..., i : i + N].add(pm & MASK)
        t = t.at[..., i + 1 : i + N + 1].add(pm >> BITS)
        carry = (cur + ((m * mod[0]) & MASK)) >> BITS

    hi = t[..., N:]
    hi = hi.at[..., 0].add(carry)
    res = _carry_propagate(hi, N + 1)
    mod17 = jnp.concatenate([spec.mod_arr, jnp.zeros(1, dtype=jnp.uint32)])
    mod17 = jnp.broadcast_to(mod17, res.shape)
    diff, borrow = _sub_limbs(res, mod17)
    keep = (borrow != 0)[..., None]
    return jnp.where(keep, res, diff)[..., :N]


def mont_sqr(a: jnp.ndarray, spec: FieldSpec) -> jnp.ndarray:
    return mont_mul(a, a, spec)


def to_mont(a: jnp.ndarray, spec: FieldSpec) -> jnp.ndarray:
    return mont_mul(a, jnp.broadcast_to(spec.r2_arr, a.shape), spec)


def from_mont(a: jnp.ndarray, spec: FieldSpec) -> jnp.ndarray:
    one = jnp.zeros_like(a).at[..., 0].set(1)
    return mont_mul(a, one, spec)


def select(cond: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Branchless limb select: cond is a batch-shaped bool array."""
    return jnp.where(cond[..., None], a, b)


def double_val(a: jnp.ndarray, spec: FieldSpec) -> jnp.ndarray:
    return add(a, a, spec)
