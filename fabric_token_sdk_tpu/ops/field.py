"""Vectorized Montgomery field arithmetic over 16-bit limbs in uint32 lanes.

Design notes (why this maps well to TPU / XLA, SURVEY.md §7 item 1):

- 16x16-bit products fit exactly in uint32 ((2^16-1)^2 < 2^32) and lazy
  column accumulation adds at most ~2^5 such 16-bit half-terms, keeping every
  lane < 2^22 — no 64-bit integers anywhere, which TPUs lack natively.
- Column sums use a shift-and-add schedule (one jnp.pad + add per limb row):
  no dynamic-update-slices, so traced graphs stay small and XLA compiles
  them quickly; the batch dimensions are the vector axes and every emitted
  op is a full-width VPU op over the batch.
- Carry/borrow chains are `lax.scan` over the limb axis: sequential by
  nature (16-33 steps) but each step is one batch-wide vector op and the
  scan body compiles once.
- Montgomery reduction is the separated (SOS) form: m = T_lo * N' mod 2^256,
  then (T + m*N) >> 256 — three shift-and-add products per modular multiply.

The functions are modulus-generic: `FieldSpec` bundles the limb constants
for Fp (point coordinates) and Fr (scalars). This layer is the TPU-native
equivalent of the reference's IBM/mathlib -> gnark-crypto assembly field
layer (reference token/core/zkatdlog/nogh/v1/crypto/setup.go:14).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from . import limbs as L

# Python int (not a jnp scalar): keeps kernels that trace field ops inside
# pallas_call bodies from capturing a device constant; dtype promotion with
# uint32 arrays is unchanged.
MASK = L.LIMB_MASK
BITS = L.LIMB_BITS
N = L.NLIMBS


@dataclass(frozen=True)
class FieldSpec:
    """Static limb constants for one prime field (hashable -> jit-static)."""

    name: str
    mod: tuple[int, ...]        # modulus limbs
    r1: tuple[int, ...]         # montgomery 1
    r2: tuple[int, ...]         # montgomery R^2 (for to_mont)
    nprime: tuple[int, ...]     # -mod^-1 mod 2^256, full 16 limbs

    @property
    def mod_arr(self) -> jnp.ndarray:
        return jnp.asarray(np.array(self.mod, dtype=np.uint32))

    @property
    def r1_arr(self) -> jnp.ndarray:
        return jnp.asarray(np.array(self.r1, dtype=np.uint32))

    @property
    def r2_arr(self) -> jnp.ndarray:
        return jnp.asarray(np.array(self.r2, dtype=np.uint32))

    @property
    def nprime_arr(self) -> jnp.ndarray:
        return jnp.asarray(np.array(self.nprime, dtype=np.uint32))

    @property
    def mod_int(self) -> int:
        v = 0
        for limb in reversed(self.mod):
            v = (v << BITS) | limb
        return v


def _spec(name, mod_limbs, r1, r2, mod_int) -> FieldSpec:
    nprime = (-pow(mod_int, -1, L.MONT_R)) % L.MONT_R
    return FieldSpec(
        name=name,
        mod=tuple(int(v) for v in mod_limbs),
        r1=tuple(int(v) for v in r1),
        r2=tuple(int(v) for v in r2),
        nprime=tuple(int(v) for v in L.int_to_limbs(nprime)),
    )


FP = _spec("fp", L.P_LIMBS, L.P_R1_LIMBS, L.P_R2_LIMBS, L.P_INT)
FR = _spec("fr", L.R_LIMBS, L.R_R1_LIMBS, L.R_R2_LIMBS, L.R_INT)


def _shift_right_one(x: jnp.ndarray) -> jnp.ndarray:
    """x_i -> x_{i-1} along the limb axis, zero-filled at i=0."""
    pad = [(0, 0)] * (x.ndim - 1) + [(1, 0)]
    return jnp.pad(x[..., :-1], pad)


def _lookahead(g: jnp.ndarray, p: jnp.ndarray) -> jnp.ndarray:
    """Exclusive carry/borrow-lookahead prefix over the limb axis.

    Kogge-Stone generate/propagate: carry_{0..i} = g_i | (p_i & carry_{0..i-1}).
    Returns carry_in per limb (exclusive prefix). Loop-free: the log2(limbs)
    combine steps are unrolled explicitly (pad + slice shifts only — an
    associative_scan here emits zero-size slices that Mosaic, the pallas TPU
    lowering, rejects; the unrolled form runs everywhere and traces to the
    same number of vector ops).
    """
    n = g.shape[-1]
    pad_cfg = lambda d: [(0, 0)] * (g.ndim - 1) + [(d, 0)]
    d = 1
    while d < n:
        # combine((g,p) shifted right by d, (g,p)): shifted-in identity is
        # (g=0, p=1) so lanes below d keep their current value.
        g_s = jnp.pad(g[..., :-d], pad_cfg(d))
        p_s = jnp.pad(p[..., :-d], pad_cfg(d), constant_values=True)
        g = g | (p & g_s)
        p = p & p_s
        d *= 2
    return _shift_right_one(g.astype(jnp.uint32))


def _carry_propagate(t: jnp.ndarray, out_limbs: int) -> jnp.ndarray:
    """Propagate lazy column sums (< 2^32) into canonical 16-bit limbs.

    t: (..., K) uint32. Returns (..., out_limbs); caller guarantees the
    value fits (any final carry is dropped). Two shift-folds bring every
    lane to <= 2^16, then one exact lookahead pass resolves ripples.
    """
    k = t.shape[-1]
    if k < out_limbs:
        t = jnp.concatenate(
            [t, jnp.zeros(t.shape[:-1] + (out_limbs - k,), dtype=t.dtype)],
            axis=-1)
    else:
        t = t[..., :out_limbs]
    v = (t & MASK) + _shift_right_one(t >> BITS)      # <= 2^17
    v = (v & MASK) + _shift_right_one(v >> BITS)      # <= 2^16
    g = (v >> BITS).astype(bool)                      # v == 2^16 exactly
    p = v == MASK
    carry_in = _lookahead(g, p)
    return (v + carry_in) & MASK


def _sub_limbs(a: jnp.ndarray, b: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """a - b over canonical limbs; returns (diff, borrow_out in {0,1})."""
    b = jnp.broadcast_to(b, a.shape)
    g = a < b
    p = a == b
    borrow_in = _lookahead(g, p)
    diff = (a + jnp.uint32(1 << BITS) - b - borrow_in) & MASK
    # total borrow-out: generate at the top limb after including borrow chain
    # (static slices, not int indexing — jnp's scalar getitem emits a
    # dynamic_slice, which the pallas TPU lowering does not implement)
    top = lambda x: jnp.squeeze(x[..., x.shape[-1] - 1:], axis=-1)
    last_g = jnp.logical_or(top(g),
                            jnp.logical_and(top(p),
                                            top(borrow_in).astype(bool)))
    return diff, last_g.astype(jnp.uint32)


_DIAG_MATS: dict = {}


def _reduction_dtype() -> jnp.dtype:
    """Element type for the column-reduction matmuls.

    TPU: bf16 byte planes — every operand is an exact small integer
    (plane values <= 255, 0/1 diagonal matrix) and the MXU accumulates in
    f32, so four SINGLE-pass bf16 matmuls replace two SIX-pass
    Precision.HIGHEST f32 matmuls (the innermost cost of every mont_mul;
    3x less MXU work). CPU: f32 — XLA:CPU cannot run bf16 dots, and a
    single f32 pass is already exact there."""
    return jnp.float32 if jax.default_backend() == "cpu" else jnp.bfloat16


def _diag_mats(na: int, nb: int, out_cols: int):
    """0/1 matrices mapping flattened partial products to columns.

    M_lo[(i*nb+j), k] = 1 iff i+j == k; M_hi shifts by one limb. Column
    sums over <= 2^8 terms of <= 2^16 values stay < 2^24: exact in f32
    accumulation (both the bf16-planes TPU path and the f32 CPU path).
    """
    key = (na, nb, out_cols, str(_reduction_dtype()))
    if key not in _DIAG_MATS:
        lo = np.zeros((na * nb, out_cols), dtype=np.float32)
        hi = np.zeros((na * nb, out_cols), dtype=np.float32)
        for i in range(na):
            for j in range(nb):
                if i + j < out_cols:
                    lo[i * nb + j, i + j] = 1.0
                if i + j + 1 < out_cols:
                    hi[i * nb + j, i + j + 1] = 1.0
        dt = _reduction_dtype()
        _DIAG_MATS[key] = (lo.astype(dt), hi.astype(dt))
    m_lo, m_hi = _DIAG_MATS[key]
    return jnp.asarray(m_lo), jnp.asarray(m_hi)


def _shift_add_product(a: jnp.ndarray, b: jnp.ndarray, nb: int,
                       out_cols: int) -> jnp.ndarray:
    """Lazy column sums of the product a * b.

    a: (..., na) canonical limbs; b: (nb,) constant or (..., nb) limbs.
    Returns (..., out_cols) lazy columns (each < 2^24). Partial products
    are reduced along anti-diagonals with exact matmuls: on TPU the 32-bit
    products split into four bf16 byte planes (values <= 255, single MXU
    pass each, f32 accumulation); on CPU into two f32 16-bit halves.
    """
    na = a.shape[-1]
    p = a[..., :, None] * jnp.broadcast_to(b, a.shape[:-1] + (nb,))[..., None, :]
    flat = a.shape[:-1] + (na * nb,)
    m_lo, m_hi = _diag_mats(na, nb, out_cols)
    if _reduction_dtype() == jnp.bfloat16:
        # uint32 -> int32 -> bf16: Mosaic (pallas) has no direct u32->bf16
        # cast; the detour is exact (values <= 255) and free under XLA.
        bf = lambda x: x.astype(jnp.int32).astype(jnp.bfloat16)
        b0 = bf(p & 0xFF).reshape(flat)
        b1 = bf((p >> 8) & 0xFF).reshape(flat)
        b2 = bf((p >> 16) & 0xFF).reshape(flat)
        b3 = bf(p >> 24).reshape(flat)
        f32 = jnp.float32
        lo_cols = (
            jnp.matmul(b0, m_lo, preferred_element_type=f32)
            + jnp.matmul(b1, m_lo, preferred_element_type=f32) * 256.0)
        hi_cols = (
            jnp.matmul(b2, m_hi, preferred_element_type=f32)
            + jnp.matmul(b3, m_hi, preferred_element_type=f32) * 256.0)
        cols = lo_cols + hi_cols          # both < 2^24: exact f32 sum
    else:
        lo = (p & MASK).astype(jnp.float32).reshape(flat)
        hi = (p >> BITS).astype(jnp.float32).reshape(flat)
        # single f32 pass is exact on CPU (sums < 2^24)
        cols = (jnp.matmul(lo, m_lo, precision=jax.lax.Precision.HIGHEST)
                + jnp.matmul(hi, m_hi, precision=jax.lax.Precision.HIGHEST))
    # f32 -> int32 -> uint32 (exact: cols < 2^26); Mosaic lacks f32->u32
    return cols.astype(jnp.int32).astype(jnp.uint32)


_NIBBLE_MATS: dict = {}


def _nibble_toeplitz(const_limbs: tuple, out_cols: int) -> np.ndarray:
    """(64, out_cols*4) int8 Toeplitz matrix: nibble convolution with a
    CONSTANT multiplicand.

    Row i holds const nibble (k-i) at output-nibble column k, so
    nibbles(a) @ W = nibble column sums of a*const — values <= 64 terms x
    15*15 = 14400, well inside the int8-MXU's int32 accumulator."""
    key = (const_limbs, out_cols)
    if key not in _NIBBLE_MATS:
        c = []
        for limb in const_limbs:
            for shift in (0, 4, 8, 12):
                c.append((int(limb) >> shift) & 0xF)
        out_n = out_cols * 4
        w = np.zeros((64, out_n), dtype=np.int8)
        for i in range(64):
            for j in range(len(c)):
                if i + j < out_n:
                    w[i, i + j] = c[j]
        _NIBBLE_MATS[key] = w
    return _NIBBLE_MATS[key]


def _const_product_cols(a: jnp.ndarray, const_limbs: tuple,
                        out_cols: int) -> jnp.ndarray:
    """Lazy column sums of a * CONSTANT via one int8 MXU dot (TPU path).

    a: (..., 16) canonical limbs. Splits a into 64 int8 nibbles, contracts
    with the precomputed Toeplitz matrix (int8 x int8 -> int32, native MXU
    at 2x bf16 rate), then folds nibble columns (weights 1,16,256,4096)
    back to 16-bit limb columns: lazy cols < 2^26, exact throughout.
    Replaces 256 VPU multiplies + two matmuls per constant product."""
    l = a.astype(jnp.int32)
    nib = jnp.stack([l & 0xF, (l >> 4) & 0xF, (l >> 8) & 0xF,
                     (l >> 12) & 0xF], axis=-1).astype(jnp.int8)
    nib = nib.reshape(*a.shape[:-1], 64)
    w = jnp.asarray(_nibble_toeplitz(const_limbs, out_cols))
    cols_n = jax.lax.dot_general(
        nib, w, (((nib.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    cn = cols_n.reshape(*cols_n.shape[:-1], out_cols, 4).astype(jnp.uint32)
    return (cn[..., 0] + (cn[..., 1] << 4) + (cn[..., 2] << 8)
            + (cn[..., 3] << 12))


def _cond_sub_mod(res: jnp.ndarray, spec: FieldSpec) -> jnp.ndarray:
    """One conditional subtract of mod over N+1 canonical limbs -> N limbs."""
    mod_ext = jnp.concatenate([spec.mod_arr, jnp.zeros(1, dtype=jnp.uint32)])
    diff, borrow = _sub_limbs(res, mod_ext)
    keep = (borrow != 0)[..., None]
    return jnp.where(keep, res, diff)[..., :N]


def add(a: jnp.ndarray, b: jnp.ndarray, spec: FieldSpec) -> jnp.ndarray:
    """Modular addition of canonical-limb values < mod."""
    s = _carry_propagate(a + b, N + 1)
    return _cond_sub_mod(s, spec)


def sub(a: jnp.ndarray, b: jnp.ndarray, spec: FieldSpec) -> jnp.ndarray:
    """Modular subtraction of canonical-limb values < mod."""
    diff, borrow = _sub_limbs(a, jnp.broadcast_to(b, a.shape))
    fixed = _carry_propagate(diff + spec.mod_arr, N)
    need_fix = (borrow != 0)[..., None]
    return jnp.where(need_fix, fixed, diff)


# --------------------------------------------------------------------------
# lazy-carry arithmetic — the (..., 16) limb-minor mirror of the
# ops/tfield.py lazy layer (rules R1-R4 documented there; ops/tfield.py
# also hosts the LimbBound schedule tracker). Limbs may sit <= 2^16
# between ops and the value < 5*mod; chains end at `normalize` or flow
# through mont_mul, which canonicalizes. Round 7 rides the same rules
# through the XLA point chains (ec.madd / ec.madd_masked table walks,
# ec.add_zlazy Z-lazy window folds) — one normalize_point per chain at
# the readback boundary, enforced by scripts/check_lazy_bounds.py.
# --------------------------------------------------------------------------

#: see tfield.LAZY_LIMB_MAX — the stable inter-op limb bound.
LAZY_LIMB_MAX = 1 << BITS


def lazy_limbs(t: jnp.ndarray, out_limbs: int) -> jnp.ndarray:
    """Lazy column sums -> LAZY limbs: ONE ripple pass, no lookahead."""
    k = t.shape[-1]
    if k < out_limbs:
        t = jnp.concatenate(
            [t, jnp.zeros(t.shape[:-1] + (out_limbs - k,), dtype=t.dtype)],
            axis=-1)
    else:
        t = t[..., :out_limbs]
    return (t & MASK) + _shift_right_one(t >> BITS)


def add_lazy(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a + b in lazy form (one ripple, no lookahead / mod subtract).

    At most one operand lazy (limbs <= 2^16), sum value < 2^256;
    output limbs <= 2^16, value exact (nothing reduced)."""
    t = a + b
    return (t & MASK) + _shift_right_one(t >> BITS)


_SUB2P_ARRS: dict = {}


def _sub2p_arr(spec: FieldSpec) -> jnp.ndarray:
    """Pre-borrowed 2*mod limbs (see tfield._sub2p_limbs) for sub_lazy."""
    if spec.name not in _SUB2P_ARRS:
        from . import tfield

        _SUB2P_ARRS[spec.name] = tfield._sub2p_limbs(spec.mod_int)
    return jnp.asarray(_SUB2P_ARRS[spec.name])


def sub_lazy(a: jnp.ndarray, b: jnp.ndarray, spec: FieldSpec) -> jnp.ndarray:
    """a + 2*mod - b in lazy form: two ripple passes, no borrow chain.

    `a` may be lazy (limbs <= 2^16); `b` MUST be canonical (< mod) so
    the pre-borrowed 2p limbs majorize it per-limb (no underflow).
    Output limbs <= 2^16, value = a + 2*mod - b."""
    t = a + _sub2p_arr(spec) - b
    return lazy_limbs(lazy_limbs(t, N), N)


def normalize(a: jnp.ndarray, spec: FieldSpec) -> jnp.ndarray:
    """Lazy form (limbs <= 2^16, value < 2*mod) -> canonical (< mod)."""
    return _cond_sub_mod(_carry_propagate(a, N + 1), spec)


def neg(a: jnp.ndarray, spec: FieldSpec) -> jnp.ndarray:
    """Modular negation: mod - a, with -0 = 0."""
    diff, _ = _sub_limbs(jnp.broadcast_to(spec.mod_arr, a.shape), a)
    zero = is_zero(a)[..., None]
    return jnp.where(zero, jnp.zeros_like(a), diff)


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    """True where all limbs are zero; shape = batch shape."""
    return jnp.all(a == 0, axis=-1)


def mont_mul(a: jnp.ndarray, b: jnp.ndarray, spec: FieldSpec) -> jnp.ndarray:
    """Montgomery product a*b*R^-1 mod m over (..., 16) uint32 limbs.

    Separated (SOS) reduction:
      T  = a*b                      (canonical, 2N+1 cols)
      m  = (T mod 2^256) * N' mod 2^256
      S  = (T + m*mod) >> 256      (exact division; low half cancels)
    Output canonical (< mod): standard bound (p^2 + 2^256 p)/2^256 < 2p.

    Lazy-carry contract (R3, see ops/tfield.py): at most ONE operand may
    be lazy (limbs <= LAZY_LIMB_MAX) with value < 5*mod; then
    S < (5p^2 + 2^256 p)/2^256 < 2p for BN254 and the single conditional
    subtract still canonicalizes. Output is always canonical.
    """
    shape = jnp.broadcast_shapes(a.shape, b.shape)
    a = jnp.broadcast_to(a, shape)
    b = jnp.broadcast_to(b, shape)

    t_cols = _shift_add_product(a, b, N, 2 * N)
    T = _carry_propagate(t_cols, 2 * N + 1)

    if jax.default_backend() != "cpu":
        # constant-operand products ride the int8 MXU (nibble Toeplitz)
        m_cols = _const_product_cols(T[..., :N], spec.nprime, N)
        m = _carry_propagate(m_cols, N)
        u_cols = _const_product_cols(m, spec.mod, 2 * N)
    else:
        m_cols = _shift_add_product(T[..., :N], spec.nprime_arr, N, N)
        m = _carry_propagate(m_cols, N)
        u_cols = _shift_add_product(m, spec.mod_arr, N, 2 * N)
    s = _carry_propagate(T + jnp.pad(u_cols, [(0, 0)] * (T.ndim - 1) + [(0, 1)]),
                         2 * N + 1)
    res = s[..., N:]  # (..., N+1); low N limbs are zero by construction
    return _cond_sub_mod(res, spec)


def mont_sqr(a: jnp.ndarray, spec: FieldSpec) -> jnp.ndarray:
    return mont_mul(a, a, spec)


def to_mont(a: jnp.ndarray, spec: FieldSpec) -> jnp.ndarray:
    return mont_mul(a, jnp.broadcast_to(spec.r2_arr, a.shape), spec)


def from_mont(a: jnp.ndarray, spec: FieldSpec) -> jnp.ndarray:
    one = jnp.zeros_like(a).at[..., 0].set(1)
    return mont_mul(a, one, spec)


def select(cond: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Branchless limb select: cond is a batch-shaped bool array."""
    return jnp.where(cond[..., None], a, b)


def double_val(a: jnp.ndarray, spec: FieldSpec) -> jnp.ndarray:
    return add(a, a, spec)


def pow_const(a: jnp.ndarray, exponent: int, spec: FieldSpec) -> jnp.ndarray:
    """a^exponent for a fixed public exponent (Montgomery in/out).

    Square-and-multiply via lax.fori_loop with the exponent bits as a
    constant device array — one compact loop body.
    """
    nbits = exponent.bit_length()
    bits = jnp.asarray(
        np.array([(exponent >> (nbits - 1 - i)) & 1 for i in range(nbits)],
                 dtype=np.uint32))
    one = jnp.broadcast_to(spec.r1_arr, a.shape)

    def body(i, acc):
        acc = mont_mul(acc, acc, spec)
        mul = mont_mul(acc, a, spec)
        return jnp.where(bits[i].astype(bool), mul, acc)

    return jax.lax.fori_loop(0, nbits, body, one)


def inv(a: jnp.ndarray, spec: FieldSpec) -> jnp.ndarray:
    """Fermat inverse a^(mod-2); Montgomery in/out. inv(0) = 0."""
    return pow_const(a, spec.mod_int - 2, spec)
