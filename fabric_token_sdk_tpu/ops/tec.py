"""Transposed-layout BN254 G1 ops for the Pallas (Mosaic) kernels.

Points are (..., 48, LANE) uint32: the X/Y/Z Montgomery projective
coordinates (16 limbs each) stacked along the sublane axis, batch on the
128-wide lane axis — see ops/tfield.py for why. Identity is (0 : r1 : 0).

Same complete RCB15 a=0 addition as ops/ec.py (eprint 2015/1060 Alg 7,
b3=9); the only structural difference is how the 14 field multiplications
batch: ec.py stacks them on a new leading axis, here they CONCATENATE along
the lane axis so the whole group stays a 2-D tile and every product rides
the in-kernel MXU nibble-Toeplitz path (tfield.mont_mul's 2-D fast path).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from . import limbs as L
from . import tfield as tf

N = L.NLIMBS


class CurveConsts(NamedTuple):
    """Field spec + curve constants for the in-kernel G1 ops."""

    ts: tf.TSpec
    b3: jnp.ndarray   # (N, 1) uint32: 3*b = 9 in Montgomery form


def make_consts() -> CurveConsts:
    from .field import FP

    b3 = np.array(L.int_to_limbs(L.fp_to_mont_int(9)),
                  dtype=np.uint32)[:, None]
    return CurveConsts(ts=tf.make_tspec(FP), b3=jnp.asarray(b3))


def coords(p: jnp.ndarray):
    """(..., 48, LANE) -> X, Y, Z as (..., 16, LANE) static slices."""
    return p[..., 0:N, :], p[..., N:2 * N, :], p[..., 2 * N:3 * N, :]


def from_coords(x, y, z) -> jnp.ndarray:
    return jnp.concatenate([x, y, z], axis=-2)


def identity(lanes: int, cc: CurveConsts,
             batch: tuple = ()) -> jnp.ndarray:
    zero = jnp.zeros(batch + (N, lanes), dtype=jnp.uint32)
    one = jnp.broadcast_to(cc.ts.r1, batch + (N, lanes))
    return from_coords(zero, one, zero)


def is_identity(p: jnp.ndarray) -> jnp.ndarray:
    """(..., 48, LANE) -> (..., 1, LANE) bool (Z == 0)."""
    _, _, z = coords(p)
    return tf.is_zero(z)


def _cat(parts) -> jnp.ndarray:
    return jnp.concatenate(parts, axis=-1)


def _split(m: jnp.ndarray, k: int):
    """Split (..., 16, k*LANE) back into k lane groups (static slices)."""
    lanes = m.shape[-1] // k
    return tuple(m[..., i * lanes:(i + 1) * lanes] for i in range(k))


def _add_complete(p: jnp.ndarray, q: jnp.ndarray, cc: CurveConsts,
                  z_lazy_out: bool) -> jnp.ndarray:
    """Shared interior of `add` / `add_zlazy` (RCB15 Alg 7, 6+2+6 muls).

    Accepts p with Z in LAZY form (limbs <= 2^16, value < 2p): Z1 feeds
    mont_mul as its single lazy operand (rule R3) and the a1-side cross
    sums add_lazy it against a canonical coordinate (rule R1, < 3p). q
    must be fully canonical (its sums ride the exact adder on the b1
    side). With z_lazy_out the output Z skips the exact carry resolve
    and stays lazy (< 2p) for the next chained `add_zlazy`.
    """
    ts = cc.ts
    X1, Y1, Z1 = coords(p)
    X2, Y2, Z2 = coords(q)
    addf = lambda a, b: tf.add(a, b, ts)
    subf = lambda a, b: tf.sub(a, b, ts)
    subl = lambda a, b: tf.sub_lazy(a, b, ts)

    # round 1: X1X2, Y1Y2, Z1Z2 and the three cross sums. The a1-side
    # sums are lazy (< 2p, one lazy mont operand per lane); the b1 side
    # stays exact so no lane sees two lazy inputs.
    a1 = _cat([X1, Y1, Z1, tf.add_lazy(X1, Y1), tf.add_lazy(Y1, Z1),
               tf.add_lazy(X1, Z1)])
    b1 = _cat([X2, Y2, Z2, addf(X2, Y2), addf(Y2, Z2), addf(X2, Z2)])
    m = tf.mont_mul(a1, b1, ts)
    t0, t1, t2, m3, m4, m5 = _split(m, 6)
    t3 = subl(subl(m3, t0), t1)          # X1Y2 + X2Y1      (lazy, < 5p)
    t4 = subl(subl(m4, t1), t2)          # Y1Z2 + Y2Z1      (lazy, < 5p)
    y3 = subl(subl(m5, t0), t2)          # X1Z2 + X2Z1      (lazy, < 5p)
    t0 = addf(addf(t0, t0), t0)          # 3*X1X2 (exact: it multiplies
                                         # lazy t3 in round 3)

    # round 2: the two b3 scalings (b3 canonical; t2/y3 may be lazy).
    b3b = jnp.broadcast_to(cc.b3, t2.shape)
    s = tf.mont_mul(_cat([t2, y3]), _cat([b3b, b3b]), ts)
    t2, y3 = _split(s, 2)
    z3 = addf(t1, t2)                    # exact: z3 multiplies lazy t4
    t1 = subf(t1, t2)                    # exact: t1 multiplies lazy t3

    # round 3: the six output products — each lane lazy x canonical.
    a3 = _cat([t4, t3, y3, t1, t0, z3])
    b3v = _cat([y3, t1, t0, z3, t3, t4])
    o = tf.mont_mul(a3, b3v, ts)
    o0, o1, o2, o3, o4, o5 = _split(o, 6)
    x3 = subf(o1, o0)                    # t3*t1 - t4*y3
    y3o = addf(o3, o2)                   # t1*z3 + y3*t0
    if z_lazy_out:
        z3o = tf.add_lazy(o5, o4)        # z3*t4 + t0*t3  (lazy, < 2p)
    else:
        z3o = addf(o5, o4)               # z3*t4 + t0*t3
    return from_coords(x3, y3o, z3o)


def add(p: jnp.ndarray, q: jnp.ndarray, cc: CurveConsts) -> jnp.ndarray:
    """Complete projective addition, valid for every input pair.

    Mirrors ec.add's three grouped multiplication rounds (6 + 2 + 6
    products), batched along the LANE axis. Canonical limbs in, canonical
    limbs out — but the INTERIOR runs in lazy-carry form (tf.add_lazy /
    tf.sub_lazy): the a1-side cross sums and the t3/t4/y3 linear
    combinations skip the Kogge-Stone lookahead + conditional subtract
    and flow into the next mont_mul as its single lazy operand (rule R3;
    every round-3 lane pairs one lazy input with one canonical input).
    """
    return _add_complete(p, q, cc, z_lazy_out=False)


def add_zlazy(p: jnp.ndarray, q: jnp.ndarray,
              cc: CurveConsts) -> jnp.ndarray:
    """Complete addition with a Z-LAZY accumulator: the chained form of
    `add` for sequential folds acc <- acc + term.

    Invariant (stable: outputs satisfy what inputs require):
      p:  X, Y canonical (< p); Z lazy (limbs <= 2^16, value < 2p).
      q:  fully canonical (the fold terms, e.g. straight out of a table
          select over normalized entries).
    The accumulator's Z carry resolution is deferred across the whole
    chain — one `normalize_point` at the chain end restores canonical
    limbs — instead of one exact carry-lookahead + conditional subtract
    per add. Same complete RCB15 formulas, so identity and p == +-q
    lanes remain valid throughout.
    """
    return _add_complete(p, q, cc, z_lazy_out=True)


def madd(p: jnp.ndarray, xq: jnp.ndarray, yq: jnp.ndarray,
         cc: CurveConsts) -> jnp.ndarray:
    """Mixed addition p + (xq : yq : 1) — RCB15 Algorithm 8 (a=0, b3=9),
    13 field muls (5 + 2 + 6) vs the 14 of the complete `add`, plus a
    lazy-carry interior that keeps the accumulator's Y/Z coordinates in
    lazy form ACROSS fold iterations (carries resolved once per chain by
    `normalize_point`, not once per add).

    Invariant (stable: outputs satisfy what inputs require):
      p:  X canonical (< p); Y, Z lazy (limbs <= 2^16, value < 2p).
      xq, yq: canonical Montgomery affine coordinates.
    Complete for every projective p — including identity (0 : y : 0) and
    p == +-Q — but NOT for Q at infinity: table digit 0 must be masked by
    the caller (jnp.where on the digit), which is what keeps the fold
    branch-free everywhere else.
    """
    ts = cc.ts
    X1, Y1, Z1 = coords(p)
    addf = lambda a, b: tf.add(a, b, ts)
    subf = lambda a, b: tf.sub(a, b, ts)
    subl = lambda a, b: tf.sub_lazy(a, b, ts)

    # round 1 (5 muls): with Z2 = 1, t2 = Z1*Z2 is free and the Alg-7
    # cross terms collapse: t4 = Y2*Z1 + Y1, y3 = X2*Z1 + X1.
    s1 = tf.add_lazy(X1, Y1)             # lazy < 3p (X canonical)
    s2 = addf(xq, yq)                    # exact (both canonical)
    a1 = _cat([X1, Y1, s1, Z1, Z1])
    b1 = _cat([xq, yq, s2, yq, xq])
    m = tf.mont_mul(a1, b1, ts)
    t0, t1, m2, m3, m4 = _split(m, 5)    # X1xq, Y1yq, s1*s2, Z1yq, Z1xq
    t3 = subl(subl(m2, t0), t1)          # X1Y2 + X2Y1      (lazy, < 5p)
    t4 = tf.add_lazy(m3, Y1)             # Y2Z1 + Y1        (lazy, < 3p)
    y3 = tf.add_lazy(m4, X1)             # X2Z1 + X1        (lazy, < 2p)
    t0 = addf(addf(t0, t0), t0)          # 3*X1X2 (exact)

    # round 2 (2 muls): b3 scalings of t2 = Z1 (lazy) and y3 (lazy).
    b3b = jnp.broadcast_to(cc.b3, t1.shape)
    s = tf.mont_mul(_cat([Z1, y3]), _cat([b3b, b3b]), ts)
    t2, y3 = _split(s, 2)
    z3 = addf(t1, t2)                    # exact: z3 multiplies lazy t4
    t1 = subf(t1, t2)                    # exact: t1 multiplies lazy t3

    # round 3 (6 muls): each lane lazy x canonical.
    a3 = _cat([t4, t3, y3, t1, t0, z3])
    b3v = _cat([y3, t1, t0, z3, t3, t4])
    o = tf.mont_mul(a3, b3v, ts)
    o0, o1, o2, o3, o4, o5 = _split(o, 6)
    x3 = subf(o1, o0)                    # canonical
    y3o = tf.add_lazy(o3, o2)            # lazy < 2p
    z3o = tf.add_lazy(o5, o4)            # lazy < 2p
    return from_coords(x3, y3o, z3o)


def madd_masked(p: jnp.ndarray, xq: jnp.ndarray, yq: jnp.ndarray,
                q_inf: jnp.ndarray, cc: CurveConsts) -> jnp.ndarray:
    """`madd` with the Q-at-infinity gap closed by a lane mask.

    q_inf: (..., 1, LANE) bool — lanes where Q is the identity keep p
    unchanged (p + 0 = p), which also preserves whatever lazy form p is
    in; the transposed twin of ec.madd_masked. This is what lets an
    affine multiple-table chain tbl[e] = tbl[e-1] + Q run branch-free
    over a batch that contains identity points.
    """
    return jnp.where(q_inf, p, madd(p, xq, yq, cc))


def normalize_point(p: jnp.ndarray, cc: CurveConsts) -> jnp.ndarray:
    """Resolve a madd-chain accumulator to fully canonical limbs.

    X is already canonical under the madd invariant; Y and Z are lazy
    with value < 2p — one carry_propagate + conditional subtract each."""
    X, Y, Z = coords(p)
    return from_coords(X, tf.normalize(Y, cc.ts), tf.normalize(Z, cc.ts))


def tree_fold(p: jnp.ndarray, cc: CurveConsts) -> jnp.ndarray:
    """Fold the LANE axis down to one point by pairwise halving.

    p: (..., 48, LANE) with LANE a power of two -> (..., 48, 1).
    Static lane-half slices, log2(LANE) add levels.
    """
    lanes = p.shape[-1]
    while lanes > 1:
        half = lanes // 2
        p = add(p[..., :half], p[..., half:2 * half], cc)
        lanes = half
    return p
