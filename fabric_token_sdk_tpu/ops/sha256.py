"""Batched SHA-256 on device (jnp, uint32 lanes).

The Fiat-Shamir transcript of the range verifier's first IPA challenge
hashes ~17 KB of (mostly device-produced) bytes per proof (reference
ipa.go:159-173). Hashing on host forces the pass-1 point bytes through the
host link — ~4 MB per 1024-proof batch, the measured round-5 transfer wall
on the tunneled chip. This kernel runs the whole compression batched over
proofs: one `lax.scan` over message blocks, 64 unrolled rounds of uint32
adds/rotates per block (natural mod-2^32 wrap), so only the 32-byte
digests ever leave the device.

Standard FIPS 180-4 SHA-256; parity-pinned against hashlib in
tests/test_sha256_device.py on both backends.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

_K = np.array([
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5,
    0x3956c25b, 0x59f111f1, 0x923f82a4, 0xab1c5ed5,
    0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174,
    0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
    0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7,
    0xc6e00bf3, 0xd5a79147, 0x06ca6351, 0x14292967,
    0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
    0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3,
    0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5,
    0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f, 0x682e6ff3,
    0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2,
], dtype=np.uint32)

_H0 = np.array([
    0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
    0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19,
], dtype=np.uint32)


def _rotr(x: jnp.ndarray, n: int) -> jnp.ndarray:
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def pad_length(msg_len: int) -> int:
    """Total padded byte length for a `msg_len`-byte message."""
    return ((msg_len + 8) // 64 + 1) * 64


def pad_tail(msg_len: int) -> np.ndarray:
    """The constant SHA-256 padding bytes for a fixed message length:
    0x80, zeros, 8-byte big-endian bit length."""
    total = pad_length(msg_len)
    tail = np.zeros(total - msg_len, dtype=np.uint8)
    tail[0] = 0x80
    bits = msg_len * 8
    tail[-8:] = np.frombuffer(bits.to_bytes(8, "big"), dtype=np.uint8)
    return tail


def digest_padded(msg: jnp.ndarray) -> jnp.ndarray:
    """SHA-256 of pre-padded messages: (B, L) u8 with L % 64 == 0
    (caller appends pad_tail) -> (B, 8) u32 big-endian digest words.

    Control flow is loops, not unrolling: a 48-step shift-register scan
    for the message schedule and a 64-step fori_loop for the compression
    rounds. The fully-unrolled form (112 serial steps of rotate/xor per
    block) nondeterministically deadlocks the XLA:CPU compiler on this
    host; the looped form keeps every traced graph tiny and compiles in
    milliseconds on both backends.
    """
    B, L = msg.shape
    assert L % 64 == 0, L
    nblocks = L // 64
    # bytes -> big-endian u32 words: (B, nblocks, 16)
    w8 = msg.reshape(B, nblocks, 16, 4).astype(jnp.uint32)
    words = ((w8[..., 0] << 24) | (w8[..., 1] << 16)
             | (w8[..., 2] << 8) | w8[..., 3])
    words = jnp.moveaxis(words, 1, 0)           # (nblocks, B, 16)
    k = jnp.asarray(_K)

    def schedule(w16):
        """(B, 16) block words -> (64, B) extended schedule."""
        reg0 = jnp.moveaxis(w16, -1, 0)         # (16, B)

        def step(reg, _):
            s0 = _rotr(reg[1], 7) ^ _rotr(reg[1], 18) \
                ^ (reg[1] >> np.uint32(3))
            s1 = _rotr(reg[14], 17) ^ _rotr(reg[14], 19) \
                ^ (reg[14] >> np.uint32(10))
            w = reg[0] + s0 + reg[9] + s1
            return jnp.concatenate([reg[1:], w[None]], axis=0), w

        _, extra = jax.lax.scan(step, reg0, None, length=48)
        return jnp.concatenate([reg0, extra], axis=0)   # (64, B)

    def block(state, w16):
        W = schedule(w16)

        def round_body(t, carry):
            a, b, c, d, e, f, g, h = (carry[i] for i in range(8))
            S1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
            ch = (e & f) ^ (~e & g)
            t1 = h + S1 + ch + k[t] + W[t]
            S0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
            maj = (a & b) ^ (a & c) ^ (b & c)
            return jnp.stack([t1 + S0 + maj, a, b, c, d + t1, e, f, g],
                             axis=0)

        carry0 = jnp.moveaxis(state, -1, 0)     # (8, B)
        out = jax.lax.fori_loop(0, 64, round_body, carry0)
        return state + jnp.moveaxis(out, 0, -1), None

    init = jnp.broadcast_to(jnp.asarray(_H0), (B, 8)).astype(jnp.uint32)
    final, _ = jax.lax.scan(block, init, words)
    return final


def digest_words_to_ints(words: np.ndarray) -> list[int]:
    """(B, 8) u32 digest words -> list of 256-bit big-endian ints."""
    out = []
    w = np.asarray(words, dtype=np.uint64)
    for row in w:
        v = 0
        for word in row:
            v = (v << 32) | int(word)
        out.append(v)
    return out
