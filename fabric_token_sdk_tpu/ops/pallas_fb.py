"""Pallas TPU kernel: fused fixed-base table select + window fold.

The XLA path (ec.fixed_base_gather / fixed_base_msm) materializes a
(B, T, 32, 256) one-hot tensor and a (B, T, 32, 3, 16) selection in HBM —
~4.5 GB at B=2048, and every field op in the 31-add window fold round-trips
HBM (the round-3 roofline's measured wall: the batch verify is
bandwidth-bound on unfused VPU ops, not compute-bound). This kernel keeps
the whole select+fold in VMEM: per grid step it loads one term's byte-plane
table block (1.6 MB), builds the one-hot per window on the fly (a (256, bB)
iota compare), selects via one MXU matmul, and folds the 32 windows into an
accumulator with the transposed complete-add chain (ops/tec.py). HBM
traffic drops to tables + digits in, folded points out.

Replaces the sequential per-proof table walk of the reference
(token/core/zkatdlog/nogh/v1/crypto/rp/bulletproof.go:252-333 and
math/mathlib G1.Mul) as the throughput path of SURVEY.md §2.5.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import limbs as L
from . import tec
from . import tfield as tf

N = L.NLIMBS

#: lane-block: batch lanes per grid step (multiple of 128).
LANE_BLOCK = 512


def _plane_dtype():
    from . import ec

    return ec.plane_dtype()


def _fb_fold_kernel(planes_ref, digits_ref, mod_ref, nprime_ref, r1_ref,
                    wnp_ref, wmod_ref, b3_ref, out_ref, *, windows: int):
    """One (term, lane-block) grid step: fold `windows` table selections.

    planes_ref: (1, windows, 96, 256) plane-dtype — one term's tables,
        transposed so the select contraction is (96, 256) x (256, bB).
    digits_ref: (1, windows, bB) int32 — 8-bit window digits.
    out_ref:    (1, 48, bB) uint32 — sum_w table[w][digit_w], transposed
        projective Montgomery.
    Remaining refs carry the field/curve constants (tfield.TSpec layout).
    """
    cc = tec.CurveConsts(
        ts=tf.TSpec(mod=mod_ref[...], nprime=nprime_ref[...],
                    r1=r1_ref[...], w_nprime=wnp_ref[...],
                    w_mod=wmod_ref[...], mod_int=0),
        b3=b3_ref[...])
    bB = digits_ref.shape[-1]
    dt = planes_ref.dtype

    def body(w, acc):
        d = digits_ref[0, w, :]                           # (bB,) int32
        iota = jax.lax.broadcasted_iota(jnp.int32, (256, bB), 0)
        onehot = (iota == d[None, :]).astype(jnp.int32).astype(dt)
        sel = jax.lax.dot_general(
            planes_ref[0, w], onehot, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # (96, bB) f32
        u = sel.astype(jnp.int32).astype(jnp.uint32)
        pt = u[0:48, :] + (u[48:96, :] << 8)              # (48, bB) limbs
        return tec.add(acc, pt, cc)

    out_ref[0] = jax.lax.fori_loop(
        0, windows, body, tec.identity(bB, cc), unroll=False)


@functools.partial(jax.jit, static_argnames=("interpret",))
def fb_fold_t(planes_t: jnp.ndarray, digits_t: jnp.ndarray,
              interpret: bool = False) -> jnp.ndarray:
    """Fused fixed-base fold, transposed interface.

    planes_t: (T, W, 96, 256) plane-dtype byte-plane tables (transposed);
    digits_t: (T, W, B) int32 with B a multiple of LANE_BLOCK (pad digit 0
        -> identity entry -> identity point for dead lanes).
    Returns (T, 48, B) uint32: per-(term, lane) folded points.
    """
    from jax.experimental import pallas as pl

    T, W, _, _ = planes_t.shape
    B = digits_t.shape[-1]
    assert B % LANE_BLOCK == 0, (B, LANE_BLOCK)
    cc = tec.make_consts()
    consts = (cc.ts.mod, cc.ts.nprime, cc.ts.r1, cc.ts.w_nprime,
              cc.ts.w_mod, cc.b3)
    const_specs = [
        pl.BlockSpec(c.shape, lambda t, b, *, _nd=c.ndim: (0,) * _nd)
        for c in consts
    ]
    kernel = functools.partial(_fb_fold_kernel, windows=W)
    return pl.pallas_call(
        kernel,
        grid=(T, B // LANE_BLOCK),
        in_specs=[
            pl.BlockSpec((1, W, 96, 256), lambda t, b: (t, 0, 0, 0)),
            pl.BlockSpec((1, W, LANE_BLOCK), lambda t, b: (t, 0, b)),
            *const_specs,
        ],
        out_specs=pl.BlockSpec((1, 48, LANE_BLOCK), lambda t, b: (t, 0, b)),
        out_shape=jax.ShapeDtypeStruct((T, 48, B), jnp.uint32),
        interpret=interpret,
    )(planes_t, digits_t, *consts)


# --------------------------------------------------------------------------
# XLA-layout adapters (drop-in for ec.fixed_base_gather / fixed_base_msm)
# --------------------------------------------------------------------------

def transpose_planes(table_planes: jnp.ndarray) -> jnp.ndarray:
    """(T, W, 256, 96) ec.fixed_base_planes layout -> (T, W, 96, 256)."""
    return jnp.transpose(table_planes, (0, 1, 3, 2))


def _digits_t(scalars: jnp.ndarray) -> jnp.ndarray:
    """(B, T, 16) limb scalars -> (T, W=32, B) int32 window digits."""
    from . import ec

    d = ec.window_digits8(scalars)            # (B, T, 32)
    return jnp.transpose(d, (1, 2, 0)).astype(jnp.int32)


def _untranspose(folded: jnp.ndarray) -> jnp.ndarray:
    """(T, 48, B) -> (B, T, 3, 16)."""
    T, _, B = folded.shape
    out = jnp.transpose(folded, (2, 0, 1))    # (B, T, 48)
    return out.reshape(B, T, 3, N)


def _pad_lanes(digits_t: jnp.ndarray) -> tuple[jnp.ndarray, int]:
    B = digits_t.shape[-1]
    pad = (-B) % LANE_BLOCK
    if pad:
        digits_t = jnp.concatenate(
            [digits_t,
             jnp.zeros(digits_t.shape[:-1] + (pad,), dtype=digits_t.dtype)],
            axis=-1)
    return digits_t, B


def fixed_base_gather_fused(planes_t: jnp.ndarray, scalars: jnp.ndarray,
                            interpret: bool = False) -> jnp.ndarray:
    """Per-term fixed-base scalar mul (ec.fixed_base_gather semantics).

    planes_t: (T, 32, 96, 256) transposed planes; scalars: (B, T, 16).
    Returns (B, T, 3, 16) = scalars[b, t] * P_t.
    """
    dt, B = _pad_lanes(_digits_t(scalars))
    return _untranspose(fb_fold_t(planes_t, dt, interpret=interpret))[:B]


def fixed_base_msm_fused(planes_t: jnp.ndarray, scalars: jnp.ndarray,
                         interpret: bool = False) -> jnp.ndarray:
    """Fixed-base MSM (ec.fixed_base_msm semantics) via the fused fold.

    planes_t: (T, 32, 96, 256); scalars: (..., T, 16) -> (..., 3, 16).
    The per-term folds run in the kernel; the T-axis fold is a small XLA
    tree (T*192 bytes per lane — negligible traffic).
    """
    from . import ec

    batch = scalars.shape[:-2]
    flat = scalars.reshape((-1,) + scalars.shape[-2:])
    per_term = fixed_base_gather_fused(planes_t, flat, interpret=interpret)
    folded = ec._tree_sum_shrink(per_term)    # (Bflat, 3, 16)
    return folded.reshape(batch + (3, N))
