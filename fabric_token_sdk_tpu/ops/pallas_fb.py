"""Pallas TPU kernel: fused fixed-base table select + window fold.

The XLA path (ec.fixed_base_gather / fixed_base_msm) materializes a
(B, T, 32, 256) one-hot tensor and a (B, T, 32, 3, 16) selection in HBM —
~4.5 GB at B=2048, and every field op in the 31-add window fold round-trips
HBM (the round-3 roofline's measured wall: the batch verify is
bandwidth-bound on unfused VPU ops, not compute-bound). This kernel keeps
the whole select+fold in VMEM: per grid step it loads one term's AFFINE
byte-plane table block (~1 MB, 64 planes — 2/3 the projective tables'
HBM), builds the one-hot per window on the fly (a (256, bB) iota
compare), selects via one MXU matmul, and folds the 32 windows into an
accumulator with the transposed MIXED-addition chain (tec.madd, 13 muls
vs 14, digit-0 masked) whose Y/Z ride in lazy-carry form until one
normalize_point per fold. HBM traffic drops to tables + digits in,
folded points out.

Replaces the sequential per-proof table walk of the reference
(token/core/zkatdlog/nogh/v1/crypto/rp/bulletproof.go:252-333 and
math/mathlib G1.Mul) as the throughput path of SURVEY.md §2.5.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import limbs as L
from . import tec
from . import tfield as tf

N = L.NLIMBS

#: lane-block: batch lanes per grid step (multiple of 128).
LANE_BLOCK = 512


def _plane_dtype():
    from . import ec

    return ec.plane_dtype()


def _fb_fold_kernel(planes_ref, digits_ref, mod_ref, nprime_ref, r1_ref,
                    wnp_ref, wmod_ref, sub2p_ref, b3_ref, out_ref, *,
                    windows: int):
    """One (term, lane-block) grid step: fold `windows` table selections.

    planes_ref: (1, windows, 64, 256) plane-dtype — one term's AFFINE
        tables (ec.fixed_base_affine_planes, transposed): 2/3 the select
        matmul rows and HBM of the old 96-row projective planes.
    digits_ref: (1, windows, bB) int32 — 8-bit window digits.
    out_ref:    (1, 48, bB) uint32 — sum_w table[w][digit_w], transposed
        projective Montgomery, canonical limbs.
    Remaining refs carry the field/curve constants (tfield.TSpec layout).

    The fold is a MIXED-addition chain (tec.madd, 13 muls vs tec.add's
    14) whose accumulator Y/Z stay in lazy-carry form across all
    `windows` iterations — one tec.normalize_point at the end resolves
    the deferred carries. Digit-0 lanes (affine entry (0,0), not a curve
    point) are masked to keep the accumulator unchanged, which restores
    completeness on the table path.
    """
    cc = tec.CurveConsts(
        ts=tf.TSpec(mod=mod_ref[...], nprime=nprime_ref[...],
                    r1=r1_ref[...], w_nprime=wnp_ref[...],
                    w_mod=wmod_ref[...], mod_int=0, sub2p=sub2p_ref[...]),
        b3=b3_ref[...])
    bB = digits_ref.shape[-1]
    dt = planes_ref.dtype

    def body(w, acc):
        d = digits_ref[0, w, :]                           # (bB,) int32
        iota = jax.lax.broadcasted_iota(jnp.int32, (256, bB), 0)
        onehot = (iota == d[None, :]).astype(jnp.int32).astype(dt)
        sel = jax.lax.dot_general(
            planes_ref[0, w], onehot, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # (64, bB) f32
        u = sel.astype(jnp.int32).astype(jnp.uint32)
        xq = u[0:16, :] + (u[32:48, :] << 8)              # (16, bB) limbs
        yq = u[16:32, :] + (u[48:64, :] << 8)
        keep = (d[None, :] == 0)                          # (1, bB)
        return jnp.where(keep, acc, tec.madd(acc, xq, yq, cc))

    folded = jax.lax.fori_loop(
        0, windows, body, tec.identity(bB, cc), unroll=False)
    out_ref[0] = tec.normalize_point(folded, cc)


@functools.partial(jax.jit, static_argnames=("interpret", "lane_block"))
def fb_fold_t(planes_t: jnp.ndarray, digits_t: jnp.ndarray,
              interpret: bool = False,
              lane_block: int = LANE_BLOCK) -> jnp.ndarray:
    """Fused fixed-base fold, transposed interface.

    planes_t: (T, W, 64, 256) plane-dtype AFFINE byte-plane tables
        (ec.fixed_base_affine_planes through transpose_planes);
    digits_t: (T, W, B) int32 with B a multiple of `lane_block` (pad digit
        0 -> masked madd -> identity point for dead lanes).
    Returns (T, 48, B) uint32: per-(term, lane) folded points.
    """
    from jax.experimental import pallas as pl

    T, W, _, _ = planes_t.shape
    B = digits_t.shape[-1]
    assert B % lane_block == 0, (B, lane_block)
    cc = tec.make_consts()
    consts = (cc.ts.mod, cc.ts.nprime, cc.ts.r1, cc.ts.w_nprime,
              cc.ts.w_mod, cc.ts.sub2p, cc.b3)
    const_specs = [
        pl.BlockSpec(c.shape, lambda t, b, *, _nd=c.ndim: (0,) * _nd)
        for c in consts
    ]
    kernel = functools.partial(_fb_fold_kernel, windows=W)
    return pl.pallas_call(
        kernel,
        grid=(T, B // lane_block),
        in_specs=[
            pl.BlockSpec((1, W, 64, 256), lambda t, b: (t, 0, 0, 0)),
            pl.BlockSpec((1, W, lane_block), lambda t, b: (t, 0, b)),
            *const_specs,
        ],
        out_specs=pl.BlockSpec((1, 48, lane_block), lambda t, b: (t, 0, b)),
        out_shape=jax.ShapeDtypeStruct((T, 48, B), jnp.uint32),
        interpret=interpret,
    )(planes_t, digits_t, *consts)


# --------------------------------------------------------------------------
# XLA-layout adapters (drop-in for ec.fixed_base_gather / fixed_base_msm)
# --------------------------------------------------------------------------

def transpose_planes(table_planes: jnp.ndarray) -> jnp.ndarray:
    """(T, W, 256, C) ec.fixed_base_[affine_]planes layout ->
    (T, W, C, 256) — C = 64 affine (the kernels' table form) or 96
    projective."""
    return jnp.transpose(table_planes, (0, 1, 3, 2))


def _digits_t(scalars: jnp.ndarray) -> jnp.ndarray:
    """(B, T, 16) limb scalars -> (T, W=32, B) int32 window digits."""
    from . import ec

    d = ec.window_digits8(scalars)            # (B, T, 32)
    return jnp.transpose(d, (1, 2, 0)).astype(jnp.int32)


def _untranspose(folded: jnp.ndarray) -> jnp.ndarray:
    """(T, 48, B) -> (B, T, 3, 16)."""
    T, _, B = folded.shape
    out = jnp.transpose(folded, (2, 0, 1))    # (B, T, 48)
    return out.reshape(B, T, 3, N)


def _lane_block_for(b: int) -> int:
    """Smallest 128-multiple block that does not over-pad small batches."""
    for cand in (128, 256, 512):
        if b <= cand:
            return cand
    return LANE_BLOCK


def _pad_lanes(digits_t: jnp.ndarray,
               lane_block: int) -> tuple[jnp.ndarray, int]:
    B = digits_t.shape[-1]
    pad = (-B) % lane_block
    if pad:
        digits_t = jnp.concatenate(
            [digits_t,
             jnp.zeros(digits_t.shape[:-1] + (pad,), dtype=digits_t.dtype)],
            axis=-1)
    return digits_t, B


@functools.partial(jax.jit, static_argnames=("interpret",))
def fixed_base_gather_fused(planes_t: jnp.ndarray, scalars: jnp.ndarray,
                            interpret: bool = False) -> jnp.ndarray:
    """Per-term fixed-base scalar mul (ec.fixed_base_gather semantics).

    planes_t: (T, 32, 64, 256) transposed affine planes; scalars: (B, T, 16).
    Returns (B, T, 3, 16) = scalars[b, t] * P_t. Jitted end-to-end so the
    digit prep / transposes / tree folds around the pallas_call never run
    eagerly (each eager op is a separate dispatch through the TPU tunnel).
    """
    lb = _lane_block_for(scalars.shape[0])
    dt, B = _pad_lanes(_digits_t(scalars), lb)
    return _untranspose(
        fb_fold_t(planes_t, dt, interpret=interpret, lane_block=lb))[:B]


def _fb_msm_kernel(planes_ref, digits_ref, mod_ref, nprime_ref, r1_ref,
                   wnp_ref, wmod_ref, sub2p_ref, b3_ref, out_ref, *,
                   windows: int):
    """One (lane-block, term) grid step of the ACCUMULATED fixed-base MSM.

    Same per-term madd select+fold as _fb_fold_kernel (affine tables,
    lazy-carry accumulator, digit-0 mask), but the grid's term axis is
    innermost and every term accumulates into the SAME output block —
    out_ref stays VMEM-resident across the consecutive revisits (Mosaic
    reduction pattern), so the T-axis fold never materializes a
    (B, T, 3, 16) intermediate nor runs XLA-layout point adds. The
    per-term fold is normalized before the cross-term complete add, so
    out_ref always holds canonical limbs.
    """
    from jax.experimental import pallas as pl

    cc = tec.CurveConsts(
        ts=tf.TSpec(mod=mod_ref[...], nprime=nprime_ref[...],
                    r1=r1_ref[...], w_nprime=wnp_ref[...],
                    w_mod=wmod_ref[...], mod_int=0, sub2p=sub2p_ref[...]),
        b3=b3_ref[...])
    bB = digits_ref.shape[-1]
    dt = planes_ref.dtype

    def body(w, acc):
        d = digits_ref[0, w, :]
        iota = jax.lax.broadcasted_iota(jnp.int32, (256, bB), 0)
        onehot = (iota == d[None, :]).astype(jnp.int32).astype(dt)
        sel = jax.lax.dot_general(
            planes_ref[0, w], onehot, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        u = sel.astype(jnp.int32).astype(jnp.uint32)
        xq = u[0:16, :] + (u[32:48, :] << 8)
        yq = u[16:32, :] + (u[48:64, :] << 8)
        keep = (d[None, :] == 0)
        return jnp.where(keep, acc, tec.madd(acc, xq, yq, cc))

    folded = tec.normalize_point(
        jax.lax.fori_loop(0, windows, body, tec.identity(bB, cc),
                          unroll=False), cc)
    t = pl.program_id(1)

    @pl.when(t == 0)
    def _init():
        out_ref[0] = folded

    @pl.when(t > 0)
    def _acc():
        out_ref[0] = tec.add(out_ref[0], folded, cc)


@functools.partial(jax.jit, static_argnames=("interpret", "lane_block"))
def fb_msm_t(planes_t: jnp.ndarray, digits_t: jnp.ndarray,
             interpret: bool = False,
             lane_block: int = LANE_BLOCK) -> jnp.ndarray:
    """Accumulated fixed-base MSM, transposed interface.

    planes_t: (T, W, 64, 256) affine; digits_t: (T, W, B) -> (48, B)
    uint32: per-lane sum over every term of table[t][digit]. The term
    axis rides the INNER grid dim so each lane-block's accumulator stays
    in VMEM.
    """
    from jax.experimental import pallas as pl

    T, W, _, _ = planes_t.shape
    B = digits_t.shape[-1]
    assert B % lane_block == 0, (B, lane_block)
    cc = tec.make_consts()
    consts = (cc.ts.mod, cc.ts.nprime, cc.ts.r1, cc.ts.w_nprime,
              cc.ts.w_mod, cc.ts.sub2p, cc.b3)
    const_specs = [
        pl.BlockSpec(c.shape, lambda b, t, *, _nd=c.ndim: (0,) * _nd)
        for c in consts
    ]
    kernel = functools.partial(_fb_msm_kernel, windows=W)
    out = pl.pallas_call(
        kernel,
        grid=(B // lane_block, T),
        in_specs=[
            pl.BlockSpec((1, W, 64, 256), lambda b, t: (t, 0, 0, 0)),
            pl.BlockSpec((1, W, lane_block), lambda b, t: (t, 0, b)),
            *const_specs,
        ],
        out_specs=pl.BlockSpec((1, 48, lane_block), lambda b, t: (0, 0, b)),
        out_shape=jax.ShapeDtypeStruct((1, 48, B), jnp.uint32),
        interpret=interpret,
    )(planes_t, digits_t, *consts)
    return out[0]


@functools.partial(jax.jit, static_argnames=("interpret",))
def fixed_base_msm_fused(planes_t: jnp.ndarray, scalars: jnp.ndarray,
                         interpret: bool = False) -> jnp.ndarray:
    """Fixed-base MSM (ec.fixed_base_msm semantics) via the fused
    accumulated fold: per-term select+fold AND the term-axis sum run in
    one pallas kernel (no XLA tree, no (B, T, 3, 16) intermediate).

    planes_t: (T, 32, 64, 256) affine; scalars: (..., T, 16) -> (..., 3, 16).
    """
    batch = scalars.shape[:-2]
    flat = scalars.reshape((-1,) + scalars.shape[-2:])
    lb = _lane_block_for(flat.shape[0])
    dt, B = _pad_lanes(_digits_t(flat), lb)
    folded = fb_msm_t(planes_t, dt, interpret=interpret, lane_block=lb)
    out = jnp.transpose(folded, (1, 0)).reshape(-1, 3, N)[:B]
    return out.reshape(batch + (3, N))


# --------------------------------------------------------------------------
# Fused variable-base windowed MSM (the combined-RLC pass-2 kernel)
# --------------------------------------------------------------------------

#: term lanes per grid step for the variable-base kernel.
VAR_BLOCK = 512
#: lanes the per-window partial reduces down to inside the kernel (the
#: Horner accumulator width; folded to one point by the XLA-side tail).
_VAR_KEEP = 128


def _msm_var_kernel(pts_ref, digits_ref, mod_ref, nprime_ref, r1_ref,
                    wnp_ref, wmod_ref, sub2p_ref, b3_ref, out_ref, *,
                    windows: int, keep: int = _VAR_KEEP):
    """One term-block: 4-bit-window Horner over a VMEM multiple table.

    pts_ref:    (48, VAR_BLOCK) uint32 transposed projective points with
        Z in {1, 0} — affine points or the identity (what every verifier
        var path uploads; the madd table chain needs affine operands).
    digits_ref: (windows, 1, VAR_BLOCK) int32 — 4-bit digits, LSB-first
        window index on the LEADING axis (dynamic indexing inside the
        window loop must hit a non-tiled dim).
    out_ref:    (1, 48, keep) uint32 — this block's partial sum, spread
        over `keep` lanes (callers fold the lanes + blocks; with
        keep = VAR_BLOCK // 2 and lanes laid out [term0 | term1] the
        halving fold makes lane i the per-row pair sum — the
        mul2_rows_fused grouping).

    LAZIFIED interiors (the round-7 treatment, twin of ec.msm_var_mixed):
    the multiple table is a 13-mul madd chain whose Y/Z stay lazy ACROSS
    all 14 steps (identity lanes ride the madd_masked lane mask), with
    one normalize_point per entry at the chain boundary; the per-window
    fold down to `keep` lanes is a Z-lazy `add_zlazy` chunk chain — same
    lane-add count as the halving tree it replaces, carries resolved
    once per window instead of once per add. Then acc = 16*acc + partial
    per window (complete adds: the Horner accumulator doubles against
    itself, so it must stay canonical). The whole walk — table build,
    selects, folds, doublings — stays in VMEM; the XLA path materializes
    each of these in HBM.
    """
    cc = tec.CurveConsts(
        ts=tf.TSpec(mod=mod_ref[...], nprime=nprime_ref[...],
                    r1=r1_ref[...], w_nprime=wnp_ref[...],
                    w_mod=wmod_ref[...], mod_int=0, sub2p=sub2p_ref[...]),
        b3=b3_ref[...])
    pts = pts_ref[...]
    bV = pts.shape[-1]
    xq, yq, _ = tec.coords(pts)                           # canonical affine
    inf = tec.is_identity(pts)                            # (1, bV)

    # 16-entry multiple table via the madd chain: tbl[e] = e * P per
    # lane. Entry 1 forces identity lanes onto the clean (0 : 1 : 0)
    # encoding; entries 2..15 carry lazy Y/Z across the whole chain and
    # resolve once each at the chain boundary.
    base = jnp.where(inf, tec.identity(bV, cc), pts)
    tbl = [tec.identity(bV, cc), base]
    cur = base
    for _ in range(2, 16):
        cur = tec.madd_masked(cur, xq, yq, inf, cc)
        tbl.append(cur)
    tbl = tbl[:2] + [tec.normalize_point(t, cc) for t in tbl[2:]]

    def body(i, acc):
        w = windows - 1 - i
        d = digits_ref[w, 0, :]                           # (bV,) int32
        sel = tbl[0]
        for e in range(1, 16):
            sel = jnp.where(d[None, :] == e, tbl[e], sel)
        if bV > keep:
            nchunks = bV // keep
            if nchunks == 2:
                # a single fold add has no carry to defer
                sel = tec.add(sel[..., :keep], sel[..., keep:], cc)
            else:
                # Z-lazy chunk chain: accumulator Z stays lazy across
                # the chunks, one normalize resolves it per window.
                accf = sel[..., :keep]
                for s in range(keep, bV, keep):
                    accf = tec.add_zlazy(accf, sel[..., s:s + keep], cc)
                sel = tec.normalize_point(accf, cc)
        for _ in range(4):                                # acc *= 16
            acc = tec.add(acc, acc, cc)
        return tec.add(acc, sel, cc)

    out_ref[0] = jax.lax.fori_loop(0, windows, body,
                                   tec.identity(keep, cc))


@functools.partial(jax.jit, static_argnames=("interpret",))
def msm_var_fused(points: jnp.ndarray, scalars: jnp.ndarray,
                  interpret: bool = False) -> jnp.ndarray:
    """Windowed variable-base MSM (ec.msm_windowed semantics, one row).

    points: (V, 3, 16) Montgomery projective; scalars: (V, 16) plain
    limbs. Returns (3, 16) = sum_v scalars[v] * points[v]. V is padded to
    a VAR_BLOCK multiple with identity points (exact no-ops).
    """
    from jax.experimental import pallas as pl

    from . import ec

    V = points.shape[0]
    pad = (-V) % VAR_BLOCK
    if pad:
        points = jnp.concatenate([points, ec.identity((pad,))], axis=0)
        scalars = jnp.concatenate(
            [scalars, jnp.zeros((pad, N), dtype=scalars.dtype)], axis=0)
        V += pad
    pts_t = jnp.transpose(points.reshape(V, 48), (1, 0))  # (48, V)
    digits = ec.window_digits4(scalars)                   # (V, W)
    W = digits.shape[-1]
    digits_t = jnp.transpose(digits, (1, 0)).reshape(W, 1, V).astype(
        jnp.int32)

    cc = tec.make_consts()
    consts = (cc.ts.mod, cc.ts.nprime, cc.ts.r1, cc.ts.w_nprime,
              cc.ts.w_mod, cc.ts.sub2p, cc.b3)
    const_specs = [
        pl.BlockSpec(c.shape, lambda b, *, _nd=c.ndim: (0,) * _nd)
        for c in consts
    ]
    nblocks = V // VAR_BLOCK
    partials = pl.pallas_call(
        functools.partial(_msm_var_kernel, windows=W),
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec((48, VAR_BLOCK), lambda b: (0, b)),
            pl.BlockSpec((W, 1, VAR_BLOCK), lambda b: (0, 0, b)),
            *const_specs,
        ],
        out_specs=pl.BlockSpec((1, 48, _VAR_KEEP), lambda b: (b, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((nblocks, 48, _VAR_KEEP),
                                       jnp.uint32),
        interpret=interpret,
    )(pts_t, digits_t, *consts)
    # XLA tail: (nblocks * _VAR_KEEP) lanes -> one point
    flat = jnp.transpose(partials, (0, 2, 1)).reshape(
        nblocks * _VAR_KEEP, 3, N)
    return ec._tree_sum_shrink(flat)


#: rows per grid block of the paired per-row mul (two term lanes per row).
_PAIR_ROWS = VAR_BLOCK // 2


@functools.partial(jax.jit, static_argnames=("interpret",))
def mul2_rows_fused(points: jnp.ndarray, scalars: jnp.ndarray,
                    interpret: bool = False) -> jnp.ndarray:
    """Per-row 2-term MSM: out[b] = sc[b,0]*pts[b,0] + sc[b,1]*pts[b,1].

    points: (B, 2, 3, 16) Montgomery projective; scalars: (B, 2, 16)
    plain limbs -> (B, 3, 16). Drop-in for ec.msm_windowed on a 2-term
    axis, but the whole Horner walk runs in VMEM via _msm_var_kernel
    with keep = rows-per-block: lanes are laid out [term0 rows | term1
    rows] inside each block, so the kernel's halving fold lands lane i
    on row i's pair sum. Replaces the XLA double-and-add chain (the
    K-equation's x*D + C), which is dispatch-overhead-bound at chunk
    shapes (measured 21.5 ms per 256-row chunk vs ~6 ms fused).
    """
    from jax.experimental import pallas as pl

    from . import ec

    B = points.shape[0]
    pad = (-B) % _PAIR_ROWS
    if pad:
        points = jnp.concatenate([points, ec.identity((pad, 2))], axis=0)
        scalars = jnp.concatenate(
            [scalars, jnp.zeros((pad, 2, N), dtype=scalars.dtype)], axis=0)
    Bp = B + pad
    nblocks = Bp // _PAIR_ROWS
    # (nblocks, 2, _PAIR_ROWS, 48): block-major, term-major inside a block
    pts_b = jnp.transpose(
        points.reshape(nblocks, _PAIR_ROWS, 2, 48), (0, 2, 1, 3))
    pts_t = jnp.transpose(pts_b.reshape(nblocks * VAR_BLOCK, 48), (1, 0))
    digits = ec.window_digits4(scalars)                   # (Bp, 2, W)
    W = digits.shape[-1]
    dig_b = jnp.transpose(
        digits.reshape(nblocks, _PAIR_ROWS, 2, W), (3, 0, 2, 1))
    digits_t = dig_b.reshape(W, 1, nblocks * VAR_BLOCK).astype(jnp.int32)

    cc = tec.make_consts()
    consts = (cc.ts.mod, cc.ts.nprime, cc.ts.r1, cc.ts.w_nprime,
              cc.ts.w_mod, cc.ts.sub2p, cc.b3)
    const_specs = [
        pl.BlockSpec(c.shape, lambda b, *, _nd=c.ndim: (0,) * _nd)
        for c in consts
    ]
    out = pl.pallas_call(
        functools.partial(_msm_var_kernel, windows=W, keep=_PAIR_ROWS),
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec((48, VAR_BLOCK), lambda b: (0, b)),
            pl.BlockSpec((W, 1, VAR_BLOCK), lambda b: (0, 0, b)),
            *const_specs,
        ],
        out_specs=pl.BlockSpec((1, 48, _PAIR_ROWS), lambda b: (b, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((nblocks, 48, _PAIR_ROWS),
                                       jnp.uint32),
        interpret=interpret,
    )(pts_t, digits_t, *consts)
    flat = jnp.transpose(out, (0, 2, 1)).reshape(Bp, 3, N)
    return flat[:B]
