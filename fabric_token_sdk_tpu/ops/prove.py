"""Shared device codecs for the TPU-side prover (ops layer).

Digest -> Fr challenge reduction, canonical byte serialization and the
Montgomery inner-product folds used by ``prover/range.py`` and
``prover/transfer.py``. Lives in ops/ so the prover kernels ride the
same layer the verifier kernels do (models/ and prover/ may import it,
never the reverse), and so `scripts/check_lazy_bounds.py` sees any
lazy-API use here under the ops discipline.

The canonicalization split mirrors the verifier's transcripts exactly:

* challenges only ever USED arithmetically (x, z, x_ipa, the IPA round
  challenges x_r) take ONE conditional subtract — the same rule-R3
  argument as ``_derive_var_scalars`` in models/range_verifier.py;
* challenges whose canonical BYTES re-enter a transcript or the proof
  (y, whose 32 big-endian bytes are hashed for z; the type-and-sum
  challenge, which is serialized) take the full reduction.

Everything the prover SERIALIZES (tau, delta, ipa.left/right, the
sigma responses) comes out of ``field.from_mont``, whose result is
already canonical — no extra reduction needed there.
"""

from __future__ import annotations

import jax.numpy as jnp

from . import ec, field, limbs


def digest_to_fr(words: jnp.ndarray, full: bool = False) -> jnp.ndarray:
    """SHA-256 digest words -> Fr scalar limbs (plain, not Montgomery).

    words: (..., 8) u32 big-endian digest words (``sha256.digest_padded``
    output). Returns (..., 16) limbs representing digest mod R — the
    device twin of ``bn254.hash_to_zr``.

    full=False: one conditional subtract. The raw 256-bit digest is
    < 2^256 ~ 5.3R; one subtract brings the value under 2^256 - R < 5R,
    inside mont_mul's single-lazy-operand value bound (rule R3,
    ops/tfield.py), so ``to_mont`` of the result lands exactly on
    to_mont(digest mod R).

    full=True: five conditional subtracts -> the canonical residue
    (digest < 6R, so five provably suffice). Required when the reduced
    value's canonical bytes are themselves transcript or proof material.
    """
    lim = jnp.stack([words & 0xFFFF, words >> 16], axis=-1)
    lim = lim[..., ::-1, :].reshape(*words.shape[:-1], limbs.NLIMBS)
    zero = jnp.zeros(lim.shape[:-1] + (1,), dtype=jnp.uint32)
    out = field._cond_sub_mod(jnp.concatenate([lim, zero], axis=-1),
                              field.FR)
    if full:
        for _ in range(4):
            out = field._cond_sub_mod(
                jnp.concatenate([out, zero], axis=-1), field.FR)
    return out


def fr_limbs_to_bytes(a: jnp.ndarray) -> jnp.ndarray:
    """Canonical plain Fr limbs -> 32-byte big-endian encoding.

    (..., 16) u32 -> (..., 32) u8, the device twin of
    ``serialization.zr_to_bytes`` (which requires its input reduced —
    hence callers feed ``digest_to_fr(..., full=True)`` or ``from_mont``
    output only)."""
    le = a[..., ::-1]
    hi = (le >> 8).astype(jnp.uint8)
    lo = (le & 0xFF).astype(jnp.uint8)
    return jnp.stack([hi, lo], axis=-1).reshape(*a.shape[:-1], 32)


def points_to_bytes(pts: jnp.ndarray) -> jnp.ndarray:
    """Montgomery projective points -> canonical mathlib G1 bytes.

    (..., K, 3, 16) -> (..., K, 64) u8 (x||y, 32-byte big-endian each),
    one batched Fermat inversion per leading row via ``to_affine_batch``.
    The identity comes out (0, 0) -> 64 zero bytes, matching
    ``serialization.g1_to_bytes`` on the host.
    """
    aff = ec.to_affine_batch(pts)                  # (..., K, 2, 16) plain
    a = aff[..., ::-1]
    hi = (a >> 8).astype(jnp.uint8)
    lo = (a & 0xFF).astype(jnp.uint8)
    inter = jnp.stack([hi, lo], axis=-1)           # (..., K, 2, 16, 2)
    return inter.reshape(*a.shape[:-2], 64)


def fr_sum(a: jnp.ndarray) -> jnp.ndarray:
    """Tree-fold field sum over axis -2: (..., m, 16) -> (..., 16).

    log2(m) levels of the exact ``field.add`` (canonical in/out); odd
    levels carry their tail term to the next level unchanged."""
    while a.shape[-2] > 1:
        m = a.shape[-2]
        h = m // 2
        s = field.add(a[..., :h, :], a[..., h:2 * h, :], field.FR)
        if m % 2:
            s = jnp.concatenate([s, a[..., 2 * h:, :]], axis=-2)
        a = s
    return a[..., 0, :]


def fr_dot(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Montgomery inner product over axis -2.

    (..., m, 16) x (..., m, 16) Montgomery limbs -> (..., 16) Montgomery
    limbs of sum_i a_i * b_i."""
    return fr_sum(field.mont_mul(a, b, field.FR))
