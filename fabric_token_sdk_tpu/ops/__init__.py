"""TPU compute kernels: limbed BN254 field/group arithmetic.

This package is the TPU-native replacement for the reference's native math
layer (github.com/IBM/mathlib -> consensys/gnark-crypto assembly BN254; see
reference token/core/zkatdlog/nogh/v1/crypto/setup.go:14 and SURVEY.md §2.2).
All arrays are uint32 with 16-bit limbs so every partial product and lazy
column sum stays inside a 32-bit lane — the layout XLA:TPU vectorizes well.
"""

from . import limbs  # noqa: F401
from . import field  # noqa: F401
from . import ec  # noqa: F401
