"""TPU compute kernels: limbed BN254 field/group arithmetic.

This package is the TPU-native replacement for the reference's native math
layer (github.com/IBM/mathlib -> consensys/gnark-crypto assembly BN254; see
reference token/core/zkatdlog/nogh/v1/crypto/setup.go:14 and SURVEY.md §2.2).
All arrays are uint32 with 16-bit limbs so every partial product and lazy
column sum stays inside a 32-bit lane — the layout XLA:TPU vectorizes well.

Submodules are imported explicitly by consumers (`from ..ops import field`),
not here: `limbs` is numpy-only and must stay importable without pulling in
jax (control-plane paths), while `field`/`ec` require a jax backend.
"""
