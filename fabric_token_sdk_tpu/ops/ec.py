"""Branchless BN254 G1 Jacobian arithmetic + batched MSM on TPU.

Points are (..., 3, 16) uint32 arrays: Montgomery-form Jacobian (X, Y, Z)
with Z == 0 denoting the identity. All control flow is `jnp.where` selects so
the code traces to a single static XLA graph (SURVEY.md §7: no data-dependent
control flow under jit); the scalar bit loop uses `lax.fori_loop`.

Equivalent of the reference's gnark-crypto G1 ops used via IBM/mathlib
(G1.Mul/Add/Sub, reference token/core/zkatdlog/nogh/v1/crypto files passim).
The batched `msm_is_identity` is the verification hot loop replacing the
sequential per-proof loop at reference rp/rangecorrectness.go:137-162.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from . import field
from .field import FP

# Point component indices.
_X, _Y, _Z = 0, 1, 2


def identity(batch_shape: tuple[int, ...] = ()) -> jnp.ndarray:
    """Identity point(s): (batch..., 3, 16) with Z = 0, X = Y = mont(1)."""
    one = FP.r1_arr
    pt = jnp.stack([one, one, jnp.zeros_like(one)])
    return jnp.broadcast_to(pt, batch_shape + pt.shape)


def is_identity(p: jnp.ndarray) -> jnp.ndarray:
    return field.is_zero(p[..., _Z, :])


def double(p: jnp.ndarray) -> jnp.ndarray:
    """Jacobian doubling (dbl-2009-l); safe for Z=0 (returns Z=0)."""
    X1, Y1, Z1 = p[..., _X, :], p[..., _Y, :], p[..., _Z, :]
    A = field.mont_sqr(X1, FP)
    B = field.mont_sqr(Y1, FP)
    C = field.mont_sqr(B, FP)
    t = field.add(X1, B, FP)
    t = field.mont_sqr(t, FP)
    t = field.sub(t, A, FP)
    t = field.sub(t, C, FP)
    D = field.double_val(t, FP)
    E = field.add(field.double_val(A, FP), A, FP)
    F = field.mont_sqr(E, FP)
    X3 = field.sub(F, field.double_val(D, FP), FP)
    Y3 = field.sub(D, X3, FP)
    Y3 = field.mont_mul(E, Y3, FP)
    C8 = field.double_val(field.double_val(field.double_val(C, FP), FP), FP)
    Y3 = field.sub(Y3, C8, FP)
    Z3 = field.double_val(field.mont_mul(Y1, Z1, FP), FP)
    return jnp.stack([X3, Y3, Z3], axis=-2)


def add(p: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
    """Branchless general Jacobian addition handling all edge cases.

    Cases folded in via selects: P=O -> Q; Q=O -> P; P==Q -> double;
    P==-Q -> O; otherwise add-2007-bl.
    """
    X1, Y1, Z1 = p[..., _X, :], p[..., _Y, :], p[..., _Z, :]
    X2, Y2, Z2 = q[..., _X, :], q[..., _Y, :], q[..., _Z, :]

    Z1Z1 = field.mont_sqr(Z1, FP)
    Z2Z2 = field.mont_sqr(Z2, FP)
    U1 = field.mont_mul(X1, Z2Z2, FP)
    U2 = field.mont_mul(X2, Z1Z1, FP)
    S1 = field.mont_mul(field.mont_mul(Y1, Z2, FP), Z2Z2, FP)
    S2 = field.mont_mul(field.mont_mul(Y2, Z1, FP), Z1Z1, FP)
    H = field.sub(U2, U1, FP)
    r = field.sub(S2, S1, FP)

    # General addition path.
    HH = field.mont_sqr(H, FP)
    HHH = field.mont_mul(H, HH, FP)
    V = field.mont_mul(U1, HH, FP)
    X3 = field.mont_sqr(r, FP)
    X3 = field.sub(X3, HHH, FP)
    X3 = field.sub(X3, field.double_val(V, FP), FP)
    Y3 = field.sub(V, X3, FP)
    Y3 = field.mont_mul(r, Y3, FP)
    Y3 = field.sub(Y3, field.mont_mul(S1, HHH, FP), FP)
    Z3 = field.mont_mul(field.mont_mul(Z1, Z2, FP), H, FP)
    added = jnp.stack([X3, Y3, Z3], axis=-2)

    doubled = double(p)

    id1 = is_identity(p)
    id2 = is_identity(q)
    h0 = field.is_zero(H)
    r0 = field.is_zero(r)

    same = jnp.logical_and(jnp.logical_and(h0, r0),
                           jnp.logical_and(~id1, ~id2))
    anni = jnp.logical_and(jnp.logical_and(h0, ~r0),
                           jnp.logical_and(~id1, ~id2))

    out = added
    out = jnp.where(same[..., None, None], doubled, out)
    out = jnp.where(anni[..., None, None], identity(p.shape[:-2]), out)
    out = jnp.where(id2[..., None, None], p, out)
    out = jnp.where(id1[..., None, None], q, out)
    return out


def neg(p: jnp.ndarray) -> jnp.ndarray:
    Y = field.neg(p[..., _Y, :], FP)
    return p.at[..., _Y, :].set(Y)


def scale(p: jnp.ndarray, bit: jnp.ndarray) -> jnp.ndarray:
    """p if bit else identity — implemented by masking Z (cheap select)."""
    Z = p[..., _Z, :] * bit[..., None].astype(jnp.uint32)
    return p.at[..., _Z, :].set(Z)


def _scalar_bit(scalars: jnp.ndarray, bit_index) -> jnp.ndarray:
    """Bit `bit_index` (0 = LSB) of (..., 16)-limb scalars -> (...,) uint32."""
    limb = bit_index // 16
    off = bit_index % 16
    word = jnp.take(scalars, limb, axis=-1)
    return (word >> off) & jnp.uint32(1)


def scalar_mul(p: jnp.ndarray, scalar: jnp.ndarray) -> jnp.ndarray:
    """Double-and-add scalar multiplication (256 fixed iterations).

    p: (..., 3, 16) point(s); scalar: (..., 16) plain-integer limbs.
    Not constant-time in value distribution but branchless in structure —
    verification-side only (SURVEY.md §7: constant-time not required).
    """
    batch = p.shape[:-2]

    def body(i, acc):
        acc = double(acc)
        bit = _scalar_bit(scalar, 255 - i)
        cand = add(acc, p)
        return jnp.where(bit[..., None, None].astype(bool), cand, acc)

    return jax.lax.fori_loop(0, 256, body, identity(batch))


def _tree_sum(pts: jnp.ndarray) -> jnp.ndarray:
    """Pairwise tree reduction of points over axis -3 (the term axis).

    pts: (..., T, 3, 16) with T a power of two -> (..., 3, 16).
    log2(T) vectorized point additions.
    """
    T = pts.shape[-3]
    while T > 1:
        half = T // 2
        pts = add(pts[..., :half, :, :], pts[..., half : 2 * half, :, :])
        T = half
    return pts[..., 0, :, :]


def _pad_pow2(pts: jnp.ndarray, scalars: jnp.ndarray):
    T = pts.shape[-3]
    pow2 = 1
    while pow2 < T:
        pow2 *= 2
    if pow2 == T:
        return pts, scalars
    pad = pow2 - T
    id_pts = identity(pts.shape[:-3] + (pad,))
    pts = jnp.concatenate([pts, id_pts], axis=-3)
    zpad = jnp.zeros(scalars.shape[:-2] + (pad, scalars.shape[-1]),
                     dtype=scalars.dtype)
    scalars = jnp.concatenate([scalars, zpad], axis=-2)
    return pts, scalars


def msm(points: jnp.ndarray, scalars: jnp.ndarray) -> jnp.ndarray:
    """Batched multi-scalar multiplication with shared doublings.

    points: (..., T, 3, 16) Montgomery Jacobian; scalars: (..., T, 16) plain
    limbs. Returns (..., 3, 16) = sum_t scalars[t] * points[t].

    MSB-first bit scan: per bit, one shared doubling of the accumulator plus
    a masked tree-sum over the T term axis — every op is batch x T wide,
    which is what keeps the VPU lanes full (SURVEY.md §2.5: batch
    data-parallel proof verification is the only first-class parallelism).
    """
    points, scalars = _pad_pow2(points, scalars)
    batch = points.shape[:-3]

    def body(i, acc):
        acc = double(acc)
        bits = _scalar_bit(scalars, 255 - i)  # (..., T)
        masked = scale(points, bits)
        return add(acc, _tree_sum(masked))

    return jax.lax.fori_loop(0, 256, body, identity(batch))


def msm_is_identity(points: jnp.ndarray, scalars: jnp.ndarray) -> jnp.ndarray:
    """True per batch element iff sum_t scalars[t]*points[t] == O."""
    return is_identity(msm(points, scalars))


def points_equal(p: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
    """Jacobian equality without inversion: cross-multiplied coordinates."""
    X1, Y1, Z1 = p[..., _X, :], p[..., _Y, :], p[..., _Z, :]
    X2, Y2, Z2 = q[..., _X, :], q[..., _Y, :], q[..., _Z, :]
    Z1Z1 = field.mont_sqr(Z1, FP)
    Z2Z2 = field.mont_sqr(Z2, FP)
    x_eq = field.is_zero(
        field.sub(field.mont_mul(X1, Z2Z2, FP),
                  field.mont_mul(X2, Z1Z1, FP), FP))
    y_eq = field.is_zero(
        field.sub(field.mont_mul(field.mont_mul(Y1, Z2, FP), Z2Z2, FP),
                  field.mont_mul(field.mont_mul(Y2, Z1, FP), Z1Z1, FP), FP))
    both_id = jnp.logical_and(is_identity(p), is_identity(q))
    one_id = jnp.logical_xor(is_identity(p), is_identity(q))
    eq = jnp.logical_and(x_eq, y_eq)
    return jnp.where(both_id, True, jnp.where(one_id, False, eq))
