"""BN254 G1 arithmetic + batched MSM on TPU via complete projective formulas.

Points are (..., 3, 16) uint32 arrays: Montgomery-form homogeneous
projective (X, Y, Z) with the identity at (0 : y≠0 : 0). Addition uses the
Renes-Costello-Batina complete formulas for a=0 short-Weierstrass curves
(eprint 2015/1060, Algorithm 7, b3 = 3*b = 9 for BN254): one unconditional
14-multiplication sequence valid for EVERY input pair — doubling, identity,
inverses — so traced graphs contain no case analysis at all. That keeps the
256-step scalar/MSM loop bodies small enough for fast XLA compiles and all
lanes doing useful work (SURVEY.md §7: no data-dependent control flow).

Equivalent of the reference's gnark-crypto G1 ops used via IBM/mathlib
(G1.Mul/Add/Sub, reference token/core/zkatdlog/nogh/v1/crypto files passim).
The batched `msm_is_identity` replaces the sequential per-proof loop at
reference rp/rangecorrectness.go:137-162.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import field
from . import limbs as L
from .field import FP

# Point component indices.
_X, _Y, _Z = 0, 1, 2

# b3 = 3*b = 9 in Montgomery form (curve y^2 = x^3 + 3).
_B3_MONT = tuple(int(v) for v in L.int_to_limbs(L.fp_to_mont_int(9)))


def _b3() -> jnp.ndarray:
    return jnp.asarray(np.array(_B3_MONT, dtype=np.uint32))


def identity(batch_shape: tuple[int, ...] = ()) -> jnp.ndarray:
    """Identity point(s): (batch..., 3, 16) = (0 : 1 : 0) in Montgomery."""
    zero = jnp.zeros(L.NLIMBS, dtype=jnp.uint32)
    pt = jnp.stack([zero, FP.r1_arr, zero])
    return jnp.broadcast_to(pt, batch_shape + pt.shape)


def is_identity(p: jnp.ndarray) -> jnp.ndarray:
    return field.is_zero(p[..., _Z, :])


def add(p: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
    """Complete projective addition (RCB15 Algorithm 7, a=0, b3=9).

    Valid unconditionally for all inputs, including p == q (doubling),
    p == -q (yields the identity), and either operand the identity.

    The 14 field multiplications are grouped into THREE stacked mont_mul
    calls (6 + 2 + 6 independent products batched along a new leading axis):
    the traced graph shrinks ~3x — which is what keeps the 256-step
    scalar/MSM loop bodies fast to compile — and the wider batches fill
    VPU lanes better at small batch sizes.
    """
    X1, Y1, Z1 = p[..., _X, :], p[..., _Y, :], p[..., _Z, :]
    X2, Y2, Z2 = q[..., _X, :], q[..., _Y, :], q[..., _Z, :]
    addf = lambda a, b: field.add(a, b, FP)
    subf = lambda a, b: field.sub(a, b, FP)

    # round 1: t0=X1X2, t1=Y1Y2, t2=Z1Z2 and the three cross sums.
    a1 = jnp.stack([X1, Y1, Z1, addf(X1, Y1), addf(Y1, Z1), addf(X1, Z1)])
    b1 = jnp.stack([X2, Y2, Z2, addf(X2, Y2), addf(Y2, Z2), addf(X2, Z2)])
    m = field.mont_mul(a1, b1, FP)
    t0, t1, t2 = m[0], m[1], m[2]
    t3 = subf(m[3], addf(t0, t1))        # X1Y2 + X2Y1
    t4 = subf(m[4], addf(t1, t2))        # Y1Z2 + Y2Z1
    y3 = subf(m[5], addf(t0, t2))        # X1Z2 + X2Z1
    t0 = addf(addf(t0, t0), t0)          # 3*X1X2

    # round 2: the two b3 scalings.
    s = field.mont_mul(jnp.stack([t2, y3]),
                       jnp.broadcast_to(_b3(), t2.shape), FP)
    t2, y3 = s[0], s[1]
    z3 = addf(t1, t2)
    t1 = subf(t1, t2)

    # round 3: the six output products.
    a3 = jnp.stack([t4, t3, y3, t1, t0, z3])
    b3v = jnp.stack([y3, t1, t0, z3, t3, t4])
    o = field.mont_mul(a3, b3v, FP)
    x3 = subf(o[1], o[0])                # t3*t1 - t4*y3
    y3o = addf(o[3], o[2])               # t1*z3 + y3*t0
    z3o = addf(o[5], o[4])               # z3*t4 + t0*t3
    return jnp.stack([x3, y3o, z3o], axis=-2)


def double(p: jnp.ndarray) -> jnp.ndarray:
    """Doubling via the complete addition (valid for all inputs)."""
    return add(p, p)


def neg(p: jnp.ndarray) -> jnp.ndarray:
    Y = field.neg(p[..., _Y, :], FP)
    return p.at[..., _Y, :].set(Y)


def scale(p: jnp.ndarray, bit: jnp.ndarray) -> jnp.ndarray:
    """p if bit else identity — mask X and Z (identity is (0 : y : 0); any
    y != 0 works, and real curve points never have Y = 0 on BN254)."""
    b = bit[..., None].astype(jnp.uint32)
    out = p.at[..., _X, :].set(p[..., _X, :] * b)
    return out.at[..., _Z, :].set(p[..., _Z, :] * b)


def _scalar_bit(scalars: jnp.ndarray, bit_index) -> jnp.ndarray:
    """Bit `bit_index` (0 = LSB) of (..., 16)-limb scalars -> (...,) uint32."""
    limb = bit_index // 16
    off = bit_index % 16
    word = jnp.take(scalars, limb, axis=-1)
    return (word >> off) & jnp.uint32(1)


def scalar_mul(p: jnp.ndarray, scalar: jnp.ndarray) -> jnp.ndarray:
    """Double-and-always-add over 256 fixed iterations (branchless).

    p: (..., 3, 16) point(s); scalar: (..., 16) plain-integer limbs.
    Verification-side only: constant-time not required (SURVEY.md §7), but
    the structure is data-oblivious anyway.
    """
    batch = p.shape[:-2]

    def body(i, acc):
        acc = add(acc, acc)
        bit = _scalar_bit(scalar, 255 - i)
        return add(acc, scale(p, bit))

    return jax.lax.fori_loop(0, 256, body, identity(batch))


def _tree_sum(pts: jnp.ndarray) -> jnp.ndarray:
    """Pairwise tree reduction of points over axis -3 (the term axis).

    pts: (..., T, 3, 16) with T a power of two -> (..., 3, 16).
    log2(T) vectorized complete additions.
    """
    T = pts.shape[-3]
    while T > 1:
        half = T // 2
        pts = add(pts[..., :half, :, :], pts[..., half : 2 * half, :, :])
        T = half
    return pts[..., 0, :, :]


def _pad_pow2(pts: jnp.ndarray, scalars: jnp.ndarray):
    T = pts.shape[-3]
    pow2 = 1
    while pow2 < T:
        pow2 *= 2
    if pow2 == T:
        return pts, scalars
    pad = pow2 - T
    id_pts = identity(pts.shape[:-3] + (pad,))
    pts = jnp.concatenate([pts, id_pts], axis=-3)
    zpad = jnp.zeros(scalars.shape[:-2] + (pad, scalars.shape[-1]),
                     dtype=scalars.dtype)
    scalars = jnp.concatenate([scalars, zpad], axis=-2)
    return pts, scalars


def msm(points: jnp.ndarray, scalars: jnp.ndarray) -> jnp.ndarray:
    """Batched multi-scalar multiplication with shared doublings.

    points: (..., T, 3, 16) Montgomery projective; scalars: (..., T, 16)
    plain limbs. Returns (..., 3, 16) = sum_t scalars[t] * points[t].

    MSB-first bit scan: per bit, one shared doubling of the accumulator plus
    a masked tree-sum over the T term axis — every op is batch x T wide,
    keeping VPU lanes full (SURVEY.md §2.5: batch data-parallel proof
    verification is the only first-class parallelism).
    """
    points, scalars = _pad_pow2(points, scalars)
    batch = points.shape[:-3]
    T = points.shape[-3]
    levels = max(1, T).bit_length() - 1  # log2(T)
    half = T // 2
    pad_ids = identity(batch + (half,)) if half else None

    def fold_level(_, x):
        # Pairwise-add neighbours, refill with identities: the array keeps
        # shape (..., T, 3, 16) every level, so the whole log2(T)-level tree
        # is ONE `add` instantiation inside a fori_loop — the key to fast
        # XLA compiles of the MSM body.
        xr = x.reshape(batch + (half, 2) + x.shape[-2:])
        s = add(xr[..., 0, :, :], xr[..., 1, :, :])
        return jnp.concatenate([s, pad_ids], axis=-3)

    def body(i, acc):
        acc = add(acc, acc)
        bits = _scalar_bit(scalars, 255 - i)  # (..., T)
        masked = scale(points, bits)
        if half:
            masked = jax.lax.fori_loop(0, levels, fold_level, masked)
        return add(acc, masked[..., 0, :, :])

    return jax.lax.fori_loop(0, 256, body, identity(batch))


def msm_is_identity(points: jnp.ndarray, scalars: jnp.ndarray) -> jnp.ndarray:
    """True per batch element iff sum_t scalars[t]*points[t] == O."""
    return is_identity(msm(points, scalars))


def to_affine(p: jnp.ndarray) -> jnp.ndarray:
    """Projective Montgomery -> canonical affine limbs (..., 2, 16).

    Identity maps to (0, 0), matching the 64-zero-byte mathlib encoding
    (reference G1.Bytes() via gnark RawBytes; see crypto/serialization.py).
    Uses vectorized Fermat inversion — fine for batch post-processing.
    """
    X, Y, Z = p[..., _X, :], p[..., _Y, :], p[..., _Z, :]
    zinv = field.inv(Z, FP)
    xa = field.from_mont(field.mont_mul(X, zinv, FP), FP)
    ya = field.from_mont(field.mont_mul(Y, zinv, FP), FP)
    inf = is_identity(p)[..., None]
    xa = jnp.where(inf, jnp.zeros_like(xa), xa)
    ya = jnp.where(inf, jnp.zeros_like(ya), ya)
    return jnp.stack([xa, ya], axis=-2)


def points_equal(p: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
    """Projective equality without inversion: cross-multiplied coordinates."""
    X1, Y1, Z1 = p[..., _X, :], p[..., _Y, :], p[..., _Z, :]
    X2, Y2, Z2 = q[..., _X, :], q[..., _Y, :], q[..., _Z, :]
    x_eq = field.is_zero(
        field.sub(field.mont_mul(X1, Z2, FP),
                  field.mont_mul(X2, Z1, FP), FP))
    y_eq = field.is_zero(
        field.sub(field.mont_mul(Y1, Z2, FP),
                  field.mont_mul(Y2, Z1, FP), FP))
    both_id = jnp.logical_and(is_identity(p), is_identity(q))
    one_id = jnp.logical_xor(is_identity(p), is_identity(q))
    eq = jnp.logical_and(x_eq, y_eq)
    return jnp.where(both_id, True, jnp.where(one_id, False, eq))
