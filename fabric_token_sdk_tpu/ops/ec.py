"""BN254 G1 arithmetic + batched MSM on TPU via complete projective formulas.

Points are (..., 3, 16) uint32 arrays: Montgomery-form homogeneous
projective (X, Y, Z) with the identity at (0 : y≠0 : 0). Addition uses the
Renes-Costello-Batina complete formulas for a=0 short-Weierstrass curves
(eprint 2015/1060, Algorithm 7, b3 = 3*b = 9 for BN254): one unconditional
14-multiplication sequence valid for EVERY input pair — doubling, identity,
inverses — so traced graphs contain no case analysis at all. That keeps the
256-step scalar/MSM loop bodies small enough for fast XLA compiles and all
lanes doing useful work (SURVEY.md §7: no data-dependent control flow).

Equivalent of the reference's gnark-crypto G1 ops used via IBM/mathlib
(G1.Mul/Add/Sub, reference token/core/zkatdlog/nogh/v1/crypto files passim).
The batched `msm_is_identity` replaces the sequential per-proof loop at
reference rp/rangecorrectness.go:137-162.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import field
from . import limbs as L
from .field import FP

# Point component indices.
_X, _Y, _Z = 0, 1, 2

# b3 = 3*b = 9 in Montgomery form (curve y^2 = x^3 + 3).
_B3_MONT = tuple(int(v) for v in L.int_to_limbs(L.fp_to_mont_int(9)))


def _b3() -> jnp.ndarray:
    return jnp.asarray(np.array(_B3_MONT, dtype=np.uint32))


def identity(batch_shape: tuple[int, ...] = ()) -> jnp.ndarray:
    """Identity point(s): (batch..., 3, 16) = (0 : 1 : 0) in Montgomery."""
    zero = jnp.zeros(L.NLIMBS, dtype=jnp.uint32)
    pt = jnp.stack([zero, FP.r1_arr, zero])
    return jnp.broadcast_to(pt, batch_shape + pt.shape)


def is_identity(p: jnp.ndarray) -> jnp.ndarray:
    return field.is_zero(p[..., _Z, :])


def _add_complete(p: jnp.ndarray, q: jnp.ndarray,
                  z_lazy_out: bool) -> jnp.ndarray:
    """Shared interior of `add` / `add_zlazy` (RCB15 Alg 7, 6+2+6 muls).

    Accepts p with Z in LAZY form (limbs <= 2^16, value < 2p): Z1 enters
    mont_mul as its single lazy operand (rule R3) and the a1-side sums
    add_lazy it against a canonical coordinate (rule R1, < 3p). q must
    be fully canonical (its sums ride the exact adder on the b1 side).
    With z_lazy_out the output Z skips the exact carry resolution and
    stays lazy (< 2p) for the next chained `add_zlazy`.
    """
    X1, Y1, Z1 = p[..., _X, :], p[..., _Y, :], p[..., _Z, :]
    X2, Y2, Z2 = q[..., _X, :], q[..., _Y, :], q[..., _Z, :]
    addf = lambda a, b: field.add(a, b, FP)
    subf = lambda a, b: field.sub(a, b, FP)
    subl = lambda a, b: field.sub_lazy(a, b, FP)

    # round 1: t0=X1X2, t1=Y1Y2, t2=Z1Z2 and the three cross sums (a1
    # side lazy, b1 side exact: no lane sees two lazy mont operands).
    a1 = jnp.stack([X1, Y1, Z1, field.add_lazy(X1, Y1),
                    field.add_lazy(Y1, Z1), field.add_lazy(X1, Z1)])
    b1 = jnp.stack([X2, Y2, Z2, addf(X2, Y2), addf(Y2, Z2), addf(X2, Z2)])
    m = field.mont_mul(a1, b1, FP)
    t0, t1, t2 = m[0], m[1], m[2]
    t3 = subl(subl(m[3], t0), t1)        # X1Y2 + X2Y1      (lazy, < 5p)
    t4 = subl(subl(m[4], t1), t2)        # Y1Z2 + Y2Z1      (lazy, < 5p)
    y3 = subl(subl(m[5], t0), t2)        # X1Z2 + X2Z1      (lazy, < 5p)
    t0 = addf(addf(t0, t0), t0)          # 3*X1X2 (exact: meets lazy t3)

    # round 2: the two b3 scalings.
    s = field.mont_mul(jnp.stack([t2, y3]),
                       jnp.broadcast_to(_b3(), t2.shape), FP)
    t2, y3 = s[0], s[1]
    z3 = addf(t1, t2)
    t1 = subf(t1, t2)

    # round 3: the six output products.
    a3 = jnp.stack([t4, t3, y3, t1, t0, z3])
    b3v = jnp.stack([y3, t1, t0, z3, t3, t4])
    o = field.mont_mul(a3, b3v, FP)
    x3 = subf(o[1], o[0])                # t3*t1 - t4*y3
    y3o = addf(o[3], o[2])               # t1*z3 + y3*t0
    if z_lazy_out:
        z3o = field.add_lazy(o[5], o[4])  # z3*t4 + t0*t3  (lazy, < 2p)
    else:
        z3o = addf(o[5], o[4])           # z3*t4 + t0*t3
    return jnp.stack([x3, y3o, z3o], axis=-2)


def add(p: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
    """Complete projective addition (RCB15 Algorithm 7, a=0, b3=9).

    Valid unconditionally for all inputs, including p == q (doubling),
    p == -q (yields the identity), and either operand the identity.

    The 14 field multiplications are grouped into THREE stacked mont_mul
    calls (6 + 2 + 6 independent products batched along a new leading axis):
    the traced graph shrinks ~3x — which is what keeps the 256-step
    scalar/MSM loop bodies fast to compile — and the wider batches fill
    VPU lanes better at small batch sizes.

    Canonical limbs in/out, but the interior runs in lazy-carry form
    (field.add_lazy / sub_lazy, rules R1-R4 in ops/tfield.py): the
    a1-side sums and t3/t4/y3 skip the carry lookahead + conditional
    subtract and enter the next mont_mul as its single lazy operand.
    """
    return _add_complete(p, q, z_lazy_out=False)


def add_zlazy(p: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
    """Complete addition with a Z-LAZY accumulator: the chained form of
    `add` for sequential folds acc <- acc + term (XLA-layout mirror of
    tec.add_zlazy — invariant documented there).

      p:  X, Y canonical (< p); Z lazy (limbs <= 2^16, value < 2p).
      q:  fully canonical.

    The accumulator's Z carry resolution is deferred across the whole
    chain (one `normalize_point` at the chain end) instead of one exact
    carry-lookahead + conditional-subtract per add. Same complete RCB15
    formulas, so identity and p == +-q lanes remain valid throughout.
    """
    return _add_complete(p, q, z_lazy_out=True)


def double(p: jnp.ndarray) -> jnp.ndarray:
    """Doubling via the complete addition (valid for all inputs)."""
    return add(p, p)


def neg(p: jnp.ndarray) -> jnp.ndarray:
    Y = field.neg(p[..., _Y, :], FP)
    return p.at[..., _Y, :].set(Y)


def madd(p: jnp.ndarray, q_aff: jnp.ndarray) -> jnp.ndarray:
    """Mixed addition p + (x2 : y2 : 1) — RCB15 Algorithm 8 (a=0, b3=9).

    13 field muls (5 + 2 + 6) instead of `add`'s 14, with a lazy-carry
    interior that keeps the accumulator's Y/Z in lazy form ACROSS fold
    iterations (XLA-layout mirror of tec.madd; the invariant and rules
    live there). p: (..., 3, 16) with X canonical and Y/Z lazy-tolerant
    (limbs <= 2^16, value < 2p); q_aff: (..., 2, 16) canonical Montgomery
    affine. Complete for every p including identity and p == +-Q, but NOT
    for q at infinity — mask digit 0 via `madd_masked`. Finish chains
    with `normalize_point`.
    """
    X1, Y1, Z1 = p[..., _X, :], p[..., _Y, :], p[..., _Z, :]
    xq, yq = q_aff[..., 0, :], q_aff[..., 1, :]
    addf = lambda a, b: field.add(a, b, FP)
    subf = lambda a, b: field.sub(a, b, FP)
    subl = lambda a, b: field.sub_lazy(a, b, FP)

    # round 1 (5 muls): Z2 = 1 makes t2 = Z1 free and collapses the
    # cross terms to t4 = Y2*Z1 + Y1, y3 = X2*Z1 + X1.
    s1 = field.add_lazy(X1, Y1)          # lazy < 3p (X canonical)
    s2 = addf(xq, yq)
    a1 = jnp.stack([X1, Y1, s1, Z1, Z1])
    b1 = jnp.stack([xq, yq, s2, yq, xq])
    m = field.mont_mul(a1, b1, FP)
    t0, t1 = m[0], m[1]                  # X1xq, Y1yq (canonical)
    t3 = subl(subl(m[2], t0), t1)        # X1Y2 + X2Y1      (lazy, < 5p)
    t4 = field.add_lazy(m[3], Y1)        # Y2Z1 + Y1        (lazy, < 3p)
    y3 = field.add_lazy(m[4], X1)        # X2Z1 + X1        (lazy, < 2p)
    t0 = addf(addf(t0, t0), t0)          # 3*X1X2 (exact: meets lazy t3)

    # round 2 (2 muls): b3 scalings of t2 = Z1 (lazy) and y3 (lazy).
    s = field.mont_mul(jnp.stack([Z1, y3]),
                       jnp.broadcast_to(_b3(), t1.shape), FP)
    t2, y3 = s[0], s[1]
    z3 = addf(t1, t2)                    # exact: z3 meets lazy t4
    t1 = subf(t1, t2)                    # exact: t1 meets lazy t3

    # round 3 (6 muls): each lane lazy x canonical.
    a3 = jnp.stack([t4, t3, y3, t1, t0, z3])
    b3v = jnp.stack([y3, t1, t0, z3, t3, t4])
    o = field.mont_mul(a3, b3v, FP)
    x3 = subf(o[1], o[0])                # canonical
    y3o = field.add_lazy(o[3], o[2])     # lazy < 2p
    z3o = field.add_lazy(o[5], o[4])     # lazy < 2p
    return jnp.stack([x3, y3o, z3o], axis=-2)


def madd_masked(p: jnp.ndarray, q_aff: jnp.ndarray,
                q_inf: jnp.ndarray) -> jnp.ndarray:
    """madd with the identity-table-entry mask: where q_inf (the digit-0
    lanes, whose affine entry (0, 0) is not a curve point) keep p."""
    return jnp.where(q_inf[..., None, None], p, madd(p, q_aff))


def normalize_point(p: jnp.ndarray) -> jnp.ndarray:
    """Resolve a madd-chain accumulator to fully canonical limbs (X is
    already canonical under the madd invariant; Y/Z are lazy < 2p)."""
    X, Y, Z = p[..., _X, :], p[..., _Y, :], p[..., _Z, :]
    return jnp.stack(
        [X, field.normalize(Y, FP), field.normalize(Z, FP)], axis=-2)


def scale(p: jnp.ndarray, bit: jnp.ndarray) -> jnp.ndarray:
    """p if bit else identity — mask X and Z (identity is (0 : y : 0); any
    y != 0 works, and real curve points never have Y = 0 on BN254)."""
    b = bit[..., None].astype(jnp.uint32)
    out = p.at[..., _X, :].set(p[..., _X, :] * b)
    return out.at[..., _Z, :].set(p[..., _Z, :] * b)


def _scalar_bit(scalars: jnp.ndarray, bit_index) -> jnp.ndarray:
    """Bit `bit_index` (0 = LSB) of (..., 16)-limb scalars -> (...,) uint32."""
    limb = bit_index // 16
    off = bit_index % 16
    word = jnp.take(scalars, limb, axis=-1)
    return (word >> off) & jnp.uint32(1)


def scalar_mul(p: jnp.ndarray, scalar: jnp.ndarray) -> jnp.ndarray:
    """Double-and-always-add over 256 fixed iterations (branchless).

    p: (..., 3, 16) point(s); scalar: (..., 16) plain-integer limbs.
    Verification-side only: constant-time not required (SURVEY.md §7), but
    the structure is data-oblivious anyway.
    """
    batch = p.shape[:-2]

    def body(i, acc):
        acc = add(acc, acc)
        bit = _scalar_bit(scalar, 255 - i)
        return add(acc, scale(p, bit))

    return jax.lax.fori_loop(0, 256, body, identity(batch))


def _tree_sum(pts: jnp.ndarray) -> jnp.ndarray:
    """Pairwise tree reduction of points over axis -3 (the term axis).

    pts: (..., T, 3, 16) with T a power of two -> (..., 3, 16).
    log2(T) vectorized complete additions.
    """
    T = pts.shape[-3]
    while T > 1:
        half = T // 2
        pts = add(pts[..., :half, :, :], pts[..., half : 2 * half, :, :])
        T = half
    return pts[..., 0, :, :]


def _pad_pow2(pts: jnp.ndarray, scalars: jnp.ndarray):
    T = pts.shape[-3]
    pow2 = 1
    while pow2 < T:
        pow2 *= 2
    if pow2 == T:
        return pts, scalars
    pad = pow2 - T
    id_pts = identity(pts.shape[:-3] + (pad,))
    pts = jnp.concatenate([pts, id_pts], axis=-3)
    zpad = jnp.zeros(scalars.shape[:-2] + (pad, scalars.shape[-1]),
                     dtype=scalars.dtype)
    scalars = jnp.concatenate([scalars, zpad], axis=-2)
    return pts, scalars


def msm(points: jnp.ndarray, scalars: jnp.ndarray) -> jnp.ndarray:
    """Batched multi-scalar multiplication with shared doublings.

    points: (..., T, 3, 16) Montgomery projective; scalars: (..., T, 16)
    plain limbs. Returns (..., 3, 16) = sum_t scalars[t] * points[t].

    MSB-first bit scan: per bit, one shared doubling of the accumulator plus
    a masked tree-sum over the T term axis — every op is batch x T wide,
    keeping VPU lanes full (SURVEY.md §2.5: batch data-parallel proof
    verification is the only first-class parallelism).
    """
    points, scalars = _pad_pow2(points, scalars)
    batch = points.shape[:-3]
    T = points.shape[-3]
    levels = max(1, T).bit_length() - 1  # log2(T)
    half = T // 2
    pad_ids = identity(batch + (half,)) if half else None

    def fold_level(_, x):
        # Pairwise-add neighbours, refill with identities: the array keeps
        # shape (..., T, 3, 16) every level, so the whole log2(T)-level tree
        # is ONE `add` instantiation inside a fori_loop — the key to fast
        # XLA compiles of the MSM body.
        xr = x.reshape(batch + (half, 2) + x.shape[-2:])
        s = add(xr[..., 0, :, :], xr[..., 1, :, :])
        return jnp.concatenate([s, pad_ids], axis=-3)

    def body(i, acc):
        acc = add(acc, acc)
        bits = _scalar_bit(scalars, 255 - i)  # (..., T)
        masked = scale(points, bits)
        if half:
            masked = jax.lax.fori_loop(0, levels, fold_level, masked)
        return add(acc, masked[..., 0, :, :])

    return jax.lax.fori_loop(0, 256, body, identity(batch))


def msm_is_identity(points: jnp.ndarray, scalars: jnp.ndarray) -> jnp.ndarray:
    """True per batch element iff sum_t scalars[t]*points[t] == O."""
    return is_identity(msm(points, scalars))


# --------------------------------------------------------------------------
# Windowed kernels: the throughput path.
#
# The bit-serial kernels above cost ~2 point-adds per scalar bit per term.
# The windowed forms below trade a small precomputed multiple table per term
# for 4-bit digits: 64 windows x (1 table-select + tree-sum) + 4 shared
# doublings per window — ~8x fewer complete additions for the same MSM.
# Fixed public-parameter generators go further: an 8-bit fixed-base table
# (built once per pp on device) turns each scalar mul into 32 gathers + 31
# adds with no doublings at all. (VERDICT round 1, Weak #7.)
# --------------------------------------------------------------------------

_W4_WINDOWS = 64   # 256 bits / 4
_W8_WINDOWS = 32   # 256 bits / 8


def window_digits4(scalars: jnp.ndarray) -> jnp.ndarray:
    """(..., 16) uint32 limbs -> (..., 64) int32 4-bit digits, LSB first."""
    l = scalars.astype(jnp.int32)
    d = jnp.stack([l & 0xF, (l >> 4) & 0xF, (l >> 8) & 0xF, (l >> 12) & 0xF],
                  axis=-1)
    return d.reshape(*scalars.shape[:-1], _W4_WINDOWS)


def window_digits8(scalars: jnp.ndarray) -> jnp.ndarray:
    """(..., 16) uint32 limbs -> (..., 32) int32 8-bit digits, LSB first."""
    l = scalars.astype(jnp.int32)
    d = jnp.stack([l & 0xFF, (l >> 8) & 0xFF], axis=-1)
    return d.reshape(*scalars.shape[:-1], _W8_WINDOWS)


def _multiple_table(points: jnp.ndarray, entries: int) -> jnp.ndarray:
    """(..., 3, 16) -> (..., entries, 3, 16): v -> v*P for v in [0, entries).

    Sequential adds via lax.scan (entries-1 steps, each batch-wide)."""
    idp = identity(points.shape[:-2])

    def step(cur, _):
        nxt = add(cur, points)
        return nxt, nxt

    _, chain = jax.lax.scan(step, idp, None, length=entries - 1)
    # chain: (entries-1, ..., 3, 16) with chain[k] = (k+1)*P
    chain = jnp.moveaxis(chain, 0, -3)
    return jnp.concatenate([idp[..., None, :, :], chain], axis=-3)


def _tree_sum_loop(pts: jnp.ndarray) -> jnp.ndarray:
    """Tree reduction over axis -3 with ONE add instantiation.

    Pads the term axis to a power of two with identities, then folds
    inside a fori_loop whose body keeps the array shape constant (pair-add
    the valid prefix, refill with identities — the ec.msm fold_level
    trick). Graph size is O(1) in T instead of O(log T) distinct add
    shapes; XLA:CPU compile time of the big term buckets drops several-
    fold, which is what keeps the driver's multichip dryrun inside its
    budget (the persistent cache cannot help: XLA:CPU AOT entries bake
    LLVM *tuning* pseudo-features like +prefer-no-gather that the loader
    then rejects against raw cpuid host features — every entry is
    write-only). Costs up to 2x the lane-adds of the shrinking fold, so
    the TPU backend keeps the shrink variant.
    """
    T = pts.shape[-3]
    pow2 = 1
    while pow2 < T:
        pow2 *= 2
    batch = pts.shape[:-3]
    if pow2 != T:
        pts = jnp.concatenate(
            [pts, identity(batch + (pow2 - T,))], axis=-3)
    if pow2 == 1:
        return pts[..., 0, :, :]
    half = pow2 // 2
    levels = pow2.bit_length() - 1
    pad_ids = identity(batch + (half,))

    def fold_level(_, x):
        xr = x.reshape(batch + (half, 2) + x.shape[-2:])
        s = add(xr[..., 0, :, :], xr[..., 1, :, :])
        return jnp.concatenate([s, pad_ids], axis=-3)

    out = jax.lax.fori_loop(0, levels, fold_level, pts)
    return out[..., 0, :, :]


def _tree_sum_shrink(pts: jnp.ndarray) -> jnp.ndarray:
    """Tree reduction over axis -3 with shrinking shapes (odd tail carried).

    On XLA:CPU, large term counts route through the compile-cheap
    single-instantiation fold instead (see _tree_sum_loop)."""
    T = pts.shape[-3]
    if T > 4 and jax.default_backend() == "cpu":
        return _tree_sum_loop(pts)
    while T > 1:
        half = T // 2
        s = add(pts[..., :half, :, :], pts[..., half : 2 * half, :, :])
        if T % 2:
            s = jnp.concatenate([s, pts[..., 2 * half :, :, :]], axis=-3)
        pts = s
        T = pts.shape[-3]
    return pts[..., 0, :, :]


def plane_dtype() -> jnp.dtype:
    """Element type for the one-hot byte-plane selection matmuls.

    bf16 on TPU: integers <= 255 are exact in bf16, so the selection rides
    the MXU at its native single-pass precision; f32 planes are NOT safe
    there because TPU matmuls truncate f32 operands to bf16 by default and
    16-bit limb values would lose their low bits. f32 on CPU: XLA:CPU's
    DotThunk cannot execute bf16 x bf16 -> f32 dots at all, and f32
    selection is equally exact (values <= 255, single 1 per one-hot row).
    Resolved at trace time from the default backend; tables and one-hot
    operands both funnel through this so they cannot disagree in-process.
    """
    return jnp.float32 if jax.default_backend() == "cpu" else jnp.bfloat16


def _to_byte_planes(tables: jnp.ndarray) -> jnp.ndarray:
    """(..., C, 16) uint32 limb tables -> (..., 2*C*16) byte planes.

    C = 3 for projective tables (96 planes), C = 2 for affine (64).
    Each 16-bit limb splits into (lo, hi) bytes; dtype per plane_dtype()
    (bf16 on TPU for MXU exactness, f32 on CPU for dispatchability)."""
    flat = tables.reshape(*tables.shape[:-2],
                          tables.shape[-2] * tables.shape[-1])
    dt = plane_dtype()
    lo = (flat & 0xFF).astype(dt)
    hi = ((flat >> 8) & 0xFF).astype(dt)
    return jnp.concatenate([lo, hi], axis=-1)


def _from_byte_planes(sel: jnp.ndarray, ncoords: int = 3) -> jnp.ndarray:
    """(..., 2*ncoords*16) f32 selected planes -> (..., ncoords, 16)."""
    u = sel.astype(jnp.uint32)
    c = ncoords * L.NLIMBS
    out = u[..., :c] + (u[..., c:] << 8)
    return out.reshape(*out.shape[:-1], ncoords, L.NLIMBS)


def _select_onehot(tables_planes: jnp.ndarray, digits: jnp.ndarray,
                   entries: int) -> jnp.ndarray:
    """Table selection as a one-hot MXU matmul (no gather).

    tables_planes: (..., T, entries, 96) bf16 byte planes
    (_to_byte_planes); digits: (..., T) int32 in [0, entries).
    Returns (..., T, 3, 16) uint32 — bit-exact (single 1 per one-hot row,
    plane values <= 255), riding the MXU instead of HBM scatter/gather,
    which is the difference between ~ms and ~100s of ms per pass on TPU.
    """
    onehot = jax.nn.one_hot(digits, entries, dtype=plane_dtype())
    sel = jnp.einsum("...tv,...tvc->...tc", onehot, tables_planes,
                     preferred_element_type=jnp.float32)
    return _from_byte_planes(sel)


def _windowed_walk(tables_planes: jnp.ndarray,
                   digits: jnp.ndarray) -> jnp.ndarray:
    """The round-6 EAGER-CARRY Horner interior, kept as the comparison
    baseline for `perf_profile.py --mode pipeline`.

    tables_planes: (..., T, 16, 96) projective multiple-table byte planes;
    digits: (..., T, 64) LSB-first 4-bit digits. Scans the 64 windows
    MSB-first: 4 shared doublings + one-hot select + a TREE fold over the
    term axis whose complete adds resolve carries exactly at every level.
    """
    batch = tables_planes.shape[:-3]

    def body(i, acc):
        for _ in range(4):
            acc = add(acc, acc)
        d = jax.lax.dynamic_slice_in_dim(
            digits, _W4_WINDOWS - 1 - i, 1, axis=-1)   # (..., T, 1)
        sel = _select_onehot(tables_planes, d[..., 0].astype(jnp.int32), 16)
        term = _tree_sum_shrink(sel)
        return add(acc, term)

    return jax.lax.fori_loop(0, _W4_WINDOWS, body, identity(batch))


def msm_windowed(points: jnp.ndarray, scalars: jnp.ndarray) -> jnp.ndarray:
    """Windowed batched MSM: (..., T, 3, 16) x (..., T, 16) -> (..., 3, 16).

    Builds a 16-entry multiple table per term (15 sequential complete
    adds, T-wide), then runs the eager-carry Horner walk. General —
    accepts ANY projective input points. Hot paths whose points are
    affine-or-identity (everything the verifier uploads) use the lazified
    `msm_var_mixed` twin instead; this form is the round-6 baseline.
    """
    tables = _multiple_table(points, 16)           # (..., T, 16, 3, 16)
    tables_planes = _to_byte_planes(tables)        # (..., T, 16, 96)
    digits = window_digits4(scalars)               # (..., T, 64)
    return _windowed_walk(tables_planes, digits)


#: lanes the Z-lazy chain fold keeps live (see _chain_sum_zlazy).
_CHAIN_KEEP = 8


def _chain_sum_zlazy(pts: jnp.ndarray) -> jnp.ndarray:
    """Sum over the term axis with a Z-LAZY chained accumulator.

    pts: (..., T, 3, 16) canonical -> (..., 3, 16). Keeps _CHAIN_KEEP
    lanes live and folds the rest in a constant-shape fori chain of
    `add_zlazy` (accumulator Z stays lazy across the whole chain; the
    chunk operands are canonical table selects), resolves the deferred
    carries ONCE via normalize_point, then tree-sums the kept lanes.
    Same lane-add count as the halving tree it replaces; the per-add
    exact Z carry resolution is what the lazy chain removes.
    """
    T = pts.shape[-3]
    batch = pts.shape[:-3]
    keep = min(_CHAIN_KEEP, T)
    rem = T % keep
    if rem:
        pts = jnp.concatenate(
            [pts, identity(batch + (keep - rem,))], axis=-3)
        T = pts.shape[-3]
    chunks = T // keep
    if chunks > 1:
        def body(c, acc):
            q = jax.lax.dynamic_slice_in_dim(pts, c * keep, keep, axis=-3)
            return add_zlazy(acc, q)

        acc = jax.lax.fori_loop(1, chunks, body, pts[..., :keep, :, :])
        acc = normalize_point(acc)
    else:
        acc = pts
    return _tree_sum_shrink(acc)


def _multiple_table_mixed(aff: jnp.ndarray, inf: jnp.ndarray,
                          entries: int) -> jnp.ndarray:
    """v*P multiple tables from AFFINE-or-identity inputs via mixed adds.

    aff: (..., T, 2, 16) canonical Montgomery affine coordinates;
    inf: (..., T) bool identity mask. Returns (..., T, entries, 3, 16)
    CANONICAL projective entries.

    The chain tbl[e] = tbl[e-1] + P runs on the 13-mul RCB15 mixed add
    (madd_masked: identity lanes keep tbl[e-1] = O) with the
    accumulator's Y/Z in LAZY form ACROSS the whole entries-2 step scan
    — one vectorized normalize_point over the finished table resolves
    every deferred carry, vs one exact resolution per add in the
    complete-add chain of `_multiple_table`.
    """
    zero = jnp.zeros_like(aff[..., 0, :])
    one = jnp.broadcast_to(FP.r1_arr, zero.shape)
    infc = inf[..., None]
    # entry 1: the point itself, with identity lanes forced to the clean
    # (0 : 1 : 0) encoding regardless of their affine placeholder coords.
    base = jnp.stack([jnp.where(infc, zero, aff[..., 0, :]),
                      jnp.where(infc, one, aff[..., 1, :]),
                      jnp.where(infc, zero, one)], axis=-2)

    def step(cur, _):
        nxt = madd_masked(cur, aff, inf)
        return nxt, nxt

    _, chain = jax.lax.scan(step, base, None, length=entries - 2)
    chain = jnp.moveaxis(chain, 0, -3)             # (..., T, entries-2, 3, 16)
    idp = identity(base.shape[:-2])
    tbl = jnp.concatenate(
        [idp[..., None, :, :], base[..., None, :, :], chain], axis=-3)
    # entries 0/1 are already canonical (normalize is idempotent there);
    # the chain entries carry lazy Y/Z — resolved here, once, vectorized.
    return normalize_point(tbl)


def _windowed_walk_lazy(tables_planes: jnp.ndarray,
                        digits: jnp.ndarray) -> jnp.ndarray:
    """The LAZIFIED Horner interior: same MSB-first window scan as
    `_windowed_walk`, but the per-window term fold is the Z-lazy chain
    (`_chain_sum_zlazy`) — carries in the fold accumulator resolve once
    per window instead of once per add level."""
    batch = tables_planes.shape[:-3]

    def body(i, acc):
        for _ in range(4):
            acc = add(acc, acc)
        d = jax.lax.dynamic_slice_in_dim(
            digits, _W4_WINDOWS - 1 - i, 1, axis=-1)   # (..., T, 1)
        sel = _select_onehot(tables_planes, d[..., 0].astype(jnp.int32), 16)
        term = _chain_sum_zlazy(sel)
        return add(acc, term)

    return jax.lax.fori_loop(0, _W4_WINDOWS, body, identity(batch))


def msm_var_mixed(points: jnp.ndarray, scalars: jnp.ndarray) -> jnp.ndarray:
    """Lazified windowed var-base MSM for AFFINE-OR-IDENTITY inputs.

    points: (..., T, 3, 16) Montgomery projective with Z in {1, 0} — i.e.
    affine points or the identity, which is exactly what every verifier
    path holds (packed uploads reconstruct Z = 1, host marshalling emits
    Z = 1, pad rows are the identity); scalars: (..., T, 16) plain limbs.
    Returns (..., 3, 16), canonical.

    XLA twin of the Pallas `_msm_var_kernel` v2: multiple tables built by
    13-mul madd chains with lazy Y/Z across the chain (ONE normalize per
    table build), then the Z-lazy Horner walk. For general projective
    inputs (arbitrary Z) use `msm_windowed` — madd needs an affine second
    operand.
    """
    inf = is_identity(points)                      # (..., T)
    aff = points[..., :2, :]                       # canonical mont affine
    tables = _multiple_table_mixed(aff, inf, 16)   # (..., T, 16, 3, 16)
    tables_planes = _to_byte_planes(tables)        # (..., T, 16, 96)
    digits = window_digits4(scalars)               # (..., T, 64)
    return _windowed_walk_lazy(tables_planes, digits)


def fixed_base_tables(points: jnp.ndarray) -> jnp.ndarray:
    """Precompute 8-bit fixed-base tables for pp-constant generators.

    points: (T, 3, 16) -> (T, 32, 256, 3, 16) with
    table[t, w, v] = v * 2^(8w) * P_t. Built once per PublicParams set;
    ~204MB device-resident for T=129 (the n=64 K-equation generators).
    """
    T = points.shape[0]

    def dbl8(cur, _):
        for _ in range(8):
            cur = add(cur, cur)
        return cur, cur

    # bases[w] = 2^(8w) * P : (32, T, 3, 16)
    _, shifted = jax.lax.scan(dbl8, points, None, length=_W8_WINDOWS - 1)
    bases = jnp.concatenate([points[None], shifted], axis=0)
    bases = jnp.moveaxis(bases, 0, 1)              # (T, 32, 3, 16)
    return _multiple_table(bases, 256)             # (T, 32, 256, 3, 16)


def fixed_base_planes(points: jnp.ndarray) -> jnp.ndarray:
    """Precompute the byte-plane form of the 8-bit fixed-base tables.

    points: (T, 3, 16) -> (T, 32, 256, 96) in plane_dtype() — what the
    fixed-base kernels consume. Built once per PublicParams set (bf16
    planes are the same memory as the uint32 tables — 96 x 2 B vs
    48 x 4 B — but need no per-call conversion)."""
    return _to_byte_planes(fixed_base_tables(points))


def _fixed_base_select(table_planes: jnp.ndarray,
                       scalars: jnp.ndarray) -> jnp.ndarray:
    """One-hot-select every (term, window) table entry for the scalars.

    table_planes: (T, 32, 256, 96) bf16 (fixed_base_planes);
    scalars: (..., T, 16) plain limbs.
    Returns (..., T, 32, 3, 16) = digit_{t,w} * 2^(8w) * P_t, via the MXU
    (see _select_onehot for why byte-plane selection is exact)."""
    digits = window_digits8(scalars)               # (..., T, 32)
    onehot = jax.nn.one_hot(digits.astype(jnp.int32), 256,
                            dtype=plane_dtype())   # (..., T, 32, 256)
    sel = jnp.einsum("...twv,twvc->...twc", onehot, table_planes,
                     preferred_element_type=jnp.float32)
    return _from_byte_planes(sel)


def fixed_base_gather(table_planes: jnp.ndarray,
                      scalars: jnp.ndarray) -> jnp.ndarray:
    """Per-term fixed-base scalar mul via one-hot table selection.

    table_planes: (T, 32, 256, 96) bf16; scalars: (..., T, 16) plain limbs.
    Returns (..., T, 3, 16) = scalars[t] * P_t. 31 complete adds per term.
    """
    sel = _fixed_base_select(table_planes, scalars)  # (..., T, 32, 3, 16)
    return _tree_sum_shrink(sel)                   # fold the 32-window axis


def fixed_base_msm(table_planes: jnp.ndarray,
                   scalars: jnp.ndarray) -> jnp.ndarray:
    """Fixed-base MSM: sum_t scalars[t] * P_t over precomputed tables.

    table_planes: (T, 32, 256, 96) bf16; scalars: (..., T, 16)
    -> (..., 3, 16). Folds the window and term axes in one tree
    (31 + T-1 adds total depth log2(32*T))."""
    sel = _fixed_base_select(table_planes, scalars)  # (..., T, 32, 3, 16)
    flat = sel.reshape(*sel.shape[:-4], -1, 3, L.NLIMBS)
    return _tree_sum_shrink(flat)


def to_affine_batch(p: jnp.ndarray, keep_mont: bool = False) -> jnp.ndarray:
    """Projective -> affine over a trailing point axis, using one Fermat
    inversion per row via the Montgomery batch-inversion trick.

    p: (..., K, 3, 16) -> (..., K, 2, 16). Identity maps to (0, 0).
    keep_mont=True returns the coordinates still in MONTGOMERY form (what
    the mixed-addition table path consumes — madd multiplies them straight
    into Montgomery accumulators); default False converts out of
    Montgomery for host-facing serialization.
    """
    X, Y, Z = p[..., _X, :], p[..., _Y, :], p[..., _Z, :]
    inf = is_identity(p)                           # (..., K)
    one = jnp.broadcast_to(FP.r1_arr, Z.shape)
    z_safe = jnp.where(inf[..., None], one, Z)

    # Inclusive prefix and suffix products along K (log2 K mont_mul levels
    # each — no K-step sequential chain; the only serial part is the single
    # Fermat inversion of the row total).
    def combine(a, b):
        return field.mont_mul(a, b, FP)

    k_axis = z_safe.ndim - 2  # nonnegative: reverse=True rejects -2
    prefix = jax.lax.associative_scan(combine, z_safe, axis=k_axis)
    suffix = jax.lax.associative_scan(combine, z_safe, axis=k_axis,
                                      reverse=True)
    total_inv = field.inv(prefix[..., -1, :], FP)  # one Fermat per row

    ones = jnp.broadcast_to(FP.r1_arr, z_safe[..., :1, :].shape)
    prefix_shift = jnp.concatenate([ones, prefix[..., :-1, :]], axis=-2)
    suffix_shift = jnp.concatenate([suffix[..., 1:, :], ones], axis=-2)
    # zinv[k] = prefix[k-1] * suffix[k+1] * (prod all)^-1
    zinv = field.mont_mul(
        field.mont_mul(prefix_shift, suffix_shift, FP),
        jnp.broadcast_to(total_inv[..., None, :], z_safe.shape), FP)

    xa = field.mont_mul(X, zinv, FP)
    ya = field.mont_mul(Y, zinv, FP)
    if not keep_mont:
        xa = field.from_mont(xa, FP)
        ya = field.from_mont(ya, FP)
    xa = jnp.where(inf[..., None], jnp.zeros_like(xa), xa)
    ya = jnp.where(inf[..., None], jnp.zeros_like(ya), ya)
    return jnp.stack([xa, ya], axis=-2)


def fixed_base_affine_planes(points: jnp.ndarray) -> jnp.ndarray:
    """Affine byte-plane form of the 8-bit fixed-base tables.

    points: (T, 3, 16) -> (T, 32, 256, 64) in plane_dtype(): every table
    entry batch-normalized to MONTGOMERY affine (one Fermat inversion per
    term row via to_affine_batch) and split into 64 byte planes — 2/3 the
    select matmul rows and HBM of the 96-plane projective tables, and the
    entries feed `madd` (13 muls) instead of the complete `add` (14).
    Digit-0 entries land on (0, 0) (identity -> (0, 0)); the fold masks
    them (madd is not complete for Q at infinity)."""
    return affine_planes_from_tables(fixed_base_tables(points))


def affine_planes_from_tables(proj: jnp.ndarray) -> jnp.ndarray:
    """(T, 32, 256, 3, 16) raw projective tables -> (T, 32, 256, 64)
    affine byte planes. Split from fixed_base_affine_planes so callers
    holding the raw tables (verifier param build derives BOTH the
    projective and affine plane flavors from one table pass) skip the
    second fixed_base_tables evaluation."""
    flat = proj.reshape(-1, 256, 3, L.NLIMBS)      # rows: (T*32, 256)
    aff = to_affine_batch(flat, keep_mont=True)    # (T*32, 256, 2, 16)
    aff = aff.reshape(*proj.shape[:-2], 2, L.NLIMBS)
    return _to_byte_planes(aff)


def fixed_base_gather_mixed(affine_planes: jnp.ndarray,
                            scalars: jnp.ndarray) -> jnp.ndarray:
    """Per-term fixed-base scalar mul over AFFINE tables via madd.

    affine_planes: (T, 32, 256, 64) (fixed_base_affine_planes);
    scalars: (..., T, 16) plain limbs. Returns (..., T, 3, 16) =
    scalars[t] * P_t, canonical (normalized at the end of the chain).

    The 32 windows fold SEQUENTIALLY — madd needs an affine second
    operand, so there is no tree over window partial sums — at 13 muls
    per window (vs 14 x 31 adds for the projective tree) with all carry
    resolution deferred to one normalize_point per chain.
    """
    digits = window_digits8(scalars)               # (..., T, 32)
    onehot = jax.nn.one_hot(digits.astype(jnp.int32), 256,
                            dtype=plane_dtype())   # (..., T, 32, 256)
    sel = jnp.einsum("...twv,twvc->...twc", onehot, affine_planes,
                     preferred_element_type=jnp.float32)
    aff = _from_byte_planes(sel, ncoords=2)        # (..., T, 32, 2, 16)
    inf = (digits == 0)                            # (..., T, 32)
    batch_t = scalars.shape[:-1]                   # (..., T)

    def body(w, acc):
        q = jax.lax.dynamic_slice_in_dim(aff, w, 1, axis=-3)[..., 0, :, :]
        m = jax.lax.dynamic_slice_in_dim(inf, w, 1, axis=-1)[..., 0]
        return madd_masked(acc, q, m)

    acc = jax.lax.fori_loop(0, _W8_WINDOWS, body, identity(batch_t))
    return normalize_point(acc)


def fixed_base_msm_mixed(affine_planes: jnp.ndarray,
                         scalars: jnp.ndarray) -> jnp.ndarray:
    """Fixed-base MSM over affine tables: sum_t scalars[t] * P_t.

    affine_planes: (T, 32, 256, 64); scalars: (..., T, 16) -> (..., 3, 16).
    Per-term madd chains (fixed_base_gather_mixed), then a projective
    tree over the term axis (the partial sums are projective, so the
    cross-term fold keeps the complete add)."""
    return _tree_sum_shrink(fixed_base_gather_mixed(affine_planes, scalars))


def to_affine(p: jnp.ndarray) -> jnp.ndarray:
    """Projective Montgomery -> canonical affine limbs (..., 2, 16).

    Identity maps to (0, 0), matching the 64-zero-byte mathlib encoding
    (reference G1.Bytes() via gnark RawBytes; see crypto/serialization.py).
    Uses vectorized Fermat inversion — fine for batch post-processing.
    """
    X, Y, Z = p[..., _X, :], p[..., _Y, :], p[..., _Z, :]
    zinv = field.inv(Z, FP)
    xa = field.from_mont(field.mont_mul(X, zinv, FP), FP)
    ya = field.from_mont(field.mont_mul(Y, zinv, FP), FP)
    inf = is_identity(p)[..., None]
    xa = jnp.where(inf, jnp.zeros_like(xa), xa)
    ya = jnp.where(inf, jnp.zeros_like(ya), ya)
    return jnp.stack([xa, ya], axis=-2)


def points_equal(p: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
    """Projective equality without inversion: cross-multiplied coordinates."""
    X1, Y1, Z1 = p[..., _X, :], p[..., _Y, :], p[..., _Z, :]
    X2, Y2, Z2 = q[..., _X, :], q[..., _Y, :], q[..., _Z, :]
    x_eq = field.is_zero(
        field.sub(field.mont_mul(X1, Z2, FP),
                  field.mont_mul(X2, Z1, FP), FP))
    y_eq = field.is_zero(
        field.sub(field.mont_mul(Y1, Z2, FP),
                  field.mont_mul(Y2, Z1, FP), FP))
    both_id = jnp.logical_and(is_identity(p), is_identity(q))
    one_id = jnp.logical_xor(is_identity(p), is_identity(q))
    eq = jnp.logical_and(x_eq, y_eq)
    return jnp.where(both_id, True, jnp.where(one_id, False, eq))
