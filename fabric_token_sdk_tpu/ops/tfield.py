"""Transposed-layout field arithmetic for the Pallas (Mosaic) kernels.

Layout: arrays are (..., K, LANE) — the limb axis K sits on TPU *sublanes*
(second-minor) and LANE is a batch axis on the 128-wide *lane* dimension.
`ops/field.py` puts limbs minor, which wastes 7/8 of every VPU lane once the
ops run inside a Pallas kernel (a 16-limb minor axis occupies 16 of 128
lanes); transposing batch onto the lane axis keeps every vector op
full-width. Semantics are identical to ops/field.py — the parity suite
pins every op against it (tests/test_tfield.py).

Mosaic constraints shape the implementation (vs ops/field.py):
- no associative_scan (zero-size slices): carry lookahead is an unrolled
  Kogge-Stone;
- no u32<->float casts: byte/nibble planes detour through int32;
- no reshapes that mix tiled dims: shifts are concatenate-based along the
  sublane axis, products use an explicit shift-add schedule;
- no captured device constants: every modulus-dependent array rides in a
  `TSpec` the caller builds (outside a kernel from host constants, inside a
  kernel from refs passed to pallas_call).

The per-mont_mul schedule mirrors field.mont_mul's separated (SOS) form:
T = a*b (schoolbook shift-add columns), m = T_lo * N' (nibble-Toeplitz
matmul, MXU), S = (T + m*mod) >> 256 (same matmul trick), one conditional
subtract. Equivalent of the reference's gnark-crypto assembly field layer
(reference token/core/zkatdlog/nogh/v1/crypto/setup.go:14) re-planned for
the TPU memory hierarchy.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from . import limbs as L

N = L.NLIMBS
BITS = L.LIMB_BITS
MASK = L.LIMB_MASK  # python int: never captured as a device constant


class TSpec(NamedTuple):
    """Field constants in transposed layout (limb axis leading, lane=1).

    All arrays broadcast over the lane axis. `w_nprime`/`w_mod` are
    5-nibble-plane Toeplitz matrices (`_toeplitz_t`) accepting LAZY
    (17-bit) limb operands, so the in-kernel contraction is a plain
    (M,K)x(K,LANE) matmul. mod_int is a python int (jit-static).
    """

    mod: jnp.ndarray       # (N, 1) uint32
    nprime: jnp.ndarray    # (N, 1) uint32  (-mod^-1 mod 2^256, low limbs)
    r1: jnp.ndarray        # (N, 1) uint32  (Montgomery 1)
    w_nprime: jnp.ndarray  # (4, N, 5N)  int8: T_lo * N' mod 2^256
    w_mod: jnp.ndarray     # (4, 2N, 5N) int8: m * mod, full 2N limbs
    mod_int: int


def _toeplitz_t(const_limbs: tuple, out_cols: int) -> np.ndarray:
    """(4, out_cols, 5N) int8 Toeplitz planes for a LAZY-limb operand.

    Input row r = 5i + k is nibble k of limb i, at bit position 4(4i + k)
    — FIVE nibbles per limb so operands may carry up to 20-bit "lazy"
    limbs (the k = 4 row overlaps limb i+1's nibble 0; for canonical
    16-bit limbs it is simply zero). W[kk, l, r] = nibble (4l + kk - p(r))
    of the constant with p(r) = 4i + k, so the in-kernel contraction is
    four plain (out_cols, 5N) x (5N, LANE) matmuls recombined by shifts —
    column sums of a * const at 16-bit granularity, truncated past
    out_cols (drops only multiples of 2^(16*out_cols))."""
    c = []
    for limb in const_limbs:
        for shift in (0, 4, 8, 12):
            c.append((int(limb) >> shift) & 0xF)
    w = np.zeros((4, out_cols, 5 * N), dtype=np.int8)
    for r in range(5 * N):
        i, k = divmod(r, 5)
        p = 4 * i + k
        for l in range(out_cols):
            for kk in range(4):
                j = 4 * l + kk - p
                if 0 <= j < len(c):
                    w[kk, l, r] = c[j]
    return np.ascontiguousarray(w)


def make_tspec(spec) -> TSpec:
    """Build a TSpec from an ops.field.FieldSpec (host-side constants)."""
    return TSpec(
        mod=jnp.asarray(np.array(spec.mod, dtype=np.uint32)[:, None]),
        nprime=jnp.asarray(np.array(spec.nprime, dtype=np.uint32)[:, None]),
        r1=jnp.asarray(np.array(spec.r1, dtype=np.uint32)[:, None]),
        w_nprime=jnp.asarray(_toeplitz_t(spec.nprime, N)),
        w_mod=jnp.asarray(_toeplitz_t(spec.mod, 2 * N)),
        mod_int=spec.mod_int,
    )


# --------------------------------------------------------------------------
# shifts along the limb (second-minor) axis — concatenate-based: Mosaic has
# no general pad, and slicing off the top + stacking zeros below is a plain
# sublane rotation it handles well.
# --------------------------------------------------------------------------

def _shift_down(x: jnp.ndarray, d: int, fill=0) -> jnp.ndarray:
    """x[..., i, :] -> x[..., i-d, :] (toward higher limb index); the d new
    bottom rows are `fill`."""
    if d == 0:
        return x
    k = x.shape[-2]
    if d >= k:
        return jnp.full_like(x, fill)
    pad = jnp.full(x.shape[:-2] + (d, x.shape[-1]), fill, dtype=x.dtype)
    return jnp.concatenate([pad, x[..., :k - d, :]], axis=-2)


def _top_row(x: jnp.ndarray) -> jnp.ndarray:
    """x[..., K-1, :] as (..., 1, LANE) (static slice; no int indexing)."""
    return x[..., x.shape[-2] - 1:, :]


# --------------------------------------------------------------------------
# carry machinery (mirrors field._carry_propagate / _lookahead / _sub_limbs)
# --------------------------------------------------------------------------

def _lookahead(g: jnp.ndarray, p: jnp.ndarray) -> jnp.ndarray:
    """Exclusive Kogge-Stone carry prefix along axis -2.

    g, p are uint32 0/1 masks (not bool: Mosaic cannot concatenate i1
    vectors, which the shifts need). Returns carry_in per limb as u32."""
    k = g.shape[-2]
    d = 1
    while d < k:
        g = g | (p & _shift_down(g, d, fill=0))
        p = p & _shift_down(p, d, fill=1)
        d *= 2
    return _shift_down(g, 1)


def _fit_limbs(t: jnp.ndarray, out_limbs: int) -> jnp.ndarray:
    k = t.shape[-2]
    if k < out_limbs:
        z = jnp.zeros(t.shape[:-2] + (out_limbs - k, t.shape[-1]),
                      dtype=t.dtype)
        return jnp.concatenate([t, z], axis=-2)
    return t[..., :out_limbs, :]


def carry_propagate(t: jnp.ndarray, out_limbs: int) -> jnp.ndarray:
    """Lazy column sums (< 2^32) -> canonical 16-bit limbs, axis -2."""
    t = _fit_limbs(t, out_limbs)
    v = (t & MASK) + _shift_down(t >> BITS, 1)
    v = (v & MASK) + _shift_down(v >> BITS, 1)
    g = v >> BITS                     # 0/1: v == 2^16 exactly
    p = (v == MASK).astype(jnp.uint32)
    return (v + _lookahead(g, p)) & MASK


def lazy_limbs(t: jnp.ndarray, out_limbs: int) -> jnp.ndarray:
    """Lazy column sums -> LAZY limbs: ONE ripple pass, no lookahead.

    Output limbs are bounded by 2^16 - 1 + (max column >> 16) — for the
    < 2^27 columns mont_mul feeds this, < 2^16 + 2^11 (17 bits), inside
    the 20-bit tolerance of the 5-nibble Toeplitz planes. Value is
    congruent mod 2^(16*out_limbs) (top carry dropped), which is all the
    Montgomery reduction needs from T_lo and m."""
    t = _fit_limbs(t, out_limbs)
    return (t & MASK) + _shift_down(t >> BITS, 1)


def _sub_limbs(a: jnp.ndarray, b: jnp.ndarray):
    """a - b canonical; returns (diff, borrow_out (..., 1, LANE) u32)."""
    b = jnp.broadcast_to(b, a.shape)
    g = (a < b).astype(jnp.uint32)
    p = (a == b).astype(jnp.uint32)
    borrow_in = _lookahead(g, p)
    diff = (a + jnp.uint32(1 << BITS) - b - borrow_in) & MASK
    last = _top_row(g) | (_top_row(p) & _top_row(borrow_in))
    return diff, last


def _cond_sub_mod(res: jnp.ndarray, ts: TSpec) -> jnp.ndarray:
    """One conditional subtract of mod over N+1 limbs -> N limbs."""
    z = jnp.zeros(res.shape[:-2] + (1, 1), dtype=jnp.uint32)
    mod_ext = jnp.concatenate(
        [jnp.broadcast_to(ts.mod, res.shape[:-2] + (N, 1)), z], axis=-2)
    diff, borrow = _sub_limbs(res, mod_ext)
    keep = borrow != 0  # (..., 1, LANE): broadcasts over the limb axis
    return jnp.where(keep, res, diff)[..., :N, :]


def add(a: jnp.ndarray, b: jnp.ndarray, ts: TSpec) -> jnp.ndarray:
    s = carry_propagate(a + b, N + 1)
    return _cond_sub_mod(s, ts)


def sub(a: jnp.ndarray, b: jnp.ndarray, ts: TSpec) -> jnp.ndarray:
    diff, borrow = _sub_limbs(a, jnp.broadcast_to(b, a.shape))
    fixed = carry_propagate(diff + ts.mod, N)
    return jnp.where(borrow != 0, fixed, diff)


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    """(..., K, LANE) -> (..., 1, LANE) bool."""
    return jnp.all(a == 0, axis=-2, keepdims=True)


# --------------------------------------------------------------------------
# products
# --------------------------------------------------------------------------

def _product_cols(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Lazy column sums of a*b, both (..., N, LANE) canonical.

    Schoolbook shift-add: for each limb row i of `a`, one full-width vector
    multiply a_i * b and two shifted accumulations (lo/hi halves). Columns
    stay < 2^21 (32 half-terms of < 2^16). Returns (..., 2N, LANE).
    All VPU; the variable x variable product has no constant operand to
    Toeplitz-ize onto the MXU.
    """
    lanes = a.shape[-1]
    batch = a.shape[:-2]

    def placed(x, before: int):
        """x padded to 2N rows starting at `before` (no zero-size pieces —
        Mosaic rejects empty vectors)."""
        parts = []
        if before:
            parts.append(jnp.zeros(batch + (before, lanes),
                                   dtype=jnp.uint32))
        parts.append(x)
        after = 2 * N - before - x.shape[-2]
        if after:
            parts.append(jnp.zeros(batch + (after, lanes),
                                   dtype=jnp.uint32))
        return parts[0] if len(parts) == 1 else jnp.concatenate(parts,
                                                                axis=-2)

    cols = jnp.zeros(batch + (2 * N, lanes), dtype=jnp.uint32)
    for i in range(N):
        p = a[..., i:i + 1, :] * b          # (..., N, LANE) full products
        cols = cols + placed(p & MASK, i)
        cols = cols + placed(p >> BITS, i + 1)
    return cols


def _nibbles(a: jnp.ndarray) -> jnp.ndarray:
    """(..., N, LANE) u32 limbs (canonical OR lazy < 2^20) ->
    (..., 5N, LANE) int8 nibbles, row 5i+k = (a[i] >> 4k) & 0xF — the
    `_toeplitz_t` row order; the fifth nibble carries the lazy overflow
    (zero for canonical limbs)."""
    parts = []
    for i in range(N):
        row = a[..., i:i + 1, :].astype(jnp.int32)
        for k in (0, 4, 8, 12, 16):
            parts.append((row >> k) & 0xF)
    return jnp.concatenate(parts, axis=-2).astype(jnp.int8)


def _const_product_cols(a: jnp.ndarray, w_t: jnp.ndarray) -> jnp.ndarray:
    """Lazy columns of a * CONSTANT via the transposed nibble-Toeplitz dots.

    a: (N, LANE) canonical or lazy (< 2^20 limbs); w_t: (4, out_cols, 5N)
    int8 (TSpec layout). Four (out_cols, 5N) x (5N, LANE) MXU matmuls in
    int32 accumulation (one per output nibble position), folded with
    shifts. No batch dims: the kernels call this on 2-D tiles.
    """
    nib = _nibbles(a)                                   # (5N, LANE) i8

    def dot_k(k):
        c = jax.lax.dot_general(
            w_t[k], nib, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)           # (out, LANE)
        return c.astype(jnp.uint32)

    return (dot_k(0) + (dot_k(1) << 4) + (dot_k(2) << 8)
            + (dot_k(3) << 12))                         # (out_cols, LANE)


def mont_mul(a: jnp.ndarray, b: jnp.ndarray, ts: TSpec) -> jnp.ndarray:
    """Montgomery product a*b*R^-1 mod m over (..., N, LANE) limbs.

    Same separated reduction as field.mont_mul. On the 2-D (in-kernel
    tile) path the two constant-operand products ride the nibble-Toeplitz
    MXU dot, and the two INNER carry resolutions are LAZY: T_lo and m
    keep 17-bit limbs from a single ripple pass (the 5-nibble planes
    tolerate them), so only the final sum resolves exactly. Bound: m_int
    < 2^256 * (1 + 2^-5), hence res < mod * (mod/2^256 + 1.04) < 1.3*mod
    for BN254's p, r ~ 0.19 * 2^256 — the single conditional subtract
    still canonicalizes. The batch-dim path (parity testing) stays fully
    exact schoolbook."""
    shape = jnp.broadcast_shapes(a.shape, b.shape)
    a = jnp.broadcast_to(a, shape)
    b = jnp.broadcast_to(b, shape)
    t_cols = _product_cols(a, b)
    if a.ndim == 2:
        t_lo = lazy_limbs(t_cols, N)          # == T mod 2^256, 17-bit lazy
        m = lazy_limbs(_const_product_cols(t_lo, ts.w_nprime), N)
        u_cols = _const_product_cols(m, ts.w_mod)
        s = carry_propagate(t_cols + u_cols, 2 * N + 1)
    else:
        # batch-dim path (parity tests): schoolbook against the limb consts.
        # m needs only the low N columns of t_lo * nprime.
        T = carry_propagate(t_cols, 2 * N + 1)
        t_lo = T[..., :N, :]
        np_b = jnp.broadcast_to(ts.nprime, t_lo.shape)
        m = carry_propagate(_product_cols(t_lo, np_b)[..., :N, :], N)
        u_cols = _product_cols(m, jnp.broadcast_to(ts.mod, m.shape))
        z1 = jnp.zeros(T.shape[:-2] + (1, T.shape[-1]), dtype=jnp.uint32)
        u_ext = jnp.concatenate([u_cols, z1], axis=-2)[..., :2 * N + 1, :]
        s = carry_propagate(T + u_ext, 2 * N + 1)
    res = s[..., N:, :]
    return _cond_sub_mod(res, ts)


def from_mont(a: jnp.ndarray, ts: TSpec) -> jnp.ndarray:
    one_col = jnp.ones(a.shape[:-2] + (1, a.shape[-1]), dtype=jnp.uint32)
    zeros = jnp.zeros(a.shape[:-2] + (N - 1, a.shape[-1]), dtype=jnp.uint32)
    return mont_mul(a, jnp.concatenate([one_col, zeros], axis=-2), ts)
