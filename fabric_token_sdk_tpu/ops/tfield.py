"""Transposed-layout field arithmetic for the Pallas (Mosaic) kernels.

Layout: arrays are (..., K, LANE) — the limb axis K sits on TPU *sublanes*
(second-minor) and LANE is a batch axis on the 128-wide *lane* dimension.
`ops/field.py` puts limbs minor, which wastes 7/8 of every VPU lane once the
ops run inside a Pallas kernel (a 16-limb minor axis occupies 16 of 128
lanes); transposing batch onto the lane axis keeps every vector op
full-width. Semantics are identical to ops/field.py — the parity suite
pins every op against it (tests/test_tfield.py).

Mosaic constraints shape the implementation (vs ops/field.py):
- no associative_scan (zero-size slices): carry lookahead is an unrolled
  Kogge-Stone;
- no u32<->float casts: byte/nibble planes detour through int32;
- no reshapes that mix tiled dims: shifts are concatenate-based along the
  sublane axis, products use an explicit shift-add schedule;
- no captured device constants: every modulus-dependent array rides in a
  `TSpec` the caller builds (outside a kernel from host constants, inside a
  kernel from refs passed to pallas_call).

The per-mont_mul schedule mirrors field.mont_mul's separated (SOS) form:
T = a*b (schoolbook shift-add columns), m = T_lo * N' (nibble-Toeplitz
matmul, MXU), S = (T + m*mod) >> 256 (same matmul trick), one conditional
subtract. Equivalent of the reference's gnark-crypto assembly field layer
(reference token/core/zkatdlog/nogh/v1/crypto/setup.go:14) re-planned for
the TPU memory hierarchy.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from . import limbs as L

N = L.NLIMBS
BITS = L.LIMB_BITS
MASK = L.LIMB_MASK  # python int: never captured as a device constant


class TSpec(NamedTuple):
    """Field constants in transposed layout (limb axis leading, lane=1).

    All arrays broadcast over the lane axis. `w_nprime`/`w_mod` are the
    nibble-Toeplitz matrices of field._nibble_toeplitz TRANSPOSED to
    (out_nibbles, 64) so the in-kernel contraction is a plain (M,K)x(K,LANE)
    matmul. mod_int is a python int (jit-static).
    """

    mod: jnp.ndarray       # (N, 1) uint32
    nprime: jnp.ndarray    # (N, 1) uint32  (-mod^-1 mod 2^256, low limbs)
    r1: jnp.ndarray        # (N, 1) uint32  (Montgomery 1)
    w_nprime: jnp.ndarray  # (4, N, 64)  int8: T_lo * N' mod 2^256
    w_mod: jnp.ndarray     # (4, 2N, 64) int8: m * mod, full 2N limbs
    mod_int: int


def _toeplitz_t(const_limbs: tuple, out_cols: int) -> np.ndarray:
    """(4, out_cols, 64) int8: W[k, l, i] = nibble (4l + k - i) of the
    constant — four per-nibble-position Toeplitz matrices so the in-kernel
    contraction is four plain matmuls with no strided slicing (Mosaic)."""
    from . import field

    w = field._nibble_toeplitz(const_limbs, out_cols)   # (64, 4*out_cols)
    return np.ascontiguousarray(
        np.stack([w[:, k::4].T for k in range(4)]))


def make_tspec(spec) -> TSpec:
    """Build a TSpec from an ops.field.FieldSpec (host-side constants)."""
    return TSpec(
        mod=jnp.asarray(np.array(spec.mod, dtype=np.uint32)[:, None]),
        nprime=jnp.asarray(np.array(spec.nprime, dtype=np.uint32)[:, None]),
        r1=jnp.asarray(np.array(spec.r1, dtype=np.uint32)[:, None]),
        w_nprime=jnp.asarray(_toeplitz_t(spec.nprime, N)),
        w_mod=jnp.asarray(_toeplitz_t(spec.mod, 2 * N)),
        mod_int=spec.mod_int,
    )


# --------------------------------------------------------------------------
# shifts along the limb (second-minor) axis — concatenate-based: Mosaic has
# no general pad, and slicing off the top + stacking zeros below is a plain
# sublane rotation it handles well.
# --------------------------------------------------------------------------

def _shift_down(x: jnp.ndarray, d: int, fill=0) -> jnp.ndarray:
    """x[..., i, :] -> x[..., i-d, :] (toward higher limb index); the d new
    bottom rows are `fill`."""
    if d == 0:
        return x
    k = x.shape[-2]
    if d >= k:
        return jnp.full_like(x, fill)
    pad = jnp.full(x.shape[:-2] + (d, x.shape[-1]), fill, dtype=x.dtype)
    return jnp.concatenate([pad, x[..., :k - d, :]], axis=-2)


def _top_row(x: jnp.ndarray) -> jnp.ndarray:
    """x[..., K-1, :] as (..., 1, LANE) (static slice; no int indexing)."""
    return x[..., x.shape[-2] - 1:, :]


# --------------------------------------------------------------------------
# carry machinery (mirrors field._carry_propagate / _lookahead / _sub_limbs)
# --------------------------------------------------------------------------

def _lookahead(g: jnp.ndarray, p: jnp.ndarray) -> jnp.ndarray:
    """Exclusive Kogge-Stone carry prefix along axis -2.

    g, p are uint32 0/1 masks (not bool: Mosaic cannot concatenate i1
    vectors, which the shifts need). Returns carry_in per limb as u32."""
    k = g.shape[-2]
    d = 1
    while d < k:
        g = g | (p & _shift_down(g, d, fill=0))
        p = p & _shift_down(p, d, fill=1)
        d *= 2
    return _shift_down(g, 1)


def carry_propagate(t: jnp.ndarray, out_limbs: int) -> jnp.ndarray:
    """Lazy column sums (< 2^32) -> canonical 16-bit limbs, axis -2."""
    k = t.shape[-2]
    if k < out_limbs:
        z = jnp.zeros(t.shape[:-2] + (out_limbs - k, t.shape[-1]),
                      dtype=t.dtype)
        t = jnp.concatenate([t, z], axis=-2)
    else:
        t = t[..., :out_limbs, :]
    v = (t & MASK) + _shift_down(t >> BITS, 1)
    v = (v & MASK) + _shift_down(v >> BITS, 1)
    g = v >> BITS                     # 0/1: v == 2^16 exactly
    p = (v == MASK).astype(jnp.uint32)
    return (v + _lookahead(g, p)) & MASK


def _sub_limbs(a: jnp.ndarray, b: jnp.ndarray):
    """a - b canonical; returns (diff, borrow_out (..., 1, LANE) u32)."""
    b = jnp.broadcast_to(b, a.shape)
    g = (a < b).astype(jnp.uint32)
    p = (a == b).astype(jnp.uint32)
    borrow_in = _lookahead(g, p)
    diff = (a + jnp.uint32(1 << BITS) - b - borrow_in) & MASK
    last = _top_row(g) | (_top_row(p) & _top_row(borrow_in))
    return diff, last


def _cond_sub_mod(res: jnp.ndarray, ts: TSpec) -> jnp.ndarray:
    """One conditional subtract of mod over N+1 limbs -> N limbs."""
    z = jnp.zeros(res.shape[:-2] + (1, 1), dtype=jnp.uint32)
    mod_ext = jnp.concatenate(
        [jnp.broadcast_to(ts.mod, res.shape[:-2] + (N, 1)), z], axis=-2)
    diff, borrow = _sub_limbs(res, mod_ext)
    keep = borrow != 0  # (..., 1, LANE): broadcasts over the limb axis
    return jnp.where(keep, res, diff)[..., :N, :]


def add(a: jnp.ndarray, b: jnp.ndarray, ts: TSpec) -> jnp.ndarray:
    s = carry_propagate(a + b, N + 1)
    return _cond_sub_mod(s, ts)


def sub(a: jnp.ndarray, b: jnp.ndarray, ts: TSpec) -> jnp.ndarray:
    diff, borrow = _sub_limbs(a, jnp.broadcast_to(b, a.shape))
    fixed = carry_propagate(diff + ts.mod, N)
    return jnp.where(borrow != 0, fixed, diff)


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    """(..., K, LANE) -> (..., 1, LANE) bool."""
    return jnp.all(a == 0, axis=-2, keepdims=True)


# --------------------------------------------------------------------------
# products
# --------------------------------------------------------------------------

def _product_cols(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Lazy column sums of a*b, both (..., N, LANE) canonical.

    Schoolbook shift-add: for each limb row i of `a`, one full-width vector
    multiply a_i * b and two shifted accumulations (lo/hi halves). Columns
    stay < 2^21 (32 half-terms of < 2^16). Returns (..., 2N, LANE).
    All VPU; the variable x variable product has no constant operand to
    Toeplitz-ize onto the MXU.
    """
    lanes = a.shape[-1]
    batch = a.shape[:-2]

    def placed(x, before: int):
        """x padded to 2N rows starting at `before` (no zero-size pieces —
        Mosaic rejects empty vectors)."""
        parts = []
        if before:
            parts.append(jnp.zeros(batch + (before, lanes),
                                   dtype=jnp.uint32))
        parts.append(x)
        after = 2 * N - before - x.shape[-2]
        if after:
            parts.append(jnp.zeros(batch + (after, lanes),
                                   dtype=jnp.uint32))
        return parts[0] if len(parts) == 1 else jnp.concatenate(parts,
                                                                axis=-2)

    cols = jnp.zeros(batch + (2 * N, lanes), dtype=jnp.uint32)
    for i in range(N):
        p = a[..., i:i + 1, :] * b          # (..., N, LANE) full products
        cols = cols + placed(p & MASK, i)
        cols = cols + placed(p >> BITS, i + 1)
    return cols


def _nibbles(a: jnp.ndarray) -> jnp.ndarray:
    """(..., N, LANE) u32 canonical -> (..., 4N, LANE) int8 nibbles,
    row 4i+k = (a[i] >> 4k) & 0xF (the field._nibble_toeplitz row order)."""
    parts = []
    for i in range(N):
        row = a[..., i:i + 1, :].astype(jnp.int32)
        for k in (0, 4, 8, 12):
            parts.append((row >> k) & 0xF)
    return jnp.concatenate(parts, axis=-2).astype(jnp.int8)


def _const_product_cols(a: jnp.ndarray, w_t: jnp.ndarray) -> jnp.ndarray:
    """Lazy columns of a * CONSTANT via the transposed nibble-Toeplitz dots.

    a: (N, LANE) canonical; w_t: (4, out_cols, 64) int8 (TSpec layout).
    Four (out_cols, 64) x (64, LANE) MXU matmuls in int32 accumulation
    (one per output nibble position), folded with shifts. No batch dims:
    the kernels call this on 2-D tiles.
    """
    nib = _nibbles(a)                                   # (64, LANE) i8

    def dot_k(k):
        c = jax.lax.dot_general(
            w_t[k], nib, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)           # (out, LANE)
        return c.astype(jnp.uint32)

    return (dot_k(0) + (dot_k(1) << 4) + (dot_k(2) << 8)
            + (dot_k(3) << 12))                         # (out_cols, LANE)


def mont_mul(a: jnp.ndarray, b: jnp.ndarray, ts: TSpec) -> jnp.ndarray:
    """Montgomery product a*b*R^-1 mod m over (..., N, LANE) limbs.

    Same separated reduction as field.mont_mul; the two constant-operand
    products ride the nibble-Toeplitz MXU dot when the input is 2-D
    (in-kernel tiles), else the schoolbook path (parity testing with
    batch dims)."""
    shape = jnp.broadcast_shapes(a.shape, b.shape)
    a = jnp.broadcast_to(a, shape)
    b = jnp.broadcast_to(b, shape)
    t_cols = _product_cols(a, b)
    T = carry_propagate(t_cols, 2 * N + 1)
    t_lo = T[..., :N, :]
    if a.ndim == 2:
        m = carry_propagate(_const_product_cols(t_lo, ts.w_nprime), N)
        u_cols = _const_product_cols(m, ts.w_mod)
    else:
        # batch-dim path (parity tests): schoolbook against the limb consts.
        # m needs only the low N columns of t_lo * nprime.
        np_b = jnp.broadcast_to(ts.nprime, t_lo.shape)
        m = carry_propagate(_product_cols(t_lo, np_b)[..., :N, :], N)
        u_cols = _product_cols(m, jnp.broadcast_to(ts.mod, m.shape))
    z1 = jnp.zeros(T.shape[:-2] + (1, T.shape[-1]), dtype=jnp.uint32)
    u_ext = jnp.concatenate([u_cols, z1], axis=-2)[..., :2 * N + 1, :]
    s = carry_propagate(T + u_ext, 2 * N + 1)
    res = s[..., N:, :]
    return _cond_sub_mod(res, ts)


def from_mont(a: jnp.ndarray, ts: TSpec) -> jnp.ndarray:
    one_col = jnp.ones(a.shape[:-2] + (1, a.shape[-1]), dtype=jnp.uint32)
    zeros = jnp.zeros(a.shape[:-2] + (N - 1, a.shape[-1]), dtype=jnp.uint32)
    return mont_mul(a, jnp.concatenate([one_col, zeros], axis=-2), ts)
