"""Transposed-layout field arithmetic for the Pallas (Mosaic) kernels.

Layout: arrays are (..., K, LANE) — the limb axis K sits on TPU *sublanes*
(second-minor) and LANE is a batch axis on the 128-wide *lane* dimension.
`ops/field.py` puts limbs minor, which wastes 7/8 of every VPU lane once the
ops run inside a Pallas kernel (a 16-limb minor axis occupies 16 of 128
lanes); transposing batch onto the lane axis keeps every vector op
full-width. Semantics are identical to ops/field.py — the parity suite
pins every op against it (tests/test_tfield.py).

Mosaic constraints shape the implementation (vs ops/field.py):
- no associative_scan (zero-size slices): carry lookahead is an unrolled
  Kogge-Stone;
- no u32<->float casts: byte/nibble planes detour through int32;
- no reshapes that mix tiled dims: shifts are concatenate-based along the
  sublane axis, products use an explicit shift-add schedule;
- no captured device constants: every modulus-dependent array rides in a
  `TSpec` the caller builds (outside a kernel from host constants, inside a
  kernel from refs passed to pallas_call).

The per-mont_mul schedule mirrors field.mont_mul's separated (SOS) form:
T = a*b (schoolbook shift-add columns), m = T_lo * N' (nibble-Toeplitz
matmul, MXU), S = (T + m*mod) >> 256 (same matmul trick), one conditional
subtract. Equivalent of the reference's gnark-crypto assembly field layer
(reference token/core/zkatdlog/nogh/v1/crypto/setup.go:14) re-planned for
the TPU memory hierarchy.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from . import limbs as L

N = L.NLIMBS
BITS = L.LIMB_BITS
MASK = L.LIMB_MASK  # python int: never captured as a device constant

#: Maximum limb magnitude a LAZY-form element may carry between ops.
#: add_lazy / sub_lazy keep limbs <= 2^16 (one bit above canonical);
#: anything that can exceed it must go through `normalize` first.
LAZY_LIMB_MAX = 1 << BITS

#: Maximum *value* (not limb) a lazy element may reach before it feeds
#: mont_mul: with one operand < 5p the reduction output stays < 2p, so a
#: single conditional subtract still canonicalizes (see mont_mul).
LAZY_VALUE_MAX_P = 5


class TSpec(NamedTuple):
    """Field constants in transposed layout (limb axis leading, lane=1).

    All arrays broadcast over the lane axis. `w_nprime`/`w_mod` are
    5-nibble-plane Toeplitz matrices (`_toeplitz_t`) accepting LAZY
    (17-bit) limb operands, so the in-kernel contraction is a plain
    (M,K)x(K,LANE) matmul. mod_int is a python int (jit-static).
    `sub2p` holds 2*mod with pre-distributed borrows (`_sub2p_limbs`) so
    sub_lazy needs no borrow lookahead at all.
    """

    mod: jnp.ndarray       # (N, 1) uint32
    nprime: jnp.ndarray    # (N, 1) uint32  (-mod^-1 mod 2^256, low limbs)
    r1: jnp.ndarray        # (N, 1) uint32  (Montgomery 1)
    w_nprime: jnp.ndarray  # (4, N, 5N)  int8: T_lo * N' mod 2^256
    w_mod: jnp.ndarray     # (4, 2N, 5N) int8: m * mod, full 2N limbs
    mod_int: int
    sub2p: jnp.ndarray = None  # (N, 1) uint32 pre-borrowed 2*mod limbs


def _sub2p_limbs(mod_int: int) -> np.ndarray:
    """Limbs of 2*mod rearranged so every h_i - b_i >= 0 for canonical b.

    h_0 = (2p)_0 + 2^16, h_i = (2p)_i - 1 + 2^16 for 0 < i < N-1,
    h_{N-1} = (2p)_{N-1} - 1: the +2^16 at limb i is paid for by the -1
    at limb i+1, so sum(h_i * 2^(16 i)) == 2p exactly, while each limb
    majorizes any canonical (< p) subtrahend limb. Requires p < 2^255
    (so 2p fits N limbs) and (2p)_i >= 1 for the interior limbs — both
    hold for BN254's p and r."""
    tp = [int(v) for v in L.int_to_limbs(2 * mod_int)]
    h = [tp[0] + (1 << BITS)]
    h += [tp[i] - 1 + (1 << BITS) for i in range(1, N - 1)]
    h += [tp[N - 1] - 1]
    assert all(v >= 0 for v in h) and h[N - 1] >= mod_int >> (BITS * (N - 1))
    return np.array(h, dtype=np.uint32)


def _toeplitz_t(const_limbs: tuple, out_cols: int) -> np.ndarray:
    """(4, out_cols, 5N) int8 Toeplitz planes for a LAZY-limb operand.

    Input row r = 5i + k is nibble k of limb i, at bit position 4(4i + k)
    — FIVE nibbles per limb so operands may carry up to 20-bit "lazy"
    limbs (the k = 4 row overlaps limb i+1's nibble 0; for canonical
    16-bit limbs it is simply zero). W[kk, l, r] = nibble (4l + kk - p(r))
    of the constant with p(r) = 4i + k, so the in-kernel contraction is
    four plain (out_cols, 5N) x (5N, LANE) matmuls recombined by shifts —
    column sums of a * const at 16-bit granularity, truncated past
    out_cols (drops only multiples of 2^(16*out_cols))."""
    c = []
    for limb in const_limbs:
        for shift in (0, 4, 8, 12):
            c.append((int(limb) >> shift) & 0xF)
    w = np.zeros((4, out_cols, 5 * N), dtype=np.int8)
    for r in range(5 * N):
        i, k = divmod(r, 5)
        p = 4 * i + k
        for l in range(out_cols):
            for kk in range(4):
                j = 4 * l + kk - p
                if 0 <= j < len(c):
                    w[kk, l, r] = c[j]
    return np.ascontiguousarray(w)


def make_tspec(spec) -> TSpec:
    """Build a TSpec from an ops.field.FieldSpec (host-side constants)."""
    return TSpec(
        mod=jnp.asarray(np.array(spec.mod, dtype=np.uint32)[:, None]),
        nprime=jnp.asarray(np.array(spec.nprime, dtype=np.uint32)[:, None]),
        r1=jnp.asarray(np.array(spec.r1, dtype=np.uint32)[:, None]),
        w_nprime=jnp.asarray(_toeplitz_t(spec.nprime, N)),
        w_mod=jnp.asarray(_toeplitz_t(spec.mod, 2 * N)),
        mod_int=spec.mod_int,
        sub2p=jnp.asarray(_sub2p_limbs(spec.mod_int)[:, None]),
    )


# --------------------------------------------------------------------------
# shifts along the limb (second-minor) axis — concatenate-based: Mosaic has
# no general pad, and slicing off the top + stacking zeros below is a plain
# sublane rotation it handles well.
# --------------------------------------------------------------------------

def _shift_down(x: jnp.ndarray, d: int, fill=0) -> jnp.ndarray:
    """x[..., i, :] -> x[..., i-d, :] (toward higher limb index); the d new
    bottom rows are `fill`."""
    if d == 0:
        return x
    k = x.shape[-2]
    if d >= k:
        return jnp.full_like(x, fill)
    pad = jnp.full(x.shape[:-2] + (d, x.shape[-1]), fill, dtype=x.dtype)
    return jnp.concatenate([pad, x[..., :k - d, :]], axis=-2)


def _top_row(x: jnp.ndarray) -> jnp.ndarray:
    """x[..., K-1, :] as (..., 1, LANE) (static slice; no int indexing)."""
    return x[..., x.shape[-2] - 1:, :]


# --------------------------------------------------------------------------
# carry machinery (mirrors field._carry_propagate / _lookahead / _sub_limbs)
# --------------------------------------------------------------------------

def _lookahead(g: jnp.ndarray, p: jnp.ndarray) -> jnp.ndarray:
    """Exclusive Kogge-Stone carry prefix along axis -2.

    g, p are uint32 0/1 masks (not bool: Mosaic cannot concatenate i1
    vectors, which the shifts need). Returns carry_in per limb as u32."""
    k = g.shape[-2]
    d = 1
    while d < k:
        g = g | (p & _shift_down(g, d, fill=0))
        p = p & _shift_down(p, d, fill=1)
        d *= 2
    return _shift_down(g, 1)


def _fit_limbs(t: jnp.ndarray, out_limbs: int) -> jnp.ndarray:
    k = t.shape[-2]
    if k < out_limbs:
        z = jnp.zeros(t.shape[:-2] + (out_limbs - k, t.shape[-1]),
                      dtype=t.dtype)
        return jnp.concatenate([t, z], axis=-2)
    return t[..., :out_limbs, :]


def carry_propagate(t: jnp.ndarray, out_limbs: int) -> jnp.ndarray:
    """Lazy column sums (< 2^32) -> canonical 16-bit limbs, axis -2."""
    t = _fit_limbs(t, out_limbs)
    v = (t & MASK) + _shift_down(t >> BITS, 1)
    v = (v & MASK) + _shift_down(v >> BITS, 1)
    g = v >> BITS                     # 0/1: v == 2^16 exactly
    p = (v == MASK).astype(jnp.uint32)
    return (v + _lookahead(g, p)) & MASK


def lazy_limbs(t: jnp.ndarray, out_limbs: int) -> jnp.ndarray:
    """Lazy column sums -> LAZY limbs: ONE ripple pass, no lookahead.

    Output limbs are bounded by 2^16 - 1 + (max column >> 16) — for the
    < 2^27 columns mont_mul feeds this, < 2^16 + 2^11 (17 bits), inside
    the 20-bit tolerance of the 5-nibble Toeplitz planes. Value is
    congruent mod 2^(16*out_limbs) (top carry dropped), which is all the
    Montgomery reduction needs from T_lo and m."""
    t = _fit_limbs(t, out_limbs)
    return (t & MASK) + _shift_down(t >> BITS, 1)


def _sub_limbs(a: jnp.ndarray, b: jnp.ndarray):
    """a - b canonical; returns (diff, borrow_out (..., 1, LANE) u32)."""
    b = jnp.broadcast_to(b, a.shape)
    g = (a < b).astype(jnp.uint32)
    p = (a == b).astype(jnp.uint32)
    borrow_in = _lookahead(g, p)
    diff = (a + jnp.uint32(1 << BITS) - b - borrow_in) & MASK
    last = _top_row(g) | (_top_row(p) & _top_row(borrow_in))
    return diff, last


def _cond_sub_mod(res: jnp.ndarray, ts: TSpec) -> jnp.ndarray:
    """One conditional subtract of mod over N+1 limbs -> N limbs."""
    z = jnp.zeros(res.shape[:-2] + (1, 1), dtype=jnp.uint32)
    mod_ext = jnp.concatenate(
        [jnp.broadcast_to(ts.mod, res.shape[:-2] + (N, 1)), z], axis=-2)
    diff, borrow = _sub_limbs(res, mod_ext)
    keep = borrow != 0  # (..., 1, LANE): broadcasts over the limb axis
    return jnp.where(keep, res, diff)[..., :N, :]


def add(a: jnp.ndarray, b: jnp.ndarray, ts: TSpec) -> jnp.ndarray:
    s = carry_propagate(a + b, N + 1)
    return _cond_sub_mod(s, ts)


def sub(a: jnp.ndarray, b: jnp.ndarray, ts: TSpec) -> jnp.ndarray:
    diff, borrow = _sub_limbs(a, jnp.broadcast_to(b, a.shape))
    fixed = carry_propagate(diff + ts.mod, N)
    return jnp.where(borrow != 0, fixed, diff)


# --------------------------------------------------------------------------
# lazy-carry arithmetic (Aranha et al., EUROCRYPT 2011 adapted to 16-bit
# limbs): between ops, limbs may sit anywhere <= LAZY_LIMB_MAX (2^16) and
# the represented VALUE anywhere < 5*mod. add_lazy/sub_lazy are single- or
# double-ripple passes — no Kogge-Stone lookahead, no conditional subtract,
# which is ~60% of the VPU work of an exact `add`. The chain must end at
# `normalize` (or flow through mont_mul, whose reduction canonicalizes)
# before the result is compared, hashed, or read back.
#
# Schedule rules (enforced statically by LimbBound + scripts/
# check_lazy_bounds.py):
#   R1  add_lazy takes at most ONE lazy operand (two 2^16 limbs would sum
#       past the stable 2^16 bound);
#   R2  sub_lazy's subtrahend must be CANONICAL (< mod);
#   R3  mont_mul takes at most ONE lazy operand, value < 5*mod;
#   R4  normalize accepts lazy values < 2*mod only.
#
# Round 7 extends the same rules across POINT-op chains: `madd` keeps its
# result's Y/Z lazy so the next madd in a multiple-table chain consumes
# them under R1/R3 (one normalize_point per table entry, not per step),
# and `add_zlazy` is a complete add whose accumulator Z stays lazy
# (< 2*mod) across a whole per-window fold chain — X/Y of the
# accumulator and the fresh operand stay canonical, so every interior
# mul still sees at most one lazy input. Both chains terminate in ONE
# normalize_point at the kernel's readback boundary (the lint above
# checks exactly that).
# --------------------------------------------------------------------------

def add_lazy(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a + b in lazy form: ONE ripple pass, no lookahead, no mod subtract.

    At most one operand may be lazy (limbs <= 2^16, the other canonical);
    the sum value must stay < 2^256. Output limbs <= 2^16 (the ripple
    carry is <= 1 on top of a <= 2^16 - 1 masked limb) and the output
    value is a + b exactly — nothing is reduced."""
    t = a + b
    return (t & MASK) + _shift_down(t >> BITS, 1)


def sub_lazy(a: jnp.ndarray, b: jnp.ndarray, ts: TSpec) -> jnp.ndarray:
    """a + 2*mod - b in lazy form: two ripple passes, no borrow chain.

    `a` may be lazy (limbs <= 2^16); `b` MUST be canonical (< mod) so the
    pre-borrowed 2p limbs (`ts.sub2p`) majorize it per-limb and the
    per-limb sums a_i + h_i - b_i never underflow. Output limbs <= 2^16;
    output value = a + 2*mod - b (exact, congruent to a - b)."""
    t = a + jnp.broadcast_to(ts.sub2p, a.shape) - jnp.broadcast_to(b, a.shape)
    # t < 3*2^16 per limb -> two ripple passes reach the stable 2^16 bound
    # (value < 2^256 keeps the top limb from ever generating a carry out).
    return lazy_limbs(lazy_limbs(t, N), N)


def normalize(a: jnp.ndarray, ts: TSpec) -> jnp.ndarray:
    """Lazy form (limbs <= 2^16, value < 2*mod) -> canonical (< mod)."""
    return _cond_sub_mod(carry_propagate(a, N + 1), ts)


class LimbBound:
    """Static bound tracker for lazy-carry schedules.

    Carries the worst-case per-limb magnitude and represented value
    (in units of mod) through a schedule of field ops, raising
    ValueError the moment a rule R1-R4 precondition breaks. Used by the
    carry-bound exhaustion test to prove the kernels' add-chains can
    never push a limb past LAZY_LIMB_MAX — and that the tracker itself
    rejects schedules that would."""

    def __init__(self, limb_max: int, value_p: float):
        self.limb_max = int(limb_max)
        self.value_p = float(value_p)   # value bound in multiples of mod

    @classmethod
    def canonical(cls) -> "LimbBound":
        return cls(MASK, 1.0)

    @property
    def is_canonical(self) -> bool:
        return self.limb_max <= MASK and self.value_p <= 1.0

    def _check_lazy(self, who: str) -> None:
        if self.limb_max > LAZY_LIMB_MAX:
            raise ValueError(
                f"{who}: operand limbs can reach {self.limb_max} > "
                f"LAZY_LIMB_MAX={LAZY_LIMB_MAX}; insert normalize()")

    def add_lazy(self, other: "LimbBound") -> "LimbBound":
        self._check_lazy("add_lazy")
        other._check_lazy("add_lazy")
        if not (self.is_canonical or other.is_canonical):
            raise ValueError(
                "add_lazy: both operands lazy (R1) — limbs could reach "
                f"{(self.limb_max & MASK) + 2} > LAZY_LIMB_MAX")
        # one ripple: masked limb <= MASK plus carry-in <= 1
        return self._with_value("add_lazy", self.value_p + other.value_p)

    def sub_lazy(self, other: "LimbBound") -> "LimbBound":
        self._check_lazy("sub_lazy")
        if not other.is_canonical:
            raise ValueError("sub_lazy: subtrahend must be canonical (R2)")
        return self._with_value("sub_lazy", self.value_p + 2.0)

    @staticmethod
    def _with_value(who: str, value_p: float) -> "LimbBound":
        # 2^256 / p for BN254: past this the (nonexistent) top carry-out
        # of a ripple pass would silently drop value.
        ceil_p = (1 << (BITS * N)) / L.P_INT
        if value_p >= ceil_p:
            raise ValueError(
                f"{who}: value bound {value_p}p overflows 2^256 "
                f"({ceil_p:.2f}p)")
        return LimbBound(LAZY_LIMB_MAX, value_p)

    def mont_mul(self, other: "LimbBound") -> "LimbBound":
        self._check_lazy("mont_mul")
        other._check_lazy("mont_mul")
        if not (self.is_canonical or other.is_canonical):
            raise ValueError("mont_mul: both operands lazy (R3)")
        if max(self.value_p, other.value_p) > LAZY_VALUE_MAX_P:
            raise ValueError(
                f"mont_mul: operand value {max(self.value_p, other.value_p)}"
                f"p exceeds {LAZY_VALUE_MAX_P}p (R3) — reduction output "
                "would pass 2p and one conditional subtract no longer "
                "canonicalizes")
        return LimbBound.canonical()

    def add(self, other: "LimbBound") -> "LimbBound":
        if not (self.is_canonical and other.is_canonical):
            raise ValueError("exact add requires canonical operands")
        return LimbBound.canonical()

    def sub(self, other: "LimbBound") -> "LimbBound":
        if not (self.is_canonical and other.is_canonical):
            raise ValueError("exact sub requires canonical operands")
        return LimbBound.canonical()

    def normalize(self) -> "LimbBound":
        self._check_lazy("normalize")
        if self.value_p > 2.0:
            raise ValueError(
                f"normalize: value {self.value_p}p > 2p (R4) — one "
                "conditional subtract cannot canonicalize")
        return LimbBound.canonical()


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    """(..., K, LANE) -> (..., 1, LANE) bool."""
    return jnp.all(a == 0, axis=-2, keepdims=True)


# --------------------------------------------------------------------------
# products
# --------------------------------------------------------------------------

def _product_cols(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Lazy column sums of a*b, both (..., N, LANE) canonical.

    Schoolbook shift-add: for each limb row i of `a`, one full-width vector
    multiply a_i * b and two shifted accumulations (lo/hi halves). Columns
    stay < 2^21 (32 half-terms of < 2^16). Returns (..., 2N, LANE).
    All VPU; the variable x variable product has no constant operand to
    Toeplitz-ize onto the MXU.
    """
    lanes = a.shape[-1]
    batch = a.shape[:-2]

    def placed(x, before: int):
        """x padded to 2N rows starting at `before` (no zero-size pieces —
        Mosaic rejects empty vectors)."""
        parts = []
        if before:
            parts.append(jnp.zeros(batch + (before, lanes),
                                   dtype=jnp.uint32))
        parts.append(x)
        after = 2 * N - before - x.shape[-2]
        if after:
            parts.append(jnp.zeros(batch + (after, lanes),
                                   dtype=jnp.uint32))
        return parts[0] if len(parts) == 1 else jnp.concatenate(parts,
                                                                axis=-2)

    cols = jnp.zeros(batch + (2 * N, lanes), dtype=jnp.uint32)
    for i in range(N):
        p = a[..., i:i + 1, :] * b          # (..., N, LANE) full products
        cols = cols + placed(p & MASK, i)
        cols = cols + placed(p >> BITS, i + 1)
    return cols


def _nibbles(a: jnp.ndarray) -> jnp.ndarray:
    """(..., N, LANE) u32 limbs (canonical OR lazy < 2^20) ->
    (..., 5N, LANE) int8 nibbles, row 5i+k = (a[i] >> 4k) & 0xF — the
    `_toeplitz_t` row order; the fifth nibble carries the lazy overflow
    (zero for canonical limbs)."""
    parts = []
    for i in range(N):
        row = a[..., i:i + 1, :].astype(jnp.int32)
        for k in (0, 4, 8, 12, 16):
            parts.append((row >> k) & 0xF)
    return jnp.concatenate(parts, axis=-2).astype(jnp.int8)


def _const_product_cols(a: jnp.ndarray, w_t: jnp.ndarray) -> jnp.ndarray:
    """Lazy columns of a * CONSTANT via the transposed nibble-Toeplitz dots.

    a: (N, LANE) canonical or lazy (< 2^20 limbs); w_t: (4, out_cols, 5N)
    int8 (TSpec layout). Four (out_cols, 5N) x (5N, LANE) MXU matmuls in
    int32 accumulation (one per output nibble position), folded with
    shifts. No batch dims: the kernels call this on 2-D tiles.
    """
    nib = _nibbles(a)                                   # (5N, LANE) i8

    def dot_k(k):
        c = jax.lax.dot_general(
            w_t[k], nib, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)           # (out, LANE)
        return c.astype(jnp.uint32)

    return (dot_k(0) + (dot_k(1) << 4) + (dot_k(2) << 8)
            + (dot_k(3) << 12))                         # (out_cols, LANE)


def mont_mul(a: jnp.ndarray, b: jnp.ndarray, ts: TSpec) -> jnp.ndarray:
    """Montgomery product a*b*R^-1 mod m over (..., N, LANE) limbs.

    Same separated reduction as field.mont_mul. On the 2-D (in-kernel
    tile) path the two constant-operand products ride the nibble-Toeplitz
    MXU dot, and the two INNER carry resolutions are LAZY: T_lo and m
    keep 17-bit limbs from a single ripple pass (the 5-nibble planes
    tolerate them), so only the final sum resolves exactly. Bound: m_int
    < 2^256 * (1 + 2^-5), hence res < mod * (mod/2^256 + 1.04) < 1.3*mod
    for BN254's p, r ~ 0.19 * 2^256 — the single conditional subtract
    still canonicalizes. The batch-dim path (parity testing) stays fully
    exact schoolbook.

    Lazy-carry contract (R3): at most ONE operand may be in lazy form
    (limbs <= LAZY_LIMB_MAX, the other canonical — two 2^16 limbs would
    overflow the uint32 partial products) and its VALUE must be < 5*mod:
    then T < 5*mod^2 and res < mod*(5*mod/2^256 + 1.04) < 2*mod for
    BN254, so the single conditional subtract still lands canonical.
    Output is always canonical — mont_mul is a normalization point."""
    shape = jnp.broadcast_shapes(a.shape, b.shape)
    a = jnp.broadcast_to(a, shape)
    b = jnp.broadcast_to(b, shape)
    t_cols = _product_cols(a, b)
    if a.ndim == 2:
        t_lo = lazy_limbs(t_cols, N)          # == T mod 2^256, 17-bit lazy
        m = lazy_limbs(_const_product_cols(t_lo, ts.w_nprime), N)
        u_cols = _const_product_cols(m, ts.w_mod)
        s = carry_propagate(t_cols + u_cols, 2 * N + 1)
    else:
        # batch-dim path (parity tests): schoolbook against the limb consts.
        # m needs only the low N columns of t_lo * nprime.
        T = carry_propagate(t_cols, 2 * N + 1)
        t_lo = T[..., :N, :]
        np_b = jnp.broadcast_to(ts.nprime, t_lo.shape)
        m = carry_propagate(_product_cols(t_lo, np_b)[..., :N, :], N)
        u_cols = _product_cols(m, jnp.broadcast_to(ts.mod, m.shape))
        z1 = jnp.zeros(T.shape[:-2] + (1, T.shape[-1]), dtype=jnp.uint32)
        u_ext = jnp.concatenate([u_cols, z1], axis=-2)[..., :2 * N + 1, :]
        s = carry_propagate(T + u_ext, 2 * N + 1)
    res = s[..., N:, :]
    return _cond_sub_mod(res, ts)


def from_mont(a: jnp.ndarray, ts: TSpec) -> jnp.ndarray:
    one_col = jnp.ones(a.shape[:-2] + (1, a.shape[-1]), dtype=jnp.uint32)
    zeros = jnp.zeros(a.shape[:-2] + (N - 1, a.shape[-1]), dtype=jnp.uint32)
    return mont_mul(a, jnp.concatenate([one_col, zeros], axis=-2), ts)
