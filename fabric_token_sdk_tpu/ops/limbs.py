"""Limb codecs and BN254 constants for the TPU kernels.

Representation: a 256-bit value is 16 little-endian limbs of 16 bits, stored
as uint32 so that (a) every 16x16-bit partial product fits exactly in one
uint32 lane and (b) lazy column accumulation of up to ~2^6 terms stays far
from the 2^32 wrap (SURVEY.md §7 item 1: "carry chains in int32 lanes").

Host <-> device conversion lives here (numpy only; no jax import so the
control plane can use it without touching a backend).
"""

from __future__ import annotations

import numpy as np

from ..crypto import bn254

LIMB_BITS = 16
LIMB_MASK = 0xFFFF
NLIMBS = 16  # 256 bits

# Base field Fp.
P_INT = bn254.P
# Scalar field Fr (group order).
R_INT = bn254.R

# Montgomery radix 2^256.
MONT_R = 1 << (LIMB_BITS * NLIMBS)


def _mont_consts(mod: int) -> tuple[int, int]:
    """(R mod m, R^2 mod m)."""
    return MONT_R % mod, (MONT_R * MONT_R) % mod


P_R1_INT, P_R2_INT = _mont_consts(P_INT)
R_R1_INT, R_R2_INT = _mont_consts(R_INT)


def int_to_limbs(x: int, nlimbs: int = NLIMBS) -> np.ndarray:
    """Little-endian 16-bit limb decomposition as uint32."""
    if x < 0:
        raise ValueError("negative value")
    out = np.empty(nlimbs, dtype=np.uint32)
    for i in range(nlimbs):
        out[i] = x & LIMB_MASK
        x >>= LIMB_BITS
    if x:
        raise ValueError("value does not fit in limbs")
    return out


def limbs_to_int(a: np.ndarray) -> int:
    """Inverse of int_to_limbs for a single limb vector (any leading dims=())."""
    x = 0
    arr = np.asarray(a, dtype=np.uint64)
    for i in range(arr.shape[-1] - 1, -1, -1):
        x = (x << LIMB_BITS) | int(arr[..., i])
    return x


def ints_to_limbs(xs, nlimbs: int = NLIMBS) -> np.ndarray:
    """Vector codec: list of ints -> (len, nlimbs) uint32."""
    return np.stack([int_to_limbs(x, nlimbs) for x in xs])


# Precomputed limb constants (numpy; jnp converts on use).
P_LIMBS = int_to_limbs(P_INT)
P_R2_LIMBS = int_to_limbs(P_R2_INT)
P_R1_LIMBS = int_to_limbs(P_R1_INT)
R_LIMBS = int_to_limbs(R_INT)
R_R2_LIMBS = int_to_limbs(R_R2_INT)
R_R1_LIMBS = int_to_limbs(R_R1_INT)
ZERO_LIMBS = np.zeros(NLIMBS, dtype=np.uint32)


def fp_to_mont_int(x: int) -> int:
    return (x * MONT_R) % P_INT


def fp_from_mont_int(x: int) -> int:
    return (x * pow(MONT_R, -1, P_INT)) % P_INT


def point_to_projective_limbs(p: bn254.G1) -> np.ndarray:
    """Affine host point -> (3, NLIMBS) Montgomery projective uint32 limbs.

    Identity encodes as (0 : 1 : 0) — the representation the complete
    RCB15 addition formulas in ops.ec expect.
    """
    if p.inf:
        return np.stack([ZERO_LIMBS, int_to_limbs(P_R1_INT), ZERO_LIMBS])
    return np.stack([
        int_to_limbs(fp_to_mont_int(p.x)),
        int_to_limbs(fp_to_mont_int(p.y)),
        int_to_limbs(P_R1_INT),  # Z = 1 in Montgomery form
    ])


def points_to_projective_limbs(points) -> np.ndarray:
    """(N, 3, NLIMBS) uint32 from a list of host points.

    Rides the native Fp Montgomery converter when available (one C call
    for the whole list); falls back to per-point Python bigint math."""
    from ..native import load_frmont

    native = load_frmont()
    if native is not None and points:
        blob = b"".join(
            (b"\x00" * 64 + b"\x01") if p.inf else
            (p.x.to_bytes(32, "little") + p.y.to_bytes(32, "little")
             + b"\x00")
            for p in points)
        out = np.frombuffer(native.points_to_limbs(blob), dtype="<u2")
        return out.astype(np.uint32).reshape(len(points), 3, NLIMBS)
    return np.stack([point_to_projective_limbs(p) for p in points])


def projective_limbs_to_point(arr: np.ndarray) -> bn254.G1:
    """Device (3, NLIMBS) Montgomery projective -> host affine point."""
    X = fp_from_mont_int(limbs_to_int(arr[0]))
    Y = fp_from_mont_int(limbs_to_int(arr[1]))
    Z = fp_from_mont_int(limbs_to_int(arr[2]))
    if Z == 0:
        return bn254.G1_IDENTITY
    zinv = pow(Z, P_INT - 2, P_INT)
    return bn254.G1(X * zinv % P_INT, Y * zinv % P_INT)


def scalars_to_limbs(scalars) -> np.ndarray:
    """Scalars mod r -> (N, NLIMBS) uint32 (plain integers, not Montgomery)."""
    return np.stack([int_to_limbs(s % R_INT) for s in scalars])


def packed_to_limbs(raw: bytes) -> np.ndarray:
    """Packed little-endian 32-byte scalars (the native _frmont wire form,
    already reduced mod r) -> (N, NLIMBS) uint32. Pure numpy reshape: the
    16-bit limb layout IS the byte layout."""
    arr = np.frombuffer(raw, dtype="<u2").reshape(-1, NLIMBS)
    return arr.astype(np.uint32)


def pack_scalars(scalars) -> bytes:
    """Ints mod r -> packed 32-byte little-endian (the _frmont wire form)."""
    return b"".join((s % R_INT).to_bytes(32, "little") for s in scalars)
