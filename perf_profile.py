"""Unified perf profiler for the batched verification pipeline, driven by
the obs span tracer.

Replaces the four one-off profile scripts (perf_block_profile,
perf_kernels_profile, perf_pass1_components, perf_stage2_profile) and
profile_verifier: every mode runs the PRODUCTION code paths under
obs.TRACER and reports from the span tree + pipeline records instead of
hand-inserted timers, so the profile and the shipped instrumentation can
never drift apart.

Modes (--mode):
  range    end-to-end BatchRangeVerifier.verify at --batch, pipelined;
           prints the per-phase split from the span tree and the
           BatchRecord (pad waste, bucket, cold/steady).
  block    ZKVerifier.verify_block at bench config-3 shapes; prints the
           zk.* child-span breakdown (deserialize / dispatch / adjust /
           range phases / sigma collect).
  barrier  barriered per-phase verify of ONE chunk: each device stage
           fenced with block_until_ready so stages sum honestly. The gap
           vs the pipelined wall time is the host/device overlap the
           pipeline buys. This is the only mode that injects fences —
           production spans never do.
  fold     standalone fixed-base fold micro-bench: times the fused
           Pallas kernels (fb_fold_t gather, fb_msm_t MSM) outside the
           verify pipeline, and prints the XLA cost-analysis FLOP
           comparison of the projective complete-add fold vs the
           mixed-affine madd fold. On CPU the kernels run in Pallas
           interpret mode (functionally exact, wall time not
           representative), so the FLOP ratio is the headline number.
  pipeline single-program chunk pipeline audit: counts every device
           upload + program dispatch the production verify() issues
           (per chunk, via the range_verifier dispatch hook), reports
           the host/device overlap from the span phases, and prints the
           XLA cost-analysis delta of the eager one-hot Horner walk vs
           the round-7 lazy-carry mixed-affine walk. On the merged
           pipeline a chunk must cost exactly 1 packed upload + 1 fused
           dispatch; FTS_NO_FUSED_PIPELINE=1 re-runs the audit on the
           legacy split pipeline for the before/after.
  mesh     multi-chip scaling audit: the sharded verify() over a
           (dp, tp) mesh must keep the SAME per-chunk contract (1 packed
           upload + 1 fused sharded dispatch + 1 finalize per verify),
           produce verdicts bit-identical to the single-device verifier,
           and reports the single-vs-mesh wall ratio. On CPU, 8 virtual
           host devices are forced automatically (JAX_PLATFORMS=cpu).
  prove    device-prover audit: DeviceRangeProver.prove() must cost
           exactly 1 packed witness upload + 1 fused synthesis dispatch
           per chunk (asserted via the same dispatch hook); prints the
           XLA cost analysis of the prove chunk program and device
           proofs/s vs the host prover's measured wall-clock.
  ingest   columnar front-door audit (crypto-free, StubZK): decodes a
           >=256-row SUBMIT_BATCH payload into numpy views and asserts
           ZERO pickle calls, then drives the real TCP RpcServer and
           asserts one N-row frame costs exactly ONE admission decision
           + ONE WAL append (+ ONE resolve); reports decode ns/row for
           the columnar layout vs the legacy per-row pickled bodies.
  egress   columnar RESULT_BATCH egress audit (crypto-free, StubZK):
           encodes >=256 verdict rows and asserts ZERO pickle calls in
           the columnar encode, then drives the real TCP front door at
           protocol v4 and asserts an N-row request returns as exactly
           ONE RESULT_BATCH frame via ONE coalesced wakeup, with the
           per-cycle pickle cost O(1) (credit frames), never O(rows);
           reports encode ns/row columnar vs per-row pickled replies.

Output: human-readable table on stderr, one JSON document on stdout.
--trace <path> additionally writes the span tree as Chrome trace-event
JSON (chrome://tracing, Perfetto). --xprof <dir> couples root spans to
jax.profiler.start_trace for device-level xprof timelines.

Run on the chip: python perf_profile.py --mode range --batch 1024
CPU smoke: JAX_PLATFORMS=cpu python perf_profile.py --batch 8 --reps 1
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _tree_lines(span, depth=0, out=None):
    out = out if out is not None else []
    out.append("  " * depth
               + f"{span.name:<28s} {span.duration * 1e3:9.2f} ms  "
               + " ".join(f"{k}={v}" for k, v in span.attributes.items()))
    for ch in span.children:
        _tree_lines(ch, depth + 1, out)
    return out


def _report(tracer, root_name: str, records, wall_s: float, n_rows: int,
            trace_path: str | None) -> dict:
    from fabric_token_sdk_tpu.obs import spans_to_chrome_trace

    root = tracer.last_root(root_name)
    doc: dict = {"wall_s": round(wall_s, 4),
                 "rows_per_sec": round(n_rows / wall_s, 2) if wall_s else 0}
    if root is not None:
        print("\n".join(_tree_lines(root)), file=sys.stderr)
        doc["span_tree"] = {
            s.name: round((s.duration or 0) * 1e3, 3) for s in root.walk()}
    rec = records.last()
    if rec is not None:
        doc["last_batch"] = rec.to_dict()
    doc["pipeline"] = records.summary()
    if trace_path and tracer.roots:
        from fabric_token_sdk_tpu.obs import write_chrome_trace

        write_chrome_trace(trace_path, tracer.roots)
        print(f"chrome trace written to {trace_path}", file=sys.stderr)
    return doc


def _load_corpus(batch: int):
    import bench

    pp, proofs, coms = bench._load()
    reps = (batch + len(proofs) - 1) // len(proofs)
    return pp, (proofs * reps)[:batch], (coms * reps)[:batch]


def _mode_range(args, tracer, records) -> dict:
    from fabric_token_sdk_tpu.models.range_verifier import BatchRangeVerifier

    pp, proofs, coms = _load_corpus(args.batch)
    verifier = BatchRangeVerifier(pp)
    print("warm-up verify (compiles)", file=sys.stderr)
    assert verifier.verify(proofs, coms).all()
    t0 = time.perf_counter()
    for _ in range(args.reps):
        assert verifier.verify(proofs, coms).all()
    wall = time.perf_counter() - t0
    return _report(tracer, "range_verify", records, wall,
                   args.reps * args.batch, args.trace)


def _mode_block(args, tracer, records) -> dict:
    import pickle

    import bench
    from fabric_token_sdk_tpu.core.zkatdlog.verifier import ZKVerifier
    from fabric_token_sdk_tpu.crypto import setup

    pp = setup.PublicParams.deserialize(
        (bench.BENCH_DIR / "pp.json").read_bytes())
    blob = pickle.loads(
        (bench.BENCH_DIR / f"block_{bench.BIT_LENGTH}.pkl").read_bytes())
    base_t, base_i = blob["transfers"], blob["issues"]
    n = max(1, args.batch // 4)
    slice_t = (base_t * (n // len(base_t) + 1))[:n]
    slice_i = (base_i * (n // len(base_i) + 1))[:n]
    zk = ZKVerifier(pp, device=True)
    print("warm-up block (compiles)", file=sys.stderr)
    t_ok, i_ok = zk.verify_block(slice_t, slice_i)
    assert t_ok.all() and i_ok.all()
    t0 = time.perf_counter()
    for _ in range(args.reps):
        t_ok, i_ok = zk.verify_block(slice_t, slice_i)
        assert t_ok.all() and i_ok.all()
    wall = time.perf_counter() - t0
    # 2 range proofs per action
    return _report(tracer, "zk.verify_block", records, wall,
                   args.reps * 2 * (len(slice_t) + len(slice_i)), args.trace)


def _mode_barrier(args, tracer, records) -> dict:
    """One chunk with every device stage fenced: honest per-stage sums.

    Uses the production verify() but with the batch capped to one chunk
    and jax.block_until_ready forced between the span-visible phases via
    a barriered wrapper around the pass-1 dispatch.
    """
    import jax

    from fabric_token_sdk_tpu.models import range_verifier as rv

    batch = min(args.batch, rv._CHUNK_ROWS)
    pp, proofs, coms = _load_corpus(batch)
    verifier = rv.BatchRangeVerifier(pp)
    print("warm-up verify (compiles)", file=sys.stderr)
    assert verifier.verify(proofs, coms).all()

    dispatch = verifier._dispatch_pass1

    def fenced_dispatch(pfs, cms, ch, prev=None):
        st = dispatch(pfs, cms, ch, prev)    # a rv._ChunkStage
        jax.block_until_ready(
            [x for x in (st.digests_dev, st.rdig_dev, st.pts_dev,
                         st.partial) if hasattr(x, "dtype")])
        return st

    verifier._dispatch_pass1 = fenced_dispatch
    try:
        t0 = time.perf_counter()
        for _ in range(args.reps):
            assert verifier.verify(proofs, coms).all()
        wall = time.perf_counter() - t0
    finally:
        verifier._dispatch_pass1 = dispatch
    doc = _report(tracer, "range_verify", records, wall,
                  args.reps * batch, args.trace)
    doc["note"] = ("barriered: pass-1 fenced before host stage-2; "
                   "phase sums exceed the pipelined wall time by the "
                   "host/device overlap")
    return doc


def _mode_fold(args, tracer, records) -> dict:
    """Fixed-base fold kernels standalone (no corpus, no verifier).

    Two artifacts:
      1. Lower-only XLA cost analysis of the per-term fold at identical
         gather shapes — projective complete-add path (96 planes, 14-mul
         adds) vs mixed-affine madd path (64 planes, 13-mul madds, lazy
         interior). This is backend-independent evidence that the madd
         rework removed work per fold term.
      2. Wall time of the fused Pallas kernels fb_fold_t (via
         fixed_base_gather_fused) and fb_msm_t (fixed_base_msm_fused):
         compiled Mosaic on TPU; interpret mode on CPU (bit-exact but
         orders of magnitude slower — sizes are capped there).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from fabric_token_sdk_tpu.crypto import bn254
    from fabric_token_sdk_tpu.ops import ec, limbs, pallas_fb

    cpu = jax.default_backend() != "tpu"
    T = 2 if cpu else 8
    B = min(args.batch, 2) if cpu else args.batch
    rng = np.random.default_rng(7)
    g = bn254.G1_GENERATOR
    pts = jnp.asarray(limbs.points_to_projective_limbs(
        [g * int(rng.integers(1, 2 ** 31)) for _ in range(T)]))
    sc = jnp.asarray(np.stack([np.stack([
        limbs.int_to_limbs(
            int.from_bytes(rng.bytes(32), "little") % bn254.R)
        for _ in range(T)]) for _ in range(B)]))

    pd = ec.plane_dtype()
    proj_sds = jax.ShapeDtypeStruct((T, 32, 256, 96), pd)
    aff_sds = jax.ShapeDtypeStruct((T, 32, 256, 64), pd)
    sc_sds = jax.ShapeDtypeStruct((B, T, limbs.NLIMBS), jnp.uint32)

    def _flops(fn, *sds):
        try:
            c = jax.jit(fn).lower(*sds).cost_analysis()
            if isinstance(c, (list, tuple)):
                c = c[0] if c else None
            return (c or {}).get("flops")
        except Exception:
            return None

    f_proj = _flops(ec.fixed_base_gather, proj_sds, sc_sds)
    f_mixed = _flops(ec.fixed_base_gather_mixed, aff_sds, sc_sds)
    ratio = (round(f_mixed / f_proj, 4) if f_proj and f_mixed else None)
    print(f"fold cost analysis (B={B}, T={T}): projective "
          f"{f_proj} flops, mixed-affine {f_mixed} flops "
          f"(ratio {ratio})", file=sys.stderr)
    doc: dict = {"terms": T, "rows": B, "interpret": cpu,
                 "cost_analysis": {
                     "projective_gather_flops": f_proj,
                     "mixed_gather_flops": f_mixed,
                     "mixed_over_projective": ratio}}

    print("building affine tables + first call (compiles)",
          file=sys.stderr)
    planes_t = pallas_fb.transpose_planes(ec.fixed_base_affine_planes(pts))
    reps = 1 if cpu else max(1, args.reps)
    t0 = time.perf_counter()
    out = pallas_fb.fixed_base_gather_fused(planes_t, sc, interpret=cpu)
    jax.block_until_ready(out)
    first_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(reps):
        out = pallas_fb.fixed_base_gather_fused(planes_t, sc,
                                                interpret=cpu)
    jax.block_until_ready(out)
    fold_s = (time.perf_counter() - t0) / reps
    t0 = time.perf_counter()
    for _ in range(reps):
        msm = pallas_fb.fixed_base_msm_fused(planes_t, sc, interpret=cpu)
    jax.block_until_ready(msm)
    msm_s = (time.perf_counter() - t0) / reps
    doc.update({"fb_fold_s": round(fold_s, 4),
                "fb_msm_s": round(msm_s, 4),
                "first_call_s": round(first_s, 4),
                "fold_terms_per_s":
                    round(B * T / fold_s, 2) if fold_s else 0})
    print(f"fb_fold_t {fold_s * 1e3:.1f} ms  fb_msm_t {msm_s * 1e3:.1f} "
          f"ms  (first call {first_s:.1f} s, interpret={cpu})",
          file=sys.stderr)
    return doc


def _mode_pipeline(args, tracer, records) -> dict:
    """Single-program chunk pipeline audit (round 7).

    Three artifacts:
      1. Dispatch/upload counts per chunk from the production verify(),
         observed via the range_verifier dispatch hook. The merged
         pipeline's contract — exactly ONE packed upload + ONE fused
         device program per chunk (plus one cross-chunk finalize fold
         per verify) — is asserted here, not just reported.
      2. Host/device overlap: production spans charge async dispatch +
         challenge hashing to host_prep and measure device_execute only
         at the blocking syncs, so the residual device-wait fraction is
         the pipeline's honesty metric (lower = more hidden).
      3. Lower-only XLA cost analysis of the var-MSM interiors at
         identical shapes: the eager one-hot Horner walk vs the
         lazy-carry mixed-affine walk (table chain + madd digits), and
         the whole kernel msm_windowed vs msm_var_mixed. Backend
         independent, mirrors --mode fold.
    """
    import collections

    import jax
    import jax.numpy as jnp

    from fabric_token_sdk_tpu.models import range_verifier as rv
    from fabric_token_sdk_tpu.ops import ec, limbs

    pp, proofs, coms = _load_corpus(args.batch)
    verifier = rv.BatchRangeVerifier(pp)
    print("warm-up verify (compiles)", file=sys.stderr)
    assert verifier.verify(proofs, coms).all()

    counts: collections.Counter = collections.Counter()
    rv._DISPATCH_HOOK = lambda kind: counts.update((kind,))
    try:
        t0 = time.perf_counter()
        for _ in range(args.reps):
            assert verifier.verify(proofs, coms).all()
        wall = time.perf_counter() - t0
    finally:
        rv._DISPATCH_HOOK = None

    doc = _report(tracer, "range_verify", records, wall,
                  args.reps * args.batch, args.trace)
    rec = records.last()
    n_chunks = max(1, rec.chunks if rec is not None else 1) * args.reps
    per_chunk = {k: counts[k] / n_chunks
                 for k in ("chunk_upload", "chunk_dispatch")}
    fused_on = rv._fused_pipeline_enabled()
    doc["dispatch_counts"] = dict(counts)
    doc["chunks_counted"] = n_chunks
    doc["per_chunk"] = per_chunk
    doc["fused_pipeline"] = fused_on
    doc["finalize_per_verify"] = counts["finalize"] / args.reps
    print(f"{n_chunks} chunks: {per_chunk['chunk_upload']:.2f} uploads + "
          f"{per_chunk['chunk_dispatch']:.2f} dispatches per chunk, "
          f"{counts['finalize']} finalize folds over {args.reps} verifies "
          f"(fused_pipeline={fused_on})", file=sys.stderr)
    if fused_on:
        assert per_chunk["chunk_upload"] == 1.0, per_chunk
        assert per_chunk["chunk_dispatch"] == 1.0, per_chunk
        # finalize is folded ACROSS chunks: exactly one O(1) total-fold
        # dispatch per verify, however many chunks the batch split into
        assert counts["finalize"] == args.reps, counts

    if rec is not None:
        tot = rec.total_s or 1.0
        doc["overlap"] = {
            "host_prep_s": round(rec.host_prep_s, 4),
            "device_wait_s": round(rec.device_execute_s, 4),
            "device_wait_fraction": round(rec.device_execute_s / tot, 4)}
        print(f"overlap: host_prep {rec.host_prep_s * 1e3:.1f} ms, "
              f"residual device wait {rec.device_execute_s * 1e3:.1f} ms "
              f"({100 * rec.device_execute_s / tot:.1f}% of wall)",
              file=sys.stderr)

    V = 512
    pd = ec.plane_dtype()
    planes = jax.ShapeDtypeStruct((V, 16, 96), pd)
    digits = jax.ShapeDtypeStruct((V, 64), jnp.int32)
    pts = jax.ShapeDtypeStruct((V, 3, limbs.NLIMBS), jnp.uint32)
    sc = jax.ShapeDtypeStruct((V, limbs.NLIMBS), jnp.uint32)

    def _flops(fn, *sds):
        try:
            c = jax.jit(fn).lower(*sds).cost_analysis()
            if isinstance(c, (list, tuple)):
                c = c[0] if c else None
            return (c or {}).get("flops")
        except Exception:
            return None

    w_old = _flops(ec._windowed_walk, planes, digits)
    w_new = _flops(ec._windowed_walk_lazy, planes, digits)
    k_old = _flops(ec.msm_windowed, pts, sc)
    k_new = _flops(ec.msm_var_mixed, pts, sc)
    w_ratio = round(w_old / w_new, 2) if w_old and w_new else None
    k_ratio = round(k_old / k_new, 2) if k_old and k_new else None
    doc["cost_analysis"] = {
        "walk_eager_flops": w_old, "walk_lazy_flops": w_new,
        "walk_eager_over_lazy": w_ratio,
        "kernel_windowed_flops": k_old, "kernel_mixed_flops": k_new,
        "kernel_windowed_over_mixed": k_ratio}
    print(f"var-MSM cost analysis (V={V}): Horner walk eager {w_old} "
          f"flops vs lazy {w_new} flops ({w_ratio}x); whole kernel "
          f"windowed {k_old} vs mixed {k_new} ({k_ratio}x)",
          file=sys.stderr)
    return doc


def _mode_prove(args, tracer, records) -> dict:
    """Device prover audit: cost analysis + dispatch contract (prover/).

    Three artifacts:
      1. Dispatch/upload counts from the production DeviceRangeProver
         .prove(), via the same range_verifier dispatch hook the verify
         pipeline audits ride: a prove chunk must cost exactly ONE
         packed witness upload + ONE fused synthesis dispatch.
      2. Lower-only XLA cost analysis of the fused prove chunk program
         (kernel_cost publishes it under profile_bucket_* as kind
         "prove_chunk").
      3. Device proofs/s vs the host prover's measured wall-clock on
         the same witnesses — the prover twin of the verify headline.
    """
    import collections
    import random

    from fabric_token_sdk_tpu.crypto import bn254, rp, setup
    from fabric_token_sdk_tpu.harness.corpus import _seeded_draws
    from fabric_token_sdk_tpu.models import range_verifier as rv
    from fabric_token_sdk_tpu.prover import DeviceRangeProver

    import bench

    pp = setup.PublicParams.deserialize(
        (bench.BENCH_DIR / "pp.json").read_bytes())
    rpp = pp.range_proof_params
    bits = rpp.bit_length
    rng = random.Random(17)
    values = [rng.randrange(1 << bits) for _ in range(args.batch)]
    bfs = [rng.randrange(1, bn254.R) for _ in range(args.batch)]
    draws = [_seeded_draws(rng, bits) for _ in range(args.batch)]

    prover = DeviceRangeProver(pp)
    chunk = prover._chunk_rows_for(args.batch)
    print(f"warm-up prove chunk ({chunk} rows, compiles)", file=sys.stderr)
    prover.prove(values[:chunk], bfs[:chunk], draws=draws[:chunk])

    counts: collections.Counter = collections.Counter()
    rv._DISPATCH_HOOK = lambda kind: counts.update((kind,))
    try:
        t0 = time.perf_counter()
        for _ in range(args.reps):
            proofs, coms = prover.prove(values, bfs, draws=draws)
        wall = time.perf_counter() - t0
    finally:
        rv._DISPATCH_HOOK = None

    doc = _report(tracer, "prover.synthesize", records, wall,
                  args.reps * args.batch, args.trace)
    n_chunks = args.reps * -(-args.batch // chunk)
    per_chunk = {k: counts[k] / n_chunks
                 for k in ("prove_chunk_upload", "prove_chunk_dispatch")}
    doc["dispatch_counts"] = dict(counts)
    doc["chunks_counted"] = n_chunks
    doc["per_chunk"] = per_chunk
    print(f"{n_chunks} prove chunks: "
          f"{per_chunk['prove_chunk_upload']:.2f} uploads + "
          f"{per_chunk['prove_chunk_dispatch']:.2f} dispatches per chunk",
          file=sys.stderr)
    # the packed-witness contract: ONE upload + ONE fused program per
    # chunk, same bar as the verify pipeline
    assert per_chunk["prove_chunk_upload"] == 1.0, per_chunk
    assert per_chunk["prove_chunk_dispatch"] == 1.0, per_chunk

    doc["cost_analysis"] = prover.kernel_cost(rows=chunk)

    cg = pp.pedersen_generators[1:3]
    t0 = time.perf_counter()
    rp.range_prove(coms[0], values[0], cg, bfs[0], rpp.left_generators,
                   rpp.right_generators, rpp.P, rpp.Q,
                   rpp.number_of_rounds, bits, draws=draws[0])
    host_s = time.perf_counter() - t0
    dev_s = wall / (args.reps * args.batch)
    doc["host_prover_s_per_proof"] = round(host_s, 4)
    doc["device_s_per_proof"] = round(dev_s, 6)
    doc["device_over_host_speedup"] = round(host_s / dev_s, 2) if dev_s \
        else None
    print(f"host {host_s:.2f} s/proof vs device {dev_s * 1e3:.2f} "
          f"ms/proof ({host_s / dev_s:.0f}x)", file=sys.stderr)
    return doc


def _mode_mesh(args, tracer, records) -> dict:
    """Multi-chip scaling audit: the fused-chunk dispatch contract under
    a (dp, tp) mesh (round 8).

    Three artifacts:
      1. Dispatch/upload counts per chunk from the production sharded
         verify(): the mesh path must keep the merged-pipeline contract
         — exactly ONE packed upload + ONE fused sharded program per
         chunk plus ONE O(1) finalize per verify — i.e. sharding must
         not reintroduce the per-stage dispatch ladder it replaced.
      2. Verdict parity: the sharded verifier's verdict vector must be
         bit-identical to the single-device verifier on the same corpus.
      3. A scaling estimate: single-device wall vs mesh wall at the same
         batch (honest on a real multi-chip; on CPU the 8 'devices' are
         virtual threads on the same cores, so the ratio only checks the
         mesh path is not pathologically slower).

    tp defaults to 2 (FTS_MESH_TP overrides; falls back to 1 when it
    does not divide the device count).
    """
    import collections

    import jax

    from fabric_token_sdk_tpu.models import range_verifier as rv
    from fabric_token_sdk_tpu.parallel import make_mesh

    n_dev = len(jax.devices())
    if n_dev < 2:
        raise SystemExit(
            "--mode mesh needs more than one device (on CPU set XLA_FLAGS="
            "--xla_force_host_platform_device_count=8, or let JAX_PLATFORMS"
            "=cpu do it here)")
    tp = int(os.environ.get("FTS_MESH_TP", "2"))
    if n_dev % tp:
        tp = 1
    mesh = make_mesh(n_dev, dp=n_dev // tp, tp=tp)
    pp, proofs, coms = _load_corpus(args.batch)
    single = rv.BatchRangeVerifier(pp)
    sharded = rv.BatchRangeVerifier(pp, mesh=mesh)
    print(f"mesh {n_dev} devices (dp={n_dev // tp}, tp={tp}); "
          "warm-up single-device verify (compiles)", file=sys.stderr)
    base = single.verify(proofs, coms)
    assert base.all()
    print("warm-up sharded verify (compiles)", file=sys.stderr)
    out = sharded.verify(proofs, coms)
    assert (out == base).all(), \
        "sharded verdicts diverge from the single-device path"

    counts: collections.Counter = collections.Counter()
    rv._DISPATCH_HOOK = lambda kind: counts.update((kind,))
    try:
        t0 = time.perf_counter()
        for _ in range(args.reps):
            assert sharded.verify(proofs, coms).all()
        mesh_wall = time.perf_counter() - t0
    finally:
        rv._DISPATCH_HOOK = None
    t0 = time.perf_counter()
    for _ in range(args.reps):
        assert single.verify(proofs, coms).all()
    single_wall = time.perf_counter() - t0

    doc = _report(tracer, "range_verify", records, mesh_wall,
                  args.reps * args.batch, args.trace)
    rec = records.last()
    n_chunks = max(1, rec.chunks if rec is not None else 1) * args.reps
    per_chunk = {k: counts[k] / n_chunks
                 for k in ("chunk_upload", "chunk_dispatch")}
    fused_on = rv._fused_pipeline_enabled()
    doc.update({
        "devices": n_dev, "dp": n_dev // tp, "tp": tp,
        "fused_pipeline": fused_on,
        "dispatch_counts": dict(counts),
        "chunks_counted": n_chunks,
        "per_chunk": per_chunk,
        "finalize_per_verify": counts["finalize"] / args.reps,
        "single_device_wall_s": round(single_wall, 4),
        "mesh_wall_s": round(mesh_wall, 4),
        "mesh_speedup": (round(single_wall / mesh_wall, 3)
                         if mesh_wall else None)})
    print(f"{n_chunks} sharded chunks: "
          f"{per_chunk['chunk_upload']:.2f} uploads + "
          f"{per_chunk['chunk_dispatch']:.2f} fused dispatches per chunk, "
          f"{counts['finalize']} finalize folds over {args.reps} verifies; "
          f"single {single_wall:.2f}s vs mesh {mesh_wall:.2f}s "
          f"(x{single_wall / mesh_wall:.2f} over {n_dev} devices)",
          file=sys.stderr)
    if fused_on:
        assert per_chunk["chunk_upload"] == 1.0, per_chunk
        assert per_chunk["chunk_dispatch"] == 1.0, per_chunk
        assert counts["finalize"] == args.reps, counts
    return doc


def _mode_ingest(args, tracer, records) -> dict:
    """Columnar front-door ingest audit (round 12). Crypto-free.

    Three artifacts:
      1. Decode cost per row: one >=256-row columnar SUBMIT_BATCH
         payload decoded into numpy views over the frame buffer vs the
         legacy per-row pickled SUBMIT bodies — with a pickle.loads
         counter proving the columnar decode performs ZERO pickle calls
         (and hence zero per-row Python object graphs).
      2. The single-decision contract, asserted on the production
         service behind the real TCP server: one N-row frame costs
         exactly ONE admission decision and ONE WAL admit append
         (plus ONE resolve append once every row completes), however
         many rows the frame carries.
      3. Ingested proofs/s through the live front door (RpcServer +
         RpcClient riding columnar frames, StubZK backend).
    """
    import asyncio
    import pickle
    import tempfile
    import threading

    from fabric_token_sdk_tpu.serve import (LANE_BULK, RpcClient,
                                            RpcServer, ServeConfig,
                                            StubZK, VerificationService)
    from fabric_token_sdk_tpu.serve.columnar import (FMT_OPAQUE,
                                                     decode_submit_batch,
                                                     encode_submit_batch,
                                                     materialize_rows,
                                                     opaque_cells)
    from fabric_token_sdk_tpu.serve.wal import WriteAheadLog

    n = max(256, args.batch)
    truth = [i % 7 != 0 for i in range(n)]
    payload = encode_submit_batch(
        fmt=FMT_OPAQUE, lane=LANE_BULK, req_id_base=1,
        deadline=time.time() + 60.0, proof_cells=opaque_cells(truth))

    pickle_calls = {"n": 0}
    real_loads = pickle.loads

    def counting_loads(*a, **kw):
        pickle_calls["n"] += 1
        return real_loads(*a, **kw)

    iters = max(20, args.reps)
    pickle.loads = counting_loads
    try:
        t0 = time.perf_counter()
        for _ in range(iters):
            batch = decode_submit_batch(payload)
        col_s = (time.perf_counter() - t0) / iters
    finally:
        pickle.loads = real_loads
    assert pickle_calls["n"] == 0, \
        "columnar decode touched pickle — the zero-copy contract broke"
    proofs, _ = materialize_rows(batch)
    assert proofs == truth

    # the layout this replaces: one pickled dict per row
    legacy_rows = [pickle.dumps(
        {"req_id": i, "kind": "range", "lane": LANE_BULK, "rows": 1,
         "deadline_s": 60.0, "payload": ([truth[i]], [None])},
        protocol=pickle.HIGHEST_PROTOCOL) for i in range(n)]
    t0 = time.perf_counter()
    for _ in range(iters):
        for body in legacy_rows:
            real_loads(body)
    pkl_s = (time.perf_counter() - t0) / iters

    col_ns_row = 1e9 * col_s / n
    pkl_ns_row = 1e9 * pkl_s / n
    print(f"decode {n} rows: columnar {col_ns_row:.0f} ns/row "
          f"({n / col_s:,.0f} rows/s) vs pickled {pkl_ns_row:.0f} ns/row "
          f"({n / pkl_s:,.0f} rows/s) — x{pkl_s / col_s:.1f}",
          file=sys.stderr)
    print(f"wire cost: {len(payload) / n:.1f} B/row columnar vs "
          f"{sum(map(len, legacy_rows)) / n:.1f} B/row pickled",
          file=sys.stderr)

    # ---- the live front door: one frame = one decision + one append
    frames = max(2, args.reps)
    counts = {"admit_calls": 0, "admit_rows": 0, "wal_admits": 0,
              "wal_resolves": 0}

    with tempfile.TemporaryDirectory() as wal_dir:
        loop = asyncio.new_event_loop()
        thread = threading.Thread(target=loop.run_forever,
                                  name="ingest-loop", daemon=True)
        thread.start()

        def run(coro):
            return asyncio.run_coroutine_threadsafe(coro, loop) \
                .result(60.0)

        wal = WriteAheadLog(wal_dir)
        cfg = ServeConfig(buckets=(max(256, n),), max_wait_s=0.002,
                          queue_capacity=4 * n)
        svc = VerificationService(StubZK(), cfg, wal=wal)

        async def _boot():
            await svc.start(prewarm=False)
            server = RpcServer(svc)
            return server, await server.start()

        server, addr = run(_boot())

        real_admit = svc.admission.admit_batch
        real_append = wal.append_admit_batch
        real_resolve = wal.append_resolve

        def admit_batch(kind, lane, rows, lane_depth, deadline, **kw):
            counts["admit_calls"] += 1
            counts["admit_rows"] += rows
            return real_admit(kind, lane, rows, lane_depth, deadline, **kw)

        def append_admit_batch(**kw):
            counts["wal_admits"] += 1
            return real_append(**kw)

        def append_resolve(*a, **kw):
            counts["wal_resolves"] += 1
            return real_resolve(*a, **kw)

        svc.admission.admit_batch = admit_batch
        wal.append_admit_batch = append_admit_batch
        wal.append_resolve = append_resolve
        try:
            cli = RpcClient(addr, tms_id="ingest", call_timeout_s=60.0)
            try:
                t0 = time.perf_counter()
                for _ in range(frames):
                    out = cli.submit_range_batch(truth, [None] * n)
                    assert out.tolist() == truth
                wall = time.perf_counter() - t0
            finally:
                cli.close()
        finally:
            async def _down():
                await server.stop(drain=True)
                await svc.stop(drain=True)
            run(_down())
            loop.call_soon_threadsafe(loop.stop)
            thread.join(timeout=5.0)
            loop.close()

    assert counts["admit_calls"] == frames, counts
    assert counts["admit_rows"] == frames * n, counts
    assert counts["wal_admits"] == frames, counts
    assert counts["wal_resolves"] == frames, counts
    print(f"{frames} frames x {n} rows through the TCP front door: "
          f"{counts['admit_calls']} admission decisions, "
          f"{counts['wal_admits']} WAL admit appends, "
          f"{counts['wal_resolves']} WAL resolves "
          f"({frames * n / wall:,.0f} proofs/s ingested)", file=sys.stderr)

    return {"rows_per_frame": n, "frames": frames,
            "wall_s": round(wall, 4),
            "ingested_proofs_per_sec": round(frames * n / wall, 2),
            "decode": {
                "columnar_ns_per_row": round(col_ns_row, 1),
                "pickled_ns_per_row": round(pkl_ns_row, 1),
                "pickled_over_columnar": round(pkl_s / col_s, 2),
                "pickle_calls_in_columnar_decode": pickle_calls["n"],
                "columnar_bytes_per_row": round(len(payload) / n, 1),
                "pickled_bytes_per_row":
                    round(sum(map(len, legacy_rows)) / n, 1)},
            "contract": dict(counts)}


def _mode_egress(args, tracer, records) -> dict:
    """Columnar RESULT_BATCH egress audit (round 15). Crypto-free.

    Three artifacts:
      1. Encode cost per row: >=256 verdict rows packed into ONE
         columnar RESULT_BATCH payload vs the legacy per-row pickled
         RESULT bodies — with a pickle.dumps counter proving the
         columnar encode performs ZERO pickle calls.
      2. The coalescing contract, asserted on the production service
         behind the real TCP server at protocol v4: an N-row request
         returns as exactly ONE RESULT_BATCH frame scheduled by ONE
         wakeup, and the pickled bytes moved per cycle are O(1)
         housekeeping (credit grants), never O(rows).
      3. Served verdicts/s through the live front door on the columnar
         egress path (RpcServer + RpcClient, StubZK backend).
    """
    import asyncio
    import pickle
    import threading

    from fabric_token_sdk_tpu.obs import GLOBAL
    from fabric_token_sdk_tpu.serve import (RpcClient, RpcServer,
                                            ServeConfig, StubZK,
                                            VerificationService,
                                            encode_result_batch)
    from fabric_token_sdk_tpu.serve.rpc import RPC_OK, ScratchPool

    def fam_count(name, **labels):
        total = 0
        for (fam, lab), val in GLOBAL.snapshot().items():
            if fam != name or any(
                    dict(lab).get(k) != v for k, v in labels.items()):
                continue
            total += val["count"] if isinstance(val, dict) else val
        return total

    n = max(256, args.batch)
    verdicts = [i % 7 != 0 for i in range(n)]
    rows = [(1, i, "ok", verdicts[i], "device", None) for i in range(n)]

    pickle_calls = {"n": 0}
    real_dumps = pickle.dumps

    def counting_dumps(*a, **kw):
        pickle_calls["n"] += 1
        return real_dumps(*a, **kw)

    iters = max(20, args.reps)
    pool = ScratchPool()
    pickle.dumps = counting_dumps
    try:
        t0 = time.perf_counter()
        for _ in range(iters):
            payload, _traced = encode_result_batch(rows, pool=pool)
        col_s = (time.perf_counter() - t0) / iters
    finally:
        pickle.dumps = real_dumps
    assert pickle_calls["n"] == 0, \
        "columnar encode touched pickle — the zero-pickle contract broke"

    # the layout this replaces: one pickled reply dict per row
    t0 = time.perf_counter()
    for _ in range(iters):
        legacy = [real_dumps(
            {"req_id": i, "status": RPC_OK, "statuses": ["ok"],
             "verdicts": [verdicts[i]], "served_by": ["device"]},
            protocol=pickle.HIGHEST_PROTOCOL) for i in range(n)]
    pkl_s = (time.perf_counter() - t0) / iters

    col_ns_row = 1e9 * col_s / n
    pkl_ns_row = 1e9 * pkl_s / n
    print(f"encode {n} rows: columnar {col_ns_row:.0f} ns/row "
          f"({n / col_s:,.0f} rows/s) vs pickled {pkl_ns_row:.0f} ns/row "
          f"({n / pkl_s:,.0f} rows/s) — x{pkl_s / col_s:.1f}",
          file=sys.stderr)
    print(f"wire cost: {len(payload) / n:.1f} B/row columnar vs "
          f"{sum(map(len, legacy)) / n:.1f} B/row pickled",
          file=sys.stderr)

    # ---- the live front door: one frame + one wakeup per request
    frames = max(2, args.reps)
    loop = asyncio.new_event_loop()
    thread = threading.Thread(target=loop.run_forever,
                              name="egress-loop", daemon=True)
    thread.start()

    def run(coro):
        return asyncio.run_coroutine_threadsafe(coro, loop).result(60.0)

    cfg = ServeConfig(buckets=(max(256, n),), max_wait_s=0.002,
                      queue_capacity=4 * n)
    svc = VerificationService(StubZK(), cfg)

    async def _boot():
        await svc.start(prewarm=False)
        server = RpcServer(svc)
        return server, await server.start()

    server, addr = run(_boot())
    try:
        cli = RpcClient(addr, tms_id="egress", call_timeout_s=60.0)
        try:
            # warm the connection (handshake pickles HELLO/WELCOME);
            # the server bumps its egress counters AFTER the reply
            # frame is on the wire, so wait for them to settle before
            # taking the baseline
            assert cli.submit_range_batch([True], [None]).tolist() == \
                [True]
            deadline = time.monotonic() + 10.0
            while fam_count("rpc_result_batch_rows_total",
                            role="server") < 1 \
                    and time.monotonic() < deadline:
                time.sleep(0.005)
            f0 = fam_count("rpc_result_batch_frames_total", role="server")
            r0 = fam_count("rpc_result_batch_rows_total", role="server")
            w0 = fam_count("rpc_wakeups_total")
            pickle_calls["n"] = 0
            pickle.dumps = counting_dumps
            try:
                t0 = time.perf_counter()
                for _ in range(frames):
                    out = cli.submit_range_batch(verdicts, [None] * n)
                    assert out.tolist() == verdicts
                wall = time.perf_counter() - t0
            finally:
                pickle.dumps = real_dumps
            deadline = time.monotonic() + 10.0
            while fam_count("rpc_result_batch_rows_total",
                            role="server") - r0 < frames * n \
                    and time.monotonic() < deadline:
                time.sleep(0.005)
            d_frames = fam_count("rpc_result_batch_frames_total",
                                 role="server") - f0
            d_rows = fam_count("rpc_result_batch_rows_total",
                               role="server") - r0
            d_wakeups = fam_count("rpc_wakeups_total") - w0
            dumps_per_frame = pickle_calls["n"] / frames
        finally:
            cli.close()
    finally:
        async def _down():
            await server.stop(drain=True)
            await svc.stop(drain=True)
        run(_down())
        loop.call_soon_threadsafe(loop.stop)
        thread.join(timeout=5.0)
        loop.close()

    # THE egress contract: every N-row request moved as ONE columnar
    # frame on ONE coalesced wakeup, and the per-cycle pickled bytes
    # are O(1) housekeeping (a credit grant), never O(rows)
    assert d_frames == frames, (d_frames, frames)
    assert d_rows == frames * n, (d_rows, frames * n)
    assert d_wakeups == frames, (d_wakeups, frames)
    assert dumps_per_frame <= 4, dumps_per_frame
    print(f"{frames} requests x {n} rows through the TCP front door: "
          f"{d_frames} RESULT_BATCH frames, {d_wakeups} wakeups, "
          f"{dumps_per_frame:.1f} pickle.dumps/cycle "
          f"({frames * n / wall:,.0f} verdicts/s served)", file=sys.stderr)

    return {"rows_per_request": n, "requests": frames,
            "wall_s": round(wall, 4),
            "served_verdicts_per_sec": round(frames * n / wall, 2),
            "encode": {
                "columnar_ns_per_row": round(col_ns_row, 1),
                "pickled_ns_per_row": round(pkl_ns_row, 1),
                "pickled_over_columnar": round(pkl_s / col_s, 2),
                "pickle_calls_in_columnar_encode": 0,
                "columnar_bytes_per_row": round(len(payload) / n, 1),
                "pickled_bytes_per_row":
                    round(sum(map(len, legacy)) / n, 1)},
            "contract": {"result_batch_frames": d_frames,
                         "result_batch_rows": d_rows,
                         "wakeups": d_wakeups,
                         "pickle_dumps_per_cycle":
                             round(dumps_per_frame, 2)}}


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--mode", choices=("range", "block", "barrier", "fold",
                                       "pipeline", "mesh", "prove",
                                       "ingest", "egress"),
                    default="range")
    ap.add_argument("--batch", type=int, default=1024)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--trace", help="write Chrome trace-event JSON here")
    ap.add_argument("--xprof", help="jax.profiler trace dir for root spans")
    args = ap.parse_args()

    if args.mode == "mesh":
        # must land before the first backend touch: on CPU the host
        # platform defaults to ONE device, and the flag is read at
        # backend initialization
        flags = os.environ.get("XLA_FLAGS", "")
        if (os.environ.get("JAX_PLATFORMS", "") == "cpu"
                and "xla_force_host_platform_device_count" not in flags):
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()

    from fabric_token_sdk_tpu.obs import RECORDS, TRACER
    from fabric_token_sdk_tpu.utils.jaxcfg import configure_jax_cache

    configure_jax_cache()
    if args.xprof:
        TRACER.profile_dir = args.xprof
    mode = {"range": _mode_range, "block": _mode_block,
            "barrier": _mode_barrier, "fold": _mode_fold,
            "pipeline": _mode_pipeline, "mesh": _mode_mesh,
            "prove": _mode_prove, "ingest": _mode_ingest,
            "egress": _mode_egress}[args.mode]
    doc = mode(args, TRACER, RECORDS)
    doc["mode"] = args.mode
    doc["batch"] = args.batch
    print(json.dumps(doc, indent=1))


if __name__ == "__main__":
    main()
