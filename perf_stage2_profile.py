"""Micro-profile of the host stage-2 path (phase-b + RLC weighting) for
one 256-row chunk of the bench corpus — splits native-C compute from
Python glue to size the batching win. Host-only (no device needed): uses
host-computed challenges instead of device digests.
"""
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).parent))
import os
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from bench import _load
from fabric_token_sdk_tpu.models import range_verifier as rv
from fabric_token_sdk_tpu.ops import limbs
from fabric_token_sdk_tpu.crypto import serialization as ser

_FR = rv._FRNATIVE
R = rv.R


def main():
    pp, proofs, coms = _load()
    reps = (256 + len(proofs) - 1) // len(proofs)
    proofs = (proofs * reps)[:256]
    coms = (coms * reps)[:256]
    params = rv._params_for(pp)
    ch = list(range(256))
    rr = params.rounds

    # phase-a (not the target, but time it)
    t0 = time.perf_counter()
    xyz = rv._phase_a_challenges_batch(proofs, coms, ch)
    transcripts = {i: rv._host_phase_a(proofs[i], coms[i], params,
                                       xyz=xyz[row])
                   for row, i in enumerate(ch)}
    t1 = time.perf_counter()
    print(f"phase-a total: {(t1-t0)*1e3:.1f} ms")

    # challenges (host path)
    t0 = time.perf_counter()
    rch = rv._round_challenges_batch(proofs, ch, rr)
    t1 = time.perf_counter()
    print(f"round challenges (host sha): {(t1-t0)*1e3:.1f} ms")

    # x_ipa: fake with fixed ints (value irrelevant for timing)
    x_ipa = [12345678901234567890 + i for i in ch]

    # --- stage-2 proper -------------------------------------------------
    for rep in range(3):
        t0 = time.perf_counter()
        ch_packed_all = limbs.pack_scalars(
            [rch[row, r] for row in range(len(ch)) for r in range(rr)])
        t1 = time.perf_counter()
        inv_packed_all = _FR.batch_inv(ch_packed_all)
        t2 = time.perf_counter()

        # per-proof phase_b: split glue (pack_scalars) from the C call
        glue = 0.0
        cc = 0.0
        eqs = {}
        for row, i in enumerate(ch):
            ts = transcripts[i]
            proof = proofs[i]
            d = proof.data
            ipa = proof.ipa
            sl = slice(row * rr * 32, (row + 1) * rr * 32)
            g0 = time.perf_counter()
            scalars = limbs.pack_scalars(
                [ipa.left, ipa.right, ts.z, ts.x, x_ipa[row],
                 d.inner_product, d.tau, d.delta]) + ts.pol_eval_packed
            g1 = time.perf_counter()
            out = _FR.phase_b(64, rr, scalars, ts.yinv_packed,
                              ch_packed_all[sl], inv_packed_all[sl])
            g2 = time.perf_counter()
            split = (2 * 64 + 5) * 32
            eqs[i] = rv._ProofEquations(fixed=[], var=[],
                                        fixed_packed=out[:split],
                                        var_packed=out[split:])
            glue += g1 - g0
            cc += g2 - g1
        t3 = time.perf_counter()

        # weighting loop (as _weight_equations does)
        import secrets
        n = 64
        n_eq2 = 2 + 2 * rr
        n_fixed = 2 * n + 5
        fixed_acc = bytes(32 * n_fixed)
        zero32 = bytes(32)
        w_t = am_t = mm_t = 0.0
        var_sc_packed = []
        for i in ch:
            w0 = time.perf_counter()
            w1 = (1 + secrets.randbelow(R - 1)).to_bytes(32, "little")
            w2 = (1 + secrets.randbelow(R - 1)).to_bytes(32, "little")
            eq = eqs[i]
            weights = w2 * (2 * n + 2) + w1 * 2 + zero32
            w1t = time.perf_counter()
            fixed_acc = _FR.addmul_many(fixed_acc, eq.fixed_packed, weights)
            w2t = time.perf_counter()
            var_sc_packed.append(_FR.mul_many(
                eq.var_packed, w2 * n_eq2 + w1 * 3))
            w3t = time.perf_counter()
            w_t += w1t - w0
            am_t += w2t - w1t
            mm_t += w3t - w2t
        sc_blob = b"".join(var_sc_packed)
        arr = limbs.packed_to_limbs(sc_blob)
        t4 = time.perf_counter()

        print(f"rep{rep}: stage2 {(t4-t0)*1e3:.1f} ms | "
              f"pack-ch {(t1-t0)*1e3:.1f} inv {(t2-t1)*1e3:.1f} "
              f"phase_b loop {(t3-t2)*1e3:.1f} (glue {glue*1e3:.1f}, "
              f"C {cc*1e3:.1f}) weight {(t4-t3)*1e3:.1f} "
              f"(rand+bytes {w_t*1e3:.1f}, addmul {am_t*1e3:.1f}, "
              f"mul {mm_t*1e3:.1f})")


if __name__ == "__main__":
    main()
