"""Headline benchmark: 64-bit range-proof verifies/sec on one chip.

Prints exactly one JSON line:
  {"metric": "...", "value": N, "unit": "...", "vs_baseline": N}

The reference publishes no performance numbers (BASELINE.md); the baseline
used here is the BASELINE.json north-star target of 10,000 64-bit range-proof
verifies/sec on a single v5e chip, so vs_baseline is the fraction of target
achieved (1.0 == target met).

Proof corpus: pre-generated 64-bit proofs in benchdata/ (host prover is
~seconds/proof; regenerate with `python bench.py --regen`). The corpus is
tiled to the benchmark batch size; verification cost is value-independent.
"""

from __future__ import annotations

import json
import os
import pathlib
import sys
import time


def _configure_jax_cache() -> None:
    """Persistent compile cache: first compile of the 64-bit kernels is
    minutes; every subsequent bench run reuses the cached executables.

    Setting BENCH_COMPILE_CACHE_DIR (e.g. to benchdata/jax_cache) makes
    BOTH caches survive across container runs: XLA executables land under
    that directory (utils/jaxcfg.py picks it up as the cache base) and the
    fixed-base tables are served from uint8 .npz files in benchdata/
    (FTS_TABLE_CACHE_DIR, keyed by the pp generator digest) instead of
    being recomputed — the dominant repeat-run warm-up cost."""
    if os.environ.get("BENCH_COMPILE_CACHE_DIR"):
        os.environ.setdefault("FTS_TABLE_CACHE_DIR", str(BENCH_DIR))
    from fabric_token_sdk_tpu.utils.jaxcfg import configure_jax_cache

    configure_jax_cache()

BENCH_DIR = pathlib.Path(__file__).parent / "benchdata"
BIT_LENGTH = 64
N_PROOFS = 4
# Batch sweep on the chip (round 3): 128 -> 129.5/s, 512 -> 159.9/s,
# 1024 -> 272.3/s, 2048 -> OOM in the one-hot selection buffers. 1024 is
# the single-chip sweet spot with the current kernel structure.
BATCH = int(os.environ.get("BENCH_BATCH", "1024"))
TARGET_BASELINE = 10_000.0  # north-star verifies/sec (BASELINE.json)


def _regen():
    from fabric_token_sdk_tpu.crypto import bn254, rp, setup
    from fabric_token_sdk_tpu.crypto import serialization as ser

    pp = setup.setup(BIT_LENGTH)
    rpp = pp.range_proof_params
    cg = pp.pedersen_generators[1:3]
    BENCH_DIR.mkdir(exist_ok=True)
    (BENCH_DIR / "pp.json").write_bytes(pp.serialize())
    blobs = []
    for i in range(N_PROOFS):
        value = (0xDEADBEEF * (i + 1)) % (1 << BIT_LENGTH)
        bf = bn254.fr_rand()
        com = bn254.g1_add(bn254.g1_mul(cg[0], value), bn254.g1_mul(cg[1], bf))
        proof = rp.range_prove(com, value, cg, bf, rpp.left_generators,
                               rpp.right_generators, rpp.P, rpp.Q,
                               rpp.number_of_rounds, rpp.bit_length)
        blobs.append(ser.marshal_std_bytes_slices(
            [proof.serialize(), ser.g1_to_bytes(com)]))
    payload = ser.marshal_std_bytes_slices(blobs)
    (BENCH_DIR / f"proofs_{BIT_LENGTH}.bin").write_bytes(payload)
    print(f"wrote {N_PROOFS} proofs to {BENCH_DIR}", file=sys.stderr)


def _regen_block():
    """Mixed Issue+Transfer corpus for BASELINE config 3 (actions with
    full Σ+range proofs, 2 outputs each -> 2 range proofs per action)."""
    import pickle

    from fabric_token_sdk_tpu.crypto import bn254, setup, token_commit
    from fabric_token_sdk_tpu.crypto import issue_proof as ipf
    from fabric_token_sdk_tpu.crypto import transfer_proof as tpf

    pp = setup.PublicParams.deserialize((BENCH_DIR / "pp.json").read_bytes())
    ped = pp.pedersen_generators
    transfers, issues = [], []
    for i in range(2):
        in_bfs = [bn254.fr_rand(), bn254.fr_rand()]
        out_bfs = [bn254.fr_rand(), bn254.fr_rand()]
        v = 1000 + i
        inputs = [token_commit.commit_token("USD", v, bf, ped)
                  for bf in in_bfs]
        outputs = [token_commit.commit_token("USD", v, bf, ped)
                   for bf in out_bfs]
        raw = tpf.transfer_prove([("USD", v, bf) for bf in in_bfs],
                                 [("USD", v, bf) for bf in out_bfs],
                                 inputs, outputs, pp)
        transfers.append((raw, inputs, outputs))
        print(f"block corpus: transfer {i} done", file=sys.stderr)
    for i in range(2):
        bfs = [bn254.fr_rand(), bn254.fr_rand()]
        v = 500 + i
        toks = [token_commit.commit_token("EUR", v, bf, ped) for bf in bfs]
        raw = ipf.issue_prove([("EUR", v, bf) for bf in bfs], toks, pp)
        issues.append((raw, toks))
        print(f"block corpus: issue {i} done", file=sys.stderr)
    (BENCH_DIR / f"block_{BIT_LENGTH}.pkl").write_bytes(
        pickle.dumps({"transfers": transfers, "issues": issues}))
    print(f"wrote mixed block corpus to {BENCH_DIR}", file=sys.stderr)


def _load():
    from fabric_token_sdk_tpu.crypto import rp, setup
    from fabric_token_sdk_tpu.crypto import serialization as ser

    pp = setup.PublicParams.deserialize((BENCH_DIR / "pp.json").read_bytes())
    raw = (BENCH_DIR / f"proofs_{BIT_LENGTH}.bin").read_bytes()
    reader = ser.DerReader(raw).read_sequence()
    proofs, coms = [], []
    while not reader.eof():
        inner = ser.DerReader(reader.read_octet_string()).read_sequence()
        proofs.append(rp.RangeProof.deserialize(inner.read_octet_string()))
        coms.append(ser.g1_from_bytes(inner.read_octet_string()))
    return pp, proofs, coms


def _replay(verifier, proofs, coms, total: int):
    """BASELINE configs 3/5 shape: replay `total` proofs through the
    batched verifier in BATCH-sized blocks (the 10k mixed block / 100k
    backlog replay), reporting aggregate throughput."""
    t0 = time.perf_counter()
    done = 0
    while done < total:
        out = verifier.verify(proofs, coms)
        assert out.all(), "replay corpus failed verification"
        done += len(proofs)
    elapsed = time.perf_counter() - t0
    return done / elapsed


def _bench_config1():
    """BASELINE config 1: single-tx 2-in/2-out transfer validate on ONE
    host CPU core (the Go-validator-equivalent reference number) at the
    reference's 16-bit range config. No device; pure host oracle."""
    import statistics

    from fabric_token_sdk_tpu.crypto import bn254, setup, token_commit
    from fabric_token_sdk_tpu.crypto import transfer_proof as tpf

    pp = setup.setup(16)
    ped = pp.pedersen_generators
    in_bfs = [bn254.fr_rand(), bn254.fr_rand()]
    out_bfs = [bn254.fr_rand(), bn254.fr_rand()]
    inputs = [token_commit.commit_token("USD", 30, bf, ped) for bf in in_bfs]
    outputs = [token_commit.commit_token("USD", 30, bf, ped)
               for bf in out_bfs]
    raw = tpf.transfer_prove([("USD", 30, bf) for bf in in_bfs],
                             [("USD", 30, bf) for bf in out_bfs],
                             inputs, outputs, pp)
    lat = []
    for _ in range(12):
        t0 = time.perf_counter()
        tpf.transfer_verify(raw, inputs, outputs, pp)
        lat.append(time.perf_counter() - t0)
    p50 = statistics.median(lat)
    # 2 outputs -> 2 range proofs per validate
    print(json.dumps({
        "metric": "config1_single_tx_transfer_validate_p50_16bit",
        "value": round(p50 * 1e3, 2),
        "unit": "ms (host single-core; 2 range proofs/tx -> "
                f"{round(2 / p50, 1)} proofs/s)",
        "vs_baseline": round((2 / p50) / TARGET_BASELINE, 6),
    }))


def _bench_block(total_actions: int):
    """BASELINE config 3: mixed Issue+Transfer block through the auditor's
    batch re-verify (ZKVerifier.verify_block; all Σ checks in one device
    pass per slice, all range proofs in one batched range pass)."""
    import pickle

    from fabric_token_sdk_tpu.core.zkatdlog.verifier import ZKVerifier
    from fabric_token_sdk_tpu.crypto import setup

    pp = setup.PublicParams.deserialize((BENCH_DIR / "pp.json").read_bytes())
    blob = pickle.loads(
        (BENCH_DIR / f"block_{BIT_LENGTH}.pkl").read_bytes())
    base_t, base_i = blob["transfers"], blob["issues"]
    # tile the corpus to BATCH//2 actions per slice (half transfers, half
    # issues); each action carries 2 range proofs, so the cross-action
    # range batch inside verify_block lands exactly on the BATCH bucket
    slice_t = (base_t * (BATCH // 4 // len(base_t) + 1))[:BATCH // 4]
    slice_i = (base_i * (BATCH // 4 // len(base_i) + 1))[:BATCH // 4]
    zk = ZKVerifier(pp, device=True)
    print("block bench: warm-up slice", file=sys.stderr)
    t0 = time.perf_counter()
    t_ok, i_ok = zk.verify_block(slice_t, slice_i)
    assert t_ok.all() and i_ok.all(), "block corpus failed"
    print(f"block bench: warm-up in {time.perf_counter()-t0:.1f}s",
          file=sys.stderr)
    done = 0
    t0 = time.perf_counter()
    while done < total_actions:
        t_ok, i_ok = zk.verify_block(slice_t, slice_i)
        assert t_ok.all() and i_ok.all()
        done += len(slice_t) + len(slice_i)
    elapsed = time.perf_counter() - t0
    proofs = done * 2  # 2 range proofs per action
    print(json.dumps({
        "metric": f"config3_mixed_block_proofs_per_sec_{BIT_LENGTH}bit",
        "value": round(proofs / elapsed, 2),
        "unit": f"proofs/s ({round(done / elapsed, 1)} actions/s, "
                f"{done} actions)",
        "vs_baseline": round(proofs / elapsed / TARGET_BASELINE, 4),
    }))


def _bench_adversarial():
    """VERDICT r4 ask #4: the adversarial floor. Blocks carrying 1, 10%,
    and 50% invalid proofs through verify() (combined reject -> per-chunk
    bisect -> exact over failing chunks), plus the pure exact-path
    throughput (the DoS floor: an adversary can always force it for the
    chunks it poisons). Prints one JSON line per config."""
    import copy

    from fabric_token_sdk_tpu.models.range_verifier import BatchRangeVerifier

    pp, proofs, coms = _load()
    reps = (BATCH + len(proofs) - 1) // len(proofs)
    proofs = (proofs * reps)[:BATCH]
    coms = (coms * reps)[:BATCH]
    verifier = BatchRangeVerifier(pp)
    print("adversarial: warm-up (clean + exact paths)", file=sys.stderr)
    t0 = time.perf_counter()
    assert verifier.verify(proofs, coms).all()
    print(f"adversarial: clean warm in {time.perf_counter()-t0:.1f}s",
          file=sys.stderr)

    # pure exact path (bit-exact per-proof MSMs over the whole batch)
    t0 = time.perf_counter()
    out = verifier.verify(proofs, coms, exact=True)
    warm = time.perf_counter() - t0  # may include exact-kernel compile
    t0 = time.perf_counter()
    out = verifier.verify(proofs, coms, exact=True)
    exact_s = time.perf_counter() - t0
    assert out.all()
    print(json.dumps({
        "metric": f"adversarial_exact_path_proofs_per_sec_{BIT_LENGTH}bit",
        "value": round(BATCH / exact_s, 2),
        "unit": f"proofs/s (warm-up incl compile {warm:.1f}s)",
        "vs_baseline": round(BATCH / exact_s / TARGET_BASELINE, 4)}))

    def forge(p):
        bad = copy.deepcopy(p)
        bad.data.tau = (bad.data.tau + 1) % (1 << 250)
        return bad

    # warm the bisect path's chunk-bucket kernels (exact over ONE failing
    # chunk) so the timed runs measure steady state, not first-compile
    mixed0 = list(proofs)
    mixed0[0] = forge(proofs[0])
    t0 = time.perf_counter()
    out = verifier.verify(mixed0, coms)
    assert not out[0] and out[1:].all()
    print(f"adversarial: bisect warm in {time.perf_counter()-t0:.1f}s",
          file=sys.stderr)

    for n_bad in (1, BATCH // 10, BATCH // 2):
        bad_idx = set(range(0, BATCH, max(1, BATCH // max(1, n_bad))))
        while len(bad_idx) > n_bad:
            bad_idx.pop()
        mixed = list(proofs)
        for i in bad_idx:
            mixed[i] = forge(proofs[i])
        t0 = time.perf_counter()
        out = verifier.verify(mixed, coms)
        elapsed = time.perf_counter() - t0
        expect = [i not in bad_idx for i in range(BATCH)]
        assert list(out) == expect, "adversarial verdict vector wrong"
        print(json.dumps({
            "metric": f"adversarial_{len(bad_idx)}bad_of_{BATCH}"
                      f"_proofs_per_sec_{BIT_LENGTH}bit",
            "value": round(BATCH / elapsed, 2),
            "unit": f"proofs/s (latency {elapsed:.2f}s, "
                    f"path={verifier.last_path})",
            "vs_baseline": round(BATCH / elapsed / TARGET_BASELINE, 4)}))


def _start_bench_telemetry(svc, supervisor=None):
    """With BENCH_TELEMETRY_PORT=<port> set, put the live telemetry
    plane on the running bench service (scrape /metrics, /statusz,
    /tracez while the open loop is in flight). Returns the server or
    None; callers stop() it after the run."""
    port = os.environ.get("BENCH_TELEMETRY_PORT")
    if not port:
        return None
    from fabric_token_sdk_tpu.obs import TelemetryConfig, serve_telemetry

    host = os.environ.get("BENCH_TELEMETRY_HOST", "0.0.0.0")
    server = serve_telemetry(svc, TelemetryConfig(host=host, port=int(port)),
                             supervisor=supervisor)
    print(f"bench: telemetry plane at {server.url} "
          "(/metrics /healthz /readyz /statusz /tracez)", file=sys.stderr)
    return server


def _configure_bench_journal() -> None:
    """With BENCH_JOURNAL_DIR=<dir> set, arm the flight recorder: every
    admit/shed/batch/dispatch/breaker/SLO event spills to journal.jsonl
    there, and any SLO fast-burn trip, breaker force-open or watchdog
    abandon during the run drops an incident snapshot (journal tail +
    all-thread stacks + open spans) alongside it."""
    from fabric_token_sdk_tpu.obs import JOURNAL, configure_journal_from_env

    directory = configure_journal_from_env(JOURNAL)
    if directory:
        print(f"bench: flight recorder armed at {directory} "
              "(incident snapshots on SLO fast-burn / breaker latch)",
              file=sys.stderr)


def _write_trace_out() -> None:
    """With BENCH_TRACE_OUT=<path> set, export the tracer's completed
    root spans (serve.request trees with linked serve.batch spans) as a
    Chrome/Perfetto trace after the run."""
    path = os.environ.get("BENCH_TRACE_OUT")
    if not path:
        return
    from fabric_token_sdk_tpu.obs import TRACER
    from fabric_token_sdk_tpu.obs.export import write_chrome_trace

    spans = TRACER.root_snapshot()
    write_chrome_trace(path, spans)
    print(f"bench: {len(spans)} trace roots written to {path}",
          file=sys.stderr)


def _bench_serve():
    """BENCH_MODE=serve: open-loop arrival bench through the serve/
    frontend on one chip. A seeded Poisson arrival schedule (default
    2,500 req/s for 30 s) submits individual range-proof requests to the
    VerificationService; the bucket scheduler assembles batches under the
    deadline policy. Prewarm wall is reported separately from steady
    state; the tail carries p50/p99, deadline-miss and shed counts.
    Before the run, a mixed clean/forged spot batch asserts the service's
    demuxed verdicts are bit-identical to the direct batched call.

    The full telemetry plane rides along: retry/breaker resilience (so
    resil_* families are live), an SLO burn-rate monitor bound to the
    breaker, per-bucket device profiling at prewarm/dispatch, and —
    with BENCH_TELEMETRY_PORT set — the HTTP scrape surface."""
    import asyncio
    import copy

    from fabric_token_sdk_tpu.core.zkatdlog.verifier import ZKVerifier
    from fabric_token_sdk_tpu.harness.txgen import open_loop_arrivals
    from fabric_token_sdk_tpu.obs import SloMonitor
    from fabric_token_sdk_tpu.resilience import ResilienceConfig
    from fabric_token_sdk_tpu.serve import (STATUS_DEADLINE_MISS, STATUS_OK,
                                            ServeConfig, VerificationService)

    pp, proofs, coms = _load()
    rate = float(os.environ.get("BENCH_SERVE_RATE", "2500"))
    duration = float(os.environ.get("BENCH_SERVE_SECONDS", "30"))
    buckets = tuple(int(b) for b in os.environ.get(
        "BENCH_SERVE_BUCKETS", "16,128,256,512,1024").split(","))
    cfg = ServeConfig(
        buckets=buckets,
        max_wait_s=float(os.environ.get("BENCH_SERVE_WAIT", "0.025")),
        default_deadline_s=float(os.environ.get("BENCH_SERVE_DEADLINE",
                                                "2.0")),
        trace_every=int(os.environ.get("BENCH_TRACE_EVERY", "100")))
    zk = ZKVerifier(pp, device=True)
    slo = SloMonitor()
    _configure_bench_journal()
    svc = VerificationService(
        zk, config=cfg,
        resilience=ResilienceConfig(watchdog_timeout_s=120.0), slo=slo)
    if svc.breaker is not None:
        slo.bind_breaker(svc.breaker)
    telemetry = _start_bench_telemetry(svc)
    n = len(proofs)

    async def run():
        print(f"serve bench: prewarming {len(cfg.buckets)} buckets",
              file=sys.stderr)
        prewarm_s = await svc.start()
        print(f"serve bench: prewarm in {prewarm_s:.1f}s "
              f"{ {b: round(s, 2) for b, s in svc.prewarm.compile_s.items()} }",
              file=sys.stderr)
        forged = copy.deepcopy(proofs[0])
        forged.data.tau = (forged.data.tau + 1) % (1 << 250)
        spot_p = [forged] + proofs[:7]
        spot_c = [coms[0]] + coms[:7]
        direct = zk._range.verify(spot_p, spot_c)
        got = await asyncio.gather(*[
            svc.submit_range(p, c) for p, c in zip(spot_p, spot_c)])
        assert [r.accepted for r in got] == [bool(x) for x in direct], \
            "serve verdicts diverge from the direct batched path"
        arrivals = open_loop_arrivals(rate, duration, seed=11)
        print(f"serve bench: open loop, {len(arrivals)} arrivals over "
              f"{duration:.0f}s", file=sys.stderr)
        loop = asyncio.get_running_loop()
        t0 = loop.time()

        async def one(i, offset):
            delay = t0 + offset - loop.time()
            if delay > 0:
                await asyncio.sleep(delay)
            return await svc.submit_range(proofs[i % n], coms[i % n])

        results = await asyncio.gather(
            *[one(i, off) for i, off in enumerate(arrivals)])
        elapsed = loop.time() - t0
        await svc.stop()
        return prewarm_s, results, elapsed

    prewarm_s, results, elapsed = asyncio.run(run())
    if telemetry is not None:
        telemetry.stop()
    from fabric_token_sdk_tpu.obs import PROFILER
    print(f"serve bench: slo {json.dumps(slo.summary())}", file=sys.stderr)
    print(f"serve bench: profile {json.dumps(PROFILER.summary())}",
          file=sys.stderr)
    ok = [r for r in results if r.status == STATUS_OK]
    misses = sum(r.status == STATUS_DEADLINE_MISS for r in results)
    shed = len(results) - len(ok) - misses
    assert all(r.accepted for r in ok), "serve bench corpus rejected"
    lat = sorted(r.total_s for r in ok) or [0.0]
    p50 = lat[len(lat) // 2]
    p99 = lat[min(len(lat) - 1, int(0.99 * len(lat)))]
    fill = [r.batch_rows / r.bucket for r in ok if r.bucket] or [0.0]
    print(json.dumps({
        "metric": f"serve_prewarm_wall_seconds_{BIT_LENGTH}bit",
        "value": round(prewarm_s, 2),
        "unit": f"s ({len(cfg.buckets)} buckets, reported separately "
                "from steady state)",
    }))
    value = len(ok) / elapsed
    print(json.dumps({
        "metric": f"serve_openloop_req_per_sec_{BIT_LENGTH}bit",
        "value": round(value, 2),
        "unit": (f"req/s served (arrival {rate:.0f}/s x {duration:.0f}s; "
                 f"p50 {p50 * 1e3:.1f}ms p99 {p99 * 1e3:.1f}ms; "
                 f"deadline_miss {misses} shed {shed}; "
                 f"mean fill {sum(fill) / len(fill):.2f})"),
        "vs_baseline": round(value / TARGET_BASELINE, 4),
    }))
    # distributed-tracing overhead, measured and bounded: the per-traced-
    # request cost is one span open/close plus one context inject+extract
    # round-trip (the v3 wire path). Amortized over trace_every sampling
    # it must stay a vanishing fraction of the measured p50 — asserted,
    # not eyeballed, so a tracing-hot-path regression fails the bench.
    from fabric_token_sdk_tpu.obs import Tracer
    from fabric_token_sdk_tpu.obs.tracing import extract_wire_context
    probe = Tracer()  # private: keeps the run's span buffers untouched
    iters = 2000
    t_tr = time.perf_counter()
    for _ in range(iters):
        with probe.span("bench.trace_probe") as psp:
            extract_wire_context(psp.context().to_bytes())
    trace_cost_s = (time.perf_counter() - t_tr) / iters
    every = max(1, cfg.trace_every or 1)
    overhead_ratio = (trace_cost_s / every) / max(p50, 1e-9)
    assert overhead_ratio < 0.05, (
        f"tracing overhead {overhead_ratio:.4f} of p50 latency — the "
        "span/context hot path regressed")
    print(json.dumps({
        "metric": f"serve_trace_overhead_ratio_{BIT_LENGTH}bit",
        "value": round(overhead_ratio, 6),
        "unit": (f"fraction of p50 request latency spent on tracing "
                 f"({trace_cost_s * 1e6:.1f}us/traced request, "
                 f"sampled 1/{every}; bound < 0.05 asserted)"),
    }))


def _bench_frontdoor():
    """BENCH_MODE=frontdoor: columnar batch ingest vs legacy per-request
    pickled frames through the real TCP front door.

    Open-loop RpcClients (BENCH_FRONTDOOR_CLIENTS, default 200) hammer
    one RpcServer in two phases of BENCH_FRONTDOOR_SECONDS each:
    phase 1 all-legacy (one pickled SUBMIT per proof — the wire format
    the columnar path replaces), phase 2 columnar
    (BENCH_FRONTDOOR_ROWS-row SUBMIT_BATCH frames) with a legacy
    minority mixed in so v1 interop is proven under load, not just in
    the handshake test. The default backend is StubZK so the bench
    measures the front door's ser/de wall, not the device;
    BENCH_FRONTDOOR_VERIFIER=device serves the real corpus through
    ZKVerifier instead, with the same spot parity gate. Reports
    ingested proofs/s per phase, bytes/proof per wire format and
    per-tenant p99, and asserts the per-client columnar speedup is
    >= BENCH_FRONTDOOR_MIN_SPEEDUP (default 5) with zero
    rpc_frame_errors_total on the clean run.

    Phase 3 adds a noisy-neighbor arm pair through the per-tenant SLO
    plane, and phase 4 a C10k connection storm (BENCH_C10K_CONNS
    open-loop conns, mixed columnar-v4/legacy-v1 dialects, 37 tenants)
    against n_loops=1 vs n_loops=4 servers — gating on zero parity
    errors, zero lost requests, zero mid-frame closes, bounded
    accept->WELCOME p99, and a proofs/s floor on the sharded arm."""
    import asyncio
    import pickle
    import threading

    from fabric_token_sdk_tpu.obs import GLOBAL
    from fabric_token_sdk_tpu.serve import (LANE_BULK, RpcClient,
                                            RpcConfig, RpcServer,
                                            ServeConfig, StubZK,
                                            VerificationService)

    clients = int(os.environ.get("BENCH_FRONTDOOR_CLIENTS", "200"))
    secs = float(os.environ.get("BENCH_FRONTDOOR_SECONDS", "10"))
    rows = int(os.environ.get("BENCH_FRONTDOOR_ROWS", "256"))
    min_speedup = float(os.environ.get("BENCH_FRONTDOOR_MIN_SPEEDUP", "5"))
    device = os.environ.get("BENCH_FRONTDOOR_VERIFIER", "stub") == "device"

    if device:
        _configure_jax_cache()
        from fabric_token_sdk_tpu.core.zkatdlog.verifier import ZKVerifier

        pp, proofs, coms = _load()
        reps = (rows + len(proofs) - 1) // len(proofs)
        row_p = (proofs * reps)[:rows]
        row_c = (coms * reps)[:rows]
        zk = ZKVerifier(pp, device=True)
        oracle = [bool(x) for x in zk._range.verify(row_p, row_c)]
    else:
        row_p = [i % 5 != 0 for i in range(rows)]
        row_c = [None] * rows
        zk = StubZK()
        oracle = list(row_p)

    def _fam(name, **labels):
        total = 0
        for (fam, lab), val in GLOBAL.snapshot().items():
            if fam != name or any(dict(lab).get(k) != v
                                  for k, v in labels.items()):
                continue
            total += val["count"] if isinstance(val, dict) else val
        return total

    cfg = ServeConfig(
        buckets=(16, 256, 1024), max_wait_s=0.005,
        default_deadline_s=60.0,
        queue_capacity=max(16384, 2 * rows * clients))
    svc = VerificationService(zk, config=cfg)
    loop = asyncio.new_event_loop()
    loop_thread = threading.Thread(target=loop.run_forever,
                                   name="frontdoor-loop", daemon=True)
    loop_thread.start()

    def run(coro):
        return asyncio.run_coroutine_threadsafe(coro, loop).result(300.0)

    async def _boot():
        await svc.start(prewarm=device)
        server = RpcServer(svc, RpcConfig(conn_credits=4 * rows))
        return server, await server.start()

    server, addr = run(_boot())
    errs0 = _fam("rpc_frame_errors_total")

    # spot parity gate before the storm: served verdicts (batch AND
    # legacy wire formats) must match the oracle for the same corpus
    spot = RpcClient(addr, tms_id="spot", call_timeout_s=120.0)
    try:
        assert spot.submit_range_batch(row_p, row_c).tolist() == oracle, \
            "frontdoor columnar verdicts diverge from the oracle"
        assert spot.submit_range(row_p[:4], row_c[:4]).tolist() \
            == oracle[:4], \
            "frontdoor legacy verdicts diverge from the oracle"
    finally:
        spot.close()

    def _storm(batch_flags, phase_secs):
        """One phase of closed-loop clients; rows by wire format plus
        per-tenant call latencies."""
        counts = {"batch": 0, "legacy": 0}
        lats: dict[str, tuple[bool, list]] = {}
        lock = threading.Lock()
        stop_at = time.perf_counter() + phase_secs

        def one(i, use_batch):
            tms = f"tenant-{i:03d}"
            cli = RpcClient(addr, tms_id=tms, call_timeout_s=120.0)
            mine, done = [], 0
            try:
                while time.perf_counter() < stop_at:
                    t0 = time.perf_counter()
                    if use_batch:
                        out = cli.submit_range_batch(row_p, row_c)
                        done += len(out)
                    else:
                        out = cli.submit_range(row_p[:1], row_c[:1])
                        done += 1
                    mine.append(time.perf_counter() - t0)
                    assert bool(out[0]) == oracle[0]
            finally:
                cli.close()
            with lock:
                counts["batch" if use_batch else "legacy"] += done
                lats[tms] = (use_batch, mine)

        threads = [threading.Thread(target=one, args=(i, batch_flags[i]))
                   for i in range(len(batch_flags))]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return counts, lats, time.perf_counter() - t0

    print(f"frontdoor bench: phase 1 — {clients} legacy clients, "
          f"{secs:.0f}s", file=sys.stderr)
    c1, _, wall1 = _storm([False] * clients, secs)
    n_mix = max(1, clients // 8)
    print(f"frontdoor bench: phase 2 — {clients - n_mix} columnar + "
          f"{n_mix} legacy clients, {secs:.0f}s", file=sys.stderr)
    flags = [i >= n_mix for i in range(clients)]
    c2, lats2, wall2 = _storm(flags, secs)

    async def _down():
        await server.stop(drain=True)
        await svc.stop(drain=True)

    run(_down())
    loop.call_soon_threadsafe(loop.stop)
    loop_thread.join(timeout=10.0)
    loop.close()

    legacy_ps = c1["legacy"] / wall1
    batch_ps = c2["batch"] / wall2
    per_legacy = legacy_ps / clients
    per_batch = batch_ps / (clients - n_mix)
    speedup = per_batch / per_legacy if per_legacy else float("inf")

    def _p99(vals):
        s = sorted(vals) or [0.0]
        return s[min(len(s) - 1, int(0.99 * len(s)))]

    p99_batch = max((_p99(v) for b, v in lats2.values() if b),
                    default=0.0)
    p99_legacy = max((_p99(v) for b, v in lats2.values() if not b),
                     default=0.0)
    col_bytes = _fam("rpc_batch_bytes_total", role="client")
    col_rows = _fam("rpc_batch_rows_total", role="client") or 1
    legacy_body = {"req_id": 1, "kind": "range", "lane": LANE_BULK,
                   "tms_id": "tenant-000", "rows": 1,
                   "deadline": time.time() + 60.0,
                   "payload": (row_p[:1], row_c[:1])}
    legacy_bpp = len(pickle.dumps(
        legacy_body, protocol=pickle.HIGHEST_PROTOCOL)) + 12
    errs = _fam("rpc_frame_errors_total") - errs0

    backend = "device" if device else "stub"
    print(json.dumps({
        "metric": f"frontdoor_ingest_proofs_per_sec_{BIT_LENGTH}bit",
        "value": round(batch_ps, 2),
        "unit": (f"proofs/s ingested, {backend} backend "
                 f"({clients - n_mix} columnar + {n_mix} legacy clients, "
                 f"{rows} rows/frame; legacy-only phase {legacy_ps:.0f}/s; "
                 f"per-client speedup x{speedup:.1f}; "
                 f"{col_bytes / col_rows:.1f} vs {legacy_bpp:.0f} B/proof; "
                 f"worst-tenant p99 batch {p99_batch * 1e3:.1f}ms "
                 f"legacy {p99_legacy * 1e3:.1f}ms; "
                 f"frame_errors {errs})"),
    }))
    assert errs == 0, f"{errs} rpc_frame_errors_total on a clean run"
    assert speedup >= min_speedup, (
        f"columnar ingest speedup x{speedup:.2f} below the "
        f"x{min_speedup:.1f} bar (per-client {per_batch:.0f} vs "
        f"{per_legacy:.0f} proofs/s)")

    # ---- phase 3: noisy neighbor through the per-tenant SLO plane ----
    # One hot tenant offered ~10x the victims' load against a THROTTLED
    # stub backend (the phase measures the SLO plane, not the verifier):
    # the hot tenant's queue_full sheds burn its own error budget, its
    # fast-burn trips, and the TenantShedPolicy isolates it with
    # shed_tenant_slo while nine victim tenants keep being served. Run
    # twice — shed ON vs FTS_NO_TENANT_SHED=1 — and assert the victims'
    # p99 does not regress when the hot tenant trips its shed.
    from fabric_token_sdk_tpu.obs import TenantSloMonitor, TenantSloPolicy
    from fabric_token_sdk_tpu.serve import WorkerUnavailable

    noisy_secs = float(os.environ.get("BENCH_NOISY_SECONDS", "6"))
    n_victims, hot_conns = 9, 6
    h_rows, v_rows = 1024, 16
    h_p, h_c = [True] * h_rows, [None] * h_rows

    class _ThrottledRange:
        def verify(self, proofs, coms):
            time.sleep(len(proofs) * 50e-6)     # ~20k rows/s capacity
            return [bool(p) for p in proofs]

    class _ThrottledZK:
        pp = None

        def __init__(self):
            self._range = _ThrottledRange()

        def verify_block(self, transfers, issues):
            return ([bool(t[0]) for t in transfers],
                    [bool(i[0]) for i in issues])

        def prewarm_shapes(self, buckets, include_block=False):
            del include_block
            return {int(b): 0.0 for b in buckets}

    def _noisy_arm(shed_on):
        prev = os.environ.pop("FTS_NO_TENANT_SHED", None)
        if not shed_on:
            os.environ["FTS_NO_TENANT_SHED"] = "1"
        try:
            monitor = TenantSloMonitor(TenantSloPolicy(
                windows=(1.0, 5.0), min_volume=64, eval_interval_s=0.05,
                max_tenants=64))
            ncfg = ServeConfig(buckets=(16, 256, 1024), max_wait_s=0.002,
                               default_deadline_s=60.0,
                               queue_capacity=4096, max_tenants=64)
            nsvc = VerificationService(_ThrottledZK(), config=ncfg,
                                       tenant_slo=monitor)
        finally:
            if prev is not None:
                os.environ["FTS_NO_TENANT_SHED"] = prev
            else:
                os.environ.pop("FTS_NO_TENANT_SHED", None)
        nloop = asyncio.new_event_loop()
        nthread = threading.Thread(target=nloop.run_forever,
                                   name="noisy-loop", daemon=True)
        nthread.start()

        def nrun(coro):
            return asyncio.run_coroutine_threadsafe(
                coro, nloop).result(120.0)

        async def _nboot():
            await nsvc.start(prewarm=False)
            s = RpcServer(nsvc, RpcConfig(conn_credits=8 * h_rows))
            return s, await s.start()

        nserver, naddr = nrun(_nboot())
        stop_at = time.perf_counter() + noisy_secs
        lock = threading.Lock()
        v_lats: list[float] = []
        stats = {"victim_errs": 0, "parity": 0}

        def victim(idx):
            cli = RpcClient(naddr, tms_id=f"victim-{idx}",
                            call_timeout_s=60.0)
            mine, errs, bad = [], 0, 0
            try:
                while time.perf_counter() < stop_at:
                    t0 = time.perf_counter()
                    try:
                        out = cli.submit_range_batch(h_p[:v_rows],
                                                     h_c[:v_rows])
                        mine.append(time.perf_counter() - t0)
                        if not all(bool(x) for x in out):
                            bad += 1
                    except WorkerUnavailable:
                        errs += 1          # shed rows raise client-side
                    time.sleep(0.02)
            finally:
                cli.close()
            with lock:
                v_lats.extend(mine)
                stats["victim_errs"] += errs
                stats["parity"] += bad

        def hot(_i):
            cli = RpcClient(naddr, tms_id="hot", call_timeout_s=60.0)
            bad = 0
            try:
                while time.perf_counter() < stop_at:
                    try:
                        out = cli.submit_range_batch(h_p, h_c)
                        if not all(bool(x) for x in out):
                            bad += 1
                    except WorkerUnavailable:
                        pass               # shed: the point of the phase
            finally:
                cli.close()
            with lock:
                stats["parity"] += bad

        threads = [threading.Thread(target=victim, args=(i,))
                   for i in range(n_victims)]
        threads += [threading.Thread(target=hot, args=(i,))
                    for i in range(hot_conns)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        summ = nsvc.tenant_status()

        async def _ndown():
            await nserver.stop(drain=True)
            await nsvc.stop(drain=False, timeout_s=30.0)

        nrun(_ndown())
        nloop.call_soon_threadsafe(nloop.stop)
        nthread.join(timeout=10.0)
        nloop.close()
        hot_row = summ["tenants"].get("hot", {})
        return {
            "victim_p99_s": _p99(v_lats),
            "victim_calls": len(v_lats),
            "victim_errors": stats["victim_errs"],
            "parity_errors": stats["parity"],
            "hot_trips": hot_row.get("trips", 0),
            "hot_sheds": hot_row.get("sheds", 0),
            "fairness": summ.get("fairness", {}),
        }

    print(f"frontdoor bench: phase 3 — noisy neighbor, shed ON "
          f"({hot_conns} hot conns vs {n_victims} victims, "
          f"{noisy_secs:.0f}s/arm)", file=sys.stderr)
    arm_on = _noisy_arm(shed_on=True)
    print("frontdoor bench: phase 3 — noisy neighbor, shed OFF "
          "(FTS_NO_TENANT_SHED=1)", file=sys.stderr)
    arm_off = _noisy_arm(shed_on=False)

    noisy_errs = _fam("rpc_frame_errors_total") - errs0 - errs
    p99_on, p99_off = arm_on["victim_p99_s"], arm_off["victim_p99_s"]
    print(json.dumps({
        "metric": f"frontdoor_noisy_victim_p99_ms_{BIT_LENGTH}bit",
        "value": round(p99_on * 1e3, 2),
        "unit": (f"ms victim p99 with tenant shed ON vs "
                 f"{p99_off * 1e3:.1f}ms OFF; hot trips "
                 f"{arm_on['hot_trips']} sheds {arm_on['hot_sheds']} "
                 f"(OFF arm trips {arm_off['hot_trips']} sheds "
                 f"{arm_off['hot_sheds']}); victim calls "
                 f"{arm_on['victim_calls']}/{arm_off['victim_calls']} "
                 f"errs {arm_on['victim_errors']}/"
                 f"{arm_off['victim_errors']}; fairness "
                 f"{arm_on['fairness']} vs {arm_off['fairness']})"),
    }))
    assert arm_on["parity_errors"] == 0 and arm_off["parity_errors"] == 0, \
        "noisy-neighbor phase saw verdict-parity errors"
    assert noisy_errs == 0, \
        f"{noisy_errs} rpc_frame_errors_total in the noisy phase"
    assert arm_on["hot_trips"] >= 1 and arm_on["hot_sheds"] > 0, (
        f"hot tenant never tripped its SLO shed (trips "
        f"{arm_on['hot_trips']}, sheds {arm_on['hot_sheds']})")
    assert arm_off["hot_sheds"] == 0, (
        f"FTS_NO_TENANT_SHED=1 arm still shed {arm_off['hot_sheds']} "
        "rows by tenant policy")
    assert p99_on <= p99_off * 1.5 + 0.05, (
        f"victim p99 regressed with the tenant shed on: "
        f"{p99_on * 1e3:.1f}ms vs {p99_off * 1e3:.1f}ms off")

    # ---- phase 4: C10k — sharded accept loops under a conn storm ----
    # BENCH_C10K_CONNS open-loop connections (default 2000, scaled to
    # the fd budget) dial one server per arm — n_loops=1 (today's
    # single loop) vs n_loops=4 (sharded) — each speaking either the
    # columnar v4 dialect (SUBMIT_BATCH in, RESULT_BATCH out) or the
    # legacy v1 pickled dialect, across 37 tenants. Gates: zero verdict
    # parity errors, zero lost requests, zero mid-frame closes, accept
    # ->WELCOME p99 bounded, and the sharded arm's proofs/s at least
    # BENCH_C10K_MIN_PROOFS_PS (default: the per-client legacy floor
    # phase 1 established — the PR 12 bar the C10k path must not lose).
    import resource

    from fabric_token_sdk_tpu.serve.columnar import (FMT_OPAQUE,
                                                     decode_result_batch,
                                                     encode_submit_batch,
                                                     opaque_cells)
    from fabric_token_sdk_tpu.serve.rpc import (CREDIT, GOAWAY, HELLO,
                                                RESULT, RESULT_BATCH,
                                                SUBMIT, SUBMIT_BATCH,
                                                WELCOME, encode_frame,
                                                encode_raw_frame,
                                                read_frame)

    conns_want = int(os.environ.get("BENCH_C10K_CONNS", "2000"))
    accept_p99_bar = float(
        os.environ.get("BENCH_C10K_ACCEPT_P99_S", "5.0"))
    min_pps = float(
        os.environ.get("BENCH_C10K_MIN_PROOFS_PS", str(per_legacy)))
    c10k_rows = 16
    batch_p = [i % 3 != 0 for i in range(c10k_rows)]

    # every conn is 1 client fd + 1 server fd in this process; raise
    # the soft NOFILE limit toward the hard one, then scale the storm
    # to whatever budget we actually got
    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    want_fds = 3 * conns_want + 512
    if soft < want_fds:
        try:
            resource.setrlimit(resource.RLIMIT_NOFILE,
                               (min(want_fds, hard), hard))
        except (ValueError, OSError):
            pass
        soft, _ = resource.getrlimit(resource.RLIMIT_NOFILE)
    n_conns = min(conns_want, max(64, (soft - 512) // 3))
    if n_conns < conns_want:
        print(f"frontdoor bench: C10k scaled to {n_conns} conns "
              f"(RLIMIT_NOFILE soft={soft})", file=sys.stderr)

    def _c10k_arm(n_loops):
        # capacity covers the whole storm arriving at once: every conn
        # submits up to c10k_rows rows before any verdict drains
        ccfg = ServeConfig(buckets=(16, 256), max_wait_s=0.002,
                           default_deadline_s=120.0,
                           queue_capacity=max(16384,
                                              2 * c10k_rows * n_conns),
                           max_tenants=64)
        csvc = VerificationService(StubZK(), config=ccfg)
        cloop = asyncio.new_event_loop()
        cthread = threading.Thread(target=cloop.run_forever,
                                   name="c10k-loop", daemon=True)
        cthread.start()

        def crun(coro, timeout=300.0):
            return asyncio.run_coroutine_threadsafe(
                coro, cloop).result(timeout)

        async def _cboot():
            await csvc.start(prewarm=False)
            s = RpcServer(csvc, RpcConfig(n_loops=n_loops,
                                          conn_credits=4 * c10k_rows))
            return s, await s.start()

        cserver, caddr = crun(_cboot())
        errs_before = _fam("rpc_frame_errors_total")
        accept_lat: list[float] = []
        stats = {"served": 0, "parity": 0, "lost": 0}

        sub_batch = encode_raw_frame(SUBMIT_BATCH, encode_submit_batch(
            fmt=FMT_OPAQUE, lane=LANE_BULK, req_id_base=11,
            deadline=time.time() + 3600.0,
            proof_cells=opaque_cells(batch_p)))
        legacy_p = [True, False]

        async def one_conn(i):
            """One open-loop connection: dial, submit once in its wire
            dialect, await the verdicts + the credit replenish, close
            cleanly. Returns (accept_s, rows, parity_ok)."""
            use_batch = i % 2 == 0
            tms = f"c10k-{i % 37}"
            t0 = time.perf_counter()
            reader, writer = await asyncio.open_connection(*caddr)
            try:
                hello = {"tms_id": tms, "t": time.time()}
                if use_batch:
                    hello["v"] = 4
                writer.write(encode_frame(HELLO, hello))
                await writer.drain()
                frame = await read_frame(reader, header_timeout_s=60.0,
                                         body_timeout_s=60.0)
                if frame is None or frame[0] != WELCOME:
                    return None, 0, False
                accept_s = time.perf_counter() - t0
                if use_batch:
                    writer.write(sub_batch)
                    expect = batch_p
                else:
                    writer.write(encode_frame(SUBMIT, {
                        "req_id": 11, "kind": "range",
                        "rows": len(legacy_p), "tms_id": tms,
                        "payload": (legacy_p, [None] * len(legacy_p))}))
                    expect = legacy_p
                await writer.drain()
                verdicts, got_credit = None, False
                while verdicts is None or not got_credit:
                    frame = await asyncio.wait_for(
                        read_frame(reader, header_timeout_s=120.0,
                                   body_timeout_s=120.0), 120.0)
                    if frame is None:
                        return accept_s, 0, False
                    ftype, body, _flags = frame
                    if ftype == RESULT_BATCH:
                        rb = decode_result_batch(body)
                        verdicts = [rb.verdict_value(j)
                                    for j in range(rb.n_rows)]
                    elif ftype == RESULT:
                        verdicts = body.get("verdicts")
                    elif ftype == CREDIT:
                        got_credit = verdicts is not None
                    elif ftype == GOAWAY:
                        return accept_s, 0, False
                ok = verdicts == expect
                return accept_s, len(verdicts), ok
            finally:
                writer.close()
                try:
                    await writer.wait_closed()
                except (ConnectionError, OSError):
                    pass

        async def storm():
            done = await asyncio.gather(
                *[one_conn(i) for i in range(n_conns)],
                return_exceptions=True)
            for out in done:
                if isinstance(out, BaseException):
                    stats["lost"] += 1
                    continue
                accept_s, served, ok = out
                if accept_s is not None:
                    accept_lat.append(accept_s)
                if served == 0:
                    stats["lost"] += 1
                    continue
                stats["served"] += served
                if not ok:
                    stats["parity"] += 1

        storm_loop = asyncio.new_event_loop()
        t0 = time.perf_counter()
        try:
            storm_loop.run_until_complete(storm())
        finally:
            storm_loop.close()
        wall = time.perf_counter() - t0

        sstat = cserver.status()
        shard_conns = {k: v["conns"] for k, v in sstat["loops"].items()}

        async def _cdown():
            await cserver.stop(drain=True)
            await csvc.stop(drain=True)

        crun(_cdown())
        cloop.call_soon_threadsafe(cloop.stop)
        cthread.join(timeout=10.0)
        cloop.close()
        lat = sorted(accept_lat) or [0.0]
        return {
            "n_loops": n_loops,
            "conns": n_conns,
            "proofs_per_sec": stats["served"] / wall,
            "accept_p99_s": lat[min(len(lat) - 1,
                                    int(0.99 * len(lat)))],
            "parity_errors": stats["parity"],
            "lost": stats["lost"],
            "midframe_closes": cserver.midframe_closes,
            "ownership_violations": cserver.ownership_violations,
            "frame_errors": _fam("rpc_frame_errors_total") - errs_before,
            "loops_used": sum(1 for v in shard_conns.values() if v > 0
                              or n_loops == 1),
        }

    print(f"frontdoor bench: phase 4 — C10k storm, {n_conns} conns, "
          f"n_loops=1 vs n_loops=4", file=sys.stderr)
    arm1 = _c10k_arm(1)
    arm4 = _c10k_arm(4)

    print(json.dumps({
        "metric": f"frontdoor_c10k_proofs_per_sec_{BIT_LENGTH}bit",
        "value": round(arm4["proofs_per_sec"], 2),
        "unit": (f"proofs/s served, {n_conns} mixed batch/legacy conns "
                 f"(n_loops=4; n_loops=1 arm "
                 f"{arm1['proofs_per_sec']:.0f}/s; accept p99 "
                 f"{arm4['accept_p99_s'] * 1e3:.1f}ms vs "
                 f"{arm1['accept_p99_s'] * 1e3:.1f}ms; parity "
                 f"{arm4['parity_errors']}/{arm1['parity_errors']}; "
                 f"lost {arm4['lost']}/{arm1['lost']}; midframe "
                 f"{arm4['midframe_closes']}/{arm1['midframe_closes']})"),
    }))
    for arm in (arm1, arm4):
        nl = arm["n_loops"]
        assert arm["parity_errors"] == 0, \
            f"n_loops={nl}: {arm['parity_errors']} verdict parity errors"
        assert arm["lost"] == 0, \
            f"n_loops={nl}: {arm['lost']} lost requests"
        assert arm["midframe_closes"] == 0, \
            f"n_loops={nl}: {arm['midframe_closes']} mid-frame closes"
        assert arm["ownership_violations"] == 0, \
            f"n_loops={nl}: cross-loop writes detected"
        assert arm["frame_errors"] == 0, \
            f"n_loops={nl}: {arm['frame_errors']} frame errors"
        assert arm["accept_p99_s"] <= accept_p99_bar, (
            f"n_loops={nl}: accept->WELCOME p99 "
            f"{arm['accept_p99_s']:.3f}s above the "
            f"{accept_p99_bar:.1f}s bar")
    assert arm4["proofs_per_sec"] >= min_pps, (
        f"C10k sharded arm {arm4['proofs_per_sec']:.0f} proofs/s below "
        f"the {min_pps:.0f}/s bar")


def _bench_prove():
    """BENCH_MODE=prove — device proof SYNTHESIS throughput: seeded
    witnesses stream through ``prover.DeviceRangeProver`` in fused
    chunks; reports proofs/s, the XLA cost analysis of the prove chunk
    program, and the speedup over the host prover's measured wall-clock
    (the "as fast as we verify" bar shares TARGET_BASELINE). A seeded
    spot sample of the synthesized proofs (plus one forged row) is
    checked against the pure-host verifier."""
    import random

    from fabric_token_sdk_tpu.crypto import bn254, rp, setup
    from fabric_token_sdk_tpu.harness.corpus import _seeded_draws
    from fabric_token_sdk_tpu.prover import DeviceRangeProver

    pp = setup.PublicParams.deserialize((BENCH_DIR / "pp.json").read_bytes())
    rpp = pp.range_proof_params
    cg = pp.pedersen_generators[1:3]
    total = int(os.environ.get("BENCH_PROVE_COUNT", "256"))
    chunk = int(os.environ.get("BENCH_PROVE_CHUNK", "64"))
    rng = random.Random(int(os.environ.get("BENCH_PROVE_SEED", "17")))
    values = [rng.randrange(1 << BIT_LENGTH) for _ in range(total)]
    bfs = [rng.randrange(1, bn254.R) for _ in range(total)]
    draws = [_seeded_draws(rng, BIT_LENGTH) for _ in range(total)]

    prover = DeviceRangeProver(pp, chunk_rows=chunk)
    print(f"prove bench: warm-up chunk ({chunk} rows)", file=sys.stderr)
    t0 = time.perf_counter()
    prover.prove(values[:chunk], bfs[:chunk], draws=draws[:chunk])
    prewarm_s = time.perf_counter() - t0
    print(f"prove bench: warm-up in {prewarm_s:.1f}s; timing {total} "
          f"proofs", file=sys.stderr)

    t0 = time.perf_counter()
    proofs, coms = prover.prove(values, bfs, draws=draws)
    elapsed = time.perf_counter() - t0
    value = total / elapsed

    # host prover wall-clock on the same witnesses (a couple of rows)
    t0 = time.perf_counter()
    host_rows = 2
    for i in range(host_rows):
        rp.range_prove(coms[i], values[i], cg, bfs[i],
                       rpp.left_generators, rpp.right_generators,
                       rpp.P, rpp.Q, rpp.number_of_rounds,
                       rpp.bit_length, draws=draws[i])
    host_s = (time.perf_counter() - t0) / host_rows
    speedup = host_s * value               # host s/proof * proofs/s

    # spot verification: a clean row accepts, a forged row rejects
    def _host_ok(proof, com):
        try:
            rp.range_verify(proof, com, cg, rpp.left_generators,
                            rpp.right_generators, rpp.P, rpp.Q,
                            rpp.number_of_rounds, rpp.bit_length)
            return True
        except rp.ProofError:
            return False

    assert _host_ok(proofs[0], coms[0]), "prove bench: clean row rejected"
    fp, fc = prover.prove([(1 << BIT_LENGTH) + 1], [bfs[0]],
                          draws=[draws[0]], forge=True)
    assert not _host_ok(fp[0], fc[0]), "prove bench: forged row accepted"

    cost = prover.kernel_cost(rows=chunk) or {}
    print(json.dumps({
        "metric": f"prove_prewarm_wall_seconds_{BIT_LENGTH}bit",
        "value": round(prewarm_s, 2),
        "unit": f"s (chunk {chunk} rows)",
    }))
    print(json.dumps({
        "metric": f"prove_proofs_per_sec_{BIT_LENGTH}bit",
        "value": round(value, 2),
        "unit": (f"proofs/s synthesized ({total} proofs, chunk {chunk}; "
                 f"host {host_s:.2f}s/proof -> {speedup:.0f}x; "
                 f"chunk flops {cost.get('flops', 0):.3g}"),
        "vs_baseline": round(value / TARGET_BASELINE, 4),
    }))


def _bench_replay():
    """BENCH_MODE=replay — BASELINE config 5 at fleet scale: the 100k
    range-proof backlog replay, open-loop through the MULTI-LANE serve
    frontend. The benchdata corpus is tiled and re-randomized (seeded
    per-request draw, seeded forgery interleave) into a
    ``BENCH_REPLAY_PROOFS``-long stream; a Poisson arrival schedule at
    ``BENCH_REPLAY_RATE`` req/s submits every proof to a
    ``VerificationService`` with ``n_lanes = BENCH_REPLAY_LANES`` device
    dispatch lanes (default: one per visible device), so batches overlap
    across every lane instead of serializing on one dispatcher.

    Reports aggregate proofs/s plus per-lane dispatch counts and
    utilization (lane busy wall / run wall), and asserts verdict parity
    two ways: every verdict against the seeded clean/forged expectation,
    and a spot sample against the pure-host ``rp.range_verify`` oracle
    (accepts AND rejects)."""
    import asyncio
    import copy
    import random

    import jax

    from fabric_token_sdk_tpu.core.zkatdlog.verifier import ZKVerifier
    from fabric_token_sdk_tpu.crypto import rp
    from fabric_token_sdk_tpu.harness.txgen import open_loop_arrivals
    from fabric_token_sdk_tpu.serve import (STATUS_DEADLINE_MISS, STATUS_OK,
                                            ServeConfig, VerificationService)

    pp, proofs, coms = _load()
    total = int(os.environ.get("BENCH_REPLAY_PROOFS", "100000"))
    rate = float(os.environ.get("BENCH_REPLAY_RATE", "4000"))
    # BENCH_REPLAY_SOURCE=prover: the replay stream draws from a corpus
    # the device prover synthesized (diverse seeded values incl. the
    # range edges) instead of tiling the 4 benchdata proofs; forged rows
    # come from seeded out-of-range witnesses with their OWN commitments
    # rather than a tau-tampered copy.
    replay_source = os.environ.get("BENCH_REPLAY_SOURCE", "benchdata")
    forged_pool: list = []
    if replay_source == "prover":
        from fabric_token_sdk_tpu.harness.corpus import ProofCorpus

        seed = int(os.environ.get("BENCH_REPLAY_SEED", "17"))
        csize = int(os.environ.get("BENCH_REPLAY_CORPUS", "1024"))
        corpus = ProofCorpus(pp, source="device", seed=seed)
        print(f"replay bench: synthesizing {csize}-proof corpus "
              f"(+8 forged) on device", file=sys.stderr)
        entries = corpus.generate(csize)
        proofs = [e.proof for e in entries]
        coms = [e.commitment for e in entries]
        forged_pool = ProofCorpus(pp, source="device", seed=seed + 1,
                                  forge_every=1).generate(8)
        corpus_prov = dict(corpus.provenance(), count=csize,
                           forged_pool=len(forged_pool))
    elif replay_source == "benchdata":
        corpus_prov = {"source": "benchdata", "count": len(proofs),
                       "forged_pool": 0}
    else:
        raise SystemExit(f"unknown BENCH_REPLAY_SOURCE: {replay_source!r}")
    n_lanes = (int(os.environ.get("BENCH_REPLAY_LANES", "0"))
               or max(1, len(jax.devices())))
    buckets = tuple(int(b) for b in os.environ.get(
        "BENCH_SERVE_BUCKETS", "16,128,256,512,1024").split(","))
    cfg = ServeConfig(
        buckets=buckets,
        max_wait_s=float(os.environ.get("BENCH_SERVE_WAIT", "0.025")),
        default_deadline_s=float(os.environ.get("BENCH_REPLAY_DEADLINE",
                                                "120.0")),
        trace_every=0,                       # 100k spans would swamp RAM
        n_lanes=n_lanes)
    zk = ZKVerifier(pp, device=True)
    _configure_bench_journal()
    svc = VerificationService(zk, config=cfg)
    telemetry = _start_bench_telemetry(svc)
    n = len(proofs)
    forged = copy.deepcopy(proofs[0])
    forged.data.tau = (forged.data.tau + 1) % (1 << 250)
    FORGE_EVERY = 101
    # re-randomized stream: seeded per-request corpus draw, so the lane
    # batches mix corpus entries instead of replaying them in phase
    draw = random.Random(13)
    picks = [draw.randrange(n) for _ in range(total)]

    def _forged_req(i):
        """(proof, commitment) for a forged submission: a prover-corpus
        out-of-range entry when available, the tau-tampered copy (paired
        with a mismatched commitment) for the benchdata source."""
        if forged_pool:
            e = forged_pool[picks[i] % len(forged_pool)]
            return e.proof, e.commitment
        return forged, coms[picks[i]]

    def _host_verdict(proof, com) -> bool:
        rpp = pp.range_proof_params
        cg = pp.pedersen_generators[1:3]
        try:
            rp.range_verify(proof, com, cg, rpp.left_generators,
                            rpp.right_generators, rpp.P, rpp.Q,
                            rpp.number_of_rounds, rpp.bit_length)
            return True
        except rp.ProofError:
            return False

    async def run():
        print(f"replay bench: prewarming {len(cfg.buckets)} buckets "
              f"x {n_lanes} lanes", file=sys.stderr)
        prewarm_s = await svc.start()
        print(f"replay bench: prewarm in {prewarm_s:.1f}s", file=sys.stderr)
        # spot parity vs the pure-host oracle, accepts AND rejects
        fp0, fc0 = _forged_req(0)
        spot_p = [fp0] + proofs[:3]
        spot_c = [fc0] + coms[:3]
        host = [_host_verdict(p, c) for p, c in zip(spot_p, spot_c)]
        got = await asyncio.gather(*[
            svc.submit_range(p, c) for p, c in zip(spot_p, spot_c)])
        assert [r.accepted for r in got] == host, \
            "replay verdicts diverge from the host oracle"
        duration = total / rate
        arrivals = open_loop_arrivals(rate, duration * 1.1, seed=11)[:total]
        while len(arrivals) < total:       # top up to exactly `total`
            arrivals.append((arrivals[-1] if arrivals else 0.0) + 1.0 / rate)
        print(f"replay bench: open loop, {total} proofs at {rate:.0f}/s "
              f"over {n_lanes} lanes", file=sys.stderr)
        loop = asyncio.get_running_loop()
        t0 = loop.time()

        async def one(i, offset):
            delay = t0 + offset - loop.time()
            if delay > 0:
                await asyncio.sleep(delay)
            if i % FORGE_EVERY == 0:
                fp, fc = _forged_req(i)
                return await svc.submit_range(fp, fc)
            return await svc.submit_range(proofs[picks[i]], coms[picks[i]])

        results = await asyncio.gather(
            *[one(i, off) for i, off in enumerate(arrivals)])
        elapsed = loop.time() - t0
        lanes_status = svc.status()["lanes"]
        await svc.stop(timeout_s=300.0)
        return prewarm_s, results, elapsed, lanes_status

    prewarm_s, results, elapsed, lanes_status = asyncio.run(run())
    if telemetry is not None:
        telemetry.stop()
    served = [r for r in results
              if r.status in (STATUS_OK, STATUS_DEADLINE_MISS)
              and r.accepted is not None]
    # forged rows reject, everything else accepts: any divergence means a
    # lane's sharded/batched verdict disagrees with ground truth
    parity_bad = sum(
        1 for i, r in enumerate(results)
        if r.accepted is not None
        and r.accepted != (i % FORGE_EVERY != 0))
    lanes_used = sorted({r.device_lane for r in served if r.device_lane >= 0})
    util = {str(ls["index"]): round(ls["busy_s"] / elapsed, 3)
            for ls in lanes_status}
    dispatches = {str(ls["index"]): ls["dispatches"] for ls in lanes_status}
    ok = [r for r in results if r.status == STATUS_OK]
    value = len(served) / elapsed
    print(json.dumps({
        "metric": f"replay_prewarm_wall_seconds_{BIT_LENGTH}bit",
        "value": round(prewarm_s, 2),
        "unit": f"s ({len(cfg.buckets)} buckets x {n_lanes} lanes)",
    }))
    print(json.dumps({
        "metric": f"replay{total}_multilane_proofs_per_sec_{BIT_LENGTH}bit",
        "value": round(value, 2),
        "unit": (f"proofs/s served ({len(served)}/{len(results)} verdicts, "
                 f"{len(ok)} in deadline; {n_lanes} lanes, "
                 f"used {lanes_used}; dispatches {dispatches}; "
                 f"utilization {util}; parity errors {parity_bad})"),
        "vs_baseline": round(value / TARGET_BASELINE, 4),
        "corpus": corpus_prov,
    }))
    assert parity_bad == 0, \
        "replay bench: verdict parity broken across lanes"
    assert len(lanes_used) == n_lanes or len(served) < n_lanes, \
        f"replay bench: only lanes {lanes_used} of {n_lanes} served traffic"


def _bench_chaos():
    """BENCH_MODE=chaos: the serve bench under a seeded fault schedule.

    A FaultInjector shims the device entry points with ~10% transient
    faults (plus optional stalls / permanent faults, env-tunable) while
    an open-loop Poisson arrival stream submits range requests through a
    resilient VerificationService (retry + breaker + watchdog + host
    fallback). Reports availability (fraction of requests that got a
    verdict), p99 under faults, the fraction served by the host
    fallback, and verdict bit-parity against the fault-free expectation
    (a seeded slice of the arrivals submits a forged proof, so parity is
    checked on both accepts and rejects). Same seeds → same fault
    schedule → reproducible run."""
    import asyncio
    import copy

    from fabric_token_sdk_tpu.core.zkatdlog.verifier import ZKVerifier
    from fabric_token_sdk_tpu.harness.txgen import open_loop_arrivals
    from fabric_token_sdk_tpu.obs import GLOBAL as METRICS
    from fabric_token_sdk_tpu.resilience import (FaultInjector,
                                                 ResilienceConfig)
    from fabric_token_sdk_tpu.serve import (SERVED_BY_HOST,
                                            STATUS_DEADLINE_MISS, STATUS_OK,
                                            ServeConfig, VerificationService)

    pp, proofs, coms = _load()
    rate = float(os.environ.get("BENCH_CHAOS_RATE", "1000"))
    duration = float(os.environ.get("BENCH_CHAOS_SECONDS", "30"))
    fault_rate = float(os.environ.get("BENCH_CHAOS_FAULT", "0.10"))
    stall_rate = float(os.environ.get("BENCH_CHAOS_STALL", "0.0"))
    seed = int(os.environ.get("BENCH_CHAOS_SEED", "7"))
    buckets = tuple(int(b) for b in os.environ.get(
        "BENCH_SERVE_BUCKETS", "16,128,256,512,1024").split(","))
    cfg = ServeConfig(
        buckets=buckets,
        max_wait_s=float(os.environ.get("BENCH_SERVE_WAIT", "0.025")),
        default_deadline_s=float(os.environ.get("BENCH_SERVE_DEADLINE",
                                                "5.0")))
    resil = ResilienceConfig(retry_attempts=4, retry_base_s=0.002,
                             retry_cap_s=0.05, seed=seed,
                             breaker_reset_s=1.0,
                             watchdog_timeout_s=120.0)
    zk = ZKVerifier(pp, device=True)
    injector = FaultInjector(seed=seed, transient_rate=fault_rate,
                             stall_rate=stall_rate, stall_s=0.02)
    faulty = injector.wrap(zk)
    # SLO gauges ride along, but the breaker stays driven by its own
    # failure accounting (no bind_breaker): a fast-burn force-open would
    # change the fault-recovery behaviour the chaos bench measures.
    from fabric_token_sdk_tpu.obs import SloMonitor
    _configure_bench_journal()
    svc = VerificationService(faulty, config=cfg, resilience=resil,
                              slo=SloMonitor())
    telemetry = _start_bench_telemetry(svc)
    n = len(proofs)
    forged = copy.deepcopy(proofs[0])
    forged.data.tau = (forged.data.tau + 1) % (1 << 250)
    # fault-free expectation: the corpus verifies, the forgery does not
    FORGE_EVERY = 97

    async def run():
        print(f"chaos bench: prewarming {len(cfg.buckets)} buckets",
              file=sys.stderr)
        prewarm_s = await svc.start()
        arrivals = open_loop_arrivals(rate, duration, seed=11)
        print(f"chaos bench: open loop, {len(arrivals)} arrivals over "
              f"{duration:.0f}s at transient_rate={fault_rate}",
              file=sys.stderr)
        loop = asyncio.get_running_loop()
        t0 = loop.time()

        async def one(i, offset):
            delay = t0 + offset - loop.time()
            if delay > 0:
                await asyncio.sleep(delay)
            if i % FORGE_EVERY == 0:
                return await svc.submit_range(forged, coms[0])
            return await svc.submit_range(proofs[i % n], coms[i % n])

        results = await asyncio.gather(
            *[one(i, off) for i, off in enumerate(arrivals)])
        elapsed = loop.time() - t0
        await svc.stop(timeout_s=60.0)
        return prewarm_s, results, elapsed

    prewarm_s, results, elapsed = asyncio.run(run())
    if telemetry is not None:
        telemetry.stop()
    total = len(results)
    served = [r for r in results if r.status in (STATUS_OK,
                                                STATUS_DEADLINE_MISS)
              and r.accepted is not None]
    # availability per the acceptance definition: every request reached a
    # non-error terminal status (errors and shutdowns are the outages;
    # sheds and misses are explicit policy, not unavailability)
    errors = sum(r.status in ("error", "shutdown") for r in results)
    availability = (total - errors) / total if total else 0.0
    fallback_frac = (sum(r.served_by == SERVED_BY_HOST for r in served)
                     / len(served)) if served else 0.0
    parity_bad = sum(
        1 for i, r in enumerate(results)
        if r.accepted is not None
        and r.accepted != (i % FORGE_EVERY != 0))
    ok = [r for r in results if r.status == STATUS_OK]
    lat = sorted(r.total_s for r in ok) or [0.0]
    p50 = lat[len(lat) // 2]
    p99 = lat[min(len(lat) - 1, int(0.99 * len(lat)))]
    snap = METRICS.snapshot()

    def fam(name):
        return sum(v for (fam_name, _), v in snap.items()
                   if fam_name == name)

    print(json.dumps({
        "metric": f"chaos_availability_{BIT_LENGTH}bit",
        "value": round(availability, 6),
        "unit": (f"non-error terminal fraction ({total - errors}/{total}; "
                 f"{len(served)} with verdicts; "
                 f"transient_rate={fault_rate} stall_rate={stall_rate} "
                 f"seed={seed}; injected "
                 f"{int(fam('resil_injected_faults_total'))} faults, "
                 f"{int(fam('resil_retries_total'))} retries, "
                 f"{int(fam('resil_fallback_batches_total'))} fallback "
                 f"batches, {int(fam('resil_watchdog_trips_total'))} "
                 "watchdog trips)"),
    }))
    print(json.dumps({
        "metric": f"chaos_p99_seconds_{BIT_LENGTH}bit",
        "value": round(p99, 4),
        "unit": (f"s (p50 {p50 * 1e3:.1f}ms; prewarm {prewarm_s:.1f}s; "
                 f"{len(ok) / elapsed:.0f} req/s served under faults)"),
    }))
    print(json.dumps({
        "metric": f"chaos_fallback_fraction_{BIT_LENGTH}bit",
        "value": round(fallback_frac, 6),
        "unit": "fraction of served requests answered by the host path",
    }))
    print(json.dumps({
        "metric": f"chaos_verdict_parity_errors_{BIT_LENGTH}bit",
        "value": parity_bad,
        "unit": (f"verdicts diverging from the fault-free expectation "
                 f"(0 == bit-identical; {total} requests)"),
    }))
    assert parity_bad == 0, "chaos bench: verdict parity broken under faults"


def _crash_worker_factory():
    """Picklable sidecar factory for BENCH_MODE=crash: the spawn context
    re-imports this module in the child and calls this to build the
    device verifier there. Caches are configured from the inherited env
    — which a cold restart has already cleared."""
    _configure_jax_cache()
    from fabric_token_sdk_tpu.core.zkatdlog.verifier import ZKVerifier
    from fabric_token_sdk_tpu.crypto import setup

    pp = setup.PublicParams.deserialize((BENCH_DIR / "pp.json").read_bytes())
    return ZKVerifier(pp, device=True)


def _bench_crash():
    """BENCH_MODE=crash: the serve bench under a seeded kill schedule.

    The device backend runs as a supervised sidecar process with the
    request WAL armed — a multiprocessing pipe worker (serve/worker.py)
    by default, or the TCP RPC sidecar (serve/sidecar.py) with a
    reconnecting RpcClient under BENCH_CRASH_TRANSPORT=tcp. While an
    open-loop arrival stream submits range requests, a seeded
    KillSchedule SIGKILLs and SIGSTOPs the sidecar mid-load; the
    supervisor detects the exit / heartbeat stall and restarts it while
    traffic rides the host fallback (degraded, never down) and, on tcp,
    the client redials through its decorrelated-jitter ladder. Reports
    availability, p99 under kills, RTO per recovery, and the WAL
    accounting — then runs a replay drill: admit a burst, abort the
    service mid-flight (simulated crash), and let a successor service
    over the same WAL directory replay every incomplete request to a
    bit-identical verdict with exactly-once terminal accounting. Same
    seeds → same kill schedule → reproducible run."""
    import asyncio
    import copy
    import shutil

    from fabric_token_sdk_tpu.harness.txgen import open_loop_arrivals
    from fabric_token_sdk_tpu.obs import GLOBAL as METRICS
    from fabric_token_sdk_tpu.obs import SloMonitor
    from fabric_token_sdk_tpu.resilience import (ChildSpec, KillSchedule,
                                                 ResilienceConfig, Supervisor,
                                                 SupervisorPolicy)
    from fabric_token_sdk_tpu.serve import (SERVED_BY_HOST,
                                            STATUS_DEADLINE_MISS, STATUS_OK,
                                            ServeConfig, VerificationService,
                                            WorkerClient, WriteAheadLog)

    pp, proofs, coms = _load()
    rate = float(os.environ.get("BENCH_CRASH_RATE", "200"))
    duration = float(os.environ.get("BENCH_CRASH_SECONDS", "30"))
    seed = int(os.environ.get("BENCH_CRASH_SEED", "7"))
    kills = int(os.environ.get("BENCH_CRASH_KILLS", "2"))
    stops = int(os.environ.get("BENCH_CRASH_STOPS", "1"))
    stall_s = float(os.environ.get("BENCH_CRASH_STALL_DEADLINE", "2.0"))
    replay_n = int(os.environ.get("BENCH_CRASH_REPLAY", "96"))
    buckets = tuple(int(b) for b in os.environ.get(
        "BENCH_SERVE_BUCKETS", "16,128,256,512,1024").split(","))
    cfg = ServeConfig(
        buckets=buckets,
        max_wait_s=float(os.environ.get("BENCH_SERVE_WAIT", "0.025")),
        default_deadline_s=float(os.environ.get("BENCH_SERVE_DEADLINE",
                                                "15.0")))
    resil = ResilienceConfig(retry_attempts=4, retry_base_s=0.002,
                             retry_cap_s=0.05, seed=seed,
                             breaker_reset_s=1.0,
                             watchdog_timeout_s=120.0)

    wal_root = BENCH_DIR / "crash_wal"
    shutil.rmtree(wal_root, ignore_errors=True)
    hb_path = str(BENCH_DIR / "crash_worker.hb.jsonl")
    transport = os.environ.get("BENCH_CRASH_TRANSPORT", "pipe")
    call_timeout_s = float(os.environ.get("BENCH_CRASH_CALL_TIMEOUT", "60"))

    _configure_bench_journal()
    if transport == "tcp":
        # real network boundary: the whole serving backend lives in the
        # TCP sidecar process; the bench dials it with a reconnecting
        # RpcClient that matches the zk duck-type, so everything below
        # (service, WAL, fallback ladder) is transport-agnostic
        from fabric_token_sdk_tpu.serve import RpcClient, RpcSidecar

        sidecar = RpcSidecar(
            _crash_worker_factory, heartbeat_path=hb_path,
            buckets=buckets, prewarm=True, name="verify-worker")
        worker = RpcClient(sidecar.address, pp=pp, tms_id="bench",
                           call_timeout_s=call_timeout_s,
                           name="verify-worker")
    elif transport == "pipe":
        sidecar = None
        worker = WorkerClient(
            _crash_worker_factory, pp=pp, heartbeat_path=hb_path,
            prewarm_buckets=buckets,
            call_timeout_s=call_timeout_s,
            name="verify-worker")
    else:
        raise SystemExit(f"unknown BENCH_CRASH_TRANSPORT {transport!r}")

    def _respawn(ctx=None):
        # clear the dead pid's stamps first: the stall watch would
        # otherwise trip on the stale "ready" beat while the fresh
        # worker is still importing (grace_s only covers an EMPTY file)
        try:
            os.remove(hb_path)
        except OSError:
            pass
        if sidecar is not None:
            return sidecar.spawn(ctx)
        return worker.spawn(ctx)

    def _get_pid():
        return sidecar.pid if sidecar is not None else worker.pid

    proc = _respawn()
    supervisor = Supervisor(
        policy=SupervisorPolicy(seed=seed, backoff_base_s=0.05,
                                backoff_cap_s=0.5,
                                cold_after=kills + stops + 2,
                                give_up_after=2 * (kills + stops) + 4),
        poll_s=0.1)
    supervisor.add_child(
        ChildSpec("verify-worker", start=_respawn,
                  heartbeat_file=hb_path,
                  # boot/prewarm legitimately take a while (bounded by
                  # the compile/table caches); only a frozen READY
                  # worker is a stall
                  deadlines={"boot": 600.0, "prewarm": 3600.0,
                             "ready": stall_s},
                  default_deadline_s=600.0, grace_s=120.0),
        handle=proc)
    supervisor.start()

    wal = WriteAheadLog(str(wal_root / "serve"))
    svc = VerificationService(worker, config=cfg, resilience=resil,
                              slo=SloMonitor(), wal=wal)
    telemetry = _start_bench_telemetry(svc, supervisor=supervisor)
    n = len(proofs)
    forged = copy.deepcopy(proofs[0])
    forged.data.tau = (forged.data.tau + 1) % (1 << 250)
    FORGE_EVERY = 97
    schedule = KillSchedule(seed=seed, duration_s=duration, kills=kills,
                            stops=stops)

    async def run():
        print(f"crash bench: worker prewarming {len(cfg.buckets)} buckets",
              file=sys.stderr)
        prewarm_s = await svc.start()
        arrivals = open_loop_arrivals(rate, duration, seed=11)
        print(f"crash bench: open loop, {len(arrivals)} arrivals over "
              f"{duration:.0f}s; kill schedule "
              f"{[(round(t, 1), s) for t, s in schedule.events]}",
              file=sys.stderr)
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        schedule.start(_get_pid)

        async def one(i, offset):
            delay = t0 + offset - loop.time()
            if delay > 0:
                await asyncio.sleep(delay)
            if i % FORGE_EVERY == 0:
                return await svc.submit_range(forged, coms[0])
            return await svc.submit_range(proofs[i % n], coms[i % n])

        results = await asyncio.gather(
            *[one(i, off) for i, off in enumerate(arrivals)])
        elapsed = loop.time() - t0
        schedule.cancel()
        await svc.stop(timeout_s=120.0)
        return prewarm_s, results, elapsed

    prewarm_s, results, elapsed = asyncio.run(run())
    total = len(results)
    served = [r for r in results if r.status in (STATUS_OK,
                                                STATUS_DEADLINE_MISS)
              and r.accepted is not None]
    errors = sum(r.status in ("error", "shutdown") for r in results)
    availability = (total - errors) / total if total else 0.0
    fallback_frac = (sum(r.served_by == SERVED_BY_HOST for r in served)
                     / len(served)) if served else 0.0
    parity_bad = sum(
        1 for i, r in enumerate(results)
        if r.accepted is not None
        and r.accepted != (i % FORGE_EVERY != 0))
    lost = wal.open_count          # admits without a terminal resolve
    ok = [r for r in results if r.status == STATUS_OK]
    lat = sorted(r.total_s for r in ok) or [0.0]
    p50 = lat[len(lat) // 2]
    p99 = lat[min(len(lat) - 1, int(0.99 * len(lat)))]
    rto = METRICS.histogram("crash_rto_seconds", child="verify-worker")
    snap = METRICS.snapshot()

    def fam(name):
        return sum(v for (fam_name, _), v in snap.items()
                   if fam_name == name)

    # ---- replay drill: admit a burst, crash mid-flight, replay -------
    # Requests queue behind a never-firing trigger (one oversized
    # bucket, hour-scale waits) so abort() leaves them all admitted but
    # unresolved; the successor service over the SAME WAL directory
    # must replay each to the ground-truth verdict exactly once.
    print("crash bench: replay drill", file=sys.stderr)
    REPLAY_FORGE = 7
    hold_cfg = ServeConfig(buckets=(max(buckets),), max_wait_s=3600.0,
                           default_deadline_s=3600.0)
    wal_a = WriteAheadLog(str(wal_root / "replay"))
    svc_a = VerificationService(worker, config=hold_cfg, resilience=resil,
                                wal=wal_a)

    async def crash_half():
        await svc_a.start(prewarm=False)
        tasks = []
        for i in range(replay_n):
            if i % REPLAY_FORGE == 0:
                tasks.append(asyncio.ensure_future(
                    svc_a.submit_range(forged, coms[0])))
            else:
                tasks.append(asyncio.ensure_future(
                    svc_a.submit_range(proofs[i % n], coms[i % n])))
        await asyncio.sleep(0.25)   # let every admit reach the WAL
        await svc_a.abort()         # simulated SIGKILL mid-flight
        for t in tasks:
            t.cancel()

    asyncio.run(crash_half())
    wal_a.close()

    wal_b = WriteAheadLog(str(wal_root / "replay"))
    svc_b = VerificationService(worker, config=cfg, resilience=resil,
                                wal=wal_b)

    async def recover_half():
        await svc_b.start(prewarm=False)   # start() awaits the replay
        await svc_b.stop(timeout_s=120.0)
        return svc_b.replayed

    replayed = asyncio.run(recover_half())
    # wal ids are assigned in admit order, so id i+1 carries request i
    replay_parity = sum(
        1 for wal_id, res in replayed
        if res.accepted != ((wal_id - 1) % REPLAY_FORGE != 0))
    replay_no_verdict = sum(1 for _, res in replayed
                            if res.accepted is None)
    snap2 = METRICS.snapshot()
    replay_dups = sum(
        v for (name, labels), v in snap2.items()
        if name == "wal_appends_total"
        and dict(labels).get("record") == "resolve_duplicate")
    if telemetry is not None:
        telemetry.stop()
    supervisor.stop()
    worker.stop()
    if sidecar is not None:
        sidecar.stop()
        # draining stops under load must never cut a frame in half;
        # the client-side counter would have recorded it
        tcp_frame_errors = sum(
            v for (name, labels), v in METRICS.snapshot().items()
            if name == "rpc_frame_errors_total"
            and dict(labels).get("kind") == "midframe_close")
        assert tcp_frame_errors == 0, \
            "crash bench: connection closed mid-frame"
    wal.close()
    wal_b.close()

    print(json.dumps({
        "metric": f"crash_availability_{BIT_LENGTH}bit",
        "value": round(availability, 6),
        "unit": (f"non-error terminal fraction ({total - errors}/{total}; "
                 f"seed={seed}; transport={transport}; injected "
                 f"{int(fam('crash_injected_signals_total'))} signals "
                 f"({kills} SIGKILL + {stops} SIGSTOP scheduled), "
                 f"{int(fam('crash_failures_total'))} failures detected, "
                 f"{int(fam('crash_restarts_total'))} restarts; "
                 f"fallback served {fallback_frac:.3f} of verdicts; "
                 f"{lost} requests lost)"),
    }))
    print(json.dumps({
        "metric": f"crash_p99_seconds_{BIT_LENGTH}bit",
        "value": round(p99, 4),
        "unit": (f"s (p50 {p50 * 1e3:.1f}ms; prewarm {prewarm_s:.1f}s; "
                 f"{len(ok) / elapsed:.0f} req/s served under kills)"),
    }))
    print(json.dumps({
        "metric": f"crash_rto_seconds_{BIT_LENGTH}bit",
        "value": round(rto.percentile(100.0), 4),
        "unit": (f"s worst recovery (mean {rto.mean:.3f}s over {rto.n} "
                 "recoveries: failure detection -> restarted worker's "
                 "first fresh heartbeat)"),
    }))
    print(json.dumps({
        "metric": f"crash_replayed_requests_{BIT_LENGTH}bit",
        "value": len(replayed),
        "unit": (f"requests replayed from the WAL after a mid-flight "
                 f"abort ({replay_parity} verdict mismatches, "
                 f"{replay_no_verdict} without verdicts, "
                 f"{int(replay_dups)} duplicate resolves, "
                 f"{wal_b.open_count} left unresolved)"),
    }))
    assert parity_bad == 0, "crash bench: verdict parity broken under kills"
    assert lost == 0, f"crash bench: {lost} admitted requests lost"
    assert replayed, "crash bench: replay drill recovered nothing"
    assert replay_parity == 0, "crash bench: replayed verdicts diverged"
    assert replay_no_verdict == 0, \
        "crash bench: replayed requests missing verdicts"
    assert replay_dups == 0, "crash bench: terminal accounting not exactly-once"
    assert wal_b.open_count == 0, \
        "crash bench: replayed requests left unresolved in the WAL"


def _bench_htlc():
    """BENCH_MODE=htlc — BASELINE config 4: an HTLC claim batch. Each
    swap claim pairs the host-side interop checks (script validation +
    hash-preimage comparison, the ownership leg of the script-owned
    token) with the claim transfer's device work (Σ + range proofs),
    routed through the serve scheduler's interactive lane — the lane
    HTLC traffic takes in production, since a claim races a deadline.
    Both TMS legs share one in-process pp (single-network stand-in for
    the cross-network swap)."""
    import asyncio
    import hashlib
    import pickle

    from fabric_token_sdk_tpu.core.zkatdlog.verifier import ZKVerifier
    from fabric_token_sdk_tpu.crypto import setup
    from fabric_token_sdk_tpu.serve import (LANE_INTERACTIVE, ServeConfig,
                                            VerificationService)
    from fabric_token_sdk_tpu.services.interop import htlc

    pp = setup.PublicParams.deserialize((BENCH_DIR / "pp.json").read_bytes())
    blob = pickle.loads((BENCH_DIR / f"block_{BIT_LENGTH}.pkl").read_bytes())
    base_t = blob["transfers"]
    total = int(os.environ.get("BENCH_HTLC", "512"))
    claims = (base_t * (total // len(base_t) + 1))[:total]
    # one script per claim; SHA256 preimage, hex-encoded image (the
    # reference's default claim framing)
    swaps = []
    for i in range(total):
        preimage = i.to_bytes(8, "big")
        info = htlc.HashInfo(
            hash=hashlib.sha256(preimage).hexdigest().encode())
        swaps.append((htlc.Script(sender=b"alice", recipient=b"bob",
                                  deadline=time.time() + 3600,
                                  hash_info=info), preimage))
    # action buckets 16/64: 64 transfers x 2 outputs = 128 range rows,
    # the same device bucket the 64-action prewarm compiles
    buckets = tuple(int(b) for b in os.environ.get(
        "BENCH_HTLC_BUCKETS", "16,64").split(","))
    cfg = ServeConfig(buckets=buckets, max_wait_s=0.01, prewarm_block=True)
    zk = ZKVerifier(pp, device=True)
    svc = VerificationService(zk, config=cfg)

    async def run():
        print(f"htlc bench: prewarming {len(cfg.buckets)} buckets "
              "(block path)", file=sys.stderr)
        prewarm_s = await svc.start()
        print(f"htlc bench: prewarm in {prewarm_s:.1f}s", file=sys.stderr)
        t0 = time.perf_counter()

        async def claim_one(i):
            script, preimage = swaps[i]
            script.validate(time_reference=time.time())
            script.hash_info.compare(script.hash_info.image(preimage))
            raw, ins, outs = claims[i]
            return await svc.submit_transfer(raw, ins, outs,
                                             lane=LANE_INTERACTIVE)

        results = await asyncio.gather(
            *[claim_one(i) for i in range(total)])
        elapsed = time.perf_counter() - t0
        await svc.stop()
        return prewarm_s, results, elapsed

    prewarm_s, results, elapsed = asyncio.run(run())
    assert all(r.ok and r.accepted for r in results), \
        "HTLC claim batch failed verification"
    n_proofs = total * 2  # 2 outputs -> 2 range proofs per claim
    print(json.dumps({
        "metric": f"htlc_prewarm_wall_seconds_{BIT_LENGTH}bit",
        "value": round(prewarm_s, 2),
        "unit": f"s ({len(cfg.buckets)} buckets incl block path)",
    }))
    print(json.dumps({
        "metric": f"config4_htlc_claims_per_sec_{BIT_LENGTH}bit",
        "value": round(total / elapsed, 2),
        "unit": (f"claims/s ({round(n_proofs / elapsed, 1)} proofs/s, "
                 f"{total} claims, interactive lane)"),
        "vs_baseline": round(n_proofs / elapsed / TARGET_BASELINE, 4),
    }))


def _write_obs_report() -> None:
    """With BENCH_OBS_OUT=<path> set, dump the observability registry
    (pipeline batch records, pad waste, compile counts, latency
    percentiles) next to the headline JSON line after any bench mode."""
    path = os.environ.get("BENCH_OBS_OUT")
    if not path:
        return
    from fabric_token_sdk_tpu.obs import write_bench_report

    write_bench_report(path, extra={"bench_batch": BATCH,
                                    "bit_length": BIT_LENGTH})
    print(f"bench: obs report written to {path}", file=sys.stderr)


def main():
    if "--regen" in sys.argv:
        _regen()
        return
    if "--regen-block" in sys.argv:
        _regen_block()
        return
    mode = os.environ.get("BENCH_MODE", "")
    if mode == "frontdoor":
        # stub-backed by default: measures the front door's ser/de
        # wall with no corpus or device compile (device mode loads
        # both itself)
        _bench_frontdoor()
        return

    if not (BENCH_DIR / f"proofs_{BIT_LENGTH}.bin").exists():
        _regen()

    if mode == "config1":
        _bench_config1()
        return

    _configure_jax_cache()

    if mode == "block":
        if not (BENCH_DIR / f"block_{BIT_LENGTH}.pkl").exists():
            _regen_block()
        _bench_block(int(os.environ.get("BENCH_BLOCK", "10000")))
        return

    if mode == "adversarial":
        _bench_adversarial()
        return

    if mode == "serve":
        _bench_serve()
        return

    if mode == "replay":
        _bench_replay()
        return

    if mode == "prove":
        _bench_prove()
        return

    if mode == "chaos":
        _bench_chaos()
        return

    if mode == "crash":
        _bench_crash()
        return

    if mode == "htlc":
        if not (BENCH_DIR / f"block_{BIT_LENGTH}.pkl").exists():
            _regen_block()
        _bench_htlc()
        return

    from fabric_token_sdk_tpu.models.range_verifier import BatchRangeVerifier

    pp, proofs, coms = _load()
    reps = (BATCH + len(proofs) - 1) // len(proofs)
    proofs = (proofs * reps)[:BATCH]
    coms = (coms * reps)[:BATCH]

    print(f"bench: corpus loaded, building verifier (tables)", file=sys.stderr)
    t0 = time.perf_counter()
    verifier = BatchRangeVerifier(pp)
    print(f"bench: tables built in {time.perf_counter()-t0:.1f}s; warm-up",
          file=sys.stderr)
    # Warm-up: compile both device passes.
    t0 = time.perf_counter()
    out = verifier.verify(proofs, coms)
    print(f"bench: warm-up verify in {time.perf_counter()-t0:.1f}s "
          f"(path={verifier.last_path})", file=sys.stderr)
    assert out.all(), "bench corpus failed verification"

    replay_total = int(os.environ.get("BENCH_REPLAY", "0"))
    if replay_total:
        value = _replay(verifier, proofs, coms, replay_total)
        print(json.dumps({
            "metric": f"range_proof_replay{replay_total}_per_sec_"
                      f"{BIT_LENGTH}bit",
            "value": round(value, 2),
            "unit": "proofs/s",
            "vs_baseline": round(value / TARGET_BASELINE, 4),
        }))
        return

    # steady state: aggregate over a few back-to-back batches (the first
    # post-warm-up call still pays one-off dispatch/allocator costs)
    reps = int(os.environ.get("BENCH_REPS", "3"))
    t0 = time.perf_counter()
    for _ in range(reps):
        out = verifier.verify(proofs, coms)
        assert out.all()
    elapsed = time.perf_counter() - t0

    value = reps * BATCH / elapsed
    print(json.dumps({
        "metric": f"range_proof_verifies_per_sec_{BIT_LENGTH}bit",
        "value": round(value, 2),
        "unit": "proofs/s",
        "vs_baseline": round(value / TARGET_BASELINE, 4),
    }))


if __name__ == "__main__":
    try:
        main()
    finally:
        _write_obs_report()
        _write_trace_out()
