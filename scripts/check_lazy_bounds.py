#!/usr/bin/env python
"""Lint: every lazy-carry producer at a readback boundary must normalize.

The lazy-carry limb discipline (ops/tfield.py rules R1-R4) keeps field
elements with limbs <= 2^16 and values < 2p BETWEEN ops, resolving
carries once per add-chain instead of once per add. The failure mode is
silent: a lazy value that escapes to a readback boundary — a Pallas
kernel's out_ref store, or a public mixed-fold entry point whose result
feeds byte serialization / transcript hashing — COMPARES unequal to its
canonical twin while being the same field element, breaking the
bit-identical verdict contract.

This lint walks the AST of the ops kernels (and every other module that
touches the lazy API) and enforces one function-level rule:

  a function that CALLS a lazy producer
      (add_lazy / sub_lazy / lazy_limbs / madd / madd_masked /
       add_zlazy) — directly, or transitively through helpers DEFINED IN
      THE SAME MODULE (the call set is closed over locally defined
      function names; cross-module attribute calls stay shallow)
  and sits at a readback boundary
      (stores to a ``*_ref`` — a Pallas kernel output — or is a public
      ``*_mixed`` fold entry point, or lives outside ops/)
  must also CALL a normalizer
      (normalize / normalize_point / carry_propagate / _carry_propagate
       / _cond_sub_mod)

A call to a ``*_mixed`` entry point additionally counts as lazy-API
usage (it makes the exact-pass / pass-2 kernels that consume the
round-7 lazified MSM interiors visible to the scan), and — only when
the function touches NO raw producer itself — as a normalization point:
the ``*_mixed`` entry points are canonical-out by contract (checked
here on their own defining module), so a caller that merely consumes
them is clean, while one that also leaks a raw ``add_lazy`` result
still needs its own normalizer.

Interior helpers (tec.add's lazy interior, madd itself) are exempt: they
are not boundaries — their canonical-out contracts are covered by the
parity/property tests, and madd's lazy-out contract is the point.

Runnable standalone (``python scripts/check_lazy_bounds.py`` — exits 1
with the offender list) and imported by tests/test_lazy_bounds_lint.py
as a tier-1 test.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
PKG = REPO / "fabric_token_sdk_tpu"

#: ops whose RESULT is in lazy form (limbs may reach 2^16 / value >= p)
LAZY_PRODUCERS = frozenset({
    "add_lazy", "sub_lazy", "lazy_limbs", "madd", "madd_masked",
    "add_zlazy",
})

#: ops that resolve carries AND reduce below p (canonicalization points)
NORMALIZERS = frozenset({
    "normalize", "normalize_point", "carry_propagate", "_carry_propagate",
    "_cond_sub_mod",
})


def _source_files() -> list[Path]:
    return sorted(PKG.rglob("*.py"))


def _called_names(fn: ast.AST) -> set[str]:
    """Bare or attribute-terminal names of every call inside ``fn``
    (``tec.madd(...)`` and ``madd(...)`` both yield ``madd``)."""
    out: set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute):
            out.add(f.attr)
        elif isinstance(f, ast.Name):
            out.add(f.id)
    return out


def _stores_to_ref(fn: ast.AST) -> bool:
    """True when the function assigns into a ``*_ref[...]`` subscript —
    the Pallas kernel output-write idiom (readback boundary)."""
    for node in ast.walk(fn):
        if not isinstance(node, (ast.Assign, ast.AugAssign)):
            continue
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for t in targets:
            if (isinstance(t, ast.Subscript)
                    and isinstance(t.value, ast.Name)
                    and t.value.id.endswith("_ref")):
                return True
    return False


def _functions(tree: ast.AST):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _closed_calls(fn: ast.AST, direct: dict[str, set[str]]) -> set[str]:
    """``fn``'s called names, closed transitively over helpers defined in
    the same module (``direct`` maps local function name -> its direct
    call set). Cross-module attribute calls stay shallow — the callee's
    module is linted on its own."""
    calls = set(_called_names(fn))
    frontier = [c for c in calls if c in direct]
    seen = {getattr(fn, "name", None)}
    while frontier:
        callee = frontier.pop()
        if callee in seen:
            continue
        seen.add(callee)
        new = direct[callee] - calls
        calls |= new
        frontier.extend(c for c in new if c in direct)
    return calls


def scan_boundaries() -> dict[str, dict]:
    """{``file::function``: info} for every function the lint treats as a
    readback boundary that calls into the lazy API. ``info`` carries the
    producer/normalizer call sets for reporting and the guard test."""
    found: dict[str, dict] = {}
    for path in _source_files():
        rel = path.relative_to(REPO)
        in_ops = rel.parts[:2] == ("fabric_token_sdk_tpu", "ops")
        try:
            tree = ast.parse(path.read_text())
        except SyntaxError:  # pragma: no cover - tree must stay parseable
            continue
        direct = {fn.name: _called_names(fn) for fn in _functions(tree)}
        for fn in _functions(tree):
            calls = _closed_calls(fn, direct)
            raw = calls & LAZY_PRODUCERS
            mixed = {c for c in calls
                     if c.endswith("_mixed") and c != fn.name}
            producers = raw | mixed
            if not producers:
                continue
            if fn.name in LAZY_PRODUCERS:
                continue  # the producers themselves are lazy-out by design
            boundary = (_stores_to_ref(fn)
                        or fn.name.endswith("_mixed")
                        or not in_ops)
            if not boundary:
                continue
            normalizers = calls & NORMALIZERS
            if not raw:
                # canonical-out *_mixed entry points self-normalize for
                # pure consumers; a raw producer leak still needs its own
                normalizers = normalizers | mixed
            found[f"{rel}::{fn.name}"] = {
                "line": fn.lineno,
                "producers": sorted(producers),
                "normalizers": sorted(normalizers),
            }
    return found


def find_offenders() -> dict[str, dict]:
    """Boundary functions using lazy producers without any normalizer."""
    return {name: info for name, info in scan_boundaries().items()
            if not info["normalizers"]}


def main() -> int:
    offenders = find_offenders()
    if offenders:
        print("lazy-carry values reach a readback boundary without a "
              "normalization point:", file=sys.stderr)
        for name, info in sorted(offenders.items()):
            print(f"  {name} (line {info['line']}): calls "
                  f"{','.join(info['producers'])} but none of "
                  f"{','.join(sorted(NORMALIZERS))}", file=sys.stderr)
        return 1
    n = len(scan_boundaries())
    print(f"ok: {n} lazy-API boundary function(s), all normalized")
    return 0


if __name__ == "__main__":
    sys.exit(main())
