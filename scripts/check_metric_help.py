#!/usr/bin/env python
"""Lint: every stable-family instrument registration must carry HELP text.

The obs registry is first-registration-wins for HELP lines, so a bare
``counter("serve_foo_total", ...)`` call silently ships ``# HELP
serve_foo_total serve_foo_total`` to every dashboard if it runs before
the describing call site. This lint scans the source for
``counter(`` / ``gauge(`` / ``histogram(`` registrations of stable
families (tests/test_metric_family_guard.py is the inventory) and
requires each registered family to have a HELP source somewhere:

  - an inline ``help=`` kwarg or positional help string at a
    registration site,
  - a ``describe("family", ...)`` call, or
  - an entry in a hoisted metadata dict (``"family": "help text"`` —
    the ``_SERVE_FAMILIES`` / ``_TTX_FAMILIES`` pattern).

Runnable standalone (``python scripts/check_metric_help.py`` — exits 1
with the offender list) and imported by tests/test_metric_help_lint.py
as a tier-1 test.
"""

from __future__ import annotations

import importlib.util
import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def _stable_families() -> tuple:
    spec = importlib.util.spec_from_file_location(
        "_metric_family_guard",
        REPO / "tests" / "test_metric_family_guard.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.STABLE_FAMILIES


def _source_files() -> list[Path]:
    files = [REPO / "bench.py"]
    files.extend(sorted((REPO / "fabric_token_sdk_tpu").rglob("*.py")))
    return files


def _registration_re(fam: str) -> re.Pattern:
    # counter( / gauge( / histogram( with the family as first argument;
    # \s crosses newlines, covering black-style wrapped calls
    return re.compile(
        r"(?:counter|gauge|histogram)\(\s*['\"]" + re.escape(fam)
        + r"['\"]")


def _help_res(fam: str) -> list[re.Pattern]:
    q = re.escape(fam)
    return [
        # inline: name followed by help= kwarg or a positional string
        # (optionally parenthesized for multi-line literals)
        re.compile(r"(?:counter|gauge|histogram)\(\s*['\"]" + q
                   + r"['\"]\s*,\s*(?:help\s*=\s*)?['\"(]"),
        # explicit describe("family", ...)
        re.compile(r"describe\(\s*['\"]" + q + r"['\"]"),
        # hoisted metadata dict entry: "family": "help" / ("help...
        re.compile(r"['\"]" + q + r"['\"]\s*:\s*['\"(]"),
    ]


def find_offenders() -> dict[str, list[str]]:
    """{family: [file:line of each registration]} for every stable
    family registered via an instrument call but lacking any HELP
    source."""
    sources = [(p, p.read_text()) for p in _source_files()]
    corpus = "\n".join(text for _, text in sources)
    offenders: dict[str, list[str]] = {}
    for fam in _stable_families():
        reg_re = _registration_re(fam)
        if not reg_re.search(corpus):
            continue  # never registered via instrument calls (dynamic)
        if any(rx.search(corpus) for rx in _help_res(fam)):
            continue
        sites = []
        for path, text in sources:
            for m in reg_re.finditer(text):
                line = text.count("\n", 0, m.start()) + 1
                sites.append(f"{path.relative_to(REPO)}:{line}")
        offenders[fam] = sites
    return offenders


def main() -> int:
    offenders = find_offenders()
    if not offenders:
        print("check_metric_help: every registered stable family has "
              "HELP text")
        return 0
    print("stable metric families registered without HELP text "
          "(add help=..., describe(), or a metadata-dict entry):")
    for fam, sites in sorted(offenders.items()):
        print(f"  {fam}")
        for site in sites:
            print(f"    {site}")
    return 1


if __name__ == "__main__":
    sys.exit(main())
