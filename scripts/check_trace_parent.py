#!/usr/bin/env python
"""Lint: serve-side spans in frame handlers must join the caller's trace.

The cross-process tracing contract (serve/rpc.py v3) is only useful if
every server-side span created while handling an RPC frame passes the
extracted wire context as ``remote_parent=``. A handler that opens
``tracer.span("rpc.serve", ...)`` WITHOUT the kwarg silently forks a
fresh trace — the fleet assembly then shows the client's ``rpc.call``
and the server's work as two unrelated traces, which is exactly the
regression this lint exists to catch (it passes tests: nothing crashes,
the trace is just disconnected).

Contract enforced by AST scan:

  - ``serve/rpc.py`` and ``serve/worker.py``: EVERY span creation
    (``.span(...)`` / ``.start_span(...)``) whose name literal is
    ``rpc.serve`` or ``rpc.serve_batch`` must carry a
    ``remote_parent=`` keyword.
  - ``serve/service.py``: at least one ``serve.request`` creation site
    must carry ``remote_parent=`` (the trace_ctx-driven branch; the
    locally-sampled branch legitimately starts its own trace).

Runnable standalone (``python scripts/check_trace_parent.py`` — exits 1
with the offender list) and imported by tests/test_trace_guard.py as a
tier-1 test.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
SERVE = REPO / "fabric_token_sdk_tpu" / "serve"

#: files whose rpc.serve/rpc.serve_batch spans must ALL be remote-parented
_STRICT_FILES = ("rpc.py", "worker.py")
_STRICT_NAMES = ("rpc.serve", "rpc.serve_batch")


def _span_calls(tree: ast.AST):
    """Yield ``(span_name, lineno, has_remote_parent)`` for every
    ``<obj>.span("name", ...)`` / ``<obj>.start_span("name", ...)``
    call with a string-literal first argument."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not (isinstance(func, ast.Attribute)
                and func.attr in ("span", "start_span")):
            continue
        if not (node.args and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            continue
        has_rp = any(kw.arg == "remote_parent" for kw in node.keywords)
        yield node.args[0].value, node.lineno, has_rp


def find_offenders() -> list[str]:
    """Human-readable offender list (empty when the contract holds)."""
    offenders: list[str] = []
    for fname in _STRICT_FILES:
        path = SERVE / fname
        tree = ast.parse(path.read_text())
        for name, lineno, has_rp in _span_calls(tree):
            if name in _STRICT_NAMES and not has_rp:
                offenders.append(
                    f"{path.relative_to(REPO)}:{lineno}: span "
                    f"'{name}' created without remote_parent=")
    svc = SERVE / "service.py"
    svc_calls = [c for c in _span_calls(ast.parse(svc.read_text()))
                 if c[0] == "serve.request"]
    if not svc_calls:
        offenders.append(f"{svc.relative_to(REPO)}: no 'serve.request' "
                         "span creation found")
    elif not any(has_rp for _, _, has_rp in svc_calls):
        offenders.append(
            f"{svc.relative_to(REPO)}: no 'serve.request' creation "
            "site passes remote_parent= (trace_ctx branch missing)")
    return offenders


def main() -> int:
    offenders = find_offenders()
    if not offenders:
        print("check_trace_parent: every serve-side frame-handler span "
              "joins the caller's trace")
        return 0
    print("serve-side spans that fork a fresh trace instead of joining "
          "the caller's (pass remote_parent=ctx):")
    for line in offenders:
        print(f"  {line}")
    return 1


if __name__ == "__main__":
    sys.exit(main())
