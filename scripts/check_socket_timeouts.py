#!/usr/bin/env python
"""Lint: every blocking socket/pipe wait in the serving plane must
carry an explicit timeout or deadline.

A hung read with no deadline is how rc=124-with-no-diagnosis comes
back: the process is alive, the stack is parked in recv, and nothing
ever reports why. This lint walks the AST of the network-facing
modules (``serve/``, ``resilience/``, ``obs/telemetry.py``,
``obs/aggregate.py``) and flags two classes of unbounded wait:

  1. **Sync waits** — calls to ``.poll`` / ``.wait`` / ``.join`` /
     ``.get`` with no positional argument and no ``timeout``/
     ``timeout_s`` kwarg. Exempt: calls under ``await`` (asyncio
     primitives are cancellable; their deadline is the enclosing task's
     ``wait_for`` or supervisor), and dict-style lookups (``.get``
     with arguments is fine by construction).
  2. **Read waits** — calls to ``.recv`` / ``.recv_into`` /
     ``.recv_bytes`` / ``.accept`` / ``.sock_accept`` (the sharded
     accept loops' manual accept path) / ``.readexactly`` /
     ``.readuntil`` / ``.readinto`` with no deadline
     source (``recv_into``/``readinto`` cover the zero-copy batch
     frame read path — filling a preallocated buffer blocks exactly
     like ``recv``). A deadline source is either an enclosing
     ``wait_for(...)`` call in the same expression, or an explicit
     waiver comment ``# io-deadline: <why>`` on the call line or the
     line above — the waiver documents which OUTER mechanism bounds
     the wait (a poll() guard, a settimeout tick, a supervisor kill
     ladder).

Runnable standalone (``python scripts/check_socket_timeouts.py`` —
exits 1 with the offender list) and imported by
tests/test_socket_timeout_lint.py as a tier-1 test.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
PKG = REPO / "fabric_token_sdk_tpu"

#: Modules whose blocking waits the serving plane depends on.
SCOPE = [
    PKG / "serve",
    PKG / "resilience",
    PKG / "obs" / "telemetry.py",
    PKG / "obs" / "aggregate.py",
]

SYNC_WAITS = {"poll", "wait", "join", "get"}
READ_WAITS = {"recv", "recv_into", "recv_bytes", "recv_bytes_into",
              "accept", "sock_accept", "readexactly", "readuntil",
              "readinto"}
WAIVER = "# io-deadline:"


def _scope_files() -> list[Path]:
    files: list[Path] = []
    for entry in SCOPE:
        if entry.is_dir():
            files.extend(sorted(entry.rglob("*.py")))
        elif entry.exists():
            files.append(entry)
    return files


def _has_timeout_arg(call: ast.Call) -> bool:
    if call.args:
        return True
    return any(kw.arg in ("timeout", "timeout_s", "deadline")
               for kw in call.keywords)


class _Walker(ast.NodeVisitor):
    """Tracks await- and wait_for-enclosure while collecting offenders."""

    def __init__(self, waived_lines: set[int]):
        self.waived_lines = waived_lines
        self.offenders: list[tuple[int, str, str]] = []
        self._await_depth = 0
        self._wait_for_depth = 0

    def visit_Await(self, node: ast.Await) -> None:
        self._await_depth += 1
        self.generic_visit(node)
        self._await_depth -= 1

    def _is_wait_for(self, call: ast.Call) -> bool:
        fn = call.func
        name = fn.attr if isinstance(fn, ast.Attribute) else \
            fn.id if isinstance(fn, ast.Name) else ""
        return name == "wait_for"

    def _waived(self, node: ast.Call) -> bool:
        return node.lineno in self.waived_lines \
            or node.lineno - 1 in self.waived_lines

    def visit_Call(self, node: ast.Call) -> None:
        if self._is_wait_for(node):
            self._wait_for_depth += 1
            self.generic_visit(node)
            self._wait_for_depth -= 1
            return
        fn = node.func
        if isinstance(fn, ast.Attribute):
            name = fn.attr
            if name in SYNC_WAITS and not _has_timeout_arg(node) \
                    and self._await_depth == 0 and not self._waived(node):
                self.offenders.append(
                    (node.lineno, name,
                     "no timeout argument on blocking wait"))
            elif name in READ_WAITS and self._wait_for_depth == 0 \
                    and not self._waived(node):
                self.offenders.append(
                    (node.lineno, name,
                     "read with no wait_for() or '# io-deadline:' waiver"))
        self.generic_visit(node)


def find_offenders() -> list[str]:
    """``file:line  .name  why`` for every unbounded wait in scope."""
    out: list[str] = []
    for path in _scope_files():
        text = path.read_text()
        waived = {i + 1 for i, line in enumerate(text.splitlines())
                  if WAIVER in line}
        walker = _Walker(waived)
        walker.visit(ast.parse(text, filename=str(path)))
        rel = path.relative_to(REPO)
        out.extend(f"{rel}:{line}  .{name}()  {why}"
                   for line, name, why in sorted(walker.offenders))
    return out


def main() -> int:
    offenders = find_offenders()
    if not offenders:
        print("check_socket_timeouts: every blocking socket/pipe wait "
              "in scope carries a timeout or documented deadline")
        return 0
    print("unbounded blocking waits (add a timeout, wrap in wait_for(), "
          "or waive with '# io-deadline: <why>'):")
    for line in offenders:
        print(f"  {line}")
    return 1


if __name__ == "__main__":
    sys.exit(main())
