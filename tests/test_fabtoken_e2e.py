"""End-to-end fabtoken slice: issue -> transfer -> ledger -> queries.

Exercises the full validation pipeline (SURVEY.md §3.2) against the
in-memory ledger: request wire format, auditor + owner/issuer signatures,
balance checks, RW-set translation, MVCC double-spend protection.
"""

import pytest

from fabric_token_sdk_tpu.core import fabtoken
from fabric_token_sdk_tpu.core.fabtoken.actions import (IssueAction, Output,
                                                        TransferAction)
from fabric_token_sdk_tpu.driver import TokenRequest
from fabric_token_sdk_tpu.driver.identity import Identity
from fabric_token_sdk_tpu.services.identity.deserializer import Deserializer
from fabric_token_sdk_tpu.services.identity.x509 import new_signing_identity
from fabric_token_sdk_tpu.services.network.tcc import MemoryLedger, TokenChaincode
from fabric_token_sdk_tpu.token.model import ID


@pytest.fixture
def world():
    issuer = new_signing_identity()
    alice = new_signing_identity()
    bob = new_signing_identity()
    auditor = new_signing_identity()
    pp = fabtoken.setup(64)
    pp.issuer_ids = [issuer.identity]
    pp.auditor = bytes(auditor.identity)
    validator = fabtoken.new_validator(pp, Deserializer())
    ledger = MemoryLedger()
    cc = TokenChaincode(validator, ledger, pp.serialize())
    return dict(issuer=issuer, alice=alice, bob=bob, auditor=auditor,
                pp=pp, cc=cc)


def _signed_request(world, tx_id, issues=(), transfers=(), signers=()):
    req = TokenRequest(issues=[a.serialize() for a in issues],
                       transfers=[a.serialize() for a in transfers])
    msg = req.message_to_sign(tx_id.encode())
    req.auditor_signatures = [world["auditor"].sign(msg)]
    req.signatures = [s.sign(msg) for s in signers]
    return req


def _issue(world, tx_id="tx1", value="0x64", owner=None):
    owner = owner if owner is not None else world["alice"]
    action = IssueAction(
        issuer=world["issuer"].identity,
        outputs=[Output(owner=bytes(owner.identity), type="USD",
                        quantity=value)],
    )
    req = _signed_request(world, tx_id, issues=[action],
                          signers=[world["issuer"]])
    return world["cc"].process_request(tx_id, req.to_bytes()), action


def test_issue_and_query(world):
    ev, action = _issue(world)
    assert ev.status == "VALID", ev.message
    toks = world["cc"].query_tokens([ID("tx1", 0)])
    assert len(toks) == 1
    out = Output.deserialize(toks[0])
    assert out.quantity == "0x64" and out.type == "USD"
    assert world["cc"].are_tokens_spent([ID("tx1", 0)]) == [False]


def test_transfer_moves_value_and_burns_input(world):
    ev, issue_action = _issue(world)
    assert ev.status == "VALID"
    in_token = issue_action.outputs[0]
    transfer = TransferAction(
        inputs=[ID("tx1", 0)],
        input_tokens=[in_token],
        outputs=[
            Output(owner=bytes(world["bob"].identity), type="USD",
                   quantity="0x60"),
            Output(owner=bytes(world["alice"].identity), type="USD",
                   quantity="0x4"),
        ],
    )
    req = _signed_request(world, "tx2", transfers=[transfer],
                          signers=[world["alice"]])
    ev = world["cc"].process_request("tx2", req.to_bytes())
    assert ev.status == "VALID", ev.message
    # input burnt; outputs live
    assert world["cc"].are_tokens_spent([ID("tx1", 0)]) == [True]
    bob_tok = Output.deserialize(world["cc"].query_tokens([ID("tx2", 0)])[0])
    assert bob_tok.quantity == "0x60"
    assert bob_tok.owner == bytes(world["bob"].identity)

    # double spend of tx1:0 must be rejected
    transfer2 = TransferAction(
        inputs=[ID("tx1", 0)], input_tokens=[in_token],
        outputs=[Output(owner=bytes(world["bob"].identity), type="USD",
                        quantity="0x64")],
    )
    req2 = _signed_request(world, "tx3", transfers=[transfer2],
                           signers=[world["alice"]])
    ev = world["cc"].process_request("tx3", req2.to_bytes())
    assert ev.status == "INVALID"
    assert "input must exist" in ev.message


def test_unbalanced_transfer_rejected(world):
    _, issue_action = _issue(world)
    transfer = TransferAction(
        inputs=[ID("tx1", 0)],
        input_tokens=[issue_action.outputs[0]],
        outputs=[Output(owner=bytes(world["bob"].identity), type="USD",
                        quantity="0x65")],  # 0x64 in, 0x65 out
    )
    req = _signed_request(world, "tx2", transfers=[transfer],
                          signers=[world["alice"]])
    ev = world["cc"].process_request("tx2", req.to_bytes())
    assert ev.status == "INVALID"
    assert "does not match output sum" in ev.message


def test_wrong_owner_signature_rejected(world):
    _, issue_action = _issue(world)
    transfer = TransferAction(
        inputs=[ID("tx1", 0)],
        input_tokens=[issue_action.outputs[0]],
        outputs=[Output(owner=bytes(world["bob"].identity), type="USD",
                        quantity="0x64")],
    )
    # bob signs instead of alice (the owner)
    req = _signed_request(world, "tx2", transfers=[transfer],
                          signers=[world["bob"]])
    ev = world["cc"].process_request("tx2", req.to_bytes())
    assert ev.status == "INVALID"
    assert "signature" in ev.message


def test_unauthorized_issuer_rejected(world):
    rogue = new_signing_identity()
    action = IssueAction(
        issuer=rogue.identity,
        outputs=[Output(owner=bytes(world["alice"].identity), type="USD",
                        quantity="0x10")],
    )
    req = _signed_request(world, "tx9", issues=[action], signers=[rogue])
    ev = world["cc"].process_request("tx9", req.to_bytes())
    assert ev.status == "INVALID"
    assert "is not in issuers" in ev.message


def test_missing_auditor_signature_rejected(world):
    action = IssueAction(
        issuer=world["issuer"].identity,
        outputs=[Output(owner=bytes(world["alice"].identity), type="USD",
                        quantity="0x10")],
    )
    req = TokenRequest(issues=[action.serialize()])
    msg = req.message_to_sign(b"txA")
    req.signatures = [world["issuer"].sign(msg)]
    # auditor signature absent entirely
    ev = world["cc"].process_request("txA", req.to_bytes())
    assert ev.status == "INVALID"


def test_request_roundtrip_bytes(world):
    action = IssueAction(
        issuer=world["issuer"].identity,
        outputs=[Output(owner=bytes(world["alice"].identity), type="USD",
                        quantity="0x10")],
    )
    req = _signed_request(world, "txB", issues=[action],
                          signers=[world["issuer"]])
    raw = req.to_bytes()
    restored = TokenRequest.from_bytes(raw)
    assert restored.to_bytes() == raw
    assert restored.issues == req.issues
    assert restored.auditor_signatures == req.auditor_signatures
