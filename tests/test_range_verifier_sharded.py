"""Production range verifier through the (dp, tp) mesh on the virtual
8-device CPU backend: sharded results must match both the single-device
device path and the host oracle (SURVEY.md §2.5; BASELINE config 5
shape — pass-1 rows dp-sharded, combined RLC terms sharded with the
all-gather point-fold)."""

import random

import jax
import numpy as np
import pytest

from fabric_token_sdk_tpu.crypto import bn254, rp, setup
from fabric_token_sdk_tpu.models.range_verifier import BatchRangeVerifier
from fabric_token_sdk_tpu.parallel import make_mesh

rng = random.Random(0x5AAD)

BIT_LENGTH = 16


@pytest.fixture(scope="module")
def pp():
    return setup.setup(BIT_LENGTH)


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-virtual-device CPU backend")
    return make_mesh(8, dp=4, tp=2)


def _prove_one(pp, value):
    rpp = pp.range_proof_params
    cg = pp.pedersen_generators[1:3]
    bf = bn254.fr_rand()
    com = bn254.g1_add(bn254.g1_mul(cg[0], value), bn254.g1_mul(cg[1], bf))
    proof = rp.range_prove(com, value, cg, bf, rpp.left_generators,
                           rpp.right_generators, rpp.P, rpp.Q,
                           rpp.number_of_rounds, rpp.bit_length)
    return proof, com


def test_sharded_matches_single_device_and_oracle(pp, mesh):
    proofs, coms = [], []
    for v in [0, 5, (1 << BIT_LENGTH) - 1, rng.randrange(1 << BIT_LENGTH)]:
        pf, com = _prove_one(pp, v)
        proofs.append(pf)
        coms.append(com)
    # two tampered rows exercise the sharded exact fallback
    bad0, cb0 = _prove_one(pp, 77)
    bad0.data.tau = bn254.fr_add(bad0.data.tau, 1)
    proofs.append(bad0); coms.append(cb0)
    bad1, cb1 = _prove_one(pp, 78)
    bad1.ipa.left = bn254.fr_add(bad1.ipa.left, 1)
    proofs.append(bad1); coms.append(cb1)

    sharded = BatchRangeVerifier(pp, mesh=mesh).verify(proofs, coms)
    single = BatchRangeVerifier(pp).verify(proofs, coms)
    assert (sharded == single).all(), f"{sharded} != {single}"
    assert list(sharded) == [True, True, True, True, False, False]


def test_sharded_all_valid_takes_combined_path(pp, mesh):
    proofs, coms = [], []
    for v in [11, 22, 33]:
        pf, com = _prove_one(pp, v)
        proofs.append(pf)
        coms.append(com)
    v = BatchRangeVerifier(pp, mesh=mesh)
    accepts = v.verify(proofs, coms)
    assert accepts.all()
    assert v.last_path == "combined"
