"""Production range verifier through the (dp, tp) mesh on the virtual
8-device CPU backend: sharded results must match both the single-device
device path and the host oracle (SURVEY.md §2.5; BASELINE config 5
shape — pass-1 rows dp-sharded, combined RLC terms sharded with the
all-gather point-fold)."""

import random
import re
from collections import Counter

import jax
import numpy as np
import pytest

from fabric_token_sdk_tpu.crypto import bn254, rp, setup
import fabric_token_sdk_tpu.models.range_verifier as rv
from fabric_token_sdk_tpu.models.range_verifier import BatchRangeVerifier
from fabric_token_sdk_tpu.obs import GLOBAL
from fabric_token_sdk_tpu.parallel import make_mesh

rng = random.Random(0x5AAD)

BIT_LENGTH = 16


@pytest.fixture(scope="module")
def pp():
    return setup.setup(BIT_LENGTH)


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-virtual-device CPU backend")
    return make_mesh(8, dp=4, tp=2)


def _prove_one(pp, value):
    rpp = pp.range_proof_params
    cg = pp.pedersen_generators[1:3]
    bf = bn254.fr_rand()
    com = bn254.g1_add(bn254.g1_mul(cg[0], value), bn254.g1_mul(cg[1], bf))
    proof = rp.range_prove(com, value, cg, bf, rpp.left_generators,
                           rpp.right_generators, rpp.P, rpp.Q,
                           rpp.number_of_rounds, rpp.bit_length)
    return proof, com


def test_sharded_matches_single_device_and_oracle(pp, mesh):
    proofs, coms = [], []
    for v in [0, 5, (1 << BIT_LENGTH) - 1, rng.randrange(1 << BIT_LENGTH)]:
        pf, com = _prove_one(pp, v)
        proofs.append(pf)
        coms.append(com)
    # two tampered rows exercise the sharded exact fallback
    bad0, cb0 = _prove_one(pp, 77)
    bad0.data.tau = bn254.fr_add(bad0.data.tau, 1)
    proofs.append(bad0); coms.append(cb0)
    bad1, cb1 = _prove_one(pp, 78)
    bad1.ipa.left = bn254.fr_add(bad1.ipa.left, 1)
    proofs.append(bad1); coms.append(cb1)

    sharded = BatchRangeVerifier(pp, mesh=mesh).verify(proofs, coms)
    single = BatchRangeVerifier(pp).verify(proofs, coms)
    assert (sharded == single).all(), f"{sharded} != {single}"
    assert list(sharded) == [True, True, True, True, False, False]


def test_sharded_all_valid_takes_combined_path(pp, mesh):
    proofs, coms = [], []
    for v in [11, 22, 33]:
        pf, com = _prove_one(pp, v)
        proofs.append(pf)
        coms.append(com)
    v = BatchRangeVerifier(pp, mesh=mesh)
    accepts = v.verify(proofs, coms)
    assert accepts.all()
    assert v.last_path == "combined"


def test_sharded_ragged_batch_identity_padding(pp, mesh):
    """A batch size not divisible by dp rides identity-padded shard rows
    (identity points, zero RLC weights): verdicts must match the
    single-device path exactly, and the pad accounting must light the
    stable mesh_* families (ROADMAP stable-metric-names)."""
    GLOBAL.reset()
    proofs, coms = [], []
    for v in [9, 10, 11, 12, 13]:          # 5 rows over dp=4
        pf, com = _prove_one(pp, v)
        proofs.append(pf)
        coms.append(com)
    sharded = BatchRangeVerifier(pp, mesh=mesh).verify(proofs, coms)
    single = BatchRangeVerifier(pp).verify(proofs, coms)
    assert (sharded == single).all(), f"{sharded} != {single}"
    assert sharded.all()
    text = GLOBAL.prometheus_text()
    assert re.search(r"^mesh_devices(?:\{[^}]*\})? 8(\.0)?$", text, re.M), \
        text
    for fam in ("mesh_chunk_dispatches_total", "mesh_pad_rows_total",
                "mesh_allgather_bytes_total"):
        m = re.search(r"^%s(?:\{[^}]*\})? ([0-9.e+]+)$" % fam, text, re.M)
        assert m, f"mesh family silent: {fam}"
        assert float(m.group(1)) > 0, fam


def test_sharded_dispatch_counts_stay_fused(pp, mesh):
    """Scaling out must not reintroduce the per-pass dispatch ladder:
    under the mesh each verify is still ONE packed upload + ONE fused
    chunk program per chunk, with the O(1) finalize folded across
    chunks (same invariant perf_profile.py --mode mesh asserts)."""
    counts = Counter()
    old = rv._DISPATCH_HOOK
    rv._DISPATCH_HOOK = lambda kind: counts.update((kind,))
    try:
        proofs, coms = [], []
        for v in [41, 42, 43]:
            pf, com = _prove_one(pp, v)
            proofs.append(pf)
            coms.append(com)
        ver = BatchRangeVerifier(pp, mesh=mesh)
        accepts = ver.verify(proofs, coms)
    finally:
        rv._DISPATCH_HOOK = old
    assert accepts.all() and ver.last_path == "combined"
    assert counts["chunk_upload"] == 1, dict(counts)
    assert counts["chunk_dispatch"] == 1, dict(counts)
    assert counts["finalize"] == 1, dict(counts)
