"""Tier-1 (crypto-free) tests for the per-device dispatch lanes.

The multi-chip serve frontend runs one DISPATCH LANE per device: each
lane owns a verifier handle, a single-thread executor, and a prewarm
inventory, and the dispatch loop keeps assembling batches while any
lane is idle so all devices verify concurrently. These tests drive the
whole service against stub verifiers (no jax, no crypto) and pin down:

  * batches SPREAD across lanes when a lane blocks (continuous
    batching actually overlaps device calls),
  * per-lane verifier routing (``lane_verifiers``) and its length
    validation,
  * per-lane prewarm inventories all populated before first dispatch,
  * the LRU lane assignment round-robins over idle lanes,
  * ``n_lanes=1`` preserves the historical single-dispatcher surface
    (``svc.prewarm``, ``svc._watchdog``, ``device_lane == 0``).

Device-side parity of the lanes is covered by the heavy smoke
(tests/test_serve_smoke.py) and BENCH_MODE=replay.
"""

import asyncio
import re
import time

import numpy as np
import pytest

from fabric_token_sdk_tpu.obs import GLOBAL
from fabric_token_sdk_tpu.serve import (ServeConfig, VerificationService)
from fabric_token_sdk_tpu.serve.scheduler import BucketScheduler


class _StubRange:
    """Blocking stand-in for BatchRangeVerifier: optional sleep holds
    the lane's executor thread busy, forcing the loop to other lanes."""

    def __init__(self, delay_s: float = 0.0):
        self.delay_s = delay_s
        self.calls = 0
        self.rows = 0

    def verify(self, proofs, commitments):
        self.calls += 1
        self.rows += len(proofs)
        if self.delay_s:
            time.sleep(self.delay_s)
        return np.ones(len(proofs), dtype=bool)


class _StubZK:
    def __init__(self, delay_s: float = 0.0):
        self._range = _StubRange(delay_s)
        self.prewarmed: list[tuple] = []

    def prewarm_shapes(self, buckets, include_block=False):
        self.prewarmed.append(tuple(buckets))
        return {b: 0.001 for b in buckets}


def _drive(svc, n_requests, prewarm=False):
    async def run():
        await svc.start(prewarm=prewarm)
        out = await asyncio.gather(*[
            svc.submit_range(object(), object()) for _ in range(n_requests)])
        await svc.stop()
        return out

    return asyncio.run(run())


def test_batches_spread_across_lanes():
    """With lane 0 blocked mid-verify, the loop must keep assembling
    and hand the next batches to the other lanes — all three serve."""
    GLOBAL.reset()
    svc = VerificationService(
        _StubZK(delay_s=0.05),
        config=ServeConfig(buckets=(8,), max_wait_s=0.001, n_lanes=3))
    results = _drive(svc, 24)
    assert all(r.ok and r.accepted for r in results)
    assert {r.device_lane for r in results} == {0, 1, 2}
    st = svc.status()
    assert [l["index"] for l in st["lanes"]] == [0, 1, 2]
    assert all(l["dispatches"] >= 1 for l in st["lanes"])
    assert sum(l["rows"] for l in st["lanes"]) == 24
    assert not any(l["busy"] for l in st["lanes"])
    # stable lane_* families export with per-lane labels (the crypto-full
    # twin of this assertion lives in tests/test_obs_smoke.py)
    text = GLOBAL.prometheus_text()
    for fam in ("lane_dispatch_total", "lane_rows_total",
                "lane_busy_seconds", "lane_inflight"):
        assert fam in text, f"lane family silent: {fam}"
    for lane in (0, 1, 2):
        assert re.search(r'lane_dispatch_total\{[^}]*lane="%d"' % lane,
                         text), lane


def test_per_lane_verifier_routing_and_validation():
    """Each lane dispatches on ITS OWN verifier handle (per-device
    placement), and a lane_verifiers list of the wrong length is a
    construction-time error."""
    zks = [_StubZK(delay_s=0.05) for _ in range(2)]
    svc = VerificationService(
        zks[0],
        config=ServeConfig(buckets=(4,), max_wait_s=0.001, n_lanes=2),
        lane_verifiers=zks)
    results = _drive(svc, 16)
    assert all(r.ok for r in results)
    assert {r.device_lane for r in results} == {0, 1}
    assert all(zk._range.calls >= 1 for zk in zks)
    assert sum(zk._range.rows for zk in zks) == 16

    with pytest.raises(ValueError, match="lane_verifiers"):
        VerificationService(
            zks[0],
            config=ServeConfig(buckets=(4,), n_lanes=3),
            lane_verifiers=zks)


def test_per_lane_prewarm_inventory():
    """start(prewarm=True) must compile every bucket on EVERY lane's
    own verifier before the first dispatch — per-lane inventories, not
    one shared set."""
    zks = [_StubZK() for _ in range(2)]
    svc = VerificationService(
        zks[0],
        config=ServeConfig(buckets=(4, 8), max_wait_s=0.001, n_lanes=2),
        lane_verifiers=zks)
    results = _drive(svc, 4, prewarm=True)
    assert all(r.ok for r in results)
    for lane in svc._lanes:
        assert lane.prewarm.ready == {4, 8}, lane.index
    # each lane warmed through its own zk handle
    assert all(zk.prewarmed for zk in zks)
    # compat alias surfaces lane 0's inventory
    assert svc.prewarm is svc._lanes[0].prewarm


def test_pick_lane_is_lru_round_robin():
    sched = BucketScheduler(ServeConfig(buckets=(4,), n_lanes=3))
    picks = [sched.pick_lane([0, 1, 2]) for _ in range(6)]
    assert picks == [0, 1, 2, 0, 1, 2]
    # only idle lanes are candidates; least-recently-used wins
    assert sched.pick_lane([2, 1]) == 1
    assert sched.pick_lane([]) is None


def test_single_lane_preserves_legacy_surface():
    svc = VerificationService(
        _StubZK(), config=ServeConfig(buckets=(4,), max_wait_s=0.001))
    assert len(svc._lanes) == 1
    results = _drive(svc, 8)
    assert all(r.ok and r.device_lane == 0 for r in results)
    assert svc._watchdog is svc._lanes[0].watchdog
    assert svc.status()["lanes"][0]["dispatches"] >= 1
