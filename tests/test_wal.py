"""Crash-recovery WAL (serve/wal.py): durable admit/resolve records,
torn-tail tolerance, rotation + compaction-on-recovery, exactly-once
terminal accounting, and bit-identical service replay — all crypto-free
(tier-1)."""

import asyncio

import pytest

from fabric_token_sdk_tpu.obs import GLOBAL
from fabric_token_sdk_tpu.serve import (STATUS_OK, STATUS_SHUTDOWN,
                                        ServeConfig, VerificationService,
                                        WalConfig, WriteAheadLog)

pytestmark = pytest.mark.crash


def test_admit_resolve_roundtrip_across_restart(tmp_path):
    wal = WriteAheadLog(tmp_path)
    payload = (b"proof-\x00\xff-bytes", 12345678901234567890, ("nested",))
    a = wal.append_admit(kind="range", lane="bulk", deadline_s=1.5,
                         payload=(1, "c"))
    b = wal.append_admit(kind="range", lane="interactive", deadline_s=2.0,
                         payload=payload)
    assert (a, b) == (1, 2)
    assert wal.open_count == 2
    assert wal.append_resolve(a, status="ok", accepted=True,
                              served_by="device")
    assert wal.open_count == 1
    wal.close()

    succ = WriteAheadLog(tmp_path)
    entries = succ.recover()
    assert [e.wal_id for e in entries] == [b]
    e = entries[0]
    assert (e.kind, e.lane, e.deadline_s) == ("range", "interactive", 2.0)
    assert e.payload == payload          # pickle round-trip, byte-exact
    # ids continue past the crash: no reuse, no collision with history
    assert succ.append_admit(kind="range", lane="bulk", deadline_s=1.0,
                             payload=(1,)) == b + 1
    succ.close()


def test_duplicate_resolve_is_dropped_and_counted(tmp_path):
    GLOBAL.reset()
    wal = WriteAheadLog(tmp_path)
    rid = wal.append_admit(kind="range", lane="bulk", deadline_s=1.0,
                           payload=(1,))
    assert wal.append_resolve(rid, status="ok", accepted=True) is True
    assert wal.append_resolve(rid, status="error", accepted=False) is False
    snap = GLOBAL.snapshot()
    dups = [v for (name, labels), v in snap.items()
            if name == "wal_appends_total"
            and dict(labels).get("record") == "resolve_duplicate"]
    assert dups == [1]
    # a resolve for an id that was never admitted is equally a no-op
    assert wal.append_resolve(999, status="ok") is False
    wal.close()


def test_torn_tail_is_skipped_and_counted(tmp_path):
    GLOBAL.reset()
    wal = WriteAheadLog(tmp_path)
    keep = wal.append_admit(kind="range", lane="bulk", deadline_s=1.0,
                            payload=("keep",))
    done = wal.append_admit(kind="range", lane="bulk", deadline_s=1.0,
                            payload=("done",))
    wal.append_resolve(done, status="ok", accepted=True)
    wal.close()
    # a SIGKILL mid-write leaves a half-written final line: simulate the
    # torn resolve of `keep`
    [seg] = list(tmp_path.glob("wal-*.jsonl"))
    with open(seg, "ab") as f:
        f.write(b'{"t":"resolve","id":%d,"status":"ok"' % keep)

    succ = WriteAheadLog(tmp_path)
    entries = succ.recover()
    # the torn resolve never counted: `keep` is still open; every
    # complete prior record survived
    assert [e.wal_id for e in entries] == [keep]
    assert succ.torn_records == 1
    assert GLOBAL.snapshot()[("wal_torn_records_total", ())] == 1
    succ.close()


def test_checksum_mismatch_is_skipped(tmp_path):
    wal = WriteAheadLog(tmp_path)
    wal.append_admit(kind="range", lane="bulk", deadline_s=1.0,
                     payload=("a",))
    ok = wal.append_admit(kind="range", lane="bulk", deadline_s=1.0,
                          payload=("b",))
    wal.close()
    [seg] = list(tmp_path.glob("wal-*.jsonl"))
    first, rest = seg.read_text().split("\n", 1)
    # flip a field without refreshing the crc: the record must not scan
    seg.write_text(first.replace('"lane":"bulk"', '"lane":"silk"')
                   + "\n" + rest)

    succ = WriteAheadLog(tmp_path)
    entries = succ.recover()
    assert [e.wal_id for e in entries] == [ok]
    assert succ.torn_records == 1
    succ.close()


def test_rotation_and_compaction_on_recovery(tmp_path):
    cfg = WalConfig(segment_max_records=2)
    wal = WriteAheadLog(tmp_path, config=cfg)
    ids = [wal.append_admit(kind="range", lane="bulk", deadline_s=1.0,
                            payload=(i,)) for i in range(6)]
    for rid in ids[:4]:
        wal.append_resolve(rid, status="ok", accepted=True)
    # 10 records at 2/segment rotated into 5 files
    assert len(list(tmp_path.glob("wal-*.jsonl"))) == 5
    wal.close()

    succ = WriteAheadLog(tmp_path, config=cfg)
    entries = succ.recover()
    assert [e.wal_id for e in entries] == ids[4:]
    # compaction: exactly one fresh segment holding only the live set;
    # history is deleted, so restart cost tracks the open set
    [seg] = list(tmp_path.glob("wal-*.jsonl"))
    assert len(seg.read_text().splitlines()) == len(entries)
    assert succ.open_count == 2
    succ.close()


def test_recover_is_idempotent_and_implicit(tmp_path):
    wal = WriteAheadLog(tmp_path)
    wal.append_admit(kind="range", lane="bulk", deadline_s=1.0,
                     payload=(1,))
    assert wal.recover() == []           # appends already recovered
    wal.close()

    succ = WriteAheadLog(tmp_path)
    # the first append triggers recovery implicitly; the incomplete
    # entry stays readable and its id is never reissued
    assert succ.append_admit(kind="range", lane="bulk", deadline_s=1.0,
                             payload=(2,)) == 2
    assert [e.wal_id for e in succ.recovered_entries] == [1]
    assert succ.open_count == 2
    assert succ.recover() == []
    succ.close()


# ------------------------------------------------------ service replay
class _TruthyRange:
    """Each 'proof' is its own verdict — replay parity is directly
    assertable without crypto."""

    def verify(self, proofs, coms):
        del coms
        return [bool(p) for p in proofs]


class _TruthyZK:
    _range = _TruthyRange()


def _hold_config():
    # one oversized bucket + hour-scale waits: nothing ever dispatches,
    # so an abort leaves every admitted request unresolved
    return ServeConfig(buckets=(64,), max_wait_s=3600.0,
                       default_deadline_s=3600.0)


def test_service_replays_wal_bit_identically(tmp_path):
    wal = WriteAheadLog(tmp_path)
    svc = VerificationService(_TruthyZK(), config=_hold_config(), wal=wal)

    async def crash():
        await svc.start(prewarm=False)
        tasks = [asyncio.ensure_future(
            svc.submit_range(i % 3 != 0, f"com{i}")) for i in range(8)]
        await asyncio.sleep(0.05)        # every admit reaches the WAL
        await svc.abort()                # simulated SIGKILL mid-flight
        for t in tasks:
            t.cancel()

    asyncio.run(crash())
    wal.close()
    assert wal.open_count == 8

    succ_wal = WriteAheadLog(tmp_path)
    succ = VerificationService(
        _TruthyZK(), config=ServeConfig(buckets=(4, 8), max_wait_s=0.001),
        wal=succ_wal)

    async def recover():
        await succ.start(prewarm=False)  # start() awaits the replay
        await succ.stop(timeout_s=10.0)
        return succ.replayed

    replayed = asyncio.run(recover())
    assert len(replayed) == 8
    # wal ids are assigned in admit order, so id i+1 carries request i:
    # the replayed verdict must match the original payload's ground truth
    for wal_id, res in replayed:
        assert res.status == STATUS_OK
        assert res.accepted is ((wal_id - 1) % 3 != 0)
    # exactly-once terminal accounting: nothing left open, nothing
    # replayed twice
    assert succ_wal.open_count == 0
    assert succ_wal.recover() == []


def test_stop_timeout_journals_shutdown_and_resolves_wal(tmp_path):
    from fabric_token_sdk_tpu.obs.journal import (EVENT_REQUEST_SHUTDOWN,
                                                  JOURNAL)

    JOURNAL.reset()
    wal = WriteAheadLog(tmp_path)
    svc = VerificationService(_TruthyZK(), config=_hold_config(), wal=wal)

    async def run():
        await svc.start(prewarm=False)
        tasks = [asyncio.ensure_future(svc.submit_range(True, "c"))
                 for _ in range(3)]
        await asyncio.sleep(0.05)
        await svc.stop(timeout_s=0.05)   # the held queue can never drain
        return await asyncio.gather(*tasks)

    results = asyncio.run(run())
    assert [r.status for r in results] == [STATUS_SHUTDOWN] * 3
    # every request resolved with the terminal shutdown status is
    # journaled (post-mortem accounting) AND resolved in the WAL, so a
    # successor has nothing to replay
    events = [e for e in JOURNAL.tail()
              if e.get("kind") == EVENT_REQUEST_SHUTDOWN]
    assert len(events) == 3
    assert wal.open_count == 0
    wal.close()
    assert WriteAheadLog(tmp_path).recover() == []
