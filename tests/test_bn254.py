"""Oracle sanity tests for the pure-Python BN254 layer."""

import hashlib

from fabric_token_sdk_tpu.crypto import bn254, serialization as ser
from fabric_token_sdk_tpu.crypto.bn254 import (
    G1_GENERATOR,
    G1_IDENTITY,
    P,
    R,
    g1_add,
    g1_mul,
    g1_neg,
    hash_to_g1,
    hash_to_zr,
    map_to_curve_svdw,
)


def test_curve_parameters():
    # generator on curve, subgroup order r
    assert G1_GENERATOR.on_curve()
    assert g1_mul(G1_GENERATOR, R).is_identity()
    assert P % 4 == 3  # sqrt via (p+1)/4 is valid


def test_group_laws():
    a = g1_mul(G1_GENERATOR, 1234567)
    b = g1_mul(G1_GENERATOR, 7654321)
    assert g1_add(a, b) == g1_add(b, a)
    assert g1_add(a, G1_IDENTITY) == a
    assert g1_add(a, g1_neg(a)).is_identity()
    # (a+b)G == aG + bG
    assert g1_mul(G1_GENERATOR, 1234567 + 7654321) == g1_add(a, b)
    # distributivity with reduction mod r
    assert g1_mul(G1_GENERATOR, R + 5) == g1_mul(G1_GENERATOR, 5)


def test_small_multiples_match_known_values():
    # 2G for BN254 is a fixed, widely published value (EIP-196 test vectors).
    two_g = g1_mul(G1_GENERATOR, 2)
    assert two_g.x == 1368015179489954701390400359078579693043519447331113978918064868415326638035
    assert two_g.y == 9918110051302171585080402603319702774565515993150576347155970296011118125764


def test_hash_to_zr_is_sha256_mod_r():
    data = b"hello fiat shamir"
    expected = int.from_bytes(hashlib.sha256(data).digest(), "big") % R
    assert hash_to_zr(data) == expected


def test_map_to_curve_outputs_on_curve():
    for u in [0, 1, 2, 12345, P - 1, 987654321987654321]:
        assert map_to_curve_svdw(u % P).on_curve()


def test_hash_to_g1_on_curve_and_deterministic():
    p1 = hash_to_g1(b"RangeProof.2")
    p2 = hash_to_g1(b"RangeProof.2")
    p3 = hash_to_g1(b"RangeProof.3")
    assert p1 == p2
    assert p1 != p3
    assert p1.on_curve()
    assert g1_mul(p1, R).is_identity()


def test_g1_bytes_roundtrip():
    p = g1_mul(G1_GENERATOR, 424242)
    raw = ser.g1_to_bytes(p)
    assert len(raw) == 64
    assert ser.g1_from_bytes(raw) == p
    assert ser.g1_from_bytes(b"\x00" * 64).is_identity()


def test_g1_from_bytes_rejects_off_curve():
    raw = bytearray(ser.g1_to_bytes(g1_mul(G1_GENERATOR, 7)))
    raw[63] ^= 1
    try:
        ser.g1_from_bytes(bytes(raw))
        raise AssertionError("expected rejection")
    except ValueError:
        pass


def test_zr_bytes_roundtrip():
    s = 0x1234567890ABCDEF
    assert ser.zr_from_bytes(ser.zr_to_bytes(s)) == s
    # reduction semantics
    assert ser.zr_from_bytes((R + 3).to_bytes(32, "big")) == 3


def test_der_matches_go_asn1_shapes():
    # Values{Values: [][]byte{"ab", "cd"}} framing round-trip
    raw = ser.marshal_values([b"ab", b"cd"])
    assert ser.unmarshal_values(raw) == [b"ab", b"cd"]
    # Element framing
    el = ser.marshal_element(1, b"\x01\x02")
    assert ser.unmarshal_element(el) == (1, b"\x01\x02")
    # hand-checked DER: SEQUENCE { SEQUENCE { OCTET STRING "ab" } }
    assert ser.marshal_values([b"ab"]) == bytes.fromhex("3006" "3004" "0402" "6162")
    # INTEGER minimal encoding incl. high-bit padding
    assert ser.der_integer(1) == bytes.fromhex("020101")
    assert ser.der_integer(128) == bytes.fromhex("02020080")
    assert ser.der_integer(0) == bytes.fromhex("020100")


def test_marshal_math_roundtrip():
    p = g1_mul(G1_GENERATOR, 99)
    q = g1_mul(G1_GENERATOR, 101)
    raw = ser.marshal_math(
        (ser.G1_KIND, p),
        (ser.ZR_KIND, 42),
        (ser.G1_ARRAY_KIND, [p, q]),
        (ser.ZR_ARRAY_KIND, [1, 2, 3]),
    )
    um = ser.MathUnmarshaller(raw)
    assert um.next_g1() == p
    assert um.next_zr() == 42
    assert um.next_g1_array() == [p, q]
    assert um.next_zr_array() == [1, 2, 3]


def test_g1_array_bytes_format():
    p = g1_mul(G1_GENERATOR, 3)
    q = g1_mul(G1_GENERATOR, 5)
    raw = ser.g1_array_bytes([p, q])
    parts = raw.split(b"||")
    assert parts == [
        ser.g1_to_bytes(p).hex().encode(),
        ser.g1_to_bytes(q).hex().encode(),
    ]
