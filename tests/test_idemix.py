"""Idemix pseudonymous owners: signatures, audit matching, unlinkable e2e.

Capability tests mirroring reference identity/idemix/km.go semantics:
fresh pseudonym per tx, Schnorr verification against the pseudonym only,
auditor-side NymEID matching, and an end-to-end zkatdlog lifecycle proving
(a) validators accept pseudonym signatures, (b) two receipts by the same
owner are distinct on-ledger identities, (c) the auditor still recovers the
enrollment ID.
"""

import pytest

from fabric_token_sdk_tpu.core import zkatdlog
from fabric_token_sdk_tpu.core.zkatdlog.driver import ZkDlogDriverService
from fabric_token_sdk_tpu.crypto import setup
from fabric_token_sdk_tpu.services.auditor import AuditorNode
from fabric_token_sdk_tpu.services.identity.deserializer import Deserializer
from fabric_token_sdk_tpu.services.identity.idemix import (
    EnrollmentAuthority, IdemixError, IdemixInfoMatcher, IdemixKeyManager,
    MuxInfoMatcher, NymVerifier, idemix_owner_resolver)
from fabric_token_sdk_tpu.services.identity.wallet import IdemixOwnerWallet
from fabric_token_sdk_tpu.services.identity.x509 import new_signing_identity
from fabric_token_sdk_tpu.services.identity import typed as typed_mod
from fabric_token_sdk_tpu.services.network.tcc import MemoryLedger, TokenChaincode
from fabric_token_sdk_tpu.services.node import TokenNode
from fabric_token_sdk_tpu.services.ttx import SessionBus

BIT_LENGTH = 16


# ---------------------------------------------------------------- unit layer

def test_pseudonyms_are_fresh_and_sign():
    ca = EnrollmentAuthority()
    km = IdemixKeyManager("alice@org1", ca)
    p1, p2 = km.fresh_pseudonym(), km.fresh_pseudonym()
    assert bytes(p1.identity()) != bytes(p2.identity())  # unlinkable ids

    msg = b"spend token 42"
    sig = km.sign(bytes(p1.identity()), msg)
    ti = typed_mod.unmarshal_typed_identity(bytes(p1.identity()))
    NymVerifier.from_typed(ti.identity).verify(msg, sig)

    # signature bound to the message and to the pseudonym
    with pytest.raises(IdemixError):
        NymVerifier.from_typed(ti.identity).verify(b"other message", sig)
    ti2 = typed_mod.unmarshal_typed_identity(bytes(p2.identity()))
    with pytest.raises(IdemixError):
        NymVerifier.from_typed(ti2.identity).verify(msg, sig)


def test_audit_info_matches_only_right_pseudonym():
    ca = EnrollmentAuthority()
    km = IdemixKeyManager("alice@org1", ca)
    p1, p2 = km.fresh_pseudonym(), km.fresh_pseudonym()
    matcher = IdemixInfoMatcher(ca.ca_identity())
    ai1 = km.audit_info(bytes(p1.identity()))
    matcher.match_identity(bytes(p1.identity()), ai1)
    assert matcher.enrollment_id(ai1) == "alice@org1"
    with pytest.raises(IdemixError):
        matcher.match_identity(bytes(p2.identity()), ai1)


def test_forged_enrollment_cert_rejected():
    ca, rogue = EnrollmentAuthority(), EnrollmentAuthority()
    km = IdemixKeyManager("mallory", rogue)  # enrolled at the WRONG ca
    p = km.fresh_pseudonym()
    matcher = IdemixInfoMatcher(ca.ca_identity())
    with pytest.raises(Exception):
        matcher.match_identity(bytes(p.identity()),
                               km.audit_info(bytes(p.identity())))


def test_mux_matcher_dispatch():
    ca = EnrollmentAuthority()
    km = IdemixKeyManager("alice", ca)
    p = km.fresh_pseudonym()
    mux = MuxInfoMatcher(ca.ca_identity())
    mux.match_identity(bytes(p.identity()),
                       km.audit_info(bytes(p.identity())))
    mux.match_identity(b"plain-key", b"plain-key")  # x509 equality path
    with pytest.raises(Exception):
        mux.match_identity(b"plain-key", b"other")


# ----------------------------------------------------------------- e2e layer

@pytest.fixture(scope="module")
def pp_module():
    return setup.setup(BIT_LENGTH)


@pytest.fixture
def net(pp_module):
    pp = pp_module
    ca = EnrollmentAuthority()
    issuer_keys = new_signing_identity()
    auditor_keys = new_signing_identity()
    pp.issuer_ids = [issuer_keys.identity]
    pp.auditor = bytes(auditor_keys.identity)
    deserializer = Deserializer(extra_owner_resolvers=[idemix_owner_resolver])
    validator = zkatdlog.new_validator(pp, deserializer, device=False)
    cc = TokenChaincode(validator, MemoryLedger(), pp.serialize())
    bus = SessionBus()
    driver = ZkDlogDriverService(
        pp, device=False, info_matcher=MuxInfoMatcher(ca.ca_identity()))
    nodes = {"issuer": TokenNode("issuer", issuer_keys, bus, cc,
                                 precision=BIT_LENGTH,
                                 auditor_name="auditor", driver=driver),
             "auditor": AuditorNode("auditor", auditor_keys, bus, cc,
                                    precision=BIT_LENGTH,
                                    auditor_name="auditor", driver=driver)}
    for name in ("alice", "bob"):
        keys = new_signing_identity()
        wallet = IdemixOwnerWallet(IdemixKeyManager(f"{name}@org1", ca))
        nodes[name] = TokenNode(name, keys, bus, cc, precision=BIT_LENGTH,
                                auditor_name="auditor", driver=driver,
                                owner_wallet=wallet)
    return nodes


def test_pseudonymous_lifecycle_with_unlinkability(net):
    alice, bob = net["alice"], net["bob"]
    assert alice.execute(
        alice.issue("issuer", "alice", "USD", hex(500))).status == "VALID"
    assert alice.execute(
        alice.issue("issuer", "alice", "USD", hex(300))).status == "VALID"
    assert alice.balance("USD") == 800

    # two receipts by the same owner are distinct on-ledger identities
    owners = {bytes(t.owner) for t in alice.tokendb.unspent_tokens("alice")}
    assert len(owners) == 2

    # spending works: validator verifies Schnorr PoK against the pseudonyms
    tx = alice.transfer("USD", hex(600), "bob")
    ev = alice.execute(tx)
    assert ev.status == "VALID", ev.message
    assert alice.balance("USD") == 200
    assert bob.balance("USD") == 600

    # bob's on-ledger identity is a pseudonym, not his x509 key
    bob_owners = {bytes(t.owner) for t in bob.tokendb.unspent_tokens("bob")}
    assert bytes(net["bob"].keys.identity) not in bob_owners

    # auditor recovered enrollment IDs via NymEID matching, yet the ledger
    # never saw them
    for key, raw in alice.cc.ledger.state.items():
        assert b"alice@org1" not in raw and b"bob@org1" not in raw


def test_wrong_wallet_cannot_spend(net):
    """A node whose wallet doesn't own the pseudonym can't sign the spend."""
    alice, bob = net["alice"], net["bob"]
    assert alice.execute(
        alice.issue("issuer", "alice", "USD", hex(50))).status == "VALID"
    tx = alice.transfer("USD", hex(50), "bob")
    # hijack: bob tries to sign alice's input pseudonym
    tx.input_owners = ["bob"] * len(tx.input_owners)
    with pytest.raises(Exception):
        alice.execute(tx)
