"""Fleet federation (obs/aggregate.py): exposition parsing, merge
semantics (HELP/TYPE conflicts, node-label collisions), the spool
publisher/aggregator pair, and the federated /metrics + /fleetz HTTP
surface. The merged document must satisfy the same Prometheus grammar
walker the single-process plane is held to (test_telemetry.py), with the
stable family inventory unchanged — only a new ``node`` dimension.
"""

import json
import urllib.request

import pytest

from fabric_token_sdk_tpu.obs.aggregate import (FleetAggregator,
                                                SpoolPublisher,
                                                merge_expositions,
                                                parse_exposition)
from fabric_token_sdk_tpu.obs.metrics import MetricsProvider

from test_telemetry import validate_prometheus


def _node_provider(name: str, reqs: int) -> MetricsProvider:
    p = MetricsProvider()
    p.describe("serve_requests_total", "Requests admitted per lane.")
    p.describe("serve_queue_depth", "Live queue depth.")
    p.describe("serve_dispatch_seconds", "Dispatch wall time.")
    p.counter("serve_requests_total", lane=name).add(reqs)
    p.gauge("serve_queue_depth").set(float(reqs % 5))
    p.histogram("serve_dispatch_seconds").observe(0.01 * (reqs + 1))
    return p


# ----------------------------------------------------------------- parse


def test_parse_roundtrips_counter_gauge_histogram():
    text = _node_provider("a", 3).prometheus_text()
    fams = parse_exposition(text)
    assert fams["serve_requests_total"]["type"] == "counter"
    assert fams["serve_requests_total"]["help"].startswith("Requests")
    (sample_name, labels, value), = fams["serve_requests_total"]["samples"]
    assert sample_name == "serve_requests_total"
    assert ("lane", "a") in labels and value == "3.0"
    # histogram series attach to the base family
    hist = fams["serve_dispatch_seconds"]["samples"]
    names = {s[0] for s in hist}
    assert {"serve_dispatch_seconds_bucket", "serve_dispatch_seconds_sum",
            "serve_dispatch_seconds_count"} <= names
    assert any(("le", "+Inf") in s[1] for s in hist)


def test_parse_keeps_values_verbatim_and_unescapes_labels():
    fams = parse_exposition(
        '# TYPE x gauge\nx{k="a\\"b\\\\c\\nd"} NaN\nx 1e-09\n')
    samples = fams["x"]["samples"]
    assert samples[0][1] == [("k", 'a"b\\c\nd')]
    assert samples[0][2] == "NaN" and samples[1][2] == "1e-09"


def test_parse_rejects_malformed_sample_line():
    with pytest.raises(ValueError):
        parse_exposition("not a metric line at all {\n")


# ----------------------------------------------------------------- merge


def test_merge_injects_node_label_and_keeps_family_names():
    docs = {n: _node_provider(n, i + 1).prometheus_text()
            for i, n in enumerate(("n0", "n1", "n2"))}
    text, merge = merge_expositions(docs)
    validate_prometheus(text)
    for n in docs:
        assert f'node="{n}"' in text
    # family names are untouched — no fleet_ prefixing of child families
    assert 'serve_requests_total{lane="n1",node="n1"} 2.0' in text
    assert merge.conflicts == {}
    assert merge.samples == sum(
        len(f["samples"]) for d in docs.values()
        for f in parse_exposition(d).values())


def test_merge_help_conflict_first_wins_and_is_counted():
    docs = {
        "a": '# HELP f one\n# TYPE f counter\nf 1.0\n',
        "b": '# HELP f two\n# TYPE f gauge\nf 2.0\n',
    }
    text, merge = merge_expositions(docs)
    validate_prometheus(text)
    assert "# HELP f one" in text and "two" not in text
    assert "# TYPE f counter" in text
    assert merge.conflicts == {"help": 1, "type": 1}


def test_merge_renames_colliding_node_label():
    docs = {"parent": '# TYPE f counter\nf{node="inner"} 1.0\n'}
    text, merge = merge_expositions(docs)
    validate_prometheus(text)
    assert 'node_orig="inner"' in text
    assert 'node="parent"' in text
    assert merge.conflicts == {"label": 1}


def test_merge_self_text_carries_no_node_label():
    text, _ = merge_expositions(
        {"n0": '# TYPE f counter\nf 1.0\n'},
        self_text='# TYPE own gauge\nown 7.0\n')
    assert "own 7.0" in text            # bare: the parent is not a node
    assert 'f{node="n0"} 1.0' in text


def test_merge_unparseable_doc_counted_not_fatal():
    text, merge = merge_expositions(
        {"good": '# TYPE f counter\nf 1.0\n', "bad": "}{ torn write\n"})
    assert 'f{node="good"} 1.0' in text
    assert merge.conflicts == {"parse": 1}


# --------------------------------------------------- spool + aggregator


def test_three_node_spool_federation(tmp_path):
    spool = tmp_path / "spool"
    for i, n in enumerate(("issuer", "alice", "bob")):
        SpoolPublisher(spool, n, provider=_node_provider(n, i + 1)).publish()

    parent = MetricsProvider()
    agg = FleetAggregator(spool, provider=parent)
    text = agg.collect()
    types = validate_prometheus(text)   # {family: type}, raises on error

    for n in ("issuer", "alice", "bob"):
        assert f'node="{n}"' in text
    # the federation observes itself, inside the same document
    assert "fleet_nodes 3.0" in text
    assert types["fleet_nodes"] == "gauge"
    assert types["serve_requests_total"] == "counter"
    assert 'fleet_node_age_seconds{node="alice"}' in text

    doc = agg.summary()
    assert set(doc["nodes"]) == {"issuer", "alice", "bob"}
    assert doc["last_collect"]["samples"] > 0
    assert doc["last_collect"]["conflicts"] == {}


def test_tenant_slo_families_federate_with_fleet_tenant_count(tmp_path):
    """Per-tenant SLO families cross the federation untouched (only the
    ``node`` dimension is added), and the aggregator publishes
    ``fleet_tenants`` — distinct tms_ids across the merged document."""
    spool = tmp_path / "spool"
    for n, tenants in (("n0", ("alice", "bob")), ("n1", ("bob", "carol"))):
        p = MetricsProvider()
        p.describe("slo_tenant_burn_rate",
                   "Per-tenant error budget burn rate.")
        p.describe("slo_fairness_index", "Jain fairness index.")
        for t in tenants:
            p.gauge("slo_tenant_burn_rate", tms_id=t, window="60s").set(1.0)
        p.gauge("slo_fairness_index", basis="throughput").set(1.0)
        SpoolPublisher(spool, n, provider=p).publish()

    parent = MetricsProvider()
    agg = FleetAggregator(spool, provider=parent)
    text = agg.collect()
    types = validate_prometheus(text)

    assert types["slo_tenant_burn_rate"] == "gauge"
    assert types["slo_fairness_index"] == "gauge"
    # family names unchanged; node label joined onto the tenant series
    assert ('slo_tenant_burn_rate{tms_id="alice",window="60s",node="n0"} '
            '1.0') in text
    # alice, bob, carol — bob counted once despite living on both nodes
    assert "fleet_tenants 3.0" in text
    assert types["fleet_tenants"] == "gauge"


def test_federated_metrics_and_fleetz_over_http(tmp_path):
    from fabric_token_sdk_tpu.obs import TelemetryConfig, TelemetryServer

    spool = tmp_path / "spool"
    for n in ("n0", "n1", "n2"):
        SpoolPublisher(spool, n, provider=_node_provider(n, 2)).publish()
    parent = MetricsProvider()
    server = TelemetryServer(TelemetryConfig(port=0), provider=parent)
    server.attach_federator(FleetAggregator(spool, provider=parent))
    url = server.start()
    try:
        with urllib.request.urlopen(url + "/metrics", timeout=10.0) as r:
            text = r.read().decode()
        with urllib.request.urlopen(url + "/fleetz", timeout=10.0) as r:
            fleetz = json.loads(r.read().decode())
    finally:
        server.stop()
    validate_prometheus(text)
    assert 'node="n2"' in text
    # the scrape itself is accounted, un-labelled (parent's own registry)
    assert "telemetry_scrapes_total" in text
    assert fleetz["enabled"] is True
    assert set(fleetz["nodes"]) == {"n0", "n1", "n2"}


def test_fleetz_disabled_without_federator():
    from fabric_token_sdk_tpu.obs import TelemetryConfig, TelemetryServer

    server = TelemetryServer(TelemetryConfig(port=0),
                             provider=MetricsProvider())
    url = server.start()
    try:
        with urllib.request.urlopen(url + "/fleetz", timeout=10.0) as r:
            doc = json.loads(r.read().decode())
    finally:
        server.stop()
    assert doc == {"enabled": False}
