"""Tier-1 guard for the multichip dryrun path (parallel/dryrun.py).

The contract under test: a dryrun can END WELL or END DIAGNOSED — never
vanish. The monitor streams the worker's output to a log, watches its
per-phase heartbeat with a StallDetector, and rewrites the report JSON
on every poll tick, so ``rc=124 with an empty report`` is impossible by
construction. Scripted children prove the three terminal shapes cheaply
(success / crash / stall); the real-worker test then runs the actual
sharded-MSM light leg on 8 simulated host CPU devices
(``--xla_force_host_platform_device_count``) under the same watch.
"""

import json
import sys
import textwrap

import pytest

from fabric_token_sdk_tpu.parallel.dryrun import monitor

# scripted child: beats phases over the monitor's heartbeat protocol
# (raw JSON lines — no repo imports, so these tests stay fast)
_CHILD_PRELUDE = textwrap.dedent("""\
    import json, os, sys, time
    def beat(phase, detail=""):
        with open(os.environ["FTS_HEARTBEAT_FILE"], "a") as f:
            f.write(json.dumps({"t": time.time(), "phase": phase,
                                "detail": detail,
                                "pid": os.getpid()}) + "\\n")
            f.flush()
    """)


def _scripted(body: str) -> list[str]:
    return [sys.executable, "-u", "-c", _CHILD_PRELUDE + textwrap.dedent(body)]


def _monitor(tmp_path, body, **kw):
    kw.setdefault("grace_s", 10.0)
    kw.setdefault("poll_s", 0.1)
    kw.setdefault("default_deadline_s", 30.0)
    return monitor(8, report_path=str(tmp_path / "report.json"),
                   child_argv=_scripted(body), **kw)


def test_monitor_success_reports_final_phase(tmp_path):
    report = _monitor(tmp_path, """
        beat("jax_init"); print("starting", flush=True)
        beat("verify"); beat("done", "all verdicts True")
        print("finished", flush=True)
        """)
    assert report["ok"] and report["rc"] == 0 and not report["stalled"]
    assert report["schema"] == "fts-multichip-v2"
    assert report["phase"] == "done"
    assert report["diagnosis"] == "completed"
    assert "finished" in report["tail"]
    # the on-disk artifact matches what the caller got
    disk = json.loads((tmp_path / "report.json").read_text())
    assert disk["phase"] == "done" and disk["ok"] is True


def test_monitor_crash_is_phase_attributed_with_tail(tmp_path):
    report = _monitor(tmp_path, """
        beat("pp_setup")
        print("about to fail: boom detail", flush=True)
        sys.exit(3)
        """)
    assert not report["ok"] and report["rc"] == 3
    assert report["phase"] == "pp_setup"
    assert "rc=3" in report["diagnosis"]
    assert "pp_setup" in report["diagnosis"]
    assert "boom detail" in report["tail"]


def test_monitor_stall_is_detected_attributed_and_killed(tmp_path):
    report = _monitor(tmp_path, """
        beat("verify")
        print("entering the wedge", flush=True)
        time.sleep(120)
        """, deadlines={"verify": 1.0})
    assert report["stalled"] is True and not report["ok"]
    assert report["phase"] == "verify"
    assert "stalled in phase 'verify'" in report["diagnosis"]
    assert report["last_heartbeat_age_s"] >= 1.0
    assert "entering the wedge" in report["tail"]
    # the worker was actually killed, not left behind
    assert report["rc"] is not None and report["rc"] != 0
    disk = json.loads((tmp_path / "report.json").read_text())
    assert disk["stalled"] is True and disk["phase"] == "verify"


def test_monitor_child_that_never_beats_trips_no_heartbeat(tmp_path):
    report = _monitor(tmp_path, """
        print("no beats ever", flush=True)
        time.sleep(120)
        """, grace_s=1.0)
    assert report["stalled"] is True
    assert report["phase"] == "(no heartbeat)"
    assert "no beats ever" in report["tail"]


def test_monitor_seeds_tail_when_worker_produced_no_output(tmp_path):
    """A worker that dies before its first print must still leave a
    non-empty tail naming the phase and diagnosis — the historical
    ``rc=124, tail=""`` artifact is impossible by construction."""
    report = _monitor(tmp_path, """
        sys.exit(5)
        """)
    assert not report["ok"] and report["rc"] == 5
    assert report["phase"] == "spawn"
    assert "rc=5" in report["diagnosis"]
    assert report["tail"], "tail must never be empty"
    assert "no worker output captured" in report["tail"]
    assert "rc=5" in report["tail"]
    disk = json.loads((tmp_path / "report.json").read_text())
    assert disk["tail"] == report["tail"]


def test_monitor_total_timeout_bounds_a_healthy_looking_run(tmp_path):
    """A worker that heartbeats forever (no per-phase stall ever fires)
    must still be bounded by ``total_timeout_s`` — and the kill is
    reported as a budget-exceeded stall, not a bare timeout."""
    report = _monitor(tmp_path, """
        print("beating forever", flush=True)
        while True:
            beat("verify"); time.sleep(0.1)
        """, deadlines={"verify": 60.0}, total_timeout_s=1.5)
    assert report["stalled"] is True and not report["ok"]
    assert report["phase"] == "verify"
    assert "total dryrun budget exceeded" in report["diagnosis"]
    assert "total_timeout_s=2s" in report["diagnosis"]   # 1.5 -> :.0f
    assert report["elapsed_s"] < 30.0
    assert report["rc"] is not None and report["rc"] != 0
    assert "beating forever" in report["tail"]


@pytest.mark.slow
def test_real_full_production_dryrun_on_8_simulated_devices(tmp_path):
    """The full multichip dryrun: the production 16-bit verifier built
    with ``mesh=make_mesh(8, dp=4, tp=2)``, a sharded verify of real
    proofs, and a tamper check that must flip exactly row 0 — the run
    the driver's MULTICHIP rounds execute. Slow-marked: first-compile
    of the fused sharded chunk program costs minutes per shape on the
    1-core gate host; tier-1 covers the same path in-process via
    tests/test_range_verifier_sharded.py."""
    report = monitor(
        8, light=False, report_path=str(tmp_path / "full.json"),
        poll_s=1.0, total_timeout_s=5400.0)
    assert report["schema"] == "fts-multichip-v2"
    assert report["phase"] not in ("", "spawn"), report
    assert report["diagnosis"], report
    assert report["tail"], "worker produced no output at all"
    if not report["ok"]:
        raise AssertionError(
            f"full dryrun failed (but was attributed): "
            f"{report['diagnosis']}\n--- tail ---\n{report['tail']}")
    assert report["phase"] == "done"
    assert "tamper check flipped row 0 only" in report["tail"]


def test_real_light_dryrun_on_8_simulated_devices(tmp_path):
    """The actual worker: mesh build + sharded-MSM identity check on 8
    simulated host devices, under the stall detector. It must either
    complete or be killed WITH a phase-attributed diagnosis — a bare
    timeout (empty phase, empty tail) fails this test in every branch."""
    report = monitor(
        8, light=True, report_path=str(tmp_path / "light.json"),
        deadlines={"jax_init": 240.0, "sharded_msm": 600.0},
        default_deadline_s=300.0, grace_s=90.0, poll_s=0.5,
        total_timeout_s=600.0)
    # attribution invariants hold on EVERY outcome
    assert report["schema"] == "fts-multichip-v2"
    assert report["phase"] not in ("", "spawn"), report
    assert report["diagnosis"], report
    assert report["tail"], "worker produced no output at all"
    if not report["ok"]:
        raise AssertionError(
            f"light dryrun failed (but was attributed): "
            f"{report['diagnosis']}\n--- tail ---\n{report['tail']}")
    assert report["phase"] == "done"
    assert "light run complete" in report["tail"]
