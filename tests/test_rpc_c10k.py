"""C10k front door (serve/rpc.py sharded loops + columnar RESULT_BATCH
egress): codec round-trip and adversity, protocol-v4 negotiation with
v1/v3 legacy fallback, loop-sharded connection ownership, coalesced
wakeup accounting, EMFILE accept backoff, draining GOAWAY across all
loops, and a few-hundred-connection smoke.

Crypto-free on :class:`StubZK` like tests/test_rpc.py (whose harness
and helpers this file reuses), so everything here is tier-1. The raw
plain-socket peers deliberately omit ``"v"`` from HELLO — the server
must treat them as protocol v1 and keep per-row pickled RESULT frames;
only a peer that declares ``v>=4`` may receive columnar RESULT_BATCH.
"""

import errno
import random
import threading
import time

import pytest

from fabric_token_sdk_tpu.obs import GLOBAL
from fabric_token_sdk_tpu.obs.tracing import CONTEXT_WIRE_SIZE, SpanContext
from fabric_token_sdk_tpu.serve import (ColumnarError, RpcConfig,
                                        ScratchPool, ServeConfig,
                                        decode_result_batch,
                                        encode_result_batch)
from fabric_token_sdk_tpu.serve.columnar import result_batch_nbytes
from fabric_token_sdk_tpu.serve.rpc import (HELLO, RESULT, RESULT_BATCH,
                                            RPC_OK, SUBMIT, WELCOME,
                                            RpcServer, recv_frame_sock,
                                            send_frame_sock)
from test_rpc import (_assert_server_alive, _await_count, _client, _count,
                      _handshake, _Harness, _raw_conn)


def _sharded(n_loops=4, serve_cfg=None):
    return _Harness(serve_cfg=serve_cfg,
                    rpc_cfg=RpcConfig(n_loops=n_loops))


# -------------------------------------------------------- codec (pure)
def test_result_batch_codec_roundtrip_with_trace():
    tc = SpanContext(trace_id=0xABCDEF, span_id=77).to_bytes()
    rows = [
        (9001, 0, "ok", True, "device", tc),
        (9001, 1, "ok", False, "device", tc),
        (9001, 2, "shed_deadline", None, "", None),
        ((1 << 64) - 1, 0, "ok", True, "host", tc),
    ]
    payload, traced = encode_result_batch(rows)
    assert traced is True
    batch = decode_result_batch(payload)
    assert batch.n_rows == 4
    assert batch.nbytes == len(payload)
    # the trace column costs 17 bytes/row on top of the 15-byte columns
    assert len(payload) >= result_batch_nbytes(4, 0, traced=True)
    assert batch.req_id.tolist() == [9001, 9001, 9001, (1 << 64) - 1]
    assert batch.row_idx.tolist() == [0, 1, 2, 0]
    assert [batch.status(i) for i in range(4)] == \
        ["ok", "ok", "shed_deadline", "ok"]
    assert [batch.verdict_value(i) for i in range(4)] == \
        [True, False, None, True]
    assert [batch.served(i) for i in range(4)] == \
        ["device", "device", "", "host"]
    assert batch.trace_cell(0) == tc and len(tc) == CONTEXT_WIRE_SIZE
    assert batch.trace_cell(2) is None  # all-zero cell -> no context


def test_result_batch_codec_fuzz_shapes():
    rng = random.Random(0xC10C)
    statuses = ["ok", "shed_queue_full", "deadline_miss", "error"]
    for _ in range(25):
        n = rng.randint(1, 300)
        traced_run = rng.random() < 0.5
        rows = []
        for i in range(n):
            verdict = rng.choice([True, False, None])
            tc = (SpanContext(rng.getrandbits(48), rng.getrandbits(32))
                  .to_bytes() if traced_run and rng.random() < 0.7
                  else None)
            rows.append((rng.getrandbits(64), i, rng.choice(statuses),
                         verdict, rng.choice(["device", "host", ""]), tc))
        payload, traced = encode_result_batch(rows)
        batch = decode_result_batch(payload)
        assert batch.n_rows == n
        for i, (rid, idx, st, vd, sv, tc) in enumerate(rows):
            assert int(batch.req_id[i]) == rid
            assert int(batch.row_idx[i]) == idx
            assert batch.status(i) == st
            assert batch.verdict_value(i) == vd
            assert batch.served(i) == sv
            if traced:
                assert batch.trace_cell(i) == tc
        if not traced:
            assert not any(r[5] for r in rows)


def test_result_batch_table_overflow_is_columnar_error():
    # >=256 distinct interned strings cannot fit u8 indices; the
    # encoder must refuse (the server then falls back to legacy RESULT)
    rows = [(1, i, f"status_{i}", True, "", None) for i in range(300)]
    with pytest.raises(ColumnarError):
        encode_result_batch(rows)


def test_legacy_fallback_regroups_rows_by_request():
    tc = SpanContext(5, 6).to_bytes()
    rows = [(7, 1, "ok", False, "device", tc),
            (7, 0, "ok", True, "device", tc),
            (8, 0, "shed_deadline", None, "", None)]
    replies = {r["req_id"]: r for r in RpcServer._legacy_replies(rows)}
    assert replies[7]["verdicts"] == [True, False]  # row_idx order
    assert replies[7]["statuses"] == ["ok", "ok"]
    assert replies[7]["served_by"] == ["device"]
    assert replies[7]["tc"] == tc
    assert replies[8]["verdicts"] == [None]
    assert "tc" not in replies[8]


def test_scratch_pool_reuses_size_classes():
    pool = ScratchPool(max_per_class=2, max_class_bytes=1 << 20)
    a = pool.acquire(100)
    assert len(a) == 4096 and pool.misses == 1  # floor class
    pool.release(a)
    b = pool.acquire(4000)
    assert b is a and pool.hits == 1  # same class -> recycled
    pool.release(b)
    big = pool.acquire(1 << 21)  # beyond max_class_bytes: never cached
    pool.release(big)
    assert pool.acquire(1 << 21) is not big


# ------------------------------------------- negotiation + egress paths
def test_v4_client_roundtrip_rides_result_batch():
    GLOBAL.reset()
    with _Harness() as h:
        cli = _client(h.address, tms_id="alpha")
        try:
            out = cli.submit_range([True, False, True, True], [None] * 4)
            assert out.tolist() == [True, False, True, True]
            assert cli.server_version == 4  # negotiated in WELCOME
            # verdicts moved as ONE columnar frame, not 4 pickled rows
            _await_count("rpc_result_batch_frames_total", 1, role="server")
            assert _count("rpc_result_batch_rows_total", role="server") == 4
            _await_count("rpc_result_batch_frames_total", 1, role="client")
            assert _count("rpc_result_batch_rows_total", role="client") == 4
            assert _count("rpc_result_batch_bytes_total", role="server") > 0
        finally:
            cli.close()


def test_v1_raw_peer_keeps_pickled_result():
    GLOBAL.reset()
    with _Harness() as h:
        sock = _handshake(h.address)  # HELLO without "v" -> protocol v1
        try:
            send_frame_sock(sock, SUBMIT, {
                "req_id": 1, "kind": "range", "rows": 2,
                "payload": ([True, False], [None, None])})
            frame = _recv_result(sock)
            assert frame[0] == RESULT  # legacy pickled reply, never v4
            assert frame[1]["status"] == RPC_OK
            assert frame[1]["verdicts"] == [True, False]
            assert _count("rpc_result_batch_frames_total", role="server") \
                == 0
        finally:
            sock.close()


def _recv_result(sock, want=RESULT):
    """Skip CREDIT/housekeeping frames until the wanted type arrives."""
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        try:
            frame = recv_frame_sock(sock, body_timeout_s=5.0)
        except TimeoutError:
            continue
        assert frame is not None, "peer closed before the reply"
        if frame[0] == want:
            return frame
    raise AssertionError(f"no frame of type {want} within deadline")


def test_raw_v4_peer_gets_result_batch_with_trace_echo():
    GLOBAL.reset()
    with _Harness() as h:
        sock = _raw_conn(h.address)
        try:
            send_frame_sock(sock, HELLO, {  # declaring protocol v4
                "tms_id": "rawv4", "t": time.time(), "v": 4})
            welcome = recv_frame_sock(sock, body_timeout_s=5.0)
            assert welcome[0] == WELCOME and welcome[1]["v"] == 4
            tc = SpanContext(trace_id=0xFEED, span_id=3).to_bytes()
            send_frame_sock(sock, SUBMIT, {
                "req_id": 42, "kind": "range", "rows": 3, "tc": tc,
                "payload": ([True, True, False], [None] * 3)})
            frame = _recv_result(sock, want=RESULT_BATCH)
            batch = decode_result_batch(frame[1])
            assert batch.req_id.tolist() == [42, 42, 42]
            assert [batch.verdict_value(i) for i in range(3)] == \
                [True, True, False]
            # the client's context rides the trace column, echoed back
            assert batch.trace_cell(0) == tc

            # a poisoned context is counted + dropped, the row is still
            # SERVED (columnar, just untraced) — never failed
            send_frame_sock(sock, SUBMIT, {
                "req_id": 43, "kind": "range", "rows": 1,
                "tc": b"\x01garbage", "payload": ([True], [None])})
            frame = _recv_result(sock, want=RESULT_BATCH)
            batch = decode_result_batch(frame[1])
            assert batch.req_id.tolist() == [43]
            assert batch.verdict_value(0) is True
            assert batch.trace_cell(0) is None
            assert _count("trace_drops_total") >= 1
        finally:
            sock.close()


# --------------------------------------------------- wakeup coalescing
def test_wakeups_coalesce_one_per_drain_cycle():
    GLOBAL.reset()
    with _Harness() as h:
        cli = _client(h.address, tms_id="coal")
        try:
            out = cli.submit_range([True] * 8, [None] * 8)
            assert out.tolist() == [True] * 8
            _await_count("rpc_result_batch_rows_total", 8, role="server")
            # 8 verdict rows cost ONE frame and ONE wakeup — a
            # doorbell-per-result design would count 8 of each
            assert _count("rpc_result_batch_frames_total",
                          role="server") == 1
            assert _count("rpc_wakeups_total") == 1
        finally:
            cli.close()


def test_wakeups_never_exceed_frames_under_concurrency():
    GLOBAL.reset()
    with _Harness(serve_cfg=ServeConfig(buckets=(8,),
                                        max_wait_s=0.01)) as h:
        cli = _client(h.address, tms_id="burst")
        try:
            threads = [threading.Thread(
                target=lambda: cli.submit_range([True, False],
                                                [None, None]))
                for _ in range(12)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30.0)
            assert not any(t.is_alive() for t in threads)
            _await_count("rpc_result_batch_rows_total", 24, role="server")
            frames = _count("rpc_result_batch_frames_total", role="server")
            wakeups = _count("rpc_wakeups_total")
            # coalescing invariant: wakeups <= frames <= rows, and
            # every row arrived
            assert 1 <= wakeups <= frames <= 24
            assert _count("rpc_result_batch_rows_total",
                          role="server") == 24
        finally:
            cli.close()


# ------------------------------------------------------- loop sharding
def test_connections_spread_across_loops_no_cross_loop_writes():
    GLOBAL.reset()
    with _sharded(n_loops=4) as h:
        status = h.server.status()
        assert len(status["loops"]) == 4
        assert all(v["alive"] for v in status["loops"].values())
        clients = [_client(h.address, tms_id=f"t{i}") for i in range(12)]
        try:
            for cli in clients:
                out = cli.submit_range([True, False], [None, None])
                assert out.tolist() == [True, False]
                assert cli.server_version == 4
            status = h.server.status()
            used = {c["loop"] for c in status["connections"].values()}
            # 12 conns over 4 loops: all-on-one-shard is a ~2e-7 event
            # under SO_REUSEPORT hashing and impossible in handoff mode
            assert len(used) >= 2, status["loops"]
            assert sum(s["conns"] for s in status["loops"].values()) == 12
            # THE ownership invariant: every write happened on the
            # connection's owning loop
            assert status["ownership_violations"] == 0
        finally:
            for cli in clients:
                cli.close()
        assert h.server.ownership_violations == 0


def test_single_loop_mode_reports_one_shard():
    GLOBAL.reset()
    with _Harness() as h:
        status = h.server.status()
        assert len(status["loops"]) == 1
        cli = _client(h.address)
        try:
            assert cli.submit_range([True], [None]).tolist() == [True]
            assert h.server.ownership_violations == 0
        finally:
            cli.close()


def test_draining_goaway_frames_clean_across_loops():
    GLOBAL.reset()
    with _sharded(n_loops=4,
                  serve_cfg=ServeConfig(buckets=(8,), max_wait_s=0.05)) as h:
        clients = [_client(h.address, tms_id=f"d{i}") for i in range(6)]
        # Warm every connection, then freeze redials. The invariant under
        # test is the SERVER's: a draining stop abandons no write between
        # header and drain. A client that sees GOAWAY mid-call redials,
        # and _dial() closes the old socket — which can cut the server's
        # in-flight reply from the peer side and score a midframe close
        # the drain didn't cause. Keeping every socket open through the
        # stop makes the server-side invariant observable; a send on a
        # dead conn just sheds as WorkerUnavailable, which the accounting
        # below accepts.
        for cli in clients:
            assert cli.submit_range([True], [None]).tolist() == [True]
            cli._ensure_conn = lambda: None
        results, sheds = [], []

        def _caller(cli):
            from fabric_token_sdk_tpu.serve import WorkerUnavailable
            try:
                results.append(
                    cli.submit_range([True] * 8, [None] * 8).tolist())
            except WorkerUnavailable as exc:
                sheds.append(exc)

        threads = [threading.Thread(target=_caller, args=(c,))
                   for c in clients]
        try:
            for t in threads:
                t.start()
            time.sleep(0.02)  # let submits get in flight on all shards
            h.stop_server()
            for t in threads:
                t.join(timeout=30.0)
            assert not any(t.is_alive() for t in threads)
            assert len(results) + len(sheds) == 6
            for verdicts in results:
                assert verdicts == [True] * 8
            # THE invariant, now across four loops: the drain cut no
            # connection mid-frame on any shard
            assert h.server.frames_clean
            assert _count("rpc_goaways_total", role="server") >= 1
        finally:
            for cli in clients:
                cli.close()


# ------------------------------------------------- accept-loop adversity
def test_emfile_accept_backs_off_and_recovers():
    GLOBAL.reset()
    with _sharded(n_loops=2) as h:
        orig = h.server._accept
        fired = threading.Event()

        async def flaky(loop, lsock):
            if not fired.is_set():
                fired.set()
                raise OSError(errno.EMFILE, "too many open files")
            return await orig(loop, lsock)

        h.server._accept = flaky
        # first post-patch accept call sheds with reason=emfile, backs
        # off, and the NEXT iteration accepts the waiting client
        _assert_server_alive(h.address)
        _await_count("rpc_accept_shed_total", 1, reason="emfile")
        assert fired.is_set()
        assert _count("rpc_accept_shed_total", reason="emfile") >= 1
        # the acceptors survived: loops still accepting, server serves
        status = h.server.status()
        assert all(v["accepting"] for v in status["loops"].values())
        _assert_server_alive(h.address)


# --------------------------------------------------------------- smoke
def test_few_hundred_connections_smoke():
    GLOBAL.reset()
    n_conns = 200
    with _sharded(n_loops=4) as h:
        socks = []
        try:
            for i in range(n_conns):
                socks.append(_handshake(h.address, tms=f"smoke{i % 7}"))
            # every one of the 200 raw peers completed HELLO/WELCOME
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                status = h.server.status()
                total = sum(s["conns"] for s in status["loops"].values())
                if total >= n_conns:
                    break
                time.sleep(0.05)
            assert total >= n_conns, status["loops"]
            assert len({c["loop"] for c
                        in status["connections"].values()}) >= 2
            # a real client still round-trips under the connection load
            cli = _client(h.address, tms_id="underload")
            try:
                out = cli.submit_range([True, False, True], [None] * 3)
                assert out.tolist() == [True, False, True]
            finally:
                cli.close()
            assert h.server.ownership_violations == 0
        finally:
            for sock in socks:
                sock.close()
        _assert_server_alive(h.address)
