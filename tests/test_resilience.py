"""resilience/ — pure-logic state machines + serve-integration chaos.

Everything here runs against fake backends (no device, no crypto), so
these tests belong to the tier-1 gate: retry/breaker transition
correctness, seeded-jitter and fault-schedule determinism, and the serve
dispatcher surviving injected faults with bit-identical verdicts. The
real-device chaos smoke lives in tests/test_serve_smoke.py (marked
slow).
"""

import asyncio
import itertools
import threading
import time

import numpy as np
import pytest

from fabric_token_sdk_tpu.obs import GLOBAL as METRICS
from fabric_token_sdk_tpu.resilience import (STATE_CLOSED, STATE_HALF_OPEN,
                                             STATE_OPEN, CircuitBreaker,
                                             DispatchWatchdog, FaultInjector,
                                             InjectedPermanentError,
                                             InjectedTransientError,
                                             ResilienceConfig, RetryExhausted,
                                             RetryPolicy, TransientError,
                                             WatchdogTimeout)
from fabric_token_sdk_tpu.serve import (SERVED_BY_DEVICE, SERVED_BY_HOST,
                                        STATUS_ERROR, STATUS_OK,
                                        STATUS_SHUTDOWN, ServeConfig,
                                        VerificationService)

pytestmark = pytest.mark.chaos


def _counter_sum(name: str) -> float:
    return sum(v for (fam, _), v in METRICS.snapshot().items()
               if fam == name)


# ---------------------------------------------------------------- RetryPolicy
def test_retry_transient_then_success():
    calls = []

    def fn():
        calls.append(1)
        if len(calls) < 3:
            raise TransientError("hiccup")
        return "ok"

    slept = []
    out = RetryPolicy(max_attempts=3, base_s=0.01, seed=1).call(
        fn, sleep=slept.append)
    assert out == "ok"
    assert len(calls) == 3
    assert len(slept) == 2 and all(s >= 0.01 for s in slept)


def test_retry_permanent_error_propagates_unchanged():
    calls = []

    def fn():
        calls.append(1)
        raise ValueError("proof is simply wrong")

    with pytest.raises(ValueError):
        RetryPolicy(max_attempts=5).call(fn, sleep=lambda s: None)
    assert len(calls) == 1  # never retried


def test_retry_exhaustion_wraps_last_error():
    def fn():
        raise ConnectionError("still down")

    with pytest.raises(RetryExhausted) as ei:
        RetryPolicy(max_attempts=3, base_s=0.0).call(
            fn, op="unit", sleep=lambda s: None)
    assert ei.value.attempts == 3
    assert isinstance(ei.value.last_error, ConnectionError)
    assert "unit failed after 3 attempts" in str(ei.value)


def test_retry_classification():
    p = RetryPolicy()
    xla_exc = type("XlaRuntimeError", (RuntimeError,), {})()
    assert p.is_transient(TransientError("x"))
    assert p.is_transient(ConnectionError())
    assert p.is_transient(TimeoutError())
    assert p.is_transient(xla_exc)  # matched by type NAME, no jaxlib import
    assert not p.is_transient(ValueError("bad proof"))
    assert not p.is_transient(RuntimeError("generic"))


def test_jitter_schedule_is_seeded_and_bounded():
    take = lambda policy, n: list(itertools.islice(policy.delays(), n))
    a = take(RetryPolicy(base_s=0.01, cap_s=0.5, seed=42), 16)
    b = take(RetryPolicy(base_s=0.01, cap_s=0.5, seed=42), 16)
    c = take(RetryPolicy(base_s=0.01, cap_s=0.5, seed=43), 16)
    assert a == b, "same seed must replay the same backoff schedule"
    assert a != c, "different seeds must decorrelate"
    assert all(0.01 <= d <= 0.5 for d in a)


# -------------------------------------------------------------- CircuitBreaker
class _Clock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


def _breaker(**kw):
    clock = _Clock()
    kw.setdefault("window", 8)
    kw.setdefault("failure_threshold", 0.5)
    kw.setdefault("min_volume", 4)
    kw.setdefault("reset_timeout_s", 5.0)
    kw.setdefault("half_open_probes", 2)
    return CircuitBreaker(clock=clock, **kw), clock


def test_breaker_opens_on_failure_rate():
    br, _ = _breaker()
    for _ in range(3):
        br.record_failure()
    assert br.state == STATE_CLOSED  # below min_volume
    br.record_failure()
    assert br.state == STATE_OPEN
    assert not br.allow()


def test_breaker_stays_closed_below_threshold():
    br, _ = _breaker()
    for _ in range(10):
        br.record_success()
    br.record_failure()
    br.record_failure()
    assert br.state == STATE_CLOSED
    assert br.allow()


def test_breaker_half_open_probe_accounting():
    br, clock = _breaker()
    for _ in range(4):
        br.record_failure()
    assert br.state == STATE_OPEN
    clock.t += 5.0
    # first allow() flips to half-open and claims probe slot 1 of 2
    assert br.allow()
    assert br.state == STATE_HALF_OPEN
    assert br.allow()          # probe slot 2
    assert not br.allow()      # probe budget exhausted
    br.record_success()
    assert br.state == STATE_HALF_OPEN  # one success is not enough
    br.record_success()
    assert br.state == STATE_CLOSED
    assert br.failure_rate == 0.0  # window cleared on close


def test_breaker_half_open_failure_reopens_and_restarts_timer():
    br, clock = _breaker()
    for _ in range(4):
        br.record_failure()
    clock.t += 5.0
    assert br.allow()
    br.record_failure()
    assert br.state == STATE_OPEN
    clock.t += 4.9             # timer restarted at the probe failure
    assert not br.allow()
    clock.t += 0.2
    assert br.allow()


def test_breaker_force_open_is_latched():
    br, clock = _breaker()
    br.force_open()
    assert not br.allow()
    clock.t += 1000.0          # reset timeout never applies while forced
    assert not br.allow()
    br.force_close()
    assert br.state == STATE_CLOSED
    assert br.allow()


# --------------------------------------------------------------- FaultInjector
def test_fault_schedule_is_deterministic_per_seed():
    mk = lambda seed: FaultInjector(seed=seed, transient_rate=0.2,
                                    permanent_rate=0.05, stall_rate=0.1,
                                    corrupt_rate=0.05, sleep=lambda s: None)
    inj1, inj2, inj3 = mk(9), mk(9), mk(10)
    seq1 = [inj1.next_action() for _ in range(500)]
    seq2 = [inj2.next_action() for _ in range(500)]
    seq3 = [inj3.next_action() for _ in range(500)]
    assert seq1 == seq2, "same seed must produce the same fault schedule"
    assert seq1 != seq3
    assert {s for s in seq1 if s is not None} <= {"transient", "permanent",
                                                 "stall", "corrupt"}
    assert any(s is not None for s in seq1)


def test_fault_rates_validated():
    with pytest.raises(ValueError):
        FaultInjector(transient_rate=0.8, permanent_rate=0.3)
    with pytest.raises(ValueError):
        FaultInjector(transient_rate=-0.1)


def test_explicit_schedule_overrides_rates():
    slept = []
    inj = FaultInjector(seed=0, transient_rate=1.0,
                        schedule={0: "transient", 2: "stall",
                                  3: "permanent"},
                        stall_s=0.5, sleep=slept.append)
    with pytest.raises(InjectedTransientError):
        inj.fire("range.verify")        # call 0
    assert inj.fire("range.verify") is None  # call 1: scheduled clean
    assert inj.fire("range.verify") is None  # call 2: stall (sleeps)
    assert slept == [0.5]
    with pytest.raises(InjectedPermanentError):
        inj.fire("range.verify")        # call 3
    assert inj.injected["transient"] == 1
    assert inj.injected["permanent"] == 1
    assert inj.injected["stall"] == 1


def test_corrupt_verdicts_flips_exactly_one_row_deterministically():
    base = np.ones(16, dtype=bool)
    a = FaultInjector(seed=5).corrupt_verdicts(base)
    b = FaultInjector(seed=5).corrupt_verdicts(base)
    assert (a == b).all()
    assert (a != base).sum() == 1
    assert base.all(), "input vector must not be mutated"


def test_faulty_zk_shims_entry_points_and_forwards_the_rest():
    class _Range:
        def verify(self, proofs, coms):
            return np.ones(len(proofs), dtype=bool)

        last_path = "device"

    class _ZK:
        _range = _Range()
        pp = "sentinel-pp"

        def verify_block(self, transfers, issues):
            return (np.ones(len(transfers), dtype=bool),
                    np.ones(len(issues), dtype=bool))

    inj = FaultInjector(seed=0, schedule={0: "transient", 2: "corrupt"})
    faulty = inj.wrap(_ZK())
    assert faulty.pp == "sentinel-pp"            # passthrough
    assert faulty._range.last_path == "device"   # passthrough on the shim
    with pytest.raises(InjectedTransientError):
        faulty._range.verify([1, 2], [1, 2])     # call 0
    out = faulty._range.verify([1, 2], [1, 2])   # call 1: clean
    assert out.all()
    t_ok, _ = faulty.verify_block([("t",)], [])  # call 2: corrupt
    assert not t_ok.all()


# ------------------------------------------------------------------- Watchdog
def test_watchdog_abandons_hung_call_and_recovers():
    wd = DispatchWatchdog(timeout_s=0.05)
    release = threading.Event()

    async def run():
        with pytest.raises(WatchdogTimeout):
            await wd.run(release.wait, 5.0)
        # fresh executor thread: the next dispatch is not queued behind
        # the orphaned hung call
        return await wd.run(lambda: "alive")

    try:
        assert asyncio.run(run()) == "alive"
        assert wd.trips == 1
    finally:
        release.set()
        wd.shutdown(wait=False)


# --------------------------------------------------- serve/ chaos integration
class _TruthRange:
    """The payload IS the expected verdict: proofs are truthy/falsy."""

    def verify(self, proofs, commitments):
        return np.asarray([bool(p) for p in proofs], dtype=bool)


class _TruthZK:
    def __init__(self):
        self._range = _TruthRange()

    def verify_block(self, transfers, issues):
        return (np.asarray([bool(t[0]) for t in transfers], dtype=bool),
                np.asarray([bool(i[0]) for i in issues], dtype=bool))

    def prewarm_shapes(self, batch_sizes=(1,), include_block=True):
        return {b: 0.0 for b in batch_sizes}


class _TruthFallback:
    """Host-path stand-in with the same truth semantics as _TruthZK."""

    def __init__(self):
        self.batches = 0

    def verify_batch(self, batch):
        self.batches += 1
        return np.asarray([bool(r.payload[0]) for r in batch], dtype=bool)


def _fast_resilience(**kw):
    kw.setdefault("retry_attempts", 4)
    kw.setdefault("retry_base_s", 0.0)
    kw.setdefault("retry_cap_s", 0.0)
    kw.setdefault("breaker_min_volume", 10_000)  # keep closed under chaos
    kw.setdefault("watchdog_timeout_s", None)
    return ResilienceConfig(**kw)


def test_serve_chaos_transient_faults_bit_identical_no_hangs():
    inj = FaultInjector(seed=3, transient_rate=0.25, sleep=lambda s: None)
    zk = inj.wrap(_TruthZK())
    fb = _TruthFallback()
    svc = VerificationService(
        zk, config=ServeConfig(buckets=(8, 32), max_wait_s=0.005),
        resilience=_fast_resilience(), fallback=fb)
    expected = [i % 3 != 0 for i in range(32)]

    async def run():
        await svc.start(prewarm=False)
        results = await asyncio.wait_for(asyncio.gather(*[
            svc.submit_range(exp, object(), deadline_s=30.0)
            for exp in expected]), timeout=10.0)
        # dispatcher must survive chaos: a second wave still completes
        again = await asyncio.wait_for(
            svc.submit_range(True, object(), deadline_s=30.0), timeout=10.0)
        await svc.stop()
        return results, again

    results, again = asyncio.run(run())
    assert [r.status for r in results] == [STATUS_OK] * 32
    assert [r.accepted for r in results] == expected, \
        "verdicts must be bit-identical under injected transient faults"
    assert all(r.served_by in (SERVED_BY_DEVICE, SERVED_BY_HOST)
               for r in results)
    assert again.ok and again.accepted is True
    assert inj.injected["transient"] > 0, "chaos test injected nothing"


def test_serve_breaker_forced_open_routes_everything_to_host():
    zk = _TruthZK()
    fb = _TruthFallback()
    svc = VerificationService(
        zk, config=ServeConfig(buckets=(8,), max_wait_s=0.005),
        resilience=_fast_resilience(), fallback=fb)
    expected = [i % 2 == 0 for i in range(8)]

    async def run():
        await svc.start(prewarm=False)
        svc._breaker.force_open()
        results = await asyncio.wait_for(asyncio.gather(*[
            svc.submit_range(exp, object(), deadline_s=30.0)
            for exp in expected]), timeout=10.0)
        await svc.stop()
        return results

    results = asyncio.run(run())
    assert all(r.ok and r.served_by == SERVED_BY_HOST for r in results)
    assert [r.accepted for r in results] == expected, \
        "host fallback verdicts must be bit-identical"
    assert fb.batches > 0


def test_serve_permanent_fault_without_fallback_errors_promptly():
    inj = FaultInjector(seed=0, schedule={0: "permanent"})
    zk = inj.wrap(_TruthZK())  # no pp attribute -> no implicit fallback
    svc = VerificationService(
        zk, config=ServeConfig(buckets=(4,), max_wait_s=0.005),
        resilience=_fast_resilience())
    assert svc._fallback is None

    async def run():
        await svc.start(prewarm=False)
        res = await asyncio.wait_for(
            svc.submit_range(True, object(), deadline_s=30.0), timeout=10.0)
        await svc.stop()
        return res

    res = asyncio.run(run())
    assert res.status == STATUS_ERROR
    assert "InjectedPermanentError" in res.error


def test_serve_watchdog_trip_retries_on_fresh_thread():
    hang = threading.Event()
    calls = []

    class _HangOnceRange(_TruthRange):
        def verify(self, proofs, commitments):
            calls.append(1)
            if len(calls) == 1:
                hang.wait(5.0)  # first dispatch wedges
            return super().verify(proofs, commitments)

    zk = _TruthZK()
    zk._range = _HangOnceRange()
    svc = VerificationService(
        zk, config=ServeConfig(buckets=(4,), max_wait_s=0.005),
        resilience=_fast_resilience(watchdog_timeout_s=0.1))

    async def run():
        await svc.start(prewarm=False)
        res = await asyncio.wait_for(
            svc.submit_range(True, object(), deadline_s=30.0), timeout=10.0)
        await svc.stop()
        return res

    try:
        res = asyncio.run(run())
    finally:
        hang.set()
    assert res.ok and res.accepted is True
    assert res.served_by == SERVED_BY_DEVICE
    assert svc._watchdog.trips == 1


def test_serve_stop_timeout_resolves_stuck_requests_with_shutdown():
    hang = threading.Event()

    class _HungRange(_TruthRange):
        def verify(self, proofs, commitments):
            hang.wait(10.0)  # device wedged for the whole test
            return super().verify(proofs, commitments)

    zk = _TruthZK()
    zk._range = _HungRange()
    svc = VerificationService(
        zk, config=ServeConfig(buckets=(4,), max_wait_s=0.005))

    async def run():
        await svc.start(prewarm=False)
        task = asyncio.create_task(
            svc.submit_range(True, object(), deadline_s=30.0))
        await asyncio.sleep(0.1)  # let it dispatch into the hung call
        await asyncio.wait_for(svc.stop(timeout_s=0.2), timeout=5.0)
        return await asyncio.wait_for(task, timeout=5.0)

    try:
        res = asyncio.run(run())
    finally:
        hang.set()
    assert res.status == STATUS_SHUTDOWN
    assert "drain timeout" in res.error


def test_chaos_metrics_families_emitted():
    METRICS.reset()
    inj = FaultInjector(seed=1, transient_rate=0.4, sleep=lambda s: None)
    zk = inj.wrap(_TruthZK())
    svc = VerificationService(
        zk, config=ServeConfig(buckets=(8,), max_wait_s=0.005),
        resilience=_fast_resilience(), fallback=_TruthFallback())

    async def run():
        await svc.start(prewarm=False)
        await asyncio.wait_for(asyncio.gather(*[
            svc.submit_range(True, object(), deadline_s=30.0)
            for _ in range(32)]), timeout=10.0)
        await svc.stop()

    asyncio.run(run())
    assert _counter_sum("resil_injected_faults_total") > 0
    text = METRICS.prometheus_text()
    assert "resil_breaker_state" in text
    # retries and/or fallback batches depending on where faults landed
    assert (_counter_sum("resil_retries_total") > 0
            or _counter_sum("resil_fallback_batches_total") > 0)
