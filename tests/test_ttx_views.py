"""Per-step ttx view choreography (services/ttx_views.py) — the protocol
surface of reference token/services/ttx/{recipients,withdrawal,accept,
status}.go over real message sessions.

Covers: recipient exchange feeding a transfer, the full withdrawal
round-trip (request -> issuer assembly -> acceptance ack -> ordering ->
finality -> balances), ack signature verification, and status queries
from multiple nodes' perspectives.
"""

import pytest

from fabric_token_sdk_tpu.core import fabtoken
from fabric_token_sdk_tpu.services import ttx_views as tv
from fabric_token_sdk_tpu.services.auditor import AuditorNode
from fabric_token_sdk_tpu.services.db.sqldb import TxStatus
from fabric_token_sdk_tpu.services.identity.deserializer import Deserializer
from fabric_token_sdk_tpu.services.identity.x509 import new_signing_identity
from fabric_token_sdk_tpu.services.network.tcc import (MemoryLedger,
                                                       TokenChaincode)
from fabric_token_sdk_tpu.services.node import TokenNode
from fabric_token_sdk_tpu.services.ttx import SessionBus, TtxError


@pytest.fixture
def net():
    issuer_keys = new_signing_identity()
    auditor_keys = new_signing_identity()
    pp = fabtoken.setup(64)
    pp.issuer_ids = [issuer_keys.identity]
    pp.auditor = bytes(auditor_keys.identity)
    validator = fabtoken.new_validator(pp, Deserializer())
    cc = TokenChaincode(validator, MemoryLedger(), pp.serialize())
    bus = SessionBus()
    nodes = {
        "issuer": TokenNode("issuer", issuer_keys, bus, cc,
                            auditor_name="auditor"),
        "auditor": AuditorNode("auditor", auditor_keys, bus, cc,
                               auditor_name="auditor"),
    }
    for name in ("alice", "bob"):
        nodes[name] = TokenNode(name, new_signing_identity(), bus, cc,
                                auditor_name="auditor")
    return nodes, tv.ViewBus(bus)


class TestRecipientExchange:
    def test_exchange_returns_usable_identity(self, net):
        nodes, vbus = net
        ident, ai = tv.request_recipient_identity(vbus, "bob")
        assert nodes["bob"].owns_identity(ident)
        assert not nodes["alice"].owns_identity(ident)
        assert ai  # audit info present

    def test_exchange_unknown_node_fails(self, net):
        _, vbus = net
        with pytest.raises(TtxError, match="unknown node"):
            vbus.open_session("mallory", "recipient")

    def test_exchange_honors_wallet_id(self, net):
        """recipients.go:140-180 carries the wallet id: a named wallet
        answers with ITS identity, not the default wallet's."""
        from fabric_token_sdk_tpu.services.identity.wallet import \
            X509OwnerWallet

        nodes, vbus = net
        savings = X509OwnerWallet(new_signing_identity())
        nodes["bob"].wallets.register_owner_wallet("savings", savings)
        ident, _ = tv.request_recipient_identity(vbus, "bob",
                                                 wallet_id="savings")
        assert savings.owns(ident)
        assert not nodes["bob"].owner_wallet.owns(ident)

    def test_exchange_unknown_wallet_id_rejected(self, net):
        """An unknown wallet id must fail loudly, not silently hand out
        the default wallet (tokens would land in the wrong wallet)."""
        _, vbus = net
        with pytest.raises(TtxError, match="recipient exchange failed"):
            tv.request_recipient_identity(vbus, "bob",
                                          wallet_id="no-such-wallet")

    def test_exchanged_identity_feeds_transfer(self, net):
        nodes, vbus = net
        alice, bob = nodes["alice"], nodes["bob"]
        tx = alice.issue("issuer", "alice", "USD", hex(100))
        assert alice.execute(tx).status == "VALID"

        recipient = tv.request_recipient_identity(vbus, "bob")
        tx2 = alice.transfer("USD", hex(40), "bob", recipient=recipient)
        assert alice.execute(tx2).status == "VALID"
        assert bob.balance("USD") == 40
        assert alice.balance("USD") == 60


class TestWithdrawal:
    def test_full_withdrawal_roundtrip(self, net):
        nodes, vbus = net
        tx_id = tv.request_withdrawal(vbus, "alice", "issuer", "USD", 250)
        assert nodes["alice"].balance("USD") == 250
        # both sides recorded the tx and saw it confirmed
        assert nodes["alice"].ttxdb.get_status(tx_id) == TxStatus.CONFIRMED
        assert nodes["issuer"].ttxdb.get_status(tx_id) == TxStatus.CONFIRMED
        # the issuer holds alice's verified acceptance ack
        acks = nodes["issuer"].ttxdb.get_endorsement_acks(tx_id)
        assert nodes["alice"].identity() in acks

    def test_issuer_failure_after_acceptance_closes_record(self, net,
                                                           monkeypatch):
        """If the issuer dies between the requester's acceptance and
        ordering, no commit event ever fires — the requester's record must
        be closed out as Deleted, not stuck Pending forever."""
        nodes, vbus = net

        def boom(tx, cc):
            raise RuntimeError("orderer unreachable")

        monkeypatch.setattr(tv, "ordering_and_finality", boom)
        with pytest.raises(TtxError, match="withdrawal failed"):
            tv.request_withdrawal(vbus, "alice", "issuer", "USD", 25)
        recs = nodes["alice"].ttxdb.query_transactions()
        assert len(recs) == 1
        assert nodes["alice"].ttxdb.get_status(recs[0].tx_id) \
            == TxStatus.DELETED
        assert nodes["alice"].balance("USD") == 0
        # the ISSUER's own record closes out too (it stored PENDING rows
        # before ordering), and it stops watching the dead request
        vbus.join()
        issuer = nodes["issuer"]
        assert issuer.ttxdb.get_status(recs[0].tx_id) == TxStatus.DELETED
        assert recs[0].tx_id not in issuer._watched

    def test_withdrawal_from_non_issuer_fails(self, net):
        nodes, vbus = net
        with pytest.raises(TtxError, match="withdrawal"):
            tv.request_withdrawal(vbus, "alice", "bob", "USD", 10)
        assert nodes["alice"].balance("USD") == 0


class TestAcceptAndStatus:
    def test_distribute_for_acceptance_collects_verified_acks(self, net):
        nodes, vbus = net
        alice, bob = nodes["alice"], nodes["bob"]
        tx = alice.issue("issuer", "alice", "USD", hex(100))
        assert alice.execute(tx).status == "VALID"
        tx2 = alice.transfer("USD", hex(30), "bob")
        # route the distribution through the accept view instead of the
        # direct dispatch: endorsements first, without distribution
        dist, tx2.distribution = tx2.distribution, []
        from fabric_token_sdk_tpu.services.ttx import collect_endorsements

        collect_endorsements(tx2, alice.bus, alice.auditor_name)
        tx2.distribution = dist
        acks = tv.distribute_for_acceptance(vbus, tx2,
                                            deserializer=Deserializer(),
                                            parties=["alice", "bob"])
        assert set(acks) == {"alice", "bob"}  # change output + payment
        alice._watched[tx2.tx_id] = tx2.request
        alice.ttxdb.add_token_request(tx2.tx_id, tx2.request.to_bytes())
        from fabric_token_sdk_tpu.services.ttx import ordering_and_finality

        ev = ordering_and_finality(tx2, alice.cc)
        assert ev.status == "VALID"
        assert bob.balance("USD") == 30

    def test_status_view_across_nodes(self, net):
        nodes, vbus = net
        tx_id = tv.request_withdrawal(vbus, "alice", "issuer", "USD", 50)
        assert tv.request_status(vbus, "alice", tx_id) == TxStatus.CONFIRMED
        assert tv.request_status(vbus, "issuer", tx_id) == TxStatus.CONFIRMED
        # a node with no record reports unknown
        assert tv.request_status(vbus, "bob", tx_id) == TxStatus.UNKNOWN


class TestZkWithdrawalViews:
    """The same view choreography with the zkatdlog driver: commitment
    openings actually ride the acceptance session."""

    @pytest.fixture
    def zknet(self):
        from fabric_token_sdk_tpu.core import zkatdlog
        from fabric_token_sdk_tpu.core.zkatdlog.driver import \
            ZkDlogDriverService
        from fabric_token_sdk_tpu.crypto import setup

        pp = setup.setup(16)
        issuer_keys = new_signing_identity()
        auditor_keys = new_signing_identity()
        pp.issuer_ids = [issuer_keys.identity]
        pp.auditor = bytes(auditor_keys.identity)
        validator = zkatdlog.new_validator(pp, Deserializer(), device=False)
        cc = TokenChaincode(validator, MemoryLedger(), pp.serialize())
        bus = SessionBus()
        driver = ZkDlogDriverService(pp, device=False)
        nodes = {
            "issuer": TokenNode("issuer", issuer_keys, bus, cc,
                                precision=16, auditor_name="auditor",
                                driver=driver),
            "auditor": AuditorNode("auditor", auditor_keys, bus, cc,
                                   precision=16, auditor_name="auditor",
                                   driver=driver),
            "alice": TokenNode("alice", new_signing_identity(), bus, cc,
                               precision=16, auditor_name="auditor",
                               driver=driver),
        }
        return nodes, tv.ViewBus(bus)

    def test_zk_withdrawal_openings_over_session(self, zknet):
        nodes, vbus = zknet
        tx_id = tv.request_withdrawal(vbus, "alice", "issuer", "EUR", 77)
        # the opening arrived over the session and was ingested at
        # finality: the committed token deobfuscates to alice's balance
        assert nodes["alice"].balance("EUR") == 77
        assert tv.request_status(vbus, "alice", tx_id) == TxStatus.CONFIRMED
