"""Parity of the native Fr module against the pure-Python BN254 oracle.

Every exported batch function is pinned element-by-element to
crypto/bn254 semantics (which themselves mirror mathlib/gnark Fr) over
random and adversarial values (0, 1, r-1, values straddling reduction).
"""

import random

import pytest

from fabric_token_sdk_tpu.crypto import bn254
from fabric_token_sdk_tpu.models.range_verifier import _fold_coefficients
from fabric_token_sdk_tpu.native import load_frmont

R = bn254.R
frmont = load_frmont()

pytestmark = pytest.mark.skipif(frmont is None,
                                reason="no C toolchain for _frmont")

rng = random.Random(42)


def pack(vals):
    return b"".join(v.to_bytes(32, "little") for v in vals)


def unpack(raw):
    return [int.from_bytes(raw[i:i + 32], "little")
            for i in range(0, len(raw), 32)]


EDGE = [0, 1, 2, R - 1, R - 2, R // 2, (1 << 255) % R]


def _rand(k):
    return [rng.randrange(R) for _ in range(k)]


def test_mul_add_sub_parity():
    a = EDGE + _rand(50)
    b = EDGE[::-1] + _rand(50)
    assert unpack(frmont.mul_many(pack(a), pack(b))) == \
        [bn254.fr_mul(x, y) for x, y in zip(a, b)]
    assert unpack(frmont.add_many(pack(a), pack(b))) == \
        [bn254.fr_add(x, y) for x, y in zip(a, b)]
    assert unpack(frmont.sub_many(pack(a), pack(b))) == \
        [bn254.fr_sub(x, y) for x, y in zip(a, b)]


def test_broadcast_scalar():
    a = _rand(17)
    s = _rand(1)
    assert unpack(frmont.mul_many(pack(a), pack(s))) == \
        [bn254.fr_mul(x, s[0]) for x in a]
    assert unpack(frmont.sub_many(pack(a), pack(s))) == \
        [bn254.fr_sub(x, s[0]) for x in a]


def test_addmul_parity():
    acc, a, b = _rand(23), _rand(23), _rand(23)
    assert unpack(frmont.addmul_many(pack(acc), pack(a), pack(b))) == \
        [bn254.fr_add(c, bn254.fr_mul(x, y))
         for c, x, y in zip(acc, a, b)]
    s = _rand(1)
    assert unpack(frmont.addmul_many(pack(acc), pack(a), pack(s))) == \
        [bn254.fr_add(c, bn254.fr_mul(x, s[0])) for c, x in zip(acc, a)]


def test_powers_parity():
    y = _rand(1)[0]
    got = unpack(frmont.powers(pack([y]), 64))
    want = [pow(y, i, R) for i in range(64)]
    assert got == want
    got_inv = unpack(frmont.powers(pack([y]), 64, True))
    y_inv = bn254.fr_inv(y)
    assert got_inv == [pow(y_inv, i, R) for i in range(64)]


def test_batch_inv_parity():
    a = [v for v in EDGE if v] + _rand(40)
    assert unpack(frmont.batch_inv(pack(a))) == bn254.fr_batch_inv(a)
    with pytest.raises(ZeroDivisionError):
        frmont.batch_inv(pack([1, 0, 2]))


@pytest.mark.parametrize("n_rounds,invert", [(4, True), (4, False),
                                             (6, True), (6, False)])
def test_fold_coeffs_parity(n_rounds, invert):
    n = 1 << n_rounds
    ch = _rand(n_rounds)
    inv = [bn254.fr_inv(x) for x in ch]
    got = unpack(frmont.fold_coeffs(pack(ch), pack(inv), n, invert))
    want = _fold_coefficients(list(zip(ch, inv)), n, invert_first_half=invert)
    assert got == want


def test_phase_a_parity():
    n = 16
    y, z, delta = _rand(3)

    class _P:  # the slice of RangeVerifierParams phase_a reads
        bit_length = n

    class _D:
        pass

    class _Proof:
        pass

    # drive the Python reference directly on the same challenge values
    from fabric_token_sdk_tpu.crypto import rp as _rp
    from fabric_token_sdk_tpu.models import range_verifier as rv

    raw = frmont.phase_a(n, pack([y, z, delta]))
    vals = unpack(raw)
    y_pows, yinv_pows = vals[:n], vals[n:2 * n]
    pol_eval = vals[2 * n]
    k_fixed = vals[2 * n + 1:]

    assert y_pows == [pow(y, i, R) for i in range(n)]
    y_inv = bn254.fr_inv(y)
    assert yinv_pows == [pow(y_inv, i, R) for i in range(n)]
    z_sq = bn254.fr_mul(z, z)
    ipy = sum(y_pows) % R
    ip2 = sum(pow(2, i, R) for i in range(n)) % R
    want_pe = bn254.fr_sub(bn254.fr_mul(bn254.fr_sub(z, z_sq), ipy),
                           bn254.fr_mul(bn254.fr_mul(z_sq, z), ip2))
    assert pol_eval == want_pe
    for i in range(n):
        want = bn254.fr_add(z, bn254.fr_mul(z_sq, bn254.fr_mul(
            pow(2, i, R), yinv_pows[i])))
        assert k_fixed[i] == want
    assert k_fixed[n] == (R - delta) % R
    assert k_fixed[n + 1] == (R - z) % R


def test_phase_b_parity():
    """Fused phase_b pinned against the pure-Python scalar assembly."""
    from fabric_token_sdk_tpu.models import range_verifier as rv

    n, rounds = 16, 4
    a, b, z, x, x_ipa, ip, tau, delta = _rand(8)
    y = _rand(1)[0]
    y_inv = bn254.fr_inv(y)
    yinv_pows = [pow(y_inv, i, R) for i in range(n)]
    pol_eval = _rand(1)[0]
    round_ch = _rand(rounds)
    round_inv = [bn254.fr_inv(c) for c in round_ch]

    raw = frmont.phase_b(
        n, rounds, pack([a, b, z, x, x_ipa, ip, tau, delta, pol_eval]),
        pack(yinv_pows), pack(round_ch), pack(round_inv))
    vals = unpack(raw)
    fixed, var = vals[:2 * n + 5], vals[2 * n + 5:]

    # reference computation (the Python loops of _host_phase_b)
    z_sq, x_sq = bn254.fr_mul(z, z), bn254.fr_mul(x, x)
    pairs = list(zip(round_ch, round_inv))
    a_coeffs = rv._fold_coefficients(pairs, n, invert_first_half=True)
    b_coeffs = rv._fold_coefficients(pairs, n, invert_first_half=False)
    want_fixed = []
    for j in range(n):
        want_fixed.append(bn254.fr_add(bn254.fr_mul(a, a_coeffs[j]), z))
    for j in range(n):
        c = bn254.fr_mul(bn254.fr_mul(b, b_coeffs[j]), yinv_pows[j])
        c = bn254.fr_sub(c, z)
        c = bn254.fr_sub(c, bn254.fr_mul(z_sq, bn254.fr_mul(
            pow(2, j, R), yinv_pows[j])))
        want_fixed.append(c)
    want_fixed.append(delta)
    want_fixed.append(bn254.fr_mul(x_ipa, bn254.fr_sub(
        bn254.fr_mul(a, b), ip)))
    want_fixed.append(bn254.fr_sub(ip, pol_eval))
    want_fixed.append(tau)
    want_fixed.append(0)
    assert fixed == want_fixed

    want_var = [(R - x) % R, R - 1]
    for xr in round_ch:
        want_var.append((R - bn254.fr_mul(xr, xr)) % R)
    for xi in round_inv:
        want_var.append((R - bn254.fr_mul(xi, xi)) % R)
    want_var += [(R - x) % R, (R - x_sq) % R, (R - z_sq) % R]
    assert var == want_var


def test_points_to_limbs_parity():
    """Native Fp conversion == the Python Montgomery projective encoder."""
    import numpy as np

    from fabric_token_sdk_tpu.ops import limbs

    pts = [bn254.G1_GENERATOR, bn254.G1_IDENTITY,
           bn254.g1_mul(bn254.G1_GENERATOR, 7),
           bn254.g1_mul(bn254.G1_GENERATOR, R - 2)]
    want = np.stack([limbs.point_to_projective_limbs(p) for p in pts])
    got = limbs.points_to_projective_limbs(pts)
    assert np.array_equal(got, want)


def test_shape_errors():
    with pytest.raises(ValueError):
        frmont.mul_many(b"\x00" * 31, b"\x00" * 32)
    with pytest.raises(ValueError):
        frmont.mul_many(b"\x00" * 64, b"\x00" * 96)
    with pytest.raises(ValueError):
        frmont.fold_coeffs(pack([1] * 3), pack([1] * 3), 16, True)
