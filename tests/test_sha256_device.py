"""Parity: the batched device SHA-256 vs hashlib (FIPS 180-4)."""

import hashlib
import secrets

import jax.numpy as jnp
import numpy as np

from fabric_token_sdk_tpu.ops import sha256 as dsha


def _check(messages: list[bytes]):
    L = len(messages[0])
    tail = dsha.pad_tail(L)
    padded = np.stack([
        np.concatenate([np.frombuffer(m, dtype=np.uint8), tail])
        for m in messages])
    words = np.asarray(dsha.digest_padded(jnp.asarray(padded)))
    got = dsha.digest_words_to_ints(words)
    want = [int.from_bytes(hashlib.sha256(m).digest(), "big")
            for m in messages]
    assert got == want


def test_single_block():
    _check([b"abc" + bytes(13)] * 2)


def test_multi_block_batch():
    msgs = [secrets.token_bytes(300) for _ in range(5)]
    _check(msgs)


def test_transcript_sized():
    # the x_ipa transcript shape: ~17 KB, 265 blocks
    msgs = [secrets.token_bytes(16944) for _ in range(3)]
    _check(msgs)


def test_block_boundary_lengths():
    for L in (55, 56, 64, 119, 120, 128):
        _check([secrets.token_bytes(L) for _ in range(2)])


def test_xipa_device_matches_host_assembly():
    """On-device transcript assembly + SHA == the host numpy/hashlib path
    (which itself is parity-pinned to the reference's ipa.go:159-173)."""
    from fabric_token_sdk_tpu.crypto import bn254
    from fabric_token_sdk_tpu.crypto import serialization as ser
    from fabric_token_sdk_tpu.models import range_verifier as rv

    class P:
        bit_length = 8          # small n: cheap layout, same code path
        rounds = 3
        left_gen_bytes = tuple(
            ser.g1_to_bytes(bn254.g1_mul(bn254.G1_GENERATOR, 3 + i))
            .hex().encode("ascii") for i in range(8))
        q_bytes = ser.g1_to_bytes(
            bn254.g1_mul(bn254.G1_GENERATOR, 99)).hex().encode("ascii")

    import numpy as np
    rng = np.random.default_rng(3)
    B = 4
    rgp = rng.integers(0, 256, size=(B, 8, 64), dtype=np.uint8)
    kb = rng.integers(0, 256, size=(B, 64), dtype=np.uint8)
    ips = [int(rng.integers(1, 1 << 62)) for _ in range(B)]

    class Proof:
        def __init__(self, ip):
            self.data = type("D", (), {"inner_product": ip})()

    proofs = [Proof(ip) for ip in ips]
    want = rv._xipa_batch(P, proofs, list(range(B)), rgp, kb)

    ip_np = np.frombuffer(
        b"".join(ser.zr_to_bytes(ip) for ip in ips),
        dtype=np.uint8).reshape(B, 32)
    words = np.asarray(rv._xipa_device_fn(P)(
        jnp.asarray(rgp), jnp.asarray(kb), jnp.asarray(ip_np)))
    from fabric_token_sdk_tpu.ops import sha256 as dsha

    got = [v % bn254.R for v in dsha.digest_words_to_ints(words)]
    assert got == want


def test_derive_pass1_scalars_matches_host():
    """Device-derived yinv powers / K coefficients == the host phase-a
    expansion (native or Python) for real transcript scalars."""
    import numpy as np

    from fabric_token_sdk_tpu.crypto import bn254
    from fabric_token_sdk_tpu.models import range_verifier as rv
    from fabric_token_sdk_tpu.ops import limbs

    n = 16
    R = bn254.R
    rng = np.random.default_rng(11)
    B = 3
    rows = []
    want_yinv, want_kf = [], []
    for _ in range(B):
        y = int(rng.integers(2, 1 << 62))
        z = int(rng.integers(2, 1 << 62))
        delta = int(rng.integers(2, 1 << 62))
        x = int(rng.integers(2, 1 << 62))
        y_inv = pow(y, R - 2, R)
        rows.append(b"".join(v.to_bytes(32, "little")
                             for v in (y_inv, z, delta, x)))
        pows = [pow(y_inv, i, R) for i in range(n)]
        want_yinv.append(pows)
        z_sq = z * z % R
        kf = [(z + z_sq * pow(2, i, R) % R * pows[i]) % R
              for i in range(n)]
        kf += [(R - delta) % R, (R - z) % R]
        want_kf.append(kf)
    sc4 = jnp.asarray(limbs.packed_to_limbs(b"".join(rows)).reshape(B, 4, 16))
    yinv_d, kf_d, kvar_d = rv._derive_pass1_scalars(sc4, n)
    for b in range(B):
        got_p = [limbs.limbs_to_int(r) for r in np.asarray(yinv_d)[b]]
        assert got_p == want_yinv[b], b
        got_k = [limbs.limbs_to_int(r) for r in np.asarray(kf_d)[b]]
        assert got_k == want_kf[b], b
        assert limbs.limbs_to_int(np.asarray(kvar_d)[b, 0]) == \
            int.from_bytes(rows[b][96:128], "little")
        assert limbs.limbs_to_int(np.asarray(kvar_d)[b, 1]) == 1


def test_round_digests_device_parity():
    """Device round-challenge digests == rp.ipa_round_challenge, including
    identity L/R points (zero-byte encodings)."""
    import numpy as np

    from fabric_token_sdk_tpu.crypto import bn254, rp
    from fabric_token_sdk_tpu.models import range_verifier as rv
    from fabric_token_sdk_tpu.ops import limbs

    rounds = 2
    nv = 2 + 2 * rounds + 3
    B = 3
    rng = np.random.default_rng(5)
    pts, proj_rows = [], []
    for b in range(B):
        row = [bn254.g1_mul(bn254.G1_GENERATOR, int(rng.integers(2, 1 << 30)))
               for _ in range(nv)]
        if b == 1:
            row[3] = bn254.G1_IDENTITY      # an identity L point
        pts.append(row)
        proj_rows.append(limbs.points_to_projective_limbs(row))
    proj = np.stack(proj_rows)
    xy = jnp.asarray(proj[:, :, :2])
    inf = jnp.asarray((proj[:, :, 2] == 0).all(-1).astype(np.uint8))
    words = np.asarray(rv._round_digests(xy, inf, rounds))
    from fabric_token_sdk_tpu.ops import sha256 as dsha

    for b in range(B):
        for r_i in range(rounds):
            got = dsha.digest_words_to_ints(words[b, r_i][None])[0] % bn254.R
            want = rp.ipa_round_challenge(pts[b][2 + r_i],
                                          pts[b][2 + rounds + r_i])
            assert got == want, (b, r_i)
