"""HTLC interop: lock/claim/reclaim/deadline/wrong-preimage matrix for BOTH
driver validator chains (reference fabtoken validator_transfer.go:96-170,
zkatdlog validator_transfer.go:112-175, htlc script.go/signer.go)."""

import hashlib
import time

import pytest

from fabric_token_sdk_tpu.core import fabtoken, zkatdlog
from fabric_token_sdk_tpu.core.fabtoken.actions import (IssueAction, Output,
                                                        TransferAction)
from fabric_token_sdk_tpu.core.zkatdlog.actions import (ActionInput,
                                                        IssueAction as ZkIssue,
                                                        Token,
                                                        TransferAction as ZkTransfer)
from fabric_token_sdk_tpu.crypto import setup as zk_setup
from fabric_token_sdk_tpu.crypto import issue_proof, token_commit, transfer_proof
from fabric_token_sdk_tpu.driver import TokenRequest
from fabric_token_sdk_tpu.driver.identity import Identity
from fabric_token_sdk_tpu.services.identity.deserializer import Deserializer
from fabric_token_sdk_tpu.services.identity.x509 import (X509Verifier,
                                                         new_signing_identity)
from fabric_token_sdk_tpu.services.interop.htlc import (ClaimSignature,
                                                        HashInfo, Script,
                                                        claim_key, lock_key,
                                                        lock_value,
                                                        script_verifier_resolver)
from fabric_token_sdk_tpu.services.network.tcc import MemoryLedger, TokenChaincode
from fabric_token_sdk_tpu.token.model import ID

PREIMAGE = b"the-atomic-swap-preimage"
IMAGE = hashlib.sha256(PREIMAGE).digest().hex().encode()


def _deserializer():
    return Deserializer(extra_owner_resolvers=[
        script_verifier_resolver(
            lambda ident: X509Verifier.from_identity(ident))])


def _script(alice, bob, deadline):
    return Script(sender=bytes(alice.identity),
                  recipient=bytes(bob.identity), deadline=deadline,
                  hash_info=HashInfo(hash=IMAGE))


# ---------------------------------------------------------------- fabtoken

@pytest.fixture
def fab():
    issuer, auditor = new_signing_identity(), new_signing_identity()
    alice, bob = new_signing_identity(), new_signing_identity()
    pp = fabtoken.setup(64)
    pp.issuer_ids = [issuer.identity]
    pp.auditor = bytes(auditor.identity)
    cc = TokenChaincode(fabtoken.new_validator(pp, _deserializer()),
                        MemoryLedger(), pp.serialize())
    return dict(pp=pp, cc=cc, issuer=issuer, auditor=auditor, alice=alice,
                bob=bob)


def _fab_request(world, tx_id, issues=(), transfers=(), sigs=()):
    req = TokenRequest(issues=[a.serialize() for a in issues],
                       transfers=[a.serialize() for a in transfers])
    msg = req.message_to_sign(tx_id.encode())
    req.auditor_signatures = [world["auditor"].sign(msg)]
    req.signatures = [s(msg) if callable(s) else s for s in sigs]
    return req, msg


def _fab_lock(world, tx_id="lk", deadline=None):
    """issue to alice, then lock into an htlc script owner."""
    if deadline is None:
        deadline = time.time() + 3600
    alice, bob = world["alice"], world["bob"]
    issue = IssueAction(issuer=world["issuer"].identity,
                        outputs=[Output(bytes(alice.identity), "USD",
                                        "0x64")])
    req, _ = _fab_request(world, "is-" + tx_id, issues=[issue],
                          sigs=[world["issuer"].sign])
    assert world["cc"].process_request("is-" + tx_id,
                                       req.to_bytes()).status == "VALID"
    script = _script(alice, bob, deadline)
    lock = TransferAction(
        inputs=[ID("is-" + tx_id, 0)],
        input_tokens=[issue.outputs[0]],
        outputs=[Output(bytes(script.to_owner()), "USD", "0x64")],
        metadata={lock_key(IMAGE): lock_value(IMAGE)},
    )
    req, _ = _fab_request(world, tx_id, transfers=[lock],
                          sigs=[alice.sign])
    ev = world["cc"].process_request(tx_id, req.to_bytes())
    return ev, lock, script


def test_fab_lock_requires_metadata_key(fab):
    ev, lock, script = _fab_lock(fab, "lk0")
    assert ev.status == "VALID", ev.message

    # a lock without the metadata entry is rejected
    lock2 = TransferAction(inputs=lock.inputs,
                           input_tokens=lock.input_tokens,
                           outputs=lock.outputs, metadata={})
    req, _ = _fab_request(fab, "lk0b", transfers=[lock2],
                          sigs=[fab["alice"].sign])
    ev = fab["cc"].process_request("lk0b", req.to_bytes())
    assert ev.status == "INVALID"
    assert "lock" in ev.message


def _fab_claim(world, lock, script, tx_id="cl", preimage=PREIMAGE,
               to=None, quantity="0x64"):
    to = to or world["bob"]
    claim = TransferAction(
        inputs=[ID("lk1", 0)],
        input_tokens=[lock.outputs[0]],
        outputs=[Output(bytes(to.identity), "USD", quantity)],
        metadata={claim_key(script.hash_info.image(preimage)): preimage},
    )
    req = TokenRequest(transfers=[claim.serialize()])
    msg = req.message_to_sign(tx_id.encode())
    req.auditor_signatures = [world["auditor"].sign(msg)]
    sig = ClaimSignature(recipient_signature=to.sign(msg),
                         preimage=preimage).to_json()
    req.signatures = [sig]
    return claim, req


def test_fab_claim_with_preimage(fab):
    ev, lock, script = _fab_lock(fab, "lk1")
    assert ev.status == "VALID", ev.message
    claim, req = _fab_claim(fab, lock, script, tx_id="cl1")
    ev = fab["cc"].process_request("cl1", req.to_bytes())
    assert ev.status == "VALID", ev.message
    # bob owns the claimed token now
    tok = Output.deserialize(fab["cc"].query_tokens([ID("cl1", 0)])[0])
    assert tok.owner == bytes(fab["bob"].identity)


def test_fab_claim_wrong_preimage_rejected(fab):
    ev, lock, script = _fab_lock(fab, "lk1")
    assert ev.status == "VALID"
    claim, req = _fab_claim(fab, lock, script, tx_id="cl2",
                            preimage=b"wrong-preimage")
    ev = fab["cc"].process_request("cl2", req.to_bytes())
    assert ev.status == "INVALID"


def test_fab_claim_after_deadline_rejected(fab):
    """Past the deadline the recipient can no longer claim."""
    ev, lock, script = _fab_lock(fab, "lk1", deadline=time.time() + 1.5)
    assert ev.status == "VALID"
    time.sleep(1.6)
    claim, req = _fab_claim(fab, lock, script, tx_id="cl3")
    ev = fab["cc"].process_request("cl3", req.to_bytes())
    assert ev.status == "INVALID"
    assert "recipient" in ev.message or "sender" in ev.message


def test_fab_reclaim_after_deadline(fab):
    ev, lock, script = _fab_lock(fab, "lk1", deadline=time.time() + 1.0)
    assert ev.status == "VALID"
    time.sleep(1.1)
    alice = fab["alice"]
    reclaim = TransferAction(
        inputs=[ID("lk1", 0)],
        input_tokens=[lock.outputs[0]],
        outputs=[Output(bytes(alice.identity), "USD", "0x64")],
    )
    req, _ = _fab_request(fab, "rc1", transfers=[reclaim],
                          sigs=[alice.sign])
    ev = fab["cc"].process_request("rc1", req.to_bytes())
    assert ev.status == "VALID", ev.message


def test_fab_reclaim_before_deadline_rejected(fab):
    ev, lock, script = _fab_lock(fab, "lk1")  # deadline far future
    assert ev.status == "VALID"
    alice = fab["alice"]
    reclaim = TransferAction(
        inputs=[ID("lk1", 0)],
        input_tokens=[lock.outputs[0]],
        outputs=[Output(bytes(alice.identity), "USD", "0x64")],
    )
    req, _ = _fab_request(fab, "rc2", transfers=[reclaim],
                          sigs=[alice.sign])
    ev = fab["cc"].process_request("rc2", req.to_bytes())
    assert ev.status == "INVALID"


def test_fab_script_spend_must_be_single_output(fab):
    ev, lock, script = _fab_lock(fab, "lk1")
    assert ev.status == "VALID"
    claim = TransferAction(
        inputs=[ID("lk1", 0)],
        input_tokens=[lock.outputs[0]],
        outputs=[Output(bytes(fab["bob"].identity), "USD", "0x32"),
                 Output(bytes(fab["alice"].identity), "USD", "0x32")],
        metadata={claim_key(IMAGE): PREIMAGE},
    )
    req = TokenRequest(transfers=[claim.serialize()])
    msg = req.message_to_sign(b"cl5")
    req.auditor_signatures = [fab["auditor"].sign(msg)]
    req.signatures = [ClaimSignature(fab["bob"].sign(msg),
                                     PREIMAGE).to_json()]
    ev = fab["cc"].process_request("cl5", req.to_bytes())
    assert ev.status == "INVALID"
    assert "only transfers the ownership" in ev.message


# ---------------------------------------------------------------- zkatdlog

BIT_LENGTH = 16


@pytest.fixture(scope="module")
def zk_world():
    issuer, auditor = new_signing_identity(), new_signing_identity()
    alice, bob = new_signing_identity(), new_signing_identity()
    pp = zk_setup.setup(BIT_LENGTH)
    pp.issuer_ids = [issuer.identity]
    pp.auditor = bytes(auditor.identity)
    cc = TokenChaincode(
        zkatdlog.new_validator(pp, _deserializer(), device=False),
        MemoryLedger(), pp.serialize())
    return dict(pp=pp, cc=cc, issuer=issuer, auditor=auditor, alice=alice,
                bob=bob)


def _zk_lock(world, tx_id, deadline):
    """ZK issue to alice, then 1-in/1-out transfer into the script owner.

    Each lock uses a tx-unique preimage: the ledger enforces lock-key
    uniqueness (one outstanding lock per hash), so reusing a hash across
    locks on one ledger is correctly rejected.
    """
    preimage = f"preimage-{tx_id}".encode()
    image = hashlib.sha256(preimage).digest().hex().encode()
    pp = world["pp"]
    alice, bob = world["alice"], world["bob"]
    coms, wits = token_commit.get_tokens_with_witness(
        [77], "USD", pp.pedersen_generators)
    proof = issue_proof.issue_prove([w.as_tuple() for w in wits], coms, pp)
    issue = ZkIssue(issuer=world["issuer"].identity,
                    outputs=[Token(bytes(alice.identity), coms[0])],
                    proof=proof)
    req = TokenRequest(issues=[issue.serialize()])
    msg = req.message_to_sign(f"zi-{tx_id}".encode())
    req.auditor_signatures = [world["auditor"].sign(msg)]
    req.signatures = [world["issuer"].sign(msg)]
    assert world["cc"].process_request(f"zi-{tx_id}",
                                       req.to_bytes()).status == "VALID"

    script = Script(sender=bytes(alice.identity),
                    recipient=bytes(bob.identity), deadline=deadline,
                    hash_info=HashInfo(hash=image))
    out_coms, out_wits = token_commit.get_tokens_with_witness(
        [77], "USD", pp.pedersen_generators)
    tproof = transfer_proof.transfer_prove(
        [w.as_tuple() for w in wits], [w.as_tuple() for w in out_wits],
        coms, out_coms, pp)
    lock = ZkTransfer(
        inputs=[ActionInput(id=ID(f"zi-{tx_id}", 0),
                            token=issue.outputs[0])],
        outputs=[Token(bytes(script.to_owner()), out_coms[0])],
        proof=tproof,
        metadata={lock_key(image): lock_value(image)},
    )
    req = TokenRequest(transfers=[lock.serialize()])
    msg = req.message_to_sign(tx_id.encode())
    req.auditor_signatures = [world["auditor"].sign(msg)]
    req.signatures = [alice.sign(msg)]
    ev = world["cc"].process_request(tx_id, req.to_bytes())
    return ev, lock, script, out_wits, preimage


def _zk_spend_script(world, lock, script, out_wits, tx_id, to_identity,
                     claim_preimage=None, signer=None):
    """1-in/1-out spend of the script-owned commitment token."""
    pp = world["pp"]
    in_wits = [w.as_tuple() for w in out_wits]
    new_coms, new_wits = token_commit.get_tokens_with_witness(
        [77], "USD", pp.pedersen_generators)
    tproof = transfer_proof.transfer_prove(
        in_wits, [w.as_tuple() for w in new_wits],
        [lock.outputs[0].data], new_coms, pp)
    action = ZkTransfer(
        inputs=[ActionInput(id=ID(lock_tx_id(world, lock), 0),
                            token=lock.outputs[0])],
        outputs=[Token(to_identity, new_coms[0])],
        proof=tproof,
    )
    if claim_preimage is not None:
        action.metadata[claim_key(
            script.hash_info.image(claim_preimage))] = claim_preimage
    req = TokenRequest(transfers=[action.serialize()])
    msg = req.message_to_sign(tx_id.encode())
    req.auditor_signatures = [world["auditor"].sign(msg)]
    if claim_preimage is not None:
        req.signatures = [ClaimSignature(
            recipient_signature=signer.sign(msg),
            preimage=claim_preimage).to_json()]
    else:
        req.signatures = [signer.sign(msg)]
    return world["cc"].process_request(tx_id, req.to_bytes())


_LOCK_TXIDS = {}


def lock_tx_id(world, lock):
    return _LOCK_TXIDS[id(lock)]


def _zk_lock_tracked(world, tx_id, deadline):
    ev, lock, script, wits, preimage = _zk_lock(world, tx_id, deadline)
    _LOCK_TXIDS[id(lock)] = tx_id
    return ev, lock, script, wits, preimage


def test_zk_htlc_lock_and_claim(zk_world):
    ev, lock, script, wits, preimage = _zk_lock_tracked(
        zk_world, "zlk1", time.time() + 3600)
    assert ev.status == "VALID", ev.message
    ev = _zk_spend_script(zk_world, lock, script, wits, "zcl1",
                          bytes(zk_world["bob"].identity),
                          claim_preimage=preimage, signer=zk_world["bob"])
    assert ev.status == "VALID", ev.message


def test_zk_htlc_wrong_preimage_rejected(zk_world):
    ev, lock, script, wits, _ = _zk_lock_tracked(zk_world, "zlk2",
                                                 time.time() + 3600)
    assert ev.status == "VALID", ev.message
    ev = _zk_spend_script(zk_world, lock, script, wits, "zcl2",
                          bytes(zk_world["bob"].identity),
                          claim_preimage=b"nope", signer=zk_world["bob"])
    assert ev.status == "INVALID"


def test_zk_htlc_reclaim_after_deadline(zk_world):
    # host proving takes seconds: the deadline must outlive the lock's own
    # validation, then we wait it out before reclaiming
    deadline = time.time() + 12.0
    ev, lock, script, wits, _ = _zk_lock_tracked(zk_world, "zlk3", deadline)
    assert ev.status == "VALID", ev.message
    time.sleep(max(0.0, deadline - time.time()) + 0.2)
    ev = _zk_spend_script(zk_world, lock, script, wits, "zrc3",
                          bytes(zk_world["alice"].identity),
                          signer=zk_world["alice"])
    assert ev.status == "VALID", ev.message


def test_zk_htlc_claim_by_sender_before_deadline_rejected(zk_world):
    ev, lock, script, wits, _ = _zk_lock_tracked(zk_world, "zlk4",
                                                 time.time() + 3600)
    assert ev.status == "VALID", ev.message
    # alice (sender) tries to take it back early, to herself
    ev = _zk_spend_script(zk_world, lock, script, wits, "zrc4",
                          bytes(zk_world["alice"].identity),
                          signer=zk_world["alice"])
    assert ev.status == "INVALID"
