"""Mixed addition (tec.madd) + lazy-carry limb arithmetic properties.

Four layers of defense for the bit-identical-verdict contract:

  1. madd parity vs the complete add and the host oracle over the
     adversarial corner inputs where mixed-addition formulas classically
     break: identity accumulator, P + P (doubling through madd),
     P + (-P) -> identity, and accumulators whose Y/Z coordinates arrive
     in maximum-magnitude lazy form (a limb at exactly 2^16).
  2. Numeric checks of the lazy field ops at their documented bound
     edges (mont_mul at operand value 5p-eps, sub_lazy output value,
     normalize at < 2p).
  3. A carry-bound exhaustion walk of the madd/add schedules through
     tfield.LimbBound: the tracker raises the moment any rule R1-R4
     precondition breaks, so the schedule COMPLETING is a proof that no
     intermediate limb can exceed LAZY_LIMB_MAX = 2^16 — and the
     violation tests prove the tracker itself rejects schedules that
     would. Round 7 adds the add_zlazy window-fold schedule and a
     composed walk of the full _msm_var_kernel chain structure.
  4. Oracle parity of the round-7 lazified variable-base MSM
     (ec.msm_var_mixed) over the classic corner inputs — identity row,
     zero scalar, scalar one, duplicate point — in both the flat and the
     batched (exact-tail / fused-chunk) forms, plus the canonical-limb
     readback contract.
  5. Oracle parity of the round-8 lazified FIXED-base mixed MSM
     (ec.fixed_base_msm_mixed over affine byte-plane tables — the entry
     the exact-pass _exact_mixed_tail_kernel consumes) over corner
     scalars (zero, one, r-1, random) in flat and batched forms, plus
     the same canonical readback contract.
"""

import secrets

import jax.numpy as jnp
import numpy as np
import pytest

from fabric_token_sdk_tpu.crypto import bn254
from fabric_token_sdk_tpu.ops import ec, field, limbs as L, tec
from fabric_token_sdk_tpu.ops import tfield as tf

P = L.P_INT
R_INV = pow(2 ** 256, -1, P)


def _digits(v: int) -> list[int]:
    return [(v >> (16 * i)) & 0xFFFF for i in range(L.NLIMBS)]


def _val(col) -> int:
    # L.limbs_to_int uses OR packing and silently corrupts limbs >= 2^16;
    # lazy values need the weighted sum.
    return sum(int(v) << (16 * i) for i, v in enumerate(col))


def _spiked_value(base: int):
    """(value, digits) for a lazy representation with one limb at exactly
    LAZY_LIMB_MAX = 2^16 and value <= base: move one unit of the top
    nonzero digit down as 2^16, overwriting the digit below (the value
    can only shrink, by < 2^16(i-1) * 2^16)."""
    d = _digits(base)
    for i in range(L.NLIMBS - 1, 0, -1):
        if d[i] >= 1:
            d[i] -= 1
            d[i - 1] = 1 << 16
            return _val(d), d
    raise AssertionError(f"value {base} too small to spike")


def _same(p: bn254.G1, q: bn254.G1) -> bool:
    return (p.inf and q.inf) or (not p.inf and not q.inf
                                 and p.x == q.x and p.y == q.y)


def _rand_pts(n):
    return [bn254.g1_mul(bn254.G1_GENERATOR, secrets.randbelow(bn254.R))
            for _ in range(n)]


@pytest.fixture(scope="module")
def cc():
    return tec.make_consts()


# --------------------------------------------------------------------------
# 1. madd parity over adversarial inputs
# --------------------------------------------------------------------------

class TestMaddParity:
    def _affine_t(self, pts):
        """Points -> canonical Montgomery affine (16, B) coordinate pair
        (madd's table-entry operand form). No identities allowed here —
        digit 0 is masked by the callers, not by madd."""
        xs = [np.array(L.int_to_limbs(L.fp_to_mont_int(p.x)),
                       dtype=np.uint32) for p in pts]
        ys = [np.array(L.int_to_limbs(L.fp_to_mont_int(p.y)),
                       dtype=np.uint32) for p in pts]
        return (jnp.asarray(np.stack(xs).T), jnp.asarray(np.stack(ys).T))

    def _acc_t(self, pts):
        arr = L.points_to_projective_limbs(pts)          # (B, 3, 16)
        return jnp.asarray(arr.reshape(len(pts), 48).T)  # (48, B)

    def test_corner_cases_match_oracle_and_complete_add(self, cc):
        base = _rand_pts(4)
        q_pts = [base[0], base[1], base[1], base[2],
                 base[3], bn254.G1_GENERATOR]
        acc_pts = [base[1],                    # generic
                   bn254.G1_IDENTITY,          # identity accumulator
                   base[1],                    # P + P (doubling)
                   bn254.g1_neg(base[2]),      # P + (-P) -> identity
                   base[0], base[2]]
        acc = self._acc_t(acc_pts)
        xq, yq = self._affine_t(q_pts)
        out = tec.normalize_point(tec.madd(acc, xq, yq, cc), cc)
        # complete-add reference on the same lanes
        q_proj = self._acc_t(q_pts)
        ref = tec.add(acc, q_proj, cc)
        out_np, ref_np = np.asarray(out), np.asarray(ref)
        assert int(out_np.max()) <= 0xFFFF      # canonical after normalize
        for i, (a, q) in enumerate(zip(acc_pts, q_pts)):
            want = bn254.g1_add(a, q)
            got = L.projective_limbs_to_point(out_np[:, i].reshape(3, 16))
            also = L.projective_limbs_to_point(ref_np[:, i].reshape(3, 16))
            assert _same(got, want), f"lane {i} vs oracle"
            assert _same(also, want), f"lane {i} complete add vs oracle"

    def test_lazy_accumulator_representation(self, cc):
        """madd must accept Y/Z in any legal lazy form: the value-
        equivalent representation add_lazy(Y, p) (value Y + p < 2p,
        ripple-carry limb layout, limbs can hit 2^16) must produce the
        bit-identical canonical result."""
        [p1], [q] = _rand_pts(1), _rand_pts(1)
        acc = np.asarray(self._acc_t([p1])).copy()       # (48, 1)
        mod = jnp.asarray(np.array(_digits(P), dtype=np.uint32)[:, None])
        y_lazy = np.asarray(tf.add_lazy(jnp.asarray(acc[16:32]), mod))
        z_lazy = np.asarray(tf.add_lazy(jnp.asarray(acc[32:48]), mod))
        assert _val(y_lazy[:, 0]) == _val(acc[16:32, 0]) + P
        assert _val(z_lazy[:, 0]) == _val(acc[32:48, 0]) + P
        lazy = acc.copy()
        lazy[16:32] = y_lazy
        lazy[32:48] = z_lazy
        xq, yq = self._affine_t([q])
        want = np.asarray(tec.normalize_point(
            tec.madd(jnp.asarray(acc), xq, yq, cc), cc))
        got = np.asarray(tec.normalize_point(
            tec.madd(jnp.asarray(lazy), xq, yq, cc), cc))
        assert (want == got).all()
        assert _same(
            L.projective_limbs_to_point(got[:, 0].reshape(3, 16)),
            bn254.g1_add(p1, q))

    def test_chain_keeps_invariant(self, cc):
        """Five madd steps WITHOUT normalization: Y/Z limbs stay
        <= 2^16 and values < 2p at every step (the kernel fold's
        steady-state invariant), and the final normalized value is
        acc + 5q."""
        [p1], [q] = _rand_pts(1), _rand_pts(1)
        acc = self._acc_t([p1])
        xq, yq = self._affine_t([q])
        for step in range(5):
            acc = tec.madd(acc, xq, yq, cc)
            a = np.asarray(acc)
            assert int(a.max()) <= (1 << 16), step
            assert _val(a[16:32, 0]) < 2 * P, step
            assert _val(a[32:48, 0]) < 2 * P, step
        out = np.asarray(tec.normalize_point(acc, cc))
        want = bn254.g1_add(p1, bn254.g1_mul(q, 5))
        assert _same(
            L.projective_limbs_to_point(out[:, 0].reshape(3, 16)), want)


# --------------------------------------------------------------------------
# 2. lazy field ops at their bound edges
# --------------------------------------------------------------------------

class TestLazyFieldOps:
    def test_add_lazy_sub_lazy_normalize_values(self, cc):
        ts = cc.ts
        a_int = secrets.randbelow(P)
        b_int = secrets.randbelow(P)
        a = jnp.asarray(np.array(L.int_to_limbs(a_int),
                                 dtype=np.uint32)[:, None])
        b = jnp.asarray(np.array(L.int_to_limbs(b_int),
                                 dtype=np.uint32)[:, None])
        s = np.asarray(tf.add_lazy(a, b))[:, 0]
        assert _val(s) == a_int + b_int                  # no reduction
        assert int(s.max()) <= (1 << 16)
        d = np.asarray(tf.sub_lazy(a, b, ts))[:, 0]
        assert _val(d) == a_int + 2 * P - b_int
        assert int(d.max()) <= (1 << 16)
        n = np.asarray(tf.normalize(jnp.asarray(
            np.array(s, dtype=np.uint32)[:, None]), ts))[:, 0]
        assert _val(n) == (a_int + b_int) % P

    def test_mont_mul_lazy_operand_at_bound(self, cc):
        """One lazy operand at value ~5p-1 with a limb spiked to 2^16:
        output must still be the exact canonical Montgomery product."""
        ts = cc.ts
        v, d = _spiked_value(tf.LAZY_VALUE_MAX_P * P - 1)
        assert 4 * P < v < 5 * P and max(d) == 1 << 16
        b_int = secrets.randbelow(P)
        a = jnp.asarray(np.array(d, dtype=np.uint32)[:, None])
        b = jnp.asarray(np.array(L.int_to_limbs(b_int),
                                 dtype=np.uint32)[:, None])
        out = np.asarray(tf.mont_mul(a, b, ts))[:, 0]
        assert L.limbs_to_int(out) == v * b_int * R_INV % P
        assert int(out.max()) <= 0xFFFF                  # canonical

    def test_field_module_lazy_ops(self):
        """ops/field.py (2-D row layout) twins of the lazy ops."""
        a_int = secrets.randbelow(P)
        b_int = secrets.randbelow(P)
        a = jnp.asarray(np.array(L.int_to_limbs(a_int),
                                 dtype=np.uint32)[None])
        b = jnp.asarray(np.array(L.int_to_limbs(b_int),
                                 dtype=np.uint32)[None])
        s = np.asarray(field.add_lazy(a, b))[0]
        assert _val(s) == a_int + b_int
        d = np.asarray(field.sub_lazy(a, b, field.FP))[0]
        assert _val(d) == a_int + 2 * P - b_int
        # normalize is an R4 op: value must be < 2p — the add_lazy result
        # qualifies, a sub_lazy result (a + 2p - b, up to 3p) does NOT.
        n = np.asarray(field.normalize(
            jnp.asarray(np.array(s, dtype=np.uint32)[None]), field.FP))[0]
        assert _val(n) == (a_int + b_int) % P
        v, dd = _spiked_value(3 * P - 1)
        m = np.asarray(field.mont_mul(
            jnp.asarray(np.array(dd, dtype=np.uint32)[None]), b,
            field.FP))[0]
        assert L.limbs_to_int(m) == v * b_int * R_INV % P


# --------------------------------------------------------------------------
# 3. carry-bound exhaustion: LimbBound schedule walk
# --------------------------------------------------------------------------

LB = tf.LimbBound


def _walk_madd(X, Y, Z):
    """tec.madd's exact op schedule in LimbBound space. Any R1-R4 break
    raises inside the tracker."""
    can = LB.canonical()
    s1 = X.add_lazy(Y)
    s2 = can.add(can)
    t0 = X.mont_mul(can)
    t1 = Y.mont_mul(can)
    m2 = s1.mont_mul(s2)
    m3 = Z.mont_mul(can)
    m4 = Z.mont_mul(can)
    t3 = m2.sub_lazy(t0).sub_lazy(t1)
    t4 = m3.add_lazy(Y)
    y3 = m4.add_lazy(X)
    t0 = t0.add(t0).add(t0)
    t2 = Z.mont_mul(can)                 # b3 * Z1
    y3 = y3.mont_mul(can)                # b3 * y3
    z3 = t1.add(t2)
    t1 = t1.sub(t2)
    o0 = t4.mont_mul(y3)
    o1 = t3.mont_mul(t1)
    o2 = y3.mont_mul(t0)
    o3 = t1.mont_mul(z3)
    o4 = t0.mont_mul(t3)
    o5 = z3.mont_mul(t4)
    return o1.sub(o0), o3.add_lazy(o2), o5.add_lazy(o4)


def _walk_add_zlazy(P1, P2):
    """tec.add_zlazy's exact op schedule (``_add_complete`` with
    ``z_lazy_out=True``) in LimbBound space: the accumulator's Z arrives
    lazy (< 2p) from the previous fold step, X/Y canonical, the chunk
    partial ``P2`` fully canonical — and Z leaves lazy again via the
    final ``add_lazy`` while X/Y leave canonical. Round 7's window-fold
    chain iterates exactly this shape."""
    a_sums = [P1[i].add_lazy(P1[j]) for i, j in ((0, 1), (1, 2), (0, 2))]
    b_sums = [P2[i].add(P2[j]) for i, j in ((0, 1), (1, 2), (0, 2))]
    t0 = P1[0].mont_mul(P2[0])
    t1 = P1[1].mont_mul(P2[1])
    t2 = P1[2].mont_mul(P2[2])
    m3 = a_sums[0].mont_mul(b_sums[0])
    m4 = a_sums[1].mont_mul(b_sums[1])
    m5 = a_sums[2].mont_mul(b_sums[2])
    t3 = m3.sub_lazy(t0).sub_lazy(t1)
    t4 = m4.sub_lazy(t1).sub_lazy(t2)
    y3 = m5.sub_lazy(t0).sub_lazy(t2)
    t0 = t0.add(t0).add(t0)
    t2 = t2.mont_mul(LB.canonical())
    y3 = y3.mont_mul(LB.canonical())
    z3 = t1.add(t2)
    t1 = t1.sub(t2)
    outs = [t4.mont_mul(y3), t3.mont_mul(t1), y3.mont_mul(t0),
            t1.mont_mul(z3), t0.mont_mul(t3), z3.mont_mul(t4)]
    return (outs[1].sub(outs[0]), outs[3].add(outs[2]),
            outs[5].add_lazy(outs[4]))


def _walk_add(P1, P2):
    """tec.add's lazified interior (canonical-in/canonical-out)."""
    a_sums = [P1[i].add_lazy(P1[j]) for i, j in ((0, 1), (1, 2), (0, 2))]
    b_sums = [P2[i].add(P2[j]) for i, j in ((0, 1), (1, 2), (0, 2))]
    t0 = P1[0].mont_mul(P2[0])
    t1 = P1[1].mont_mul(P2[1])
    t2 = P1[2].mont_mul(P2[2])
    m3 = a_sums[0].mont_mul(b_sums[0])
    m4 = a_sums[1].mont_mul(b_sums[1])
    m5 = a_sums[2].mont_mul(b_sums[2])
    t3 = m3.sub_lazy(t0).sub_lazy(t1)
    t4 = m4.sub_lazy(t1).sub_lazy(t2)
    y3 = m5.sub_lazy(t0).sub_lazy(t2)
    t0 = t0.add(t0).add(t0)
    t2 = t2.mont_mul(LB.canonical())
    y3 = y3.mont_mul(LB.canonical())
    z3 = t1.add(t2)
    t1 = t1.sub(t2)
    outs = [t4.mont_mul(y3), t3.mont_mul(t1), y3.mont_mul(t0),
            t1.mont_mul(z3), t0.mont_mul(t3), z3.mont_mul(t4)]
    return (outs[1].sub(outs[0]), outs[3].add(outs[2]),
            outs[5].add(outs[4]))


class TestCarryBoundExhaustion:
    def test_madd_invariant_is_a_fixed_point(self):
        """Start at the fold invariant (X canonical; Y, Z lazy < 2p),
        iterate the schedule: bounds must come back AT OR BELOW the
        invariant every time — carries can never accumulate across fold
        iterations. Completing without ValueError proves no intermediate
        limb exceeds LAZY_LIMB_MAX."""
        X = LB.canonical()
        Y = Z = LB(tf.LAZY_LIMB_MAX, 2.0)
        for it in range(32):
            X, Y, Z = _walk_madd(X, Y, Z)
            assert X.is_canonical, it
            assert Y.limb_max <= tf.LAZY_LIMB_MAX and Y.value_p <= 2.0, it
            assert Z.limb_max <= tf.LAZY_LIMB_MAX and Z.value_p <= 2.0, it
        # the chain terminator is legal: < 2p normalizes (R4)
        Y.normalize()
        Z.normalize()

    def test_add_schedule_canonical_out(self):
        p1 = [LB.canonical()] * 3
        p2 = [LB.canonical()] * 3
        x, y, z = _walk_add(p1, p2)
        assert x.is_canonical and y.is_canonical and z.is_canonical

    def test_add_zlazy_invariant_is_a_fixed_point(self):
        """The window-fold invariant (X/Y canonical, Z lazy < 2p) must be
        a fixed point of the add_zlazy schedule: chaining folds can never
        grow the Z bound, and the chain terminator normalize is legal
        (R4)."""
        acc = [LB.canonical(), LB.canonical(), LB(tf.LAZY_LIMB_MAX, 2.0)]
        part = [LB.canonical()] * 3
        for it in range(32):
            x, y, z = _walk_add_zlazy(acc, part)
            assert x.is_canonical and y.is_canonical, it
            assert z.limb_max <= tf.LAZY_LIMB_MAX and z.value_p <= 2.0, it
            acc = [x, y, z]
        acc[2].normalize()

    def test_add_zlazy_rejects_illegal_inputs(self):
        """The schedule's preconditions are load-bearing: a lazy chunk
        partial (q side feeds exact adds) or a lazy accumulator X (two
        lazy operands meet in the cross sums) must trip the tracker."""
        lazy = LB(tf.LAZY_LIMB_MAX, 2.0)
        good = [LB.canonical(), LB.canonical(), lazy]
        with pytest.raises(ValueError, match="canonical"):
            _walk_add_zlazy(good, [LB.canonical(), LB.canonical(), lazy])
        with pytest.raises(ValueError, match="R1|both operands lazy"):
            _walk_add_zlazy([lazy, LB.canonical(), lazy],
                            [LB.canonical()] * 3)

    def test_var_kernel_chain_schedule(self):
        """_msm_var_kernel's full lazy-chain structure end to end in
        LimbBound space: the 14-step madd table chain (Y/Z lazy across
        steps, one normalize at the table-entry store), then the
        add_zlazy window-fold chain (Z lazy across chunks, one normalize
        at the fold store). Completing proves no interior limb of the
        round-7 lazified Horner walk can pass LAZY_LIMB_MAX."""
        # table build: entry k = entry k-1 + base, madd chain of 14
        X, Y, Z = LB.canonical(), LB.canonical(), LB.canonical()
        for step in range(14):
            X, Y, Z = _walk_madd(X, Y, Z)
            assert Y.value_p <= 2.0 and Z.value_p <= 2.0, step
        X, Y, Z = X, Y.normalize(), Z.normalize()   # per-entry store
        assert X.is_canonical and Y.is_canonical and Z.is_canonical
        # window fold: chunk-partial chain through add_zlazy
        acc = [X, Y, LB(tf.LAZY_LIMB_MAX, 2.0)]
        for _ in range(8):
            acc = list(_walk_add_zlazy(acc, [LB.canonical()] * 3))
        acc[2].normalize()                          # fold store

    def test_violating_schedules_raise(self):
        can = LB.canonical()
        lazy2 = LB(tf.LAZY_LIMB_MAX, 2.0)
        with pytest.raises(ValueError, match="R1|both operands lazy"):
            lazy2.add_lazy(lazy2)            # R1: both lazy
        with pytest.raises(ValueError, match="R2|canonical"):
            can.sub_lazy(lazy2)              # R2: lazy subtrahend
        with pytest.raises(ValueError, match="R3|both operands lazy"):
            lazy2.mont_mul(lazy2)            # R3: both lazy
        with pytest.raises(ValueError, match="R3|exceeds"):
            LB(tf.LAZY_LIMB_MAX, 5.5).mont_mul(can)   # R3: value > 5p
        with pytest.raises(ValueError, match="R4|2p"):
            LB(tf.LAZY_LIMB_MAX, 3.0).normalize()     # R4: value > 2p
        with pytest.raises(ValueError, match="LAZY_LIMB_MAX"):
            LB(tf.LAZY_LIMB_MAX + 1, 1.0).add_lazy(can)   # limb > 2^16
        with pytest.raises(ValueError, match="overflow"):
            # un-normalized accumulation blows past 2^256/p
            LB(tf.LAZY_LIMB_MAX, 4.0).sub_lazy(can).sub_lazy(can)

    def test_skipping_the_madd_mask_invariant_breaks_loudly(self):
        """Feeding a LAZY value where madd requires canonical X (e.g.
        reusing an un-normalized accumulator X slot) trips the walk —
        the exhaustion test would catch a mis-threaded kernel."""
        bad_X = LB(tf.LAZY_LIMB_MAX, 2.0)
        Y = Z = LB(tf.LAZY_LIMB_MAX, 2.0)
        with pytest.raises(ValueError):
            _walk_madd(bad_X, Y, Z)


# --------------------------------------------------------------------------
# 4. round-7 lazified var-MSM: oracle parity + canonical-out contract
# --------------------------------------------------------------------------

class TestVarMsmLazyParity:
    """ec.msm_var_mixed is the XLA twin of the Pallas _msm_var_kernel:
    madd table chains + add_zlazy window folds, one normalize_point per
    chain. It now carries the K pass, the exact-pass var tails AND the
    fused chunk partial — parity over the classic MSM corner inputs plus
    the canonical-limb readback contract is what keeps verdicts
    bit-identical to the host verifier."""

    def _corner_case(self):
        T = 7
        pts = _rand_pts(T - 2) + [bn254.G1_IDENTITY]
        pts.append(pts[0])                   # duplicate (doubling in fold)
        sc = [secrets.randbelow(bn254.R) for _ in range(T)]
        sc[2] = 0                            # zero scalar
        sc[3] = 1                            # scalar one
        return pts, sc

    def test_oracle_parity_corner_inputs(self):
        pts, sc = self._corner_case()
        proj = jnp.asarray(L.points_to_projective_limbs(pts))
        scl = jnp.asarray(L.scalars_to_limbs(sc))
        got = np.asarray(ec.msm_var_mixed(proj, scl))
        want = bn254.msm(pts, sc)
        gp = L.projective_limbs_to_point(got)
        assert not want.inf and _same(gp, want)
        # readback boundary contract: fully canonical limbs
        assert int(got.max()) <= 0xFFFF

    def test_batched_matches_per_row(self):
        """The (B, T, ...) form the exact-pass tails and the fused chunk
        partial use must agree row-by-row with the flat form."""
        pts, sc = self._corner_case()
        proj = jnp.asarray(L.points_to_projective_limbs(pts))
        scl = jnp.asarray(L.scalars_to_limbs(sc))
        flat = np.asarray(ec.msm_var_mixed(proj, scl))
        B = 2
        batched = np.asarray(ec.msm_var_mixed(
            jnp.broadcast_to(proj, (B,) + proj.shape),
            jnp.broadcast_to(scl, (B,) + scl.shape)))
        assert int(batched.max()) <= 0xFFFF
        for b in range(B):
            assert (batched[b] == flat).all(), b


# --------------------------------------------------------------------------
# 5. round-8 lazified FIXED-base tails: oracle parity + canonical-out
# --------------------------------------------------------------------------

class TestFixedBaseMixedParity:
    """ec.fixed_base_msm_mixed is the XLA entry the round-8 lazified
    exact-pass FIXED-base tails (_exact_mixed_tail_kernel) consume: madd
    window chains over the affine byte-plane tables (digit-0 entries
    masked to identity), one normalize per chain, then the projective
    cross-term tree. Parity vs the host oracle over the corner scalars
    — zero, one, r-1, random — in both the flat and the batched
    (exact-tail) forms is what keeps the FTS_EXACT_MIXED path's verdicts
    bit-identical to the unfused exact pass."""

    T = 2

    @pytest.fixture(scope="class")
    def tables(self):
        pts = _rand_pts(self.T)
        proj = jnp.asarray(L.points_to_projective_limbs(pts))
        return pts, ec.fixed_base_affine_planes(proj)

    def test_oracle_parity_corner_scalars(self, tables):
        pts, aff = tables
        rows = [
            [0, secrets.randbelow(bn254.R)],         # zero scalar
            [1, bn254.R - 1],                        # one + max scalar
            [secrets.randbelow(bn254.R) for _ in range(self.T)],
        ]
        scl = jnp.asarray(np.stack([L.scalars_to_limbs(r) for r in rows]))
        got = np.asarray(ec.fixed_base_msm_mixed(aff, scl))   # (B, 3, 16)
        # readback boundary contract: fully canonical limbs
        assert int(got.max()) <= 0xFFFF
        for b, sc in enumerate(rows):
            want = bn254.msm(pts, sc)
            gp = L.projective_limbs_to_point(got[b])
            assert not want.inf and _same(gp, want), b

    def test_flat_matches_batched(self, tables):
        """The flat (T, 16) scalar form and the batched (B, T, 16) form
        the exact tails use must agree bit-for-bit row-by-row."""
        _, aff = tables
        sc = [secrets.randbelow(bn254.R) for _ in range(self.T)]
        scl = jnp.asarray(L.scalars_to_limbs(sc))
        flat = np.asarray(ec.fixed_base_msm_mixed(aff, scl))
        batched = np.asarray(ec.fixed_base_msm_mixed(
            aff, jnp.broadcast_to(scl, (2,) + scl.shape)))
        assert int(flat.max()) <= 0xFFFF
        for b in range(2):
            assert (batched[b] == flat).all(), b
