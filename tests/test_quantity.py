"""Quantity parsing: exact Go big.Int.SetString(s, 0) semantics.

Reference token/token/quantity.go:46-69 parses via big.Int#scan with
base 0; divergences from Python int(s, 0) are deliberate test targets:
whitespace is rejected, a leading "0" means octal, underscores follow Go
placement rules.
"""

import pytest

from fabric_token_sdk_tpu.token import quantity as q


@pytest.mark.parametrize("s,expected", [
    ("0", 0),
    ("10", 10),
    ("0x10", 16),
    ("0X10", 16),
    ("0o17", 15),
    ("0b101", 5),
    ("010", 8),          # Go legacy octal; Python int("010", 0) raises
    ("0_10", 8),         # underscore after the legacy-octal prefix
    ("0x_ff", 255),      # underscore after the prefix
    ("1_000", 1000),     # underscore between digits
    ("0xAb", 171),
])
def test_accepts_go_forms(s, expected):
    assert q.to_quantity(s, 64).value == expected


@pytest.mark.parametrize("s", [
    "", " 10", "10 ", "\t7", "10\n",   # whitespace anywhere: rejected
    "0x", "0b", "0o",                  # prefix without digits
    "_10", "10_", "1__0",              # bad underscore placement
    "0x1g", "0b12", "0o8", "08",       # digit out of base (08 is octal)
    "++1", "--1", "+-1",
    "ten",
])
def test_rejects_non_go_forms(s):
    with pytest.raises(q.QuantityError):
        q.to_quantity(s, 64)


def test_negative_rejected_positive_sign_ok():
    with pytest.raises(q.QuantityError):
        q.to_quantity("-5", 64)
    assert q.to_quantity("+5", 64).value == 5
    # Go: Sign() of "-0" is 0, so it passes the negativity check.
    assert q.to_quantity("-0", 64).value == 0


def test_precision_bounds():
    assert q.to_quantity("0xffff", 16).value == 0xFFFF
    with pytest.raises(q.QuantityError):
        q.to_quantity("0x10000", 16)
    with pytest.raises(q.QuantityError):
        q.to_quantity("1", 0)


def test_arithmetic():
    a = q.to_quantity("0x8000", 16)
    b = q.to_quantity("0x7fff", 16)
    assert a.add(b).value == 0xFFFF
    with pytest.raises(q.QuantityError):
        a.add(a)
    assert a.sub(b).value == 1
    with pytest.raises(q.QuantityError):
        b.sub(a)
    assert a.hex() == "0x8000"
