"""Flight recorder (obs/journal.py): ring semantics, spill, concurrency,
and incident snapshots — including the end-to-end "stalled dispatch"
scenario the subsystem exists for: a seeded FaultInjector stall wedges a
serve dispatch, the watchdog abandons it, and the incident snapshot must
contain the journal tail, every thread's stack, and the still-open
``serve.dispatch`` span.

Everything here runs against fake backends (no device, no crypto): tier-1.
"""

import asyncio
import json
import os
import threading

import numpy as np
import pytest

from fabric_token_sdk_tpu.obs import GLOBAL, TRACER
from fabric_token_sdk_tpu.obs.journal import (EVENT_DISPATCH_START,
                                              EVENT_INCIDENT, EVENT_KINDS,
                                              JOURNAL, Journal,
                                              configure_from_env)

# ------------------------------------------------------------ ring + spill


def test_ring_wraps_and_counts_drops():
    j = Journal(capacity=4, provider=GLOBAL)
    for i in range(10):
        j.record("heartbeat", i=i)
    events = j.tail()
    assert len(events) == 4
    assert [e["i"] for e in events] == [6, 7, 8, 9]  # oldest first
    assert j.dropped == 6
    assert events[-1]["seq"] == 10
    assert j.summary()["dropped"] == 6


def test_tail_n_returns_newest_oldest_first():
    j = Journal(capacity=16)
    for i in range(8):
        j.record("heartbeat", i=i)
    assert [e["i"] for e in j.tail(3)] == [5, 6, 7]


def test_event_kind_inventory_is_unique():
    assert len(set(EVENT_KINDS)) == len(EVENT_KINDS)


def test_tenant_event_kinds_registered_and_recorded():
    """The per-tenant SLO plane's journal vocabulary is part of the
    EVENT_KINDS inventory, and a tripped tenant leaves a named trail:
    who burned, who was shed, and the recovery edge."""
    from fabric_token_sdk_tpu.obs.journal import (EVENT_TENANT_FAST_BURN,
                                                  EVENT_TENANT_SHED)

    assert EVENT_TENANT_FAST_BURN in EVENT_KINDS
    assert EVENT_TENANT_SHED in EVENT_KINDS

    from fabric_token_sdk_tpu.obs import TenantSloMonitor, TenantSloPolicy
    from fabric_token_sdk_tpu.obs.metrics import MetricsProvider

    clk = {"t": 1000.0}
    monitor = TenantSloMonitor(
        policy=TenantSloPolicy(min_volume=4),
        provider=MetricsProvider(), clock=lambda: clk["t"])
    before = len(JOURNAL.tail())
    for _ in range(8):
        monitor.record("hot", False)
        clk["t"] += 0.01
    clk["t"] += 400.0                       # age the failures out
    monitor.record("hot", True, 0.01)       # recovery edge
    events = [e for e in JOURNAL.tail()[before:]
              if e["kind"] == EVENT_TENANT_FAST_BURN]
    phases = [(e["phase"], e.get("tms_id")) for e in events]
    assert ("trip", "hot") in phases and ("recover", "hot") in phases

    # a shed decision is journaled with the offending tenant named
    from fabric_token_sdk_tpu.serve import TenantShedPolicy

    TenantShedPolicy(monitor, enabled=True).shed("hot", "bulk", rows=3)
    last = [e for e in JOURNAL.tail()
            if e["kind"] == EVENT_TENANT_SHED][-1]
    assert last["tms_id"] == "hot" and last["rows"] == 3


def test_spill_writes_parseable_jsonl(tmp_path):
    j = Journal(capacity=8)
    j.configure(tmp_path)
    for i in range(5):
        j.record("dispatch_start", group="range", i=i)
    lines = (tmp_path / "journal.jsonl").read_text().splitlines()
    assert len(lines) == 5
    docs = [json.loads(line) for line in lines]
    assert [d["i"] for d in docs] == list(range(5))
    assert all(d["kind"] == "dispatch_start" for d in docs)
    # spill is flushed per event: the file is already complete on disk
    assert docs[-1]["seq"] == 5


def test_reconfigure_switches_spill_directory(tmp_path):
    j = Journal()
    j.configure(tmp_path / "a")
    j.record("heartbeat")
    j.configure(tmp_path / "b")
    j.record("heartbeat")
    assert len((tmp_path / "a" / "journal.jsonl").read_text()
               .splitlines()) == 1
    assert len((tmp_path / "b" / "journal.jsonl").read_text()
               .splitlines()) == 1


def test_concurrent_record_loses_nothing(tmp_path):
    j = Journal(capacity=10_000)
    j.configure(tmp_path)
    n_threads, per = 8, 200

    def work(tid):
        for i in range(per):
            j.record("heartbeat", tid=tid, i=i)

    threads = [threading.Thread(target=work, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    events = j.tail()
    assert len(events) == n_threads * per
    assert j.dropped == 0
    # seq is a gapless total order under contention
    assert sorted(e["seq"] for e in events) == \
        list(range(1, n_threads * per + 1))
    spilled = (tmp_path / "journal.jsonl").read_text().splitlines()
    assert len(spilled) == n_threads * per


# -------------------------------------------------------------- incidents


def test_incident_without_directory_degrades_to_ring_event():
    j = Journal()
    assert j.incident("smoke", reason="no home") is None
    last = j.tail(1)[0]
    assert last["kind"] == EVENT_INCIDENT
    assert last["trigger"] == "smoke"


def test_incident_snapshot_contents_and_rate_limit(tmp_path):
    fake = [1000.0]
    j = Journal(provider=GLOBAL, clock=lambda: fake[0],
                min_interval_s=30.0)
    j.configure(tmp_path)
    j.add_status_source("good", lambda: {"depth": 3})
    j.add_status_source("broken", lambda: 1 / 0)
    j.record("batch_formed", group="range", rows=7)

    path = j.incident("breaker_force_open", reason="latched",
                      extra={"note": "x"})
    assert path is not None and os.path.exists(path)
    doc = json.loads(open(path).read())
    assert doc["schema"] == "fts-incident-v1"
    assert doc["trigger"] == "breaker_force_open"
    assert doc["reason"] == "latched"
    assert any(e["kind"] == "batch_formed" for e in doc["journal_tail"])
    # faulthandler's all-thread dump is embedded
    assert "thread" in doc["threads"].lower()
    assert doc["status"]["good"] == {"depth": 3}
    assert "error" in doc["status"]["broken"]
    assert doc["extra"] == {"note": "x"}

    # rate limit: a second trigger inside min_interval_s is suppressed
    fake[0] += 5.0
    assert j.incident("breaker_force_open") is None
    assert j.tail(1)[0]["rate_limited"] is True
    # ... unless forced, or the interval has elapsed
    assert j.incident("slo_fast_burn", force=True) is not None
    fake[0] += 60.0
    assert j.incident("slo_fast_burn") is not None


def test_incident_includes_open_spans(tmp_path):
    TRACER.clear()
    j = Journal(min_interval_s=0.0)
    j.configure(tmp_path)
    with TRACER.span("serve.dispatch", group="range", rows=4):
        path = j.incident("watchdog_abandon")
    doc = json.loads(open(path).read())
    names = [s["name"] for s in doc["active_spans"]]
    assert "serve.dispatch" in names
    sp = doc["active_spans"][names.index("serve.dispatch")]
    assert sp["attributes"]["rows"] == 4
    # after the with-block the span is closed: no longer "active"
    path2 = j.incident("watchdog_abandon")
    doc2 = json.loads(open(path2).read())
    assert "serve.dispatch" not in [s["name"] for s in doc2["active_spans"]]


def test_configure_from_env(tmp_path, monkeypatch):
    j = Journal()
    monkeypatch.delenv("FTS_JOURNAL_DIR", raising=False)
    monkeypatch.delenv("BENCH_JOURNAL_DIR", raising=False)
    assert configure_from_env(j) is None
    monkeypatch.setenv("BENCH_JOURNAL_DIR", str(tmp_path / "flight"))
    assert configure_from_env(j) == str(tmp_path / "flight")
    j.record("heartbeat")
    assert (tmp_path / "flight" / "journal.jsonl").exists()


# ------------------------------------------------- e2e: stalled dispatch


class _StallOnceRange:
    """First verify wedges on an event (the injected stall); later calls
    answer instantly — the watchdog's retry lands here."""

    def __init__(self, hang):
        self.hang = hang
        self.calls = 0

    def verify(self, proofs, commitments):
        self.calls += 1
        if self.calls == 1:
            self.hang.wait(10.0)
        return np.ones(len(proofs), dtype=bool)


class _ZK:
    def __init__(self, rng):
        self._range = rng


@pytest.fixture
def global_journal(tmp_path):
    """Point the process-global JOURNAL (hardwired into watchdog/breaker)
    at a temp dir for one test, then restore its unconfigured state."""
    JOURNAL.reset()
    JOURNAL.configure(tmp_path)
    old_interval, JOURNAL.min_interval_s = JOURNAL.min_interval_s, 0.0
    yield tmp_path
    JOURNAL.reset()
    with JOURNAL._lock:
        if JOURNAL._spill_file is not None:
            JOURNAL._spill_file.close()
            JOURNAL._spill_file = None
        JOURNAL._spill_path = None
        JOURNAL._incident_dir = None
    JOURNAL.min_interval_s = old_interval


def test_watchdog_abandon_snapshot_contains_stalled_dispatch(global_journal):
    """A stalled dispatch (FaultInjector-style wedge) must produce an
    incident snapshot whose payload shows WHERE it stalled: the open
    serve.dispatch span and the wedged thread's stack."""
    from fabric_token_sdk_tpu.resilience import ResilienceConfig
    from fabric_token_sdk_tpu.serve import ServeConfig, VerificationService

    TRACER.clear()
    hang = threading.Event()
    rng = _StallOnceRange(hang)
    svc = VerificationService(
        _ZK(rng), config=ServeConfig(buckets=(4,), max_wait_s=0.005),
        resilience=ResilienceConfig(
            retry_attempts=3, retry_base_s=0.0, retry_cap_s=0.0,
            breaker_min_volume=10_000, watchdog_timeout_s=0.15))

    async def run():
        await svc.start(prewarm=False)
        res = await asyncio.wait_for(
            svc.submit_range(True, object(), deadline_s=30.0), timeout=10.0)
        await svc.stop()
        return res

    try:
        res = asyncio.run(run())
    finally:
        hang.set()
    assert res.ok and rng.calls >= 2  # abandoned once, then served

    snaps = sorted(global_journal.glob("incident_watchdog_abandon_*.json"))
    assert snaps, "watchdog abandon wrote no incident snapshot"
    doc = json.loads(snaps[0].read_text())
    # the stalled dispatch span was still open at snapshot time
    names = [s["name"] for s in doc["active_spans"]]
    assert "serve.dispatch" in names
    # the journal tail shows the dispatch that never ended
    kinds = [e["kind"] for e in doc["journal_tail"]]
    assert EVENT_DISPATCH_START in kinds
    assert kinds.index(EVENT_DISPATCH_START) < kinds.index(EVENT_INCIDENT)
    # the wedged worker thread's stack is in the all-thread dump
    assert "verify" in doc["threads"]
