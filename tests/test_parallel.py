"""Sharded MSM verification on the virtual 8-device CPU mesh."""

import random

import jax
import jax.numpy as jnp
import numpy as np

from fabric_token_sdk_tpu.crypto import bn254
from fabric_token_sdk_tpu.ops import limbs
from fabric_token_sdk_tpu.parallel import make_mesh, sharded_msm_is_identity

rng = random.Random(0x5A)


def _case(balanced: bool):
    p = bn254.g1_mul(bn254.G1_GENERATOR, rng.randrange(1, bn254.R))
    s = [rng.randrange(bn254.R) for _ in range(3)]
    last = (bn254.R - sum(s) % bn254.R) % bn254.R
    if not balanced:
        last = (last + 1) % bn254.R
    pts = [p, p, p, p]
    scalars = s + [last]
    return pts, scalars


def test_sharded_identity_check_dp_tp():
    assert len(jax.devices()) == 8, "conftest should force 8 virtual devices"
    mesh = make_mesh(8, dp=4, tp=2)
    B, T = 4, 4
    rows = [_case(balanced=(b % 2 == 0)) for b in range(B)]
    pts = jnp.asarray(np.stack(
        [limbs.points_to_projective_limbs(r[0]) for r in rows]))
    sc = jnp.asarray(np.stack(
        [limbs.scalars_to_limbs(r[1]) for r in rows]))
    got = np.asarray(sharded_msm_is_identity(mesh, pts, sc))
    assert list(got) == [True, False, True, False]
