"""Sharded MSM verification on the virtual 8-device CPU mesh.

VERDICT r1 #3: beyond the single toy case — dp*tp shape sweeps, uneven
batches padded to the mesh, wider term counts, and a block-replay shape
(BASELINE config 5's sharded backlog pattern at test scale).
"""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fabric_token_sdk_tpu.crypto import bn254
from fabric_token_sdk_tpu.ops import limbs
from fabric_token_sdk_tpu.parallel import (make_mesh, shard_batch,
                                           sharded_msm_is_identity)

rng = random.Random(0x5A)


def _case(T: int, balanced: bool):
    p = bn254.g1_mul(bn254.G1_GENERATOR, rng.randrange(1, bn254.R))
    s = [rng.randrange(bn254.R) for _ in range(T - 1)]
    last = (bn254.R - sum(s) % bn254.R) % bn254.R
    if not balanced:
        last = (last + 1) % bn254.R
    return [p] * T, s + [last]


def _batch(B: int, T: int, pattern):
    rows = [_case(T, balanced=pattern(b)) for b in range(B)]
    pts = jnp.asarray(np.stack(
        [limbs.points_to_projective_limbs(r[0]) for r in rows]))
    sc = jnp.asarray(np.stack(
        [limbs.scalars_to_limbs(r[1]) for r in rows]))
    return pts, sc


def test_sharded_identity_check_dp_tp():
    assert len(jax.devices()) == 8, "conftest should force 8 virtual devices"
    mesh = make_mesh(8, dp=4, tp=2)
    pts, sc = _batch(4, 4, lambda b: b % 2 == 0)
    got = np.asarray(sharded_msm_is_identity(mesh, pts, sc))
    assert list(got) == [True, False, True, False]


@pytest.mark.parametrize("dp,tp", [(8, 1), (2, 4), (1, 8)])
def test_mesh_shape_sweep(dp, tp):
    """Every dp*tp factorization verifies identically."""
    mesh = make_mesh(8, dp=dp, tp=tp)
    B = max(dp, 2)
    T = 8  # divisible by every tp in the sweep
    pts, sc = _batch(B, T, lambda b: b != 1)
    got = np.asarray(sharded_msm_is_identity(mesh, pts, sc))
    assert list(got) == [b != 1 for b in range(B)]


def test_uneven_batch_padded_to_mesh():
    """B=5 on dp=4: pad with identity rows (exact no-ops) then slice."""
    mesh = make_mesh(8, dp=4, tp=2)
    B, T = 5, 4
    pts, sc = _batch(B, T, lambda b: b in (0, 3, 4))
    pad = 8 - B  # to a dp multiple
    id_pt = limbs.point_to_projective_limbs(bn254.G1_IDENTITY)
    pts_p = jnp.concatenate(
        [pts, jnp.broadcast_to(jnp.asarray(id_pt), (pad, T, 3, 16))])
    sc_p = jnp.concatenate(
        [sc, jnp.zeros((pad, T, limbs.NLIMBS), dtype=jnp.uint32)])
    got = np.asarray(sharded_msm_is_identity(mesh, pts_p, sc_p))[:B]
    assert list(got) == [b in (0, 3, 4) for b in range(B)]
    # padding rows themselves are identities -> True
    assert np.asarray(sharded_msm_is_identity(mesh, pts_p, sc_p))[B:].all()


def test_block_replay_sharded_over_mesh():
    """BASELINE config-5 shape at test scale: a backlog of checks larger
    than the mesh, processed in dp-sharded slabs with device-resident
    placement (shard_batch)."""
    mesh = make_mesh(8, dp=8, tp=1)
    B, T = 24, 4  # 3 slabs of 8
    pattern = lambda b: (b % 5) != 2  # noqa: E731
    pts, sc = _batch(B, T, pattern)
    accept = []
    for s in range(0, B, 8):
        p_slab = shard_batch(mesh, pts[s:s + 8])
        s_slab = shard_batch(mesh, sc[s:s + 8])
        accept.extend(
            np.asarray(sharded_msm_is_identity(mesh, p_slab, s_slab)))
    assert accept == [pattern(b) for b in range(B)]
